"""The ISSUE-12 full-factorization mega-kernels — ONE pallas_call owns
the ENTIRE right-looking factorization (``getrf_full_fused`` /
``potrf_full_fused``) with in-kernel lookahead — and the ``full`` rung
of the ``lu_step`` / ``potrf_step`` fusion-depth ladder, exercised in
interpret mode on CPU (the same program the TPU compiles, so
pivot/factor parity, the one-launch census and the zero-round-trip pin
here certify the default-capable path).
"""

import functools

import numpy as np
import jax
import jax.numpy as jnp
import pytest
import scipy.linalg as sla

import slate_tpu as st
from slate_tpu.linalg.lu import getrf_scattered
from slate_tpu.ops import blocks
from slate_tpu.perf import autotune, metrics
from slate_tpu.perf.hlo_profile import count_pallas_calls


@functools.lru_cache(maxsize=None)
def _scattered_fn(nb, step):
    """One memoized jitted driver per (nb, depth): same-shape tests
    share a single trace of the (expensive to interpret-trace) full
    mega-kernel instead of re-tracing per fresh lambda."""
    return jax.jit(functools.partial(getrf_scattered, nb=nb, step=step))


@functools.lru_cache(maxsize=None)
def _potrf_fn(depth, nb):
    fn = {"fused": blocks.potrf_steps, "full": blocks.potrf_full}[depth]
    return jax.jit(functools.partial(fn, nb=nb))


def _scipy_perm(a):
    """Replay scipy's swap sequence into a permutation vector."""
    _, piv = sla.lu_factor(np.asarray(a, np.float64)
                           if a.dtype == np.float64 else np.asarray(a),
                           check_finite=False)
    want = np.arange(a.shape[0])
    for k, p in enumerate(piv):
        want[k], want[p] = want[p], want[k]
    return want


def _check_lu(a, nb, step, pivot_parity=True, tol=3.0):
    """Residual gate + (optionally) scipy-exact pivots for one step
    composition of the scattered driver (the test_step_fused helper)."""
    m, n = a.shape
    lu, perm = _scattered_fn(nb, step)(jnp.asarray(a))
    lu, perm = np.asarray(lu), np.asarray(perm)
    k = min(m, n)
    assert sorted(perm.tolist()) == list(range(m)), "perm not a permutation"
    lmat = np.tril(lu[:, :k], -1) + np.eye(m, k, dtype=a.dtype)
    umat = np.triu(lu[:k])
    eps = np.finfo(a.dtype).eps
    res = (np.abs(a[perm] - lmat @ umat).max()
           / (np.abs(a).max() * max(m, n) * eps))
    assert res < tol, f"scaled residual {res} ({step})"
    # TRUE partial pivoting: |L| ≤ 1 up to roundoff
    assert np.abs(np.tril(lu[:, :k], -1)).max() <= 1.0 + 100 * eps
    if pivot_parity:
        np.testing.assert_array_equal(perm[:k], _scipy_perm(a)[:k])
    return lu, perm


class TestGetrfFullFused:
    """Driver-level parity of the whole-factorization depth vs scipy
    across square/tall × f32/f64 × the nb sweep the ISSUE names."""

    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    @pytest.mark.parametrize("m,n", [(256, 256), (384, 256)])
    def test_shapes(self, m, n, dtype):
        a = np.random.default_rng(m + n).standard_normal(
            (m, n)).astype(dtype)
        _check_lu(a, 128, "full")

    def test_wide(self):
        """m < n: the LAST step has no next panel (look off) but still
        streams the remainder U columns — the has_trail-without-look
        branch, bitwise against the per-step fused depth."""
        m, n = 256, 384
        a = np.random.default_rng(m + n).standard_normal(
            (m, n)).astype(np.float32)
        lu_F, p_F = _check_lu(a, 128, "full")
        lu_f, p_f = map(np.asarray,
                        _scattered_fn(128, "fused")(jnp.asarray(a)))
        np.testing.assert_array_equal(p_f, p_F)
        np.testing.assert_array_equal(lu_f, lu_F)

    @pytest.mark.parametrize("nb", [128, 256, 512])
    def test_nb_sweep(self, nb):
        n = 2 * nb if nb <= 256 else nb
        a = np.random.default_rng(nb).standard_normal(
            (n, n)).astype(np.float32)
        _check_lu(a, nb, "full")

    def test_many_tied_pivots(self):
        """Adversarial ±1 matrix: every column's pivot search hits an
        m-way exact magnitude tie; the carried-across-steps pivot state
        must still produce a valid partial-pivot factorization
        (distinct pivots, |L| ≤ 1, residual-gated) even though tie
        ORDER differs from LAPACK."""
        rng = np.random.default_rng(13)
        a = np.sign(rng.standard_normal((256, 256))).astype(np.float32)
        _check_lu(a, 128, "full", pivot_parity=False)

    def test_depth_agreement(self):
        """The full kernel runs the step kernel's exact per-chunk
        arithmetic (same panel phase, same G/W composition) — where
        pivots tie-break identically the pivots AND the factors must be
        BITWISE identical to the fused depth, not merely close.  The
        composed depth shares the panel arithmetic too (identical
        pivots) but orders its trailing products differently, so its
        factors agree only to gemm-rounding."""
        a = np.random.default_rng(6).standard_normal(
            (256, 256)).astype(np.float32)
        lu_F, p_F = _check_lu(a, 128, "full")
        lu_f, p_f = map(np.asarray,
                        _scattered_fn(128, "fused")(jnp.asarray(a)))
        np.testing.assert_array_equal(p_f, p_F)
        np.testing.assert_array_equal(lu_f, lu_F)
        lu_c, p_c = map(np.asarray,
                        _scattered_fn(128, "composed")(jnp.asarray(a)))
        np.testing.assert_array_equal(p_c, p_F)
        assert np.abs(lu_F - lu_c).max() < 1e-3 * np.abs(lu_c).max()


class TestPotrfFullFused:
    """Factor parity of the whole-factorization Cholesky kernel."""

    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    @pytest.mark.parametrize("n,nb", [(256, 128), (384, 128), (512, 256)])
    def test_factor_parity(self, n, nb, dtype):
        rng = np.random.default_rng(n + nb)
        g = rng.standard_normal((n, n)).astype(dtype)
        spd = g @ g.T + n * np.eye(n, dtype=dtype)
        l = np.asarray(_potrf_fn("full", nb)(jnp.asarray(spd)))
        eps = np.finfo(dtype).eps
        res = np.linalg.norm(l @ l.T - spd) / (
            np.linalg.norm(spd) * eps * n)
        assert res < 3.0, res
        assert np.abs(np.triu(l, 1)).max() == 0.0
        ref = np.linalg.cholesky(spd.astype(np.float64))
        dev = np.abs(l - ref).max() / np.abs(ref).max()
        assert dev < 300 * eps, dev

    def test_nb512(self):
        n, nb = 1024, 512
        rng = np.random.default_rng(7)
        g = rng.standard_normal((n, n)).astype(np.float32)
        spd = g @ g.T + n * np.eye(n, dtype=np.float32)
        l = np.asarray(_potrf_fn("full", nb)(jnp.asarray(spd)))
        eps = np.finfo(np.float32).eps
        res = np.linalg.norm(l @ l.T - spd) / (
            np.linalg.norm(spd) * eps * n)
        assert res < 3.0, res

    def test_matches_fused_steps_bitwise(self):
        """Same per-tile arithmetic as the per-step kernel (the
        lookahead column is the same dot partitioned differently) —
        the factors must be bitwise identical."""
        rng = np.random.default_rng(8)
        g = rng.standard_normal((256, 256)).astype(np.float32)
        spd = g @ g.T + 256 * np.eye(256, dtype=np.float32)
        l_s = np.asarray(_potrf_fn("fused", 128)(jnp.asarray(spd)))
        l_F = np.asarray(_potrf_fn("full", 128)(jnp.asarray(spd)))
        np.testing.assert_array_equal(l_s, l_F)


class TestLaunchAndRoundtripBudgets:
    """The acceptance pins: exactly ONE pallas_call per whole
    factorization at eligible sizes, and ``step.hbm_roundtrips == 0``
    across the entire factorization — structurally, not just timed."""

    def test_getrf_one_pallas_call_per_factorization(self):
        for m, n, nb in ((256, 256, 128), (384, 256, 128),
                         (512, 512, 256)):
            a = jnp.zeros((m, n), jnp.float32)
            calls = count_pallas_calls(
                lambda x: getrf_scattered(x, nb, step="full"), a)
            assert calls == 1, (m, n, nb, calls)

    def test_potrf_one_pallas_call_per_factorization(self):
        for n, nb in ((256, 128), (512, 256)):
            a = jnp.zeros((n, n), jnp.float32)
            calls = count_pallas_calls(
                lambda x: blocks.potrf_full(x, nb), a)
            assert calls == 1, (n, nb, calls)

    def _roundtrips(self, fn, *args):
        was = metrics.enabled()
        metrics.reset()
        metrics.on()
        try:
            jax.make_jaxpr(fn)(*args)   # trace-time counters fire here
            snap = metrics.snapshot()["counters"]
        finally:
            metrics.reset()
            if not was:
                metrics.off()
        return snap.get(metrics.STEP_HBM_ROUNDTRIPS, 0.0)

    def test_full_depth_pins_zero_hbm_roundtrips(self):
        a = jnp.zeros((256, 256), jnp.float32)
        assert self._roundtrips(
            lambda x: getrf_scattered(x, 128, step="full"), a) == 0.0
        assert self._roundtrips(
            lambda x: blocks.potrf_full(x, 128), a) == 0.0

    def test_eligibility_gates(self):
        """The full gates plan against the shared VMEM budget and sit
        strictly inside the step gates (TWO resident panels)."""
        from slate_tpu.linalg.lu import (_full_fused_bytes,
                                         _fused_step_bytes,
                                         _use_full_fused)

        assert _use_full_fused(256, 256, 128, jnp.float32)
        assert not _use_full_fused(256, 256, 192, jnp.float32)  # nb%128
        for m, nb, tc in ((8192, 512, 512), (4096, 256, 128)):
            assert _full_fused_bytes(m, nb, tc) > \
                _fused_step_bytes(m, nb, tc)
        assert blocks._potrf_full_bytes(1024, 512, 512) > \
            blocks._potrf_step_bytes(1024, 512, 512)
        assert blocks.use_full_potrf(1024, 512, jnp.float32)
        assert not blocks.use_full_potrf(512, 512, jnp.float32)  # n<=nb
        assert not blocks.use_full_potrf(1024, 512, jnp.float64)

    def test_vmem_budget_moves_the_full_gates(self, monkeypatch):
        """A starved SLATE_TPU_VMEM_BUDGET_MB must close the full
        gates through the shared ops.vmem budget (the ONE-helper
        contract of ISSUE 8)."""
        from slate_tpu.linalg.lu import _use_full_fused

        monkeypatch.setenv("SLATE_TPU_VMEM_BUDGET_MB", "1")
        assert not _use_full_fused(4096, 4096, 512, jnp.float32)
        assert not blocks.use_full_potrf(4096, 512, jnp.float32)


class TestEndToEndThroughFullSites:
    """gesv/posv routed through the full-depth mega-kernels by the
    autotune sites (force knobs), residual-gated end to end — proof the
    SHIPPED dispatch (not just the raw drivers) takes the full path."""

    @pytest.fixture(autouse=True)
    def _force(self, monkeypatch):
        from slate_tpu.linalg import lu as lu_mod
        monkeypatch.setattr("slate_tpu.config.scattered_lu", True)
        monkeypatch.setattr(lu_mod, "_SCATTERED_NB", 128)
        monkeypatch.setenv("SLATE_TPU_AUTOTUNE_FORCE",
                           "lu_step=full,potrf_step=full")
        autotune.reset_table()
        yield
        autotune.reset_table()

    def test_gesv(self):
        rng = np.random.default_rng(4)
        n, nrhs = 256, 3
        a = (rng.standard_normal((n, n)).astype(np.float32)
             + n * np.eye(n, dtype=np.float32))
        b = rng.standard_normal((n, nrhs)).astype(np.float32)
        lu, perm, x = st.gesv(st.Matrix.from_array(a, nb=128),
                              jnp.asarray(b))
        xv = np.asarray(x)
        eps = np.finfo(np.float32).eps
        res = (np.linalg.norm(a @ xv - b)
               / (np.linalg.norm(a) * np.linalg.norm(xv) * n * eps))
        assert res < 3, f"solve residual {res}"
        dec = autotune.decisions()
        assert any(k.startswith("lu_step|") and v == "full"
                   for k, v in dec.items()), dec

    def test_posv(self):
        rng = np.random.default_rng(9)
        n, nrhs = 1024, 2
        g = rng.standard_normal((n, n)).astype(np.float32)
        a = (g @ g.T / n + np.eye(n, dtype=np.float32)).astype(np.float32)
        b = rng.standard_normal((n, nrhs)).astype(np.float32)
        fac, x = st.posv(st.HermitianMatrix(jnp.asarray(a),
                                            uplo=st.Uplo.Lower),
                         jnp.asarray(b))
        xv = np.asarray(x)
        eps = np.finfo(np.float32).eps
        res = (np.linalg.norm(a @ xv - b)
               / (np.linalg.norm(a) * np.linalg.norm(xv) * n * eps))
        assert res < 3, f"solve residual {res}"
        dec = autotune.decisions()
        assert any(k.startswith("potrf_step|") and v == "full"
                   for k, v in dec.items()), dec
