"""QR/LQ family tests — residual + orthogonality gates like the
reference tester (``test/test_geqrf.cc``: ‖A − QR‖/(m‖A‖ε) and
‖I − QᴴQ‖/(mε) ≤ 3-ish)."""

import numpy as np
import jax.numpy as jnp
import pytest

import slate_tpu as st
from slate_tpu.enums import MethodGels, Op, Side, Uplo
from slate_tpu.linalg.qr import (cholqr, gelqf, gels, gels_cholqr, gels_qr,
                                 geqrf, larft_rec, ungqr, unmlq, unmqr)
from slate_tpu.testing.matgen import generate_matrix


def _qr_checks(a, packed, taus, nb=16):
    a = np.asarray(a)
    m, n = a.shape
    k = min(m, n)
    eps = np.finfo(a.dtype).eps
    q = np.asarray(ungqr(packed, taus, n_cols=m))
    r = np.triu(np.asarray(packed if not hasattr(packed, "array")
                           else packed.array))[:k if m >= n else m]
    # orthogonality
    orth = np.linalg.norm(q.conj().T @ q - np.eye(m)) / (m * eps)
    assert orth < 50, f"orthogonality {orth}"
    # reconstruction: A = Q·[R; 0]
    rfull = np.zeros((m, n), a.dtype)
    rfull[:min(m, n), :] = np.triu(np.asarray(
        packed.array if hasattr(packed, "array") else packed))[:min(m, n)]
    res = np.linalg.norm(a - q @ rfull) / (np.linalg.norm(a) * m * eps)
    assert res < 50, f"reconstruction {res}"


@pytest.mark.parametrize("m,n", [(64, 64), (120, 40), (40, 96)])
def test_geqrf(m, n):
    a = np.asarray(generate_matrix("randn", m, n, dtype=jnp.float64, seed=1))
    f, taus = geqrf(st.Matrix.from_array(a, nb=16))
    _qr_checks(a, f, taus)


def test_geqrf_complex():
    a = np.asarray(generate_matrix("randn", 48, 48, dtype=jnp.complex128, seed=2))
    f, taus = geqrf(st.Matrix.from_array(a, nb=16))
    q = np.asarray(ungqr(f, taus, n_cols=48))
    eps = np.finfo(np.float64).eps
    assert np.linalg.norm(q.conj().T @ q - np.eye(48)) / (48 * eps) < 50
    r = np.triu(np.asarray(f.array))
    assert np.linalg.norm(a - q @ r) / (np.linalg.norm(a) * 48 * eps) < 50


def test_larft_matches_product_of_reflectors():
    rng = np.random.default_rng(3)
    m, k = 20, 6
    a = rng.standard_normal((m, k))
    f, taus = geqrf(st.Matrix.from_array(a, nb=8))
    v = np.tril(np.asarray(f.array), -1) + np.eye(m, k)
    t = np.asarray(larft_rec(jnp.asarray(v), taus))
    q_wy = np.eye(m) - v @ t @ v.T
    q_prod = np.eye(m)
    for i in range(k):
        h = np.eye(m) - float(taus[i]) * np.outer(v[:, i], v[:, i])
        q_prod = q_prod @ h
    np.testing.assert_allclose(q_wy, q_prod, atol=1e-12)


@pytest.mark.parametrize("side", [Side.Left, Side.Right])
@pytest.mark.parametrize("op", [Op.NoTrans, Op.Trans])
def test_unmqr_sides_ops(side, op):
    rng = np.random.default_rng(4)
    m, k = 40, 24
    a = rng.standard_normal((m, k))
    f, taus = geqrf(st.Matrix.from_array(a, nb=8))
    q = np.asarray(ungqr(f, taus, n_cols=m))
    c = rng.standard_normal((m, m))
    got = np.asarray(unmqr(side, op, f, taus, jnp.asarray(c)))
    qop = q if op is Op.NoTrans else q.T
    want = qop @ c if side is Side.Left else c @ qop
    np.testing.assert_allclose(got, want, atol=1e-11)


def test_gelqf_unmlq():
    rng = np.random.default_rng(5)
    m, n = 30, 70
    a = rng.standard_normal((m, n))
    f, taus = gelqf(st.Matrix.from_array(a, nb=16))
    l = np.tril(np.asarray(f.array))[:, :m]
    # reconstruct A = L·Q by applying Q to [I_m; 0] rows: A = unmlq(L_ext)
    lext = np.zeros((m, n))
    lext[:, :m] = l
    got = np.asarray(unmlq(Side.Right, Op.NoTrans, f, taus, jnp.asarray(lext)))
    np.testing.assert_allclose(got, a, atol=1e-11)


@pytest.mark.parametrize("m,n", [(90, 30), (30, 80)])
def test_gels_qr(m, n):
    rng = np.random.default_rng(6)
    a = rng.standard_normal((m, n))
    b = rng.standard_normal(m)
    x = np.asarray(gels_qr(st.Matrix.from_array(a, nb=16), jnp.asarray(b)))
    want, *_ = np.linalg.lstsq(a, b, rcond=None)
    np.testing.assert_allclose(x, want, atol=1e-9)


def test_cholqr():
    a = np.asarray(generate_matrix("cond", 200, 24, dtype=jnp.float64,
                                   seed=7, cond=1e3))
    q, r = cholqr(st.Matrix.from_array(a, nb=16))
    q, r = np.asarray(q), np.asarray(r)
    eps = np.finfo(np.float64).eps
    assert np.linalg.norm(q.T @ q - np.eye(24)) / (200 * eps) < 1e6  # cond² loss
    np.testing.assert_allclose(q @ r, a, atol=1e-11)
    assert np.allclose(r, np.triu(r))


def test_gels_cholqr_and_auto():
    rng = np.random.default_rng(8)
    m, n = 300, 40
    a = rng.standard_normal((m, n))
    b = rng.standard_normal((m, 3))
    want, *_ = np.linalg.lstsq(a, b, rcond=None)
    x1 = np.asarray(gels_cholqr(st.Matrix.from_array(a, nb=16), jnp.asarray(b)))
    np.testing.assert_allclose(x1, want, atol=1e-8)
    x2 = np.asarray(gels(st.Matrix.from_array(a, nb=16), jnp.asarray(b)))
    np.testing.assert_allclose(x2, want, atol=1e-8)
    x3 = np.asarray(gels(st.Matrix.from_array(a, nb=16), jnp.asarray(b),
                         {"method_gels": MethodGels.QR}))
    np.testing.assert_allclose(x3, want, atol=1e-8)


def test_larft_interior_zero_tau():
    """A tau=0 column (H_j = I) must contribute nothing to T — the
    closed-form larft must zero both its row and column (dlarft)."""
    from slate_tpu.linalg.qr import larft_rec
    rng = np.random.default_rng(55)
    m, k = 8, 3
    v = np.tril(rng.standard_normal((m, k)), -1)
    v[np.arange(k), np.arange(k)] = 1.0
    tau = np.array([0.7, 0.0, 0.4])
    t = np.asarray(larft_rec(jnp.asarray(v), jnp.asarray(tau)))
    # reference: product of reflectors, skipping the identity one
    q = np.eye(m)
    for j in range(k):
        h = np.eye(m) - tau[j] * np.outer(v[:, j], v[:, j])
        q = q @ h
    q_wy = np.eye(m) - v @ t @ v.T
    np.testing.assert_allclose(q_wy, q, atol=1e-12)


def test_cholqr2_panel_guard_ill_conditioned():
    """f32 CholQR² panel path must keep LAPACK-grade orthogonality on
    panels past its cond ≈ 1/√ε breakdown (ADVICE r3: the guard falls
    back to the Householder panel instead of silently degrading)."""
    from slate_tpu.linalg.qr import geqrf_panels
    n = 32
    a64 = np.asarray(generate_matrix("cond", 128, n, dtype=jnp.float64,
                                     seed=11, cond=1e6))
    a = jnp.asarray(a64, dtype=jnp.float32)
    f, taus = geqrf_panels(a, nb=n)
    q = np.asarray(ungqr(f, taus, n_cols=128)).astype(np.float64)
    eps = np.finfo(np.float32).eps
    orth = np.linalg.norm(q.T @ q - np.eye(128)) / (128 * eps)
    assert orth < 50, f"orthogonality {orth} (guard did not engage?)"
    r = np.triu(np.asarray(f, dtype=np.float64))[:n]
    res = np.linalg.norm(a64 - (q[:, :n] @ r)) / (
        np.linalg.norm(a64) * 128 * eps)
    assert res < 50, f"reconstruction {res}"
