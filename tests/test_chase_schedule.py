"""Pure-Python property test of the recorded wavefront dependence
analysis (STATUS r4), which BOTH chase parallelizations rely on — the
device Pallas mega-kernel (``ops.pallas_kernels.hb2st_wavefront`` /
``tb2bd_wavefront`` batch same-stagger windows inside one grid step)
and the still-documented OpenMP wavefront in ``native/runtime.cc``:

* task (sweep j, window w) touches band rows
  [j+1+(w−1)·kd, j+1+(w+1)·kd) (+1 row for the trailing length-1
  coupling apply);
* with stagger t = 3j + w, same-t tasks are pairwise ROW-DISJOINT;
* every conflicting (row-overlapping) pair is stagger-ORDERED the same
  way the serial sweep-major chase orders it — so executing staggers in
  sequence with any order inside a stagger reproduces the serial chase.

No jax, no native runtime: the schedule is arithmetic.
"""

import numpy as np
import pytest

from slate_tpu.linalg.eig import _hb_sweep_counts
from slate_tpu.linalg.svd import _bd_sweep_counts


def _hb_tasks(n, kd):
    """(j, w, t, row_lo, row_hi) for every window task of the symmetric
    chase; the row interval includes the coupling row."""
    tasks = []
    for j, nwin in zip(range(0, max(n - 2, 0)), _hb_sweep_counts(n, kd)):
        for w in range(nwin):
            if w == 0:
                r0, length = j + 1, min(kd, n - 1 - j)
            else:
                r0 = j + 1 + w * kd
                length = min(kd, n - r0)
            # window rows plus the previous window's columns it
            # updates; the trailing coupling row exists only when the
            # next block is a single row (the serial loop's Lt == 1
            # right-apply-then-break)
            lo = r0 - (kd if w else 1)
            hi = r0 + length + (1 if n - (r0 + length) == 1 else 0)
            tasks.append((j, w, 3 * j + w, lo, min(n, hi)))
    return tasks


def _bd_tasks(n, kd):
    tasks = []
    for s, nblk in zip(range(0, max(n - 1, 0)), _bd_sweep_counts(n, kd)):
        for b in range(nblk):
            if b == 0:
                lo = s
                hi = min(n, s + kd + 1)
            else:
                i_lo = (b - 1) * kd + 1 + s
                j_lo = b * kd + 1 + s
                lo = i_lo
                hi = min(n, j_lo + kd)
            tasks.append((s, b, 3 * s + b, lo, hi))
    return tasks


def _overlap(a, b):
    return a[3] < b[4] and b[3] < a[4]


@pytest.mark.parametrize("n,kd", [(64, 8), (96, 8), (100, 13), (128, 48)])
@pytest.mark.parametrize("kind", ["hb2st", "tb2bd"])
def test_same_stagger_tasks_are_row_disjoint(kind, n, kd):
    tasks = _hb_tasks(n, kd) if kind == "hb2st" else _bd_tasks(n, kd)
    by_t: dict = {}
    for task in tasks:
        by_t.setdefault(task[2], []).append(task)
    for t, group in by_t.items():
        for i in range(len(group)):
            for k in range(i + 1, len(group)):
                assert not _overlap(group[i], group[k]), \
                    f"stagger {t}: tasks {group[i][:2]} and " \
                    f"{group[k][:2]} touch overlapping rows"


@pytest.mark.parametrize("n,kd", [(64, 8), (100, 13), (128, 48)])
@pytest.mark.parametrize("kind", ["hb2st", "tb2bd"])
def test_conflicting_pairs_are_stagger_ordered(kind, n, kd):
    """Any two row-overlapping tasks must execute in the serial
    (sweep-major) order under the stagger schedule: serial-earlier ⇒
    strictly smaller t.  This is the property that makes the per-t
    batched execution bitwise-equivalent to the serial chase."""
    tasks = _hb_tasks(n, kd) if kind == "hb2st" else _bd_tasks(n, kd)
    for i in range(len(tasks)):
        ji, wi, ti = tasks[i][:3]
        for k in range(i + 1, len(tasks)):
            jk, wk, tk = tasks[k][:3]
            if not _overlap(tasks[i], tasks[k]):
                continue
            serial_before = (ji, wi) < (jk, wk)
            assert (ti < tk) == serial_before and ti != tk, \
                f"conflicting tasks {(ji, wi)}@{ti} vs {(jk, wk)}@{tk} " \
                "not stagger-ordered"


@pytest.mark.parametrize("n,kd", [(48, 8), (96, 8), (100, 13), (128, 48),
                                  (10, 3)])
def test_kernel_window_counts_match_log_packer(n, kd):
    """The wavefront kernels' closed-form per-sweep window counts must
    equal the packer's (`_hb_sweep_counts` / `_bd_sweep_counts`) — the
    contract that makes the kernel's (nsweeps, tmax, kd) log layout
    byte-compatible with what unmtr_hb2st_hh consumes."""
    hb = [(n - 3 - j) // kd + 1 for j in range(0, max(n - 2, 0))
          if j <= n - 3]
    assert hb == _hb_sweep_counts(n, kd)
    bd = [(n - 2 - s) // kd + 1 for s in range(0, max(n - 1, 0))
          if s <= n - 3]
    assert bd == _bd_sweep_counts(n, kd)


def test_documented_dependence_list_is_complete():
    """The recorded dep list of task (j, w) — (j, w−1)@t−1,
    (j−1, w+1)@t−2, (j−1, w+2)@t−1 — covers every conflicting
    PREDECESSOR within the previous two staggers (the window any
    wavefront implementation must honor)."""
    n, kd = 96, 8
    tasks = _hb_tasks(n, kd)
    index = {(j, w): task for (j, w, *_), task in
             zip([(t[0], t[1]) for t in tasks], tasks)}
    documented = lambda j, w: {(j, w - 1), (j - 1, w + 1), (j - 1, w + 2)}
    for task in tasks:
        j, w, t = task[:3]
        for other in tasks:
            jo, wo, to = other[:3]
            if (jo, wo) == (j, w) or not _overlap(task, other):
                continue
            if 0 < t - to <= 2:
                assert (jo, wo) in documented(j, w), \
                    f"conflicting near-predecessor {(jo, wo)}@{to} of " \
                    f"{(j, w)}@{t} missing from the documented dep list"
