"""Headline benchmark: Cholesky factorization throughput on one chip.

Reproduces the reference tester's metric — GFLOP/s from model flop counts
(``/root/reference/test/test_gemm.cc:244-245``, ``params.gflops()``) — for
the flagship driver ``potrf`` (BASELINE.md config #2: potrf fp32 n=8192,
single device).  ``vs_baseline`` is measured against the reference's only
in-repo per-device throughput anchor, 702 GFLOP/s/GPU
(``/root/reference/docs/usage.md:36-44``).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

import numpy as np

BASELINE_GFLOPS = 702.0  # reference docs/usage.md per-GPU gemm anchor


def main():
    import jax
    import jax.numpy as jnp

    from slate_tpu.ops import blocks

    on_tpu = jax.devices()[0].platform == "tpu"
    n = 8192 if on_tpu else 1024
    nb = 512 if on_tpu else 128
    dtype = jnp.float32

    rng = np.random.default_rng(0)
    g = rng.standard_normal((n, n)).astype(np.float32)
    a = jnp.asarray(g @ g.T + n * np.eye(n, dtype=np.float32), dtype)

    # reduce on device and read one scalar back: a sync point that works
    # even where block_until_ready only waits for enqueue (axon tunnel)
    step = jax.jit(lambda a: blocks.potrf_rec(a, nb)[-1, -1])
    float(step(a))  # compile + warm up

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(step(a))
        times.append(time.perf_counter() - t0)
    t = min(times)

    flops = n ** 3 / 3.0  # LAPACK model count for potrf
    gflops = flops / t / 1e9
    print(json.dumps({
        "metric": f"potrf_fp32_n{n}_gflops",
        "value": round(gflops, 2),
        "unit": "GFLOP/s",
        "vs_baseline": round(gflops / BASELINE_GFLOPS, 4),
    }))
    print(f"# t={t:.4f}s n={n} nb={nb} platform={jax.devices()[0].platform}",
          file=sys.stderr)


if __name__ == "__main__":
    main()
