"""Headline benchmark: Cholesky factorization throughput on one chip.

Reproduces the reference tester's metric — GFLOP/s from model flop counts
(``/root/reference/test/test_gemm.cc:244-245``, ``params.gflops()``) — for
the flagship driver ``potrf`` (BASELINE.md config #2: potrf fp32 n=8192,
single device).  ``vs_baseline`` is measured against the reference's only
in-repo per-device throughput anchor, 702 GFLOP/s/GPU
(``/root/reference/docs/usage.md:36-44``).

Timing: the factorization is run iters+1 times *chained inside one jit*
(each iteration's input depends on the previous result, so XLA cannot
collapse the chain) and the wall time is divided by iters+1.  This
measures on-device time the way the reference's MPI-barrier wall clock
does (``test/test_gemm.cc:224-245``) and amortizes the host↔device
round-trip latency of the tunnel (~100 ms, which would otherwise swamp a
~25 ms factorization) down to a few percent of the total.

The metric only prints after the factorization passes the reference's
scaled-residual gate (≤ 3, ``test/test_gemm.cc:260``); a broken factor
exits nonzero instead of publishing a number.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

import numpy as np

BASELINE_GFLOPS = 702.0  # reference docs/usage.md per-GPU gemm anchor


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from slate_tpu.ops import blocks

    on_tpu = jax.devices()[0].platform == "tpu"
    n = 8192 if on_tpu else 1024
    nb = 4096 if on_tpu else 128
    iters = 32 if on_tpu else 2
    dtype = jnp.float32

    rng = np.random.default_rng(0)
    g = rng.standard_normal((n, n)).astype(np.float32)
    anp = g @ g.T + n * np.eye(n, dtype=np.float32)
    a = jnp.asarray(anp, dtype)

    def chained(a):
        def body(i, x):
            l = blocks.potrf_rec(x, nb)
            # tie the next iteration to this result (prevents hoisting)
            # without changing the factored matrix beyond rounding
            return a + l[-1, -1] * jnp.float32(1e-30)
        out = lax.fori_loop(0, iters, body, a)
        # reduce to one scalar: the host float() below is the sync point
        # (works even where block_until_ready only waits for enqueue)
        return blocks.potrf_rec(out, nb)[-1, -1]

    step = jax.jit(chained)
    float(step(a))  # compile + warm up

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(step(a))
        times.append(time.perf_counter() - t0)
    t = min(times) / (iters + 1)

    # correctness gate on a single factorization (reference ≤ 3ε criterion)
    l = np.asarray(jax.jit(lambda a: blocks.potrf_rec(a, nb))(a))
    resid = (np.linalg.norm(np.tril(l) @ np.tril(l).T - anp)
             / (np.linalg.norm(anp) * np.finfo(np.float32).eps * n))

    if resid > 3.0:
        print(f"# FAILED residual gate: scaled_resid={resid:.3e} > 3",
              file=sys.stderr)
        sys.exit(1)

    flops = n ** 3 / 3.0  # LAPACK model count for potrf
    gflops = flops / t / 1e9
    print(json.dumps({
        "metric": f"potrf_fp32_n{n}_gflops",
        "value": round(gflops, 2),
        "unit": "GFLOP/s",
        "vs_baseline": round(gflops / BASELINE_GFLOPS, 4),
    }))
    print(f"# t={t:.4f}s n={n} nb={nb} iters={iters} scaled_resid={resid:.3e}"
          f" platform={jax.devices()[0].platform}", file=sys.stderr)


if __name__ == "__main__":
    main()
