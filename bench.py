"""Benchmark suite: gemm / potrf / getrf / geqrf throughput on one chip.

Reproduces the reference tester's metric — GFLOP/s from model flop counts
(``/root/reference/test/test_gemm.cc:244-245``, ``params.gflops()``) — at
the BASELINE.md configs (fp32, nb in the reference's 256-512 range or the
vendor-dispatch default):

* gemm  n=8192                      (config 1 scaled to the chip)
* potrf n=8192                      (config 2)
* getrf n=8192, nb=512              (config 3, single chip)
* geqrf m=32768 n=4096              (config 4)

``vs_baseline`` compares against the reference's only in-repo per-device
throughput anchor, 702 GFLOP/s/GPU (``/root/reference/docs/usage.md:36-44``).
The headline value is the geometric mean of the four routines; the
``submetrics`` key carries each routine's GFLOP/s and its fraction of the
measured gemm rate (the chip's practical fp32 peak).

Timing: each routine is run iters times *chained inside one jit* (each
iteration's input depends on the previous result, so XLA cannot collapse
the chain) and the wall time is divided by iters.  This measures on-device
time the way the reference's MPI-barrier wall clock does
(``test/test_gemm.cc:224-245``) and amortizes the host↔device round-trip
latency of the tunnel (~100 ms) to a few percent.

Every number only prints after the routine passes a scaled-residual gate
(≤ 3 in units of eps·n, the reference's criterion ``test/test_gemm.cc:260``),
checked with O(n²) matrix-vector probes so the gate itself stays cheap.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

import json
import sys
import time

import numpy as np

BASELINE_GFLOPS = 702.0  # reference docs/usage.md per-GPU gemm anchor


def _timeit(fn, args, iters):
    float(fn(*args))  # compile + warm
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(fn(*args))
        times.append(time.perf_counter() - t0)
    return min(times) / iters


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from slate_tpu.ops import blocks
    from slate_tpu.linalg.lu import getrf_rec

    on_tpu = jax.devices()[0].platform == "tpu"
    scale = 1 if on_tpu else 8
    eps = float(np.finfo(np.float32).eps)
    rng = np.random.default_rng(0)
    sub = {}
    fails = []

    def gate(name, resid):
        if resid > 3.0:
            fails.append(f"{name}: scaled_resid={resid:.3e} > 3")

    def mv(mat, x):
        return mat @ x

    # ---- gemm --------------------------------------------------------
    n = 8192 // scale
    iters = 8 if on_tpu else 2
    a_np = rng.standard_normal((n, n)).astype(np.float32)
    b_np = rng.standard_normal((n, n)).astype(np.float32)
    a = jnp.asarray(a_np)
    b = jnp.asarray(b_np)

    @jax.jit
    def gemm_chain(a, b):
        def body(i, x):
            return (x @ b) * jnp.float32(1e-4)
        return lax.fori_loop(0, iters, body, a)[0, 0]

    t = _timeit(gemm_chain, (a, b), iters)
    gemm_gf = 2.0 * n ** 3 / t / 1e9
    c_np = np.asarray(jax.jit(jnp.matmul)(a, b))
    x = rng.standard_normal((n,)).astype(np.float32)
    resid = (np.linalg.norm(mv(c_np, x) - mv(a_np, mv(b_np, x)))
             / (np.linalg.norm(a_np) * np.linalg.norm(mv(b_np, x))
                * eps * n))
    gate("gemm", resid)
    sub["gemm_fp32_n%d" % n] = round(gemm_gf, 1)

    # ---- potrf -------------------------------------------------------
    g = rng.standard_normal((n, n)).astype(np.float32)
    spd_np = g @ g.T + n * np.eye(n, dtype=np.float32)
    spd = jnp.asarray(spd_np)

    @jax.jit
    def potrf_chain(spd):
        def body(i, x):
            l = jnp.tril(lax.linalg.cholesky(x))
            return spd + l[-1, -1] * jnp.float32(1e-30)
        out = lax.fori_loop(0, iters, body, spd)
        return jnp.tril(lax.linalg.cholesky(out))[-1, -1]

    t = _timeit(potrf_chain, (spd,), iters + 1)
    potrf_gf = n ** 3 / 3.0 / t / 1e9
    l_np = np.asarray(jax.jit(
        lambda a: jnp.tril(lax.linalg.cholesky(a)))(spd))
    resid = (np.linalg.norm(mv(l_np, mv(l_np.T, x)) - mv(spd_np, x))
             / (np.linalg.norm(spd_np) * np.linalg.norm(x) * eps * n))
    gate("potrf", resid)
    sub["potrf_fp32_n%d" % n] = round(potrf_gf, 1)

    # ---- getrf (partial-pivot LU, nb=512) ----------------------------
    nb_lu = 512 // scale
    am_np = (rng.standard_normal((n, n)).astype(np.float32)
             + n * np.eye(n, dtype=np.float32))
    am = jnp.asarray(am_np)
    lu_iters = 4 if on_tpu else 2

    @jax.jit
    def getrf_chain(am):
        def body(i, x):
            lu, piv = getrf_rec(x, nb_lu)
            return am + lu[-1, -1] * jnp.float32(1e-30)
        out = lax.fori_loop(0, lu_iters - 1, body, am)
        return getrf_rec(out, nb_lu)[0][-1, -1]

    t = _timeit(getrf_chain, (am,), lu_iters)
    getrf_gf = 2.0 * n ** 3 / 3.0 / t / 1e9
    lu_np, perm_np = map(np.asarray,
                         jax.jit(lambda a: getrf_rec(a, nb_lu))(am))
    l_f = np.tril(lu_np, -1) + np.eye(n, dtype=np.float32)
    u_f = np.triu(lu_np)
    resid = (np.linalg.norm(mv(l_f, mv(u_f, x)) - mv(am_np[perm_np], x))
             / (np.linalg.norm(am_np) * np.linalg.norm(x) * eps * n))
    gate("getrf", resid)
    sub["getrf_fp32_n%d_nb%d" % (n, nb_lu)] = round(getrf_gf, 1)

    # ---- geqrf (tall QR, vendor dispatch) ----------------------------
    m2, n2 = 32768 // scale, 4096 // scale
    tall_np = rng.standard_normal((m2, n2)).astype(np.float32)
    tall = jnp.asarray(tall_np)
    qr_iters = 4 if on_tpu else 2

    def geqrf_raw(x):
        h, tau = jnp.linalg.qr(x, mode="raw")
        return jnp.swapaxes(h, -1, -2), tau

    @jax.jit
    def geqrf_chain(tall):
        def body(i, x):
            f2, taus = geqrf_raw(x)
            return tall + f2[-1, -1] * jnp.float32(1e-30)
        out = lax.fori_loop(0, qr_iters - 1, body, tall)
        return geqrf_raw(out)[0][-1, -1]

    t = _timeit(geqrf_chain, (tall,), qr_iters)
    qr_flops = 2.0 * m2 * n2 ** 2 - 2.0 * n2 ** 3 / 3.0
    geqrf_gf = qr_flops / t / 1e9
    r_np = np.triu(np.asarray(jax.jit(geqrf_raw)(tall)[0])[:n2])
    x2 = rng.standard_normal((n2,)).astype(np.float32)
    # Gram identity AᵀA = RᵀR probed with a vector
    resid = (np.linalg.norm(mv(tall_np.T, mv(tall_np, x2))
                            - mv(r_np.T, mv(r_np, x2)))
             / (np.linalg.norm(tall_np) ** 2 * np.linalg.norm(x2)
                * eps * np.sqrt(m2)))
    gate("geqrf", resid)
    sub["geqrf_fp32_m%d_n%d" % (m2, n2)] = round(geqrf_gf, 1)

    if fails:
        for f in fails:
            print(f"# FAILED residual gate: {f}", file=sys.stderr)
        sys.exit(1)

    vals = [gemm_gf, potrf_gf, getrf_gf, geqrf_gf]
    geomean = float(np.exp(np.mean(np.log(vals))))
    peak = {k: round(v / sub["gemm_fp32_n%d" % n], 3)
            for k, v in sub.items()}
    print(json.dumps({
        "metric": "factor_suite_fp32_geomean",
        "value": round(geomean, 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(geomean / BASELINE_GFLOPS, 2),
        "submetrics": sub,
        "fraction_of_measured_gemm": peak,
    }))
    print(f"# platform={jax.devices()[0].platform} "
          f"all residual gates passed", file=sys.stderr)


if __name__ == "__main__":
    main()
