"""Benchmark suite: gemm / potrf / getrf / geqrf throughput on one chip.

Reproduces the reference tester's metric — GFLOP/s from model flop counts
(``/root/reference/test/test_gemm.cc:244-245``, ``params.gflops()``) — at
the BASELINE.md configs (fp32, nb in the reference's 256-512 range or the
vendor-dispatch default):

* gemm  n=8192                      (config 1 scaled to the chip)
* potrf n=8192                      (config 2)
* getrf n=8192, nb=512              (config 3, single chip)
* geqrf m=32768 n=4096              (config 4)

``vs_baseline`` compares against the reference's only in-repo per-device
throughput anchor, 702 GFLOP/s/GPU (``/root/reference/docs/usage.md:36-44``).
The headline value is the geometric mean of the routines that ran; the
``submetrics`` key carries each routine's GFLOP/s and its fraction of the
measured gemm rate.

The gemm anchor is the LIBRARY's gemm (``blocks.matmul`` at the library
precision, 3-pass-bf16 HIGH, ~1.3e-5 max-rel — the same accuracy class
every factorization runs at), exactly as the reference tester times
``slate::gemm`` rather than raw cuBLAS.  The raw single-pass-bf16 MXU
rate (~2.5e-3 max-rel, not LAPACK-grade) is reported alongside as
``mxu_bf16_*`` for transparency; on this chip it is ~179 TF/s vs ~60
TF/s for the anchor (tools/probe_precision.py).

Timing: each routine is run iters times *chained inside one jit* (each
iteration's input depends on the previous result, so XLA cannot collapse
the chain) and the wall time is divided by iters.  This measures on-device
time the way the reference's MPI-barrier wall clock does
(``test/test_gemm.cc:224-245``) and amortizes the host↔device round-trip
latency of the tunnel (~100 ms) to a few percent.

Every number only prints after the routine passes a scaled-residual gate
(≤ 3 in units of eps·n, the reference's criterion ``test/test_gemm.cc:260``),
checked with O(n²) matrix-vector probes so the gate itself stays cheap.

Fault isolation (the round-2 lesson, BENCH_r02 lost to one flaky RPC):
each routine runs inside its own try/except with ONE retry; an infra error
(tunnel RPC, OOM, compile failure) drops that routine into the ``failed``
list but never kills the suite and never sets a nonzero exit code.  Only a
*residual-gate* failure — numerically wrong answers — exits nonzero, and
even then the JSON line with everything that passed is printed first.

Incremental output (the round-5 lesson: BENCH_r05.json came back empty
because the driver timed out before the suite's single final print —
rc=124, parsed=null): every routine flushes its own JSON line to stdout
the moment it completes (``{"routine": ..., "label": ..., "gflops":
...}``; failures flush ``{"routine": ..., "error": ...}``), so a SIGTERM
or timeout mid-suite keeps every number already measured.  The final
aggregate line — {"metric", "value", "unit", "vs_baseline", ...} — is
unchanged and remains the LAST line, so existing parsers that read only
the tail still work.

Watchdog (the rest of the round-5 root cause: one hung potrf_fp64 ate
the GLOBAL timeout): each routine runs under its own SIGALRM deadline
(``SLATE_TPU_BENCH_ROUTINE_TIMEOUT_S``, default 900 s) with a bounded
infra-retry count (one retry; deadline hits never retry), so a single
hung kernel costs at most its own deadline and the suite keeps going.
Each JSON line carries an ``"autotune"`` map of the backend decisions
(:mod:`slate_tpu.perf.autotune`) made while that routine ran, and the
aggregate line carries the full decision table — the measured numbers
are attributable to the kernels that produced them.

Global deadline budgeting (closes the r5 hole for good): set
``SLATE_TPU_BENCH_DEADLINE_S`` to one wall-clock budget and every
routine's SIGALRM deadline is derived from it — remaining budget split
evenly over remaining routines — so the whole suite provably finishes
inside the budget and the aggregate LAST line always flushes.  A
SIGTERM from an outer ``timeout`` triggers the same flush.  Every JSON
line additionally embeds a ``"metrics"`` DELTA from the runtime
registry (:mod:`slate_tpu.perf.metrics`, snapshot-and-diff around the
routine so each line is self-contained — the registry accumulates
process-wide) plus an ``"attribution"`` roofline gap report
(:mod:`slate_tpu.perf.attr`): per-stage flops/bytes placed on the
MXU/HBM roofline, joined with the routine's measured stage timers, with
a ranked bottleneck list.  The aggregate keeps the CUMULATIVE snapshot
and the full ``{label: attribution}`` map.  Compare artifacts with
``python tools/bench_diff.py BENCH_r03.json BENCH_r04.json [--explain]``
— the regression sentinel that exits nonzero on throughput drops and on
infra-shaped artifacts, and with ``--explain`` names the stage a drop
came from; render one artifact's roofline tables with ``python
tools/gap_report.py BENCH_r04.json``.

Batched serving throughput (round 8): the ``batched_posv`` /
``batched_gesv`` routines measure the many-problem drivers
(:mod:`slate_tpu.linalg.batched`) at (B=64, n=256) under the same
per-routine watchdog, emitting TWO families per routine — the GFLOP/s
label (``posv_batched_fp32_n256_b64``, roofline-attributed like any
other submetric) and the ``throughput_solves_per_s`` family: batched
solves/s, the Python loop-of-singles baseline
(``posv_loop_fp32_n256_solves_per_s``) and the
``..._speedup_vs_loop`` ratio the acceptance criterion pins (batched ≥
5× loop on TPU).  The sentinel judges ``*_solves_per_s`` rows
higher-is-better like GFLOP/s.
"""

import json
import os
import signal
import sys
import time
import traceback

import numpy as np

BASELINE_GFLOPS = 702.0  # reference docs/usage.md per-GPU gemm anchor

#: derived-submetric suffixes that are NOT GFLOP/s rates: excluded from
#: the headline geomean, and (with the wall-time/ratio families below)
#: from the fraction-of-gemm / low-anchor math.  ONE definition — the
#: four filter sites below share it, so the next derived family cannot
#: silently pollute the headline by missing a hand-copied tuple.
DERIVED_SUFFIXES = ("_frac_of_gemm", "_frac_of_split_gemm",
                    "_hbm_roundtrips", "_abft_overhead_pct",
                    "_over_floor", "_host_gb_transferred",
                    "_hbm_peak_gb")

#: everything a gemm-fraction would be unit salad for: wall seconds,
#: speedup ratios, and the derived families above.
NON_RATE_SUFFIXES = ("_s", "_speedup_vs_loop", "_rps",
                     "_slo_violations",
                     "_speedup_vs_single") + DERIVED_SUFFIXES

#: per-routine wall-clock deadline (seconds).  Each routine runs under
#: its own SIGALRM watchdog so ONE hung kernel (the round-5 lesson:
#: potrf_fp64 hung, consumed the driver's global timeout and zeroed the
#: whole artifact) can never starve the routines after it — it times
#: out alone, is recorded as an infra failure, and the suite moves on.
ROUTINE_TIMEOUT_S = float(os.environ.get("SLATE_TPU_BENCH_ROUTINE_TIMEOUT_S",
                                         "900"))

#: ONE global wall-clock budget (seconds) from which every routine's
#: SIGALRM deadline is DERIVED: before routine i runs, its deadline is
#: the remaining budget split evenly over the remaining routines (still
#: capped by ROUTINE_TIMEOUT_S).  Set this to comfortably less than the
#: outer driver timeout and the suite mathematically cannot be killed
#: from outside mid-flight: every routine either finishes or times out
#: inside the budget, and the aggregate LAST line flushes with whatever
#: completed — the BENCH_r05 failure shape (rc=124, parsed empty)
#: becomes unreachable.  0 (default) keeps the flat per-routine
#: deadline only.
DEADLINE_S = float(os.environ.get("SLATE_TPU_BENCH_DEADLINE_S", "0"))

#: routines get at least this much even when the budget is nearly spent
#: (enough to flush an infra line; a full compile won't fit, and that is
#: the point — fail fast, keep the artifact).
MIN_DEADLINE_S = 20.0


def _metrics_snapshot():
    """The metrics registry's JSON view (CUMULATIVE since process
    start) — the aggregate line's block; never allowed to kill the
    artifact."""
    try:
        from slate_tpu.perf import metrics

        return metrics.snapshot()
    except Exception:
        return {}


def _metrics_delta(before):
    """What the registry recorded SINCE ``before`` — the self-contained
    per-routine block.  The registry accumulates across the whole
    process, so a raw snapshot on a late routine's line would carry
    every earlier routine's counters/timers; snapshot-and-diff around
    each runner iteration keeps each line's ``metrics`` (and the
    ``attribution`` derived from it) about THAT routine only."""
    try:
        from slate_tpu.perf import metrics

        return metrics.snapshot_delta(before or {}, metrics.snapshot())
    except Exception:
        return {}


#: jax platform of device 0, set by main() — the roofline constant set
#: the attribution engine prices stages with
_PLATFORM = "tpu"


def _bb_record(kind, **fields):
    """Bench-lifecycle seam of the flight recorder (ISSUE 15): one
    event per routine phase so a forensic bundle names the routine the
    process died inside.  Never allowed to kill a routine."""
    try:
        from slate_tpu.perf import blackbox

        blackbox.record(kind, **fields)
    except Exception:
        pass


def _blackbox_bundle(reason, detail=""):
    """Dump (or point at) a flight-recorder bundle for an infra-shaped
    failure: the trigger respects the per-process dump cap, so a late
    failure past the cap still references the last bundle written —
    every degraded line points at A postmortem.  Returns
    ``{"path", "digest", "reason"}`` or None (recorder off / dump
    failed); never raises."""
    try:
        from slate_tpu.perf import blackbox

        if not blackbox.enabled():
            return None
        return blackbox.trigger(reason, detail) or blackbox.last_bundle()
    except Exception:
        return None


def _bundle_tag():
    """The active offline autotune bundle's identity (version/digest —
    ``SLATE_TPU_AUTOTUNE_BUNDLE``, slate_tpu/perf/sweep.py) or None:
    stamped on every JSON line and the aggregate so an artifact says
    whether its numbers came from a bundle-warm or probe-cold process
    (the sentinel NOTEs a change between rounds).  Never allowed to
    kill a line."""
    try:
        from slate_tpu.perf import autotune

        return autotune.bundle_info()
    except Exception:
        return None


def _probes_avoided(snapshot):
    """The ``probes_avoided`` counter family out of a metrics snapshot:
    how many decisions resolved probe-free from the bundle (exact +
    model), how many entries a quarantine masked, whether a stale
    bundle was rejected — the aggregate's bundle-effectiveness block."""
    counters = (snapshot or {}).get("counters") or {}
    fam = {k: v for k, v in counters.items()
           if k == "autotune.probes_avoided"
           or k.startswith("autotune.bundle.")}
    return fam or None


def _attribution(label, gflops, metrics_delta, autotune_tags):
    """The routine's roofline gap report (slate_tpu/perf/attr.py):
    analytical per-stage flops/bytes joined with this routine's
    measured timer deltas — or, when an ``SLATE_TPU_XPROF`` capture
    wrapped this routine, with the capture's per-stage DEVICE seconds
    (the report's ``compute_source`` says which rung won) — placed on
    the platform roofline.  Also feeds the per-stage ``roofline.*``
    gauges the Perfetto export renders as counter tracks.  None (and
    never an exception) when the label has no model."""
    try:
        from slate_tpu.perf import attr

        dev_prof = None
        try:
            from slate_tpu.perf import xprof

            dev_prof = xprof.last_profile()
        except Exception:
            pass
        rep = attr.attribute(label, gflops, metrics_snapshot=metrics_delta,
                             autotune=autotune_tags, platform=_PLATFORM,
                             device_profile=dev_prof)
        if rep:
            attr.record_rooflines(rep)
        return rep
    except Exception:
        return None


def _xprof_capture(label):
    """The routine's opt-in device-truth capture window
    (``SLATE_TPU_XPROF=<dir>`` — slate_tpu/perf/xprof.py); an inert
    context manager when the knob is unset or xprof cannot load.
    Never allowed to kill a routine."""
    try:
        from slate_tpu.perf import xprof

        xprof.clear()           # a stale capture must not join THIS line
        return xprof.capture(label)
    except Exception:
        import contextlib

        return contextlib.nullcontext()


def _device_mem():
    """``slate_tpu.debug.memory_stats()``, hardened — ``{}`` rather
    than ever killing a routine."""
    try:
        import slate_tpu.debug as _debug

        return _debug.memory_stats()
    except Exception:
        return {}


def _hbm_peak_gb(mem_before):
    """Per-routine HBM high-water delta (GB) against a pre-routine
    ``memory_stats`` block; None on backends without the allocator API
    (CPU CI) — the submetric is then simply absent, never a lie."""
    try:
        from slate_tpu.perf import xprof

        return xprof.hbm_peak_delta_gb(mem_before, _device_mem())
    except Exception:
        return None


class _RoutineTimeout(Exception):
    pass


def _init_platform():
    """First touch of the jax backend (where the r05 worker-hostname
    init RPC died).  The ``infra.init`` injection site lets the chaos
    tests drive the retry without a broken TPU."""
    from slate_tpu.resilience import inject

    inject.fault_here("infra.init")
    import jax

    return jax.devices()[0].platform


def _init_backend_with_retry():
    """ONE classified retry-with-backoff around TPU backend init (the
    resilience satellite: an r05-shaped transient init failure must
    produce a degraded-but-nonempty artifact, not an empty one).
    Returns ``(platform | None, retried_infra, error | None)`` —
    platform None means init failed even after the retry; the caller
    emits the degraded aggregate instead of crashing with no JSON."""
    from slate_tpu.resilience import retry as _retry

    retried = []

    def classify(e):
        # with_backoff consults the classifier only when a retry will
        # actually run, so this records true retries — a deterministic
        # (non-transient) first failure must NOT be tagged as one
        ok = _retry.transient_infra(e)
        if ok:
            retried.append(type(e).__name__)
        return ok

    try:
        platform, retries = _retry.with_backoff(
            _init_platform, attempts=2,
            base_s=float(os.environ.get("SLATE_TPU_INIT_BACKOFF_S",
                                        "2.0")),
            classify=classify)
        return platform, retries > 0, None
    except Exception as e:          # still down (or never retryable)
        return None, bool(retried), e


# ---------------------------------------------------------------------------
# Batched many-problem throughput (ISSUE 8) — the serving workload: B
# small/medium independent solves per launch (slate_tpu/linalg/batched).
# Module-level (unlike the big-matrix routines) so tests can run one
# routine without the whole suite.  Two submetric families per routine:
# the GFLOP/s label (roofline-attributed like every other submetric) and
# the throughput_solves_per_s family — batched solves/s, the Python
# loop-of-singles baseline, and the speedup ratio the acceptance
# criterion pins (batched ≥ 5× loop at n≤1024, B≥64 on TPU).
# ---------------------------------------------------------------------------

def _batched_suite(op_name, on_tpu, make_ops, batched_fn, single_fn,
                   model_fl, resid_fn, nbat, bsz):
    """Shared runner: chained-jit batched timing, loop-of-singles
    baseline, residual gate, the solves/s family."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    ops_np = make_ops()
    ops_dev = tuple(jnp.asarray(o) for o in ops_np)
    it = 8 if on_tpu else 2

    @jax.jit
    def chain(a, b):
        def body(i, bb):
            x = batched_fn(a, bb)
            return b + x * jnp.float32(1e-30)
        out = lax.fori_loop(0, it - 1, body, b)
        return batched_fn(a, out)[-1, -1]

    t = _timeit(chain, ops_dev, it)
    solves_per_s = bsz / t
    gf = model_fl * bsz / t / 1e9
    # loop-of-singles baseline: the SAME solve through the
    # single-problem driver facade, one dispatch per problem
    single = jax.jit(single_fn)
    jax.block_until_ready(single(ops_dev[0][0], ops_dev[1][0]))
    lb = min(bsz, 16 if on_tpu else 4)
    t0 = time.perf_counter()
    for i in range(lb):
        jax.block_until_ready(single(ops_dev[0][i], ops_dev[1][i]))
    loop_sps = lb / (time.perf_counter() - t0)
    x = np.asarray(jax.jit(batched_fn)(*ops_dev))
    resid = resid_fn(ops_np, x)
    label = "%s_batched_fp32_n%d_b%d" % (op_name, nbat, bsz)
    extra = {
        label + "_solves_per_s": round(solves_per_s, 1),
        "%s_loop_fp32_n%d_solves_per_s" % (op_name, nbat):
            round(loop_sps, 1),
        label + "_speedup_vs_loop":
            round(solves_per_s / max(loop_sps, 1e-9), 2),
    }
    return label, gf, resid, extra


def _batched_resid(ops_np, x, nbat):
    a, rhs = ops_np
    eps32 = float(np.finfo(np.float32).eps)
    r = np.linalg.norm(np.einsum("bij,bj->bi", a, x) - rhs, axis=-1)
    den = (np.linalg.norm(a, axis=(-2, -1))
           * np.linalg.norm(rhs, axis=-1) * eps32 * nbat)
    return float(np.max(r / np.maximum(den, 1e-300)))


def bench_batched_posv(on_tpu, nbat=None, bsz=64):
    import slate_tpu as st
    from slate_tpu.linalg import batched as bat

    nbat = nbat or (256 if on_tpu else 64)

    def make_ops():
        rng = np.random.default_rng(11)
        g = rng.standard_normal((bsz, nbat, nbat)).astype(np.float32)
        spd = (np.einsum("bij,bkj->bik", g, g)
               + nbat * np.eye(nbat, dtype=np.float32))
        rhs = rng.standard_normal((bsz, nbat)).astype(np.float32)
        return spd, rhs

    return _batched_suite(
        "posv", on_tpu, make_ops,
        lambda a, b: bat.posv_batched(a, b)[1],
        lambda a, b: st.posv(a, b)[1],
        nbat ** 3 / 3.0 + 2.0 * nbat * nbat,
        lambda ops_np, x: _batched_resid(ops_np, x, nbat), nbat, bsz)


def bench_batched_gesv(on_tpu, nbat=None, bsz=64):
    import slate_tpu as st
    from slate_tpu.linalg import batched as bat

    nbat = nbat or (256 if on_tpu else 64)

    def make_ops():
        rng = np.random.default_rng(12)
        a = (rng.standard_normal((bsz, nbat, nbat)).astype(np.float32)
             + nbat * np.eye(nbat, dtype=np.float32))
        rhs = rng.standard_normal((bsz, nbat)).astype(np.float32)
        return a, rhs

    return _batched_suite(
        "gesv", on_tpu, make_ops,
        lambda a, b: bat.gesv_batched(a, b)[2],
        lambda a, b: st.gesv(a, b)[2],
        2.0 * nbat ** 3 / 3.0 + 2.0 * nbat * nbat,
        lambda ops_np, x: _batched_resid(ops_np, x, nbat), nbat, bsz)


def bench_serve(on_tpu, n=None, nreq=None, max_batch=16):
    """Serve-path latency percentiles (ISSUE 10): drive the batched
    serving front door with 4 threaded submitters under live telemetry,
    read p50/p99 back from the SLO histograms
    (``serve.latency_ms.posv.*``, via the registry's stdlib quantile
    readback over the per-routine metrics DELTA so an earlier phase's
    samples can't leak in), and emit them as lower-is-better ``_ms``
    submetrics next to a served-solves GFLOP/s label.  The bucket is
    warmed with one request first so the percentiles measure SERVING,
    not the one-time executable compile warm start exists to remove."""
    import threading as _threading

    from slate_tpu.perf import metrics as _metrics
    from slate_tpu.perf import telemetry
    from slate_tpu.serve.queue import BatchQueue, ServeConfig, _bucket

    n = n or (256 if on_tpu else 48)
    nreq = nreq or (192 if on_tpu else 32)
    rng = np.random.default_rng(21)
    g = rng.standard_normal((n, n)).astype(np.float32)
    spd = g @ g.T + n * np.eye(n, dtype=np.float32)
    rhs = [rng.standard_normal(n).astype(np.float32) for _ in range(4)]
    # telemetry.on() also enables the metrics registry (the histograms
    # live there): restore BOTH afterwards, or this routine would
    # silently override an explicit SLATE_TPU_METRICS=0 opt-out for
    # every routine after it
    was_on = telemetry.enabled()
    was_metrics = _metrics.enabled()
    telemetry.on()
    srv = BatchQueue(ServeConfig(max_batch=max_batch, max_wait_s=0.002))
    try:
        srv.submit("posv", spd, rhs[0]).result(timeout=900)   # warm
        before = _metrics.snapshot()
        futs = [None] * nreq

        def worker(base):
            for i in range(base, nreq, 4):
                futs[i] = srv.submit("posv", spd, rhs[i % 4])

        t0 = time.perf_counter()
        threads = [_threading.Thread(target=worker, args=(k,))
                   for k in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        xs = [np.asarray(f.result(timeout=900)) for f in futs]
        wall = time.perf_counter() - t0
        delta = _metrics.snapshot_delta(before, _metrics.snapshot())
    finally:
        srv.close()
        if not was_on:
            telemetry.off()
        if not was_metrics:
            _metrics.off()
    hname = "serve.latency_ms.posv.fp32.n%d" % _bucket(n)
    qs = telemetry.quantiles_from_buckets(
        (delta.get("hists") or {}).get(hname), (0.5, 0.99))
    x, b = xs[0], rhs[0]
    eps = float(np.finfo(np.float32).eps)
    resid = (np.linalg.norm(spd @ x - b)
             / (np.linalg.norm(spd) * np.linalg.norm(b) * eps * n))
    gf = (n ** 3 / 3.0 + 2.0 * n * n) * nreq / wall / 1e9
    label = "serve_posv_fp32_n%d" % n
    extra = {}
    if qs:
        extra[label + "_p50_ms"] = round(qs[0.5], 3)
        extra[label + "_p99_ms"] = round(qs[0.99], 3)
    return label, gf, resid, extra


def bench_serve_fleet(on_tpu, nreq=None):
    """Fleet-router throughput under chaos (ISSUE 20): open-loop
    mixed-shape posv load against :class:`slate_tpu.serve.Router` —
    first a single-replica baseline, then the full fleet with a fault
    plan killing one replica MID-RUN.  Emits the sustained
    ``serve_fleet_rps`` (and its ``_speedup_vs_single`` ratio — the
    ≥ 2× acceptance sentinel), client-observed ``_p50_ms``/``_p99_ms``,
    and a ``_slo_violations`` sentinel counted over a POST-RECOVERY
    wave (the elastic-degradation claim: after drain → reverify →
    rejoin the fleet serves clean again).  Every answer is
    residual-gated; the routine's gf number is the served-solves
    GFLOP/s of the fleet phase.

    Off-TPU the host has no accelerator, so each dispatch carries an
    EMULATED device wall — the injection system's ``slow`` hook sleeps
    ``SLATE_TPU_FAULT_SLOW_S`` (default 50 ms) inside every dispatch,
    identically in both phases.  That is the quantity fleet serving
    exists to overlap (a real TPU batch blocks its dispatcher thread
    for the device wall the same way), and what makes the speedup
    measurable on a single-core CI host; on TPU no emulation is
    installed and the real device walls carry the comparison."""
    import threading as _threading

    import jax

    from slate_tpu.perf import blackbox as _bb
    from slate_tpu.perf import metrics as _metrics
    from slate_tpu.perf import telemetry
    from slate_tpu.resilience import inject
    from slate_tpu.serve import FleetConfig, Router, ServeConfig

    ndev = len(jax.devices())
    nrep = min(4, ndev)
    shapes = (96, 64, 48) if on_tpu else (48, 32, 24)
    nreq = nreq or (256 if on_tpu else 96)
    slo_ms = 2000.0
    rng = np.random.default_rng(33)
    probs = {}
    for n in shapes:
        g = rng.standard_normal((n, n)).astype(np.float32)
        probs[n] = (g @ g.T + n * np.eye(n, dtype=np.float32),
                    rng.standard_normal(n).astype(np.float32))

    def _check(n, x):
        a, b = probs[n]
        eps = float(np.finfo(np.float32).eps)
        return (np.linalg.norm(a @ x - b)
                / (np.linalg.norm(a) * np.linalg.norm(b) * eps * n))

    # the emulated device wall (see docstring): active through BOTH
    # phases off-TPU, absent on real hardware
    base_plan = "" if on_tpu else "serve.dispatch=slow:1.0"

    def run_phase(router, count, fault_plan=None):
        """Submit ``count`` mixed-shape requests from 4 open-loop
        submitters (optionally arming the chaos plan halfway), resolve
        them all, and return (wall_s, latencies, worst_resid)."""
        lat = [0.0] * count
        futs = [None] * count
        fault_at = count // 2 if fault_plan else None

        def worker(base):
            for i in range(base, count, 4):
                if fault_at is not None and i == fault_at:
                    inject.install(inject.parse_plan(fault_plan))
                n = shapes[i % len(shapes)]
                a, b = probs[n]
                ts = time.perf_counter()
                f = router.submit("posv", a, b)
                futs[i] = (f, ts, n)

        t0 = time.perf_counter()
        threads = [_threading.Thread(target=worker, args=(k,))
                   for k in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        worst = 0.0
        for i, (f, ts, n) in enumerate(futs):
            x = np.asarray(f.result(timeout=900))
            lat[i] = time.perf_counter() - ts
            if i % 8 == 0:              # gate a sample of every shape
                worst = max(worst, _check(n, x))
        return time.perf_counter() - t0, lat, worst

    cfg = ServeConfig(max_batch=4, max_wait_s=0.002, slo_ms=slo_ms)
    was_on = telemetry.enabled()
    was_metrics = _metrics.enabled()
    was_bb = _bb.enabled()
    telemetry.on()
    _bb.on()
    worst = 0.0
    try:
        # every phase serves pre-warmed (the cold-start story is the
        # PR 11 bundle's, measured elsewhere): the rps numbers compare
        # SERVING, not per-replica executable compiles
        warm_specs = [{"op": "posv", "batch": cfg.max_batch,
                       "dims": (n,), "dtype": "float32"}
                      for n in shapes]
        # phase 1: the single-replica baseline
        single = Router(FleetConfig(replicas=1, serve=cfg,
                                    enable_sharded=False))
        try:
            single.warm_start(specs=warm_specs)
            single.submit("posv", *probs[shapes[0]]).result(timeout=900)
            if base_plan:
                inject.install(inject.parse_plan(base_plan))
            wall1, _, r1 = run_phase(single, nreq)
        finally:
            single.close()
            inject.clear_plan()
        worst = max(worst, r1)
        rps_single = nreq / wall1
        # phase 2: the fleet, one replica killed mid-run
        fleet = Router(FleetConfig(replicas=nrep, serve=cfg,
                                   enable_sharded=False,
                                   cooldown_s=0.05))
        try:
            fleet.warm_start(specs=warm_specs)
            for n in shapes:
                fleet.submit("posv", *probs[n]).result(timeout=900)
            if base_plan:
                inject.install(inject.parse_plan(base_plan))
            wall2, lat, r2 = run_phase(
                fleet, nreq,
                fault_plan=(base_plan + "," if base_plan else "")
                + "fleet.replica1=device_loss:1.0:2")
            worst = max(worst, r2)
            # post-recovery wave: wait out the rejoin, then count SLO
            # violations over a fresh delta — the ~0 sentinel
            deadline = time.perf_counter() + 30.0
            while (fleet.replica_states().count("closed") < nrep
                   and time.perf_counter() < deadline):
                time.sleep(0.05)
            before = _metrics.snapshot()
            wall3, _, r3 = run_phase(fleet, max(16, nreq // 4))
            worst = max(worst, r3)
            delta = _metrics.snapshot_delta(before, _metrics.snapshot())
            viol = (delta.get("counters") or {}).get(
                "serve.slo.violations", 0.0)
        finally:
            fleet.close()
            inject.clear_plan()
    finally:
        if not was_bb:
            _bb.off()
        if not was_on:
            telemetry.off()
        if not was_metrics:
            _metrics.off()
    lat.sort()
    rps = nreq / wall2
    flops = sum((shapes[i % len(shapes)] ** 3 / 3.0
                 + 2.0 * shapes[i % len(shapes)] ** 2)
                for i in range(nreq))
    gf = flops / wall2 / 1e9
    label = "serve_fleet_fp32"
    extra = {
        label + "_rps": round(rps, 2),
        label + "_p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
        label + "_p99_ms": round(lat[min(len(lat) - 1,
                                         int(len(lat) * 0.99))] * 1e3,
                                 3),
        label + "_slo_violations": float(viol),
        label + "_speedup_vs_single": round(rps / max(rps_single, 1e-9),
                                            3),
    }
    return label, gf, worst, extra


#: per-stage wall-time attribution for the two-stage eig/SVD pipelines:
#: metric-timer keys (recorded by the drivers / the chase dispatch) →
#: the submetric suffix each lands under in the routine's JSON line, so
#: a BENCH_r* diff can attribute a heev/svd move to the stage that
#: caused it (stage 2's bulge chase specifically has its own key — the
#: autotuned `chase` site's hot section).
_HEEV_STAGES = {"stage1_s": "stage.heev.stage1",
                "stage2_s": "stage.heev.stage2",
                "stage2_chase_s": "chase.hb2st",
                "stage3_s": "stage.heev.stage3"}
_SVD_STAGES = {"stage1_s": "stage.svd.stage1",
               "stage2_s": "stage.svd.stage2",
               "stage2_chase_s": "chase.tb2bd",
               "stage3_s": "stage.svd.stage3"}

#: the QDWH spectral tier's stage timers (ISSUE 18): the polar loop and
#: D&C record stage.<ns>.{qr,chol,gemm} (linalg/polar.py); crossover
#: leaves falling back to the two-stage chain still land on
#: stage.<ns>.stage1 — carried so the leaf share is visible.
_QDWH_HEEV_STAGES = {"qr_s": "stage.heev.qr",
                     "chol_s": "stage.heev.chol",
                     "gemm_s": "stage.heev.gemm",
                     "stage1_s": "stage.heev.stage1"}
_QDWH_SVD_STAGES = {"qr_s": "stage.svd.qr",
                    "chol_s": "stage.svd.chol",
                    "gemm_s": "stage.svd.gemm",
                    "stage1_s": "stage.svd.stage1"}


def _stage_totals(stage_map):
    timers = _metrics_snapshot().get("timers", {})
    return {k: float(timers.get(v, {}).get("total_s", 0.0))
            for k, v in stage_map.items()}


def _stage_delta(label, stage_map, before):
    """Submetric dict of per-stage wall seconds accumulated since
    ``before`` (one timed driver call), keyed ``<label>_<stage>``."""
    after = _stage_totals(stage_map)
    return {"%s_%s" % (label, k): round(after[k] - before[k], 4)
            for k in stage_map}


def _partial_aggregate(sub, fails, infra, attribution=None,
                       blackbox_bundles=None):
    """The aggregate line's load-bearing fields from whatever completed
    so far — emitted by the hard watchdog so a hard hang still ends the
    artifact with a parseable LAST-line aggregate (the tail-reader
    contract) instead of a bare per-routine error line."""
    headline_keys = [k for k in sub
                     if k.startswith(("gemm_fp32", "potrf_fp32",
                                      "getrf_fp32", "geqrf_fp32",
                                      "gels_fp32"))
                     and not k.startswith("gemm_fp32_split")
                     and not k.endswith(DERIVED_SUFFIXES)]
    vals = [sub[k] for k in headline_keys
            if isinstance(sub[k], (int, float)) and sub[k] > 0]
    geomean = float(np.exp(np.mean(np.log(vals)))) if vals else 0.0
    out = {
        "metric": "factor_suite_fp32_geomean",
        "value": round(geomean, 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(geomean / BASELINE_GFLOPS, 2),
        "submetrics": dict(sub),
        "partial": True,
        "failed": list(fails) + [f"infra: {s}" for s in infra],
        "autotune": _autotune_tags(set()),
        "bundle": _bundle_tag(),
        "metrics": _metrics_snapshot(),
    }
    if attribution:
        out["attribution"] = dict(attribution)
    if blackbox_bundles:
        out["blackbox_bundles"] = list(blackbox_bundles)
    return out


def _run_with_deadline(fn, seconds, name="", on_hard_hang=None):
    """Run ``fn()`` under a SIGALRM deadline (main thread, POSIX).
    Falls back to an unguarded call where SIGALRM is unavailable.

    SIGALRM only interrupts Python bytecode: a hang INSIDE one blocking
    C call (a libtpu RPC that never returns — the r5 potrf_fp64 mode)
    never re-enters the interpreter, so the handler can't raise.  A
    daemon-thread hard watchdog backstops that case at 1.5×deadline+60s:
    it flushes this routine's infra line plus a partial AGGREGATE line
    (``on_hard_hang``) and ``os._exit(0)``s — the artifact keeps every
    number already measured AND ends in a parseable aggregate, and the
    exit code stays 0 per the suite's infra-failures-never-fail
    contract."""
    if not hasattr(signal, "SIGALRM") or seconds <= 0:
        return fn()

    def _on_alarm(signum, frame):
        raise _RoutineTimeout(f"exceeded {seconds:.0f}s routine deadline")

    def _hard_exit():
        try:
            if on_hard_hang is not None:
                on_hard_hang()
        finally:
            print(f"# {name}: hard-hung (uninterruptible C call); exiting "
                  "to preserve the artifact", file=sys.stderr, flush=True)
            os._exit(0)

    import threading
    hard = threading.Timer(1.5 * seconds + 60.0, _hard_exit)
    hard.daemon = True
    prev = signal.signal(signal.SIGALRM, _on_alarm)
    signal.setitimer(signal.ITIMER_REAL, seconds)
    hard.start()
    try:
        return fn()
    finally:
        hard.cancel()
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, prev)


def _autotune_tags(keys_before):
    """Autotune decisions made since ``keys_before`` — the backends this
    routine actually ran on (tagged into its JSON line)."""
    try:
        from slate_tpu.perf import autotune

        dec = autotune.decisions()
        return {k: v for k, v in dec.items() if k not in keys_before}
    except Exception:
        return {}


def _autotune_keys():
    try:
        from slate_tpu.perf import autotune

        return set(autotune.decisions())
    except Exception:
        return set()


def _timed_in_window(keys_before, sites):
    """Did a decision for one of ``sites`` land since ``keys_before``
    with source "timed" — i.e. ``decide()`` actually probed candidates
    (tracing the losers into the current routine's metrics delta)?
    Forced pins, bundle hits, cache hits and static fallbacks run zero
    candidates, so their windows stay clean — and an unrelated site's
    probe in the same window must not count."""
    try:
        from slate_tpu.perf import autotune

        return any(k.split("|", 1)[0] in sites
                   and v.get("source") == "timed"
                   for k, v in autotune.table().decisions.items()
                   if k not in keys_before)
    except Exception:
        return False


def _timeit(fn, args, iters):
    float(fn(*args))  # compile + warm
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(fn(*args))
        times.append(time.perf_counter() - t0)
    return min(times) / iters


def _abft_overhead_pct(run_eager, reps: int = 2):
    """``<label>_abft_overhead_pct`` (ISSUE 14): wall overhead of the
    SAME eager driver call with ``SLATE_TPU_ABFT=correct`` vs off —
    the checksum carriage + per-step verify cost as a percentage.  The
    ABFT layer is host-side/eager-only, so both sides time the eager
    path (an apples-to-apples pair; the jitted chain above stays the
    headline number).  Judged lower-is-better with a pinned 10%%
    ceiling by the sentinel (``perf/regress.py``), excluded from the
    headline geomean / frac-of-gemm / low-anchor math.  None (submetric
    omitted) when either side fails OR when the probe would be slow —
    the probe runs inside the routine's SIGALRM deadline BEFORE the
    headline number flushes, so after timing the abft-off side the
    projected remaining cost (warm + reps of the slower abft-on side)
    must fit ``budget_s`` or the probe bails with the measured number
    intact (the BENCH_r05 flush-first contract)."""

    def _wall():
        run_eager()                      # warm (compiles once per mode)
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            run_eager()
            times.append(time.perf_counter() - t0)
        return min(times)

    budget_s = 120.0
    prev = os.environ.get("SLATE_TPU_ABFT")
    try:
        os.environ.pop("SLATE_TPU_ABFT", None)
        t_off = _wall()
        # the eager ABFT loop's per-step host syncs can run well past
        # 2x the plain eager wall at small dims: project generously
        if t_off * 4.0 * (reps + 2) > budget_s:
            return None          # too slow for the watchdog window
        os.environ["SLATE_TPU_ABFT"] = "correct"
        t_on = _wall()
    except _RoutineTimeout:
        # the probe crossed the routine's SIGALRM deadline: this MUST
        # reach _run_routine's infra classification — swallowing it
        # here would record a blown deadline as a clean success
        raise
    except Exception:
        return None
    finally:
        if prev is None:
            os.environ.pop("SLATE_TPU_ABFT", None)
        else:
            os.environ["SLATE_TPU_ABFT"] = prev
    if t_off <= 0:
        return None
    return round((t_on / t_off - 1.0) * 100.0, 2)


def _run_routine(name, fn, sub, fails, infra, deadline=None,
                 attr_sink=None, bb_sink=None):
    """Run one routine under its own watchdog with a bounded infra-error
    retry count; classify failures.

    ``fn`` returns (label, gflops, scaled_resid [, extra_sub]).  Residual
    failures go to ``fails`` (the only thing that makes the suite exit
    nonzero); infrastructure exceptions go to ``infra``.  A routine that
    hits its SIGALRM deadline is recorded as infra WITHOUT retry (a hung
    kernel would just hang again and eat a second deadline).

    ``deadline`` overrides the flat ROUTINE_TIMEOUT_S — the global
    budgeting in :func:`main` derives it from SLATE_TPU_BENCH_DEADLINE_S
    (remaining budget / remaining routines).

    Every emitted JSON line carries the routine's metrics DELTA
    (snapshot-and-diff around this iteration — self-contained per
    routine) and, on success, the roofline ``attribution`` block
    derived from it; ``attr_sink`` collects the blocks for the
    aggregate line.
    """
    last_err = None
    keys_before = _autotune_keys()
    if deadline is None:
        deadline = ROUTINE_TIMEOUT_S
    snap_before = _metrics_snapshot()

    def _on_hard_hang():
        # snap_before rebinds per attempt: the hard-hang line's delta
        # covers only the attempt that hung
        bb = _blackbox_bundle("bench.watchdog",
                              f"{name}: hard-hung in a blocking C call")
        line = {"routine": name,
                "error": "infra: hard-hung in a blocking C "
                         "call past the SIGALRM deadline",
                "autotune": _autotune_tags(keys_before),
                "bundle": _bundle_tag(),
                "metrics": _metrics_delta(snap_before)}
        if bb:
            line["blackbox"] = bb
            if bb_sink is not None:
                bb_sink.append(dict(bb, routine=name))
        print(json.dumps(line), flush=True)
        print(json.dumps(_partial_aggregate(
            sub, fails, infra + [f"{name}: hard-hung"],
            attribution=attr_sink, blackbox_bundles=bb_sink)),
            flush=True)

    for attempt in range(2):
        try:
            if attempt:           # a retry's delta must not carry the
                snap_before = _metrics_snapshot()   # failed attempt's
            from slate_tpu.resilience import inject as _inj

            _bb_record("bench.routine", name=name, phase="start",
                       attempt=attempt)
            # chaos seam: an injected routine-startup fault takes the
            # same classified-infra retry path a real one would
            _inj.fault_here("bench.startup")
            mem_before = _device_mem()
            with _xprof_capture(name):
                out = _run_with_deadline(fn, deadline, name=name,
                                         on_hard_hang=_on_hard_hang)
            label, gf, resid = out[0], out[1], out[2]
            tags = _autotune_tags(keys_before)
            delta = _metrics_delta(snap_before)
            if resid > 3.0:
                fails.append(f"{name}: scaled_resid={resid:.3e} > 3")
                _bb_record("bench.routine", name=name,
                           phase="residual_fail", resid=float(resid))
                print(json.dumps({"routine": name, "label": label,
                                  "error": "residual_gate",
                                  "scaled_resid": float(resid),
                                  "autotune": tags,
                                  "bundle": _bundle_tag(),
                                  "metrics": delta}),
                      flush=True)
                return None
            if len(out) > 3:   # auxiliary submetrics, gated like the rest
                sub.update(out[3])
            sub[label] = round(gf, 1)
            # flush this routine's line NOW: a later timeout/SIGTERM must
            # never lose a number already measured (BENCH_r05 lesson) —
            # aux submetrics, the autotuner's chosen backends, the
            # metrics delta and the roofline attribution ride along for
            # the same reason
            line = {"routine": name, "label": label,
                    "gflops": round(gf, 1), "scaled_resid": float(resid),
                    "autotune": tags,
                    "bundle": _bundle_tag(),
                    "metrics": delta}
            rep = _attribution(label, gf, delta, tags)
            if rep is not None:
                line["attribution"] = rep
                if attr_sink is not None:
                    attr_sink[label] = rep
            if label.startswith(("getrf_fp32", "potrf_fp32")) \
                    and (delta.get("counters") or {}):
                # structural submetric (ISSUE 12): materialized
                # inter-stage HBM round trips per factorization — 0 on
                # the fused/full depths, judged lower-is-better by the
                # sentinel, excluded from every GFLOP/s aggregate.  A
                # probing window is contaminated: decide() traces the
                # LOSING depth candidates inside this routine's delta,
                # so when a factorization-site decision was actually
                # TIMED in-window the shipped depth's model count
                # (already reconciled against the live counter in CI)
                # stands in for the raw counter.  Forced pins, bundle
                # hits and static fallbacks run zero candidates — their
                # raw counter is clean and stays authoritative (the
                # bundle-warm fresh-replica case must keep measuring).
                probed = _timed_in_window(
                    keys_before, ("lu_step", "potrf_step",
                                  "lu_driver", "potrf_panel"))
                if probed and rep is not None:
                    rt = rep["hbm_roundtrips"]["model"]
                else:
                    rt = (delta.get("counters") or {}).get(
                        "step.hbm_roundtrips", 0.0)
                sub[label + "_hbm_roundtrips"] = float(rt)
            peak_gb = _hbm_peak_gb(mem_before)
            if peak_gb is not None:
                # device-memory submetric (ISSUE 19): per-routine HBM
                # high-water, lower-is-better, excluded from the
                # GFLOP/s aggregates like the other derived families;
                # absent on backends without the allocator API
                sub[label + "_hbm_peak_gb"] = round(float(peak_gb), 6)
            if len(out) > 3:
                line.update(out[3])
            print(json.dumps(line), flush=True)
            _bb_record("bench.routine", name=name, phase="ok",
                       label=label)
            return gf
        except _RoutineTimeout as e:  # hung kernel: no retry, move on
            last_err = e
            _bb_record("bench.routine", name=name, phase="deadline")
            print(f"# {name} hit its routine deadline: {e}", file=sys.stderr)
            break
        except Exception as e:  # infra: tunnel RPC, OOM, compile, ...
            last_err = e
            _bb_record("bench.routine", name=name, phase="infra_error",
                       error=type(e).__name__)
            traceback.print_exc(file=sys.stderr)
            print(f"# retry {name} after infra error (attempt {attempt})",
                  file=sys.stderr)
    infra.append(f"{name}: {type(last_err).__name__}: {last_err}")
    # the flight-recorder postmortem rides the flushed infra line: a
    # degraded artifact points at its own bundle (path + digest), and
    # the aggregate collects them so the regression sentinel can
    # surface each as a NOTE row
    bb = _blackbox_bundle(
        "bench.watchdog" if isinstance(last_err, _RoutineTimeout)
        else "bench.infra",
        f"{name}: {type(last_err).__name__}: {last_err}")
    line = {"routine": name,
            "error": f"infra: {type(last_err).__name__}: {last_err}",
            "autotune": _autotune_tags(keys_before),
            "bundle": _bundle_tag(),
            "metrics": _metrics_delta(snap_before)}
    if bb:
        line["blackbox"] = bb
        if bb_sink is not None:
            bb_sink.append(dict(bb, routine=name))
    print(json.dumps(line), flush=True)
    return None


def main():
    import os

    import jax
    import jax.numpy as jnp
    from jax import lax

    # wall-time budget: the REQUIRED submetric set (fp32 factor suite +
    # the four fp64 entries the round contract names) always runs — the
    # r4 mis-ordering protected the fp32 headline and sacrificed
    # exactly the configs the round was asked to cover (VERDICT r4
    # Weak #3).  The budget now only guards true extras, and the fp64
    # anchors run immediately after their fp32 siblings so a late kill
    # loses the least-important tail first.
    budget_s = float(os.environ.get("SLATE_TPU_BENCH_BUDGET_S", "3300"))
    t_start = time.perf_counter()
    skipped = []

    def over_budget(name):
        if time.perf_counter() - t_start > budget_s:
            skipped.append(name)
            return True
        return False


    platform, retried_infra, init_err = _init_backend_with_retry()
    if platform is None:
        # degraded-but-nonempty artifact: a parseable per-routine error
        # line plus an aggregate LAST line, exit 0 (infra never fails
        # the suite) — the r05 "rc=124, parsed=null" shape is dead
        print(json.dumps({"routine": "_suite",
                          "error": "infra: backend init failed"
                                   + (" after retry" if retried_infra
                                      else "")
                                   + f": {init_err}"}), flush=True)
        agg = _partial_aggregate({}, [], [f"init: {init_err}"])
        if retried_infra:
            agg["retried_infra"] = True
        print(json.dumps(agg), flush=True)
        print("# backend init failed%s: %s"
              % (" after retry" if retried_infra else "", init_err),
              file=sys.stderr)
        return
    if retried_infra:
        print("# backend init succeeded on retry (transient infra "
              "error absorbed)", file=sys.stderr)
    on_tpu = platform == "tpu"
    global _PLATFORM
    _PLATFORM = "tpu" if on_tpu else "cpu"
    scale = 1 if on_tpu else 8
    eps = float(np.finfo(np.float32).eps)
    sub = {}
    fails = []   # residual-gate failures → exit 1 (after printing JSON)
    infra = []   # infrastructure failures → recorded, exit stays 0
    attr_map = {}   # label -> roofline attribution block (aggregate)
    bb_sink = []    # flight-recorder bundles attached to infra lines

    # the bench run is an observability harness: turn the metrics
    # registry on (host-side counters only — it never changes the
    # compiled programs) so every JSON line carries the snapshot;
    # SLATE_TPU_METRICS=0 opts out.  The flight recorder rides along
    # under the same contract (SLATE_TPU_BLACKBOX=0 opts out) so an
    # infra-classified failure, watchdog timeout or SIGTERM flush can
    # attach its forensic bundle to the flushed JSON line.
    try:
        from slate_tpu.perf import metrics as _metrics_mod

        if os.environ.get("SLATE_TPU_METRICS", "").strip().lower() \
                not in ("0", "false", "off", "no"):
            _metrics_mod.on()
    except Exception:
        pass
    try:
        if os.environ.get("SLATE_TPU_BLACKBOX", "").strip().lower() \
                not in ("0", "false", "off", "no"):
            from slate_tpu.perf import blackbox as _blackbox_mod

            _blackbox_mod.on()
    except Exception:
        pass

    # an outer `timeout` sends SIGTERM before SIGKILL: flush the
    # aggregate LAST line with whatever completed so the artifact stays
    # parseable (the other half of the BENCH_r05 root cause — the suite
    # died with every number buffered behind one final print)
    def _on_sigterm(signum, frame):
        bb = _blackbox_bundle("bench.sigterm",
                              "SIGTERM before suite completion")
        line = {"routine": "_suite",
                "error": "infra: SIGTERM before completion"}
        if bb:
            line["blackbox"] = bb
            bb_sink.append(dict(bb, routine="_suite"))
        print(json.dumps(line), flush=True)
        print(json.dumps(_partial_aggregate(
            sub, fails, infra + ["suite: SIGTERM"],
            attribution=attr_map, blackbox_bundles=bb_sink)),
            flush=True)
        os._exit(0)

    if hasattr(signal, "SIGTERM"):
        signal.signal(signal.SIGTERM, _on_sigterm)

    def mv(mat, x):
        return mat @ x

    n = 8192 // scale
    iters = 8 if on_tpu else 2

    # ---- gemm --------------------------------------------------------
    def bench_gemm():
        rng = np.random.default_rng(0)  # per-routine stream: a retry cannot shift later routines
        a_np = rng.standard_normal((n, n)).astype(np.float32)
        b_np = rng.standard_normal((n, n)).astype(np.float32)
        a = jnp.asarray(a_np)
        b = jnp.asarray(b_np)

        from slate_tpu.ops import blocks

        gemm_iters = 4 * iters

        @jax.jit
        def gemm_chain(a, b):
            def body(i, x):
                return blocks.matmul(x, b) * jnp.float32(1e-4)
            return lax.fori_loop(0, gemm_iters, body, a)[0, 0]

        t = _timeit(gemm_chain, (a, b), gemm_iters)
        gf = 2.0 * n ** 3 / t / 1e9

        # single-pass bf16 MXU ceiling probe PER SIZE (was a one-off on
        # the largest n only): the bf16 roofline lane (perf/attr.py)
        # prices split-gemm labels against this ceiling, so it needs
        # the measured number at every dim the suite reports
        extra = {}
        for s in sorted({n // 4, n // 2, n}):
            if s < 128:
                continue
            asz, bsz = a[:s, :s], b[:s, :s]

            @jax.jit
            def raw_chain(a, b):
                def body(i, x):
                    return (x @ b) * jnp.float32(1e-4)
                return lax.fori_loop(0, gemm_iters, body, a)[0, 0]

            t_raw = _timeit(raw_chain, (asz, bsz), gemm_iters)
            extra["mxu_bf16_n%d" % s] = round(2.0 * s ** 3 / t_raw / 1e9,
                                              1)
        c_np = np.asarray(jax.jit(blocks.matmul)(a, b))
        x = rng.standard_normal((n,)).astype(np.float32)
        resid = (np.linalg.norm(mv(c_np, x) - mv(a_np, mv(b_np, x)))
                 / (np.linalg.norm(a_np) * np.linalg.norm(mv(b_np, x))
                    * eps * n))
        return "gemm_fp32_n%d" % n, gf, resid, extra


    # ---- gemm fp32 split (bf16x3: error-free fp32 trailing-update
    # grade on the MXU's bf16 peak, ops/split_gemm.py).  Reported as
    # its own submetric so the sentinel floor below and the
    # *_frac_of_split_gemm family have a measured anchor; the headline
    # geomean excludes it (it is an alternate lowering of the same
    # gemm, not another routine).
    def bench_gemm_split():
        rng = np.random.default_rng(0)
        a_np = rng.standard_normal((n, n)).astype(np.float32)
        b_np = rng.standard_normal((n, n)).astype(np.float32)
        a = jnp.asarray(a_np)
        b = jnp.asarray(b_np)

        from slate_tpu.ops.split_gemm import matmul_split3

        gemm_iters = 4 * iters

        @jax.jit
        def chain(a, b):
            def body(i, x):
                return matmul_split3(x, b) * jnp.float32(1e-4)
            return lax.fori_loop(0, gemm_iters, body, a)[0, 0]

        t = _timeit(chain, (a, b), gemm_iters)
        gf = 2.0 * n ** 3 / t / 1e9
        c_np = np.asarray(jax.jit(matmul_split3)(a, b))
        x = rng.standard_normal((n,)).astype(np.float32)
        resid = (np.linalg.norm(mv(c_np, x) - mv(a_np, mv(b_np, x)))
                 / (np.linalg.norm(a_np) * np.linalg.norm(mv(b_np, x))
                    * eps * n))
        return "gemm_fp32_split_n%d" % n, gf, resid


    # ---- gemm fp64 (config 2 anchor, right after its fp32 sibling) --
    # TPU matrix units are fp32/bf16; fp64 rides the Ozaki int8-slice
    # MXU path (ops/ozaki.py) under blocks.matmul — measured ~3.7x
    # XLA's software emulation at fp64-grade accuracy.  The fp64
    # routines are expressed as a fraction of THIS anchor (the
    # reference's A100 does native fp64 — the one place the hardware
    # class differs; BASELINE.md notes it).
    n64 = (4096 if on_tpu else 512)
    def bench_gemm64():
        import jax
        jax.config.update("jax_enable_x64", True)
        from slate_tpu.ops import blocks
        rng = np.random.default_rng(5)
        a_np = rng.standard_normal((n64, n64))
        b_np = rng.standard_normal((n64, n64))
        a = jnp.asarray(a_np, jnp.float64)
        b = jnp.asarray(b_np, jnp.float64)

        g_iters = 8 if on_tpu else 2

        @jax.jit
        def chain64(a, b):
            def body(i, x):
                return blocks.matmul(x, b) * jnp.float64(1e-4)
            return lax.fori_loop(0, g_iters, body, a)[0, 0]

        t = _timeit(chain64, (a, b), g_iters)
        gf = 2.0 * n64 ** 3 / t / 1e9
        c = np.asarray(jax.jit(blocks.matmul)(a, b))
        x = rng.standard_normal(n64)
        e64 = 10.0 * float(np.finfo(np.float64).eps)
        resid = (np.linalg.norm(c @ x - a_np @ (b_np @ x))
                 / (np.linalg.norm(a_np) * np.linalg.norm(b_np @ x)
                    * e64 * n64))
        return "gemm_fp64_n%d" % n64, gf, resid


    # ---- potrf -------------------------------------------------------
    def bench_potrf():
        rng = np.random.default_rng(1)  # per-routine stream: a retry cannot shift later routines
        g = rng.standard_normal((n, n)).astype(np.float32)
        spd_np = g @ g.T + n * np.eye(n, dtype=np.float32)
        spd = jnp.asarray(spd_np)

        from slate_tpu.ops import blocks

        po_iters = (4 * iters) if on_tpu else iters

        @jax.jit
        def potrf_chain(spd):
            def body(i, x):
                l = blocks.potrf_panels(x, 512)
                return spd + l[-1, -1] * jnp.float32(1e-30)
            out = lax.fori_loop(0, po_iters, body, spd)
            return blocks.potrf_panels(out, 512)[-1, -1]

        t = _timeit(potrf_chain, (spd,), po_iters + 1)
        gf = n ** 3 / 3.0 / t / 1e9
        l_np = np.asarray(jax.jit(
            lambda a: blocks.potrf_panels(a, 512))(spd))
        x = rng.standard_normal((n,)).astype(np.float32)
        resid = (np.linalg.norm(mv(l_np, mv(l_np.T, x)) - mv(spd_np, x))
                 / (np.linalg.norm(spd_np) * np.linalg.norm(x) * eps * n))
        label = "potrf_fp32_n%d" % n
        from slate_tpu.linalg.cholesky import potrf as potrf_driver
        over = _abft_overhead_pct(
            lambda: jax.block_until_ready(potrf_driver(spd).data))
        aux = ({label + "_abft_overhead_pct": over}
               if over is not None else {})
        return label, gf, resid, aux


    # ---- potrf fp64 (config 2, right after its fp32 sibling) --------
    # f32 Pallas panel + two fp64 Newton steps + Ozaki trailing gemms
    # (blocks.potrf_panels_f64) — ~5x the r4 emulated rate
    def bench_potrf64():
        import jax
        jax.config.update("jax_enable_x64", True)
        rng = np.random.default_rng(6)
        g = rng.standard_normal((n64, n64))
        spd_np = g @ g.T + n64 * np.eye(n64)
        spd = jnp.asarray(spd_np, jnp.float64)
        import slate_tpu as st
        from slate_tpu.enums import Uplo

        def po(x):
            return st.potrf(st.HermitianMatrix(x, uplo=Uplo.Lower)).data

        @jax.jit
        def chain(x):
            l = po(x)
            return po(x + l[-1, -1] * jnp.float64(1e-30))[-1, -1]

        t = _timeit(chain, (spd,), 2)
        gf = n64 ** 3 / 3.0 / t / 1e9
        l_np = np.asarray(jax.jit(po)(spd))
        l_np = np.tril(l_np)
        x = rng.standard_normal(n64)
        e64 = 10.0 * float(np.finfo(np.float64).eps)   # emulated fp64
        resid = (np.linalg.norm(l_np @ (l_np.T @ x) - spd_np @ x)
                 / (np.linalg.norm(spd_np) * np.linalg.norm(x)
                    * e64 * n64))
        return "potrf_fp64_n%d" % n64, gf, resid


    # ---- getrf (partial-pivot LU, nb=512) ----------------------------
    # runs the SHIPPED PartialPiv dispatch (_getrf_partial): on TPU the
    # autotuned lu_driver decision picks the scattered fused-panel
    # driver where it wins, and the decision is tagged into this
    # routine's JSON line — the measured path is the default path
    def bench_getrf():
        rng = np.random.default_rng(2)  # per-routine stream: a retry cannot shift later routines
        nb_lu = 512 // scale
        am_np = (rng.standard_normal((n, n)).astype(np.float32)
                 + n * np.eye(n, dtype=np.float32))
        am = jnp.asarray(am_np)
        lu_iters = 12 if on_tpu else 2

        from slate_tpu.linalg import lu as lu_mod

        def getrf_run(x):
            return lu_mod._getrf_partial(x, nb_lu)

        @jax.jit
        def getrf_chain(am):
            def body(i, x):
                lu, piv = getrf_run(x)
                return am + lu[-1, -1] * jnp.float32(1e-30)
            out = lax.fori_loop(0, lu_iters - 1, body, am)
            return getrf_run(out)[0][-1, -1]

        t = _timeit(getrf_chain, (am,), lu_iters)
        gf = 2.0 * n ** 3 / 3.0 / t / 1e9
        lu_np, perm_np = map(np.asarray, jax.jit(getrf_run)(am))
        l_f = np.tril(lu_np, -1) + np.eye(n, dtype=np.float32)
        u_f = np.triu(lu_np)
        x = rng.standard_normal((n,)).astype(np.float32)
        resid = (np.linalg.norm(mv(l_f, mv(u_f, x)) - mv(am_np[perm_np], x))
                 / (np.linalg.norm(am_np) * np.linalg.norm(x) * eps * n))
        label = "getrf_fp32_n%d_nb%d" % (n, nb_lu)
        over = _abft_overhead_pct(
            lambda: jax.block_until_ready(getrf_run(am)[0]))
        aux = ({label + "_abft_overhead_pct": over}
               if over is not None else {})
        return label, gf, resid, aux


    # ---- geqrf (tall QR, vendor dispatch) ----------------------------
    def bench_geqrf():
        rng = np.random.default_rng(3)  # per-routine stream: a retry cannot shift later routines
        m2, n2 = 32768 // scale, 4096 // scale
        tall_np = rng.standard_normal((m2, n2)).astype(np.float32)
        tall = jnp.asarray(tall_np)
        qr_iters = 8 if on_tpu else 2

        if on_tpu:
            from slate_tpu.linalg.qr import geqrf_panels

            def geqrf_raw(x):
                return geqrf_panels(x, 512)
        else:
            def geqrf_raw(x):
                h, tau = jnp.linalg.qr(x, mode="raw")
                return jnp.swapaxes(h, -1, -2), tau

        @jax.jit
        def geqrf_chain(tall):
            def body(i, x):
                f2, taus = geqrf_raw(x)
                return tall + f2[-1, -1] * jnp.float32(1e-30)
            out = lax.fori_loop(0, qr_iters - 1, body, tall)
            return geqrf_raw(out)[0][-1, -1]

        t = _timeit(geqrf_chain, (tall,), qr_iters)
        qr_flops = 2.0 * m2 * n2 ** 2 - 2.0 * n2 ** 3 / 3.0
        gf = qr_flops / t / 1e9
        r_np = np.triu(np.asarray(jax.jit(geqrf_raw)(tall)[0])[:n2])
        x2 = rng.standard_normal((n2,)).astype(np.float32)
        # Gram identity AᵀA = RᵀR probed with a vector
        resid = (np.linalg.norm(mv(tall_np.T, mv(tall_np, x2))
                                - mv(r_np.T, mv(r_np, x2)))
                 / (np.linalg.norm(tall_np) ** 2 * np.linalg.norm(x2)
                    * eps * np.sqrt(m2)))
        return "geqrf_fp32_m%d_n%d" % (m2, n2), gf, resid


    # ---- gels (config 4: least squares, m=32768 n=4096) -------------
    def bench_gels():
        rng = np.random.default_rng(4)
        m2, n2 = 32768 // scale, 4096 // scale
        a_np = rng.standard_normal((m2, n2)).astype(np.float32)
        b_np = rng.standard_normal((m2,)).astype(np.float32)
        a = jnp.asarray(a_np)
        b = jnp.asarray(b_np)
        import slate_tpu as st

        gl_iters = 4 if on_tpu else 2

        @jax.jit
        def gels_chain(a, b):
            def body(i, x):
                xs = st.gels(a, x)
                pad = jnp.zeros((a.shape[0] - xs.shape[0],), a.dtype)
                return b + jnp.concatenate([xs, pad]) * jnp.float32(1e-30)
            out = lax.fori_loop(0, gl_iters - 1, body, b)
            return st.gels(a, out)[-1]

        t = _timeit(gels_chain, (a, b), gl_iters)
        fl = 2.0 * m2 * n2 ** 2 - 2.0 * n2 ** 3 / 3.0 + 4.0 * m2 * n2
        gf = fl / t / 1e9
        x_np = np.asarray(jax.jit(lambda a, b: st.gels(a, b))(a, b))
        # normal-equations residual: Aᵀ(Ax − b) ≈ 0
        r = a_np.T @ (a_np @ x_np - b_np)
        resid = (np.linalg.norm(r)
                 / (np.linalg.norm(a_np) ** 2 * np.linalg.norm(x_np)
                    * eps * np.sqrt(m2)))
        return "gels_fp32_m%d_n%d" % (m2, n2), gf, resid


    # ---- heev / svd fp32 (BASELINE config 5, n ≥ 8192 on chip) -------
    # the two-stage eig/svd pipelines at the library's native MXU
    # precision class — previously unmeasured at fp32 anywhere
    # (VERDICT r5 weak #5); the fraction-of-gemm anchor is
    # informational (the middle stage runs partly on host), so these
    # stay out of the headline geomean and the below-10% flag
    nev32 = 8192 // scale

    def bench_heev32():
        rng = np.random.default_rng(9)
        g = rng.standard_normal((nev32, nev32)).astype(np.float32)
        herm_np = ((g + g.T) / 2).astype(np.float32)
        import slate_tpu as st
        from slate_tpu.enums import Uplo
        hm = st.HermitianMatrix(jnp.asarray(herm_np), uplo=Uplo.Lower)
        # warm the jit cache AND sync: dispatch is async, so an
        # unsynced warm run would bleed into the timed region
        jax.block_until_ready(st.heev(hm, jobz=True))
        stages0 = _stage_totals(_HEEV_STAGES)
        t0 = time.perf_counter()
        w, z = st.heev(hm, jobz=True)
        w = np.asarray(w); z = np.asarray(z)
        t = time.perf_counter() - t0
        gf = (4.0 / 3.0) * nev32 ** 3 / t / 1e9
        # 10·eps32 allowance, like the fp64 entries: the two-stage
        # pipeline accumulates over n/nb band/chase stages
        e32 = 10.0 * eps
        resid = (np.linalg.norm(herm_np @ z - z * w[None, :])
                 / (np.linalg.norm(herm_np) * nev32 * e32))
        label = "heev_fp32_n%d" % nev32
        return label, gf, resid, _stage_delta(label, _HEEV_STAGES, stages0)


    def bench_svd32():
        rng = np.random.default_rng(10)
        a_np = rng.standard_normal((nev32, nev32)).astype(np.float32)
        import slate_tpu as st
        jax.block_until_ready(st.svd(jnp.asarray(a_np)))  # warm + sync
        stages0 = _stage_totals(_SVD_STAGES)
        t0 = time.perf_counter()
        sv, u, vt = st.svd(jnp.asarray(a_np))
        sv = np.asarray(sv); u = np.asarray(u); vt = np.asarray(vt)
        t = time.perf_counter() - t0
        gf = (8.0 / 3.0) * nev32 ** 3 / t / 1e9
        e32 = 10.0 * eps
        resid = (np.linalg.norm(a_np - (u * sv[None, :]) @ vt)
                 / (np.linalg.norm(a_np) * nev32 * e32))
        label = "svd_fp32_n%d" % nev32
        return label, gf, resid, _stage_delta(label, _SVD_STAGES, stages0)


    # ---- heev / svd fp64 (config 5 scaled to one chip) ---------------
    # the two-stage eig/svd pipeline through the fp64 MXU path; n=1024
    # (up from r4's 512) keeps wall time sane while measuring more
    # pipeline than compile latency (config 5 scaled)
    nev = 1024 if on_tpu else 256
    def bench_heev64():
        import jax
        jax.config.update("jax_enable_x64", True)
        rng = np.random.default_rng(7)
        g = rng.standard_normal((nev, nev))
        herm = (g + g.T) / 2
        import slate_tpu as st
        from slate_tpu.enums import Uplo
        hm = st.HermitianMatrix(jnp.asarray(herm, jnp.float64),
                                uplo=Uplo.Lower)
        jax.block_until_ready(st.heev(hm, jobz=True))  # warm + sync
        stages0 = _stage_totals(_HEEV_STAGES)
        t0 = time.perf_counter()
        w, z = st.heev(hm, jobz=True)
        w = np.asarray(w); z = np.asarray(z)
        t = time.perf_counter() - t0
        gf = (4.0 / 3.0) * nev ** 3 / t / 1e9
        e64 = 10.0 * float(np.finfo(np.float64).eps)   # emulated fp64
        resid = (np.linalg.norm(herm @ z - z * w[None, :])
                 / (np.linalg.norm(herm) * nev * e64))
        label = "heev_fp64_n%d" % nev
        return label, gf, resid, _stage_delta(label, _HEEV_STAGES, stages0)


    def bench_svd64():
        import jax
        jax.config.update("jax_enable_x64", True)
        rng = np.random.default_rng(8)
        a_np = rng.standard_normal((nev, nev))
        import slate_tpu as st
        jax.block_until_ready(
            st.svd(jnp.asarray(a_np, jnp.float64)))      # warm + sync
        stages0 = _stage_totals(_SVD_STAGES)
        t0 = time.perf_counter()
        sv, u, vt = st.svd(jnp.asarray(a_np, jnp.float64))
        sv = np.asarray(sv); u = np.asarray(u); vt = np.asarray(vt)
        t = time.perf_counter() - t0
        gf = (8.0 / 3.0) * nev ** 3 / t / 1e9
        e64 = 10.0 * float(np.finfo(np.float64).eps)   # emulated fp64
        resid = (np.linalg.norm(a_np - (u * sv[None, :]) @ vt)
                 / (np.linalg.norm(a_np) * nev * e64))
        label = "svd_fp64_n%d" % nev
        return label, gf, resid, _stage_delta(label, _SVD_STAGES, stages0)


    # ---- QDWH spectral tier (ISSUE 18) -------------------------------
    # heev/svd through the gemm-rich QDWH drivers, pinned per call via
    # the eig_driver/svd_driver options (forced dispatch, not autotune —
    # the plain heev/svd rows above keep measuring whatever the table
    # picks).  Labeled heev_qdwh_*/svd_qdwh_* so attr.py prices them
    # with the QDWH stage model; excluded from the headline geomean
    # like every other spectral row.
    nqd32 = nev32 // 2

    def bench_heev_qdwh32():
        rng = np.random.default_rng(11)
        g = rng.standard_normal((nqd32, nqd32)).astype(np.float32)
        herm_np = ((g + g.T) / 2).astype(np.float32)
        import slate_tpu as st
        from slate_tpu.enums import Uplo
        hm = st.HermitianMatrix(jnp.asarray(herm_np), uplo=Uplo.Lower)
        opts = {"eig_driver": "qdwh"}
        jax.block_until_ready(
            st.heev(hm, jobz=True, opts=opts)[1])        # warm + sync
        stages0 = _stage_totals(_QDWH_HEEV_STAGES)
        t0 = time.perf_counter()
        w, z = st.heev(hm, jobz=True, opts=opts)
        w = np.asarray(w); z = np.asarray(z)
        t = time.perf_counter() - t0
        gf = (4.0 / 3.0) * nqd32 ** 3 / t / 1e9
        e32 = 10.0 * eps
        resid = (np.linalg.norm(herm_np @ z - z * w[None, :])
                 / (np.linalg.norm(herm_np) * nqd32 * e32))
        label = "heev_qdwh_fp32_n%d" % nqd32
        return label, gf, resid, _stage_delta(label, _QDWH_HEEV_STAGES,
                                              stages0)


    def bench_svd_qdwh32():
        rng = np.random.default_rng(12)
        a_np = rng.standard_normal((nqd32, nqd32)).astype(np.float32)
        import slate_tpu as st
        opts = {"svd_driver": "qdwh"}
        jax.block_until_ready(
            st.svd(jnp.asarray(a_np), opts=opts)[1])     # warm + sync
        stages0 = _stage_totals(_QDWH_SVD_STAGES)
        t0 = time.perf_counter()
        sv, u, vt = st.svd(jnp.asarray(a_np), opts=opts)
        sv = np.asarray(sv); u = np.asarray(u); vt = np.asarray(vt)
        t = time.perf_counter() - t0
        gf = (8.0 / 3.0) * nqd32 ** 3 / t / 1e9
        e32 = 10.0 * eps
        resid = (np.linalg.norm(a_np - (u * sv[None, :]) @ vt)
                 / (np.linalg.norm(a_np) * nqd32 * e32))
        label = "svd_qdwh_fp32_n%d" % nqd32
        return label, gf, resid, _stage_delta(label, _QDWH_SVD_STAGES,
                                              stages0)


    # ---- out-of-core getrf/potrf (ISSUE 17) --------------------------
    # host-DRAM tile pool with a FORCED tiny window (3 tiles) at
    # in-core dims: every run proves LRU eviction + dirty write-back +
    # prefetch against real transfers, and `_host_gb_transferred`
    # (lower-is-better, derived — excluded from every GFLOP/s
    # aggregate) is the measured ooc.host_bytes odometer for ONE cold
    # factorization.  The true out-of-core row (SLATE_TPU_BENCH_OOC_N,
    # e.g. 131072) is opt-in and bail-governed: it runs only when the
    # attr roofline (the host stage on the PCIe lane) projects the
    # single factorization inside the routine watchdog — a mispriced
    # giant probe skips to omitted submetrics, never an infra line.
    def _ooc_big_row(routine, run, flops_of):
        big_n = int(os.environ.get("SLATE_TPU_BENCH_OOC_N", "0") or 0)
        nb_b = 1024
        if big_n <= 0 or big_n % nb_b or big_n // nb_b < 2:
            return {}
        try:
            from slate_tpu.perf import attr as attr_mod

            pred = attr_mod.predict_seconds(
                routine, {"m": big_n, "n": big_n, "nb": nb_b, "ooc": 1},
                "fp32", platform=_PLATFORM)
            if not pred or pred * 1.5 > ROUTINE_TIMEOUT_S * 0.8:
                return {}              # projected wall over budget: bail
            rng = np.random.default_rng(13)
            a = rng.standard_normal((big_n, big_n), dtype=np.float32)
            if routine == "potrf":
                # blocked in-place symmetrization (a whole-matrix
                # (a + a.T)/2 would triple the host footprint) plus a
                # Gershgorin shift past the GOE spectral radius √(2n)
                bs = 8192
                for i0 in range(0, big_n, bs):
                    for j0 in range(i0, big_n, bs):
                        blk = 0.5 * (a[i0:i0 + bs, j0:j0 + bs]
                                     + a[j0:j0 + bs, i0:i0 + bs].T)
                        a[i0:i0 + bs, j0:j0 + bs] = blk
                        a[j0:j0 + bs, i0:i0 + bs] = blk.T
                a[np.diag_indices(big_n)] += 4.0 * np.sqrt(big_n)
            snap = _metrics_snapshot()
            t0 = time.perf_counter()
            run(a, nb_b)
            t = time.perf_counter() - t0
        except _RoutineTimeout:
            raise
        except Exception:
            return {}
        gb = ((_metrics_delta(snap).get("counters") or {})
              .get("ooc.host_bytes", 0.0)) / 1e9
        label = "%s_ooc_fp32_n%d_nb%d" % (routine, big_n, nb_b)
        out = {label: round(flops_of(big_n) / t / 1e9, 1)}
        if gb > 0:
            out[label + "_host_gb_transferred"] = round(gb, 3)
        return out


    def bench_getrf_ooc():
        rng = np.random.default_rng(11)  # per-routine stream: a retry cannot shift later routines
        n_o, nb_o = 1024 // scale, 256 // scale
        a_np = (rng.standard_normal((n_o, n_o)).astype(np.float32)
                + n_o * np.eye(n_o, dtype=np.float32))

        from slate_tpu.linalg import ooc as ooc_mod

        def run():
            lu, perm = ooc_mod.getrf_ooc(jnp.asarray(a_np), nb=nb_o,
                                         capacity=3, depth=2)
            jax.block_until_ready(lu)
            return lu, perm

        snap = _metrics_snapshot()
        t0 = time.perf_counter()
        lu, perm = run()                   # cold: compiles the tile ops
        t = time.perf_counter() - t0
        gb = ((_metrics_delta(snap).get("counters") or {})
              .get("ooc.host_bytes", 0.0)) / 1e9
        for _ in range(2):
            t0 = time.perf_counter()
            lu, perm = run()
            t = min(t, time.perf_counter() - t0)
        gf = 2.0 * n_o ** 3 / 3.0 / t / 1e9
        lu_np, perm_np = np.asarray(lu), np.asarray(perm)
        l_f = np.tril(lu_np, -1) + np.eye(n_o, dtype=np.float32)
        u_f = np.triu(lu_np)
        x = rng.standard_normal((n_o,)).astype(np.float32)
        resid = (np.linalg.norm(mv(l_f, mv(u_f, x))
                                - mv(a_np[perm_np], x))
                 / (np.linalg.norm(a_np) * np.linalg.norm(x) * eps * n_o))
        label = "getrf_ooc_fp32_n%d_nb%d" % (n_o, nb_o)
        aux = {}
        if gb > 0:
            aux[label + "_host_gb_transferred"] = round(gb, 4)
        aux.update(_ooc_big_row(
            "getrf",
            lambda a, nb: ooc_mod.getrf_ooc(a, nb=nb, to_device=False),
            lambda N: 2.0 * N ** 3 / 3.0))
        return label, gf, resid, aux


    def bench_potrf_ooc():
        rng = np.random.default_rng(12)  # per-routine stream: a retry cannot shift later routines
        n_o, nb_o = 1024 // scale, 256 // scale
        g = rng.standard_normal((n_o, n_o)).astype(np.float32)
        spd_np = g @ g.T + n_o * np.eye(n_o, dtype=np.float32)

        from slate_tpu.linalg import ooc as ooc_mod

        def run():
            l = ooc_mod.potrf_ooc(jnp.asarray(spd_np), nb=nb_o,
                                  capacity=3, depth=2)
            jax.block_until_ready(l)
            return l

        snap = _metrics_snapshot()
        t0 = time.perf_counter()
        l = run()                          # cold: compiles the tile ops
        t = time.perf_counter() - t0
        gb = ((_metrics_delta(snap).get("counters") or {})
              .get("ooc.host_bytes", 0.0)) / 1e9
        for _ in range(2):
            t0 = time.perf_counter()
            l = run()
            t = min(t, time.perf_counter() - t0)
        gf = n_o ** 3 / 3.0 / t / 1e9
        l_np = np.asarray(l)
        x = rng.standard_normal((n_o,)).astype(np.float32)
        resid = (np.linalg.norm(mv(l_np, mv(l_np.T, x)) - mv(spd_np, x))
                 / (np.linalg.norm(spd_np) * np.linalg.norm(x)
                    * eps * n_o))
        label = "potrf_ooc_fp32_n%d_nb%d" % (n_o, nb_o)
        aux = {}
        if gb > 0:
            aux[label + "_host_gb_transferred"] = round(gb, 4)
        aux.update(_ooc_big_row(
            "potrf",
            lambda a, nb: ooc_mod.potrf_ooc(a, nb=nb, to_device=False),
            lambda N: N ** 3 / 3.0))
        return label, gf, resid, aux

    # ---- the runner loop: global deadline budgeting ------------------
    # The routine list is known up front, so each routine's SIGALRM
    # deadline can be derived from ONE global budget
    # (SLATE_TPU_BENCH_DEADLINE_S): remaining time split evenly over the
    # remaining routines.  The required set runs unconditionally; the
    # optional tail (heev/svd extras) still yields to the soft
    # SLATE_TPU_BENCH_BUDGET_S wall like before.
    routines = [
        ("gemm", bench_gemm, False),
        ("gemm_split", bench_gemm_split, False),
        ("gemm_fp64", bench_gemm64, False),
        ("potrf", bench_potrf, False),
        ("potrf_fp64", bench_potrf64, False),
        ("getrf", bench_getrf, False),
        ("geqrf", bench_geqrf, False),
        ("gels", bench_gels, False),
        ("batched_posv", lambda: bench_batched_posv(on_tpu), False),
        ("batched_gesv", lambda: bench_batched_gesv(on_tpu), False),
        ("serve_posv", lambda: bench_serve(on_tpu), False),
        ("serve_fleet", lambda: bench_serve_fleet(on_tpu), True),
        ("getrf_ooc", bench_getrf_ooc, True),
        ("potrf_ooc", bench_potrf_ooc, True),
        ("heev_fp32", bench_heev32, True),
        ("svd_fp32", bench_svd32, True),
        ("heev_qdwh_fp32", bench_heev_qdwh32, True),
        ("svd_qdwh_fp32", bench_svd_qdwh32, True),
        ("heev_fp64", bench_heev64, True),
        ("svd_fp64", bench_svd64, True),
    ]
    results = {}
    for i, (name, fn, optional) in enumerate(routines):
        if optional and over_budget(name):
            continue
        deadline = ROUTINE_TIMEOUT_S
        if DEADLINE_S > 0:
            remaining = DEADLINE_S - (time.perf_counter() - t_start)
            if remaining <= MIN_DEADLINE_S and optional:
                # no room left for extras: record and move on — the
                # aggregate still flushes inside the budget
                skipped.append(name)
                continue
            per = remaining / max(1, len(routines) - i)
            deadline = max(MIN_DEADLINE_S, min(ROUTINE_TIMEOUT_S, per))
        results[name] = _run_routine(name, fn, sub, fails, infra,
                                     deadline=deadline,
                                     attr_sink=attr_map,
                                     bb_sink=bb_sink)
    gemm_gf = results.get("gemm")

    # headline geomean: fp32 factor suite ONLY (the metric BENCH_r01-r03
    # track); fp64/eig/svd submetrics are reported but kept out so the
    # round-over-round number keeps meaning what its name says
    headline_keys = [k for k in sub
                     if k.startswith(("gemm_fp32", "potrf_fp32",
                                      "getrf_fp32", "geqrf_fp32",
                                      "gels_fp32"))
                     and not k.startswith("gemm_fp32_split")
                     and not k.endswith(DERIVED_SUFFIXES)]
    vals = [sub[k] for k in headline_keys
            if isinstance(sub[k], (int, float)) and sub[k] > 0]
    geomean = (float(np.exp(np.mean(np.log(vals)))) if vals else 0.0)
    gemm_key = "gemm_fp32_n%d" % n
    gemm64_key = "gemm_fp64_n%d" % n64
    peak = {}
    low = []
    if gemm_gf and sub.get(gemm_key):
        for k, v in sub.items():
            if k.endswith(NON_RATE_SUFFIXES):
                # solves/s rates, stage seconds, speedup ratios and
                # round-trip counts are not GFLOP/s — a gemm fraction
                # would be unit salad
                continue
            anchor = (sub.get(gemm64_key) if "fp64" in k
                      else sub.get(gemm_key))
            if anchor:
                peak[k] = round(v / anchor, 3)
                if peak[k] < 0.10 and "gemm" not in k and "mxu" not in k \
                        and "heev" not in k and "svd" not in k \
                        and "batched" not in k and "serve" not in k \
                        and "_ooc_" not in k:
                    # two-stage eig/svd run partly on host, the
                    # batched/serve suites' tiny per-problem shapes
                    # cannot reach big-matrix fractions, and the
                    # out-of-core rows are PCIe-bound by design;
                    # informational
                    low.append(k)
    # frac_of_gemm as a FIRST-CLASS derived submetric per factorization
    # routine (routine TF/s ÷ same-run gemm TF/s): the ROADMAP targets
    # (getrf ≥ 0.4×, potrf ≥ 0.6× of measured gemm) become sentinel
    # rows that tools/bench_diff.py aligns, thresholds and renders,
    # instead of hand arithmetic over two GFLOP/s columns.  Wall-time
    # (_s) stage keys carry no fraction; the geomean/anchor math above
    # already excludes the derived keys.
    for k in list(sub):
        if not k.startswith(("potrf_", "getrf_", "geqrf_", "gels_",
                             "heev_", "svd_")):
            continue
        if k.endswith(NON_RATE_SUFFIXES):
            continue
        anchor = sub.get(gemm64_key) if "fp64" in k else sub.get(gemm_key)
        if anchor and isinstance(sub[k], (int, float)):
            sub[k + "_frac_of_gemm"] = round(sub[k] / anchor, 3)
    # the split-gemm anchor family (ISSUE 16): the fp32 factorization
    # fractions RESTATED against the bf16x3 split gemm rate — sentinel
    # rows (derived, headline-excluded) that show how much of the
    # emulated-fp32 peak each driver's trailing updates would bank if
    # routed through the split backend
    split_key = "gemm_fp32_split_n%d" % n
    if sub.get(split_key):
        for k in list(sub):
            if not k.startswith(("potrf_fp32", "getrf_fp32",
                                 "geqrf_fp32", "gels_fp32")):
                continue
            if k.endswith(NON_RATE_SUFFIXES):
                continue
            if isinstance(sub[k], (int, float)):
                sub[k + "_frac_of_split_gemm"] = round(
                    sub[k] / sub[split_key], 3)
    if on_tpu and sub.get(split_key) and sub.get(gemm_key):
        # enforceable acceptance floor: split3 must deliver >= 1.5x the
        # stock fp32 gemm rate at the headline n.  regress.py judges
        # any *_over_floor value < 1.0 as REGRESS even single-artifact;
        # emitted on TPU only so a CPU CI artifact (where the bf16
        # fold has no MXU to win on) cannot trip it
        sub["gemm_fp32_split_speedup_over_floor"] = round(
            (sub[split_key] / sub[gemm_key]) / 1.5, 3)
    out = {
        "metric": "factor_suite_fp32_geomean",
        "value": round(geomean, 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(geomean / BASELINE_GFLOPS, 2),
        "submetrics": sub,
        "fraction_of_measured_gemm": peak,
        "autotune": _autotune_tags(set()),   # full decision table
        "bundle": _bundle_tag(),             # bundle-warm or probe-cold?
        "metrics": _metrics_snapshot(),      # full registry snapshot
        "attribution": attr_map,             # per-routine gap reports
    }
    pa = _probes_avoided(out["metrics"])
    if pa:
        out["probes_avoided"] = pa
    if bb_sink:
        # each degraded routine's forensic bundle (path + digest) —
        # regress.py/tools/bench_diff.py surface these as NOTE rows
        out["blackbox_bundles"] = list(bb_sink)
    # regression tripwire (r4 lesson: geqrf silently lost 20% between
    # rounds): compare every submetric against the newest BENCH_r*.json
    # in the repo root and flag drops > 5%.  The offline/multi-artifact
    # sibling with verdicts and a nonzero exit is tools/bench_diff.py.
    regressions = {}
    try:
        import glob
        prevs = sorted(glob.glob(os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_r*.json")))
        if prevs:
            with open(prevs[-1]) as f:
                prev = json.load(f)
            if isinstance(prev.get("parsed"), dict):
                prev = prev["parsed"]   # driver wrapper: {rc, tail, parsed}
            prev_sub = prev.get("submetrics", {})
            for k, v in sub.items():
                pv = prev_sub.get(k)
                if (isinstance(pv, (int, float)) and pv > 0
                        and isinstance(v, (int, float)) and v < 0.95 * pv):
                    regressions[k] = {
                        "prev": pv, "now": v, "ratio": round(v / pv, 3),
                        "prev_file": os.path.basename(prevs[-1])}
    except Exception as e:  # the tripwire must never kill the JSON
        regressions = {"error": str(e)}
    if regressions:
        out["regressions"] = regressions
    if low:
        out["below_10pct_of_anchor"] = low
    if skipped:
        out["skipped_for_time"] = skipped
    if retried_infra:
        # the sentinel (perf/regress.py) surfaces this as a note: the
        # numbers are real but the run absorbed a transient init flake
        out["retried_infra"] = True
    if fails or infra:
        out["failed"] = fails + [f"infra: {s}" for s in infra]
    print(json.dumps(out), flush=True)   # aggregate stays the LAST line
    for f in fails:
        print(f"# FAILED residual gate: {f}", file=sys.stderr)
    for s in infra:
        print(f"# infra failure (non-fatal): {s}", file=sys.stderr)
    print(f"# platform={jax.devices()[0].platform} "
          f"{len(sub)} submetrics, {len(fails)} residual failures, "
          f"{len(infra)} infra failures", file=sys.stderr)
    if fails:
        sys.exit(1)


if __name__ == "__main__":
    main()
