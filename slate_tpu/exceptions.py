"""Errors, reference ``include/slate/Exception.hh`` (122 LoC).

The reference throws ``slate::Exception`` from ``slate_error`` /
``slate_assert`` macros.  Numerical non-success (singular pivot, failed
convergence) is reported via *info codes* in LAPACK style; on TPU the
data-dependent branch can't throw from inside jit, so drivers return info
values alongside results and ``check_info`` raises host-side.
"""

from __future__ import annotations


class SlateError(RuntimeError):
    """Reference ``slate::Exception``."""


def slate_assert(cond: bool, msg: str = "assertion failed") -> None:
    if not cond:
        raise SlateError(msg)


def check_info(info, what: str = "routine") -> None:
    """Raise if a device-computed info code is nonzero (host sync point)."""
    import numpy as np

    i = int(np.asarray(info))
    if i != 0:
        raise SlateError(f"{what}: info = {i}")
