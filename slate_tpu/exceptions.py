"""Errors, reference ``include/slate/Exception.hh`` (122 LoC).

The reference throws ``slate::Exception`` from ``slate_error`` /
``slate_assert`` macros.  Numerical non-success (singular pivot, failed
convergence) is reported via *info codes* in LAPACK style; on TPU the
data-dependent branch can't throw from inside jit, so drivers return info
values alongside results and ``check_info`` raises host-side.
"""

from __future__ import annotations


class SlateError(RuntimeError):
    """Reference ``slate::Exception``."""


def slate_assert(cond: bool, msg: str = "assertion failed") -> None:
    if not cond:
        raise SlateError(msg)


def check_info(info, what: str = "routine") -> None:
    """Raise if a device-computed info code is nonzero (host sync point).

    Accepts a scalar (the single-problem drivers' contract) OR a
    batched info array from the ``linalg/batched`` drivers and serve
    responses: for an array, the error reports the FIRST nonzero
    problem index, its info value, and how many problems failed — the
    same host-side contract as singles, so a serving layer can catch
    one exception type whatever the batch shape."""
    import numpy as np

    arr = np.asarray(info)
    if arr.ndim == 0:
        i = int(arr)
        if i != 0:
            raise SlateError(f"{what}: info = {i}")
        return
    nz = np.flatnonzero(arr)
    if nz.size:
        first = int(nz[0])
        raise SlateError(
            f"{what}: info nonzero for {nz.size} of {arr.size} "
            f"problems; first at index {first} "
            f"(info = {int(arr.reshape(-1)[first])})")
