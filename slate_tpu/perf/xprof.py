"""Device-truth profiling: XProf capture → parse → join (ISSUE 19).

Every other timer in the repo is a host wall — ``step.*`` timers record
Python composition at trace/dispatch time, ``dist.step.*`` rows are
synced host walls, serve spans are dispatcher clocks.  This module adds
the device side: an opt-in capture window around a region of interest
(``SLATE_TPU_XPROF=<dir>`` + :class:`capture`) drives
``jax.profiler.start_trace``/``stop_trace`` and parses the emitted
trace-event JSON (stdlib gzip+json — no tensorboard, no protobuf) into:

* a **per-kernel table** — every execution event on an XLA/device lane
  (``dot.3``, ``fusion.12``, TPU kernel launches), aggregated by name;
* a **stage rollup** — kernels bucketed onto the existing annotation
  vocabulary (``step.<op>.<stage>`` / ``stage.<op>.<name>`` from
  :func:`slate_tpu.perf.metrics.step_timer`, ``dist.<driver>.k<k>``
  from :func:`slate_tpu.parallel.dist_util.run_timeline`) by time
  overlap, so fused-step / full-fused / dist-step kernels land in the
  attr stage vocabulary.  Execution that happens outside any
  annotation span (a jitted driver executes AFTER its trace-time
  annotations) falls back to the annotation's own profiler wall —
  the same proxy semantics as the host-timer rung, but on the
  profiler's clock — and ``stage_source`` records which rung each
  stage used;
* **device memory** — per-device HBM high-water / live-bytes gauges
  read through :func:`slate_tpu.debug.memory_stats` before and after
  the window (graceful ``[]`` on backends without the API);
* a **compile ledger** — per-fn compile walls forwarded from the PR 4
  ``jax.monitoring`` compile-watch hook
  (:func:`slate_tpu.perf.metrics.add_compile_listener`) while the
  window is open.

The parsed profile is written next to the trace
(``xprof_<label>.json``) and kept as module state so the downstream
joins are one call away: :func:`last_profile` feeds
``attr.attribute(device_profile=...)`` and
``dist_util.overlap_summary(device_profile=...)`` their
``device_profile`` compute-source rung, and ``sweep.run_sweep
(profile=...)`` consumes :func:`signals_from` (measured per-collective
overhead + measured stage seconds) when pricing ``dist_chunk`` /
``dist_lookahead`` / fusion-rung candidates.

Contract (same as metrics/blackbox): **off by default, and enabling it
never changes a compiled program** — the capture wraps execution in
profiler hooks and host-side annotations only, so lowered text is
bit-identical with the knob set or unset
(``tests/test_backend_registry.py`` pins it).  This module is
stdlib-only at import and dual-life: importable as
``slate_tpu.perf.xprof`` or exec'd by file path like ``regress.py``
(``tools/xprof_report.py`` does exactly that on jax-free machines —
the parser half works anywhere; only :class:`capture` needs jax).

Env knobs:

* ``SLATE_TPU_XPROF`` — capture directory; unset (default) makes
  :class:`capture` a no-op context manager.
"""

from __future__ import annotations

import bisect
import gzip
import hashlib
import json
import os
import time

__all__ = [
    "ENV_DIR", "PROFILE_FORMAT", "capture", "capture_dir", "clear",
    "enabled", "find_trace_file", "hbm_peak_delta_gb", "last_profile",
    "last_stages", "load_profile", "parse_trace", "profile_digest",
    "signals_from", "stage_bucket",
]

ENV_DIR = "SLATE_TPU_XPROF"

PROFILE_FORMAT = 1

#: most recent parsed profile (module state, like dist_util's timeline
#: rows): bench's per-routine attribution join reads it right after the
#: capture window closes.
_last: list = [None]


def capture_dir():
    """The ``SLATE_TPU_XPROF`` capture directory, or None (off)."""
    v = os.environ.get(ENV_DIR, "").strip()
    return v or None


def enabled() -> bool:
    return capture_dir() is not None


def last_profile():
    """The most recent capture's parsed profile dict (or None)."""
    return _last[0]


def last_stages() -> dict:
    """``{op: {stage: seconds}}`` of the most recent capture — the
    ``device_profile`` argument shape ``attr.attribute`` joins."""
    p = _last[0]
    return dict((p or {}).get("stages") or {})


def clear() -> None:
    _last[0] = None


# ---------------------------------------------------------------------------
# Stage bucketing: the trace.py / metrics.py annotation vocabulary
# ---------------------------------------------------------------------------

def stage_bucket(name: str):
    """``(op, stage)`` for an annotation name in the repo's span
    vocabulary, else None.

    * ``step.<op>.<stage>`` / ``stage.<op>.<name>`` — the
      :func:`metrics.step_timer` join keys ``attr.stage_timers``
      already consumes;
    * ``dist.<driver>.k<k>`` — the PR 15 timeline chunk spans, rolled
      up under stage ``"dist"`` per driver;
    * ``driver.<name>`` — the instrumented driver facades, stage
      ``"driver"``.
    """
    parts = str(name).split(".")
    if len(parts) == 3 and parts[0] in ("step", "stage"):
        return parts[1], parts[2]
    if len(parts) == 3 and parts[0] == "dist" and parts[2][:1] == "k":
        return parts[1], "dist"
    if len(parts) == 2 and parts[0] == "driver":
        return parts[1], "driver"
    return None


# ---------------------------------------------------------------------------
# Trace-event JSON parsing (stdlib gzip+json)
# ---------------------------------------------------------------------------

def find_trace_file(root: str):
    """Newest trace-event JSON under ``root`` (jax writes
    ``plugins/profile/<ts>/*.trace.json.gz`` when asked for a perfetto
    trace).  Accepts a direct file path too.  None when nothing
    parseable exists."""
    if os.path.isfile(root):
        return root
    best, best_m = None, -1.0
    for dirpath, _dirs, files in os.walk(root):
        for f in files:
            if f.endswith((".trace.json.gz", ".trace.json")) \
                    or f in ("perfetto_trace.json.gz",
                             "perfetto_trace.json"):
                p = os.path.join(dirpath, f)
                try:
                    m = os.path.getmtime(p)
                except OSError:
                    continue
                # prefer the xprof .trace.json.gz flavor over the
                # perfetto duplicate of the same session (same events;
                # the former carries the thread metadata we key on)
                rank = 1.0 if ".trace.json" in f else 0.0
                if (m, rank) > (best_m, 0.0 if best is None
                                else (1.0 if ".trace.json" in
                                      os.path.basename(best) else 0.0)):
                    if m > best_m or rank > 0:
                        best, best_m = p, m
    return best


def _load_events(path: str) -> list:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        blob = json.load(f)
    if isinstance(blob, dict):
        return list(blob.get("traceEvents") or [])
    return list(blob or [])


def _is_exec_lane(pname: str, tname: str) -> bool:
    # TPU device traces land under "/device:TPU:N" processes; CPU thunk
    # execution lands on the XLA client / codegen thread pools.  The
    # python thread is host-side and never a kernel lane.
    p = (pname or "").lower()
    t = tname or ""
    return ("device" in p) or ("XLA" in t) or t.startswith("tf_")


def parse_trace(path_or_dir: str, label: str = "") -> dict:
    """Parse one emitted trace into the profile dict (see module doc).

    Raises ``OSError``/``ValueError`` on an unreadable or empty trace —
    :class:`capture` converts that into an ``error`` field instead of
    killing the profiled run.
    """
    path = find_trace_file(path_or_dir)
    if path is None:
        raise ValueError("no trace-event JSON under %r" % path_or_dir)
    events = _load_events(path)

    pname: dict = {}
    tname: dict = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        args = e.get("args") or {}
        if e.get("name") == "process_name":
            pname[e.get("pid")] = str(args.get("name", ""))
        elif e.get("name") == "thread_name":
            tname[(e.get("pid"), e.get("tid"))] = str(args.get("name", ""))

    anns = []          # (start_s, stop_s, dur_s, name, op, stage)
    kernels = []       # (start_s, stop_s, dur_s, name)
    for e in events:
        if e.get("ph") != "X":
            continue
        name = str(e.get("name", ""))
        try:
            ts = float(e.get("ts", 0.0)) / 1e6
            dur = max(float(e.get("dur", 0.0)), 0.0) / 1e6
        except (TypeError, ValueError):
            continue
        bucket = stage_bucket(name)
        if bucket is not None:
            anns.append((ts, ts + dur, dur, name) + bucket)
            continue
        if not name or name.startswith("$") or "::" in name:
            continue                    # python frames / runtime infra
        if _is_exec_lane(pname.get(e.get("pid"), ""),
                         tname.get((e.get("pid"), e.get("tid")), "")):
            kernels.append((ts, ts + dur, dur, name))

    # interval-stabbing join: each kernel's midpoint finds the
    # INNERMOST covering annotation span (shortest dur wins — nested
    # step.<op>.<stage> inside driver.<op> buckets to the stage)
    anns.sort(key=lambda a: a[0])
    starts = [a[0] for a in anns]
    max_dur = max((a[2] for a in anns), default=0.0)

    def _covering(mid: float):
        hit, hit_dur = None, max_dur
        i = bisect.bisect_right(starts, mid) - 1
        while i >= 0:
            a = anns[i]
            if a[1] >= mid and a[2] <= hit_dur:
                hit, hit_dur = a, a[2]
            # spans are start-sorted: an earlier span covering mid
            # needs dur >= mid - start, so once mid - a[0] exceeds the
            # best (or max) duration nothing earlier can win
            if mid - a[0] > hit_dur:
                break
            i -= 1
        return hit

    ktab: dict = {}
    stages: dict = {}
    stage_source: dict = {}
    for ts, stop, dur, name in kernels:
        cover = _covering((ts + stop) / 2.0) if anns else None
        key = (name, cover[4] if cover else None,
               cover[5] if cover else None)
        row = ktab.get(key)
        if row is None:
            ktab[key] = row = {"name": name, "count": 0, "total_s": 0.0,
                               "op": key[1], "stage": key[2]}
        row["count"] += 1
        row["total_s"] += dur
        if cover is not None:
            op, stage = cover[4], cover[5]
            stages.setdefault(op, {})
            stages[op][stage] = stages[op].get(stage, 0.0) + dur
            stage_source.setdefault(op, {})[stage] = "kernels"

    ann_tab: dict = {}
    for ts, stop, dur, name, op, stage in anns:
        k = "%s.%s" % (op, stage)
        row = ann_tab.get(k)
        if row is None:
            ann_tab[k] = row = {"op": op, "stage": stage, "count": 0,
                                "wall_s": 0.0}
        row["count"] += 1
        row["wall_s"] += dur
        # fallback rung: no kernel executed INSIDE this span (jitted
        # drivers execute after their trace-time annotations) — the
        # annotation's own profiler wall stands in, and stage_source
        # says so
        if stage not in (stages.get(op) or {}):
            stages.setdefault(op, {})
            stages[op][stage] = stages[op].get(stage, 0.0) + dur
            stage_source.setdefault(op, {})[stage] = "annotation"

    kernel_rows = sorted(ktab.values(),
                         key=lambda r: (-r["total_s"], r["name"]))
    prof = {
        "format": PROFILE_FORMAT,
        "label": str(label or ""),
        "trace_path": path,
        "events": len(events),
        "kernels": [dict(r, total_s=round(r["total_s"], 9))
                    for r in kernel_rows],
        "stages": {op: {st: round(v, 9) for st, v in m.items()}
                   for op, m in stages.items()},
        "stage_source": stage_source,
        "annotations": {k: dict(v, wall_s=round(v["wall_s"], 9))
                        for k, v in ann_tab.items()},
    }
    prof["digest"] = profile_digest(prof)
    return prof


def profile_digest(prof: dict) -> str:
    """Content digest over the decision-bearing parts of a profile (the
    kernel table + stage rollup) — what a timeline-informed sweep
    bundle is stamped with, so it is distinguishable from a
    roofline-only one."""
    core = {"kernels": prof.get("kernels") or [],
            "stages": prof.get("stages") or {}}
    payload = json.dumps(core, sort_keys=True,
                         separators=(",", ":")).encode()
    return hashlib.sha256(payload).hexdigest()[:16]


def load_profile(path_or_dir: str) -> dict:
    """Load a profile from a capture dir, a written ``xprof_*.json``
    artifact, or a raw trace-event file.

    A dir is searched for the newest artifact first (it carries memory
    and compile blocks a re-parse cannot reconstruct), then for a raw
    trace to parse."""
    if os.path.isdir(path_or_dir):
        best, best_m = None, -1.0
        for dirpath, _dirs, files in os.walk(path_or_dir):
            for f in files:
                if f.startswith("xprof_") and f.endswith(".json"):
                    p = os.path.join(dirpath, f)
                    try:
                        m = os.path.getmtime(p)
                    except OSError:
                        continue
                    if m > best_m:
                        best, best_m = p, m
        if best is not None:
            with open(best) as f:
                return json.load(f)
        return parse_trace(path_or_dir)
    if path_or_dir.endswith(".json") and not path_or_dir.endswith(
            (".trace.json", "perfetto_trace.json")):
        with open(path_or_dir) as f:
            blob = json.load(f)
        if isinstance(blob, dict) and "stages" in blob:
            return blob
    return parse_trace(path_or_dir)


# ---------------------------------------------------------------------------
# Device memory gauges
# ---------------------------------------------------------------------------

def _memory_block():
    """``slate_tpu.debug.memory_stats()`` hardened: {} when the debug
    module (or jax) is unreachable — by-path loads and jax-free
    machines must keep the parser half working."""
    try:
        from slate_tpu import debug as _debug

        return _debug.memory_stats()
    except Exception:
        return {}


def hbm_peak_delta_gb(before, after):
    """Per-window HBM high-water (GB) out of two
    ``debug.memory_stats()`` blocks.

    The runtime's ``peak_bytes_in_use`` is a process-lifetime
    high-water with no reset API, so the per-window figure is only
    directly observable when the window ADVANCED the peak — then it is
    ``after.peak − before.live``.  Otherwise the live-bytes delta
    (floored at 0) stands in as the lower bound.  None when no device
    reports the API (CPU CI) — the bench submetric is simply absent
    there instead of lying."""
    b = {d.get("device"): d for d in (before or {}).get("devices") or []
         if isinstance(d, dict)}
    total = None
    for d in (after or {}).get("devices") or []:
        if not isinstance(d, dict):
            continue
        prev = b.get(d.get("device")) or {}
        peak, peak0 = d.get("peak_bytes_in_use"), \
            prev.get("peak_bytes_in_use")
        live, live0 = d.get("bytes_in_use"), prev.get("bytes_in_use")
        if peak is None and live is None:
            continue
        base = float(live0 or 0.0)
        if peak is not None and peak0 is not None \
                and float(peak) > float(peak0):
            gb = max(0.0, float(peak) - base)
        elif live is not None:
            gb = max(0.0, float(live) - base)
        else:
            continue
        total = (total or 0.0) + gb
    return None if total is None else total / 1e9


# ---------------------------------------------------------------------------
# Compile ledger (rides the PR 4 jax.monitoring compile watch)
# ---------------------------------------------------------------------------

_ledger: list = []
_ledger_installed = [False]
_capture_active = [False]


def _install_ledger() -> None:
    if _ledger_installed[0]:
        return
    try:
        from slate_tpu.perf import metrics as _metrics
    except Exception:
        return

    def _cb(event, secs, **kw):
        if not _capture_active[0]:
            return
        name = kw.get("fun_name") or kw.get("module_name") \
            or kw.get("event_name") or ""
        _ledger.append({"event": str(event), "fn": str(name),
                        "secs": float(secs)})

    _metrics.add_compile_listener(_cb)
    _metrics.install_compile_watch()
    _ledger_installed[0] = True


def _ledger_rollup(rows) -> dict:
    out = {"events": len(rows), "total_s": 0.0, "by_fn": {}}
    for r in rows:
        out["total_s"] += r["secs"]
        key = r["fn"] or r["event"].rsplit("/", 1)[-1]
        ent = out["by_fn"].setdefault(key, {"count": 0, "total_s": 0.0})
        ent["count"] += 1
        ent["total_s"] += r["secs"]
    out["total_s"] = round(out["total_s"], 9)
    for ent in out["by_fn"].values():
        ent["total_s"] = round(ent["total_s"], 9)
    return out


# ---------------------------------------------------------------------------
# The capture window
# ---------------------------------------------------------------------------

class capture:
    """Opt-in device-truth capture around a region of interest::

        with xprof.capture("getrf_fp32_n4096") as cap:
            run()
        cap.profile       # parsed profile dict (or None when off)

    No-op (and allocation-free) unless ``SLATE_TPU_XPROF`` names a
    directory or ``log_dir`` is passed.  While open, the window also
    forces the repo's host annotations onto the profiler clock
    (``metrics.set_annotation_hook`` + ``trace.force_annotations``) so
    the stage vocabulary exists in the trace even when SVG tracing and
    the metrics registry are off.  Capture failures (profiler busy,
    unparseable trace) are recorded on ``self.error`` — the profiled
    run itself is never killed by its observer."""

    def __init__(self, label: str, log_dir=None):
        self.label = str(label)
        self.dir = log_dir or capture_dir()
        self.profile = None
        self.error = None
        self._active = False
        self._mem0 = None
        self._ledger0 = 0
        self._t0 = 0.0
        self._hooked = False

    # -- annotation plumbing ------------------------------------------------
    def _annotations(self, on: bool) -> None:
        try:
            from jax.profiler import TraceAnnotation

            from slate_tpu import trace as _trace
            from slate_tpu.perf import metrics as _metrics
        except Exception:
            return
        if on:
            _metrics.set_annotation_hook(TraceAnnotation)
            _trace.force_annotations(True)
            self._hooked = True
        elif self._hooked:
            _metrics.set_annotation_hook(None)
            _trace.force_annotations(False)
            self._hooked = False

    def __enter__(self):
        if not self.dir:
            return self
        try:
            import jax
        except Exception as e:                  # jax-free process
            self.error = "jax unavailable: %s" % e
            return self
        try:
            os.makedirs(self.dir, exist_ok=True)
            self._mem0 = _memory_block()
            _install_ledger()
            self._ledger0 = len(_ledger)
            _capture_active[0] = True
            self._t0 = time.perf_counter()
            jax.profiler.start_trace(self.dir,
                                     create_perfetto_trace=True)
            self._active = True
            self._annotations(True)
        except Exception as e:                  # profiler already busy
            _capture_active[0] = False
            self.error = "%s: %s" % (type(e).__name__, e)
        return self

    def __exit__(self, *exc):
        if not self._active:
            _capture_active[0] = False
            return False
        self._annotations(False)
        _capture_active[0] = False
        wall = time.perf_counter() - self._t0
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:
            self.error = "stop_trace: %s: %s" % (type(e).__name__, e)
            return False
        try:
            prof = parse_trace(self.dir, label=self.label)
        except Exception as e:
            self.error = "parse: %s: %s" % (type(e).__name__, e)
            return False
        prof["capture_wall_s"] = round(wall, 9)
        mem1 = _memory_block()
        peak_gb = hbm_peak_delta_gb(self._mem0, mem1)
        prof["memory"] = {"before": self._mem0, "after": mem1}
        if peak_gb is not None:
            prof["memory"]["hbm_peak_gb"] = round(peak_gb, 9)
        prof["compile"] = _ledger_rollup(_ledger[self._ledger0:])
        self._gauges(prof, mem1, peak_gb)
        self._write(prof)
        _last[0] = prof
        self.profile = prof
        return False

    def _gauges(self, prof, mem1, peak_gb) -> None:
        """Per-routine HBM high-water / live-bytes gauges + capture
        accounting through the public metrics facade (no-ops while the
        registry is off)."""
        try:
            from slate_tpu.perf import metrics as _metrics

            _metrics.inc("xprof.captures")
            _metrics.observe_time("xprof.capture.%s"
                                  % self.label.replace(".", "_")[:40],
                                  prof["capture_wall_s"])
            if peak_gb is not None:
                _metrics.set_gauge("xprof.hbm.peak_gb", peak_gb)
            live = sum(float(d.get("bytes_in_use") or 0.0)
                       for d in (mem1 or {}).get("devices") or [])
            if (mem1 or {}).get("devices"):
                _metrics.set_gauge("xprof.hbm.live_bytes", live)
        except Exception:
            pass

    def _write(self, prof) -> None:
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in self.label) or "capture"
        path = os.path.join(self.dir, "xprof_%s.json" % safe)
        try:
            tmp = path + ".tmp.%d" % os.getpid()
            with open(tmp, "w") as f:
                json.dump(prof, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
            prof["artifact"] = path
        except OSError:
            pass                        # read-only FS: in-memory only


# ---------------------------------------------------------------------------
# Measured signals for the sweep (ROADMAP 5(b))
# ---------------------------------------------------------------------------

def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2] if xs else None


def signals_from(profile=None, measured_steps=None, ici_gbs=None) -> dict:
    """Distill a captured profile (+ the PR 15 measured step rows) into
    the compute signals ``sweep.py`` prices candidates with::

        {"digest", "launch_s", "stages", "measured_steps"}

    * ``launch_s`` — measured per-collective exposed overhead: each
      timeline row's synced host wall minus its wire time
      (``bcast_bytes / ici_gbs``), divided by the row's collective
      count; the median over rows.  An upper bound on the dispatch
      latency (the window's un-overlapped compute rides along), which
      is exactly the exposure a ``dist_chunk``/``dist_lookahead``
      candidate pays per extra collective — the measured substitute
      for ``attr._DEF_LAUNCH_S``.  A profile artifact may also carry a
      precomputed ``signals.launch_s`` (synthetic test signals do).
    * ``stages`` — the capture's ``{op: {stage: seconds}}`` rollup.
    * None/{} fields mean "no signal": callers fall back to the
      analytical roofline, never to a guess.
    """
    prof = profile or {}
    sig = {"digest": prof.get("digest"),
           "launch_s": None,
           "stages": dict(prof.get("stages") or {}),
           "measured_steps": 0}
    pre = (prof.get("signals") or {}).get("launch_s")
    if isinstance(pre, (int, float)) and pre > 0:
        sig["launch_s"] = float(pre)
    rows = list(measured_steps or prof.get("measured_steps") or [])
    rows = [r for r in rows if isinstance(r, dict)]
    sig["measured_steps"] = len(rows)
    if sig["launch_s"] is None and rows and ici_gbs:
        per = []
        for r in rows:
            cnt = float(r.get("bcast_count") or 0.0)
            if cnt <= 0:
                continue
            wire = float(r.get("bcast_bytes") or 0.0) / (float(ici_gbs)
                                                         * 1e9)
            per.append(max(0.0, float(r.get("wall_s") or 0.0) - wire)
                       / cnt)
        med = _median(per)
        if med is not None and med > 0:
            sig["launch_s"] = med
    return sig
