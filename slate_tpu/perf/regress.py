"""Bench regression sentinel: diff ``BENCH_r*.json`` artifacts.

The performance trajectory of this library is a sequence of bench
artifacts — either the driver wrapper shape (``{"n", "cmd", "rc",
"tail", "parsed"}``) checked in as ``BENCH_r*.json``, or raw
``bench.py`` stdout (JSON lines ending in the aggregate).  Two failure
modes have already cost real rounds:

* **silent throughput regressions** — geqrf dropped 23.5 → 18.9 TF/s
  between r3 and r4 (a per-panel ``lax.cond`` guard) and was only found
  by a human reading numbers side by side;
* **infra-shaped artifacts** — BENCH_r05 landed as ``rc=124`` with
  ``parsed: null`` (outer timeout beat the suite's single final print)
  and looked like "no data" instead of "broken run".

This module machine-checks both: load two or more artifacts, align
routines by their submetric identity (routine name, dtype, dims —
parsed from labels like ``geqrf_fp32_m32768_n4096``), emit a verdict
table, and exit nonzero on any regression past the threshold or any
infra-shaped artifact.  The CLI lives in ``tools/bench_diff.py``
(stdlib-only — it never imports jax, so it runs anywhere in
milliseconds).

Backend attribution: when artifacts carry the ``autotune`` decision
table (r6+) the sentinel reports a per-routine backend tag and NOTES a
tag change next to the verdict rather than splitting the alignment key
— older artifacts carry no tags, and a tag-keyed alignment would
silently stop comparing the moment tagging was introduced.

Metric direction: submetrics are GFLOP/s (higher is better) except the
per-stage wall-time keys bench emits for the two-stage eig/SVD
pipelines (suffix ``_s``, e.g. ``heev_fp64_n1024_stage2_chase_s``) —
those are seconds, LOWER is better, and the verdict logic inverts the
sign so a faster stage reads IMPROVE, not REGRESS.  The batched
serving-throughput family (suffix ``_solves_per_s``, r8 bench) is a
RATE again — higher is better — so :func:`direction` carves it back
out of the wall-time rule; the sentinel pins serving throughput like
any other metric.

Multichip scaling curves (ISSUE 13): ``MULTICHIP_r*.json`` artifacts
whose tail carries the ``MULTICHIP_CURVE`` line (r6+ dry runs —
``__graft_entry__.dryrun_multichip``'s weak-scaling sweep assembled by
``dist_util.scaling_curve``) load as per-device-efficiency submetrics
(``multichip_d<nd>_perdev_eff`` / ``..._perdev_gflops``, higher is
better) plus ONE sentinel row ``multichip_min_eff_over_floor`` — the
worst point's efficiency over the artifact's pinned floor.  Any
``*_over_floor`` row whose newest value is below 1.0 is a REGRESS even
with no predecessor artifact: the floor is a pinned CI gate, so a
collapsing curve fails exactly like a bench regression.  Curve-less
multichip artifacts that predate the sweep (r03–r05: rc=0 with the
``DRYRUN_MULTICHIP_OK`` marker) load clean with a provenance note;
rc≠0 or marker-less ones are infra-shaped as always.

Gap explanation (r7): when the sentinel flags a drop, :func:`explain`
diffs the two artifacts' roofline attribution blocks (bench r7 embeds
them; older artifacts get the analytical model derived on the spot from
the submetric label + autotune tags via ``attr.py``) and names the
stage whose share of the wall time moved — the r3→r4 geqrf
investigation as one line of sentinel output instead of a STATUS round.
``tools/bench_diff.py --explain`` prints these lines under the verdict
table.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import List, Optional

__all__ = [
    "ABFT_OVERHEAD_CEILING_PCT", "Artifact", "Report", "Row",
    "load_artifact", "diff", "direction", "explain", "format_table",
    "frac_of_gemm", "DEFAULT_THRESHOLD_PCT",
]

#: flag a drop bigger than this (percent) between consecutive artifacts
DEFAULT_THRESHOLD_PCT = 5.0

#: pinned ceiling for the ``*_abft_overhead_pct`` family (ISSUE 14):
#: checksum carriage + per-step verify must stay within this share of
#: the abft-off wall; a newest value above it is a REGRESS even on the
#: first artifact carrying the submetric (like the multichip
#: efficiency floor).
ABFT_OVERHEAD_CEILING_PCT = 10.0

_LABEL_RE = re.compile(
    r"^(?P<routine>[a-z0-9]+?)(?P<qdwh>_qdwh)?(?P<batched>_batched)?"
    r"(?P<ooc>_ooc)?_"
    r"(?P<dtype>fp32|fp64|bf16|c64|c128)_"
    r"(?P<dims>.+)$")

#: submetric-label prefix → the autotune op sites that produce it (for
#: the backend tag; see module docstring on why tags don't key alignment)
_OPS_FOR_ROUTINE = {
    "gemm": ("matmul",),
    "mxu": (),
    "potrf": ("potrf_panel", "potrf_panel_f64"),
    "getrf": ("lu_driver", "lu_panel"),
    "geqrf": ("geqrf_panel",),
    "gels": ("geqrf_panel",),
    "trtri": ("trtri_panel",),
    # batched-driver labels (<op>_batched_<dtype>_n<n>_b<B>): the
    # backend tag is the batched site's grid-vs-vmapped decision
    "potrf_batched": ("batched_potrf",),
    "getrf_batched": ("batched_lu",),
    "posv_batched": ("batched_potrf",),
    "gesv_batched": ("batched_lu",),
    "geqrf_batched": ("batched_qr",),
    "gels_batched": ("batched_qr",),
    # out-of-core labels (<op>_ooc_<dtype>_n<n>_nb<nb>, ISSUE 17): the
    # backend tag is the ooc site's pool-vs-incore residency decision
    "getrf_ooc": ("ooc",),
    "potrf_ooc": ("ooc",),
    # spectral-driver labels (ISSUE 18): the plain rows carry the
    # autotuned whole-driver decision; the _qdwh rows (forced dispatch)
    # additionally tag the in-loop Halley variant switch
    "heev": ("eig_driver",),
    "svd": ("svd_driver",),
    "heev_qdwh": ("eig_driver", "qdwh_step"),
    "svd_qdwh": ("svd_driver", "qdwh_step"),
}


def parse_label(label: str):
    """``geqrf_fp32_m32768_n4096`` → ("geqrf", "fp32", "m32768_n4096");
    batched labels keep their ``_batched`` marker in the routine
    (``posv_batched_fp32_n256_b64`` → ("posv_batched", ...)); labels
    that don't match keep their full text as the routine."""
    m = _LABEL_RE.match(label)
    if not m:
        return (label, "", "")
    return (m.group("routine") + (m.group("qdwh") or "")
            + (m.group("batched") or "") + (m.group("ooc") or ""),
            m.group("dtype"), m.group("dims"))


def direction(label: str) -> float:
    """+1 when bigger is better (GFLOP/s, ``*_solves_per_s`` rates,
    speedup ratios), −1 for wall-second keys (``*_s`` stage timers),
    the serve-latency percentile keys (``*_ms`` — the ISSUE 10
    ``serve_*_p50_ms``/``..._p99_ms`` family: milliseconds, lower is
    better; spelled out explicitly even though ``_ms`` ends in ``_s``
    so the rule survives a refactor of the wall-second suffix) and the
    structural ``*_hbm_roundtrips`` counts (ISSUE 12: materialized
    inter-stage intermediates per factorization — 0 on the full-fused
    depth, and a rise is a structural regression) and the
    ``*_abft_overhead_pct`` family (ISSUE 14: abft-on vs abft-off wall
    overhead in percent — lower is better, with the
    :data:`ABFT_OVERHEAD_CEILING_PCT` ceiling pinned even
    single-artifact).  The split-gemm families (ISSUE 16) need no
    special case: ``*_frac_of_split_gemm`` fractions and the
    ``*_over_floor`` sentinel (split rate ÷ stock rate ÷ 1.5× floor —
    judged REGRESS below 1.0 even single-artifact, see
    ``_floor_override``) are both bigger-is-better, the +1 default."""
    if label.endswith(("_per_s", "_rps")):
        # _rps (ISSUE 20): the fleet router's sustained requests per
        # second — a rate despite the trailing "s"
        return 1.0
    if label.endswith("_slo_violations"):
        # the fleet chaos run's post-recovery SLO-violation sentinel
        # (ISSUE 20): ~0 after a clean rejoin — any rise is the
        # degradation ladder failing to re-absorb traffic
        return -1.0
    if label.endswith(("_ms", "_hbm_roundtrips", "_abft_overhead_pct",
                       "_host_gb_transferred", "_hbm_peak_gb")):
        # _host_gb_transferred (ISSUE 17): GB moved over the host link
        # per out-of-core factorization — a rise means the window or
        # prefetch schedule regressed into re-fetching tiles.
        # _hbm_peak_gb (ISSUE 19): the routine's device-memory
        # high-water from the allocator gauges — a rise means an extra
        # materialized buffer on the critical path
        return -1.0
    return -1.0 if label.endswith("_s") else 1.0


@dataclass
class Artifact:
    """One loaded bench artifact."""

    path: str
    name: str
    rc: int = 0
    aggregate: Optional[dict] = None
    submetrics: dict = field(default_factory=dict)
    autotune: dict = field(default_factory=dict)
    attribution: dict = field(default_factory=dict)
    #: the active offline autotune bundle's identity (bench r11+ tags
    #: every artifact with ``{"digest", "version", ...}`` or null —
    #: whether the numbers came from a bundle-warm or probe-cold
    #: process); None for older artifacts.  A digest change between
    #: consecutive artifacts surfaces as a NOTE line next to the
    #: verdicts — like a backend tag change, it must annotate, never
    #: re-key, the alignment.
    bundle: Optional[dict] = None
    infra: List[str] = field(default_factory=list)
    #: non-fatal annotations (e.g. ``retried_infra=true`` — the run
    #: absorbed a transient backend-init failure via the resilience
    #: layer's classified retry; numbers are real, provenance noted)
    notes: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.infra

    def backend_tag(self, label: str) -> str:
        """Comma-joined backends of the autotune decisions feeding this
        routine ('' when the artifact carries no decision table)."""
        routine = parse_label(label)[0]
        ops = _OPS_FOR_ROUTINE.get(routine, ())
        hits = sorted({v for k, v in self.autotune.items()
                       if isinstance(v, str)
                       and any(k.startswith(op + "|") for op in ops)})
        return ",".join(hits)


def _aggregate_from_lines(text: str):
    """Raw bench stdout: per-routine JSON lines with the aggregate LAST.
    Returns (aggregate|None) — scans from the end, tolerating trailing
    non-JSON noise (log lines)."""
    agg = None
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            d = json.loads(ln)
        except ValueError:
            continue
        if isinstance(d, dict) and "metric" in d:
            agg = d                      # keep the LAST aggregate seen
    return agg


def _load_multichip(art: "Artifact", blob: dict) -> "Artifact":
    """Multichip dry-run wrapper (``{"n_devices", "rc", "tail", ...}``):
    parse the ``MULTICHIP_CURVE`` tail line into per-device-efficiency
    submetrics plus the ``multichip_min_eff_over_floor`` sentinel row
    (see module docstring)."""
    try:
        art.rc = int(blob.get("rc", 0))
    except (TypeError, ValueError):
        art.rc = -1
    if art.rc != 0:
        art.infra.append(f"rc={art.rc}")
    tail = str(blob.get("tail", ""))
    if "DRYRUN_RETRIED_INFRA" in tail:
        art.notes.append("retried_infra=true")
    curve = None
    for ln in tail.splitlines():
        if ln.startswith("MULTICHIP_CURVE "):
            try:
                curve = json.loads(ln[len("MULTICHIP_CURVE "):])
            except ValueError:
                art.infra.append("unparseable scaling curve")
    if not isinstance(curve, dict):
        if not art.infra and "DRYRUN_MULTICHIP_OK" in tail:
            # pre-r6 dry runs are complete artifacts without a curve —
            # provenance, not breakage
            art.notes.append("predates scaling curve")
        elif not art.infra:
            art.infra.append("no scaling curve")
        return art
    try:
        floor = float(curve.get("efficiency_floor") or 0.0)
    except (TypeError, ValueError):
        floor = 0.0
    subs = {}
    min_eff = None
    for pt in curve.get("points") or ():
        try:
            nd = int(pt["n_devices"])
            eff = float(pt["per_device_efficiency"])
            gf = float(pt.get("per_device_gflops", 0.0))
        except (TypeError, KeyError, ValueError):
            art.infra.append("malformed scaling-curve point")
            continue
        subs[f"multichip_d{nd}_perdev_eff"] = eff
        subs[f"multichip_d{nd}_perdev_gflops"] = gf
        min_eff = eff if min_eff is None else min(min_eff, eff)
    if min_eff is not None and floor > 0:
        subs["multichip_min_eff_over_floor"] = min_eff / floor
    art.submetrics = subs
    if not subs:
        art.infra.append("empty scaling curve")
    return art


def load_artifact(path: str) -> "Artifact":
    """Load one artifact: driver wrapper dict, bare aggregate dict,
    multichip dry-run wrapper, or raw bench JSON-lines output.  Never
    raises on malformed content — a file the sentinel cannot parse IS
    an infra finding."""
    name = path.rsplit("/", 1)[-1]
    art = Artifact(path=path, name=name)
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        art.infra.append(f"unreadable: {e}")
        return art
    blob = None
    try:
        blob = json.loads(text)
    except ValueError:
        pass
    if isinstance(blob, dict) and "n_devices" in blob \
            and "parsed" not in blob:
        return _load_multichip(art, blob)
    if isinstance(blob, dict) and ("parsed" in blob or "rc" in blob):
        # driver wrapper: {"n", "cmd", "rc", "tail", "parsed"}
        try:
            art.rc = int(blob.get("rc", 0))
        except (TypeError, ValueError):
            art.rc = -1
        agg = blob.get("parsed")
        if not isinstance(agg, dict):
            # rc!=0 runs may still have flushed per-routine lines +
            # a partial aggregate into the captured tail
            agg = _aggregate_from_lines(str(blob.get("tail", "")))
    elif isinstance(blob, dict) and "metric" in blob:
        agg = blob                       # bare aggregate
    elif blob is None:
        agg = _aggregate_from_lines(text)  # raw bench stdout
    else:
        agg = None
    if art.rc != 0:
        art.infra.append(f"rc={art.rc}")
    if not isinstance(agg, dict):
        art.infra.append("missing aggregate")
        return art
    art.aggregate = agg
    subs = agg.get("submetrics")
    art.submetrics = dict(subs) if isinstance(subs, dict) else {}
    at = agg.get("autotune")
    art.autotune = dict(at) if isinstance(at, dict) else {}
    ab = agg.get("attribution")
    art.attribution = {k: v for k, v in ab.items()
                       if isinstance(v, dict)} \
        if isinstance(ab, dict) else {}
    bd = agg.get("bundle")
    art.bundle = bd if isinstance(bd, dict) else None
    if not art.submetrics:
        art.infra.append("no parsed routines")
    if agg.get("partial"):
        art.infra.append("partial aggregate (suite truncated)")
    bbs = agg.get("blackbox_bundles")
    if isinstance(bbs, list):
        # flight-recorder postmortems (ISSUE 15): a degraded artifact
        # points at its own forensic bundle — surfaced as NOTE rows
        # next to the verdicts, never re-keying the alignment
        for b in bbs:
            if isinstance(b, dict) and b.get("path"):
                art.notes.append(
                    "blackbox bundle [%s] %s (digest %s)"
                    % (b.get("routine") or b.get("reason", "?"),
                       b["path"], b.get("digest", "?")))
    if agg.get("retried_infra"):
        # tagged, not failed: bench absorbed a transient init error
        # with its classified retry (resilience satellite) — the
        # artifact is complete, its provenance just carries the flag
        art.notes.append("retried_infra=true")
    return art


@dataclass
class Row:
    """One aligned routine across the artifact sequence."""

    label: str
    values: List[Optional[float]]
    verdict: str                 # REGRESS | IMPROVE | OK | NEW | GONE | n/a
    delta_pct: Optional[float]   # first present → last present
    note: str = ""


@dataclass
class Report:
    rows: List[Row]
    artifacts: List[Artifact]
    threshold_pct: float

    @property
    def regressions(self) -> List[Row]:
        return [r for r in self.rows if r.verdict == "REGRESS"]

    @property
    def infra(self):
        return [(a.name, a.infra) for a in self.artifacts if a.infra]

    @property
    def exit_code(self) -> int:
        return 1 if (self.regressions or self.infra) else 0


def _num(v, label: str = "") -> Optional[float]:
    if not isinstance(v, (int, float)):
        return None
    if label.endswith("_abft_overhead_pct"):
        # overhead percentages legitimately sit at (or noisily below)
        # zero — every finite value is a measurement the ceiling
        # sentinel must see
        return float(v)
    if label.endswith(("_hbm_roundtrips", "_over_floor",
                       "_host_gb_transferred", "_hbm_peak_gb",
                       "_slo_violations")):
        # _slo_violations: zero IS the healthy post-recovery reading
        # (ISSUE 20) — dropping it would hide the one value the
        # sentinel exists to pin
        # structural counts (steady state 0), floor-sentinel ratios (a
        # total efficiency collapse IS 0), host-link byte odometers
        # (an all-resident window legitimately moves ~0 GB) and HBM
        # high-water deltas (a tiny routine can round to 0): zero is a
        # measured value the structural judges below compare against,
        # not the failed-routine placeholder the v > 0 filter drops
        return float(v) if v >= 0 else None
    return float(v) if v > 0 else None


def _floor_override(label: str, vals, verdict: str, note: str):
    """``*_over_floor`` sentinel rows (the multichip curve's pinned
    per-device-efficiency floor): a newest value below 1.0 is a REGRESS
    regardless of history — the floor gates CI even on the first
    artifact that carries the curve.  The ``*_abft_overhead_pct``
    family gets the mirror-image CEILING pin: a newest overhead above
    :data:`ABFT_OVERHEAD_CEILING_PCT` is a REGRESS single-artifact
    (checksum protection that costs more than 10% of the run is a
    broken integration, not a tuning choice)."""
    last = next((v for v in reversed(vals) if v is not None), None)
    if label.endswith("_over_floor"):
        if last is not None and last < 1.0:
            return "REGRESS", ((note + "; ") if note else "") \
                + "below pinned floor"
    elif label.endswith("_abft_overhead_pct"):
        if last is not None and last > ABFT_OVERHEAD_CEILING_PCT:
            return "REGRESS", ((note + "; ") if note else "") \
                + "above pinned %.0f%% ceiling" % ABFT_OVERHEAD_CEILING_PCT
    return verdict, note


def diff(artifacts: List[Artifact],
         threshold_pct: float = DEFAULT_THRESHOLD_PCT) -> Report:
    """Align every submetric across the artifact sequence and judge it.

    The verdict looks at CONSECUTIVE present values (a regression in the
    middle of a three-artifact chain is still a regression even if a
    later round wins it back); ``delta_pct`` summarizes first → last.
    """
    labels: List[str] = []
    for a in artifacts:
        for k in a.submetrics:
            if k not in labels:
                labels.append(k)
    rows = []
    for label in labels:
        vals = [_num(a.submetrics.get(label), label) for a in artifacts]
        present = [v for v in vals if v is not None]
        note = ""
        tags = [a.backend_tag(label) for a in artifacts
                if a.submetrics.get(label) is not None]
        tags = [t for t in tags if t]
        if len(set(tags)) > 1:
            note = "backend changed: " + " -> ".join(
                dict.fromkeys(tags))     # ordered unique
        if len(present) < 2:
            verdict = "n/a"
            if vals and vals[-1] is not None and len(present) == 1 \
                    and all(v is None for v in vals[:-1]):
                verdict = "NEW"
            elif present and vals and vals[-1] is None:
                verdict = "GONE"
            verdict, note = _floor_override(label, vals, verdict, note)
            rows.append(Row(label, vals, verdict, None, note))
            continue
        worst_drop = 0.0
        best_gain = 0.0
        # "_s"-suffixed labels are wall SECONDS (lower is better, the
        # sign flips) — EXCEPT the "*_per_s" throughput rates, which
        # are higher-is-better like GFLOP/s (see :func:`direction`).
        # The *_abft_overhead_pct family is judged by its PINNED
        # ceiling only (the _floor_override below): it is a noisy
        # near-zero percentage where a 2.0 -> 2.2 move is a "-10%"
        # ratio regression in name only — the consecutive-ratio rule
        # would make the sentinel flaky exactly where the ceiling is
        # the meaningful gate.
        ratio_judged = not label.endswith("_abft_overhead_pct")
        sign = direction(label)
        prev = None
        for v in vals:
            if v is None:
                continue
            if ratio_judged and prev is not None and prev > 0:
                change = sign * (v / prev - 1.0) * 100.0
                worst_drop = min(worst_drop, change)
                best_gain = max(best_gain, change)
            elif prev == 0 and v > 0 \
                    and label.endswith("_hbm_roundtrips"):
                # the structural count's expected steady state IS 0, so
                # a ratio can't express its headline regression — any
                # materialized intermediate reappearing (0 -> N) is a
                # REGRESS, not a skipped comparison
                worst_drop = -float("inf")
            prev = v
        if -worst_drop > threshold_pct or _floor_override(
                label, vals, "", "")[0] == "REGRESS":
            verdict = "REGRESS"
            _, note = _floor_override(label, vals, verdict, note)
        elif vals[-1] is None:
            # present history but missing from the NEWEST artifact: the
            # silent-dropout mode the sentinel exists to catch must not
            # read as OK (REGRESS above still wins — it is more severe)
            verdict = "GONE"
        elif best_gain > threshold_pct:
            verdict = "IMPROVE"
        else:
            verdict = "OK"
        delta = ((present[-1] / present[0] - 1.0) * 100.0
                 if present[0] > 0 else None)
        rows.append(Row(label, vals, verdict, delta, note))
    order = {"REGRESS": 0, "GONE": 1, "NEW": 2, "IMPROVE": 3, "OK": 4,
             "n/a": 5}
    rows.sort(key=lambda r: (order.get(r.verdict, 9), r.label))
    # a bundle-version change between consecutive artifacts is a NOTE
    # (provenance, like retried_infra): the numbers are comparable, but
    # the reader must know one run was bundle-warm where the other was
    # probe-cold (or swept against a different offline table)
    prev_digest, seen_first = None, False
    for a in artifacts:
        if a.aggregate is None:
            continue
        cur = (a.bundle or {}).get("digest")
        if seen_first and cur != prev_digest:
            note = "bundle changed: %s -> %s" % (prev_digest or "none",
                                                 cur or "none")
            if note not in a.notes:
                a.notes.append(note)
        prev_digest, seen_first = cur, True
    return Report(rows=rows, artifacts=list(artifacts),
                  threshold_pct=threshold_pct)


def _fmt_val(v: Optional[float]) -> str:
    if v is None:
        return "-"
    if v < 10:                       # fractions / per-stage seconds
        return "%.3f" % v
    return ("%.1f" % v) if v < 10000 else ("%.0f" % v)


def frac_of_gemm(report: Report, label: str) -> Optional[float]:
    """The NEWEST artifact's ``<label>_frac_of_gemm`` derived submetric
    (bench.py r6+: routine TF/s ÷ same-run gemm TF/s) for a routine row
    — the ROADMAP fraction targets surfaced next to the verdict instead
    of living in hand arithmetic.  Strictly the newest artifact, never
    an older fallback: a missing fraction (artifact predates the
    submetric, or the newest run's gemm anchor never landed — exactly
    the infra shapes this tool flags) must read as absent, not as a
    stale number that looks current.  None also for the derived rows
    themselves and for wall-time keys."""
    if label.endswith("_frac_of_gemm") or label.endswith("_s"):
        return None
    if not report.artifacts:
        return None
    v = report.artifacts[-1].submetrics.get(label + "_frac_of_gemm")
    return float(v) if isinstance(v, (int, float)) else None


def frac_of_split_gemm(report: Report, label: str) -> Optional[float]:
    """The NEWEST artifact's ``<label>_frac_of_split_gemm`` derived
    submetric (bench.py ISSUE 16: fp32 routine TF/s ÷ same-run bf16x3
    split-gemm TF/s — the fraction of the EMULATED-fp32 peak each
    factorization banks).  Same strict-newest / absent-not-stale
    contract as :func:`frac_of_gemm`."""
    if label.endswith(("_frac_of_gemm", "_frac_of_split_gemm", "_s")):
        return None
    if not report.artifacts:
        return None
    v = report.artifacts[-1].submetrics.get(label + "_frac_of_split_gemm")
    return float(v) if isinstance(v, (int, float)) else None


def format_table(report: Report) -> str:
    """Human-readable verdict table + infra findings.  The ``frac``
    column renders each routine's newest fraction-of-gemm
    (:func:`frac_of_gemm`); ``frac_split`` the fraction of the bf16x3
    split-gemm anchor (:func:`frac_of_split_gemm`, ISSUE 16)."""
    heads = ["routine"] + [a.name for a in report.artifacts] \
        + ["Δ%", "frac", "frac_split", "verdict"]
    body = []
    for r in report.rows:
        delta = "%+.1f%%" % r.delta_pct if r.delta_pct is not None else "-"
        frac = frac_of_gemm(report, r.label)
        fsp = frac_of_split_gemm(report, r.label)
        line = [r.label] + [_fmt_val(v) for v in r.values] \
            + [delta, "%.3f" % frac if frac is not None else "-",
               "%.3f" % fsp if fsp is not None else "-",
               r.verdict + ((" (%s)" % r.note) if r.note else "")]
        body.append(line)
    widths = [max(len(str(row[i])) for row in [heads] + body)
              for i in range(len(heads))]
    out = []
    for row in [heads] + body:
        out.append("  ".join(str(c).ljust(w) for c, w in zip(row, widths))
                   .rstrip())
    out.append("")
    n_reg = len(report.regressions)
    out.append("threshold: %.1f%%  regressions: %d"
               % (report.threshold_pct, n_reg))
    for name, reasons in report.infra:
        out.append("INFRA %s: %s" % (name, "; ".join(reasons)))
    for a in report.artifacts:
        for note in a.notes:
            out.append("NOTE %s: %s" % (a.name, note))
    out.append("verdict: %s"
               % ("FAIL" if report.exit_code else "PASS"))
    return "\n".join(out)


# ---------------------------------------------------------------------------
# Roofline attribution diff — the sentinel's gap EXPLANATION
# ---------------------------------------------------------------------------

def _attr_mod():
    """The attribution engine (``perf/attr.py``).  This module runs in
    two lives — imported as ``slate_tpu.perf.regress`` (tests) and
    exec'd by file path from ``tools/bench_diff.py`` on jax-free
    machines — so the sibling is loaded the same way when the package
    context is absent."""
    try:
        from . import attr
        return attr
    except ImportError:
        import importlib.util
        import os
        import sys
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "attr.py")
        name = "_slate_tpu_attr"
        if name in sys.modules:
            return sys.modules[name]
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
        return mod


def attribution_for(artifact: Artifact, label: str):
    """The artifact's gap report for one routine: the embedded
    ``attribution`` block when the artifact carries one (bench r7+),
    else derived analytically from the submetric label, its GFLOP/s and
    the autotune tags — so pre-r7 artifacts (r03/r04) explain too."""
    blk = artifact.attribution.get(label)
    if isinstance(blk, dict) and blk.get("stages"):
        return blk
    gf = artifact.submetrics.get(label)
    try:
        return _attr_mod().attribute(label, gf,
                                     autotune=artifact.autotune or None)
    except Exception:
        return None


def explain(report: Report) -> List[str]:
    """One line per REGRESS row naming the stage whose share of the
    wall time moved between the first and last artifacts that carry the
    routine (plus the backend-change note when the autotune tag moved).
    Empty when nothing regressed."""
    attr = _attr_mod()
    lines = []
    for row in report.regressions:
        present = [a for a, v in zip(report.artifacts, row.values)
                   if v is not None]
        if len(present) < 2:
            continue
        old = attribution_for(present[0], row.label)
        new = attribution_for(present[-1], row.label)
        if not old or not new:
            lines.append("%s: no attribution model for this routine"
                         % row.label)
            continue
        try:
            lines.append(attr.explain_pair(old, new,
                                           delta_pct=row.delta_pct,
                                           note=row.note))
        except Exception as e:    # an explanation must never mask the verdict
            lines.append("%s: attribution diff failed: %s"
                         % (row.label, e))
    return lines
