"""Performance analysis helpers (lowered-HLO collective/flop profiling)."""

from .hlo_profile import (CollectiveOp, ComputationProfile, DotOp,
                          ModuleProfile, profile_fn, profile_hlo_text,
                          stablehlo_collective_shapes)

__all__ = [
    "CollectiveOp", "ComputationProfile", "DotOp", "ModuleProfile",
    "profile_fn", "profile_hlo_text", "stablehlo_collective_shapes",
]
