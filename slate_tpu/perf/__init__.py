"""Performance helpers: lowered-HLO collective/flop profiling
(:mod:`.hlo_profile`) and the autotuned backend dispatch table
(:mod:`.autotune`)."""

from .hlo_profile import (CollectiveOp, ComputationProfile, DotOp,
                          ModuleProfile, profile_fn, profile_hlo_text,
                          stablehlo_collective_shapes)

__all__ = [
    "CollectiveOp", "ComputationProfile", "DotOp", "ModuleProfile",
    "autotune", "profile_fn", "profile_hlo_text",
    "stablehlo_collective_shapes",
]


def __getattr__(name):
    # lazy: autotune pulls in jax.random/pallas bits only when used
    if name == "autotune":
        import importlib

        return importlib.import_module(".autotune", __name__)
    raise AttributeError(name)
