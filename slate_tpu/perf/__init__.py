"""Performance helpers: lowered-HLO collective/flop profiling
(:mod:`.hlo_profile`), the autotuned backend dispatch table
(:mod:`.autotune`), the runtime metrics registry (:mod:`.metrics`),
the bench regression sentinel (:mod:`.regress`), the roofline
attribution engine (:mod:`.attr`) that joins the analytical per-stage
cost model with the measured metrics to say where the time went, and
the live serving telemetry layer (:mod:`.telemetry`): per-request
tracing, SLO histograms, Prometheus/JSONL streaming exporters and the
in-process live sentinel, the offline autotune sweep engine +
versioned warm-start bundles (:mod:`.sweep`) that close the loop
between the roofline model and the decision table, and the
device-truth profiling layer (:mod:`.xprof`) that captures an XProf
trace around an opt-in region, joins per-kernel device walls onto the
repo's stage vocabulary, and feeds the measured signals back into
attribution and the sweep."""

from .hlo_profile import (CollectiveOp, ComputationProfile, DotOp,
                          ModuleProfile, collective_byte_census,
                          profile_fn, profile_hlo_text,
                          stablehlo_collective_shapes)

__all__ = [
    "CollectiveOp", "ComputationProfile", "DotOp", "ModuleProfile",
    "attr", "autotune", "blackbox", "collective_byte_census", "metrics",
    "profile_fn", "profile_hlo_text", "regress",
    "stablehlo_collective_shapes", "sweep", "telemetry", "xprof",
]


def __getattr__(name):
    # lazy: autotune pulls in jax.random/pallas bits only when used;
    # attr/metrics/regress/sweep/telemetry stay stdlib-light and import
    # on demand
    if name in ("attr", "autotune", "blackbox", "metrics", "regress",
                "sweep", "telemetry", "xprof"):
        import importlib

        return importlib.import_module("." + name, __name__)
    raise AttributeError(name)
