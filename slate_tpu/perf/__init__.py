"""Performance helpers: lowered-HLO collective/flop profiling
(:mod:`.hlo_profile`), the autotuned backend dispatch table
(:mod:`.autotune`), the runtime metrics registry (:mod:`.metrics`) and
the bench regression sentinel (:mod:`.regress`)."""

from .hlo_profile import (CollectiveOp, ComputationProfile, DotOp,
                          ModuleProfile, profile_fn, profile_hlo_text,
                          stablehlo_collective_shapes)

__all__ = [
    "CollectiveOp", "ComputationProfile", "DotOp", "ModuleProfile",
    "autotune", "metrics", "profile_fn", "profile_hlo_text", "regress",
    "stablehlo_collective_shapes",
]


def __getattr__(name):
    # lazy: autotune pulls in jax.random/pallas bits only when used;
    # metrics/regress stay stdlib-light and import on demand
    if name in ("autotune", "metrics", "regress"):
        import importlib

        return importlib.import_module("." + name, __name__)
    raise AttributeError(name)
