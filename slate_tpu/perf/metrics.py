"""Process-wide runtime metrics registry: counters, gauges, timers and
histograms behind one env-gated, thread-safe facade.

The reference ships first-class observability (``trace::Block`` RAII
events + SVG gantt, ``Debug`` invariant checks); this module is the
*quantitative* sibling the port was missing: every layer that makes a
silent decision — the autotune table (cache hit/miss/stale, candidates
pruned and why, probe reps, winning backend per site), the driver
facades (calls, wall time, jit compiles, post-condition outcomes,
fallback activations such as the LU ``triangular_solve`` path), Pallas
dispatch, and the ``parallel/dist_util`` collectives — now increments a
named counter here, and two exporters make the numbers travel:

* :func:`snapshot` → a JSON-safe dict embedded in every ``bench.py``
  line and aggregate, so each ``BENCH_r*.json`` artifact carries the
  decisions that produced its numbers;
* :func:`slate_tpu.trace.finish_perfetto` → Chrome-trace/Perfetto JSON
  merging ``trace.Block`` spans with this registry's counter tracks.

The stage-2 bulge-chase dispatch (``linalg._chase``) adds its own
counter family: ``chase.dispatch.<backend>`` per chase execution,
``chase.host_bytes`` for band/reflector-log bytes crossing the
host↔device boundary (pinned to 0 in CI on the device-resident
``pallas_wavefront`` path — the "zero tunnel" claim made observable),
``chase.ingest_bytes`` for the distributed drivers' one-time operand
upload, and timers ``chase.hb2st`` / ``chase.tb2bd`` feeding bench's
per-stage ``*_stage2_chase_s`` submetrics.

Design rules (the BLASX lesson — scheduler behavior is only tunable
once it is measured — balanced against the library's perf contract):

* **Near-zero overhead when off.**  Every recording entry point checks
  one attribute (``_registry.enabled``) and returns; no locks, no
  allocation.  The registry is OFF unless ``SLATE_TPU_METRICS=1`` (or a
  harness calls :func:`on`, as ``bench.py`` does).
* **Host-side only by default.**  Instrumentation runs in Python at
  dispatch/trace time; it never changes the compiled program.  The one
  exception — the LU ``_u12_with_linv`` fallback counter, which needs a
  runtime ``jax.debug.callback`` — is gated by its own knob
  (``SLATE_TPU_METRICS_DEVICE=1``) precisely because inserting the
  callback changes the traced program.
* **One facade.**  Non-``perf`` modules reach the registry ONLY through
  the public functions here (``tests/test_backend_registry.py`` guards
  against private ``_registry`` imports), keeping the instrumentation
  seams enumerable.

Env knobs:

* ``SLATE_TPU_METRICS`` — ``1`` enables the registry at import.
* ``SLATE_TPU_CHECK_FINITE`` — ``1`` makes every instrumented driver
  facade validate its outputs with :func:`slate_tpu.debug.check_finite`
  and increment ``checks.nonfinite`` (a warning, not an exception)
  instead of letting NaNs fail silently downstream; ``2`` is the strict
  tier — it folds into ``SLATE_TPU_HEALTH=strict``
  (:mod:`slate_tpu.resilience.health`), where a failed gate degrades to
  the stock backend and RAISES ``SlateError`` if still failing.
* ``SLATE_TPU_METRICS_DEVICE`` — ``1`` adds runtime callbacks for
  data-dependent counters (LU u12 fallback activations).  Perturbs
  timing; off by default.
"""

from __future__ import annotations

import functools
import math
import os
import threading
import time
import warnings

__all__ = [
    "enabled", "on", "off", "reset", "inc", "set_gauge", "observe",
    "timer", "observe_time", "snapshot", "snapshot_delta",
    "counter_series", "drain_samples", "instrument_driver",
    "check_finite_wanted", "device_metrics_wanted",
    "resilience_wanted", "set_resilience_hint",
    "record_fallback_outcome", "pallas_census", "install_compile_watch",
    "add_compile_listener", "set_annotation_hook",
    "step_timer", "count_hbm_roundtrips", "STEP_HBM_ROUNDTRIPS",
    "bucket_bounds", "quantiles_from_buckets", "hist_quantiles",
    "env_flag",
]

_ENV = "SLATE_TPU_METRICS"

#: cap on stored (ts, name, value) counter samples (the Perfetto counter
#: tracks); past the cap counters keep counting but stop sampling.
_MAX_SAMPLES = 65536


def env_flag(name: str, default: str = "") -> bool:
    """Truthy-env-knob parse shared by the observability modules (one
    helper, not a private copy per module — the registry-guard test
    forbids non-perf modules reaching ``metrics._*``)."""
    return os.environ.get(name, default).strip().lower() in (
        "1", "true", "on", "yes")


class _Registry:
    """The process-wide store.  Private — use the module facade."""

    def __init__(self):
        self.enabled = env_flag(_ENV)
        self.lock = threading.RLock()
        self.counters: dict = {}
        self.gauges: dict = {}
        self.timers: dict = {}      # name -> [count, total, min, max]
        self.hists: dict = {}       # name -> {count, total, buckets{}}
        self.samples: list = []     # (perf_counter ts, name, value)


_registry = _Registry()


# ---------------------------------------------------------------------------
# Lifecycle
# ---------------------------------------------------------------------------

def enabled() -> bool:
    return _registry.enabled


def on() -> None:
    """Enable recording (also installs the jit compile-watch hook)."""
    _registry.enabled = True
    install_compile_watch()


def off() -> None:
    _registry.enabled = False


def reset() -> None:
    """Drop every recorded value (the enabled flag is left as is)."""
    reg = _registry
    with reg.lock:
        reg.counters.clear()
        reg.gauges.clear()
        reg.timers.clear()
        reg.hists.clear()
        reg.samples.clear()


# ---------------------------------------------------------------------------
# Recording
# ---------------------------------------------------------------------------

def inc(name: str, value: float = 1.0, force: bool = False) -> None:
    """Add ``value`` to counter ``name``.  ``force`` records even while
    the registry is off — reserved for counters whose OWN opt-in knob is
    set (``checks.*``, device callbacks), so enabling that knob alone is
    enough to see its numbers."""
    reg = _registry
    if not (reg.enabled or force):
        return
    with reg.lock:
        v = reg.counters.get(name, 0.0) + value
        reg.counters[name] = v
        if len(reg.samples) < _MAX_SAMPLES:
            reg.samples.append((time.perf_counter(), name, v))


def set_gauge(name: str, value: float) -> None:
    reg = _registry
    if not reg.enabled:
        return
    with reg.lock:
        reg.gauges[name] = float(value)
        if len(reg.samples) < _MAX_SAMPLES:
            reg.samples.append((time.perf_counter(), name, float(value)))


def observe_time(name: str, seconds: float) -> None:
    """Record one duration into timer ``name`` (count/total/min/max)."""
    reg = _registry
    if not reg.enabled:
        return
    with reg.lock:
        t = reg.timers.get(name)
        if t is None:
            reg.timers[name] = [1, seconds, seconds, seconds]
        else:
            t[0] += 1
            t[1] += seconds
            t[2] = min(t[2], seconds)
            t[3] = max(t[3], seconds)


#: optional factory (name -> context manager) entered/exited around every
#: named timer window — the xprof capture installs
#: ``jax.profiler.TraceAnnotation`` here so the ``step.<op>.<stage>``
#: vocabulary exists on the profiler timeline even while the registry is
#: off.  Host-side only: annotations never change a compiled program.
_annotation_hook: list = [None]


def set_annotation_hook(factory) -> None:
    """Install (or clear, with None) the timer annotation factory — see
    :data:`_annotation_hook`.  Used by ``slate_tpu.perf.xprof.capture``
    for the duration of a capture window."""
    _annotation_hook[0] = factory


class _Timer:
    """Context manager recording its wall time into a named timer."""

    __slots__ = ("name", "_t0", "_ann")

    def __init__(self, name: str):
        self.name = name
        self._t0 = 0.0
        self._ann = None

    def __enter__(self):
        hook = _annotation_hook[0]
        if hook is not None:
            try:
                ann = hook(self.name)
                ann.__enter__()
                self._ann = ann
            except Exception:
                self._ann = None
        if _registry.enabled:
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._ann is not None:
            try:
                self._ann.__exit__(None, None, None)
            finally:
                self._ann = None
        if _registry.enabled and self._t0:
            observe_time(self.name, time.perf_counter() - self._t0)
        return False


def timer(name: str) -> _Timer:
    return _Timer(name)


#: counter of materialized HBM intermediates between the sub-stages of a
#: right-looking factorization step (pivot-row gather, u12 write-back,
#: per-strip trailing read-modify-write).  The composed step drivers
#: increment it per step at trace time; the fused step kernels
#: (``getrf_step_fused`` / ``potrf_step_fused`` — one pallas_call per
#: step, aliased carry) never do, and CI pins the fused paths at ZERO.
STEP_HBM_ROUNDTRIPS = "step.hbm_roundtrips"


def step_timer(op: str, stage: str) -> _Timer:
    """Timer ``step.<op>.<stage>`` for one sub-stage of a right-looking
    factorization step (``panel`` / ``trsm`` / ``update`` on the
    composed paths, ``fused`` when one kernel owns the whole step).
    Recorded at trace/dispatch time — under jit this attributes Python
    composition cost and, on the bench's per-routine lines, lets a diff
    say WHICH stage composition a getrf/potrf move came from.

    The key is the JOIN KEY the attribution engine
    (``slate_tpu/perf/attr.py``) consumes, so it must stay unambiguous
    under splitting on ``"."``: dots inside ``op`` or ``stage`` are
    sanitized to underscores.  Without this, an op named ``"a.b"``
    firing stage ``"update"`` would parse as op ``a`` stage ``b`` and
    collide its count/total into another routine's stage — two ops
    firing the same stage name in one routine must keep distinct
    timers (regression-tested in ``tests/test_metrics.py``)."""
    return _Timer("step.%s.%s" % (op.replace(".", "_"),
                                  stage.replace(".", "_")))


def count_hbm_roundtrips(n: float = 1.0) -> None:
    """Count ``n`` materialized inter-stage HBM intermediates (see
    :data:`STEP_HBM_ROUNDTRIPS`)."""
    inc(STEP_HBM_ROUNDTRIPS, n)


def _bucket(value: float) -> str:
    if value <= 0:
        return "le_0"
    return "le_2^%d" % math.ceil(math.log2(value))


def observe(name: str, value: float) -> None:
    """Record one sample into histogram ``name`` (power-of-two buckets —
    the same granularity the autotune matmul keys use)."""
    reg = _registry
    if not reg.enabled:
        return
    with reg.lock:
        h = reg.hists.get(name)
        if h is None:
            h = reg.hists[name] = {"count": 0, "total": 0.0, "buckets": {}}
        h["count"] += 1
        h["total"] += value
        b = _bucket(value)
        h["buckets"][b] = h["buckets"].get(b, 0) + 1


# ---------------------------------------------------------------------------
# Histogram quantile readback (ISSUE 10: the serve SLO histograms need
# p50/p95/p99 without pulling in numpy — the resolution is the log2
# bucket width, exactly the granularity an SLO judgment needs)
# ---------------------------------------------------------------------------

def bucket_bounds(bucket: str):
    """``(lo, hi)`` of one log2 histogram bucket key (``"le_2^k"`` →
    ``(2^(k-1), 2^k)``; ``"le_0"`` → ``(0, 0)``); None for keys this
    registry never produces."""
    if bucket == "le_0":
        return (0.0, 0.0)
    if not bucket.startswith("le_2^"):
        return None
    try:
        k = int(bucket[5:])
    except ValueError:
        return None
    hi = 2.0 ** k
    return (hi / 2.0, hi)


def quantiles_from_buckets(hist, qs=(0.5, 0.95, 0.99)) -> dict:
    """Stdlib quantile readback from one histogram snapshot dict
    (``{"count", "total", "buckets"}`` — a :func:`snapshot` or
    :func:`snapshot_delta` entry): the q-quantile's bucket is found by
    cumulative count and the value placed inside it by linear
    interpolation, so the estimate always lies within a factor of two
    of the exact order statistic (the bucket width).  Returns
    ``{q: value}``; an empty histogram returns ``{}``."""
    buckets = (hist or {}).get("buckets") or {}
    items = []
    for b, c in buckets.items():
        bounds = bucket_bounds(b)
        if bounds is not None and c > 0:
            items.append((bounds[0], bounds[1], int(c)))
    items.sort(key=lambda x: x[1])
    total = sum(c for _, _, c in items)
    if total <= 0:
        return {}
    out = {}
    for q in qs:
        rank = max(float(q), 0.0) * total
        cum = 0.0
        val = items[-1][1]
        for lo, hi, c in items:
            if cum + c >= rank - 1e-12:
                frac = 0.0 if c <= 0 else max(0.0, min(1.0,
                                                       (rank - cum) / c))
                val = lo + frac * (hi - lo)
                break
            cum += c
        out[q] = val
    return out


def hist_quantiles(name: str, qs=(0.5, 0.95, 0.99)) -> dict:
    """p50/p95/p99 readback of registry histogram ``name`` (see
    :func:`quantiles_from_buckets`); ``{}`` when it never recorded."""
    reg = _registry
    with reg.lock:
        h = reg.hists.get(name)
        if h is None:
            return {}
        h = {"count": h["count"], "total": h["total"],
             "buckets": dict(h["buckets"])}
    return quantiles_from_buckets(h, qs)


# ---------------------------------------------------------------------------
# Exporters
# ---------------------------------------------------------------------------

def snapshot() -> dict:
    """JSON-safe view of everything recorded so far — the dict embedded
    in every ``bench.py`` JSON line and aggregate."""
    reg = _registry
    with reg.lock:
        return {
            "enabled": reg.enabled,
            "counters": dict(reg.counters),
            "gauges": dict(reg.gauges),
            "timers": {k: {"count": t[0], "total_s": t[1],
                           "min_s": t[2], "max_s": t[3]}
                       for k, t in reg.timers.items()},
            "hists": {k: {"count": h["count"], "total": h["total"],
                          "buckets": dict(h["buckets"])}
                      for k, h in reg.hists.items()},
        }


def snapshot_delta(before: dict, after: dict) -> dict:
    """What happened BETWEEN two :func:`snapshot` calls — the
    self-contained per-routine block ``bench.py`` embeds in each JSON
    line (the registry accumulates across the whole process, so a raw
    snapshot on a late routine's line would carry every earlier
    routine's counters/timers).

    * counters/gauges: entries whose value changed (counters as the
      numeric difference, gauges at their new value);
    * timers: count/total differences for timers that fired in the
      window (``min_s``/``max_s`` are process-lifetime bounds and
      cannot be diffed — they are carried from ``after`` and marked by
      the window's ``"delta": true`` flag);
    * hists: count/total/bucket differences for histograms that grew.
    """
    b_c = before.get("counters", {}) or {}
    a_c = after.get("counters", {}) or {}
    counters = {k: v - b_c.get(k, 0.0) for k, v in a_c.items()
                if v != b_c.get(k, 0.0)}
    b_g = before.get("gauges", {}) or {}
    gauges = {k: v for k, v in (after.get("gauges", {}) or {}).items()
              if k not in b_g or v != b_g[k]}
    b_t = before.get("timers", {}) or {}
    timers = {}
    for k, t in (after.get("timers", {}) or {}).items():
        prev = b_t.get(k, {})
        dc = t.get("count", 0) - prev.get("count", 0)
        if dc <= 0:
            continue
        timers[k] = {"count": dc,
                     "total_s": t.get("total_s", 0.0)
                     - prev.get("total_s", 0.0),
                     "min_s": t.get("min_s"), "max_s": t.get("max_s")}
    b_h = before.get("hists", {}) or {}
    hists = {}
    for k, h in (after.get("hists", {}) or {}).items():
        prev = b_h.get(k, {})
        dc = h.get("count", 0) - prev.get("count", 0)
        if dc <= 0:
            continue
        pb = prev.get("buckets", {}) or {}
        hists[k] = {"count": dc,
                    "total": h.get("total", 0.0) - prev.get("total", 0.0),
                    "buckets": {bk: bv - pb.get(bk, 0)
                                for bk, bv in h.get("buckets", {}).items()
                                if bv != pb.get(bk, 0)}}
    return {"enabled": after.get("enabled", False), "delta": True,
            "counters": counters, "gauges": gauges, "timers": timers,
            "hists": hists}


def counter_series() -> list:
    """``[(perf_counter_ts, name, value)]`` counter samples, oldest
    first — the Perfetto counter tracks."""
    with _registry.lock:
        return list(_registry.samples)


def drain_samples() -> list:
    """Pop and return every counter sample (used by
    :func:`slate_tpu.trace.finish_perfetto` so a second export starts
    clean)."""
    with _registry.lock:
        out = list(_registry.samples)
        _registry.samples.clear()
        return out


# ---------------------------------------------------------------------------
# jit compile watch — the "how many times did this routine recompile"
# counter.  jax.monitoring publishes per-compile durations
# (/jax/core/compile/backend_compile_duration); one process-wide
# listener forwards them into the registry while it is enabled.
# ---------------------------------------------------------------------------

_compile_watch_installed = [False]

#: extra ``callback(event, duration, **kw)`` sinks fanned the raw
#: jax.monitoring stream (the xprof capture's per-fn compile ledger
#: registers here).  Called BEFORE the registry-enabled check so a
#: capture window sees compiles even with metrics off; each callback is
#: individually guarded — a broken listener must never raise from
#: inside jax's compile path.
_compile_listeners: list = []


def add_compile_listener(cb) -> None:
    """Fan the jax.monitoring compile-event stream out to ``cb`` too
    (idempotent per callback object).  Callers still need
    :func:`install_compile_watch` to register the process-wide hook."""
    if cb not in _compile_listeners:
        _compile_listeners.append(cb)


def _on_jax_event(event: str, duration, **kw) -> None:
    # jax.monitoring's documented listener contract is
    # callback(event, duration, **kwargs) — swallow the kwargs or a
    # future jax that passes them raises from inside its compile path
    for cb in _compile_listeners:
        try:
            cb(event, duration, **kw)
        except Exception:
            pass
    if not _registry.enabled:
        return
    if event.endswith("backend_compile_duration"):
        inc("jit.backend_compiles")
        inc("jit.backend_compile_secs", float(duration))
        fn = kw.get("fun_name") or kw.get("module_name")
        if fn:
            observe_time("jit.compile.%s" % str(fn).replace(".", "_")[:60],
                         float(duration))
    elif "compile" in event:
        inc("jit.compile_events")


def install_compile_watch() -> None:
    """Register the jax.monitoring listener once per process.  The
    listener itself is a no-op while the registry is off, so installing
    it costs nothing for untraced runs."""
    if _compile_watch_installed[0]:
        return
    try:
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(_on_jax_event)
        _compile_watch_installed[0] = True
    except Exception:       # pragma: no cover - jax without monitoring
        pass


# ---------------------------------------------------------------------------
# Driver facade instrumentation
# ---------------------------------------------------------------------------

def check_finite_wanted() -> bool:
    """The ``SLATE_TPU_CHECK_FINITE=1`` opt-in: instrumented drivers
    validate their outputs post-call (read per call so tests can
    monkeypatch the environment)."""
    return env_flag("SLATE_TPU_CHECK_FINITE")


def device_metrics_wanted() -> bool:
    """The ``SLATE_TPU_METRICS_DEVICE=1`` opt-in for runtime-callback
    counters (changes the traced program — never on by default)."""
    return env_flag("SLATE_TPU_METRICS_DEVICE")


#: set by slate_tpu.resilience when a PROGRAMMATIC fault plan is
#: installed (the env knobs are read directly below), so the driver
#: wrapper consults the resilience pipeline without importing it when
#: nothing is configured
_resilience_hint = [False]


def set_resilience_hint(on: bool) -> None:
    """Flag that a programmatic resilience plan is active (called by
    :func:`slate_tpu.resilience.inject.install` / ``clear_plan``)."""
    _resilience_hint[0] = bool(on)


def resilience_wanted() -> bool:
    """Should the instrumented driver facades run the resilience
    post-condition pipeline (fault injection + health gates)?  True
    when a programmatic plan is installed, a ``SLATE_TPU_FAULT_INJECT``
    plan is set, ``SLATE_TPU_HEALTH`` names an active tier, or the
    legacy finite check is at its strict level (``=2``, folded into
    the health knob as ``strict``)."""
    return (_resilience_hint[0]
            or bool(os.environ.get("SLATE_TPU_FAULT_INJECT", "").strip())
            or os.environ.get("SLATE_TPU_HEALTH", "").strip().lower()
            in ("warn", "retry", "strict")
            or os.environ.get("SLATE_TPU_CHECK_FINITE", "").strip()
            == "2")


def _leaves(x, out=None) -> list:
    """Array leaves of a driver result: raw arrays, matrix wrappers
    (``.array`` resolves the stored op view) and (named) tuples."""
    if out is None:
        out = []
    if x is None or isinstance(x, (bool, int, float, complex, str)):
        return out
    if isinstance(x, (list, tuple)):
        for e in x:
            _leaves(e, out)
        return out
    arr = getattr(x, "array", x)
    if hasattr(arr, "shape") and hasattr(arr, "dtype"):
        out.append(arr)
    return out


def _check_outputs(name: str, out) -> None:
    """The opt-in post-condition: per-tile NaN/Inf census on every array
    leaf via :func:`slate_tpu.debug.check_finite`; a hit increments
    ``checks.nonfinite`` and warns instead of raising (counting beats
    failing silently downstream, and beats killing a pipeline whose
    caller may handle the NaN)."""
    try:
        import jax

        tracer_t = jax.core.Tracer
    except Exception:           # pragma: no cover
        tracer_t = ()
    import slate_tpu.debug as _debug
    from slate_tpu.exceptions import SlateError

    inc("checks.runs", force=True)
    for arr in _leaves(out):
        if tracer_t and isinstance(arr, tracer_t):
            continue            # inside a jit trace: nothing to check yet
        try:
            _debug.check_finite(arr, name="%s output" % name)
        except SlateError as e:
            inc("checks.nonfinite", force=True)
            warnings.warn(str(e), RuntimeWarning, stacklevel=3)
        except Exception:
            continue            # unconvertible leaf (weak types, etc.)


def instrument_driver(name: str):
    """Decorator for a public driver facade: counts calls and wall time
    (``driver.<name>.calls`` / timer ``driver.<name>``) and runs the
    opt-in finite check.  When every observability knob is off the
    wrapper is two attribute reads and a call — the wrapped driver runs
    the identical backend path."""

    label = "driver.%s" % name

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            reg = _registry
            checks = check_finite_wanted()
            resil = resilience_wanted()
            if not (reg.enabled or checks or resil):
                return fn(*args, **kwargs)
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            if reg.enabled:
                inc(label + ".calls")
                observe_time(label, time.perf_counter() - t0)
            if resil:
                # the resilience post-condition pipeline: driver.output
                # fault injection + the SLATE_TPU_HEALTH gate ladder
                # (warn / retry-on-safe-backend-with-quarantine /
                # strict).  Skips itself under a jit trace.
                from slate_tpu.resilience import health as _health

                out = _health.driver_gate(name, fn, args, kwargs, out)
                checks = checks and _health.mode() == "off"
            if checks:
                # legacy SLATE_TPU_CHECK_FINITE=1 warn-and-count path
                # (=2 folds into the health gate above as strict)
                _check_outputs(name, out)
            return out

        wrapper.__metrics_driver__ = name
        return wrapper

    return deco


def record_fallback_outcome(took_fallback) -> None:
    """Runtime-callback sink for the LU ``_u12_with_linv`` guard
    (``SLATE_TPU_METRICS_DEVICE=1``): counts which branch the traced
    ``lax.cond`` actually took."""
    inc("lu.u12_linv.fallback" if bool(took_fallback)
        else "lu.u12_linv.fast", force=True)


# ---------------------------------------------------------------------------
# Pallas launch census bridge
# ---------------------------------------------------------------------------

def pallas_census(op: str, fn, *args, **kwargs) -> int:
    """Count ``fn(*args)``'s ``pallas_call`` invocations with the
    existing jaxpr census (:func:`slate_tpu.perf.hlo_profile.
    count_pallas_calls` — platform-independent) and record the result as
    gauge ``pallas.launches.<op>``.  Returns the count."""
    from slate_tpu.perf.hlo_profile import count_pallas_calls

    n = count_pallas_calls(fn, *args, **kwargs)
    set_gauge("pallas.launches.%s" % op, float(n))
    return n
