"""Autotuned kernel dispatch: measured per-(op, shape, dtype) backend
selection with a persistent decision cache.

The library carries more than one implementation of its hot op sites —
stock XLA ops, the hand-written Pallas VMEM kernels
(:mod:`slate_tpu.ops.pallas_kernels`) and the Ozaki int8-slice fp64
matmul (:mod:`slate_tpu.ops.ozaki`).  SLATE itself auto-selects among
algorithm variants per problem (``method.hh`` → :mod:`slate_tpu.method`),
and the tile-granularity literature ("Design in Tiles", BLASX) shows
that backend selection — searched once, cached, then reused — is what
turns hand-tuned kernels into delivered throughput.  This module is that
search:

* Every multi-backend op site asks :func:`select` (usually through
  :func:`slate_tpu.method.select_backend`) for a backend name keyed by
  ``(op, shape, dtype, precision)``.
* On first use of a key the candidate implementations are **pruned**
  (a candidate that fails to compile — e.g. a Mosaic VMEM overflow — or
  that exceeds the library's scaled-residual accuracy guard is dropped
  before any clock starts), then **timed** on synthetic operands of the
  concrete shape, and the winner is recorded.
* Decisions land in an in-process table AND an on-disk JSON cache keyed
  by (jax version, jaxlib version, backend platform, platform/libtpu
  version), so subsequent processes compile straight to the winning
  backend with **zero timing repetitions**.  A version-key mismatch
  invalidates the whole cache.

Environment knobs:

* ``SLATE_TPU_AUTOTUNE_CACHE`` — cache file path (default
  ``$XDG_CACHE_HOME/slate_tpu/autotune.json``).
* ``SLATE_TPU_AUTOTUNE_BUNDLE`` — path to an offline warm-start bundle
  (``tools/sweep.py`` / :mod:`slate_tpu.perf.sweep`): a version-keyed
  decision table + fitted interpolating model consumed as the
  first-priority probe-free source.  The full resolution ladder is
  forced pin → quarantine filter → bundle entry → cached timing →
  bundle model (shapes never swept) → runtime probe fallback, with
  quarantine demotions masking bundle entries exactly as they mask
  cached winners.
* ``SLATE_TPU_AUTOTUNE`` — ``0`` disables timing: every decision falls
  back to the first (heuristically preferred) eligible candidate.
* ``SLATE_TPU_AUTOTUNE_FORCE`` — comma list of ``op=backend`` pairs
  pinning decisions (e.g. ``matmul=pallas,potrf_panel=xla``).
* ``SLATE_TPU_USE_PALLAS`` / ``SLATE_TPU_F64_MXU`` — tri-state
  (``auto``/``1``/``0``) eligibility of the Pallas / Ozaki candidate
  sets (:mod:`slate_tpu.config`).
* ``SLATE_TPU_QUARANTINE_TTL_S`` — lifetime of resilience demotions
  (health-gate quarantine, persisted at ``<cache>.quarantine``; see
  :mod:`slate_tpu.resilience.health` and :meth:`AutotuneTable.
  quarantine_backend`).

Timing never runs on non-TPU backends: there the candidate set collapses
to the single heuristic default (Pallas kernels run in interpret mode on
CPU and are only selected when forced), so CI and CPU users pay nothing.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, NamedTuple, Optional

from . import blackbox, metrics
from . import sweep as _sweep

__all__ = [
    "AutotuneTable", "Candidate", "table", "reset_table", "select",
    "decide", "decisions", "timing_reps", "kernel",
    "quarantine", "quarantine_key", "safe_backend",
    "suppress_knob_records", "bundle_info", "bundle_warm_specs",
    "choose_matmul", "choose_potrf_panel", "choose_potrf_panel_f64",
    "choose_lu_panel", "choose_lu_driver", "choose_trtri_panel",
    "choose_geqrf_panel", "choose_chase", "choose_lu_step",
    "choose_potrf_step", "choose_dist_panel", "choose_dist_pivot",
    "choose_dist_chunk", "choose_dist_lookahead", "choose_batched_potrf",
    "choose_batched_lu", "choose_batched_qr", "choose_batched_heev",
    "choose_route",
]

#: timed repetitions per surviving candidate (after the compile/warm rep)
_REPS = 2


def _on_tpu() -> bool:
    import jax

    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _enabled() -> bool:
    return os.environ.get("SLATE_TPU_AUTOTUNE", "1").strip().lower() \
        not in ("0", "false", "off", "no")


def _forced(op: str) -> Optional[str]:
    raw = os.environ.get("SLATE_TPU_AUTOTUNE_FORCE", "")
    for part in raw.split(","):
        if "=" in part:
            k, v = part.split("=", 1)
            if k.strip() == op:
                return v.strip()
    return None


_warned_forces: set = set()


def _warn_bad_force(op: str, forced: str, names) -> None:
    """A pin naming a backend this key doesn't offer (typo, or e.g.
    ``matmul=ozaki`` on an f32 key) must not fail silently — the user
    believes the pin is active.  Warn once per (op, value)."""
    if (op, forced) not in _warned_forces:
        _warned_forces.add((op, forced))
        import warnings

        warnings.warn(
            f"SLATE_TPU_AUTOTUNE_FORCE pins {op}={forced!r} but this "
            f"key's candidates are {names}; the pin is ignored here")


def _version_key() -> dict:
    """The cache validity key: any component changing (new jax, new
    libtpu, different platform) invalidates every stored decision."""
    import jax

    try:
        import jaxlib

        jl = getattr(jaxlib, "__version__", "?")
    except Exception:  # pragma: no cover - jaxlib always ships with jax
        jl = "?"
    platform, platform_version = "unknown", "unknown"
    try:
        dev = jax.devices()[0]
        platform = dev.platform
        client = getattr(dev, "client", None)
        platform_version = getattr(client, "platform_version", "unknown")
    except Exception:
        pass
    return {
        "jax": jax.__version__,
        "jaxlib": jl,
        "platform": platform,
        "platform_version": str(platform_version),
    }


def _cache_path() -> str:
    env = os.environ.get("SLATE_TPU_AUTOTUNE_CACHE")
    if env:
        return env
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "slate_tpu", "autotune.json")


#: canonical decision-key string — shared with the sweep grid keys
_key_str = _sweep.key_str


def _load_bundle() -> Optional[dict]:
    """The active warm-start bundle (``SLATE_TPU_AUTOTUNE_BUNDLE``), or
    None.  Loaded when the decision table is constructed — NEVER at
    import, and loading starts no exporters and runs no probes
    (registry-guard pinned) — and version-checked exactly like the
    timing cache: any jax/jaxlib/platform/libtpu component changing
    rejects the whole artifact (``autotune.bundle.stale``)."""
    path = os.environ.get(_sweep.BUNDLE_ENV, "").strip()
    if not path:
        return None
    try:
        blob = _sweep.read_bundle(path)
    except (OSError, ValueError):
        metrics.inc("autotune.bundle.unreadable")
        return None
    if blob.get("version") != _version_key():
        metrics.inc("autotune.bundle.stale")
        return None
    blob.setdefault("path", path)
    metrics.inc("autotune.bundle.loaded",
                float(len(blob.get("decisions") or {})))
    return blob


def bundle_info() -> Optional[dict]:
    """``{"path", "digest", "version"}`` of the ACTIVE warm-start
    bundle (None without one) — bench.py tags every JSON line with it
    so artifacts say whether numbers came from a bundle-warm or
    probe-cold process."""
    b = table().bundle
    if not isinstance(b, dict):
        return None
    return {"path": b.get("path"), "digest": b.get("digest"),
            "version": b.get("version")}


def bundle_warm_specs() -> list:
    """The AOT warm-start bucket specs the active bundle ships for
    :func:`slate_tpu.serve.warm_start` (empty without a bundle)."""
    b = table().bundle
    specs = (b or {}).get("warm_start") if isinstance(b, dict) else None
    if not isinstance(specs, list):
        return []
    return [dict(s) for s in specs if isinstance(s, dict)]


def _bundle_entry(bundle: dict, key: str, names, quar) -> Optional[str]:
    """Exact-entry stage of the bundle ladder: the offline decision for
    this key, unless a live quarantine entry MASKS it exactly like a
    cached winner (PR 9 negative evidence).  The ONE implementation
    shared by :meth:`AutotuneTable.decide` and :func:`_default`."""
    ent = (bundle.get("decisions") or {}).get(key)
    b = ent.get("backend") if isinstance(ent, dict) else None
    if isinstance(b, str) and b in names:
        if b in quar:
            metrics.inc("autotune.bundle.masked")
        else:
            return b
    return None


def _bundle_model(bundle: dict, op: str, key_parts, names, quar
                  ) -> Optional[str]:
    """Model stage of the bundle ladder: the fitted interpolating model
    for shapes the sweep never timed, quarantined backends excluded.
    Shared by :meth:`AutotuneTable.decide` and :func:`_default`."""
    try:
        mb = _sweep.model_backend(bundle, op, key_parts, names,
                                  exclude=quar)
    except Exception:       # a malformed model must never break dispatch
        mb = None
    if mb is not None and mb in names and mb not in quar:
        return mb
    return None


def _bundle_resolve(bundle: dict, op: str, key: str, key_parts, names,
                    quar) -> Optional[tuple]:
    """Both bundle stages in sequence (the chooser-default ladder,
    where no cached timing can sit between them).  Returns
    ``(backend, source)`` or None."""
    b = _bundle_entry(bundle, key, names, quar)
    if b is not None:
        return b, "bundle"
    mb = _bundle_model(bundle, op, key_parts, names, quar)
    if mb is not None:
        return mb, "bundle-model"
    return None


#: op site -> the stock-library candidate name (the one whose failure
#: mode is shared with the non-autotuned library).  The quarantine
#: layer never demotes it — there must always be a backend left to
#: degrade to — and the health gates' safe re-run resolves to it.
_SAFE_BACKENDS = {
    "lu_driver": "rec", "lu_step": "composed", "potrf_step": "composed",
    "batched_potrf": "vmapped", "batched_lu": "vmapped",
    "batched_qr": "vmapped", "chase": "host_native",
    "dist_pivot": "maxloc", "dist_chunk": "whole", "dist_lookahead": "1",
    "eig_driver": "twostage", "svd_driver": "twostage",
    "qdwh_step": "qr",
}


def safe_backend(op: str) -> str:
    return _SAFE_BACKENDS.get(op, "xla")


#: > 0 while a resilience degraded re-run is forcing the safe knobs
#: (:func:`slate_tpu.resilience.health.safe_backend`).  The temporary
#: knob state must not overwrite settled decisions via :func:`_static`
#: — a clobbered "timed" record would force re-timing probes on the
#: serving path after the knobs are restored.
_knob_records_suppressed = [0]


@contextmanager
def suppress_knob_records():
    """While active, knob-derived :func:`_static` resolutions count
    their dispatch but leave the stored decision table untouched."""
    _knob_records_suppressed[0] += 1
    try:
        yield
    finally:
        _knob_records_suppressed[0] -= 1


def _quarantine_ttl_s() -> float:
    """Runtime demotions expire after this many seconds (re-probed on
    the next decide past expiry); a version bump (:func:`_version_key`)
    drops the whole quarantine file regardless."""
    return float(os.environ.get("SLATE_TPU_QUARANTINE_TTL_S",
                                str(24 * 3600)))


class Candidate(NamedTuple):
    """One backend candidate for a decision.

    ``setup()`` builds probe operands and returns a zero-arg ``run()``
    that executes one blocked repetition; a raised exception during
    setup or the warm run prunes the candidate (compile failures).
    ``check(out)``, when given, receives the warm run's output and
    prunes the candidate when it returns False (accuracy guards).
    """

    name: str
    setup: Callable[[], Callable[[], Any]]
    check: Optional[Callable[[Any], bool]] = None


class AutotuneTable:
    """In-process decision table + on-disk persistence."""

    def __init__(self, path: Optional[str] = None):
        self.path = path or _cache_path()
        self.decisions: dict = {}       # key -> {"backend", "source", ...}
        self.timing_reps = 0            # timed reps performed THIS process
        self._persist: dict = {}        # subset of decisions worth saving
        # the offline warm-start bundle (SLATE_TPU_AUTOTUNE_BUNDLE):
        # first-priority probe-free source, version-checked on load
        self.bundle: Optional[dict] = _load_bundle()
        # key -> {backend -> {"until": epoch_s, "reason": str}}: runtime
        # demotions from the resilience health gates, persisted next to
        # the cache (see quarantine_backend)
        self.quarantine: dict = {}
        self._lock = threading.RLock()
        self._load()
        self._load_quarantine()

    # -- persistence ------------------------------------------------------

    def _load(self) -> None:
        try:
            with open(self.path) as f:
                blob = json.load(f)
        except (OSError, ValueError):
            return
        if blob.get("version") != _version_key():
            metrics.inc("autotune.cache.stale")
            return                      # stale: different jax/libtpu/platform
        stored = blob.get("decisions", {})
        if not isinstance(stored, dict):
            return
        for k, v in stored.items():
            if isinstance(v, dict) and "backend" in v:
                self.decisions[k] = dict(v, source="cache")
                self._persist[k] = v
        metrics.inc("autotune.cache.loaded", float(len(self._persist)))

    def _save(self) -> None:
        blob = {"version": _version_key(), "decisions": self._persist}
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            tmp = self.path + ".tmp.%d" % os.getpid()
            with open(tmp, "w") as f:
                json.dump(blob, f, indent=1, sort_keys=True)
            os.replace(tmp, self.path)
        except OSError:
            pass                        # read-only FS: stay in-process only

    # -- quarantine (resilience demotions) --------------------------------

    @property
    def quarantine_path(self) -> str:
        return self.path + ".quarantine"

    def _load_quarantine(self) -> None:
        try:
            with open(self.quarantine_path) as f:
                blob = json.load(f)
        except (OSError, ValueError):
            return
        if blob.get("version") != _version_key():
            # version bump: every demotion is re-probed, by design
            metrics.inc("resilience.quarantine.stale")
            return
        entries = blob.get("entries", {})
        if not isinstance(entries, dict):
            return
        now = time.time()
        for k, backends in entries.items():
            if not isinstance(backends, dict):
                continue
            live = {b: e for b, e in backends.items()
                    if isinstance(e, dict) and e.get("until", 0) > now}
            if live:
                self.quarantine[k] = live
        if self.quarantine:
            metrics.inc("resilience.quarantine.loaded",
                        float(sum(len(v) for v in
                                  self.quarantine.values())))

    def _save_quarantine(self) -> None:
        blob = {"version": _version_key(), "entries": self.quarantine}
        try:
            os.makedirs(os.path.dirname(self.quarantine_path) or ".",
                        exist_ok=True)
            tmp = self.quarantine_path + ".tmp.%d" % os.getpid()
            with open(tmp, "w") as f:
                json.dump(blob, f, indent=1, sort_keys=True)
            os.replace(tmp, self.quarantine_path)
        except OSError:
            pass                        # read-only FS: in-process only

    def quarantine_backend(self, key: str, backend: str,
                           reason: str = "",
                           ttl_s: Optional[float] = None) -> None:
        """Demote one (key, backend) pair: the decision (in-process AND
        persisted) is dropped so the next resolve re-decides among the
        remaining candidates, and the demotion is written next to the
        cache with a TTL — a poisoned winner is never pinned forever,
        and re-probing happens at TTL expiry or the next version bump."""
        with self._lock:
            ttl = _quarantine_ttl_s() if ttl_s is None else float(ttl_s)
            self.quarantine.setdefault(key, {})[backend] = {
                "until": time.time() + ttl, "reason": reason}
            hit = self.decisions.get(key)
            if hit is not None and hit.get("backend") == backend:
                del self.decisions[key]
            if key in self._persist \
                    and self._persist[key].get("backend") == backend:
                del self._persist[key]
                self._save()
            self._save_quarantine()
        metrics.inc("resilience.demotions")
        # flight-recorder trigger (outside the table lock — a dump does
        # file IO): a quarantine means a measured winner just got
        # demoted for cause, exactly the moment a postmortem wants
        blackbox.record("autotune.quarantine", key=key, backend=backend,
                        reason=str(reason)[:200])
        blackbox.trigger("quarantine",
                         "%s -> %s: %s" % (key, backend, reason))

    def _live_quarantined(self, key: str) -> set:
        """Backends currently quarantined for ``key``; expired entries
        are dropped here (TTL re-probe)."""
        q = self.quarantine.get(key)
        if not q:
            return set()
        now = time.time()
        dead = [b for b, e in q.items() if e.get("until", 0) <= now]
        if dead:
            for b in dead:
                del q[b]
            if not q:
                del self.quarantine[key]
            self._save_quarantine()
            metrics.inc("resilience.quarantine.expired", float(len(dead)))
        return set(q)

    # -- recording --------------------------------------------------------

    def _record(self, op: str, key: str, backend: str, source: str,
                times: Optional[dict] = None, persist: bool = False) -> str:
        info = {"backend": backend, "source": source, "op": op}
        if times:
            info["times"] = times
        self.decisions[key] = info
        metrics.inc("dispatch.%s.%s" % (op, backend))
        # flight-recorder seam: the decision enters the ring so a
        # postmortem bundle names the backends the failing run was
        # actually dispatched to (one attribute read when off)
        blackbox.record("autotune.decide", site=op, key=key,
                        backend=backend, source=source)
        if source == "timed":
            metrics.inc("autotune.win.%s.%s" % (op, backend))
        if persist:
            self._persist[key] = {"backend": backend, "times": times or {}}
            self._save()
        return backend

    # -- the decision engine ----------------------------------------------

    def decide(self, op: str, key_parts, candidates, reps: int = _REPS,
               force_timing: bool = False) -> str:
        """Resolve one decision.  ``candidates`` is an ordered list of
        :class:`Candidate` — the first entry is the heuristic default
        used when timing is disabled; when EVERY candidate fails the
        ``"xla"`` entry (the stock-library backend) is preferred.
        A key with a live resilience quarantine entry (health-gate
        demotion, see :meth:`quarantine_backend`) resolves probe-free
        to the heuristic head of the non-quarantined candidates until
        the TTL expires or the version key bumps.

        With a warm-start bundle active (``SLATE_TPU_AUTOTUNE_BUNDLE``)
        the ladder is: forced pin → quarantine filter → bundle entry →
        cached timing → interpolating bundle model (shapes never
        swept) → runtime probe fallback — a quarantined backend masks
        its bundle entry exactly as it masks a cached winner.

        ``force_timing=True`` is the OFFLINE SWEEP's entry
        (perf/sweep.py): skip every probe-free source (bundle, cache,
        quarantine, the off-TPU short circuit, even the
        single-candidate shortcut) and measure now — never set on a
        serving path.  Returns the chosen backend name."""

        key = _key_str(op, key_parts)
        with self._lock:
            hit = self.decisions.get(key)
            names = [c.name for c in candidates]
            if force_timing:
                return self._probe(op, key, candidates, names, reps)
            forced = _forced(op)
            if forced is not None:
                if forced in names:
                    # an explicit user pin outranks a quarantine demotion
                    metrics.inc("autotune.forced")
                    if hit is None or hit.get("backend") != forced:
                        self._record(op, key, forced, "forced")
                    else:
                        metrics.inc("dispatch.%s.%s" % (op, forced))
                    return forced
                _warn_bad_force(op, forced, names)
            quar = self._live_quarantined(key)
            if self.bundle is not None:
                # fast path: a key already resolved from the bundle
                # re-dispatches without re-running the lookup/model.  A
                # "bundle-model" record only short-circuits while the
                # bundle has no exact entry for the key — a model
                # resolution recorded while a quarantine masked the
                # entry must not outlive the mask (expiry re-admits the
                # offline decision)
                if hit is not None \
                        and hit.get("source") in ("bundle", "bundle-model") \
                        and (hit["source"] == "bundle"
                             or key not in (self.bundle.get("decisions")
                                            or {})) \
                        and hit["backend"] in names \
                        and hit["backend"] not in quar:
                    metrics.inc("autotune.bundle.hit"
                                if hit["source"] == "bundle"
                                else "autotune.bundle.model_hit")
                    metrics.inc("dispatch.%s.%s" % (op, hit["backend"]))
                    return hit["backend"]
                # the bundle's exact decision table: the first-priority
                # probe-free source (measured OFFLINE on this exact
                # version key, so it outranks this machine's cache); a
                # live quarantine masks the entry — PR 9 negative
                # evidence feeding back into the offline table
                bb = _bundle_entry(self.bundle, key, names, quar)
                if bb is not None:
                    metrics.inc("autotune.bundle.hit")
                    metrics.inc("autotune.probes_avoided")
                    return self._record(op, key, bb, "bundle")
            # resilience demotions: while a LIVE quarantine entry names
            # this key, resolve to the heuristic head of the remaining
            # candidates (never the quarantined ones; the safe backend
            # always survives) with a NON-sticky, non-persisted record
            # and NO timing probe — degraded mode wants the known-good
            # choice, not a measurement.  Once the TTL expires (or the
            # version bumps) the quarantine vanishes and the next call
            # re-probes from scratch.
            if quar:
                # with a bundle active, offline evidence about the
                # REMAINING candidates (the interpolating model with
                # the quarantined backends excluded) still beats the
                # heuristic head — same degraded ladder _default runs
                if self.bundle is not None:
                    mb = _bundle_model(self.bundle, op, key_parts,
                                       names, quar)
                    if mb is not None:
                        metrics.inc("autotune.bundle.model_hit")
                        return self._record(op, key, mb, "bundle-model")
                safe_name = safe_backend(op)
                kept = [c.name for c in candidates
                        if c.name not in quar or c.name == safe_name]
                if kept:
                    metrics.inc("autotune.quarantine.filtered")
                    return self._record(op, key, kept[0], "quarantined")
            # Only settled results pin a key: knob-derived records
            # ("forced-config", "forced", "default") must not outlive
            # the knob that produced them, so they re-resolve cheaply on
            # the next call.  "all-pruned"/"only" stay sticky for the
            # process — re-running failed probes on every trace-time
            # call would stall the caller far worse than a conservative
            # xla fallback does.
            if hit is not None and hit["backend"] in names \
                    and hit.get("source") in ("timed", "cache",
                                              "all-pruned", "only"):
                metrics.inc("autotune.cache.hit"
                            if hit.get("source") == "cache"
                            else "autotune.table.hit")
                metrics.inc("dispatch.%s.%s" % (op, hit["backend"]))
                return hit["backend"]
            metrics.inc("autotune.miss")
            if len(candidates) == 1:
                return self._record(op, key, names[0], "only")
            # shapes the sweep never timed: the bundle's fitted
            # interpolating model resolves probe-free — below cached
            # timing (an exact local measurement beats interpolation),
            # above the heuristic default and the runtime probe.  The
            # analytical >10× guard lives inside model_backend.
            if self.bundle is not None:
                mb = _bundle_model(self.bundle, op, key_parts, names,
                                   quar)
                if mb is not None:
                    metrics.inc("autotune.bundle.model_hit")
                    metrics.inc("autotune.probes_avoided")
                    return self._record(op, key, mb, "bundle-model")
            if not _enabled() or not _on_tpu():
                # no measurement possible/wanted: heuristic default.
                # (Interpret-mode Pallas timings on CPU are meaningless.)
                return self._record(op, key, names[0], "default")
            return self._probe(op, key, candidates, names, reps)

    def _probe(self, op: str, key: str, candidates, names,
               reps: int) -> str:
        """The measurement tail of :meth:`decide`: prune-by-exception /
        accuracy-guard, time the survivors, record the winner.  Caller
        holds the lock."""
        times: dict = {}
        failures: dict = {}
        from ..resilience import inject as _inject
        for cand in candidates:
            try:
                # chaos seam: an injected "error" prunes the
                # candidate like a real compile failure; "nan"
                # corrupts the warm output so the accuracy guard
                # prunes it (no-op without an active fault plan)
                ikind = _inject.fault_here("autotune.probe")
                run = cand.setup()
                out = run()                       # compile + warm
                if ikind in ("nan", "inf"):
                    out = _inject.corrupt_outputs(out, ikind)
                if cand.check is not None and not cand.check(out):
                    failures[cand.name] = "accuracy-guard"
                    metrics.inc("autotune.pruned.accuracy-guard")
                    continue
                ts = []
                for _ in range(reps):
                    t0 = time.perf_counter()
                    run()
                    ts.append(time.perf_counter() - t0)
                self.timing_reps += reps
                metrics.inc("autotune.probe_reps", float(reps))
                times[cand.name] = min(ts)
            except Exception as e:  # compile failure / OOM / ...
                failures[cand.name] = f"{type(e).__name__}: {e}"
                metrics.inc("autotune.pruned.compile")
        if not times:
            metrics.inc("autotune.all_pruned")
            # every candidate pruned (probe OOM, compile outage):
            # fall back to the stock-XLA backend when one is listed
            # — it is the only candidate whose failure mode is
            # shared with the non-autotuned library — else the
            # heuristic first entry
            safe = "xla" if "xla" in names else names[0]
            return self._record(op, key, safe, "all-pruned",
                                times=failures or None)
        winner = min(times, key=times.get)
        rounded = {k: round(v, 6) for k, v in times.items()}
        rounded.update({k: f"pruned: {v}" for k, v in failures.items()})
        return self._record(op, key, winner, "timed", times=rounded,
                            persist=True)


_table: Optional[AutotuneTable] = None
_table_lock = threading.Lock()


def table() -> AutotuneTable:
    global _table
    with _table_lock:
        if _table is None:
            _table = AutotuneTable()
        return _table


def reset_table() -> None:
    """Drop the in-process table (tests; the next :func:`table` call
    re-reads the on-disk cache)."""
    global _table
    with _table_lock:
        _table = None


def decide(op: str, key_parts, candidates, reps: int = _REPS,
           force_timing: bool = False) -> str:
    return table().decide(op, key_parts, candidates, reps, force_timing)


def decisions() -> dict:
    """``{key: backend}`` snapshot of every decision made so far."""
    return {k: v["backend"] for k, v in table().decisions.items()}


def timing_reps() -> int:
    return table().timing_reps


def quarantine(op: str, key_parts, backend: str, reason: str = "",
               ttl_s: Optional[float] = None) -> None:
    """Demote one decision's backend (see
    :meth:`AutotuneTable.quarantine_backend`)."""
    table().quarantine_backend(_key_str(op, key_parts), backend,
                               reason, ttl_s)


def quarantine_key(key: str, backend: str, reason: str = "",
                   ttl_s: Optional[float] = None) -> None:
    """Demote by raw table key (``"op|part,part,..."``) — the form the
    resilience health gates hold when walking ``table().decisions``."""
    table().quarantine_backend(key, backend, reason, ttl_s)


def kernel(name: str):
    """Registered accessor for Pallas leaf kernels used by backend
    implementations that live outside :mod:`slate_tpu.ops` (e.g. the
    CholQR² panel in ``linalg/qr.py``).  Routing those call sites here
    keeps them enumerable: the registry-guard test asserts no module
    outside ``ops/`` imports ``pallas_kernels``/``ozaki`` directly, so
    every multi-backend site provably dispatches through this table."""
    from ..ops import pallas_kernels as pk

    return getattr(pk, name)


# ---------------------------------------------------------------------------
# Probe helpers
# ---------------------------------------------------------------------------

def _randn(shape, dtype, seed: int = 0):
    import jax

    return jax.random.normal(jax.random.PRNGKey(seed), shape, dtype)


def _memo(cache: dict, name: str, mk):
    """Per-decision probe memo: every candidate's setup() and check()
    shares ONE set of probe operands instead of regenerating an O(n³)
    input per use — halves-to-thirds first-use tuning cost and peak
    probe memory.  ``_randn`` is seed-deterministic, so sharing changes
    nothing numerically."""
    if name not in cache:
        cache[name] = mk()
    return cache[name]


def _bucket_dim(d: int) -> int:
    """Next power of two ≥ d (floor 8) — the matmul decision-key
    granularity.  The blocked recursions emit many distinct trailing-
    update shapes; exact (m, k, n) keys would compile and probe both
    candidates per shape on a cold cache (minutes of first-run stall on
    TPU), while one decision per power-of-two bucket covers them with
    log-many searches — the same bucketing ``linalg.lu``'s Pallas panel
    applies to its lane dimension.  Delegates to the ONE shared helper
    (:func:`slate_tpu.perf.sweep.pow2_bucket`) so autotune cache keys,
    serve bucket keys and sweep grid keys can never drift apart
    (agreement pinned in tests/test_sweep.py)."""
    return _sweep.pow2_bucket(d)


def _timed_call(fn, *args):
    """Wrap a jitted fn + concrete args into a blocking zero-arg run()."""
    import jax

    jfn = jax.jit(fn)

    def run():
        return jax.block_until_ready(jfn(*args))

    return run


def _rel_fro(x, ref) -> float:
    import jax.numpy as jnp

    num = float(jnp.linalg.norm((x - ref).astype(jnp.float32)))
    den = float(jnp.linalg.norm(ref.astype(jnp.float32))) or 1.0
    return num / den


def _precision_name() -> str:
    from .. import config

    return getattr(config.matmul_precision, "name",
                   str(config.matmul_precision))



def _static(op: str, key_parts, backend: str, source: str) -> str:
    """Record a decision resolved without timing (heuristic default,
    config-forced, ineligible shape) so every dispatch — not just the
    timed ones — is visible in the table.  Inside a resilience
    safe-backend window (:func:`suppress_knob_records`) the table is
    left untouched: the knobs are temporarily forced and a clobbered
    settled decision would re-probe at serving time after restore."""
    if _knob_records_suppressed[0]:
        metrics.inc("dispatch.%s.%s" % (op, backend))
        return backend
    tab = table()
    key = _key_str(op, key_parts)
    if key not in tab.decisions or tab.decisions[key]["backend"] != backend:
        tab._record(op, key, backend, source)     # counts the dispatch too
    else:
        metrics.inc("dispatch.%s.%s" % (op, backend))
    return backend


def _default(op: str, key_parts, names, fallback: str) -> str:
    """Probe-free resolution for the choosers' no-measurement branches
    (off-TPU, timing disabled): the active warm-start bundle — timed
    OFFLINE on matching hardware (``perf/sweep.py``) — outranks the
    heuristic ``fallback``, with live quarantine entries masking bundle
    entries exactly as in :meth:`AutotuneTable.decide`.  Without a
    bundle this is exactly the old ``_static(..., "default")``."""
    tab = table()
    if tab.bundle is not None:
        key = _key_str(op, key_parts)
        with tab._lock:
            quar = tab._live_quarantined(key)
            hit = tab.decisions.get(key)
        # fast path (mirrors decide's): a key already resolved from the
        # bundle re-dispatches without re-running the lookup/model —
        # the model interpolation must not re-price every hot dispatch.
        # Same bundle-model caveat as decide's: an exact entry (masked
        # when the model record was made) wins again once re-admitted.
        if hit is not None \
                and hit.get("source") in ("bundle", "bundle-model") \
                and (hit["source"] == "bundle"
                     or key not in (tab.bundle.get("decisions") or {})) \
                and hit["backend"] in names \
                and hit["backend"] not in quar:
            metrics.inc("autotune.bundle.hit"
                        if hit["source"] == "bundle"
                        else "autotune.bundle.model_hit")
            metrics.inc("dispatch.%s.%s" % (op, hit["backend"]))
            return hit["backend"]
        res = _bundle_resolve(tab.bundle, op, key, key_parts,
                              list(names), quar)
        if res is not None:
            backend, src = res
            metrics.inc("autotune.bundle.hit" if src == "bundle"
                        else "autotune.bundle.model_hit")
            if key not in tab.decisions:
                metrics.inc("autotune.probes_avoided")
            return _static(op, key_parts, backend, src)
    return _static(op, key_parts, fallback, "default")


# ---------------------------------------------------------------------------
# Op-site choosers.  Each returns a backend NAME; the call site maps the
# name to its implementation.  Candidate order = heuristic preference
# (what today's defaults pick), used when timing is off.
# ---------------------------------------------------------------------------

def choose_matmul(shape_a, shape_b, dtype) -> str:
    """Backend for a 2-D real tile/trailing-update product:
    ``"xla"`` | ``"pallas"`` (VMEM K-loop kernel) | ``"ozaki"``
    (int8-slice fp64) | ``"split3"`` / ``"split6"`` (bf16-slice fp32,
    :mod:`slate_tpu.ops.split_gemm`).  Also covers every recursive
    trailing update — the blocked drivers' hot GEMMs all flow through
    :func:`slate_tpu.ops.blocks.matmul`."""

    import jax.numpy as jnp

    from .. import config

    # decide (and probe) at power-of-two-BUCKETED dims: one search
    # covers every trailing-update shape in the bucket (see
    # :func:`_bucket_dim`); eligibility still checks the ACTUAL dims
    am, ak = int(shape_a[0]), int(shape_a[1])
    an = int(shape_b[1])
    m, k, n = _bucket_dim(am), _bucket_dim(ak), _bucket_dim(an)
    dt = jnp.dtype(dtype)
    key = (m, k, n, dt.name, _precision_name())
    probes: dict = {}

    def _ab():
        return _memo(probes, "ab", lambda: (_randn((m, k), dt, 0),
                                            _randn((k, n), dt, 1)))

    if dt == jnp.float64:
        mode = config.f64_mxu_mode()
        if mode == "off":
            return _static("matmul", key, "xla", "forced-config")
        if not _on_tpu():
            return _default("matmul", key, ("ozaki", "xla"), "xla")
        if mode == "on":
            return _static("matmul", key, "ozaki", "forced-config")

        def setup_ozaki():
            from ..ops.ozaki import matmul_f64

            return _timed_call(matmul_f64, *_ab())

        def setup_xla():
            return _timed_call(
                lambda x, y: jnp.matmul(x, y,
                                        precision=config.matmul_precision),
                *_ab())

        def check_ozaki(out):
            import jax

            ref = jax.jit(jnp.matmul)(*_ab())
            # dropped-tail bound ~k·2⁻⁴⁸ relative; 1e-9 is ~30x slack
            return _rel_fro(out, ref) < 1e-9

        return decide("matmul", key, [
            Candidate("ozaki", setup_ozaki, check_ozaki),
            Candidate("xla", setup_xla),
        ])

    mode = config.use_pallas_mode()
    smode = config.split_gemm_mode()
    # the bf16 slices share fp32's exponent range — the split is only
    # defined (and only profitable) for the fp32 precision class
    split_ok = dt == jnp.float32
    if smode == "on" and split_ok:
        # the split pin wins over shape eligibility AND over a pallas
        # pin: the K-fold is a concat + one dot, so it needs no
        # tile-grid alignment — forced mode covers ragged shapes too
        return _static("matmul", key, "split3", "forced-config")
    eligible = (jnp.issubdtype(dt, jnp.floating)
                and am % 128 == 0 and an % 128 == 0 and ak % 128 == 0)
    if not eligible:
        return "xla"
    if mode == "on":
        return _static("matmul", key, "pallas", "forced-config")
    names = ["xla"]
    if mode != "off":
        names.append("pallas")
    if smode != "off" and split_ok:
        names += ["split3", "split6"]
    if len(names) == 1:
        return _static("matmul", key, "xla", "forced-config")
    if not _on_tpu():
        # an explicit env pin must work off-TPU too (the --split CI
        # tier and the interpret-mode tests pin split3/split6 this way)
        forced = _forced("matmul")
        if forced in names:
            return _static("matmul", key, forced, "forced")
        return _default("matmul", key, tuple(names), "xla")

    def setup_pallas():
        from ..ops.pallas_kernels import matmul as pallas_matmul

        def blk(dim, pref):
            return pref if dim % pref == 0 else 128

        return _timed_call(
            lambda x, y: pallas_matmul(x, y, bm=blk(m, 256), bn=blk(n, 256),
                                       bk=blk(k, 512)), *_ab())

    def setup_xla32():
        return _timed_call(
            lambda x, y: jnp.matmul(x, y, precision=config.matmul_precision),
            *_ab())

    def setup_split3():
        from ..ops.split_gemm import matmul_split3

        return _timed_call(matmul_split3, *_ab())

    def setup_split6():
        from ..ops.split_gemm import matmul_split6

        return _timed_call(matmul_split6, *_ab())

    def check_hi(out):
        import jax
        from jax import lax

        ref = jax.jit(lambda x, y: jnp.matmul(
            x, y, precision=lax.Precision.HIGHEST))(*_ab())
        # pallas accumulates at HIGHEST in VMEM and the bf16 splits
        # land at ~(2⁷+3k)·eps32 (split3) / ~3k·eps32 (split6)
        # componentwise: agreement with the 6-pass XLA dot should be
        # well under 1e-4, the library gate
        return _rel_fro(out, ref) < 1e-4

    setups = {"xla": Candidate("xla", setup_xla32),
              "pallas": Candidate("pallas", setup_pallas, check_hi),
              "split3": Candidate("split3", setup_split3, check_hi),
              "split6": Candidate("split6", setup_split6, check_hi)}
    return decide("matmul", key, [setups[nm] for nm in names])


def _spd_probe(n, dtype, seed=2):
    import jax.numpy as jnp

    g = _randn((n, n), dtype, seed)
    return jnp.matmul(g, g.T) + n * jnp.eye(n, dtype=dtype)


def _potrf_guard(spd, l, gate: float) -> bool:
    """The reference tester's criterion on matvec probes:
    ‖L(Lᵀx) − Ax‖ / (‖A‖·‖x‖·eps·n) ≤ gate."""
    import jax.numpy as jnp
    import numpy as np

    if not bool(jnp.all(jnp.isfinite(l))):
        return False
    n = spd.shape[-1]
    eps = float(np.finfo(np.dtype(spd.dtype).name).eps)
    x = _randn((n,), spd.dtype, 3)
    lt = jnp.tril(l)
    r = float(jnp.linalg.norm(lt @ (lt.T @ x) - spd @ x))
    den = float(jnp.linalg.norm(spd)) * float(jnp.linalg.norm(x)) * eps * n
    return r / max(den, 1e-300) <= gate


def choose_potrf_panel(n: int, nb: int, dtype) -> str:
    """f32 Cholesky driver backend: ``"pallas"`` (fused VMEM chol+inv
    panel + triangular-strip trailing, :func:`ops.blocks.potrf_panels`)
    vs ``"xla"`` (fused ``lax.linalg.cholesky``)."""

    import jax.numpy as jnp

    from .. import config

    dt = jnp.dtype(dtype)
    key = (n, nb, dt.name, _precision_name())
    mode = config.use_pallas_mode()
    if mode == "off":
        return _static("potrf_panel", key, "xla", "forced-config")
    if mode == "on":
        return _static("potrf_panel", key, "pallas", "forced-config")
    if not _on_tpu():
        return _default("potrf_panel", key, ("pallas", "xla"), "xla")

    probes: dict = {}

    def _spd():
        return _memo(probes, "spd", lambda: _spd_probe(n, dt))

    def setup_pallas():
        from ..ops import blocks

        return _timed_call(lambda x: blocks.potrf_panels(x, nb), _spd())

    def setup_xla():
        from jax import lax

        return _timed_call(lambda x: jnp.tril(lax.linalg.cholesky(x)),
                           _spd())

    def check(out):
        return _potrf_guard(_spd(), out, 3.0)

    return decide("potrf_panel", key, [
        Candidate("pallas", setup_pallas, check),
        Candidate("xla", setup_xla),
    ])


def choose_potrf_panel_f64(n: int, nb: int) -> str:
    """fp64 Cholesky driver backend on TPU: ``"ozaki_newton"`` (f32
    Pallas panel + fp64 Newton refinement + Ozaki trailing gemms) vs
    ``"xla"`` (software-emulated fp64 cholesky)."""

    import jax.numpy as jnp

    from .. import config

    key = (n, nb, "float64", _precision_name())
    mode = config.f64_mxu_mode()
    if mode == "off":
        return _static("potrf_panel_f64", key, "xla", "forced-config")
    if not _on_tpu():
        return _default("potrf_panel_f64", key, ("ozaki_newton", "xla"),
                        "xla")
    if mode == "on":
        return _static("potrf_panel_f64", key, "ozaki_newton", "forced-config")

    probes: dict = {}

    def _spd():
        return _memo(probes, "spd", lambda: _spd_probe(n, jnp.float64))

    def setup_fast():
        from ..ops import blocks

        return _timed_call(lambda x: blocks.potrf_panels_f64(x, nb), _spd())

    def setup_xla():
        from jax import lax

        return _timed_call(lambda x: jnp.tril(lax.linalg.cholesky(x)),
                           _spd())

    def check(out):
        # 10·eps64 gate units (the bench's emulated-fp64 allowance)
        return _potrf_guard(_spd(), out, 30.0)

    return decide("potrf_panel_f64", key, [
        Candidate("ozaki_newton", setup_fast, check),
        Candidate("xla", setup_xla),
    ])


def choose_lu_panel(m: int, w: int, dtype, eligible: bool,
                    eligible_fused: bool = False) -> str:
    """LU panel backend: ``"pallas"`` (one-call masked lane-major panel
    with TRUE partial pivoting + L11⁻¹, ``getrf_panel_linv``) vs
    ``"pallas_fused"`` (the grid-stepped fused mega-kernel,
    ``getrf_panel_fused`` at k0=0 — same contract, one compilation per
    bucket and a single-copy VMEM slab) vs ``"xla"`` (fused
    ``lax.linalg.lu``).  ``eligible``/``eligible_fused`` are the call
    site's shape/VMEM gates (``linalg.lu._use_pallas_panel`` /
    ``_use_fused_panel``); when one holds off-TPU the caller forced
    the gate open (tests/interpret mode), so the Pallas leaf is
    honoured without timing."""

    import jax.numpy as jnp

    from .. import config

    dt = jnp.dtype(dtype)
    key = (m, w, dt.name, _precision_name())
    if not (eligible or eligible_fused):
        return _static("lu_panel", key, "xla", "ineligible")
    if config.use_pallas_mode() == "on":
        return _static("lu_panel", key,
                       "pallas" if eligible else "pallas_fused",
                       "forced-config")
    if not _on_tpu():
        return _static("lu_panel", key,
                       "pallas" if eligible else "pallas_fused",
                       "gate-forced")

    probes: dict = {}

    def _a():
        return _memo(probes, "a", lambda: _randn((m, w), dt, 4))

    def setup_pallas():
        from ..linalg.lu import _panel_lu_pallas

        return _timed_call(lambda x: _panel_lu_pallas(x)[:2], _a())

    def setup_fused():
        from ..linalg.lu import _panel_lu_fused

        return _timed_call(lambda x: _panel_lu_fused(x)[:2], _a())

    def check(out):
        import numpy as np

        lu, perm = map(np.asarray, out)
        a = np.asarray(_a())
        lmat = np.tril(lu, -1)[:, :w] + np.eye(m, w, dtype=lu.dtype)
        res = np.linalg.norm(lmat @ np.triu(lu[:w]) - a[perm])
        eps = float(np.finfo(np.dtype(dt.name)).eps)
        return res / (np.linalg.norm(a) * eps * m + 1e-300) < 100.0

    def setup_xla():
        from jax import lax

        return _timed_call(lambda x: lax.linalg.lu(x)[::2], _a())

    cands = []
    if eligible:
        cands.append(Candidate("pallas", setup_pallas, check))
    if eligible_fused:
        cands.append(Candidate("pallas_fused", setup_fused, check))
    cands.append(Candidate("xla", setup_xla, check))
    return decide("lu_panel", key, cands)


def choose_lu_driver(m: int, n: int, nb: int, dtype,
                     eligible: bool) -> str:
    """Whole-factorization driver for partial-pivot getrf:
    ``"scattered"`` (transposed in-place scattered-row driver whose
    panel loop is ONE fused Pallas invocation per step,
    ``linalg.lu.getrf_scattered``) vs ``"rec"`` (the blocked recursion
    ``getrf_rec``, the stock path).  ``eligible`` is the call site's
    shape gate (``linalg.lu._use_scattered``); the tri-state
    ``SLATE_TPU_SCATTERED_LU`` knob (:func:`slate_tpu.config.
    scattered_lu_mode`) forces the decision, replacing the raw env
    read the driver used to hide."""

    import jax.numpy as jnp

    from .. import config

    dt = jnp.dtype(dtype)
    key = (m, n, nb, dt.name, _precision_name())
    if not eligible:
        return _static("lu_driver", key, "rec", "ineligible")
    mode = config.scattered_lu_mode()
    if mode == "off":
        return _static("lu_driver", key, "rec", "forced-config")
    if mode == "on":
        return _static("lu_driver", key, "scattered", "forced-config")
    if not _on_tpu():
        return _default("lu_driver", key, ("rec", "scattered"), "rec")

    probes: dict = {}

    def _a():
        return _memo(probes, "a", lambda: _randn((m, n), dt, 8))

    def setup_scattered():
        from ..linalg.lu import getrf_scattered

        return _timed_call(lambda x: getrf_scattered(x, nb), _a())

    def setup_rec():
        from ..linalg.lu import getrf_rec

        return _timed_call(lambda x: getrf_rec(x, nb), _a())

    def check(out):
        return _lu_factor_residual_ok(out, _a(), m, n, dt)

    return decide("lu_driver", key, [
        Candidate("rec", setup_rec, check),
        Candidate("scattered", setup_scattered, check),
    ])


def _lu_factor_residual_ok(out, a, m: int, n: int, dt) -> bool:
    """O(n²) matvec probe of the factor identity L·(U·x) = A[perm]·x
    (the reference tester's criterion, kept on device — n=8192 operands
    never land on the host).  Shared by the ``lu_driver`` and
    ``lu_step`` accuracy guards."""
    import jax.numpy as jnp
    import numpy as np

    lu, perm = out
    if not bool(jnp.all(jnp.isfinite(lu))):
        return False
    x = _randn((n,), dt, 9)
    k = min(m, n)
    y = jnp.triu(lu[:k]) @ x
    z = jnp.tril(lu[:, :k], -1) @ y + jnp.pad(y, (0, m - k))
    r = float(jnp.linalg.norm(z - a[perm] @ x))
    eps = float(np.finfo(np.dtype(dt.name)).eps)
    den = (float(jnp.linalg.norm(a)) * float(jnp.linalg.norm(x))
           * eps * max(m, n))
    return r / max(den, 1e-300) < 100.0


def _lu_step_depths(eligible: bool, eligible_full: bool):
    """The ``lu_step`` depth ladder admitted by the call site's gates,
    in heuristic-preference order (shared with the sweep's candidate
    builder so the offline and runtime candidate sets agree)."""
    depths = ["composed"]
    if eligible:
        depths += ["fused", "fused_trsm"]
    if eligible_full:
        depths.append("full")
    return depths


def choose_lu_step(m: int, n: int, nb: int, dtype, eligible: bool,
                   eligible_full: bool = False) -> str:
    """Fusion DEPTH of one right-looking step of the scattered LU
    driver: ``"composed"`` (fused panel kernel + XLA glue — pivot-row
    gather, u12 gemm pair, rank-nb trailing update: panel-only depth),
    ``"fused_trsm"`` (panel + pivot-gather-fused u12 scatter inside ONE
    pallas invocation, trailing gemm in XLA), ``"fused"`` (the whole
    step — panel + trsm + streamed trailing update — one pallas_call on
    the aliased carry; ~2× the composed trailing MXU flops bought back
    by zero inter-stage HBM round trips, which is exactly the trade
    this table exists to measure) or ``"full"`` (ONE pallas_call owns
    the ENTIRE factorization with in-kernel lookahead — zero launches
    and zero round trips between steps, at the cost of a larger VMEM
    residency).  ``eligible`` / ``eligible_full`` are the call site's
    shape/VMEM gates (``linalg.lu._use_fused_step`` /
    ``_use_full_fused``); off-TPU the forced knob is honoured so
    interpret-mode CI can pin the fused depths."""

    import jax.numpy as jnp

    from .. import config

    dt = jnp.dtype(dtype)
    key = (m, n, nb, dt.name, _precision_name())
    if not eligible and not eligible_full:
        return _static("lu_step", key, "composed", "ineligible")
    if config.use_pallas_mode() == "off":
        return _static("lu_step", key, "composed", "forced-config")
    depths = _lu_step_depths(eligible, eligible_full)
    if not _on_tpu():
        forced = _forced("lu_step")
        if forced in depths:
            return _static("lu_step", key, forced, "forced")
        return _default("lu_step", key, tuple(depths), "composed")

    probes: dict = {}

    def _a():
        return _memo(probes, "a", lambda: _randn((m, n), dt, 12))

    def _setup(depth):
        from ..linalg.lu import getrf_scattered

        return _timed_call(
            lambda x: getrf_scattered(x, nb, step=depth), _a())

    def check(out):
        return _lu_factor_residual_ok(out, _a(), m, n, dt)

    return decide("lu_step", key, [
        Candidate(d, (lambda d=d: _setup(d)), check) for d in depths])


def _potrf_step_depths(eligible: bool, eligible_full: bool):
    """The ``potrf_step`` depth ladder admitted by the call site's
    gates (shared with the sweep's candidate builder)."""
    depths = ["composed"]
    if eligible:
        depths.append("fused")
    if eligible_full:
        depths.append("full")
    return depths


def _potrf_step_driver(depth: str):
    """Depth rung → driver callable of the ``potrf_step`` ladder — ONE
    map shared by the runtime chooser and the offline sweep's candidate
    builder so a new rung cannot land in only one of them (the LU
    ladder needs no map: every depth routes through
    ``getrf_scattered(..., step=depth)``)."""
    from ..ops import blocks

    return {"composed": blocks.potrf_panels,
            "fused": blocks.potrf_steps,
            "full": blocks.potrf_full}[depth]


def choose_potrf_step(n: int, nb: int, dtype, eligible: bool,
                      eligible_full: bool = False) -> str:
    """Step composition of the f32 right-looking Cholesky driver:
    ``"composed"`` (the strip driver :func:`ops.blocks.potrf_panels` —
    fused chol+inv panel kernel, XLA trsm-as-gemm and strip updates),
    ``"fused"`` (:func:`ops.blocks.potrf_steps` — the WHOLE step as
    one pallas invocation with the trailing tiles streamed through a
    double-buffered VMEM residency) or ``"full"``
    (:func:`ops.blocks.potrf_full` — ONE pallas invocation owns the
    entire factorization, the next panel column lookahead-updated in
    VMEM).  ``eligible`` / ``eligible_full`` are the call site's gates
    (``ops.blocks.use_fused_potrf_step`` / ``use_full_potrf``); off-TPU
    the forced knob is honoured for interpret-mode CI."""

    import jax.numpy as jnp

    from .. import config

    dt = jnp.dtype(dtype)
    key = (n, nb, dt.name, _precision_name())
    if not eligible and not eligible_full:
        return _static("potrf_step", key, "composed", "ineligible")
    if config.use_pallas_mode() == "off":
        return _static("potrf_step", key, "composed", "forced-config")
    depths = _potrf_step_depths(eligible, eligible_full)
    if not _on_tpu():
        forced = _forced("potrf_step")
        if forced in depths:
            return _static("potrf_step", key, forced, "forced")
        return _default("potrf_step", key, tuple(depths), "composed")

    probes: dict = {}

    def _spd():
        return _memo(probes, "spd", lambda: _spd_probe(n, dt))

    def _setup(depth):
        fn = _potrf_step_driver(depth)
        return _timed_call(lambda x: fn(x, nb), _spd())

    def check(out):
        return _potrf_guard(_spd(), out, 3.0)

    return decide("potrf_step", key, [
        Candidate(d, (lambda d=d: _setup(d)), check) for d in depths])


def choose_ooc(n: int, nb: int, dtype, eligible: bool) -> str:
    """Single-chip residency of one square factorization: ``"pool"``
    (the out-of-core tile-pool drivers — host-DRAM (nb, nb)-tile grid
    with a bounded LRU window of HBM-resident tiles, dirty write-back
    and async prefetch, ``linalg.ooc`` over ``ops.tilepool``) vs
    ``"incore"`` (every existing driver; the matrix stays in HBM).
    ``eligible`` is the call site's shape gate
    (``linalg.ooc.pool_eligible``); the tri-state ``SLATE_TPU_OOC``
    knob forces the decision.

    Unlike the kernel ladders this site resolves ANALYTICALLY under
    ``auto`` (the ``dist_chunk`` precedent): a timing rep at genuinely
    out-of-core dims (n=131072 fp32 = 64 GiB) would itself be a
    multi-hour factorization, so on TPU the decision weighs the
    working set — operand + factor + workspace headroom — against the
    HBM budget (``SLATE_TPU_OOC_HBM_MB``), both ends priced by the same
    ``host``-stage roofline (``SLATE_TPU_PCIE_GBS``) the attr.py gap
    reports reconcile against.  Off-TPU the ladder resolves to in-core
    (the forced knob honoured for CI, like every other site)."""

    import jax.numpy as jnp

    from .. import config

    dt = jnp.dtype(dtype)
    key = (n, nb, dt.name, _precision_name())
    if not eligible:
        return _static("ooc", key, "incore", "ineligible")
    mode = config.ooc_mode()
    if mode == "off":
        return _static("ooc", key, "incore", "forced-config")
    if mode == "on":
        return _static("ooc", key, "pool", "forced-config")
    if not _on_tpu():
        forced = _forced("ooc")
        if forced in ("incore", "pool"):
            return _static("ooc", key, forced, "forced")
        return _default("ooc", key, ("incore", "pool"), "incore")
    from ..ops import tilepool

    # 3x: operand tiles + trailing workspace + double-buffer headroom —
    # in-core needs the whole set resident, the pool only its window
    need = 3.0 * n * n * dt.itemsize
    if need > tilepool.hbm_budget_bytes():
        return _static("ooc", key, "pool", "analytic")
    return _default("ooc", key, ("incore", "pool"), "incore")


def choose_dist_panel(op: str, nb: int, dtype, eligible: bool,
                      eligible_fused: bool = True, m: int | None = None,
                      w: int | None = None) -> str:
    """Per-step panel solve backend inside the DISTRIBUTED drivers'
    shard_map bodies: ``"xla"`` (lax cholesky/lu + triangular_solve
    chain — today's path), ``"pallas_panel"`` (the fused VMEM
    chol+inverse / trtri panel kernel + MXU gemms — ONE kernel launch
    per step per device, the single-chip fused-step win inherited by
    the lookahead pipeline) or ``"pallas_fused"`` (ISSUE 13: the panel
    kernel fused with its IMMEDIATE trailing correction — chol+inv+l21
    / trtri+u12+Newton-correction in one launch per step body, so the
    per-step glue gemms ride the same VMEM residency as the panel).
    ``"geqrf"`` resolves two candidates only (``xla`` vs
    ``pallas_panel`` = the CholQR² reconstruction panel, which already
    carries its T matrix — there is no separate correction to fuse).
    Heuristic + forceable only: timing a collective driver needs the
    mesh, which the autotuner does not own, so on TPU the fused Pallas
    panel is the default for eligible shapes and
    ``SLATE_TPU_AUTOTUNE_FORCE=dist_panel=...`` pins any rung.

    ``eligible_fused`` gates the ``pallas_fused`` rung separately: its
    kernels stage the full-height (M, nb) panel (ppotrf) / full-width
    (nb, W) block row (pgetrf) as VMEM operands, so unlike the
    (nb, nb)-operand ``pallas_panel`` rung it must fit the VMEM budget
    — :func:`slate_tpu.parallel.dist_util.dist_panel_backend` plans
    the footprint with :mod:`slate_tpu.ops.vmem` and drops the rung
    (forced pins included) when it cannot compile."""

    import jax.numpy as jnp

    from .. import config

    dt = jnp.dtype(dtype)
    # the m/w dims drive the fused rung's VMEM eligibility, so they
    # belong in the key (pow2-bucketed like lu_step's dims) — one
    # (op, nb, dtype) key flapping between backends as the matrix size
    # changes would re-record every dispatch and let a quarantine
    # raised at one size govern the other
    key = (op, nb, dt.name) \
        + (() if m is None else ("m%d" % _bucket_dim(m),)) \
        + (() if w is None else ("w%d" % _bucket_dim(w),))
    names = (("xla", "pallas_panel") if op == "geqrf"
             else ("xla", "pallas_panel", "pallas_fused"))
    if not eligible_fused and "pallas_fused" in names:
        names = names[:-1]
    if not eligible:
        return _static("dist_panel", key, "xla", "ineligible")
    forced = _forced("dist_panel")
    if forced in names:
        return _static("dist_panel", key, forced, "forced")
    mode = config.use_pallas_mode()
    if mode == "off":
        return _static("dist_panel", key, "xla", "forced-config")
    if mode == "on":
        return _static("dist_panel", key, names[-1], "forced-config")
    if _on_tpu() and dt == jnp.float32 and op != "geqrf":
        return _default("dist_panel", key, names, names[-1])
    return _default("dist_panel", key, names, "xla")


def choose_dist_pivot(nb: int, p: int, dtype, eligible: bool) -> str:
    """Pivot-search strategy for pgetrf's replicated panel:
    ``"maxloc"`` (the classic per-column |·|-argmax chain over the
    full (M, nb) panel, eliminating through the shared ``_elim_col``
    step — deliberately unblocked so the two backends are bitwise
    comparable) vs ``"tournament"``
    (CALU: the panel rows split into p owner groups, each factored
    independently for nb local pivot candidates, candidates combined
    in a log₂(p) pairwise tournament, then ONE pivot-given elimination
    of the permuted panel — the longest sequential chain shrinks to
    M/p + nb·log₂(p) rows and the whole search is one reduction shape
    per panel).  Heuristic + forceable (no mesh to time): tournament
    is the TPU default for multi-row meshes, maxloc everywhere else —
    and the arbitration point where depth-1/maxloc can win back."""

    import jax.numpy as jnp

    dt = jnp.dtype(dtype)
    key = (nb, p, dt.name)
    if not eligible:
        return _static("dist_pivot", key, "maxloc", "ineligible")
    forced = _forced("dist_pivot")
    if forced in ("maxloc", "tournament"):
        return _static("dist_pivot", key, forced, "forced")
    if _on_tpu() and p > 1:
        return _default("dist_pivot", key, ("maxloc", "tournament"),
                        "tournament")
    return _default("dist_pivot", key, ("maxloc", "tournament"), "maxloc")


def choose_dist_chunk(op: str, nb: int, dtype, p: int, q: int) -> str:
    """Pipelined slice count for the distributed drivers' fused panel
    broadcasts (``dist_util.bcast_block_col/row``): ``"whole"`` (one
    (M, nb) psum — today's path), ``"2"`` or ``"4"`` (that many
    narrower psums XLA's latency-hiding scheduler interleaves with the
    trailing MXU contraction; same total bytes, bitwise-identical
    values).  Keyed per (driver, mesh shape, nb, dtype) — the ICI
    topology axis of the ISSUE 13 co-design; ``perf/sweep.py`` prices
    the candidates with attr.py's ICI roofline (wire time ÷ slices +
    per-slice latency) so the offline bundle can pin it per mesh."""

    import jax.numpy as jnp

    dt = jnp.dtype(dtype)
    key = (op, p, q, nb, dt.name)
    names = ("whole", "2", "4")
    forced = _forced("dist_chunk")
    if forced in names:
        return _static("dist_chunk", key, forced, "forced")
    if _on_tpu() and nb >= 1024:
        # big panels: wire time dominates the per-slice latency, so a
        # 2-way split exposes half the bytes to overlap for one extra
        # collective launch (the sweep's roofline pricing refines this)
        return _default("dist_chunk", key, names, "2")
    return _default("dist_chunk", key, names, "whole")


def choose_dist_lookahead(op: str, nt: int, nb: int, dtype) -> str:
    """Depth D of the lookahead panel ring the distributed
    factorizations carry (``"1"`` — the PR 1 single double-buffered
    panel — through ``"4"``).  Depth D keeps the next D block-column
    panels in flight: broadcasts for steps k+1..k+D all overlap the
    step-k trailing contraction, at the cost of D−1 redundant (M, nb)
    rank-nb corrections per step (replicated compute, ZERO extra
    collectives — the per-step collective count is pinned independent
    of D in tests/test_multichip_scaleout.py).  Heuristic + forceable: deeper
    rings only pay when the trailing window is wide enough to hide
    more than one broadcast, so depth 2 is the TPU default for long
    factorizations and depth 1 everywhere else."""

    import jax.numpy as jnp

    dt = jnp.dtype(dtype)
    key = (op, nt, nb, dt.name)
    names = ("1", "2", "3", "4")
    forced = _forced("dist_lookahead")
    if forced in names:
        return _static("dist_lookahead", key, forced, "forced")
    if _on_tpu() and nt >= 8:
        return _default("dist_lookahead", key, names, "2")
    return _default("dist_lookahead", key, names, "1")


def choose_trtri_panel(n: int, dtype) -> str:
    """Lower non-unit triangular-inverse tile backend: ``"pallas"``
    (fused recursive-doubling VMEM ``trtri_panel``) vs ``"xla"``
    (``triangular_solve`` against the identity).  Eligibility (f32,
    power-of-two n ≥ 32, 2-D) is enforced by the call site
    (:func:`slate_tpu.ops.blocks.trtri_rec`)."""

    import jax.numpy as jnp

    from .. import config

    dt = jnp.dtype(dtype)
    key = (n, dt.name, _precision_name())
    mode = config.use_pallas_mode()
    if mode == "off":
        return _static("trtri_panel", key, "xla", "forced-config")
    if mode == "on":
        return _static("trtri_panel", key, "pallas", "forced-config")
    if not _on_tpu():
        return _default("trtri_panel", key, ("xla", "pallas"), "xla")

    probes: dict = {}

    def _probe_l():
        return _memo(probes, "l", lambda: jnp.tril(_randn((n, n), dt, 5))
                     + 2 * n * jnp.eye(n, dtype=dt))

    def setup_pallas():
        from ..ops.pallas_kernels import trtri_panel

        return _timed_call(trtri_panel, _probe_l())

    def setup_xla():
        from jax import lax

        eye = jnp.eye(n, dtype=dt)
        return _timed_call(
            lambda t: lax.linalg.triangular_solve(
                t, eye, left_side=True, lower=True), _probe_l())

    def check(out):
        import numpy as np

        l = np.asarray(_probe_l())
        x = np.tril(np.asarray(out))
        eps = float(np.finfo(np.dtype(dt.name)).eps)
        res = np.linalg.norm(x @ l - np.eye(n)) / (eps * n)
        return res < 100.0          # well-conditioned probe: tight gate

    return decide("trtri_panel", key, [
        Candidate("xla", setup_xla),
        Candidate("pallas", setup_pallas, check),
    ])


def choose_geqrf_panel(m: int, n: int, nb: int, dtype) -> str:
    """f32 QR driver backend: ``"cholqr2"`` (shifted-CholQR² panels +
    Householder reconstruction, :func:`linalg.qr.geqrf_panels`) vs
    ``"xla"`` (fused blocked geqrf)."""

    import jax.numpy as jnp

    from .. import config

    dt = jnp.dtype(dtype)
    key = (m, n, nb, dt.name, _precision_name())
    mode = config.use_pallas_mode()
    if mode == "off":
        return _static("geqrf_panel", key, "xla", "forced-config")
    if mode == "on":
        return _static("geqrf_panel", key, "cholqr2", "forced-config")
    if not _on_tpu():
        return _default("geqrf_panel", key, ("cholqr2", "xla"), "xla")

    probes: dict = {}

    def _a():
        return _memo(probes, "a", lambda: _randn((m, n), dt, 6))

    def setup_cholqr2():
        from ..linalg.qr import geqrf_panels

        return _timed_call(lambda x: geqrf_panels(x, nb)[0], _a())

    def setup_xla():
        return _timed_call(
            lambda x: jnp.swapaxes(jnp.linalg.qr(x, mode="raw")[0],
                                   -1, -2), _a())

    def check(out):
        import numpy as np

        a = np.asarray(_a())
        r = np.triu(np.asarray(out)[:n])
        x = np.asarray(_randn((n,), dt, 7))
        eps = float(np.finfo(np.dtype(dt.name)).eps)
        num = np.linalg.norm(a.T @ (a @ x) - r.T @ (r @ x))
        den = (np.linalg.norm(a) ** 2 * np.linalg.norm(x)
               * eps * np.sqrt(m)) + 1e-300
        return num / den < 10.0

    return decide("geqrf_panel", key, [
        Candidate("cholqr2", setup_cholqr2, check),
        Candidate("xla", setup_xla, check),
    ])


def choose_chase(kind: str, n: int, kd: int, dtype, eligible: bool) -> str:
    """Stage-2 bulge-chase backend for the two-stage eig/SVD middle:
    ``"host_native"`` (the compiled single-node chase in
    ``native/runtime.cc`` — today's path, band pulled to host and the
    packed reflector log shipped back to the device) vs
    ``"pallas_wavefront"`` (ONE device-resident Pallas invocation per
    chase chunk, aliased HBM band carry, zero host↔device tunnel —
    ``ops.pallas_kernels.hb2st_wavefront`` / ``tb2bd_wavefront``).
    ``kind`` is ``"hb2st"`` (band→tridiag) or ``"tb2bd"``
    (band→bidiag); both the single-chip drivers and the checkpointed
    sweep-range chunks of ``parallel.dist_twostage`` resolve through
    this one decision.  ``eligible`` is the call site's shape gate
    (vectors wanted, kd ≥ 4, n > kd+2)."""

    import jax.numpy as jnp

    dt = jnp.dtype(dtype)
    key = (kind, n, kd, dt.name)
    if not eligible:
        return _static("chase", key, "host_native", "ineligible")
    if not _on_tpu():
        # interpret-mode timings are meaningless; the heuristic default
        # keeps today's host path unless a force pins the device chase
        # (tests do, via SLATE_TPU_AUTOTUNE_FORCE=chase=pallas_wavefront)
        forced = _forced("chase")
        if forced == "pallas_wavefront":
            return _static("chase", key, forced, "forced")
        return _default("chase", key,
                        ("host_native", "pallas_wavefront"),
                        "host_native")

    from .. import native

    probes: dict = {}

    def _mk_band():
        import numpy as np

        rng = np.random.default_rng(11)
        if kind == "hb2st":
            abw = np.zeros((n, 2 * kd + 2))
            for d in range(kd + 1):
                abw[:n - d, d] = rng.standard_normal(n - d)
        else:
            abw = np.zeros((n, 3 * kd + 2))
            for d in range(kd + 1):
                abw[:n - d, d + kd] = rng.standard_normal(n - d)
        return abw

    def _band():
        return _memo(probes, "band", _mk_band)

    def setup_host():
        if not native.available():
            raise RuntimeError("native runtime unavailable")

        def run():
            ab = _band().copy()
            if kind == "hb2st":
                return native.hb2st_hh_banded_range(ab, n, kd, 0, n - 2)
            return native.tb2bd_hh_banded(ab, n, kd)

        return run

    def setup_pallas():
        import jax

        if kind == "hb2st":
            fn = kernel("hb2st_wavefront")
        else:
            fn = kernel("tb2bd_wavefront")
        # probe in the KEY's dtype: an f32 key must compile (and be
        # accuracy-checked on) the f32 kernel, so a Mosaic failure
        # prunes here instead of crashing at real dispatch
        op = jnp.asarray(_band()).astype(dt)

        def run():
            return jax.block_until_ready(fn(op, kd))

        run()                           # compile once before timing
        return run

    def check_pallas(out):
        # d/e of the chased band must agree with the host chase: the
        # tridiagonal/bidiagonal spectrum is the chase's contract
        # (reference always f64 — the native chase's only precision)
        import numpy as np

        ab = _band().copy()
        if kind == "hb2st":
            native.hb2st_hh_banded_range(ab, n, kd, 0, n - 2)
            d_ref, e_ref = ab[:, 0], ab[:n - 1, 1]
            ab_dev = np.asarray(out[0])
            d_new, e_new = ab_dev[:, 0], ab_dev[:n - 1, 1]
        else:
            native.tb2bd_hh_banded(ab, n, kd)
            d_ref, e_ref = ab[:, kd], ab[:n - 1, kd + 1]
            ab_dev = np.asarray(out[0])
            d_new, e_new = ab_dev[:, kd], ab_dev[:n - 1, kd + 1]
        scale = max(np.max(np.abs(d_ref)), 1e-300)
        eps = float(np.finfo(np.dtype(dt.name)).eps) \
            if jnp.issubdtype(dt, jnp.floating) else 2.2e-16
        # loose catastrophe gate (the chase accumulates ~sqrt(#windows)
        # rounding): it prunes a wrong kernel, not honest rounding
        tol = 1e5 * eps * scale * n
        return (np.max(np.abs(np.abs(d_new) - np.abs(d_ref))) < tol
                and np.max(np.abs(np.abs(e_new) - np.abs(e_ref))) < tol)

    cands = []
    if native.available():
        cands.append(Candidate("host_native", setup_host))
    cands.append(Candidate("pallas_wavefront", setup_pallas,
                           check_pallas if native.available() else None))
    return decide("chase", key, cands)


def _batched_common(op: str, b: int, n: int, dtype, eligible: bool,
                    grid_name: str = "grid"):
    """Shared front half of the batched-site choosers: the pow2-BUCKETED
    key over BOTH batch size and n (Design-in-Tiles: one probe serves a
    bucket — a timing probe per exact (B, n) is too slow when the
    serving layer produces many buckets), plus the knob/off-TPU
    short-circuits.  Returns ``(key, dt, short_circuit_backend|None)``.
    The vmapped-composed candidate is the heuristic default: off-TPU
    grid-batched interpret timings are meaningless, and the forced knob
    is honoured so interpret CI can pin the grid path."""

    import jax.numpy as jnp

    from .. import config

    dt = jnp.dtype(dtype)
    key = (_bucket_dim(b), _bucket_dim(n), dt.name, _precision_name())
    if not eligible:
        return key, dt, _static(op, key, "vmapped", "ineligible")
    if config.use_pallas_mode() == "off":
        return key, dt, _static(op, key, "vmapped", "forced-config")
    if config.use_pallas_mode() == "on":
        return key, dt, _static(op, key, grid_name, "forced-config")
    if not _on_tpu():
        forced = _forced(op)
        if forced in (grid_name, "vmapped"):
            return key, dt, _static(op, key, forced, "forced")
        return key, dt, _default(op, key, (grid_name, "vmapped"),
                                 "vmapped")
    return key, dt, None


def choose_batched_potrf(b: int, n: int, dtype, eligible: bool) -> str:
    """Backend for the leading-batch-dim Cholesky driver
    (:func:`slate_tpu.linalg.batched.potrf_batched`): ``"grid"`` (ONE
    pallas_call owns B problems — grid over batch blocks, whole
    problems VMEM-resident, :func:`ops.pallas_kernels.potrf_batched`)
    vs ``"vmapped"`` (vmap-composed ``lax.linalg.cholesky`` — XLA's
    batching of the fused single-problem kernel).  ``eligible`` is the
    call site's shape/VMEM gate."""

    key, dt, short = _batched_common("batched_potrf", b, n, dtype, eligible)
    if short is not None:
        return short
    bb, nn = key[0], key[1]
    probes: dict = {}

    def _spd_batch():
        def mk():
            import jax.numpy as jnp

            g = _randn((bb, nn, nn), dt, 20)
            eye = nn * jnp.eye(nn, dtype=dt)
            return jnp.einsum("bij,bkj->bik", g, g) + eye[None]
        return _memo(probes, "spd", mk)

    def setup_grid():
        from ..linalg.batched import _potrf_grid

        return _timed_call(_potrf_grid, _spd_batch())

    def setup_vmapped():
        from ..linalg.batched import _potrf_vmapped

        return _timed_call(_potrf_vmapped, _spd_batch())

    def check(out):
        from ..linalg.batched import batched_factor_resid_potrf

        return batched_factor_resid_potrf(_spd_batch(), out) < 100.0

    return decide("batched_potrf", key, [
        Candidate("vmapped", setup_vmapped),
        Candidate("grid", setup_grid, check),
    ])


def choose_batched_lu(b: int, n: int, dtype, eligible: bool) -> str:
    """Backend for the leading-batch-dim partial-pivot LU driver
    (:func:`slate_tpu.linalg.batched.getrf_batched`): ``"grid"`` (one
    pallas_call, scattered-row masked-argmax pivoting per resident
    problem) vs ``"vmapped"`` (vmap-composed ``lax.linalg.lu``)."""

    key, dt, short = _batched_common("batched_lu", b, n, dtype, eligible)
    if short is not None:
        return short
    bb, nn = key[0], key[1]
    probes: dict = {}

    def _a_batch():
        def mk():
            import jax.numpy as jnp

            return (_randn((bb, nn, nn), dt, 21)
                    + nn * jnp.eye(nn, dtype=dt)[None])
        return _memo(probes, "a", mk)

    def setup_grid():
        from ..linalg.batched import _getrf_grid

        return _timed_call(_getrf_grid, _a_batch())

    def setup_vmapped():
        from ..linalg.batched import _getrf_vmapped

        return _timed_call(_getrf_vmapped, _a_batch())

    def check(out):
        from ..linalg.batched import batched_factor_resid_lu

        return batched_factor_resid_lu(_a_batch(), out) < 100.0

    return decide("batched_lu", key, [
        Candidate("vmapped", setup_vmapped),
        Candidate("grid", setup_grid, check),
    ])


def choose_batched_qr(b: int, m: int, n: int, dtype) -> str:
    """Backend for the leading-batch-dim QR/least-squares drivers:
    today a single candidate (``"vmapped"`` — XLA's batched Householder
    geqrf), registered through the table so the site is enumerable and
    a grid-batched candidate can arbitrate here later without touching
    the call sites."""

    import jax.numpy as jnp

    dt = jnp.dtype(dtype)
    key = (_bucket_dim(b), _bucket_dim(m), _bucket_dim(n), dt.name,
           _precision_name())

    def setup_vmapped():
        from ..linalg.batched import _geqrf_vmapped

        return _timed_call(lambda x: _geqrf_vmapped(x)[0],
                           _randn((key[0], key[1], key[2]), dt, 22))

    return decide("batched_qr", key, [Candidate("vmapped", setup_vmapped)])


def choose_batched_heev(b: int, n: int, dtype) -> str:
    """Backend for the leading-batch-dim Hermitian eigensolver
    (ISSUE 20 — batched heev joins the served surface): today a single
    candidate (``"vmapped"`` — XLA's natively batched ``eigh``),
    registered through the table like ``batched_qr`` so the site is
    enumerable, its cache keys warm the serving ``heev`` buckets
    (``serve.queue._SITE_TO_OPS``), and a grid-batched spectral
    candidate can arbitrate here later without touching the call
    sites."""

    import jax.numpy as jnp

    dt = jnp.dtype(dtype)
    key = (_bucket_dim(b), _bucket_dim(n), dt.name, _precision_name())

    def setup_vmapped():
        def run(x):
            w, _ = jnp.linalg.eigh(x)
            return w
        a = _randn((key[0], key[1], key[1]), dt, 23)
        spd = jnp.matmul(a, jnp.conj(jnp.swapaxes(a, -1, -2)))
        return _timed_call(run, spd)

    return decide("batched_heev", key,
                  [Candidate("vmapped", setup_vmapped)])


def _route_crossover_s() -> float:
    """The replica→sharded crossover in model wall seconds
    (``SLATE_TPU_FLEET_SHARD_MS``, default 25 ms): a problem whose
    single-chip predicted wall exceeds this is worth the ICI-sharded
    lane's collective overhead."""
    try:
        return float(os.environ.get("SLATE_TPU_FLEET_SHARD_MS",
                                    "") or 25.0) * 1e-3
    except ValueError:
        return 25e-3


def choose_route(op: str, n: int, ndev: int, dtype) -> str:
    """Fleet placement for ONE served problem (ISSUE 20):
    ``"replica"`` (data-parallel — the per-device BatchQueue whose
    predicted completion is shortest) vs ``"sharded"`` (the dedicated
    ICI lane through the PR 13 p* drivers — pposv/pgesv/pgels).

    Like ``choose_ooc``/``dist_chunk`` this site resolves
    ANALYTICALLY under ``auto``: a timing rep at genuinely
    sharded-worthy dims is itself a multi-second distributed
    factorization, so the heuristic compares the single-chip
    :func:`slate_tpu.perf.attr.predict_seconds` wall against the
    crossover knob (``SLATE_TPU_FLEET_SHARD_MS``).  The bundle
    resolution ladder (:func:`_default`) outranks the heuristic — an
    offline sweep that TIMED the crossover on matching hardware ships
    the decision in the PR 11 bundle, so a fresh fleet routes its
    first request with zero probes.  ``n`` is the problem's dominant
    dim (rows for gels)."""

    import jax.numpy as jnp

    dt = jnp.dtype(dtype)
    key = (op, _bucket_dim(n), dt.name, max(1, int(ndev)),
           _precision_name())
    if ndev <= 1 or op not in ("posv", "gesv", "gels"):
        # potrf/getrf/geqrf/heev serve factor-only outputs the dist
        # lane has no undistribute story for yet; single-device fleets
        # have no lane to shard across
        return _static("route", key, "replica", "ineligible")
    forced = _forced("route")
    if forced in ("replica", "sharded"):
        return _static("route", key, forced, "forced")
    from . import attr

    routine = {"posv": "posv", "gesv": "gesv", "gels": "gels"}[op]
    dims = {"m": n, "n": n} if op == "gels" else {"n": n, "k": 1}
    short = {"float32": "fp32", "float64": "fp64",
             "complex64": "c64", "complex128": "c128"}.get(dt.name,
                                                           "fp32")
    plat = "tpu" if _on_tpu() else "cpu"
    t1 = attr.predict_seconds(routine, dims, dtype=short, platform=plat)
    fallback = ("sharded" if t1 is not None
                and t1 >= _route_crossover_s() else "replica")
    return _default("route", key, ("replica", "sharded"), fallback)


def _spectral_residual_ok(a, w, z, n: int, dt) -> bool:
    """Probe gate shared by the eig/svd driver sites: eigen residual
    ‖A·Z − Z·Λ‖ and orthogonality ‖ZᴴZ − I‖, both scaled by ε·n (the
    library's usual gates, 100× headroom)."""
    import jax.numpy as jnp
    import numpy as np

    if z is None or not bool(jnp.all(jnp.isfinite(z))):
        return False
    eps = float(np.finfo(np.dtype(dt.name)).eps)
    anorm = float(jnp.linalg.norm(a)) or 1.0
    r = float(jnp.linalg.norm(a @ z - z * w[None, :].astype(z.dtype)))
    o = float(jnp.linalg.norm(jnp.conj(z.T) @ z
                              - jnp.eye(z.shape[1], dtype=z.dtype)))
    return (r / (anorm * eps * n) < 100.0) and (o / (eps * n) < 100.0)


def choose_eig_driver(n: int, dtype, eligible: bool) -> str:
    """Whole-driver site for heev: ``"twostage"`` (he2hb → bulge chase
    → tridiagonal solve, the stock chain) vs ``"qdwh"`` (spectral
    divide-and-conquer over the QDWH polar factor,
    :mod:`slate_tpu.linalg.polar` — all geqrf/potrf/gemm flops, so its
    roofline is the gemm roofline).  ``eligible`` is the call site's
    gate (``MethodEig.Auto`` only — an explicit band-stage method
    request pins the two-stage chain); the tri-state ``SLATE_TPU_QDWH``
    knob (:func:`slate_tpu.config.qdwh_mode`) forces the decision."""

    import jax.numpy as jnp

    from .. import config

    dt = jnp.dtype(dtype)
    key = (_bucket_dim(n), dt.name, _precision_name())
    names = ("twostage", "qdwh")
    if not eligible or n < 4:
        return _static("eig_driver", key, "twostage", "ineligible")
    mode = config.qdwh_mode()
    if mode == "off":
        return _static("eig_driver", key, "twostage", "forced-config")
    if mode == "on":
        return _static("eig_driver", key, "qdwh", "forced-config")
    if not _on_tpu():
        forced = _forced("eig_driver")
        if forced is not None:
            if forced in names:
                return _static("eig_driver", key, forced, "forced")
            _warn_bad_force("eig_driver", forced, names)
        return _default("eig_driver", key, names, "twostage")

    nprobe = key[0]
    probes: dict = {}

    def _a():
        def mk():
            g = _randn((nprobe, nprobe), dt, 31)
            return 0.5 * (g + jnp.conj(g.T))
        return _memo(probes, "a", mk)

    def setup_twostage():
        from ..linalg.eig import _heev_twostage

        def run():
            import jax

            w, z = _heev_twostage(_a(), True, None)
            jax.block_until_ready(z)
            return w, z

        return run

    def setup_qdwh():
        from ..linalg.polar import heev_qdwh

        def run():
            import jax

            w, z = heev_qdwh(_a(), True, None)
            jax.block_until_ready(z)
            return w, z

        return run

    def check(out):
        return _spectral_residual_ok(_a(), out[0], out[1], nprobe, dt)

    return decide("eig_driver", key, [
        Candidate("twostage", setup_twostage, check),
        Candidate("qdwh", setup_qdwh, check),
    ])


def choose_svd_driver(m: int, n: int, dtype, eligible: bool) -> str:
    """Whole-driver site for svd: ``"twostage"`` (ge2tb → chase →
    bidiagonal solve) vs ``"qdwh"`` (polar then QDWH-eig of the SPSD
    factor).  Same ladder shape as :func:`choose_eig_driver`; callers
    guarantee m ≥ n."""

    import jax.numpy as jnp

    from .. import config

    dt = jnp.dtype(dtype)
    key = (_bucket_dim(m), _bucket_dim(n), dt.name, _precision_name())
    names = ("twostage", "qdwh")
    if not eligible or n < 4:
        return _static("svd_driver", key, "twostage", "ineligible")
    mode = config.qdwh_mode()
    if mode == "off":
        return _static("svd_driver", key, "twostage", "forced-config")
    if mode == "on":
        return _static("svd_driver", key, "qdwh", "forced-config")
    if not _on_tpu():
        forced = _forced("svd_driver")
        if forced is not None:
            if forced in names:
                return _static("svd_driver", key, forced, "forced")
            _warn_bad_force("svd_driver", forced, names)
        return _default("svd_driver", key, names, "twostage")

    mp, np_ = key[0], key[1]
    probes: dict = {}

    def _a():
        return _memo(probes, "a", lambda: _randn((mp, np_), dt, 32))

    def setup_twostage():
        from ..linalg.svd import _svd_twostage

        def run():
            import jax

            s, u, vh = _svd_twostage(_a(), True, True, None)
            jax.block_until_ready(u)
            return s, u, vh

        return run

    def setup_qdwh():
        from ..linalg.polar import svd_qdwh

        def run():
            import jax

            s, u, vh = svd_qdwh(_a(), True, True, None)
            jax.block_until_ready(u)
            return s, u, vh

        return run

    def check(out):
        import jax.numpy as jnp_

        s, u, vh = out
        if u is None or vh is None:
            return False
        if not (bool(jnp_.all(jnp_.isfinite(u)))
                and bool(jnp_.all(jnp_.isfinite(vh)))):
            return False
        import numpy as np

        a = _a()
        eps = float(np.finfo(np.dtype(dt.name)).eps)
        anorm = float(jnp_.linalg.norm(a)) or 1.0
        r = float(jnp_.linalg.norm(
            a - u @ (s[:, None].astype(u.dtype) * vh)))
        o = float(jnp_.linalg.norm(
            jnp_.conj(u.T) @ u - jnp_.eye(np_, dtype=u.dtype)))
        return (r / (anorm * eps * max(mp, np_)) < 100.0) \
            and (o / (eps * np_) < 100.0)

    return decide("svd_driver", key, [
        Candidate("twostage", setup_twostage, check),
        Candidate("qdwh", setup_qdwh, check),
    ])


def choose_qdwh_step(n: int, c: float, dtype) -> str:
    """Per-iteration Halley variant inside the QDWH loop: ``"qr"``
    (stacked-QR step, backward stable at any conditioning) vs
    ``"chol"`` (``chol(I + c·XᴴX)`` + two trsm — roughly half the
    flops, safe only once the weight ``c`` is moderate since
    κ(I + c·XᴴX) ≈ c near convergence).  Probe-free by design (a
    mid-iteration timing race would measure the wrong operand state):
    the heuristic threshold is :data:`slate_tpu.config.qdwh_switch_c`,
    with the c-decade folded into the key so an offline bundle can pin
    the switch point per (n-bucket, c-regime, dtype) and a forced
    ``qdwh_step=qr|chol`` pin overrides everywhere."""

    import math

    import jax.numpy as jnp

    from .. import config

    dt = jnp.dtype(dtype)
    cd = 0 if c <= 1.0 else min(17, int(math.log10(c)))
    key = (_bucket_dim(n), "c1e%d" % cd, dt.name)
    names = ("qr", "chol")
    forced = _forced("qdwh_step")
    if forced is not None:
        if forced in names:
            return _static("qdwh_step", key, forced, "forced")
        _warn_bad_force("qdwh_step", forced, names)
    heur = "chol" if c <= config.qdwh_switch_c else "qr"
    return _default("qdwh_step", key, names, heur)


#: op name → chooser, the :func:`select` registry.  ``method.select_backend``
#: is the driver-facing façade over this table.
_CHOOSERS = {
    "matmul": lambda **kw: choose_matmul(kw["shape_a"], kw["shape_b"],
                                         kw["dtype"]),
    "potrf_panel": lambda **kw: choose_potrf_panel(kw["n"], kw["nb"],
                                                   kw["dtype"]),
    "potrf_panel_f64": lambda **kw: choose_potrf_panel_f64(kw["n"], kw["nb"]),
    "lu_panel": lambda **kw: choose_lu_panel(kw["m"], kw["w"], kw["dtype"],
                                             kw["eligible"],
                                             kw.get("eligible_fused",
                                                    False)),
    "lu_driver": lambda **kw: choose_lu_driver(kw["m"], kw["n"], kw["nb"],
                                               kw["dtype"], kw["eligible"]),
    "lu_step": lambda **kw: choose_lu_step(kw["m"], kw["n"], kw["nb"],
                                           kw["dtype"], kw["eligible"],
                                           kw.get("eligible_full",
                                                  False)),
    "potrf_step": lambda **kw: choose_potrf_step(kw["n"], kw["nb"],
                                                 kw["dtype"],
                                                 kw["eligible"],
                                                 kw.get("eligible_full",
                                                        False)),
    "ooc": lambda **kw: choose_ooc(kw["n"], kw["nb"], kw["dtype"],
                                   kw["eligible"]),
    "dist_panel": lambda **kw: choose_dist_panel(kw["driver"], kw["nb"],
                                                 kw["dtype"],
                                                 kw["eligible"],
                                                 kw.get("eligible_fused",
                                                        True),
                                                 kw.get("m"), kw.get("w")),
    "dist_pivot": lambda **kw: choose_dist_pivot(kw["nb"], kw["p"],
                                                 kw["dtype"],
                                                 kw["eligible"]),
    "dist_chunk": lambda **kw: choose_dist_chunk(kw["driver"], kw["nb"],
                                                 kw["dtype"], kw["p"],
                                                 kw["q"]),
    "dist_lookahead": lambda **kw: choose_dist_lookahead(
        kw["driver"], kw["nt"], kw["nb"], kw["dtype"]),
    "trtri_panel": lambda **kw: choose_trtri_panel(kw["n"], kw["dtype"]),
    "geqrf_panel": lambda **kw: choose_geqrf_panel(kw["m"], kw["n"],
                                                   kw["nb"], kw["dtype"]),
    "chase": lambda **kw: choose_chase(kw["kind"], kw["n"], kw["kd"],
                                       kw["dtype"], kw["eligible"]),
    "batched_potrf": lambda **kw: choose_batched_potrf(
        kw["b"], kw["n"], kw["dtype"], kw["eligible"]),
    "batched_lu": lambda **kw: choose_batched_lu(
        kw["b"], kw["n"], kw["dtype"], kw["eligible"]),
    "batched_qr": lambda **kw: choose_batched_qr(
        kw["b"], kw["m"], kw["n"], kw["dtype"]),
    "batched_heev": lambda **kw: choose_batched_heev(
        kw["b"], kw["n"], kw["dtype"]),
    "route": lambda **kw: choose_route(kw["serve_op"], kw["n"],
                                       kw["ndev"],
                                       kw["dtype"]),
    "eig_driver": lambda **kw: choose_eig_driver(kw["n"], kw["dtype"],
                                                 kw["eligible"]),
    "svd_driver": lambda **kw: choose_svd_driver(kw["m"], kw["n"],
                                                 kw["dtype"],
                                                 kw["eligible"]),
    "qdwh_step": lambda **kw: choose_qdwh_step(kw["n"], kw["c"],
                                               kw["dtype"]),
}


def select(op: str, **key) -> str:
    """Resolve the backend for a named op site (see ``_CHOOSERS``)."""
    try:
        chooser = _CHOOSERS[op]
    except KeyError:
        raise KeyError(f"unknown autotune op {op!r}; "
                       f"known: {sorted(_CHOOSERS)}") from None
    return chooser(**key)
