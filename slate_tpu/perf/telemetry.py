"""Live serving telemetry: per-request tracing, SLO latency histograms,
streaming exporters, and an in-process live sentinel.

PR 8/9 made slate_tpu a serving system; every observability surface so
far is post-hoc (metrics snapshots in bench artifacts, the sentinel
running offline over ``BENCH_r*.json``).  This module is the LIVE
half — while the process serves, it can answer:

* **Where is a request's time going right now?**  Per-request tracing:
  :meth:`slate_tpu.serve.queue.BatchQueue.submit` mints a trace id
  (attached to the returned future as ``future.trace_id``) and the
  dispatcher records contiguous ``queue_wait`` / ``dispatch`` /
  ``post_check`` spans (plus a ``compile`` span when an on-demand
  executable build happened inside the dispatch) — the spans of one
  request sum to its future-observed latency.
  :func:`slate_tpu.trace.finish_perfetto` exports them as Perfetto
  flow events on the existing clock, one lane per dispatcher thread.
* **Are we meeting latency SLOs?**  Every resolved request lands in a
  log2-bucketed ``serve.latency_ms.<op>.<dtype>.<dims>`` histogram in
  the metrics registry; :func:`slate_tpu.perf.metrics.hist_quantiles`
  reads p50/p95/p99 back with stdlib math, and a
  ``ServeConfig.slo_ms`` target (or ``SLATE_TPU_SLO_MS``) counts
  ``serve.slo.violations``.
* **Can an external system watch?**  Streaming exporters: a Prometheus
  text-exposition endpoint on a stdlib ``http.server`` daemon thread
  (``SLATE_TPU_METRICS_PORT``) and a rotating JSONL telemetry log
  (``SLATE_TPU_TELEMETRY_LOG``), flushed on an interval and at
  :func:`close`.  Render a log offline with
  ``tools/telemetry_report.py`` (stdlib-only, like ``bench_diff.py``).
* **Did performance just degrade?**  :class:`LiveSentinel` — a
  sliding-window monitor over the streaming samples that reuses the
  bench sentinel's thresholds (:data:`slate_tpu.perf.regress.
  DEFAULT_THRESHOLD_PCT`) and the roofline attribution engine
  (:func:`slate_tpu.perf.attr.attribute_live`), classifies sustained
  latency/throughput drops (``degradation``) vs infra-shaped blips
  (``infra``: error bursts), and emits structured events that can —
  opt-in (``ServeConfig.sentinel_trip`` / ``SLATE_TPU_SENTINEL_TRIP``)
  — trip the PR 9 circuit breaker and autotune-quarantine hooks.

**Off-by-default, the PR 4 no-op contract**: every recording entry
point checks one attribute (``_state.enabled``) and returns; nothing
here ever touches a traced program, so compiled executables are
bit-identical whatever the knobs (pinned in
``tests/test_telemetry.py``).  Importing this module starts NO threads
and binds NO sockets — exporters start only from :func:`maybe_start`
(called by the serving front door's constructor) or an explicit
:func:`start_exporter` / :func:`start_log` (guarded in
``tests/test_backend_registry.py``).

Env knobs (all unset by default):

* ``SLATE_TPU_TELEMETRY=1`` — enable per-request tracing, SLO
  histograms and the sentinel feed (implies ``SLATE_TPU_METRICS``).
* ``SLATE_TPU_METRICS_PORT`` — start the Prometheus endpoint on this
  port at front-door construction (``0`` = ephemeral;
  ``SLATE_TPU_METRICS_HOST`` overrides the bind host).
* ``SLATE_TPU_TELEMETRY_LOG`` — JSONL log path;
  ``SLATE_TPU_TELEMETRY_FLUSH_S`` (default 5) the flush interval,
  ``SLATE_TPU_TELEMETRY_LOG_MB`` (default 64) the rotation size (one
  rotation is kept at ``<path>.1``).
* ``SLATE_TPU_SLO_MS`` — default per-request latency SLO when
  ``ServeConfig.slo_ms`` is unset.
* ``SLATE_TPU_SENTINEL_BASELINE`` / ``_WINDOW`` / ``_THRESHOLD_PCT`` /
  ``_COOLDOWN_S`` — default sentinel window geometry;
  ``SLATE_TPU_SENTINEL_TRIP=1`` — let degradation events open the
  serve breaker and quarantine the batched driver's autotune winners.
"""

from __future__ import annotations

import itertools
import json
import os
import re
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from . import metrics

__all__ = [
    "ENV_TELEMETRY", "ENV_PORT", "ENV_HOST", "ENV_LOG", "ENV_FLUSH_S",
    "ENV_LOG_MB", "ENV_SLO_MS", "ENV_SENTINEL_TRIP", "LiveSentinel",
    "add_hook", "remove_hook", "close", "configure_sentinel",
    "default_slo_ms", "drain_spans", "enabled", "exporter_port",
    "log_record", "maybe_start", "new_trace_id",
    "observe_dispatch_error", "observe_fleet", "observe_request",
    "off", "on",
    "percentiles", "prometheus_text", "quantiles_from_buckets",
    "record_span", "sentinel", "short_dtype", "spans", "start_exporter",
    "start_log", "stop_exporter", "trip_wanted",
]

ENV_TELEMETRY = "SLATE_TPU_TELEMETRY"
ENV_PORT = "SLATE_TPU_METRICS_PORT"
ENV_HOST = "SLATE_TPU_METRICS_HOST"
ENV_LOG = "SLATE_TPU_TELEMETRY_LOG"
ENV_FLUSH_S = "SLATE_TPU_TELEMETRY_FLUSH_S"
ENV_LOG_MB = "SLATE_TPU_TELEMETRY_LOG_MB"
ENV_SLO_MS = "SLATE_TPU_SLO_MS"
ENV_SENTINEL_TRIP = "SLATE_TPU_SENTINEL_TRIP"

#: cap on buffered request spans (same backstop as the metrics counter
#: samples): past it requests keep serving, spans stop accumulating.
_MAX_SPANS = 65536

#: cap on queued-but-unflushed JSONL records; past it the OLDEST are
#: dropped (``telemetry.log.dropped`` counts) — a slow disk must never
#: grow the serving process without bound.
_MAX_LOG_QUEUE = 65536

_DTYPE_SHORT = {"float32": "fp32", "float64": "fp64", "bfloat16": "bf16",
                "complex64": "c64", "complex128": "c128"}

_SAN_RE = re.compile(r"[^a-zA-Z0-9_]")

#: the shared truthy-env parse (public on metrics so this module needs
#: no private copy)
_env_on = metrics.env_flag


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "").strip() or default)
    except ValueError:
        return default


def short_dtype(dt) -> str:
    """``"float32"`` → ``"fp32"`` — the bench-label dtype token."""
    return _DTYPE_SHORT.get(str(dt), str(dt))


class _State:
    """Process-wide telemetry state.  Private — use the module facade
    (the registry-guard test pins that serve/ and this module reach
    metrics only through its public functions; the same discipline
    applies here)."""

    def __init__(self):
        self.enabled = _env_on(ENV_TELEMETRY)
        self.lock = threading.RLock()
        # (trace_id, name, t0, t1, lane, args|None) — absolute
        # perf_counter stamps, like the metrics counter samples
        self.request_spans: List[tuple] = []
        self.ids = itertools.count(1)
        self.hooks: List[Callable] = []


_state = _State()


# ---------------------------------------------------------------------------
# Lifecycle
# ---------------------------------------------------------------------------

def enabled() -> bool:
    return _state.enabled


def on() -> None:
    """Enable per-request tracing, SLO histograms and the sentinel feed
    (also enables the metrics registry — the histograms live there)."""
    metrics.on()
    _state.enabled = True


def off() -> None:
    _state.enabled = False


def default_slo_ms() -> Optional[float]:
    """The ``SLATE_TPU_SLO_MS`` fallback SLO (None when unset)."""
    raw = os.environ.get(ENV_SLO_MS, "").strip()
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        return None
    return v if v > 0 else None


def trip_wanted() -> bool:
    """The ``SLATE_TPU_SENTINEL_TRIP=1`` opt-in: degradation events may
    open serve breakers / quarantine autotune winners."""
    return _env_on(ENV_SENTINEL_TRIP)


# ---------------------------------------------------------------------------
# Per-request tracing
# ---------------------------------------------------------------------------

def new_trace_id() -> int:
    """Mint one process-unique request trace id."""
    return next(_state.ids)


def record_span(trace_id, name: str, t0: float, t1: float,
                args: Optional[dict] = None) -> None:
    """Record one request span (absolute ``perf_counter`` stamps) on
    the CALLING thread's lane — :func:`slate_tpu.trace.finish_perfetto`
    exports the buffer as complete events plus flow events joining each
    trace id's spans across lanes.  One attribute read when off."""
    st = _state
    if not st.enabled or trace_id is None:
        return
    from .. import trace as _trace

    lane = _trace.current_lane()
    with st.lock:
        if len(st.request_spans) < _MAX_SPANS:
            st.request_spans.append((int(trace_id), str(name), float(t0),
                                     float(t1), lane, args or None))


def spans() -> List[tuple]:
    """A copy of the buffered request spans (newest last)."""
    with _state.lock:
        return list(_state.request_spans)


def drain_spans() -> List[tuple]:
    """Pop and return every buffered request span (the Perfetto export
    consumes the buffer so a second export starts clean)."""
    with _state.lock:
        out = list(_state.request_spans)
        _state.request_spans.clear()
        return out


# ---------------------------------------------------------------------------
# Histogram quantile readback (re-exported convenience; the math lives
# in metrics so the registry's log2 buckets and their readback evolve
# together)
# ---------------------------------------------------------------------------

quantiles_from_buckets = metrics.quantiles_from_buckets


def percentiles(name: str, qs=(0.5, 0.95, 0.99)) -> Dict[float, float]:
    """p50/p95/p99 readback of one registry histogram by name."""
    return metrics.hist_quantiles(name, qs)


# ---------------------------------------------------------------------------
# The request observation fan-out: histogram + SLO + JSONL + sentinel
# ---------------------------------------------------------------------------

def observe_request(op: str, bucket: str, latency_s: float,
                    slo_ms: Optional[float] = None, error: bool = False,
                    batch: int = 1, key: Optional[tuple] = None,
                    dtype: str = "fp32", n: Optional[int] = None) -> None:
    """One served request's end-to-end outcome: records the
    ``serve.latency_ms.<op>.<bucket>`` histogram (successes only),
    counts SLO violations against ``slo_ms`` (falling back to
    ``SLATE_TPU_SLO_MS``), appends a ``request`` JSONL record, and
    feeds the live sentinel.  One attribute read when telemetry is
    off."""
    if not _state.enabled:
        return
    ms = float(latency_s) * 1e3
    if not error:
        metrics.observe("serve.latency_ms.%s.%s" % (op, bucket), ms)
    slo = slo_ms if slo_ms is not None else default_slo_ms()
    # an errored request (deadline expiry, failed resolution) never
    # delivered a timely answer — with an SLO configured it counts as
    # a violation whatever its wall time, or the violation counter
    # reads green exactly under total overload
    viol = slo is not None and (error or ms > float(slo))
    if viol:
        metrics.inc("serve.slo.violations")
        metrics.inc("serve.slo.violations.%s" % op)
    if error:
        metrics.inc("telemetry.request.errors")
    log_record("request", op=op, bucket=bucket,
               latency_ms=round(ms, 3), error=bool(error),
               slo_violation=bool(viol), batch=int(batch))
    sentinel().observe(op, bucket, latency_s, error=error, batch=batch,
                       key=key, dtype=dtype, n=n)


def observe_fleet(event: str, replica: Optional[int] = None,
                  lane: Optional[str] = None, op: Optional[str] = None,
                  latency_s: Optional[float] = None,
                  error: bool = False, **fields) -> None:
    """One fleet-router observation (ISSUE 20) into the counters + the
    JSONL log.  ``event`` is the record vocabulary the ``--fleet``
    report rolls up:

    * ``"request"`` — one routed request's final outcome (fields:
      ``replica`` OR ``lane="sharded"``, ``op``, ``latency_s``,
      ``error``) → per-replica req/s + p99 + the replica/sharded split.
    * ``"breaker"`` — a replica availability transition (fields:
      ``replica``, ``state`` in closed/open/half_open) → the incident
      timeline.
    * anything else — counted and logged verbatim (``preempt``,
      ``drain``, ``rejoin``...).

    One attribute read when telemetry is off — the router calls this
    unconditionally."""
    if not _state.enabled:
        return
    metrics.inc("fleet.%s" % event)
    if error:
        metrics.inc("fleet.%s.errors" % event)
    rec: dict = {"error": bool(error)} if event == "request" else {}
    if replica is not None:
        rec["replica"] = int(replica)
    if lane is not None:
        rec["lane"] = str(lane)
    if op is not None:
        rec["op"] = str(op)
    if latency_s is not None:
        ms = float(latency_s) * 1e3
        rec["latency_ms"] = round(ms, 3)
        if event == "request" and not error:
            metrics.observe("fleet.latency_ms.%s" % (lane or "replica"),
                            ms)
    rec.update(fields)
    log_record("fleet_%s" % event, **rec)


def observe_dispatch_error(op: str, bucket: str,
                           key: Optional[tuple] = None,
                           dtype: str = "fp32",
                           n: Optional[int] = None) -> None:
    """One FAILED batch dispatch into the sentinel's error feed only —
    no per-request JSONL record and no histogram sample.  Used on the
    transient-failure → loop-of-singles path, where every request will
    still get exactly one final :func:`observe_request` from the
    singles resolution: recording a request-level error here too would
    double-count it in the report/hist while the sentinel would miss
    the infra-shaped signal without this."""
    if not _state.enabled:
        return
    metrics.inc("telemetry.dispatch.errors")
    sentinel().observe(op, bucket, 0.0, error=True, batch=1, key=key,
                       dtype=dtype, n=n)


def observe_abft(driver: str, rung: str, detail: str = "") -> None:
    """One ABFT recovery-ladder escalation (ISSUE 14): counts
    ``telemetry.abft.<rung>``, appends an ``abft`` JSONL record, and —
    for the rungs that mean repeated hardware trouble (``recomputed``
    / ``restarted`` / ``unrecovered``) — feeds the live sentinel's
    error window under the synthetic ``abft`` bucket, so a burst of
    silent-corruption recoveries on one driver classifies as an infra
    degradation exactly like a dispatch-error burst would.  One
    attribute read when telemetry is off."""
    if not _state.enabled:
        return
    metrics.inc("telemetry.abft.%s" % rung)
    log_record("abft", driver=str(driver), rung=str(rung),
               detail=str(detail)[:200])
    if rung in ("recomputed", "restarted", "unrecovered"):
        sentinel().observe(str(driver), "abft", 0.0, error=True, batch=1)


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

def _san(name: str) -> str:
    return _SAN_RE.sub("_", name)


def _fmt(v) -> str:
    f = float(v)
    return "%d" % int(f) if f == int(f) and abs(f) < 1e15 else repr(f)


def _bucket_upper(bucket: str) -> Optional[float]:
    bounds = metrics.bucket_bounds(bucket)
    return None if bounds is None else bounds[1]


def prometheus_text(snap: Optional[dict] = None) -> str:
    """Render a metrics snapshot in the Prometheus text exposition
    format (version 0.0.4): counters and gauges one series each, timers
    as ``_count``/``_seconds_total``, histograms as cumulative
    ``_bucket{le=...}`` series with ``_sum``/``_count`` plus
    convenience ``_quantile{quantile=...}`` gauges (p50/p95/p99 via
    :func:`metrics.hist_quantiles` math)."""
    snap = snap if snap is not None else metrics.snapshot()
    lines: List[str] = []
    for k, v in sorted((snap.get("counters") or {}).items()):
        mn = "slate_tpu_" + _san(k)
        lines.append("# TYPE %s counter" % mn)
        lines.append("%s %s" % (mn, _fmt(v)))
    for k, v in sorted((snap.get("gauges") or {}).items()):
        mn = "slate_tpu_" + _san(k)
        lines.append("# TYPE %s gauge" % mn)
        lines.append("%s %s" % (mn, _fmt(v)))
    for k, t in sorted((snap.get("timers") or {}).items()):
        mn = "slate_tpu_" + _san(k)
        lines.append("# TYPE %s_count counter" % mn)
        lines.append("%s_count %s" % (mn, _fmt(t.get("count", 0))))
        lines.append("# TYPE %s_seconds_total counter" % mn)
        lines.append("%s_seconds_total %s"
                     % (mn, _fmt(t.get("total_s", 0.0))))
    for k, h in sorted((snap.get("hists") or {}).items()):
        mn = "slate_tpu_" + _san(k)
        buckets = []
        for b, c in (h.get("buckets") or {}).items():
            hi = _bucket_upper(b)
            if hi is not None:
                buckets.append((hi, int(c)))
        buckets.sort()
        lines.append("# TYPE %s histogram" % mn)
        cum = 0
        for hi, c in buckets:
            cum += c
            lines.append('%s_bucket{le="%s"} %d' % (mn, _fmt(hi), cum))
        lines.append('%s_bucket{le="+Inf"} %d' % (mn, h.get("count", 0)))
        lines.append("%s_sum %s" % (mn, _fmt(h.get("total", 0.0))))
        lines.append("%s_count %d" % (mn, h.get("count", 0)))
        qs = quantiles_from_buckets(h, (0.5, 0.95, 0.99))
        if qs:
            lines.append("# TYPE %s_quantile gauge" % mn)
            for q in sorted(qs):
                lines.append('%s_quantile{quantile="%s"} %s'
                             % (mn, q, _fmt(qs[q])))
    return "\n".join(lines) + "\n"


_exporter_lock = threading.Lock()
_exporter: Dict[str, object] = {"server": None, "thread": None,
                                "port": None}


def exporter_port() -> Optional[int]:
    """The bound Prometheus port (None when the exporter is down) —
    pass port 0 to :func:`start_exporter` and read the real port
    here."""
    return _exporter["port"]                                # type: ignore


def start_exporter(port: Optional[int] = None,
                   host: Optional[str] = None) -> int:
    """Start the Prometheus scrape endpoint (``GET /metrics``) on a
    daemon thread; idempotent (a second call returns the bound port).
    ``port`` defaults to ``SLATE_TPU_METRICS_PORT``; 0 binds an
    ephemeral port.  Enables the metrics registry — a scrape of an off
    registry would read empty."""
    with _exporter_lock:
        if _exporter["server"] is not None:
            return _exporter["port"]                        # type: ignore
        if port is None:
            raw = os.environ.get(ENV_PORT, "").strip()
            if not raw:
                raise ValueError(
                    "start_exporter: no port given and %s unset" % ENV_PORT)
            port = int(raw)
        if host is None:
            # loopback by default: setting only the PORT knob must not
            # expose an unauthenticated metrics endpoint on every
            # interface of a shared host — widening the bind scope is
            # an explicit SLATE_TPU_METRICS_HOST decision
            host = os.environ.get(ENV_HOST, "").strip() or "127.0.0.1"
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):                   # noqa: N802 (stdlib API)
                if self.path.split("?", 1)[0].rstrip("/") not in (
                        "", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                try:
                    body = prometheus_text().encode("utf-8")
                except Exception as e:      # a bad render must not 500-loop
                    body = ("# render error: %s\n" % e).encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):          # noqa: N802 — quiet
                pass

        srv = ThreadingHTTPServer((host, int(port)), _Handler)
        srv.daemon_threads = True
        th = threading.Thread(target=srv.serve_forever,
                              name="slate-telemetry-exporter", daemon=True)
        th.start()
        metrics.on()
        _exporter.update(server=srv, thread=th,
                         port=int(srv.server_address[1]))
        metrics.inc("telemetry.exporter.started")
        return _exporter["port"]                            # type: ignore


def stop_exporter() -> None:
    with _exporter_lock:
        srv = _exporter["server"]
        if srv is None:
            return
        srv.shutdown()                                      # type: ignore
        srv.server_close()                                  # type: ignore
        _exporter.update(server=None, thread=None, port=None)


# ---------------------------------------------------------------------------
# Rotating JSONL telemetry log
# ---------------------------------------------------------------------------

_log_lock = threading.RLock()
_log: Dict[str, object] = {"path": None, "queue": None, "thread": None,
                           "stop": None, "flush_s": 5.0,
                           "max_bytes": 64 * 1024 * 1024}
_atexit_registered = [False]


def start_log(path: Optional[str] = None,
              flush_s: Optional[float] = None,
              max_mb: Optional[float] = None) -> str:
    """Start the rotating JSONL telemetry log on a daemon flusher
    thread; idempotent.  ``path`` defaults to
    ``SLATE_TPU_TELEMETRY_LOG``; records queue via :func:`log_record`
    and flush every ``flush_s`` seconds (each flush also appends one
    trimmed ``snapshot`` record) and at :func:`close`.  Past
    ``max_mb`` the file rotates once to ``<path>.1``."""
    with _log_lock:
        if _log["path"] is not None:
            return _log["path"]                             # type: ignore
        if path is None:
            path = os.environ.get(ENV_LOG, "").strip()
            if not path:
                raise ValueError(
                    "start_log: no path given and %s unset" % ENV_LOG)
        if flush_s is None:
            flush_s = _env_float(ENV_FLUSH_S, 5.0)
        if max_mb is None:
            max_mb = _env_float(ENV_LOG_MB, 64.0)
        stop = threading.Event()
        _log.update(path=str(path), queue=deque(), stop=stop,
                    flush_s=max(float(flush_s), 0.01),
                    max_bytes=max(int(float(max_mb) * 1024 * 1024), 1024))
        th = threading.Thread(target=_log_loop,
                              name="slate-telemetry-log", daemon=True)
        _log["thread"] = th
        th.start()
        if not _atexit_registered[0]:
            import atexit

            atexit.register(close)
            _atexit_registered[0] = True
        metrics.inc("telemetry.log.started")
        return _log["path"]                                 # type: ignore


def log_record(kind: str, **fields) -> None:
    """Queue one JSONL record (no-op until :func:`start_log`); the
    flusher writes it on the next interval.  The queue is bounded —
    past :data:`_MAX_LOG_QUEUE` the oldest records are dropped and
    ``telemetry.log.dropped`` counts them."""
    q = _log["queue"]
    if q is None:
        return
    rec = {"t": round(time.time(), 6), "kind": str(kind)}
    rec.update(fields)
    with _log_lock:
        if len(q) >= _MAX_LOG_QUEUE:                        # type: ignore
            q.popleft()                                     # type: ignore
            metrics.inc("telemetry.log.dropped")
        q.append(rec)                                       # type: ignore


#: counter/gauge prefixes worth streaming into the JSONL snapshots (the
#: full registry would dominate the log; the serving story lives here)
_SNAP_PREFIXES = ("serve.", "telemetry.", "resilience.", "jit.",
                  "xprof.", "fleet.")


def _snapshot_record() -> dict:
    snap = metrics.snapshot()
    return {
        "counters": {k: v for k, v in (snap.get("counters") or {}).items()
                     if k.startswith(_SNAP_PREFIXES)},
        "gauges": {k: v for k, v in (snap.get("gauges") or {}).items()
                   if k.startswith(_SNAP_PREFIXES)},
    }


def _flush_log(with_snapshot: bool = False) -> None:
    with _log_lock:
        q, path = _log["queue"], _log["path"]
        if q is None or path is None:
            return
        if with_snapshot and metrics.enabled():
            rec = {"t": round(time.time(), 6), "kind": "snapshot"}
            rec.update(_snapshot_record())
            q.append(rec)                                   # type: ignore
        recs = []
        while q:                                            # type: ignore
            recs.append(q.popleft())                        # type: ignore
        max_bytes = _log["max_bytes"]
    if not recs:
        return
    data = "".join(json.dumps(r, default=str) + "\n" for r in recs)
    try:
        if os.path.exists(path) \
                and os.path.getsize(path) >= max_bytes:     # type: ignore
            os.replace(path, "%s.1" % path)
        with open(path, "a") as f:                          # type: ignore
            f.write(data)
    except OSError:
        metrics.inc("telemetry.log.write_errors")


def _log_loop() -> None:
    stop = _log["stop"]
    flush_s = _log["flush_s"]
    while not stop.wait(flush_s):                           # type: ignore
        if _log["stop"] is not stop:        # close()d and restarted
            return
        _flush_log(with_snapshot=True)


def close() -> None:
    """Stop the JSONL flusher after one final flush (the "at close"
    half of the flush contract) and reset the log state so a test or a
    new serving phase can :func:`start_log` again.  The Prometheus
    exporter is left running (scrapes are pull — stop it explicitly
    with :func:`stop_exporter`).  Idempotent."""
    with _log_lock:
        th, stop = _log["thread"], _log["stop"]
        _log["thread"] = None
    if stop is not None:
        stop.set()                                          # type: ignore
    if th is not None and th.is_alive():                    # type: ignore
        th.join(timeout=10.0)                               # type: ignore
    _flush_log(with_snapshot=True)
    with _log_lock:
        _log.update(path=None, queue=None, stop=None)


def maybe_start() -> None:
    """Start whatever the environment asks for — called by the serving
    front door's constructor, NEVER at import: the Prometheus endpoint
    when ``SLATE_TPU_METRICS_PORT`` is set, the JSONL log when
    ``SLATE_TPU_TELEMETRY_LOG`` is set, telemetry recording when
    ``SLATE_TPU_TELEMETRY=1``.  With no knob set this is a pure
    no-op."""
    if _env_on(ENV_TELEMETRY):
        on()
    if os.environ.get(ENV_PORT, "").strip():
        try:
            start_exporter()
        except Exception:
            metrics.inc("telemetry.exporter.start_errors")
    if os.environ.get(ENV_LOG, "").strip():
        try:
            start_log()
        except Exception:
            metrics.inc("telemetry.log.start_errors")


# ---------------------------------------------------------------------------
# Event hooks (the serve layer's opt-in breaker/quarantine trip path)
# ---------------------------------------------------------------------------

def _resolve_hook(h):
    import weakref

    return h() if isinstance(h, weakref.WeakMethod) else h


def add_hook(fn: Callable[[dict], None]) -> None:
    """Register a callback for every sentinel event (the serving front
    door registers one per queue; see ``BatchQueue._on_sentinel_event``).
    Bound methods are held WEAKLY: ``close()`` is documented as polite,
    not required, so a dropped-without-close BatchQueue must not stay
    pinned forever through this module-global list (nor keep receiving
    trip fan-out after it is gone)."""
    import weakref

    with _state.lock:
        if any(_resolve_hook(h) is fn for h in _state.hooks):
            return
        if hasattr(fn, "__self__"):
            _state.hooks.append(weakref.WeakMethod(fn))
        else:
            _state.hooks.append(fn)


def remove_hook(fn: Callable[[dict], None]) -> None:
    with _state.lock:
        _state.hooks = [h for h in _state.hooks
                        if _resolve_hook(h) not in (None, fn)]


# ---------------------------------------------------------------------------
# The live sentinel
# ---------------------------------------------------------------------------

class LiveSentinel:
    """In-process sliding-window serving monitor.

    Per (op, bucket) it keeps the last ``baseline + window`` dispatch
    samples ``(latency_s, error, batch)``; once full, every new sample
    compares the RECENT window against the BASELINE prefix:

    * an error rate ≥ ``infra_error_rate`` in the recent window is an
      **infra**-shaped blip (the r05 failure class: the fabric, not the
      kernels) — classification ``infra``, kind ``errors``;
    * a recent-median latency rise (or batch-throughput drop) past
      ``threshold_pct`` — the bench sentinel's threshold
      (:data:`slate_tpu.perf.regress.DEFAULT_THRESHOLD_PCT`) by default
      — is a sustained **degradation**, kind ``latency`` /
      ``throughput``, with a roofline attribution block
      (:func:`slate_tpu.perf.attr.attribute_live`) attached when the
      bucket's shape is known.

    A single slow sample moves the median by at most one rank — blips
    don't fire; ``cooldown_s`` bounds events to one per key per
    window so a sustained problem produces exactly one event, not a
    stream.  Events append to :attr:`events`, count
    ``telemetry.sentinel.<classification>``, stream to the JSONL log,
    and fan out to the registered hooks (the serve layer's opt-in
    breaker-trip / quarantine path)."""

    def __init__(self, baseline: Optional[int] = None,
                 window: Optional[int] = None,
                 threshold_pct: Optional[float] = None,
                 infra_error_rate: float = 0.5,
                 cooldown_s: Optional[float] = None,
                 platform: str = "tpu",
                 clock=time.monotonic):
        if threshold_pct is None:
            thr = os.environ.get("SLATE_TPU_SENTINEL_THRESHOLD_PCT",
                                 "").strip()
            if thr:
                threshold_pct = float(thr)
            else:
                from . import regress

                threshold_pct = regress.DEFAULT_THRESHOLD_PCT
        self.baseline = int(baseline if baseline is not None
                            else _env_float("SLATE_TPU_SENTINEL_BASELINE",
                                            32))
        self.window = int(window if window is not None
                          else _env_float("SLATE_TPU_SENTINEL_WINDOW", 8))
        self.threshold_pct = float(threshold_pct)
        self.infra_error_rate = float(infra_error_rate)
        self.cooldown_s = float(
            cooldown_s if cooldown_s is not None
            else _env_float("SLATE_TPU_SENTINEL_COOLDOWN_S", 30.0))
        self.platform = platform
        self._clock = clock
        self._lock = threading.Lock()
        self._samples: Dict[tuple, deque] = {}
        self._last: Dict[tuple, float] = {}
        self.events: List[dict] = []

    def observe(self, op: str, bucket: str, latency_s: float,
                error: bool = False, batch: int = 1,
                key: Optional[tuple] = None, dtype: str = "fp32",
                n: Optional[int] = None) -> Optional[dict]:
        """Feed one dispatch sample; returns the emitted event (or
        None).  Evaluation runs under the sentinel lock; emission
        (counters, log, hooks) outside it."""
        k = (str(op), str(bucket))
        ev = None
        with self._lock:
            dq = self._samples.get(k)
            if dq is None:
                dq = self._samples[k] = deque(
                    maxlen=self.baseline + self.window)
            dq.append((float(latency_s), bool(error), max(1, int(batch))))
            if len(dq) == self.baseline + self.window \
                    and (self._clock() - self._last.get(k, -1e18)
                         >= self.cooldown_s):
                ev = self._evaluate(op, bucket, list(dq), key=key,
                                    dtype=dtype, n=n)
                if ev is not None:
                    self._last[k] = self._clock()
                    self.events.append(ev)
        if ev is not None:
            self._emit(ev)
        return ev

    # -- classification ----------------------------------------------------

    def _evaluate(self, op, bucket, samples, key=None, dtype="fp32",
                  n=None) -> Optional[dict]:
        import statistics

        recent = samples[-self.window:]
        base = samples[:-self.window]
        errs = sum(1 for _, e, _ in recent if e)
        err_rate = errs / float(len(recent))
        common = {"t": round(time.time(), 3), "op": str(op),
                  "bucket": str(bucket), "window": self.window,
                  "key": list(key) if key else None}
        if err_rate >= self.infra_error_rate:
            ev = dict(common, classification="infra", kind="errors",
                      error_rate=round(err_rate, 3),
                      detail="infra-shaped: %d/%d recent dispatch "
                             "samples errored" % (errs, len(recent)))
            return ev
        base_ok = [(l, b) for l, e, b in base if not e and l > 0]
        rec_ok = [(l, b) for l, e, b in recent if not e and l > 0]
        if len(base_ok) < max(2, self.baseline // 2) \
                or len(rec_ok) < max(2, self.window // 2):
            return None
        med = statistics.median
        b_lat = med([l for l, _ in base_ok])
        r_lat = med([l for l, _ in rec_ok])
        rise_pct = (r_lat / b_lat - 1.0) * 100.0 if b_lat > 0 else 0.0
        b_tp = med([b / l for l, b in base_ok])
        r_tp = med([b / l for l, b in rec_ok])
        drop_pct = (1.0 - r_tp / b_tp) * 100.0 if b_tp > 0 else 0.0
        if rise_pct > self.threshold_pct:
            kind = "latency"
        elif drop_pct > self.threshold_pct:
            kind = "throughput"
        else:
            return None
        ev = dict(common, classification="degradation", kind=kind,
                  baseline_ms=round(b_lat * 1e3, 3),
                  recent_ms=round(r_lat * 1e3, 3),
                  rise_pct=round(rise_pct, 1),
                  throughput_drop_pct=round(drop_pct, 1),
                  threshold_pct=self.threshold_pct)
        if n:
            try:
                from . import attr

                bmed = int(med([b for _, b in rec_ok]))
                rep = attr.attribute_live(str(op), n=int(n),
                                          dtype=dtype or "fp32",
                                          batch=bmed, latency_s=r_lat,
                                          platform=self.platform)
                if rep:
                    ev["attribution"] = {
                        "label": rep.get("label"),
                        "gflops": rep.get("gflops"),
                        "achieved_frac": rep.get("achieved_frac"),
                        "bottlenecks": rep.get("bottlenecks"),
                    }
            except Exception:       # attribution must never mask the event
                pass
        return ev

    def _emit(self, ev: dict) -> None:
        metrics.inc("telemetry.sentinel.events")
        metrics.inc("telemetry.sentinel." + ev["classification"])
        # flight-recorder seam: the sentinel verdict enters the ring so
        # a bundle and the JSONL log correlate on the same event
        # (tools/telemetry_report.py --blackbox joins them by time)
        from . import blackbox

        blackbox.record("sentinel." + str(ev["classification"]),
                        op=ev.get("op"), bucket=ev.get("bucket"),
                        what=ev.get("kind"), detail=ev.get("detail"))
        # nested under "event": the event's own "kind" (latency/
        # throughput/errors) must not collide with the record kind
        log_record("sentinel", event=dict(ev))
        with _state.lock:
            hooks = [_resolve_hook(h) for h in _state.hooks]
            # prune hooks whose bound receiver was garbage-collected
            _state.hooks = [h for h, r in zip(_state.hooks, hooks)
                            if r is not None]
        for hook in hooks:
            if hook is None:
                continue
            try:
                hook(ev)
            except Exception:
                metrics.inc("telemetry.sentinel.hook_errors")


_sentinel: List[Optional[LiveSentinel]] = [None]
_sentinel_lock = threading.Lock()


def sentinel() -> LiveSentinel:
    """The process-default sentinel (lazily built from the env
    defaults)."""
    with _sentinel_lock:
        if _sentinel[0] is None:
            _sentinel[0] = LiveSentinel()
        return _sentinel[0]


def configure_sentinel(**kwargs) -> LiveSentinel:
    """Replace the process-default sentinel (window geometry, threshold,
    cooldown — the :class:`LiveSentinel` constructor's kwargs).  Used
    by tests and by operators who want per-deployment windows."""
    with _sentinel_lock:
        _sentinel[0] = LiveSentinel(**kwargs)
        return _sentinel[0]
