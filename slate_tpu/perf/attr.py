"""Roofline attribution: an analytical per-stage cost model joined with
measured metrics — the layer that says *where* the time went.

The observability stack so far can **measure** the factorization-vs-gemm
gap (``step.<op>.<stage>`` timers, the HLO collective/flop census, the
bench sentinel) but not **decompose** it: nobody could answer "is getrf
at 13.6% of gemm because of panel latency, HBM round-trips, exposed
collectives, or relayout?" without hand-reading a Perfetto trace.  This
module closes that: for one driver invocation it

1. derives an analytical flops/bytes model per tile-level stage
   (``panel`` / ``trsm`` / ``update`` / ``pivot`` / ``chase`` /
   ``collective``) from the *same inputs the autotune decision table
   keys on* — shapes, nb, dtype, and the chosen backend/fusion depth
   (Design-in-Tiles: the model is cheap to build from shapes alone);
2. places every stage on the MXU/HBM roofline (per-platform peaks,
   overridable via ``SLATE_TPU_PEAK_*`` env for new TPU generations)
   and computes its achieved fraction;
3. joins the measured ``step.*`` / ``stage.*`` timers and the
   collective byte counters from a metrics snapshot when one is
   available, and apportions the measured wall time across stages
   (timer-weighted when timers exist, model-flop-weighted otherwise);
4. emits a **gap report**: per-stage roofline placement plus a ranked
   bottleneck list whose gap shares sum to the observed deficit
   (1 − model_s/measured_s — the frac_of_gemm shortfall in seconds).

Consumers: ``bench.py`` embeds one report per routine JSON line (the
``attribution`` block next to ``metrics``); ``perf/regress.py`` diffs
the blocks of two artifacts so the sentinel names the stage/backend
whose share moved; ``tools/gap_report.py`` renders a block as a
human-readable roofline table; :func:`record_rooflines` feeds
``roofline.<label>.<stage>`` gauge samples to the metrics registry so
``trace.finish_perfetto`` exports them as counter tracks on the
existing clock.

STDLIB-ONLY, like ``regress.py``: the offline tools load this module
directly by file path on jax-free machines, so nothing here may import
jax (or anything outside the standard library).  The one package-aware
entry point, :func:`record_rooflines`, degrades to a no-op when the
module was loaded standalone.

Flop normalization contract: the per-stage discrete sums are scaled so
they total EXACTLY the driver's model flop count (the count bench.py
divides by — 2n³/3 for getrf, n³/3 for potrf, 2mn²−2n³/3 for geqrf,
…).  That makes every report self-reconciling: stage-flop total ÷
measured seconds reproduces the routine's reported GFLOP/s to float
rounding, which CI pins at 1%.

Join-key namespacing: measured stage timers are consumed ONLY under
their namespaced ``step.<op>.<stage>`` / ``stage.<op>.<name>`` keys
(:func:`stage_timers`); a bare ``step.<stage>`` or cross-op key can
never collide into another routine's attribution (the r7 fix —
``metrics.step_timer`` sanitizes dots out of op/stage for the same
reason).
"""

from __future__ import annotations

import os
import re

__all__ = [
    "DEFAULT_NB", "attribute", "attribute_live",
    "expected_hbm_roundtrips", "explain_pair", "format_report",
    "fusion_from_autotune", "model_flops", "parse_label", "peaks",
    "predict_request_seconds", "predict_seconds", "record_rooflines",
    "stage_model", "stage_timers",
]

#: panel width assumed when the submetric label carries no ``nb`` token
#: (the drivers' TPU default).
DEFAULT_NB = 512

#: trailing-strip width of the composed potrf driver
#: (``blocks._potrf_strips``) — the bytes/round-trip model must count
#: the same strips the driver materializes.
_POTRF_STRIP_W = 2048

_ITEMSIZE = {"fp32": 4, "bf16": 2, "fp64": 8, "c64": 8, "c128": 16}

#: per-platform roofline constants.  The TPU fp32 peak is the measured
#: LIBRARY gemm rate (~53.5 TF/s on v5e-class chips, BENCH_r03), i.e.
#: the practical ceiling every factorization competes against — not the
#: marketing bf16 number (that one anchors the bf16 row).  Override any
#: of these for a new TPU generation with the ``SLATE_TPU_PEAK_*`` env
#: knobs (see :func:`peaks`).
_DEF_PEAKS = {
    "tpu": {
        "tflops": {"fp32": 55.0, "bf16": 110.0, "fp64": 6.5,
                   "c64": 27.0, "c128": 3.2},
        "hbm_gbs": 819.0,
        "ici_gbs": 45.0,
        "pcie_gbs": 32.0,
    },
    "cpu": {
        "tflops": {"fp32": 0.10, "bf16": 0.10, "fp64": 0.05,
                   "c64": 0.05, "c128": 0.025},
        "hbm_gbs": 20.0,
        "ici_gbs": 10.0,
        "pcie_gbs": 8.0,
    },
}

_LABEL_RE = re.compile(
    r"^(?P<routine>[a-z0-9]+?)(?P<qdwh>_qdwh)?(?:_batched)?(?P<ooc>_ooc)?_"
    r"(?P<dtype>fp32|fp64|bf16|c64|c128)_"
    r"(?P<dims>.+)$")
_DIM_RE = re.compile(r"^([a-z]+)([0-9]+)$")

#: autotune op site whose decision is the routine's fusion depth
#: (``composed`` | ``fused_trsm`` | ``fused`` | ``full``).
_FUSION_OPS = {"getrf": "lu_step", "potrf": "potrf_step"}

#: autotune op site whose decision is the spectral routine's driver
#: chain (``twostage`` | ``qdwh``) — the eig/svd analog of
#: :data:`_FUSION_OPS`.
_DRIVER_OPS = {"heev": "eig_driver", "svd": "svd_driver"}


def _env_float(name: str):
    v = os.environ.get(name, "").strip()
    if not v:
        return None
    try:
        return float(v)
    except ValueError:
        return None


def peaks(platform: str = "tpu", dtype: str = "fp32") -> dict:
    """Roofline constants ``{"tflops", "hbm_gbs", "ici_gbs"}`` for one
    (platform, dtype).  Env overrides, checked in this order:

    * ``SLATE_TPU_PEAK_TFLOPS_<DTYPE>`` (e.g. ``_FP32``) then
      ``SLATE_TPU_PEAK_TFLOPS`` — compute peak in TF/s;
    * ``SLATE_TPU_PEAK_HBM_GBS`` — HBM bandwidth in GB/s;
    * ``SLATE_TPU_PEAK_ICI_GBS`` — per-link ICI bandwidth in GB/s;
    * ``SLATE_TPU_PCIE_GBS`` (alias ``SLATE_TPU_PEAK_PCIE_GBS``) — the
      host↔HBM link the out-of-core tile pool streams over (ISSUE 17:
      the ``host`` stage's roofline lane).
    """
    base = _DEF_PEAKS.get(platform) or _DEF_PEAKS["tpu"]
    dtype = dtype or "fp32"
    tf = base["tflops"].get(dtype, base["tflops"]["fp32"])
    out = {"tflops": tf, "hbm_gbs": base["hbm_gbs"],
           "ici_gbs": base["ici_gbs"], "pcie_gbs": base["pcie_gbs"]}
    env_tf = _env_float("SLATE_TPU_PEAK_TFLOPS_" + dtype.upper())
    if env_tf is None:
        env_tf = _env_float("SLATE_TPU_PEAK_TFLOPS")
    if env_tf is not None:
        out["tflops"] = env_tf
    env_bw = _env_float("SLATE_TPU_PEAK_HBM_GBS")
    if env_bw is not None:
        out["hbm_gbs"] = env_bw
    env_ici = _env_float("SLATE_TPU_PEAK_ICI_GBS")
    if env_ici is not None:
        out["ici_gbs"] = env_ici
    env_pcie = _env_float("SLATE_TPU_PCIE_GBS")
    if env_pcie is None:
        env_pcie = _env_float("SLATE_TPU_PEAK_PCIE_GBS")
    if env_pcie is not None:
        out["pcie_gbs"] = env_pcie
    return out


def parse_label(label: str):
    """``getrf_fp32_n8192_nb512`` → ``("getrf", "fp32", {"n": 8192,
    "nb": 512})``.  Batched-driver labels carry a ``_batched`` marker
    and a leading-batch-dim token (``posv_batched_fp32_n256_b64`` →
    ``("posv", "fp32", {"n": 256, "b": 64})``) — the routine keeps its
    base name and the model scales by ``b``.  Out-of-core labels carry
    an ``_ooc`` marker (``getrf_ooc_fp32_n131072_nb512``), surfaced as
    ``dims["ooc"] = 1`` so :func:`stage_model` prices the host-transfer
    stage without a signature change.  Labels that don't match the
    bench convention return ``(label, "", {})``."""
    m = _LABEL_RE.match(label or "")
    if not m:
        return (label, "", {})
    dims = {}
    for tok in m.group("dims").split("_"):
        dm = _DIM_RE.match(tok)
        if dm:
            dims[dm.group(1)] = int(dm.group(2))
    if m.group("ooc"):
        dims["ooc"] = 1
    if m.group("qdwh"):
        dims["qdwh"] = 1
    return (m.group("routine"), m.group("dtype"), dims)


# ---------------------------------------------------------------------------
# The analytical model
# ---------------------------------------------------------------------------

def model_flops(routine: str, dims: dict):
    """The driver's model flop count — the figure ``bench.py`` divides
    wall time by.  A leading batch dim (``dims["b"]``, the batched
    drivers) scales the whole count; solve drivers (posv/gesv) count
    the factor plus the triangular sweeps over ``nrhs`` (``dims["k"]``,
    default 1).  None for routines without a model."""
    n = dims.get("n")
    m = dims.get("m", n)
    if not n or not m:
        return None
    bfac = max(1, int(dims.get("b", 1)))
    k = min(m, n)
    nrhs = dims.get("k", 1)
    if routine in ("gemm", "mxu"):
        kk = dims.get("k", k)
        return bfac * 2.0 * m * n * kk
    if routine == "potrf":
        return bfac * n ** 3 / 3.0
    if routine == "posv":
        return bfac * (n ** 3 / 3.0 + 2.0 * n * n * nrhs)
    if routine == "getrf":
        # m·n·k − (m+n)k²/2 + k³/3 MACs ×2; = 2n³/3 for square
        return bfac * 2.0 * (m * n * k - (m + n) * k * k / 2.0
                             + k ** 3 / 3.0)
    if routine == "gesv":
        return bfac * (2.0 * n ** 3 / 3.0 + 2.0 * n * n * nrhs)
    if routine in ("geqrf", "gels"):
        fl = 2.0 * max(m, n) * k * k - 2.0 * k ** 3 / 3.0
        if routine == "gels":
            fl += 4.0 * m * n
        return bfac * fl
    if routine == "heev":
        return bfac * 4.0 * n ** 3 / 3.0
    if routine == "svd":
        return bfac * 8.0 * n ** 3 / 3.0
    return None


def _acc(stages, name, f, b):
    st = stages.setdefault(name, [0.0, 0.0])
    st[0] += f
    st[1] += b


_RT_PER_STEP_GETRF = {"composed": 3.0, "fused_trsm": 1.0, "fused": 0.0,
                      "full": 0.0}

#: ABFT checksum block-row height per element width (ISSUE 14) — one
#: checksum lane sublane-padded, matching
#: ``slate_tpu.ops.vmem.checksum_block_rows`` (kept as a literal here:
#: this module must stay stdlib-only).
_CHECKSUM_ROWS = {4: 8, 8: 4}

_ABFT_ENV = "SLATE_TPU_ABFT"


def _abft_wanted(abft) -> bool:
    """Resolve the ``abft`` model flag: an explicit bool wins; None
    reads ``SLATE_TPU_ABFT`` (so the offline sweep's candidate pricing
    and the autotune ladder see the checksum overhead automatically
    whenever the process runs with ABFT on, without plumbing a flag
    through every call site)."""
    if abft is not None:
        return bool(abft)
    raw = os.environ.get(_ABFT_ENV, "").strip().lower()
    return raw in ("correct", "verify", "1", "on", "true", "yes")


def _abft_stages(raw, routine: str, m, n, nb, isz):
    """Price the checksum carriage + per-step verify into the stage
    map: the checksum block-row/column ride the trailing update's gemm
    (extra rank-``cb`` rows/cols through the same contraction) and each
    step's verify reads the live trailing block once for its two sum
    sweeps.  Mutates ``raw`` in place — runs BEFORE the normalization
    that reconciles stage flops with the driver's model count."""
    cb = _CHECKSUM_ROWS.get(isz, 8)
    k = min(m, n)
    for k0 in range(0, k, nb):
        w = min(nb, k - k0)
        rows = m - k0
        r = n - k0 - w
        if r <= 0:
            continue
        if routine in ("getrf", "gesv"):
            # checksum row rides as cb extra L21 rows, checksum column
            # as cb extra U12 columns — both through the ONE step gemm
            _acc(raw, "update", 2.0 * cb * w * (r + rows),
                 cb * (r + rows) * isz)
            trail = (rows - w) * r
        else:                              # potrf / posv
            _acc(raw, "update", 2.0 * cb * w * r, cb * r * isz)
            trail = float(r) * r
        # per-step verify: one read of the trailing block + two sum
        # sweeps (HBM-bound — the dominant ABFT cost at large n)
        _acc(raw, "verify", 2.0 * trail, trail * isz)


def _stages_getrf(m, n, nb, isz, fusion):
    stages, rts = {}, 0.0
    per_step = _RT_PER_STEP_GETRF.get(fusion, 3.0)
    k = min(m, n)
    for k0 in range(0, k, nb):
        w = min(nb, k - k0)
        rows = m - k0
        r = n - k0 - w
        _acc(stages, "panel", 2.0 * w * w * (rows - w / 3.0),
             2.0 * rows * w * isz)
        _acc(stages, "pivot", 0.0, 2.0 * w * n * isz)
        if r > 0:
            _acc(stages, "trsm", 2.0 * w * w * r,
                 (2.0 * w * r + w * w) * isz)
            _acc(stages, "update", 2.0 * (rows - w) * w * r,
                 (2.0 * (rows - w) * r + (rows - w) * w + w * r) * isz)
            rts += per_step
    return stages, rts


def _stages_potrf(n, nb, isz, fusion):
    stages, rts = {}, 0.0
    ws = nb * max(1, _POTRF_STRIP_W // nb)
    for k0 in range(0, n, nb):
        w = min(nb, n - k0)
        r = n - k0 - w
        # panel = diagonal chol + explicit inverse (the trsm-as-gemm
        # enabler), each ~w³/3
        _acc(stages, "panel", 2.0 * w ** 3 / 3.0, 2.0 * w * w * isz)
        if r > 0:
            _acc(stages, "trsm", 2.0 * r * w * w,
                 (2.0 * r * w + w * w) * isz)
            _acc(stages, "update", float(r) * (r + w) * w,
                 (float(r) * r + r * w) * isz)
            if fusion not in ("fused", "fused_trsm", "full"):
                rts += 1.0 + len(range(k0 + w, n, ws))
    return stages, rts


def _stages_geqrf(m, n, nb, isz, with_solve):
    stages, rts = {}, 0.0
    k = min(m, n)
    for k0 in range(0, k, nb):
        w = min(nb, k - k0)
        rows = m - k0
        r = n - k0 - w
        _acc(stages, "panel", 2.0 * w * w * (rows - w / 3.0),
             2.0 * rows * w * isz)
        if r > 0:
            _acc(stages, "update", 4.0 * w * rows * r,
                 (2.0 * rows * r + rows * w) * isz)
    if with_solve:
        _acc(stages, "solve", 4.0 * m * n, (m * n + m + n) * isz)
    return stages, rts


#: coarse flop shares of the two-stage eig/SVD pipelines (band
#: reduction / device bulge chase / back-transform).  The chase carries
#: ~no flops but sweeps the band through HBM once per panel — its cost
#: is the bytes term.
_TWOSTAGE_SHARES = {"stage1": 0.55, "chase": 0.05, "stage3": 0.40}
_TWOSTAGE_BAND = 256


def _stages_twostage(n, isz, total):
    stages = {}
    sweeps = max(1, n // _TWOSTAGE_BAND)
    _acc(stages, "stage1", _TWOSTAGE_SHARES["stage1"] * total,
         (2.0 / 3.0) * sweeps * n * n * isz)
    _acc(stages, "chase", _TWOSTAGE_SHARES["chase"] * total,
         2.0 * n * n * isz)
    _acc(stages, "stage3", _TWOSTAGE_SHARES["stage3"] * total,
         2.0 * n * n * isz)
    return stages, 0.0


#: coarse flop shares of the QDWH spectral tier (``linalg/polar.py``):
#: the polar iterations' stacked-QR steps, the Cholesky-variant
#: factor+trsm steps, the epilogue/projector/similarity gemms, and the
#: crossover leaves' two-stage tail (priced as ``stage1`` so the leaf
#: blocks' measured ``stage.heev.stage1`` timers join the same row).
#: >= 80% lands on qr/chol/gemm — the gemm-roofline stages the tier
#: exists for — which the reconciliation pin in ``tests/test_qdwh.py``
#: asserts.
_QDWH_SHARES = {"qr": 0.30, "chol": 0.10, "gemm": 0.50, "stage1": 0.10}


def _stages_qdwh(n, isz, total):
    """Stage map of ``heev_qdwh``/``svd_qdwh``.  Shares are coarse by
    design — the normalization in :func:`stage_model` reconciles them
    exactly against :func:`model_flops`.  Byte terms: each QR iteration
    streams the stacked 2n x n operand a few times, the chol variant
    touches the n x n Gram matrix, and the divide-and-conquer gemms
    sweep the operand once per similarity transform."""
    stages = {}
    nn = float(n) * n * isz
    _acc(stages, "qr", _QDWH_SHARES["qr"] * total, 6.0 * nn)
    _acc(stages, "chol", _QDWH_SHARES["chol"] * total, 3.0 * nn)
    _acc(stages, "gemm", _QDWH_SHARES["gemm"] * total, 8.0 * nn)
    _acc(stages, "stage1", _QDWH_SHARES["stage1"] * total, 2.0 * nn)
    return stages, 0.0


#: bf16 MXU passes behind one nominal fp32 flop on the split-product
#: gemm (``ops/split_gemm.py`` bf16x3): the K-folded slice dot streams
#: three bf16 gemm passes to produce one error-free fp32 product, so
#: its roofline lane is the bf16 peak with a 3x flop carriage.
SPLIT_GEMM_PASSES = 3.0


def split_lane(label: str):
    """``(lane_dtype, pass_multiplier)`` for a bench label.  The
    ``gemm_fp32_split_n*`` family (``ops/split_gemm.py``) executes
    :data:`SPLIT_GEMM_PASSES` bf16 MXU passes per nominal fp32 flop, so
    gap reports and :func:`predict_seconds` must price it against the
    bf16 peak (``SLATE_TPU_PEAK_TFLOPS_BF16`` overridable via
    :func:`peaks`) instead of the emulated-fp32 lane; every other label
    prices in its own dtype lane at 1x (``(None, 1.0)``)."""
    if "_split_" in (label or ""):
        return "bf16", SPLIT_GEMM_PASSES
    return None, 1.0


#: stage order for reports (model dicts are unordered)
_STAGE_ORDER = ("panel", "pivot", "trsm", "update", "verify", "solve",
                "host", "qr", "chol", "gemm", "stage1", "chase",
                "stage3", "mxu", "collective")


def _ooc_host_bytes(routine: str, n: int, nb: int, isz: int) -> float:
    """Byte model of the out-of-core tile pool's host↔HBM traffic
    (ISSUE 17) — what ``ooc.host_bytes`` counts with a cold window.
    Per right-looking step k over a g = n/nb tile grid the getrf driver
    reads + writes the (g−k)-tile strip of EVERY block column (panel,
    laswp'd left columns, updated trailing columns); potrf touches only
    the lower tiles.  A warm window turns re-reads into hits, so the
    measured counter is ≤ this cold-window ceiling."""
    g = max(1, n // max(1, nb))
    tb = float(nb) * nb * isz
    if routine in ("potrf", "posv"):
        # Σ_k [1 diag + (g−k−1) panel + lower-trailing reads+writes]
        strips = (g * (g + 1) / 2.0        # panel column tiles, r/w
                  + g * (g + 1) * (g + 2) / 6.0)  # trailing lower tiles
        return 2.0 * tb * strips
    # getrf/gesv: every block column's rows-below-k strip, read + write
    return 2.0 * tb * g * (g * (g + 1) / 2.0)


def stage_model(routine: str, dims: dict, dtype: str = "fp32",
                fusion: str = "composed", abft=None):
    """``(stages, hbm_roundtrips)`` for one routine invocation, or None
    when no model exists.  ``stages`` is ``[{"stage", "flops",
    "bytes"}]`` in pipeline order with the flops NORMALIZED so they sum
    exactly to :func:`model_flops` (the self-reconciliation contract);
    ``hbm_roundtrips`` is the materialized inter-stage intermediate
    count the composed drivers record on ``step.hbm_roundtrips`` (0 on
    the fused paths — the CI pin).  ``abft`` (ISSUE 14; None = read
    ``SLATE_TPU_ABFT``) prices the checksum block-row carriage and the
    per-step verify sweep into the factorization families, so abft-on
    reports still reconcile and :func:`predict_seconds` sees the
    overhead."""
    total = model_flops(routine, dims)
    if total is None or total <= 0:
        return None
    isz = _ITEMSIZE.get(dtype or "fp32", 4)
    n = dims.get("n")
    m = dims.get("m", n)
    bfac = max(1, int(dims.get("b", 1)))
    nrhs = dims.get("k", 1)
    nb = min(dims.get("nb") or DEFAULT_NB, min(m, n))
    if routine in ("gemm", "mxu"):
        k = dims.get("k", min(m, n))
        raw = {"mxu": [2.0 * m * n * k,
                       (m * k + k * n + 2.0 * m * n) * isz]}
        rts = 0.0
    elif routine in ("getrf", "gesv"):
        raw, rts = _stages_getrf(m, n, nb, isz, fusion)
        if routine == "gesv":
            _acc(raw, "solve", 2.0 * n * n * nrhs,
                 (n * n + 2.0 * n * nrhs) * isz)
    elif routine in ("potrf", "posv"):
        raw, rts = _stages_potrf(n, nb, isz, fusion)
        if routine == "posv":
            _acc(raw, "solve", 2.0 * n * n * nrhs,
                 (n * n + 2.0 * n * nrhs) * isz)
    elif routine in ("geqrf", "gels"):
        raw, rts = _stages_geqrf(m, n, nb, isz, routine == "gels")
    elif routine in ("heev", "svd"):
        if dims.get("qdwh"):
            raw, rts = _stages_qdwh(n, isz, total / bfac)
        else:
            raw, rts = _stages_twostage(n, isz, total / bfac)
    else:
        return None
    if _abft_wanted(abft) and bfac == 1 \
            and routine in ("getrf", "gesv", "potrf", "posv"):
        _abft_stages(raw, routine, m, n, nb, isz)
    if dims.get("ooc") and routine in ("getrf", "gesv", "potrf", "posv"):
        # out-of-core tile pool (ISSUE 17): the host↔HBM tile traffic
        # as a zero-flop stage priced on the PCIe lane (flop
        # normalization is untouched, so reconciliation stays exact)
        _acc(raw, "host", 0.0, _ooc_host_bytes(routine, n, nb, isz))
    if bfac > 1:
        # leading batch dim: per-problem stage bytes and round trips
        # scale with the batch; flops ride the normalization below
        # (``total`` already carries the ×b)
        for st in raw.values():
            st[1] *= bfac
        rts *= bfac
    raw_total = sum(f for f, _ in raw.values())
    scale = total / raw_total if raw_total > 0 else 1.0
    stages = [{"stage": s, "flops": raw[s][0] * scale,
               "bytes": raw[s][1]}
              for s in _STAGE_ORDER if s in raw]
    return stages, rts


#: per-platform dispatch/launch latency (seconds) charged once per
#: invocation and once per materialized HBM round trip by
#: :func:`predict_seconds` — the term that separates fusion depths at
#: small shapes, where the roofline minima alone are indistinguishable.
#: Override with ``SLATE_TPU_LAUNCH_S`` for a new TPU generation.
_DEF_LAUNCH_S = {"tpu": 5e-6, "cpu": 2e-5}


def predict_seconds(routine: str, dims: dict, dtype: str = "fp32",
                    fusion: str = "composed", platform: str = "tpu",
                    launch_s=None, abft=None, lane=None,
                    lane_passes: float = 1.0):
    """Model-predicted wall seconds for ONE invocation at the given
    fusion depth: the per-stage roofline minima (:func:`stage_model` on
    :func:`peaks`) plus a launch-latency + panel-strip-traffic term per
    materialized HBM round trip.  This is the candidate pricing the
    offline sweep (``perf/sweep.py``) prunes with BEFORE any timing rep
    runs, and the analytical guard its interpolating decision model
    cross-checks selections against — so it must stay loadable
    stdlib-only, like everything else in this module.  None when the
    routine has no stage model.  ``abft`` (None = read
    ``SLATE_TPU_ABFT``) includes the checksum-carriage and verify
    pricing, so depth rankings under ABFT stay honest — a depth whose
    verify is whole-run (fused/full envelope) and one that verifies
    per step are priced with the same sweep term.  ``lane`` /
    ``lane_passes`` (see :func:`split_lane`) price an emulated-precision
    invocation against another dtype's peak with a flop multiplier —
    the bf16 lane the split-product gemm family reconciles against."""
    model = stage_model(routine, dims, dtype, fusion, abft=abft)
    if model is None:
        return None
    stages, rts = model
    pk = peaks(platform, lane or dtype)
    t = 0.0
    mins = {}
    for s in stages:
        # the host stage streams over the PCIe link, not HBM (ISSUE 17)
        bw = pk["pcie_gbs"] if s["stage"] == "host" else pk["hbm_gbs"]
        m = max(s["flops"] * lane_passes / (pk["tflops"] * 1e12),
                s["bytes"] / (bw * 1e9))
        mins[s["stage"]] = mins.get(s["stage"], 0.0) + m
        t += m
    if fusion == "full":
        # lookahead overlap credit: the full-depth kernel factors panel
        # k+1 while step k's trailing gemm streams, so panel time hides
        # under the update stage's roofline minimum (the same
        # exposed-vs-overlapped split the dist_util pipeline models)
        t -= min(mins.get("panel", 0.0), mins.get("update", 0.0))
    if launch_s is None:
        launch_s = _env_float("SLATE_TPU_LAUNCH_S")
    if launch_s is None:
        launch_s = _DEF_LAUNCH_S.get(platform, _DEF_LAUNCH_S["tpu"])
    n = dims.get("n") or dims.get("m") or 1
    nb = min(dims.get("nb") or DEFAULT_NB, n)
    isz = _ITEMSIZE.get(dtype or "fp32", 4)
    # one panel-strip write+read per materialized inter-stage
    # intermediate (rts already carries the leading batch factor)
    rt_bytes = 2.0 * n * nb * isz
    t += launch_s + rts * (launch_s + rt_bytes / (pk["hbm_gbs"] * 1e9))
    return t


#: serve-surface op (``serve/queue.py``'s SUPPORTED_OPS) → the stage
#: model routine pricing one such problem — the fleet router's cost
#: vocabulary (ISSUE 20)
_SERVE_ROUTINES = {"potrf": "potrf", "getrf": "getrf", "posv": "posv",
                   "gesv": "gesv", "geqrf": "geqrf", "gels": "gels",
                   "heev": "heev"}


def predict_request_seconds(op: str, dims, nrhs: int = 1,
                            dtype: str = "fp32", batch: int = 1,
                            platform: str = "tpu") -> float:
    """Model-predicted wall seconds for ONE serve-surface request
    batch — the fleet router's analytical cost model: placement
    compares each replica's ``queue backlog × this`` against the
    ICI-sharded lane without timing anything (BLASX's cost-model
    scheduling stance).  ``op`` is a serve op name, ``dims`` the RAW
    problem dims ((n,) square, (m, n) tall).  Always returns a
    positive float: when the stage model abstains, a crude
    flops-over-peak bound (plus a launch floor) keeps the router's
    argmin ordered instead of crashing placement."""
    routine = _SERVE_ROUTINES.get(op)
    if routine is None:
        raise KeyError(f"unknown serve op {op!r}; "
                       f"known: {sorted(_SERVE_ROUTINES)}")
    dims = tuple(int(d) for d in (dims if isinstance(dims, (tuple, list))
                                  else (dims,)))
    d = {"b": max(1, int(batch))}
    if op in ("geqrf", "gels"):
        d["m"], d["n"] = dims
    else:
        d["n"] = dims[0]
    if op in ("posv", "gesv", "gels"):
        d["k"] = max(1, int(nrhs))
    t = predict_seconds(routine, d, dtype=dtype, platform=platform)
    if t is not None and t > 0.0:
        return float(t)
    fl = model_flops(routine, d) or (2.0 * dims[0] ** 3)
    pk = peaks(platform, dtype)
    return float(fl / (pk["tflops"] * 1e12) + 2e-5)


def expected_hbm_roundtrips(routine: str, dims: dict,
                            fusion: str = "composed"):
    """The analytic ``step.hbm_roundtrips`` count for one invocation —
    must agree with what the composed drivers record at trace time
    (regression-tested against the live counter)."""
    model = stage_model(routine, dims, fusion=fusion)
    return model[1] if model else None


def fusion_from_autotune(routine: str, autotune) -> str:
    """The fusion depth this routine actually ran at, read off its
    autotune decision tags (the ``lu_step`` / ``potrf_step`` sites);
    ``"composed"`` when untagged."""
    op = _FUSION_OPS.get(routine)
    if op and isinstance(autotune, dict):
        for key, val in autotune.items():
            if isinstance(key, str) and key.startswith(op + "|") \
                    and isinstance(val, str):
                return val
    return "composed"


def driver_from_autotune(routine: str, autotune) -> str:
    """The driver chain a spectral routine actually ran ("twostage" |
    "qdwh"), read off its ``eig_driver`` / ``svd_driver`` decision tags
    — the eig/svd analog of :func:`fusion_from_autotune`.  "twostage"
    when untagged: labels from forced-opts benches carry the ``_qdwh``
    label marker instead, so an untagged plain label really did run the
    two-stage chain."""
    op = _DRIVER_OPS.get(routine)
    if op and isinstance(autotune, dict):
        for key, val in autotune.items():
            if isinstance(key, str) and key.startswith(op + "|") \
                    and val == "qdwh":
                return "qdwh"
    return "twostage"


# ---------------------------------------------------------------------------
# Measured-timer join
# ---------------------------------------------------------------------------

def stage_timers(metrics_snapshot, op: str) -> dict:
    """Measured per-stage timers for ``op`` out of a metrics snapshot:
    ``{stage: {"count", "total_s"}}``.

    Joins ONLY the namespaced keys ``step.<op>.<stage>`` and
    ``stage.<op>.<name>`` — a bare two-segment ``step.<stage>`` key or
    another op's timers can never collide into this op's attribution,
    so the count/total distinction of each (op, stage) pair survives
    two ops firing the same stage name in one routine."""
    out = {}
    timers = (metrics_snapshot or {}).get("timers") or {}
    for key, t in timers.items():
        parts = key.split(".")
        if len(parts) != 3 or parts[0] not in ("step", "stage") \
                or parts[1] != op:
            continue
        if not isinstance(t, dict):
            continue
        out[parts[2]] = {"count": t.get("count", 0),
                         "total_s": float(t.get("total_s", 0.0))}
    return out


def profile_stage_seconds(device_profile, op: str) -> dict:
    """Per-stage DEVICE seconds for ``op`` out of an xprof capture —
    ``{stage: seconds}``, the ``device_profile`` join
    :func:`attribute`'s top compute-source rung weighs stages with.

    Accepts the shapes a caller naturally holds: a full parsed profile
    (``{"stages": {op: {stage: s}}}`` — ``xprof.last_profile()``), the
    per-op stages map alone, or a flat ``{stage: seconds}`` for this
    op.  Non-numeric leaves and other ops' entries are ignored; ``{}``
    when the capture saw nothing for ``op``."""
    if not isinstance(device_profile, dict):
        return {}
    m = device_profile.get("stages", device_profile)
    if isinstance(m, dict) and isinstance(m.get(op), dict):
        m = m[op]
    if not isinstance(m, dict):
        return {}
    out = {}
    for stage, v in m.items():
        if isinstance(v, (int, float)) and not isinstance(v, bool) \
                and float(v) > 0.0:
            out[str(stage)] = float(v)
        elif isinstance(v, dict):
            # flat map keyed by op: only this op's sub-map counts
            continue
    return out


# ---------------------------------------------------------------------------
# The attribution engine
# ---------------------------------------------------------------------------

def _r(x, nd=9):
    # ns resolution on the seconds fields: small-shape reports (CPU CI)
    # must still reconcile stage flops against GFLOP/s to well under 1%
    return round(float(x), nd)


def attribute(label: str, gflops, metrics_snapshot=None, autotune=None,
              platform: str = "tpu", n_devices: int = 1,
              collective_bytes=None, device_profile=None) -> dict | None:
    """The gap report for one routine invocation, or None when the
    label has no model (derived ``_s`` / ``_frac_of_gemm`` /
    ``_frac_of_split_gemm`` / ``_over_floor`` keys, zero throughput,
    unknown routines).  Labels carrying the ``_split_`` marker (the
    ``gemm_fp32_split_n*`` family) are priced against the bf16 roofline
    lane with the :data:`SPLIT_GEMM_PASSES` flop carriage — see
    :func:`split_lane`.

    Inputs are exactly what a bench JSON line carries: the submetric
    label, its GFLOP/s, the routine's metrics snapshot (ideally the
    per-routine DELTA — r7 bench), and its autotune tags.  On mesh runs
    pass ``n_devices`` and either ``collective_bytes`` or a snapshot
    carrying the ``collective.bcast_*.bytes`` counters.  When an xprof
    capture exists, pass its profile (or per-stage seconds) as
    ``device_profile`` — device truth outranks host timers on the
    compute-source ladder (``device_profile > timers > model``,
    reported as ``compute_source``).
    """
    if label.endswith(("_s", "_frac_of_gemm", "_frac_of_split_gemm",
                       "_over_floor")):
        return None
    if not isinstance(gflops, (int, float)) or gflops <= 0:
        return None
    routine, dtype, dims = parse_label(label)
    fusion = fusion_from_autotune(routine, autotune)
    if routine in ("heev", "svd") and not dims.get("qdwh") \
            and driver_from_autotune(routine, autotune) == "qdwh":
        # autotune picked the QDWH chain for a plain-labeled run —
        # price it with the QDWH stage model, not the two-stage one
        dims = dict(dims, qdwh=1)
    model = stage_model(routine, dims, dtype, fusion)
    if model is None:
        return None
    stage_fb, model_rts = model
    lane, lane_passes = split_lane(label)
    if lane:
        # bf16 lane: the split kernel streams ``lane_passes`` bf16
        # slice copies of each operand (itemsize 2) through the MXU and
        # writes the fp32 result once, so the mxu stage's byte model is
        # re-derived here instead of inheriting the fp32 operand bytes
        nn = dims.get("n")
        mm = dims.get("m", nn)
        kk = dims.get("k", nn if mm is None else min(mm, nn))
        if nn:
            for s in stage_fb:
                if s["stage"] == "mxu":
                    s["bytes"] = (lane_passes * (mm * kk + kk * nn) * 2.0
                                  + 2.0 * mm * nn * 4.0)
    pk = peaks(platform, lane or dtype)
    total_flops = sum(s["flops"] for s in stage_fb)
    measured_s = total_flops / (float(gflops) * 1e9)

    counters = (metrics_snapshot or {}).get("counters") or {}
    if collective_bytes is None:
        collective_bytes = (counters.get("collective.bcast_col.bytes", 0.0)
                            + counters.get("collective.bcast_row.bytes",
                                           0.0))

    stages = []
    for s in stage_fb:
        t_mxu = s["flops"] * lane_passes / (pk["tflops"] * 1e12)
        if s["stage"] == "host":
            # out-of-core tile traffic prices on the PCIe lane, and the
            # pool's prefetch overlaps it with MXU work — but the gap
            # report keeps it on the critical path (worst case) so an
            # overlap regression shows up as a closing gap, not a lie
            t_bw = s["bytes"] / (pk["pcie_gbs"] * 1e9)
            bound = "pcie"
        else:
            t_bw = s["bytes"] / (pk["hbm_gbs"] * 1e9)
            bound = "mxu" if t_mxu >= t_bw else "hbm"
        stages.append({"stage": s["stage"], "flops": s["flops"],
                       "bytes": s["bytes"], "bound": bound,
                       "min_s": max(t_mxu, t_bw)})

    lookahead = None
    if fusion == "full":
        # in-kernel lookahead: panel k+1 factors while step k's trailing
        # gemm streams — the panel stage's critical-path minimum shrinks
        # by whatever hides under the update stage's roofline minimum
        # (the overlap-budget rule of the collective split below)
        pmin = sum(s["min_s"] for s in stages if s["stage"] == "panel")
        budget = sum(s["min_s"] for s in stages if s["stage"] == "update")
        overlapped = min(pmin, budget)
        if pmin > 0:
            for s in stages:
                if s["stage"] == "panel":
                    s["min_s"] -= overlapped * (s["min_s"] / pmin)
        lookahead = {"panel_min_s": _r(pmin),
                     "overlap_budget_s": _r(budget),
                     "overlapped_s": _r(overlapped),
                     "exposed_s": _r(pmin - overlapped)}

    collective = None
    if collective_bytes and collective_bytes > 0:
        coll_s = (float(collective_bytes)
                  / (pk["ici_gbs"] * 1e9) / max(1, int(n_devices)))
        # the lookahead pipeline overlaps the panel broadcast with the
        # trailing update: overlap budget = the update stage's roofline
        # minimum; anything past it is exposed on the critical path
        budget = sum(s["min_s"] for s in stages if s["stage"] == "update")
        overlapped = min(coll_s, budget)
        exposed = coll_s - overlapped
        stages.append({"stage": "collective", "flops": 0.0,
                       "bytes": float(collective_bytes), "bound": "ici",
                       "min_s": exposed})
        collective = {"bytes": float(collective_bytes),
                      "min_s": _r(coll_s),
                      "overlapped_s": _r(overlapped),
                      "exposed_s": _r(exposed)}

    model_s = sum(s["min_s"] for s in stages)
    gap_s = measured_s - model_s

    # apportion the measured wall time across stages — the
    # compute-source ladder: device-profile-weighted when an xprof
    # capture covered this op (device truth), timer-weighted when
    # namespaced host stage timers exist, model-flop-weighted otherwise
    timers = stage_timers(metrics_snapshot, routine)
    dev = profile_stage_seconds(device_profile, routine)
    if routine in ("heev", "svd") and not dims.get("qdwh"):
        # the drivers record the two-stage middle as stage.<op>.stage2;
        # the model calls that stage "chase" — without the alias the
        # measured middle-stage time would silently redistribute onto
        # stage1/stage3 and a chase regression would be misattributed
        if "stage2" in timers and "chase" not in timers:
            timers["chase"] = timers.pop("stage2")
        if "stage2" in dev and "chase" not in dev:
            dev["chase"] = dev.pop("stage2")
    dev_timed = {s["stage"]: dev[s["stage"]] for s in stages
                 if dev.get(s["stage"], 0.0) > 0.0}
    timed = {s["stage"]: timers[s["stage"]]["total_s"] for s in stages
             if s["stage"] in timers
             and timers[s["stage"]]["total_s"] > 0.0}
    weights = dev_timed or timed
    if weights:
        source = "device_profile" if dev_timed else "timers"
        unweighted_min = sum(s["min_s"] for s in stages
                             if s["stage"] not in weights)
        leftover = max(measured_s - unweighted_min, 0.0)
        tot_w = sum(weights.values())
        for s in stages:
            s["measured_s"] = (leftover * weights[s["stage"]] / tot_w
                               if s["stage"] in weights else s["min_s"])
    else:
        source = "model"
        pos_gap = max(gap_s, 0.0)
        flops_tot = sum(s["flops"] for s in stages)
        for s in stages:
            w = (s["flops"] / flops_tot if flops_tot > 0
                 else 1.0 / len(stages))
            s["measured_s"] = s["min_s"] + pos_gap * w

    for s in stages:
        g = max(s["measured_s"] - s["min_s"], 0.0)
        s["gap_s"] = _r(g)
        s["gap_share"] = _r(g / measured_s if measured_s > 0 else 0.0, 4)
        s["roofline_frac"] = _r(
            min(s["min_s"] / s["measured_s"], 1.0)
            if s["measured_s"] > 0 else 1.0, 4)
        s["min_s"] = _r(s["min_s"])
        s["measured_s"] = _r(s["measured_s"])
        s["flops"] = float(s["flops"])
        s["bytes"] = float(s["bytes"])

    bottlenecks = [{"stage": s["stage"], "gap_s": s["gap_s"],
                    "gap_share": s["gap_share"]}
                   for s in sorted(stages, key=lambda s: -s["gap_s"])
                   if s["gap_s"] > 0]

    report = {
        "label": label,
        "routine": routine,
        "dtype": dtype,
        "dims": dims,
        "platform": platform,
        "fusion": fusion,
        "backend_source": source,
        "compute_source": source,
        "peaks": {k: _r(v, 3) for k, v in pk.items()},
        "gflops": float(gflops),
        "total_flops": float(total_flops),
        "measured_s": _r(measured_s),
        "model_s": _r(model_s),
        "gap_s": _r(gap_s),
        "achieved_frac": _r(min(model_s / measured_s, 1.0)
                            if measured_s > 0 else 1.0, 4),
        "frac_of_peak": _r(total_flops * lane_passes / measured_s
                           / (pk["tflops"] * 1e12)
                           if measured_s > 0 else 0.0, 4),
        "stages": stages,
        "bottlenecks": bottlenecks,
        "hbm_roundtrips": {
            "model": float(model_rts),
            "measured": counters.get("step.hbm_roundtrips"),
        },
        "n_devices": int(n_devices),
    }
    if lane:
        report["lane"] = lane
        report["lane_passes"] = float(lane_passes)
    if lookahead is not None:
        report["lookahead"] = lookahead
    if collective is not None:
        report["collective"] = collective
    if dev_timed:
        prov = {"stages": sorted(dev_timed),
                "device_s": _r(sum(dev_timed.values()))}
        if isinstance(device_profile, dict) \
                and device_profile.get("digest"):
            prov["digest"] = str(device_profile["digest"])
        report["device_profile"] = prov
    return report


def attribute_live(op: str, n: int, dtype: str = "fp32", batch: int = 1,
                   latency_s: float = 0.0, platform: str = "tpu"):
    """The gap report for one LIVE serving sample — the telemetry
    sentinel's attribution hook (ISSUE 10): build the batched-driver
    label bench would emit for this bucket
    (``<op>_batched_<dtype>_n<n>_b<batch>``), derive GFLOP/s from the
    model flop count over the observed dispatch latency, and return
    :func:`attribute`'s block.  None when the op has no model or the
    latency is unusable — a live event must degrade to "no
    attribution", never raise."""
    if not n or not latency_s or latency_s <= 0:
        return None
    b = max(1, int(batch))
    fl = model_flops(str(op), {"n": int(n), "b": b})
    if not fl:
        return None
    label = "%s_batched_%s_n%d_b%d" % (op, dtype or "fp32", int(n), b)
    return attribute(label, fl / float(latency_s) / 1e9,
                     platform=platform)


# ---------------------------------------------------------------------------
# Diff / rendering
# ---------------------------------------------------------------------------

def explain_pair(old: dict, new: dict, delta_pct=None,
                 note: str = "") -> str:
    """One sentinel line naming the stage whose share of the wall time
    moved most between two gap reports of the same routine — e.g.
    ``geqrf_fp32_m32768_n4096 -19.6%: update stage roofline fraction
    0.43->0.34 (gap share 0.50->0.58)``.  ``note`` (the sentinel's
    backend-change note) rides along when present."""
    olds = {s["stage"]: s for s in old.get("stages", ())}
    best, best_score = None, None
    for s in new.get("stages", ()):
        o = olds.get(s["stage"])
        if o is None:
            continue
        score = s["gap_share"] - o["gap_share"]
        if best_score is None or score > best_score:
            best, best_score = (o, s), score
    label = new.get("label", old.get("label", "?"))
    head = label
    if delta_pct is not None:
        head += " %+.1f%%" % delta_pct
    if best is None:
        line = "%s: no comparable stages" % head
    else:
        o, s = best
        line = ("%s: %s stage roofline fraction %.2f->%.2f "
                "(gap share %.2f->%.2f)"
                % (head, s["stage"], o["roofline_frac"],
                   s["roofline_frac"], o["gap_share"], s["gap_share"]))
    src_o = old.get("compute_source") or old.get("backend_source")
    src_n = new.get("compute_source") or new.get("backend_source")
    if src_n:
        # a reader must be able to tell a device-truth claim from a
        # host-timer or model-only apportionment at a glance
        line += " [source %s]" % (src_n if src_o in (src_n, None)
                                  else "%s->%s" % (src_o, src_n))
    if note:
        line += "; " + note
    return line


def _eng(x: float) -> str:
    for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(x) >= div:
            return "%.2f%s" % (x / div, unit)
    return "%.0f" % x


def format_report(rep: dict) -> str:
    """Human-readable roofline table for one gap report (the
    ``tools/gap_report.py`` rendering)."""
    pk = rep["peaks"]
    head = [
        "%s  [%s %s, fusion=%s, attribution=%s]"
        % (rep["label"], rep["platform"], rep["dtype"] or "?",
           rep["fusion"],
           rep.get("compute_source") or rep.get("backend_source")),
        "  achieved %.1f GFLOP/s = %.3f of %.1f TF/s peak "
        "(HBM %.0f GB/s); measured %.2f ms, roofline-min %.2f ms, "
        "gap %.2f ms"
        % (rep["gflops"], rep["frac_of_peak"], pk["tflops"],
           pk["hbm_gbs"], rep["measured_s"] * 1e3, rep["model_s"] * 1e3,
           rep["gap_s"] * 1e3),
    ]
    rows = [("stage", "flops", "bytes", "bound", "min_ms", "est_ms",
             "frac", "gap_ms", "gap%")]
    for s in rep["stages"]:
        rows.append((s["stage"], _eng(s["flops"]), _eng(s["bytes"]),
                     s["bound"], "%.3f" % (s["min_s"] * 1e3),
                     "%.3f" % (s["measured_s"] * 1e3),
                     "%.2f" % s["roofline_frac"],
                     "%.3f" % (s["gap_s"] * 1e3),
                     "%.1f" % (s["gap_share"] * 100.0)))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    body = ["  " + "  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
            for r in rows]
    tail = []
    if rep.get("bottlenecks"):
        tail.append("  bottlenecks: " + ", ".join(
            "%s (%.0f%% of time)" % (b["stage"], b["gap_share"] * 100.0)
            for b in rep["bottlenecks"]))
    if rep.get("lookahead"):
        la = rep["lookahead"]
        tail.append("  lookahead: panel min %.2f ms, %.2f overlapped "
                    "under the update stream, %.2f exposed"
                    % (la["panel_min_s"] * 1e3, la["overlapped_s"] * 1e3,
                       la["exposed_s"] * 1e3))
    if rep.get("collective"):
        c = rep["collective"]
        tail.append("  collectives: %sB, %.2f ms (%.2f overlapped, "
                    "%.2f exposed)"
                    % (_eng(c["bytes"]), c["min_s"] * 1e3,
                       c["overlapped_s"] * 1e3, c["exposed_s"] * 1e3))
    rt = rep.get("hbm_roundtrips") or {}
    if rt.get("model") or rt.get("measured"):
        tail.append("  hbm round-trips: model %s, measured %s"
                    % (rt.get("model"), rt.get("measured")))
    return "\n".join(head + body + tail)


def record_rooflines(rep: dict) -> bool:
    """Feed ``roofline.<label>.<stage>`` gauge samples into the metrics
    registry so ``trace.finish_perfetto`` exports per-stage roofline
    fractions as counter tracks on the existing clock.  No-op (returns
    False) when this module was loaded standalone by file path — the
    offline tools have no registry to feed."""
    try:
        from . import metrics
    except ImportError:
        return False
    if not metrics.enabled():
        return False
    for s in rep.get("stages", ()):
        metrics.set_gauge("roofline.%s.%s" % (rep["label"], s["stage"]),
                          float(s["roofline_frac"]))
    return True
