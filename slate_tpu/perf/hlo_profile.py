"""Lowered-HLO collective / flop profiler for the distributed drivers.

The reference project reads its comm behavior off MPI traces; here the
whole communication schedule is a *compile-time artifact*, so regressions
are visible without running anything: parse the compiled HLO of a driver
and count, per while-loop body (= per factorization step),

* collective ops (all-reduce / all-gather / reduce-scatter /
  collective-permute / all-to-all), with element counts and bytes;
* ``dot`` flops (2·M·N·K per contraction), the trailing-update currency.

``tests/test_collective_profile.py`` pins per-driver budgets on these so
a silent "one extra collective per step" or "full-size masked trailing
gemm" regression fails CI instead of eating the ICI at scale — round 5's
empty bench artifact proved runtime-only accounting is too fragile.

XLA's ``cost_analysis()`` counts a while body ONCE, not per trip, so the
per-body tallies here must be combined with externally-known trip counts
(:func:`~slate_tpu.parallel.dist_util.stage_bounds` for the staged
factorization loops); :meth:`ModuleProfile.stepped_totals` does exactly
that.  The raw ``cost_analysis()`` flops are surfaced too
(:attr:`ModuleProfile.cost_flops`) for one-shot (loop-free) programs.

Works on the CPU-mesh simulation (conftest's 8 virtual devices) and on
real TPU meshes alike — only the HLO text is inspected.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from math import prod

COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

_SHAPE_RE = r"([a-z]+\d+|pred)\[([0-9,]*)\]"
_COLLECTIVE_RE = re.compile(
    r"= " + _SHAPE_RE + r"\S* (" + "|".join(COLLECTIVE_KINDS) + r")\(")
_CUSTOM_CALL_RE = re.compile(r"=\s*\S+\s+custom-call\(")
_CC_TARGET_RE = re.compile(r'custom_call_target="([^"]+)"')
_DOT_RE = re.compile(
    r"= " + _SHAPE_RE + r"\S* dot\((.*)\), lhs_contracting_dims=\{([0-9,]*)\}")
_CALL_RE = re.compile(r"(?:calls|to_apply|body|condition)=(%[\w.\-]+)")
_WHILE_RE = re.compile(r"\bwhile\(.*body=(%[\w.\-]+)")
_COMP_HEAD_RE = re.compile(r"^(ENTRY )?(%[\w.\-]+)\s*(?:\([^)]*\))?.*\{\s*$")


def _dims(txt: str):
    return tuple(int(d) for d in txt.split(",")) if txt else ()


@dataclass(frozen=True)
class CollectiveOp:
    """One collective instruction: kind, result dtype and shape."""

    kind: str
    dtype: str
    shape: tuple

    @property
    def elems(self) -> int:
        return prod(self.shape) if self.shape else 1

    @property
    def bytes(self) -> int:
        return self.elems * _DTYPE_BYTES.get(self.dtype, 8)


@dataclass(frozen=True)
class DotOp:
    """One ``dot`` instruction; ``flops`` uses the 2·M·N·K convention
    (operation count — complex dots are counted as one op per MAC)."""

    dtype: str
    out_shape: tuple
    contract: int

    @property
    def flops(self) -> int:
        return 2 * (prod(self.out_shape) if self.out_shape else 1) \
            * self.contract


@dataclass
class ComputationProfile:
    """Tallies for one HLO computation, with kLoop/kOutput fusions (and
    reduce appliers) flattened in.  Nested while loops are NOT folded in
    — their bodies run an unknown number of trips; they are listed in
    ``nested_whiles`` for the caller to resolve."""

    name: str
    collectives: list = field(default_factory=list)
    dots: list = field(default_factory=list)
    nested_whiles: list = field(default_factory=list)
    custom_calls: list = field(default_factory=list)  # target names

    @property
    def collective_count(self) -> int:
        return len(self.collectives)

    @property
    def collective_bytes(self) -> int:
        return sum(op.bytes for op in self.collectives)

    @property
    def dot_flops(self) -> int:
        return sum(op.flops for op in self.dots)


@dataclass
class ModuleProfile:
    """Whole-module view: the entry tallies plus the entry's while-loop
    bodies in program order (the staged factorization loops appear here
    one per stage)."""

    entry: ComputationProfile
    loops: list                      # [ComputationProfile], program order
    cost_flops: float | None = None  # cost_analysis(); while bodies ×1

    @property
    def step_loops(self):
        """The communicating while bodies, program order — the staged
        factorization loops.  (XLA's ScatterExpander also rewrites
        scatters into entry-level while loops on CPU; those carry no
        collectives and are filtered out here.)"""
        return [b for b in self.loops if b.collective_count > 0]

    def stepped_totals(self, trip_counts, bodies=None):
        """Combine per-body tallies with trip counts (e.g. from
        ``stage_bounds``): returns ``(collective_count, collective_bytes,
        dot_flops)`` over the whole run, entry included.  ``bodies``
        defaults to :attr:`step_loops`."""

        bodies = self.step_loops if bodies is None else bodies
        if len(trip_counts) != len(bodies):
            raise ValueError(
                f"{len(bodies)} loop bodies but {len(trip_counts)} "
                "trip counts")
        count = self.entry.collective_count
        nbytes = self.entry.collective_bytes
        flops = self.entry.dot_flops
        for trips, body in zip(trip_counts, bodies):
            count += trips * body.collective_count
            nbytes += trips * body.collective_bytes
            flops += trips * body.dot_flops
        return count, nbytes, flops

    @property
    def all_collectives(self):
        """Every collective in the module — entry plus each loop body
        (each body counted once; combine with trip counts yourself)."""
        ops = list(self.entry.collectives)
        for body in self.loops:
            ops += body.collectives
        return ops

    @property
    def max_collective_elems(self) -> int:
        """Largest collective result anywhere (the gather-everything
        smell test: must stay well below the full matrix)."""
        return max((op.elems for op in self.all_collectives), default=0)

    @property
    def custom_call_targets(self):
        """Every custom-call target in the module — entry plus each
        loop body (each body counted once)."""
        targets = list(self.entry.custom_calls)
        for body in self.loops:
            targets += body.custom_calls
        return targets

    def count_custom_calls(self, substr: str = "tpu_custom_call") -> int:
        """Custom-call census: how many custom-call instructions whose
        target contains ``substr`` the compiled module carries.  Pallas
        kernels lower to ``custom_call_target="tpu_custom_call"`` on
        TPU, so this pins "≤ N Pallas invocations" budgets on compiled
        HLO (see :func:`count_pallas_calls` for the interpret-mode /
        CPU equivalent at the jaxpr level)."""
        return sum(substr in t for t in self.custom_call_targets)


def _split_computations(hlo_text: str):
    """``{name: [instruction lines]}`` plus the entry computation name."""

    comps, entry = {}, None
    cur, lines = None, None
    for raw in hlo_text.splitlines():
        if cur is None:
            m = _COMP_HEAD_RE.match(raw)
            if m:
                cur, lines = m.group(2), []
                if m.group(1):
                    entry = cur
        elif raw.startswith("}"):
            comps[cur] = lines
            cur, lines = None, None
        else:
            lines.append(raw.strip())
    if entry is None and comps:
        # post-optimization dumps mark entry with "ENTRY"; fall back to
        # the last computation (HLO prints callees first)
        entry = list(comps)[-1]
    return comps, entry


def _tally(name, comps, cache):
    """ComputationProfile for ``name``, flattening fusion/apply calls
    but keeping nested whiles symbolic."""

    if name in cache:
        return cache[name]
    prof = ComputationProfile(name)
    cache[name] = prof
    for ln in comps.get(name, ()):
        wm = _WHILE_RE.search(ln)
        if wm:
            prof.nested_whiles.append(wm.group(1))
            continue
        cm = _COLLECTIVE_RE.search(ln)
        if cm:
            prof.collectives.append(CollectiveOp(
                kind=cm.group(3), dtype=cm.group(1),
                shape=_dims(cm.group(2))))
            continue    # a collective's to_apply region is scalar math
        ccm = _CUSTOM_CALL_RE.search(ln)
        if ccm:
            tm = _CC_TARGET_RE.search(ln)
            prof.custom_calls.append(tm.group(1) if tm else "?")
            continue
        dm = _DOT_RE.search(ln)
        if dm:
            ops = re.findall(_SHAPE_RE + r"\S* %", dm.group(3))
            contract = 1
            if ops:
                lhs_dims = _dims(ops[0][1])
                cdims = _dims(dm.group(4))
                contract = prod(lhs_dims[i] for i in cdims) if cdims else 1
            prof.dots.append(DotOp(dtype=dm.group(1),
                                   out_shape=_dims(dm.group(2)),
                                   contract=contract))
        for callee in _CALL_RE.findall(ln):
            if callee == name:
                continue
            sub = _tally(callee, comps, cache)
            prof.collectives += sub.collectives
            prof.dots += sub.dots
            prof.nested_whiles += sub.nested_whiles
            prof.custom_calls += sub.custom_calls
    return prof


def collective_byte_census(profile: ModuleProfile, trip_counts=None):
    """``{"count", "bytes", "by_kind"}`` over a whole run — the compiled
    module's collective traffic in the exact shape the attribution
    engine (``perf/attr.py``) joins as its ``collective_bytes`` input.

    With ``trip_counts`` (one per communicating while body, e.g. from
    ``stage_bounds``) each loop's tallies are multiplied out the same
    way :meth:`ModuleProfile.stepped_totals` does; without them every
    collective is counted once (loop-free programs, or a lower bound
    for stepped ones)."""
    by_kind: dict = {}
    count = 0

    def add(ops, mult=1):
        nonlocal count
        for op in ops:
            count += mult
            by_kind[op.kind] = by_kind.get(op.kind, 0) + mult * op.bytes

    if trip_counts is None:
        add(profile.all_collectives)
    else:
        bodies = profile.step_loops
        if len(trip_counts) != len(bodies):
            raise ValueError(
                f"{len(bodies)} loop bodies but {len(trip_counts)} "
                "trip counts")
        add(profile.entry.collectives)
        for trips, body in zip(trip_counts, bodies):
            add(body.collectives, trips)
    return {"count": count, "bytes": sum(by_kind.values()),
            "by_kind": by_kind}


def profile_hlo_text(hlo_text: str) -> ModuleProfile:
    """Parse compiled (post-optimization) HLO text into a
    :class:`ModuleProfile`."""

    comps, entry_name = _split_computations(hlo_text)
    cache = {}
    # entry tallied WITHOUT following while bodies (nested_whiles keeps
    # them); loop bodies tallied independently, in program order
    entry = _tally(entry_name, comps, cache)
    loops = [_tally(b, comps, dict()) for b in entry.nested_whiles]
    return ModuleProfile(entry=entry, loops=loops)


def profile_fn(fn, *args, static_argnums=None) -> ModuleProfile:
    """Lower + compile ``fn(*args)`` and profile the optimized HLO.
    ``fn`` may be jitted or plain (it is jitted here); the
    ``cost_analysis()`` flop figure rides along when available."""

    import jax

    jfn = fn if hasattr(fn, "lower") else \
        jax.jit(fn, static_argnums=static_argnums)
    compiled = jfn.lower(*args).compile()
    prof = profile_hlo_text(compiled.as_text())
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        prof.cost_flops = float(cost.get("flops", 0.0))
    except Exception:
        prof.cost_flops = None
    return prof


# ---------------------------------------------------------------------------
# StableHLO (pre-compile lowering) support — shard_map programs keep
# their collectives explicit at this level, but ops with reduction
# regions (all_reduce) print across several lines, so a line-based scan
# misses them; this scans the whole text.
# ---------------------------------------------------------------------------

_STABLE_RE = re.compile(
    r'"?stablehlo\.(all_reduce|all_gather|reduce_scatter|'
    r'collective_permute|all_to_all)"?.*?-> tensor<((?:[0-9]+x)*)'
    r'([a-z]+\d+|complex<f\d+>)>',
    re.S)


def stablehlo_collective_shapes(lowered_text: str):
    """``[(kind, elems)]`` for every collective in a StableHLO module,
    robust to the multi-line region form of ``all_reduce``."""

    out = []
    for m in _STABLE_RE.finditer(lowered_text):
        dims = [int(d) for d in m.group(2).split("x") if d]
        out.append((m.group(1), prod(dims) if dims else 1))
    return out


# ---------------------------------------------------------------------------
# Pallas-invocation census — the kernel-launch sibling of the collective
# budgets above.  On TPU a pallas_call compiles to ONE
# custom_call_target="tpu_custom_call" instruction, so
# ModuleProfile.count_custom_calls pins launch budgets off compiled HLO;
# in interpret mode (CPU CI) the kernel body is inlined at lowering and
# no custom call survives, so the same budget is pinned one level up, on
# the jaxpr, where the ``pallas_call`` primitive is present either way.
# tests/test_collective_profile.py uses this to guard the fused LU panel
# against regressing back into the r4 per-block call chain (64 kernel
# launches per factorization at n=8192/nb=512 vs one per panel step).
# ---------------------------------------------------------------------------


def _sub_jaxprs(params):
    """Every Jaxpr hiding in an eqn's params (call/branch/scan bodies),
    one level deep — `_count_primitive` recurses from there."""
    out = []

    def visit(v):
        if hasattr(v, "jaxpr") and hasattr(getattr(v, "jaxpr"), "eqns"):
            out.append(v.jaxpr)          # ClosedJaxpr
        elif hasattr(v, "eqns"):
            out.append(v)                # raw Jaxpr
        elif isinstance(v, (list, tuple)):
            for x in v:
                visit(x)

    for v in params.values():
        visit(v)
    return out


def _count_primitive(jaxpr, name: str) -> int:
    total = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == name:
            total += 1
        for sub in _sub_jaxprs(eqn.params):
            total += _count_primitive(sub, name)
    return total


def count_pallas_calls(fn, *args, static_argnums=None,
                       primitive: str = "pallas_call") -> int:
    """How many ``pallas_call`` invocations ``fn(*args)`` traces to,
    counted on the jaxpr (recursing through control-flow and call
    sub-jaxprs).  Platform-independent: the count is identical whether
    the kernels compile (TPU) or interpret (CPU CI), unlike the
    compiled-HLO custom-call census which only exists on TPU."""
    import jax

    closed = jax.make_jaxpr(
        fn, static_argnums=() if static_argnums is None
        else static_argnums)(*args)
    return _count_primitive(closed.jaxpr, primitive)
