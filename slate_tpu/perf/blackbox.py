"""Black-box flight recorder: a bounded ring of decision events plus
one-shot forensic failure bundles.

When a long run dies today — a health-gate trip, an ABFT ladder
escalation, an injected (or real) ``device_loss``, an r05-shaped
infra-failed bench — the context the process held at that moment
(recent autotune decisions, breaker state, fault-plan firings, step
timings) evaporates with it.  This module is the aircraft-style black
box the postmortem needs:

* **The ring.**  A process-wide, thread-safe, bounded ``deque`` of
  structured events recorded at every decision seam that already
  exists: autotune decide/quarantine
  (:mod:`slate_tpu.perf.autotune`), health verdicts and safe-backend
  retries (:mod:`slate_tpu.resilience.health`), ABFT ladder rungs
  (:mod:`slate_tpu.resilience.abft`), breaker transitions
  (:mod:`slate_tpu.resilience.breaker`), fault-plan firings
  (:mod:`slate_tpu.resilience.inject`), serve dispatch/deadline/
  backpressure (:mod:`slate_tpu.serve.queue` — serve events carry the
  PR 10 request trace ids), bench routine lifecycle (``bench.py``) and
  distributed step boundaries (:mod:`slate_tpu.resilience.checkpoint`
  and the measured timeline below).
* **The trigger ladder.**  On a trigger — health strict failure,
  autotune quarantine, ``device_loss``, breaker open/trip, bench
  watchdog/SIGTERM, or the opt-in excepthook — :func:`trigger` dumps
  ONE versioned forensic bundle: ring contents +
  ``metrics.snapshot()`` + knob/config state + an autotune table
  digest + the active ``FaultPlan``'s replay log + python/jax/platform
  keys.  Bundles render with the stdlib-only, by-path-loadable
  ``tools/blackbox.py`` CLI.
* **The measured distributed timeline.**  ``SLATE_TPU_DIST_TIMELINE=1``
  drives ``pgetrf``/``ppotrf`` through their chunked step-window
  builders one window at a time
  (:func:`slate_tpu.parallel.dist_util.run_timeline`), recording
  per-step host walls + per-step collective byte deltas as ring events
  and ``trace.Block`` Perfetto spans — the measured compute signal
  ``dist_util.overlap_summary`` feeds the MULTICHIP overlap blocks
  with, replacing the "fully exposed" roofline guess.

**Off-by-default, the PR 4 no-op contract**: every recording entry
point checks one attribute (``_rec.enabled``) and returns; nothing
here ever touches a traced program, so compiled executables stay
bit-identical whatever the knobs (pinned in
``tests/test_backend_registry.py``).  Importing this module starts no
threads, opens no files and installs no hooks.

Env knobs (all unset by default):

* ``SLATE_TPU_BLACKBOX=1`` — enable the recorder (ring + triggers).
* ``SLATE_TPU_BLACKBOX_RING`` — ring capacity in events (default 512).
* ``SLATE_TPU_BLACKBOX_DIR`` — bundle directory (default: the system
  temp dir).
* ``SLATE_TPU_BLACKBOX_MAX_DUMPS`` — per-process bundle cap (default
  8); past it triggers record but stop dumping.
* ``SLATE_TPU_BLACKBOX_EXCEPTHOOK=1`` — dump a bundle from an
  uncaught exception (installed lazily at the first recorded event or
  :func:`on`, never at import).
* ``SLATE_TPU_DIST_TIMELINE=1`` — measured per-step distributed
  timelines (see above); ``SLATE_TPU_DIST_TIMELINE_WINDOW`` sets the
  steps per measured window (default 1 — one wall/byte sample per
  factorization step; larger windows amortize the chunked re-dispatch
  cost).
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import sys
import threading
import time
from collections import deque

from . import metrics

__all__ = [
    "ENV_BLACKBOX", "ENV_DIR", "ENV_EXCEPTHOOK", "ENV_MAX_DUMPS",
    "ENV_RING", "ENV_TIMELINE", "ENV_TIMELINE_WINDOW", "SCHEMA",
    "dump", "enabled", "events", "install_excepthook", "last_bundle",
    "off", "on", "record", "reset", "ring_size", "timeline_wanted",
    "timeline_window", "trigger",
]

ENV_BLACKBOX = "SLATE_TPU_BLACKBOX"
ENV_RING = "SLATE_TPU_BLACKBOX_RING"
ENV_DIR = "SLATE_TPU_BLACKBOX_DIR"
ENV_MAX_DUMPS = "SLATE_TPU_BLACKBOX_MAX_DUMPS"
ENV_EXCEPTHOOK = "SLATE_TPU_BLACKBOX_EXCEPTHOOK"
ENV_TIMELINE = "SLATE_TPU_DIST_TIMELINE"
ENV_TIMELINE_WINDOW = "SLATE_TPU_DIST_TIMELINE_WINDOW"

#: bundle schema identity — bump on incompatible layout changes so the
#: CLI can refuse bundles it does not understand under ``--strict``
SCHEMA = "slate_tpu.blackbox/1"

_DEFAULT_RING = 512
_DEFAULT_MAX_DUMPS = 8
_dump_seq = itertools.count()


def _env_int(name: str, default: int, lo: int = 1) -> int:
    try:
        return max(lo, int(os.environ.get(name, "").strip() or default))
    except ValueError:
        return default


class _Recorder:
    """The process-wide ring.  Private — use the module facade (the
    registry-guard test forbids ``blackbox._*`` / ``_ring`` access
    outside perf/)."""

    def __init__(self):
        self.enabled = metrics.env_flag(ENV_BLACKBOX)
        # RLock (like the metrics registry): bench's SIGTERM handler
        # dumps a bundle from a signal frame that may have interrupted
        # the SAME thread inside a recorder critical section — a plain
        # Lock would self-deadlock and eat the artifact's LAST-line
        # aggregate flush
        self.lock = threading.RLock()
        self.ring: deque = deque(maxlen=_env_int(ENV_RING, _DEFAULT_RING))
        self.dumps = 0
        self.last: dict | None = None


_rec = _Recorder()

#: lazily install the excepthook on the first recorded event when the
#: env opts in (never at import — the inert-at-import guard)
_hook_wanted = [metrics.env_flag(ENV_EXCEPTHOOK)]
_prev_hook: list = [None]


# ---------------------------------------------------------------------------
# Lifecycle
# ---------------------------------------------------------------------------

def enabled() -> bool:
    return _rec.enabled


def on(ring: int | None = None) -> None:
    """Enable the recorder (optionally resizing the ring); installs the
    excepthook when ``SLATE_TPU_BLACKBOX_EXCEPTHOOK`` opts in."""
    rec = _rec
    if ring is not None and int(ring) != rec.ring.maxlen:
        with rec.lock:
            rec.ring = deque(rec.ring, maxlen=max(1, int(ring)))
    rec.enabled = True
    if _hook_wanted[0]:
        install_excepthook()


def off() -> None:
    _rec.enabled = False


def reset() -> None:
    """Drop every recorded event and the dump bookkeeping (the enabled
    flag is left as is) — test/bench isolation."""
    rec = _rec
    with rec.lock:
        rec.ring.clear()
        rec.dumps = 0
        rec.last = None


def ring_size() -> int:
    return int(_rec.ring.maxlen or 0)


# ---------------------------------------------------------------------------
# Recording
# ---------------------------------------------------------------------------

def record(kind: str, **fields) -> None:
    """Append one structured event to the ring.  ONE attribute read and
    out when the recorder is off — cheap enough for every decision seam
    to call unconditionally."""
    rec = _rec
    if not rec.enabled:
        return
    if _hook_wanted[0]:
        install_excepthook()
    ev = {"t": time.time(), "kind": str(kind)}
    ev.update(fields)
    with rec.lock:
        rec.ring.append(ev)


def events() -> list:
    """A copy of the ring, oldest first."""
    with _rec.lock:
        return [dict(e) for e in _rec.ring]


# ---------------------------------------------------------------------------
# Distributed-timeline knobs (read here so the parallel/ layer keeps
# its no-raw-env-reads guard; consumed by dist_util.run_timeline and
# the pgetrf/ppotrf drivers)
# ---------------------------------------------------------------------------

def timeline_wanted() -> bool:
    """The ``SLATE_TPU_DIST_TIMELINE=1`` opt-in: drive pgetrf/ppotrf
    through their chunked step-window builders and measure per-step
    walls + collective byte deltas (read per call so tests can
    monkeypatch the environment)."""
    return metrics.env_flag(ENV_TIMELINE)


def timeline_window() -> int:
    """Steps per measured window (``SLATE_TPU_DIST_TIMELINE_WINDOW``,
    default 1 — one sample per factorization step)."""
    return _env_int(ENV_TIMELINE_WINDOW, 1)


# ---------------------------------------------------------------------------
# Bundle assembly — every section individually guarded: a forensic
# dump must never raise out of a recovery path, and must never IMPORT
# heavyweight modules the process had not already loaded (reading
# versions off sys.modules keeps a dump cheap and side-effect-free).
# ---------------------------------------------------------------------------

def _host_info() -> dict:
    info = {"python": sys.version.split()[0], "platform": sys.platform,
            "pid": os.getpid(), "argv0": sys.argv[0] if sys.argv else ""}
    for mod in ("jax", "jaxlib", "numpy"):
        m = sys.modules.get(mod)
        if m is not None:
            info[mod] = str(getattr(m, "__version__", "?"))
    return info


def _knob_state() -> dict:
    keep = {k: v for k, v in os.environ.items()
            if k.startswith("SLATE_TPU_")}
    for k in ("JAX_PLATFORMS", "XLA_FLAGS"):
        if k in os.environ:
            keep[k] = os.environ[k]
    return dict(sorted(keep.items()))


def _config_state() -> dict:
    cfg = sys.modules.get("slate_tpu.config")
    if cfg is None:
        return {}
    return {"use_pallas": cfg.use_pallas_mode(),
            "f64_mxu": cfg.f64_mxu_mode(),
            "scattered_lu": cfg.scattered_lu_mode(),
            "matmul_precision": str(cfg.matmul_precision),
            "default_block_size": int(cfg.default_block_size)}


def _autotune_digest() -> dict:
    """Compact identity of the live decision table: per-site counts and
    a content hash — enough for a postmortem to say WHICH table state a
    failure happened under without shipping the whole table.  Only
    reads a table that already exists (never constructs one)."""
    at = sys.modules.get("slate_tpu.perf.autotune")
    tab = getattr(at, "_table", None) if at is not None else None
    if tab is None:
        return {"decisions": 0}
    dec = dict(tab.decisions)
    sites: dict = {}
    lines = []
    for key in sorted(dec):
        info = dec[key] or {}
        site = key.split("|", 1)[0]
        sites[site] = sites.get(site, 0) + 1
        lines.append("%s=%s:%s" % (key, info.get("backend"),
                                   info.get("source")))
    sha = hashlib.sha1("\n".join(lines).encode()).hexdigest()[:12]
    return {"decisions": len(dec), "sites": sites, "sha1": sha,
            "quarantined": sum(len(v) for v in
                               getattr(tab, "quarantine", {}).values())}


def _fault_plan_state() -> dict | None:
    inj = sys.modules.get("slate_tpu.resilience.inject")
    if inj is None:
        return None
    plan = inj.get_plan()
    if plan is None:
        return None
    return {"seed": plan.seed,
            "specs": [{"site": s.site, "kind": s.kind, "rate": s.rate,
                       "count": s.count}
                      for s in plan.specs.values()],
            "fired": plan.fired(),
            "log": [{"site": s, "index": i, "kind": k}
                    for s, i, k in plan.log[-200:]]}


def _section(fn):
    try:
        return fn()
    except Exception as e:          # a dump must never break a recovery
        return {"error": "%s: %s" % (type(e).__name__, e)}


def _assemble(reason: str, detail: str) -> dict:
    return {
        "schema": SCHEMA,
        "created": time.time(),
        "trigger": {"reason": str(reason), "detail": str(detail)[:500],
                    "t": time.time()},
        "host": _section(_host_info),
        "knobs": _section(_knob_state),
        "config": _section(_config_state),
        "autotune": _section(_autotune_digest),
        "fault_plan": _section(_fault_plan_state),
        "metrics": _section(metrics.snapshot),
        "events": events(),
    }


def dump(reason: str, detail: str = "", path: str | None = None):
    """Write one forensic bundle NOW (ignores the per-process cap —
    harnesses that want a bundle on demand).  Returns
    ``{"path", "digest", "reason"}`` or None when the recorder is off
    or the write failed."""
    rec = _rec
    if not rec.enabled:
        return None
    try:
        blob = _assemble(reason, detail)
        text = json.dumps(blob, default=str)
        digest = hashlib.sha256(text.encode()).hexdigest()[:16]
        if path is None:
            d = os.environ.get(ENV_DIR, "").strip()
            if not d:
                import tempfile

                d = tempfile.gettempdir()
            os.makedirs(d, exist_ok=True)
            # ms timestamp + pid alone can collide when two triggers
            # fire within the same millisecond — a process-wide
            # sequence number keeps every bundle filename distinct
            # (itertools.count is atomic under the GIL)
            path = os.path.join(
                d, "slate_tpu_blackbox_%d_%d_%d.json"
                % (int(time.time() * 1e3), os.getpid(), next(_dump_seq)))
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except Exception:
        metrics.inc("blackbox.dump_errors")
        return None
    info = {"path": path, "digest": digest, "reason": str(reason)}
    with rec.lock:
        rec.dumps += 1
        rec.last = info
    metrics.inc("blackbox.dumps")
    return info


def trigger(reason: str, detail: str = ""):
    """One rung of the trigger ladder: record the trigger event and —
    while under the per-process dump cap — write the bundle.  Returns
    the :func:`dump` info dict (None when off, capped, or failed)."""
    rec = _rec
    if not rec.enabled:
        return None
    record("trigger", reason=str(reason), detail=str(detail)[:500])
    metrics.inc("blackbox.trigger." + str(reason).replace(" ", "_"))
    with rec.lock:
        capped = rec.dumps >= _env_int(ENV_MAX_DUMPS, _DEFAULT_MAX_DUMPS)
    if capped:
        return None
    return dump(reason, detail)


def last_bundle():
    """The most recent bundle's ``{"path", "digest", "reason"}`` (None
    when no dump has happened) — lets a late failure line point at an
    earlier postmortem once the dump cap is hit."""
    with _rec.lock:
        return dict(_rec.last) if _rec.last else None


# ---------------------------------------------------------------------------
# Opt-in excepthook
# ---------------------------------------------------------------------------

def install_excepthook() -> None:
    """Chain a bundle dump into ``sys.excepthook`` (idempotent; the
    previous hook always runs).  Installed lazily — never at import —
    by :func:`on`/:func:`record` when ``SLATE_TPU_BLACKBOX_EXCEPTHOOK``
    opts in, or explicitly by a harness."""
    _hook_wanted[0] = False
    if _prev_hook[0] is not None:
        return
    prev = sys.excepthook
    _prev_hook[0] = prev

    def hook(tp, val, tb):
        try:
            trigger("excepthook", "%s: %s" % (tp.__name__, val))
        finally:
            prev(tp, val, tb)

    sys.excepthook = hook
