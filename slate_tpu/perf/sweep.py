"""Offline autotune sweep engine, cost-model decision tables, and the
versioned warm-start bundle (Autotune v2).

Runtime first-use probing is a cold-start tax a production replica
serving millions of users cannot pay, and pow2 buckets multiply the
probe count.  SLATE itself ships tuned tile-size defaults instead of
probing at run time, and the tile-granularity literature
(Design-in-Tiles, BLASX — PAPERS.md) shows analytical models can select
near-optimal configurations without exhaustive timing.  This module
connects the two halves the library already owns — the persisted
timing table (:mod:`.autotune`) and the analytical roofline
(:mod:`.attr`) — into an OFFLINE layer between measurement and
dispatch:

1. **Enumerate** the candidate space per autotune site — backend,
   fusion depth, nb, batch-per-launch — across a shape/dtype grid
   (:data:`GRIDS`, or a custom spec through ``tools/sweep.py``).
2. **Prune analytically before any clock starts**: every candidate is
   priced with :func:`slate_tpu.perf.attr.predict_seconds` (roofline
   minima + launch latency per materialized HBM round trip); a
   candidate beyond a configurable ``margin`` of the predicted best is
   SKIPPED, and the skip is recorded with its predicted gap so the
   pruning is auditable (``bundle["pruned"]``).
3. **Time the survivors** through the existing
   :meth:`~slate_tpu.perf.autotune.AutotuneTable.decide` machinery
   (``force_timing=True`` on a sweep-private table) with resumable
   checkpointing and the classified-infra retry from
   :mod:`slate_tpu.resilience.retry`.
4. **Fit an interpolating decision model** — piecewise (inverse-
   distance-blended nearest neighbors) over the pow2 key lattice in
   log2 space — so shapes the sweep never timed still resolve
   probe-free.  Selection is cross-checked against the analytical
   model: a candidate the roofline prices more than
   :data:`MODEL_GUARD`× the predicted best at the query shape can
   never be selected by interpolation.

The output is ONE **versioned warm-start bundle**: the decision table,
the fitted model, AOT bucket specs for
:func:`slate_tpu.serve.warm_start`, the pruning log, and the
jax/jaxlib/platform/libtpu version key.  A serving replica boots with
``SLATE_TPU_AUTOTUNE_BUNDLE=<path>``; :mod:`.autotune` consumes it as
the first-priority source (forced pin → quarantine filter → bundle →
cached timing → interpolating model → runtime probe fallback), with
resilience quarantine events masking bundle entries the same way they
mask cached winners.

STDLIB-ONLY AT IMPORT, like ``regress.py``/``attr.py``: bundle loading
and model evaluation must work in any process (and never start
exporters or probes — registry-guard pinned); jax and the kernel
layers are imported lazily inside the sweep-execution functions only.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import tempfile
from typing import Callable, Dict, List, NamedTuple, Optional

__all__ = [
    "BUNDLE_ENV", "BUNDLE_FORMAT", "GRIDS", "MODEL_GUARD", "SITES",
    "SiteSpec", "build_bundle", "bundle_digest", "key_str",
    "model_backend", "model_fit", "pow2_bucket", "predict_times",
    "profile_signals", "prune", "read_bundle", "run_sweep",
    "set_profile_signals", "split_key", "warm_specs_from_results",
    "write_bundle",
]

#: env var naming the active bundle file (consumed by perf/autotune.py)
BUNDLE_ENV = "SLATE_TPU_AUTOTUNE_BUNDLE"

#: bundle schema version; a reader rejects files it does not speak
BUNDLE_FORMAT = 1

#: analytical-rejection factor for the interpolating model: a candidate
#: the roofline model prices more than this many times the predicted
#: best at the QUERY shape can never be selected by interpolation
#: (pinned in tests/test_sweep.py)
MODEL_GUARD = 10.0


def pow2_bucket(d, floor: int = 8) -> int:
    """Next power of two ≥ d (with a floor) — THE one shared bucketing
    helper: autotune decision keys (``autotune._bucket_dim``), serve
    executable-bucket keys (``serve.queue._bucket``) and the sweep grid
    keys all derive from this function, so the three layers can never
    drift apart (agreement pinned in tests/test_sweep.py)."""
    return max(int(floor), 1 << (max(1, int(d)) - 1).bit_length())


def key_str(op: str, key_parts) -> str:
    """The canonical decision-key string ``"op|part,part,..."`` shared
    with the autotune table."""
    return op + "|" + ",".join(str(p) for p in key_parts)


def split_key(key_parts):
    """Split a decision key into ``(log2 coords, ctx)``: integer parts
    become log2 coordinates (the pow2 lattice the model interpolates
    over), string parts (dtype, precision) join into the exact-match
    context."""
    coords, ctx = [], []
    for p in key_parts:
        if isinstance(p, bool):
            ctx.append(str(p))
        elif isinstance(p, (int, float)):
            coords.append(math.log2(max(1.0, float(p))))
        else:
            ctx.append(str(p))
    return coords, ",".join(ctx)


def _attr():
    """The roofline pricing engine (``perf/attr.py``) — imported the
    dual-life way (package-relative, else by file path) so the bundle
    side of this module keeps working when loaded standalone on a
    jax-free machine, exactly like ``regress.py`` does."""
    try:
        from . import attr
        return attr
    except ImportError:
        import importlib.util
        import sys
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "attr.py")
        name = "_slate_tpu_attr"
        if name in sys.modules:
            return sys.modules[name]
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
        return mod


def _xprof():
    """The device-truth profiling layer (``perf/xprof.py``) — loaded
    the same dual-life way as :func:`_attr` so ``run_sweep(profile=
    <path>)`` can read a capture artifact on a jax-free machine (the
    parser half of xprof is stdlib-only)."""
    try:
        from . import xprof
        return xprof
    except ImportError:
        import importlib.util
        import sys
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "xprof.py")
        name = "_slate_tpu_xprof"
        if name in sys.modules:
            return sys.modules[name]
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
        return mod


# ---------------------------------------------------------------------------
# Candidate pricing (the analytical pre-prune)
# ---------------------------------------------------------------------------

#: measured compute signals the pricing functions consult (ROADMAP
#: 5(b)): ``{"digest", "launch_s", "stages", ...}`` distilled from a
#: captured xprof profile / PR 15 timeline rows by
#: ``xprof.signals_from``.  Installed for the duration of one
#: ``run_sweep(profile=...)`` call (try/finally) — None means
#: roofline-only pricing, the pre-ISSUE-19 behavior.
_PROFILE_SIGNALS: list = [None]


def set_profile_signals(sig) -> None:
    """Install (or clear, with None) the measured pricing signals —
    see :data:`_PROFILE_SIGNALS`."""
    _PROFILE_SIGNALS[0] = dict(sig) if isinstance(sig, dict) else None


def profile_signals():
    """The active measured pricing signals dict, or None."""
    return _PROFILE_SIGNALS[0]


def _measured_launch_s():
    """The measured per-dispatch exposed-overhead signal (seconds), or
    None when pricing is roofline-only."""
    sig = _PROFILE_SIGNALS[0]
    if isinstance(sig, dict):
        v = sig.get("launch_s")
        if isinstance(v, (int, float)) and v > 0:
            return float(v)
    return None

_SHORT_DTYPE = {"float32": "fp32", "float64": "fp64", "bfloat16": "bf16",
                "complex64": "c64", "complex128": "c128"}

#: effective slice-pass multiplier of the Ozaki int8-split fp64 matmul
#: vs one bf16 MXU pass (compute AND operand traffic)
_OZAKI_PASSES = 6.0

#: bf16-pass multipliers of the fp32 split gemm (ops/split_gemm.py):
#: bf16x3 is one K-folded 3k-length dot, bf16x6 keeps the three
#: slice-pair diagonals (k + 2k + 3k)
_SPLIT3_PASSES = 3.0
_SPLIT6_PASSES = 6.0


def _short(dt) -> str:
    return _SHORT_DTYPE.get(str(dt), "fp32")


def _fusion_predict(routine: str, dims_of: Callable, fusion_of: dict):
    """Pricing for sites whose candidates are FUSION DEPTHS of one
    routine (or driver/backend pairs that map onto depths): each name
    is priced as :func:`attr.predict_seconds` at its fusion, so the
    materialized-round-trip term is what separates them.  An unknown
    candidate name (or a missing stage model) disables pruning for the
    whole unit — the sweep must never skip what it cannot price.  When
    a captured profile installed measured signals
    (:func:`set_profile_signals`), the measured per-dispatch overhead
    replaces the default launch constant — the term that separates
    fusion rungs at small shapes is then an observation."""
    def predict(key_parts, names, platform):
        dims, dt = dims_of(key_parts)
        a = _attr()
        out = {}
        for name in names:
            f = fusion_of.get(name)
            if f is None:
                return {}
            t = a.predict_seconds(routine, dims, dt, fusion=f,
                                  platform=platform,
                                  launch_s=_measured_launch_s())
            if t is None:
                return {}
            out[name] = t
        return out
    return predict


def _dims_mnnb(key_parts):
    m, n, nb = (int(x) for x in key_parts[:3])
    return {"m": m, "n": n, "nb": nb}, _short(key_parts[3])


def _dims_nnb(key_parts):
    n, nb = int(key_parts[0]), int(key_parts[1])
    return {"n": n, "nb": nb}, _short(key_parts[2])


def _dims_batched(key_parts):
    b, n = int(key_parts[0]), int(key_parts[1])
    # the grid kernels hold whole problems VMEM-resident on an ib=32
    # block grid; the vmapped composition steps nb=32 panels through HBM
    return {"n": n, "b": b, "nb": min(n, 32)}, _short(key_parts[2])


def _predict_matmul(key_parts, names, platform):
    """Backend pricing for the 2-D product site: XLA and Pallas run the
    same MXU pass (indistinguishable analytically — neither is ever
    pruned against the other); the Ozaki fp64 split pays
    :data:`_OZAKI_PASSES` bf16-grade passes vs XLA's software-emulated
    fp64 peak, and the fp32 bf16x3/bf16x6 splits pay
    :data:`_SPLIT3_PASSES` / :data:`_SPLIT6_PASSES` bf16 passes vs the
    stock fp32 dot — the matmul choices the model CAN separate.  The
    bf16 lane reads ``SLATE_TPU_PEAK_TFLOPS_BF16`` via
    :func:`attr.peaks`, so an operator who pins the measured bf16 peak
    re-prices the split against the real emulated-fp32 ceiling."""
    m, k, n = (int(x) for x in key_parts[:3])
    dt = _short(key_parts[3])
    a = _attr()
    fl = 2.0 * m * k * n
    isz = {"fp64": 8, "c64": 8, "c128": 16, "bf16": 2}.get(dt, 4)
    by = (m * k + k * n + 2.0 * m * n) * isz
    passes = {"ozaki": _OZAKI_PASSES, "split3": _SPLIT3_PASSES,
              "split6": _SPLIT6_PASSES}
    out = {}
    for name in names:
        if name in passes:
            pk = a.peaks(platform, "bf16")
            t = (fl * passes[name] / (pk["tflops"] * 1e12)
                 + by * passes[name] / (pk["hbm_gbs"] * 1e9))
        else:
            pk = a.peaks(platform, dt)
            t = max(fl / (pk["tflops"] * 1e12),
                    by / (pk["hbm_gbs"] * 1e9))
        out[name] = t
    return out


#: representative local block-row count per mesh row for pricing the
#: distributed panel broadcast (the dist_chunk key carries no matrix
#: height — relative candidate ordering only needs a typical panel)
_CHUNK_ROWS_PER_DEV = 8


def _predict_dist_chunk(key_parts, names, platform):
    """ICI-roofline pricing for the ``dist_chunk`` site (ISSUE 13):
    splitting the fused (M, nb) panel broadcast into ``c`` pipelined
    slices exposes roughly ``wire/c`` seconds of fabric time (the
    first slice; the rest hide under the trailing MXU contraction) but
    pays one collective dispatch latency PER slice — predicted exposed
    ≈ c·launch + wire/c, minimized near c* = √(wire/launch).  Wire
    time uses :func:`attr.peaks`' ``ici_gbs`` with a representative
    panel height (:data:`_CHUNK_ROWS_PER_DEV` block rows per mesh
    row); the key carries no matrix size, so this prices candidate
    ORDER per (mesh, nb, dtype), which is all pruning needs.  A
    measured ``launch_s`` signal (:func:`set_profile_signals`) moves
    the optimum c* = √(wire/launch) — the slice count is then tuned
    against observed exposure, not the launch constant."""
    if len(key_parts) < 4:
        return {}
    _op, p, q, nb = key_parts[:4]
    dt = key_parts[4] if len(key_parts) > 4 else "float32"
    a = _attr()
    p, q, nb = int(p), int(q), int(nb)
    isz = {"float64": 8, "complex64": 8, "complex128": 16,
           "bfloat16": 2}.get(str(dt), 4)
    m = _CHUNK_ROWS_PER_DEV * p * nb
    wire = m * nb * isz / (a.peaks(platform)["ici_gbs"] * 1e9)
    launch = _measured_launch_s() \
        or a._DEF_LAUNCH_S.get(platform, a._DEF_LAUNCH_S["tpu"])
    out = {}
    for name in names:
        try:
            c = 1 if name == "whole" else int(name)
        except ValueError:
            return {}
        out[name] = c * launch + wire / max(1, c)
    return out


def _predict_dist_lookahead(key_parts, names, platform):
    """Exposure pricing for the ``dist_lookahead`` site: a depth-D
    panel ring overlaps the broadcasts for steps k+1..k+D with the
    step-k trailing contraction — exposed wire shrinks as
    ``max(0, wire − D·budget)`` — but pays D−1 redundant rank-nb
    corrections (replicated compute, zero extra collectives) plus
    their dispatch per step::

        t(D) = max(0, wire − D·budget) + (D−1)·(redund + launch)

    ``budget`` is the per-device trailing-update roofline at a
    representative window (the trailing width the ``nt`` key carries),
    ``launch`` the per-dispatch overhead — the MEASURED signal when a
    profile installed one, which is exactly where a timeline-informed
    bundle flips the depth a roofline-only bundle picks."""
    if len(key_parts) < 4:
        return {}
    _op, nt, nb = key_parts[:3]
    dt = key_parts[3] if len(key_parts) > 3 else "float32"
    a = _attr()
    nt, nb = int(nt), int(nb)
    pk = a.peaks(platform)
    isz = {"float64": 8, "complex64": 8, "complex128": 16,
           "bfloat16": 2}.get(str(dt), 4)
    m = _CHUNK_ROWS_PER_DEV * nb
    t_w = max(1, nt - 1) * nb
    wire = m * nb * isz / (pk["ici_gbs"] * 1e9)
    budget = 2.0 * m * nb * t_w / (pk["tflops"] * 1e12)
    redund = 2.0 * m * nb * nb / (pk["tflops"] * 1e12)
    launch = _measured_launch_s() \
        or a._DEF_LAUNCH_S.get(platform, a._DEF_LAUNCH_S["tpu"])
    out = {}
    for name in names:
        try:
            d = int(name)
        except ValueError:
            return {}
        out[name] = (max(0.0, wire - d * budget)
                     + (d - 1) * (redund + launch))
        if out[name] <= 0.0:
            out[name] = 1e-12           # depth 1 fully hidden: keep > 0
    return out


def predict_times(site: str, key_parts, names, platform: str = "tpu"
                  ) -> dict:
    """Model-predicted seconds per candidate for one sweep unit (or a
    model-guard query).  ``{}`` when the site has no pricing — an
    unpriced unit is never pruned and never guard-filtered."""
    spec = SITES.get(site)
    if spec is None:
        return {}
    try:
        return dict(spec.predict(tuple(key_parts), list(names),
                                 platform) or {})
    except Exception:
        return {}


def prune(predicted: dict, names, margin: float):
    """Split candidates into ``(survivors, pruned)`` on the analytical
    prediction: a candidate priced more than ``margin`` (fractional)
    above the predicted best is skipped before a single timing rep
    runs.  Each pruned entry carries ``predicted_s`` /
    ``best_predicted_s`` / ``predicted_gap`` so the skip is auditable.
    With any candidate unpriced (or fewer than two candidates) nothing
    is pruned; the predicted best always survives."""
    names = list(names)
    if len(names) < 2 or any(not isinstance(predicted.get(n2), (int, float))
                             or predicted[n2] <= 0 for n2 in names):
        return names, []
    best = min(predicted[n2] for n2 in names)
    survivors, dropped = [], []
    for n2 in names:
        if predicted[n2] <= best * (1.0 + float(margin)):
            survivors.append(n2)
        else:
            dropped.append({
                "candidate": n2,
                "predicted_s": round(predicted[n2], 9),
                "best_predicted_s": round(best, 9),
                "predicted_gap": round(predicted[n2] / best, 3),
            })
    return survivors, dropped


# ---------------------------------------------------------------------------
# Site specs: candidate builders + pricing, one per swept autotune site
# ---------------------------------------------------------------------------

class SiteSpec(NamedTuple):
    """One sweepable autotune site.

    ``build(unit)`` (jax-side, imported lazily) returns ``(key_parts,
    [Candidate, ...])`` with the SAME key derivation the runtime
    chooser uses — a drifting key would write bundle entries dispatch
    can never hit.  ``predict(key_parts, names, platform)`` returns
    model-predicted seconds per candidate (``{}`` = unpriceable)."""

    build: Callable
    predict: Callable


def _build_matmul(u):
    from . import autotune as at
    import jax.numpy as jnp

    from .. import config

    dt = jnp.dtype(u.get("dtype", "float32"))
    m, k, n = (at._bucket_dim(int(u[d])) for d in ("m", "k", "n"))
    key = (m, k, n, dt.name, at._precision_name())
    probes: dict = {}

    def _ab():
        return at._memo(probes, "ab", lambda: (at._randn((m, k), dt, 0),
                                               at._randn((k, n), dt, 1)))

    if dt == jnp.float64:
        def setup_ozaki():
            from ..ops.ozaki import matmul_f64

            return at._timed_call(matmul_f64, *_ab())

        def setup_xla():
            return at._timed_call(
                lambda x, y: jnp.matmul(x, y,
                                        precision=config.matmul_precision),
                *_ab())

        return key, [at.Candidate("ozaki", setup_ozaki),
                     at.Candidate("xla", setup_xla)]

    def setup_pallas():
        from ..ops.pallas_kernels import matmul as pallas_matmul

        def blk(dim, pref):
            return pref if dim % pref == 0 else 128

        return at._timed_call(
            lambda x, y: pallas_matmul(x, y, bm=blk(m, 256), bn=blk(n, 256),
                                       bk=blk(k, 512)), *_ab())

    def setup_xla32():
        return at._timed_call(
            lambda x, y: jnp.matmul(x, y, precision=config.matmul_precision),
            *_ab())

    cands = [at.Candidate("xla", setup_xla32),
             at.Candidate("pallas", setup_pallas)]
    if dt == jnp.float32:
        # the bf16x3/bf16x6 split candidates (same runtime candidate
        # set choose_matmul probes, same key) so the warm-start bundle
        # can pin a split winner for the zero-probe replica boot
        def setup_split3():
            from ..ops.split_gemm import matmul_split3

            return at._timed_call(matmul_split3, *_ab())

        def setup_split6():
            from ..ops.split_gemm import matmul_split6

            return at._timed_call(matmul_split6, *_ab())

        cands += [at.Candidate("split3", setup_split3),
                  at.Candidate("split6", setup_split6)]
    return key, cands


def _build_lu_step(u):
    from . import autotune as at
    import jax.numpy as jnp

    m, n, nb = int(u["m"]), int(u["n"]), int(u["nb"])
    dt = jnp.dtype(u.get("dtype", "float32"))
    key = (m, n, nb, dt.name, at._precision_name())
    probes: dict = {}

    def _a():
        return at._memo(probes, "a", lambda: at._randn((m, n), dt, 12))

    def _setup(depth):
        from ..linalg.lu import getrf_scattered

        return at._timed_call(
            lambda x: getrf_scattered(x, nb, step=depth), _a())

    def check(out):
        return at._lu_factor_residual_ok(out, _a(), m, n, dt)

    from ..linalg.lu import _use_full_fused, _use_fused_step

    depths = at._lu_step_depths(_use_fused_step(m, n, nb, dt),
                                _use_full_fused(m, n, nb, dt))
    return key, [at.Candidate(d, (lambda d=d: _setup(d)), check)
                 for d in depths]


def _build_potrf_step(u):
    from . import autotune as at
    import jax.numpy as jnp

    n, nb = int(u["n"]), int(u["nb"])
    dt = jnp.dtype(u.get("dtype", "float32"))
    key = (n, nb, dt.name, at._precision_name())
    probes: dict = {}

    def _spd():
        return at._memo(probes, "spd", lambda: at._spd_probe(n, dt))

    def _setup(depth):
        fn = at._potrf_step_driver(depth)
        return at._timed_call(lambda x: fn(x, nb), _spd())

    def check(out):
        return at._potrf_guard(_spd(), out, 3.0)

    from ..ops.blocks import use_full_potrf, use_fused_potrf_step

    depths = at._potrf_step_depths(use_fused_potrf_step(n, nb, dt),
                                   use_full_potrf(n, nb, dt))
    return key, [at.Candidate(d, (lambda d=d: _setup(d)), check)
                 for d in depths]


def _build_lu_driver(u):
    from . import autotune as at
    import jax.numpy as jnp

    m, n, nb = int(u["m"]), int(u["n"]), int(u["nb"])
    dt = jnp.dtype(u.get("dtype", "float32"))
    key = (m, n, nb, dt.name, at._precision_name())
    probes: dict = {}

    def _a():
        return at._memo(probes, "a", lambda: at._randn((m, n), dt, 8))

    def setup_scattered():
        from ..linalg.lu import getrf_scattered

        return at._timed_call(lambda x: getrf_scattered(x, nb), _a())

    def setup_rec():
        from ..linalg.lu import getrf_rec

        return at._timed_call(lambda x: getrf_rec(x, nb), _a())

    def check(out):
        return at._lu_factor_residual_ok(out, _a(), m, n, dt)

    return key, [at.Candidate("rec", setup_rec, check),
                 at.Candidate("scattered", setup_scattered, check)]


def _build_ooc(u):
    """Sweep unit for the out-of-core residency site (ISSUE 17): time
    the in-core blocked recursion against the host-DRAM tile pool at
    the SAME key ``choose_ooc`` derives.  Both candidates share one
    diag-dominant probe and the LU factor residual gate; at sweepable
    dims the pool pays pure PCIe overhead — in-core should win, and a
    bundle that says otherwise is auditable evidence the host path
    regressed.  The tiny forced window (capacity 4) makes the CPU
    smoke sweep exercise eviction + write-back, not just residency."""
    from . import autotune as at
    import jax.numpy as jnp

    n, nb = int(u["n"]), int(u["nb"])
    dt = jnp.dtype(u.get("dtype", "float32"))
    key = (n, nb, dt.name, at._precision_name())
    probes: dict = {}

    def _a():
        def mk():
            return at._randn((n, n), dt, 17) + n * jnp.eye(n, dtype=dt)
        return at._memo(probes, "a", mk)

    def setup_incore():
        from ..linalg.lu import getrf_rec

        return at._timed_call(lambda x: getrf_rec(x, nb), _a())

    def setup_pool():
        import jax

        from ..linalg import ooc

        # NOT _timed_call: the pool is host-side/eager-only (a jitted
        # probe would trace the host grid) — time the eager driver
        # exactly as dispatch runs it
        x = _a()

        def run():
            return jax.block_until_ready(
                ooc.getrf_ooc(x, nb=nb, capacity=4))

        return run

    def check(out):
        return at._lu_factor_residual_ok(out, _a(), n, n, dt)

    return key, [at.Candidate("incore", setup_incore, check),
                 at.Candidate("pool", setup_pool, check)]


def _predict_ooc(key_parts, names, platform):
    """Roofline pricing for the ``ooc`` site (ISSUE 17): both
    candidates run the same right-looking tile arithmetic, so the pool
    is priced as the in-core prediction PLUS the cold-window host↔HBM
    tile traffic (attr's zero-flop ``host`` stage on the PCIe
    roofline).  At any HBM-resident shape in-core prices cheaper —
    that ordering is all pruning needs; the runtime chooser owns the
    case pricing can't express, the working set exceeding HBM."""
    if len(key_parts) < 3:
        return {}
    n, nb = int(key_parts[0]), int(key_parts[1])
    dt = _short(key_parts[2])
    a = _attr()
    out = {}
    for name in names:
        dims = {"m": n, "n": n, "nb": nb}
        if name == "pool":
            dims["ooc"] = 1
        elif name != "incore":
            return {}
        t = a.predict_seconds("getrf", dims, dt, platform=platform)
        if t is None:
            return {}
        out[name] = t
    return out


def _build_eig_driver(u):
    """Sweep unit for the heev whole-driver site (ISSUE 18): time the
    two-stage chain against QDWH spectral divide-and-conquer at the
    SAME key ``choose_eig_driver`` derives, gated by the shared
    eigen-residual + orthogonality check.  Probes are host-driven run()
    closures (NOT ``_timed_call``): both drivers carry host-side work
    a jitted probe would trace away."""
    from . import autotune as at
    import jax.numpy as jnp

    n = at._bucket_dim(int(u["n"]))
    dt = jnp.dtype(u.get("dtype", "float32"))
    key = (n, dt.name, at._precision_name())
    probes: dict = {}

    def _a():
        def mk():
            g = at._randn((n, n), dt, 31)
            return 0.5 * (g + jnp.conj(g.T))
        return at._memo(probes, "a", mk)

    def _run(fn):
        def run():
            import jax

            w, z = fn(_a(), True, None)
            jax.block_until_ready(z)
            return w, z
        return run

    def setup_twostage():
        from ..linalg.eig import _heev_twostage

        return _run(_heev_twostage)

    def setup_qdwh():
        from ..linalg.polar import heev_qdwh

        return _run(heev_qdwh)

    def check(out):
        return at._spectral_residual_ok(_a(), out[0], out[1], n, dt)

    return key, [at.Candidate("twostage", setup_twostage, check),
                 at.Candidate("qdwh", setup_qdwh, check)]


def _build_svd_driver(u):
    """Sweep unit for the svd whole-driver site — the ``eig_driver``
    mirror with a reconstruction + left-orthogonality gate."""
    from . import autotune as at
    import jax.numpy as jnp

    m = at._bucket_dim(int(u.get("m", u["n"])))
    n = at._bucket_dim(int(u["n"]))
    dt = jnp.dtype(u.get("dtype", "float32"))
    key = (m, n, dt.name, at._precision_name())
    probes: dict = {}

    def _a():
        return at._memo(probes, "a", lambda: at._randn((m, n), dt, 32))

    def _run(fn):
        def run():
            import jax

            s, uu, vh = fn(_a(), True, True, None)
            jax.block_until_ready(uu)
            return s, uu, vh
        return run

    def setup_twostage():
        from ..linalg.svd import _svd_twostage

        return _run(_svd_twostage)

    def setup_qdwh():
        from ..linalg.polar import svd_qdwh

        return _run(svd_qdwh)

    def check(out):
        import numpy as np

        s, uu, vh = out
        if uu is None or vh is None:
            return False
        if not (bool(jnp.all(jnp.isfinite(uu)))
                and bool(jnp.all(jnp.isfinite(vh)))):
            return False
        a = _a()
        eps = float(np.finfo(np.dtype(dt.name)).eps)
        anorm = float(jnp.linalg.norm(a)) or 1.0
        r = float(jnp.linalg.norm(a - uu @ (s[:, None].astype(uu.dtype)
                                            * vh)))
        o = float(jnp.linalg.norm(jnp.conj(uu.T) @ uu
                                  - jnp.eye(n, dtype=uu.dtype)))
        return (r / (anorm * eps * max(m, n)) < 100.0) \
            and (o / (eps * n) < 100.0)

    return key, [at.Candidate("twostage", setup_twostage, check),
                 at.Candidate("qdwh", setup_qdwh, check)]


def _predict_spectral_driver(routine: str):
    """Pricing for the eig/svd whole-driver sites: both candidates are
    normalized to the same model flop total (``model_flops``), so only
    the stage byte terms separate them analytically — honest enough for
    the coarse ordering pruning needs, and the sweep margin protects
    the rest.  ``dims["qdwh"]`` routes the QDWH stage model."""
    def predict(key_parts, names, platform):
        if len(key_parts) < 2:
            return {}
        off = 1 if routine == "svd" else 0
        n = int(key_parts[off])
        dims0 = {"n": n}
        if routine == "svd":
            dims0["m"] = int(key_parts[0])
        dt = _short(key_parts[1 + off])
        a = _attr()
        out = {}
        for name in names:
            dims = dict(dims0)
            if name == "qdwh":
                dims["qdwh"] = 1
            elif name != "twostage":
                return {}
            t = a.predict_seconds(routine, dims, dt, platform=platform)
            if t is None:
                return {}
            out[name] = t
        return out
    return predict


def _build_qdwh_step(u):
    """Sweep unit for the per-iteration Halley variant inside the QDWH
    polar loop (``qdwh_step``): time the stacked-QR step against the
    Cholesky step on an operand SYNTHESIZED AT THE KEY'S c-REGIME —
    ``u["cdec"]`` picks the weight decade, the matching lower bound
    ``l`` is recovered by bisection (c(l) is monotone decreasing), and
    the probe is built with singular values spanning exactly [l, 1].
    The runtime chooser is probe-free (``choose_qdwh_step``); this unit
    exists so an offline bundle can pin the variant-switch threshold
    per (n-bucket, c-decade, dtype) from measured step times.  The gate
    checks the step's contraction contract: finite output with the
    spectrum still inside (0, ~1] — the Cholesky variant fails it at
    high c, which is the whole point of the site."""
    from . import autotune as at
    import jax.numpy as jnp

    n = at._bucket_dim(int(u["n"]))
    dt = jnp.dtype(u.get("dtype", "float32"))
    cdec = int(u.get("cdec", 0))
    key = (n, "c1e%d" % cdec, dt.name)
    probes: dict = {}

    from ..linalg.polar import _chol_step, _halley_weights, _qr_step

    def _l_for_decade():
        # c(l) spans [~2, ~1/l] as l: 1 → 0; bisect to the decade target
        target = 10.0 ** cdec
        lo, hi = 1e-16, 1.0
        for _ in range(200):
            mid = 0.5 * (lo + hi)
            _, _, c = _halley_weights(mid)
            if c > target:
                lo = mid
            else:
                hi = mid
        return 0.5 * (lo + hi)

    def _x():
        def mk():
            l = _l_for_decade()
            g = at._randn((n, n), dt, 33)
            q1, _ = jnp.linalg.qr(g)
            q2, _ = jnp.linalg.qr(at._randn((n, n), dt, 34))
            sv = jnp.linspace(l, 1.0, n).astype(dt)
            return (q1 * sv[None, :]) @ jnp.conj(q2.T)
        return at._memo(probes, "x", mk)

    a_k, b_k, c_k = _halley_weights(_l_for_decade())
    nb = min(256, n)

    def _setup(step_fn):
        def run():
            import jax

            return jax.block_until_ready(
                step_fn(_x(), a_k, b_k, c_k, nb, "polar"))
        return run

    def check(out):
        if out is None or not bool(jnp.all(jnp.isfinite(out))):
            return False
        # one Halley step maps [l, 1] into [l', ~1]; a variant whose
        # output spectrum escapes (0, 1.1] lost the contraction
        sv = jnp.linalg.svd(out, compute_uv=False)
        return bool(sv[0] <= 1.1) and bool(sv[-1] > 0.0)

    return key, [at.Candidate("qr", lambda: _setup(_qr_step), check),
                 at.Candidate("chol", lambda: _setup(_chol_step), check)]


def _build_dist_chunk(u):
    """Sweep unit for the distributed panel-broadcast slice count: time
    the fused ``bcast_block_col`` at each chunking on THE MESH THIS
    PROCESS OWNS (all available devices, the squarest grid — offline
    sweeps run on the target topology, which is the whole point of the
    per-mesh key).  Values are bitwise identical across candidates, so
    no residual check is needed."""
    from . import autotune as at
    import jax
    import jax.numpy as jnp

    from .._jax_compat import shard_map
    from jax.sharding import PartitionSpec as P
    from ..parallel import dist_util
    from ..parallel.mesh import AXIS_P, AXIS_Q, make_grid_mesh, \
        mesh_grid_shape

    op = str(u.get("op", "potrf"))
    nb = int(u["nb"])
    dt = jnp.dtype(u.get("dtype", "float32"))
    mesh = make_grid_mesh()
    p, q = mesh_grid_shape(mesh)
    key = (op, p, q, nb, dt.name)
    mlb = _CHUNK_ROWS_PER_DEV           # block rows per mesh row (the
    M = mlb * nb * p                    # pricing model's panel height)
    probes: dict = {}

    nlb = _CHUNK_ROWS_PER_DEV           # block cols per mesh col (the
    N = nlb * nb * q                    # row-space mirror, for "trsm")

    def _col():
        return at._memo(probes, "col",
                        lambda: at._randn((M, nb), dt, 3))

    def _row():
        return at._memo(probes, "row",
                        lambda: at._randn((nb, N), dt, 4))

    def _setup(chunks):
        if op == "trsm":
            # the ptrsm backward sweep's bcast_block_row — the one
            # row-space chunked broadcast — times its own variant so
            # the bundle can pin the solve sweeps too
            def kernel(row):
                c = jax.lax.axis_index(AXIS_Q)
                gcols = dist_util.local_grows(nlb, nb, q, c)
                own = (jax.lax.axis_index(AXIS_P) == 0)
                return dist_util.bcast_block_row(row, gcols, own, N,
                                                 chunks=chunks)

            fn = shard_map(kernel, mesh=mesh,
                           in_specs=(P(None, AXIS_Q),),
                           out_specs=P(None, None))
            return at._timed_call(fn, _row())

        def kernel(col):
            r = jax.lax.axis_index(AXIS_P)
            grows = dist_util.local_grows(mlb, nb, p, r)
            own = (jax.lax.axis_index(AXIS_Q) == 0)
            return dist_util.bcast_block_col(col, grows, own, M,
                                             chunks=chunks)

        fn = shard_map(kernel, mesh=mesh, in_specs=(P(AXIS_P, None),),
                       out_specs=P(None, None))
        return at._timed_call(fn, _col())

    return key, [at.Candidate("whole", lambda: _setup(1)),
                 at.Candidate("2", lambda: _setup(2)),
                 at.Candidate("4", lambda: _setup(4))]


#: steps in the dist_lookahead proxy window — enough that a depth-2+
#: ring has broadcasts to float ahead of the consuming contraction
_LOOKAHEAD_WINDOW = 4


def _build_dist_lookahead(u):
    """Sweep unit for the lookahead panel-ring depth
    (``autotune.choose_dist_lookahead``; names ``"1"``..``"4"``, key
    ``(op, nt, nb, dtype)``).  The proxy times a W-step window on the
    process's own mesh with the ring's actual cost/benefit structure:
    at depth D the panel broadcast for step k+D is issued while step
    k's trailing contraction consumes panel k (XLA's async collectives
    overlap them exactly as the distributed drivers' rings do), and
    each step pays the ring's D−1 redundant rank-nb corrections.  Each
    broadcast carries a distinct operand scale so CSE cannot collapse
    the window.  Values are a timing proxy, not driver output — no
    residual gate."""
    from . import autotune as at
    import jax
    import jax.numpy as jnp

    from .._jax_compat import shard_map
    from jax.sharding import PartitionSpec as P
    from ..parallel import dist_util
    from ..parallel.mesh import AXIS_P, AXIS_Q, make_grid_mesh, \
        mesh_grid_shape

    op = str(u.get("op", "getrf"))
    nt = int(u.get("nt", 16))
    nb = int(u["nb"])
    dt = jnp.dtype(u.get("dtype", "float32"))
    mesh = make_grid_mesh()
    p, q = mesh_grid_shape(mesh)
    key = (op, nt, nb, dt.name)
    mlb = _CHUNK_ROWS_PER_DEV
    M = mlb * nb * p
    T = mlb * nb * q
    probes: dict = {}

    def _col():
        return at._memo(probes, "col",
                        lambda: at._randn((M, nb), dt, 5))

    def _trail():
        return at._memo(probes, "trail",
                        lambda: at._randn((nb, T), dt, 6))

    def _setup(depth):
        W = _LOOKAHEAD_WINDOW

        def kernel(col, trail):
            r = jax.lax.axis_index(AXIS_P)
            grows = dist_util.local_grows(mlb, nb, p, r)
            own = (jax.lax.axis_index(AXIS_Q) == 0)

            def bcast(j):
                return dist_util.bcast_block_col(
                    col * jnp.asarray(1.0 + j, dt), grows, own, M)

            ring = [bcast(j) for j in range(min(depth, W))]
            acc = jnp.zeros((M, T), dt)
            for k in range(W):
                nxt = k + depth
                if nxt < W:
                    ring.append(bcast(nxt))
                pan = ring[k]
                acc = acc + pan @ trail
                for j in range(depth - 1):
                    # the ring's redundant rank-nb corrections: depth D
                    # replicates D-1 narrow updates per step
                    acc = acc.at[:, :nb].add(
                        pan @ trail[:, :nb] * jnp.asarray(1.0 + j, dt))
            return acc

        fn = shard_map(kernel, mesh=mesh,
                       in_specs=(P(AXIS_P, None), P(None, None)),
                       out_specs=P(None, None))
        return at._timed_call(fn, _col(), _trail())

    return key, [at.Candidate(str(d), lambda d=d: _setup(d))
                 for d in (1, 2, 3, 4)]


def _build_batched(kind):
    def build(u):
        from . import autotune as at
        import jax.numpy as jnp

        bb, nn = pow2_bucket(int(u["b"])), pow2_bucket(int(u["n"]))
        dt = jnp.dtype(u.get("dtype", "float32"))
        key = (bb, nn, dt.name, at._precision_name())
        probes: dict = {}

        if kind == "potrf":
            def _ops():
                def mk():
                    g = at._randn((bb, nn, nn), dt, 20)
                    eye = nn * jnp.eye(nn, dtype=dt)
                    return jnp.einsum("bij,bkj->bik", g, g) + eye[None]
                return at._memo(probes, "a", mk)

            def setup_grid():
                from ..linalg.batched import _potrf_grid

                return at._timed_call(_potrf_grid, _ops())

            def setup_vmapped():
                from ..linalg.batched import _potrf_vmapped

                return at._timed_call(_potrf_vmapped, _ops())

            def check(out):
                from ..linalg.batched import batched_factor_resid_potrf

                return batched_factor_resid_potrf(_ops(), out) < 100.0
        else:
            def _ops():
                def mk():
                    return (at._randn((bb, nn, nn), dt, 21)
                            + nn * jnp.eye(nn, dtype=dt)[None])
                return at._memo(probes, "a", mk)

            def setup_grid():
                from ..linalg.batched import _getrf_grid

                return at._timed_call(_getrf_grid, _ops())

            def setup_vmapped():
                from ..linalg.batched import _getrf_vmapped

                return at._timed_call(_getrf_vmapped, _ops())

            def check(out):
                from ..linalg.batched import batched_factor_resid_lu

                return batched_factor_resid_lu(_ops(), out) < 100.0

        return key, [at.Candidate("vmapped", setup_vmapped),
                     at.Candidate("grid", setup_grid, check)]
    return build


SITES: Dict[str, SiteSpec] = {
    "matmul": SiteSpec(_build_matmul, _predict_matmul),
    "lu_step": SiteSpec(
        _build_lu_step,
        _fusion_predict("getrf", _dims_mnnb,
                        {"composed": "composed", "fused": "fused",
                         "fused_trsm": "fused_trsm", "full": "full"})),
    "potrf_step": SiteSpec(
        _build_potrf_step,
        _fusion_predict("potrf", _dims_nnb,
                        {"composed": "composed", "fused": "fused",
                         "full": "full"})),
    "lu_driver": SiteSpec(
        _build_lu_driver,
        # the scattered driver's step loop is the fused mega-kernel;
        # the blocked recursion materializes the composed glue
        _fusion_predict("getrf", _dims_mnnb,
                        {"rec": "composed", "scattered": "fused"})),
    "batched_potrf": SiteSpec(
        _build_batched("potrf"),
        _fusion_predict("potrf", _dims_batched,
                        {"vmapped": "composed", "grid": "fused"})),
    "batched_lu": SiteSpec(
        _build_batched("lu"),
        _fusion_predict("getrf", _dims_batched,
                        {"vmapped": "composed", "grid": "fused"})),
    # the distributed panel-broadcast slice count (ISSUE 13): priced
    # analytically with attr.py's ICI roofline (c·launch + wire/c), so
    # the offline bundle can pin the chunking per (mesh shape, nb,
    # dtype) without the runtime ever owning a timeable mesh
    "dist_chunk": SiteSpec(_build_dist_chunk, _predict_dist_chunk),
    # the lookahead panel-ring depth (ISSUE 19): exposure-priced from
    # the overlap model, with the per-dispatch overhead replaced by the
    # MEASURED signal when run_sweep was handed a captured profile —
    # the timeline-informed half of ROADMAP 5(b)
    "dist_lookahead": SiteSpec(_build_dist_lookahead,
                               _predict_dist_lookahead),
    # host-DRAM tile-pool residency (ISSUE 17): priced as in-core +
    # PCIe tile traffic, timed with a forced tiny window so the smoke
    # sweep proves eviction/write-back end to end
    "ooc": SiteSpec(_build_ooc, _predict_ooc),
    # QDWH spectral tier (ISSUE 18): the whole-driver crossover sites
    # (where QDWH's gemm-rich chain beats the two-stage pipelines, per
    # n-bucket/dtype) and the in-loop Halley variant switch — all three
    # bundle-pinnable so a replica boots with the crossover dimension
    # and switch threshold already settled
    "eig_driver": SiteSpec(_build_eig_driver,
                           _predict_spectral_driver("heev")),
    "svd_driver": SiteSpec(_build_svd_driver,
                           _predict_spectral_driver("svd")),
    # the variant switch is unpriceable analytically on purpose: the
    # Cholesky step's validity depends on the c-regime (numerics, not
    # rooflines), so both variants are always timed and the check gate
    # decides
    "qdwh_step": SiteSpec(_build_qdwh_step, lambda kp, names, p: {}),
}


# ---------------------------------------------------------------------------
# Grids
# ---------------------------------------------------------------------------

def _full_units():
    units = []
    for n in (512, 1024, 2048, 4096, 8192):
        units.append({"site": "matmul", "m": n, "k": n, "n": n,
                      "dtype": "float32"})
        if n >= 1024:
            units.append({"site": "matmul", "m": n, "k": n, "n": n,
                          "dtype": "float64"})
    for n in (1024, 2048, 4096, 8192):
        for nb in (256, 512):
            units.append({"site": "lu_step", "m": n, "n": n, "nb": nb})
            units.append({"site": "lu_driver", "m": n, "n": n, "nb": nb})
            units.append({"site": "potrf_step", "n": n, "nb": nb})
    for b in (8, 32, 64):
        for n in (64, 128, 256, 512):
            units.append({"site": "batched_potrf", "b": b, "n": n})
            units.append({"site": "batched_lu", "b": b, "n": n})
    for op in ("potrf", "getrf", "geqrf", "trsm"):
        for nb in (256, 512, 1024):
            units.append({"site": "dist_chunk", "op": op, "nb": nb})
    for op in ("potrf", "getrf", "geqrf"):
        for nt in (8, 16, 32):
            units.append({"site": "dist_lookahead", "op": op, "nt": nt,
                          "nb": 512})
    for n in (4096, 8192):
        for nb in (512, 1024):
            units.append({"site": "ooc", "n": n, "nb": nb})
    for n in (1024, 2048, 4096):
        units.append({"site": "eig_driver", "n": n})
        units.append({"site": "svd_driver", "m": n, "n": n})
        for cdec in (0, 2, 6):
            units.append({"site": "qdwh_step", "n": n, "cdec": cdec})
    return units


#: named grids for ``tools/sweep.py --grid``.  ``smoke`` is the tiny
#: CPU-runnable end-to-end grid ``run_tests.py --sweep`` drives; its
#: shapes are the ones the interpret-mode CI already exercises.  The
#: extra ``warm`` spec covers a serve bucket the grid never sweeps, so
#: a bundle-booted replica proves the interpolating-model path too.
GRIDS = {
    "smoke": {
        "margin": 0.1,
        "units": [
            {"site": "lu_step", "m": 256, "n": 256, "nb": 128},
            {"site": "potrf_step", "n": 256, "nb": 128},
            {"site": "lu_driver", "m": 256, "n": 256, "nb": 128},
            {"site": "batched_potrf", "b": 4, "n": 64},
            {"site": "batched_lu", "b": 4, "n": 64},
            {"site": "ooc", "n": 128, "nb": 32},
        ],
        "warm": [{"op": "posv", "batch": 1, "dims": [96],
                  "dtype": "float32"}],
    },
    "full": {
        "margin": 0.25,
        "units": _full_units(),
    },
}


# ---------------------------------------------------------------------------
# The interpolating decision model
# ---------------------------------------------------------------------------

def model_fit(results) -> dict:
    """Fit the decision model from sweep results: measured survivor
    times (and the audited predictions) at every swept lattice point,
    grouped ``{op: {ctx: [{"coords", "times"[, "predicted"]}]}}``.
    Pruned candidates keep NO measured time — interpolation can only
    ever select a candidate the sweep actually timed somewhere."""
    model: dict = {}
    for r in results:
        coords, ctx = split_key(r.get("key_parts") or ())
        pt = {"coords": [round(c, 6) for c in coords],
              "times": {k: v for k, v in (r.get("times") or {}).items()
                        if isinstance(v, (int, float)) and v > 0}}
        if r.get("predicted"):
            pt["predicted"] = dict(r["predicted"])
        model.setdefault(r["site"], {}).setdefault(ctx, []).append(pt)
    return model


def model_backend(bundle: dict, op: str, key_parts, names,
                  exclude=(), k: int = 4, guard: float = MODEL_GUARD
                  ) -> Optional[str]:
    """Resolve one UNSWEPT key through the bundle's fitted model:
    inverse-distance-weighted geometric blend of each candidate's
    measured times over the ``k`` nearest swept lattice points (L1 in
    log2 space; the dtype/precision context must match exactly), then
    argmin — with the analytical guard applied at the QUERY shape: a
    candidate :func:`predict_times` prices more than ``guard``× the
    predicted best is never selected, however its blended time reads.
    None when the model has no usable data for this key."""
    sites = (bundle.get("model") or {}).get(op)
    if not isinstance(sites, dict):
        return None
    coords, ctx = split_key(key_parts)
    pts = sites.get(ctx)
    if not isinstance(pts, list) or not pts:
        return None
    scored = sorted(
        ((sum(abs(a - b) for a, b in zip(coords, p["coords"])), p)
         for p in pts
         if isinstance(p, dict)
         and len(p.get("coords") or ()) == len(coords)),
        key=lambda dp: dp[0])
    near = scored[:max(1, int(k))]
    if not near:
        return None
    exclude = set(exclude or ())
    est = {}
    for name in names:
        if name in exclude:
            continue
        num = den = 0.0
        for d, p in near:
            t = (p.get("times") or {}).get(name)
            if isinstance(t, (int, float)) and t > 0:
                w = 1.0 / (1.0 + d)
                num += w * math.log(t)
                den += w
        if den > 0:
            est[name] = math.exp(num / den)
    if not est:
        return None
    platform = ((bundle.get("version") or {}).get("platform")) or "tpu"
    pred = predict_times(op, key_parts, list(names), platform)
    if pred:
        best = min((v for n2, v in pred.items()
                    if n2 in est and isinstance(v, (int, float)) and v > 0),
                   default=None)
        if best:
            est = {n2: t for n2, t in est.items()
                   if not isinstance(pred.get(n2), (int, float))
                   or pred[n2] <= guard * best}
    if not est:
        return None
    return min(est, key=est.get)


# ---------------------------------------------------------------------------
# The bundle artifact
# ---------------------------------------------------------------------------

def bundle_digest(blob: dict) -> str:
    """Content digest over the decision-bearing parts (decisions +
    model + version) — what bench.py tags artifacts with so a diff can
    NOTE a bundle change between rounds."""
    core = {"decisions": blob.get("decisions") or {},
            "model": blob.get("model") or {},
            "version": blob.get("version") or {}}
    payload = json.dumps(core, sort_keys=True,
                         separators=(",", ":")).encode()
    return hashlib.sha256(payload).hexdigest()[:16]


def build_bundle(results, version: dict, *, pruned=(), grid_name: str = "",
                 warm=(), stats: Optional[dict] = None) -> dict:
    """Assemble the versioned warm-start bundle from sweep results."""
    decisions = {}
    for r in results:
        decisions[key_str(r["site"], r["key_parts"])] = {
            "backend": r["backend"],
            "times": {k: v for k, v in (r.get("times") or {}).items()
                      if isinstance(v, (int, float))},
        }
    blob = {
        "format": BUNDLE_FORMAT,
        "version": dict(version),
        "grid": grid_name,
        "decisions": decisions,
        "model": model_fit(results),
        "pruned": [dict(p) for p in pruned],
        "warm_start": [dict(s) for s in warm],
        "stats": dict(stats or {}),
    }
    blob["digest"] = bundle_digest(blob)
    return blob


def read_bundle(path: str) -> dict:
    """Load one bundle file.  Raises ``OSError``/``ValueError`` on an
    unreadable or malformed file (the autotune loader classifies those
    as ``autotune.bundle.unreadable``); a format this reader does not
    speak is malformed too."""
    with open(path) as f:
        blob = json.load(f)
    if not isinstance(blob, dict) \
            or blob.get("format") != BUNDLE_FORMAT \
            or not isinstance(blob.get("decisions", {}), dict):
        raise ValueError(f"not a v{BUNDLE_FORMAT} autotune bundle: {path}")
    return blob


def write_bundle(path: str, blob: dict) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                exist_ok=True)
    tmp = path + ".tmp.%d" % os.getpid()
    with open(tmp, "w") as f:
        json.dump(blob, f, indent=1, sort_keys=True)
    os.replace(tmp, path)


#: autotune batched-site op → the serve ops its sweep results warm
#: (same mapping as serve.queue.specs_from_autotune_cache)
_SITE_TO_SERVE = {"batched_potrf": ("potrf", "posv"),
                  "batched_lu": ("getrf", "gesv"),
                  "batched_qr": ("geqrf",)}


def warm_specs_from_results(results, extra=()) -> list:
    """AOT warm-start bucket specs for :func:`slate_tpu.serve.
    warm_start`, derived from the swept ``batched_*`` sites (each key
    names a (bucketed batch, bucketed n, dtype) a replica will serve)
    plus any grid-spec extras."""
    specs, seen = [], set()

    def _add(sp):
        sk = json.dumps(sp, sort_keys=True)
        if sk not in seen:
            seen.add(sk)
            specs.append(sp)

    for sp in extra:
        if isinstance(sp, dict) and "op" in sp:
            _add(dict(sp))
    for r in results:
        ops = _SITE_TO_SERVE.get(r.get("site"))
        if not ops:
            continue
        kp = list(r.get("key_parts") or ())
        try:
            if r["site"] == "batched_qr":
                b, dims, dt = int(kp[0]), [int(kp[1]), int(kp[2])], \
                    str(kp[3])
            else:
                b, dims, dt = int(kp[0]), [int(kp[1])], str(kp[2])
        except (ValueError, IndexError):
            continue
        for op in ops:
            _add({"op": op, "batch": b, "dims": dims, "dtype": dt})
    return specs


# ---------------------------------------------------------------------------
# The sweep engine
# ---------------------------------------------------------------------------

def _resolve_profile_signals(profile, measured_steps, platform):
    """Turn ``run_sweep``'s ``profile``/``measured_steps`` inputs into
    ``(provenance, signals)``: the profile is loaded when given as a
    capture dir / artifact path, the timeline rows default to the last
    :func:`~slate_tpu.parallel.dist_util.timeline_steps` run when the
    caller passed none, and ``xprof.signals_from`` distills both at
    the platform's ICI peak.  ``(None, None)`` when nothing usable was
    supplied — the sweep then prices roofline-only, exactly as before."""
    xp = _xprof()
    prof = None
    src = None
    if isinstance(profile, str):
        src = profile
        prof = xp.load_profile(profile)
    elif isinstance(profile, dict):
        prof = profile
        src = profile.get("artifact") or profile.get("trace_path")
    if measured_steps is None:
        try:
            from ..parallel import dist_util as _du

            measured_steps = _du.timeline_steps() or None
        except Exception:
            measured_steps = None
    if prof is None and not measured_steps:
        return None, None
    sig = xp.signals_from(prof, measured_steps=measured_steps,
                          ici_gbs=_attr().peaks(platform).get("ici_gbs"))
    prov = {"digest": sig.get("digest"),
            "launch_s": sig.get("launch_s"),
            "stage_ops": sorted(sig.get("stages") or {}),
            "measured_steps": int(sig.get("measured_steps") or 0)}
    if src:
        prov["source"] = str(src)
    return prov, sig


def _write_checkpoint(path: str, done: dict) -> None:
    tmp = path + ".tmp.%d" % os.getpid()
    with open(tmp, "w") as f:
        json.dump({"format": BUNDLE_FORMAT, "done": done}, f)
    os.replace(tmp, path)


def run_sweep(grid="smoke", *, margin: Optional[float] = None,
              reps: Optional[int] = None, checkpoint: Optional[str] = None,
              resume: bool = False, out: Optional[str] = None,
              table_path: Optional[str] = None,
              log: Optional[Callable] = None,
              profile=None, measured_steps=None) -> dict:
    """Run the offline sweep and return (and optionally write) the
    bundle.

    ``grid`` is a name from :data:`GRIDS` or a spec dict (``{"units":
    [...], "margin": ..., "warm": [...], "name": ...}``).  Per unit:
    build the candidates with the runtime key derivation, PRICE them
    analytically, skip the model-predicted losers past ``margin``
    (logged with their predicted gap), and time the survivors through
    ``AutotuneTable.decide(force_timing=True)`` on a sweep-private
    table.  Each completed unit is checkpointed (``--resume`` skips
    it on the next run) and transient infra failures take one
    classified retry (:mod:`slate_tpu.resilience.retry`); a unit that
    still fails is recorded in ``stats["units_failed"]`` and never
    kills the sweep.

    ``profile`` closes the measurement loop (ROADMAP 5(b)): a captured
    ``slate_tpu.perf.xprof`` artifact — a capture dir / artifact path,
    or an already-parsed profile dict — distilled (with the optional
    PR 15 ``measured_steps`` timeline rows; when omitted the module's
    last :func:`~slate_tpu.parallel.dist_util.timeline_steps` rows are
    pulled) into measured pricing signals for the duration of the
    sweep: the per-dispatch overhead that sizes ``dist_chunk`` slices,
    prices ``dist_lookahead`` depth and separates the
    ``lu_step``/``potrf_step`` fusion rungs comes from observation
    instead of the launch constant.  The bundle's ``version`` (and so
    its digest) and a ``bundle["profile"]`` block record the profile
    digest and signal provenance — a timeline-informed bundle is
    distinguishable from a roofline-only one by inspection."""
    from . import autotune as at
    from ..resilience.retry import transient_infra, with_backoff

    if isinstance(grid, str):
        spec = GRIDS[grid]
        grid_name = grid
    else:
        spec = dict(grid)
        grid_name = str(spec.get("name", "custom"))
    units = list(spec.get("units") or ())
    margin = float(spec.get("margin", 0.25)) if margin is None \
        else float(margin)
    reps = at._REPS if reps is None else int(reps)
    say = log or (lambda *a: None)
    version = at._version_key()
    platform = version.get("platform") or "tpu"
    if platform not in ("tpu", "cpu"):
        platform = "tpu"

    prof_prov = sig = None
    if profile is not None or measured_steps is not None:
        try:
            prof_prov, sig = _resolve_profile_signals(
                profile, measured_steps, platform)
        except Exception as e:
            say(f"# sweep: profile unusable "
                f"({type(e).__name__}: {e}); pricing roofline-only")
    if sig is not None:
        # the measured-signal provenance rides the version key, so the
        # bundle digest of a timeline-informed sweep can never collide
        # with the roofline-only bundle of the same grid
        version = dict(version, profile=prof_prov)
        say(f"# sweep: measured signals installed "
            f"(digest {prof_prov.get('digest')}, "
            f"launch_s {prof_prov.get('launch_s')})")

    done: dict = {}
    if checkpoint and resume and os.path.exists(checkpoint):
        try:
            with open(checkpoint) as f:
                done = (json.load(f) or {}).get("done", {}) or {}
        except (OSError, ValueError):
            done = {}

    if table_path is None:
        table_path = (checkpoint + ".table") if checkpoint else \
            os.path.join(tempfile.mkdtemp(prefix="slate_tpu_sweep_"),
                         "table.json")
    tab = at.AutotuneTable(path=table_path)

    results, pruned_log = [], []
    stats = {"units": 0, "units_resumed": 0, "units_failed": 0,
             "candidates": 0, "reps_timed": 0, "reps_saved": 0}
    seen_this_run: set = set()

    if sig is not None:
        set_profile_signals(sig)

    for u in units:
        site = u.get("site")
        sspec = SITES.get(site)
        if sspec is None:
            say(f"# sweep: unknown site {site!r}, skipped")
            continue

        def _one(u=u, site=site, sspec=sspec):
            key_parts, cands = sspec.build(u)
            uid = key_str(site, key_parts)
            if uid in done:
                return dict(done[uid], resumed=True)
            names = [c.name for c in cands]
            predicted = predict_times(site, key_parts, names, platform)
            survivors, dropped = prune(predicted, names, margin)
            keep = [c for c in cands if c.name in survivors]
            backend = tab.decide(site, key_parts, keep, reps=reps,
                                 force_timing=True)
            rec = tab.decisions.get(uid) or {}
            times = {k: v for k, v in (rec.get("times") or {}).items()
                     if isinstance(v, (int, float))}
            return {"site": site, "key_parts": list(key_parts),
                    "backend": backend, "times": times,
                    "predicted": {k: round(v, 9)
                                  for k, v in predicted.items()},
                    "pruned": [dict(d, site=site, key=uid)
                               for d in dropped],
                    "n_candidates": len(names), "n_timed": len(keep)}

        try:
            res, _retries = with_backoff(
                _one, attempts=2, classify=transient_infra,
                metric="autotune.sweep.retries")
        except Exception as e:
            stats["units_failed"] += 1
            say(f"# sweep unit FAILED: {site} {u}: "
                f"{type(e).__name__}: {e}")
            continue
        uid = key_str(res["site"], res["key_parts"])
        if uid in seen_this_run:
            # two grid units bucketing to the same pow2 key (e.g. b=5
            # and b=8): one lattice point, once — a duplicate would
            # double-weight the model's nearest-neighbor blend and
            # duplicate the pruning audit
            stats["units_duplicate"] = stats.get("units_duplicate", 0) + 1
            say(f"# sweep: duplicate unit {uid} "
                "(same pow2 bucket), skipped")
            continue
        seen_this_run.add(uid)
        done[uid] = {k: v for k, v in res.items() if k != "resumed"}
        results.append(done[uid])
        pruned_log.extend(done[uid].get("pruned") or ())
        if res.get("resumed"):
            stats["units_resumed"] += 1
        else:
            stats["units"] += 1
            stats["candidates"] += res.get("n_candidates", 0)
            stats["reps_timed"] += res.get("n_timed", 0) * reps
            stats["reps_saved"] += (res.get("n_candidates", 0)
                                    - res.get("n_timed", 0)) * reps
            say(f"# swept {uid}: winner {res['backend']} "
                f"({res['n_timed']}/{res['n_candidates']} timed, "
                f"{len(res.get('pruned') or ())} pruned by model)")
        if checkpoint:
            try:
                _write_checkpoint(checkpoint, done)
            except OSError:
                pass                    # read-only FS: in-memory only
    if sig is not None:
        set_profile_signals(None)
    stats["reps_exhaustive"] = stats["reps_timed"] + stats["reps_saved"]
    stats["timing_reps_actual"] = tab.timing_reps
    warm = warm_specs_from_results(results, extra=spec.get("warm") or ())
    bundle = build_bundle(results, version, pruned=pruned_log,
                          grid_name=grid_name, warm=warm, stats=stats)
    if prof_prov is not None:
        bundle["profile"] = dict(prof_prov)
    if out:
        write_bundle(out, bundle)
        say(f"# bundle written: {out} (digest {bundle['digest']}, "
            f"{len(bundle['decisions'])} decisions, "
            f"{stats['reps_timed']}/{stats['reps_exhaustive']} "
            f"exhaustive reps timed)")
    return bundle
