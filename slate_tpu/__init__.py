"""slate_tpu — a TPU-native distributed dense linear algebra framework.

Brand-new design with the capabilities of SLATE (the reference at
``/root/reference``: distributed tiled BLAS-3, LU/Cholesky/QR solvers,
two-stage eigensolvers/SVD, LAPACK/ScaLAPACK compat APIs), re-thought for
TPU: JAX/pjit SPMD over the ICI mesh, ``jax.lax`` collectives instead of
MPI tile broadcasts, recursive blocked XLA programs instead of OpenMP task
DAGs, and Pallas kernels for the hot tile batches.

Public surface mirrors ``include/slate/slate.hh`` (BLAS-named drivers) and
``include/slate/simplified_api.hh`` (verb-named wrappers in
:mod:`slate_tpu.api.simplified`).
"""

from . import config  # noqa: F401
from .enums import (  # noqa: F401
    Diag, GridOrder, Layout, MethodCholQR, MethodEig, MethodGels, MethodGemm,
    MethodHemm, MethodLU, MethodSVD, MethodTrsm, Norm, Op, Option, Side,
    Target, TileKind, Uplo,
)
from .exceptions import SlateError  # noqa: F401
from .grid import ProcessGrid  # noqa: F401
from .matrix import (  # noqa: F401
    BandMatrix, BaseMatrix, BaseTrapezoidMatrix, HermitianBandMatrix,
    HermitianMatrix, Matrix, SymmetricMatrix, TrapezoidMatrix,
    TriangularBandMatrix, TriangularMatrix,
)
from .options import Options, get_option  # noqa: F401
from . import method  # noqa: F401
from .linalg import *  # noqa: F401,F403
from .printing import print_matrix, redistribute, sprint_matrix  # noqa: F401

__version__ = "0.1.0"
