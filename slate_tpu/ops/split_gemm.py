"""Split-precision fp32 matmul on the bf16 MXU (bf16x3 / bf16x6).

The fp32 sibling of the Ozaki int8-slice fp64 kernel
(:mod:`slate_tpu.ops.ozaki`), exploiting the same exactness property of
MXU accumulation one precision class down:

* **Slicing.**  bf16 shares fp32's 8-bit exponent, so — unlike the
  int8 split — NO per-row/column pow2 scaling is needed.  Each fp32
  operand splits into three bf16 mantissa slices at their natural
  scale::

      s0 = bf16(x);  r1 = x − f32(s0)     # exact (Sterbenz-style:
      s1 = bf16(r1); r2 = r1 − f32(s1)    #  both terms are multiples
      s2 = bf16(r2)                        #  of ulp(x), diff < 2²⁴ ulp)

  The dropped tail |x − Σsᵢ| is ≲ 2⁻²⁵·|x| — below fp32 resolution.

* **Exact pair products.**  Each slice carries ≤ 8 mantissa bits, so
  any pairwise product sᵢ(a)·sⱼ(b) has ≤ 16 significant bits and is
  EXACT in the MXU's native bf16×bf16→fp32 accumulation mode; only the
  k-direction accumulation rounds, in fp32.

* **bf16x3** (:func:`matmul_split3`, throughput grade): fold the three
  DOMINANT slice pairs along K — ``concat([s0a,s0a,s1a], 1) @
  concat([s0b,s1b,s0b], 0)`` — so ONE ``lax.dot`` of length 3k
  computes s₀a·s₀b + s₀a·s₁b + s₁a·s₀b inside the fp32 accumulator.
  This is the LP-GEMM operand-folding trick: 3 bf16-gemm-equivalents
  total, and a pre-split resident panel (:func:`split_slices`) folds
  once, not once per chunk.  The dropped pairs (s₁s₁, s₀s₂, s₂s₀) are
  each ≤ 2⁻¹⁶·|a||b|, so the componentwise error is
  ≈ (2⁷ + 3k)·ε₃₂·(|a|·|b|) — inside the stock fp32 gemm's k·ε₃₂
  backward-error envelope class for the blocked drivers' trailing
  contractions (k ≥ 64), and a full precision class above the
  library-default ``high`` 3-pass dot (~1.3e-5 componentwise, which
  never meets that envelope).

* **bf16x6** (:func:`matmul_split6`, accuracy grade): Ozaki-style
  diagonal combining — keep ALL slice-pair diagonals tot = i+j ≤ 2
  (six products, 6 bf16 passes), accumulate each diagonal in its own
  fp32 dot and sum them smallest-magnitude-first.  No dropped-pair
  floor: true ~3k·ε₃₂ componentwise (``Precision.HIGHEST`` grade),
  with each accumulator only ever adding same-magnitude terms — for
  ill-scaled or short-k trailing updates where the 3-pass variant's
  2⁻¹⁶ envelope term shows.

Caveats (the documented contract, matching ``ozaki.py``):

* **Subnormals flush (DAZ/FTZ).**  TPU flushes bf16 subnormals: slices
  whose scale falls below 2⁻¹²⁶ vanish, so inputs within ~2⁸ of the
  fp32 subnormal range lose low-order slices and fully subnormal
  inputs contribute zero.  Same semantics as the int8 split's flush.
* **Non-finite inputs produce garbage.**  Inf/NaN survive the bf16
  cast but the residual recurrence (∞ − ∞) manufactures NaN.  Callers
  that admit non-finite data must gate on the input, as the drivers'
  residual/health gates do.
* fp32 2-D operands only — the split is pointless for bf16 inputs and
  wrong for fp64 (use :mod:`.ozaki`).

Throughput: 3 (split3) or 6 (split6) bf16 passes against the MXU's
bf16 peak (~2–3.3× the fp32 ``HIGHEST`` rate on v5e), priced in the
offline sweep against ``SLATE_TPU_PEAK_TFLOPS_BF16``.  Selection is
the ``matmul`` autotune site (``SLATE_TPU_SPLIT_GEMM`` tri-state).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

#: bf16 mantissa slices per fp32 operand — 3×8 explicit bits cover the
#: 24-bit fp32 significand
NSLICES = 3


def _guard(a, b) -> None:
    if a.ndim != 2 or b.ndim != 2:
        raise ValueError(
            "split gemm is 2-D only (the blocked drivers' tile and "
            f"trailing-update products); got {a.ndim}-D @ {b.ndim}-D")
    if a.dtype != jnp.float32 or b.dtype != jnp.float32:
        raise TypeError(
            "split gemm wants float32 operands (bf16x3 slices share "
            f"fp32's exponent range); got {a.dtype} @ {b.dtype}")


def split_slices(x):
    """The three bf16 mantissa slices of fp32 ``x`` (elementwise, any
    shape), with ``s0 + s1 + s2 == x`` to ~2⁻²⁵ relative.

    The split is ELEMENTWISE, so slicing commutes with splitting:
    ``split_slices(x)[i][rows, cols] == split_slices(x[rows, cols])[i]``
    bit-for-bit.  That is what makes panel folding work — a resident
    trailing-update panel splits ONCE and every strip product reuses
    row/column windows of the same slices (LP-GEMM operand folding).
    """
    s = []
    r = x
    for _ in range(NSLICES):
        si = r.astype(jnp.bfloat16)
        s.append(si)
        r = r - si.astype(jnp.float32)
    return tuple(s)


def matmul_sliced3(sa, sb):
    """bf16x3 product from pre-split operands: ``sa`` are the lhs
    slices (each (m, k)), ``sb`` the rhs slices (each (k, n)).  The
    three DOMINANT slice pairs — s₀a·s₀b + s₀a·s₁b + s₁a·s₀b, every
    product of magnitude ≥ 2⁻⁸·|ab| — folded along K into ONE dot of
    length 3k in the fp32 MXU accumulator.  A same-length fold of the
    (i, i) diagonal would drop the 2⁻⁸ cross terms and land at bf16
    grade; pairing (0,0), (0,1), (1,0) leaves only the ≤ 2⁻¹⁶ terms
    (s₁s₁, s₀s₂, s₂s₀) out of the sum."""
    fa = jnp.concatenate((sa[0], sa[0], sa[1]), axis=1)  # (m, 3k) bf16
    fb = jnp.concatenate((sb[0], sb[1], sb[0]), axis=0)  # (3k, n) bf16
    return lax.dot(fa, fb, preferred_element_type=jnp.float32)


def matmul_sliced6(sa, sb):
    """bf16x6 product from pre-split operands: the three slice-pair
    diagonals tot = i+j ≤ 2 as separate fp32-accumulated dots, summed
    smallest-first so each addition only rounds against terms of its
    own magnitude."""
    def diag(xs, ys):
        return lax.dot(jnp.concatenate(xs, axis=1),
                       jnp.concatenate(ys, axis=0),
                       preferred_element_type=jnp.float32)

    d2 = diag((sa[0], sa[1], sa[2]), (sb[2], sb[1], sb[0]))  # ~2⁻¹⁶·|ab|
    d1 = diag((sa[0], sa[1]), (sb[1], sb[0]))                # ~2⁻⁸·|ab|
    d0 = lax.dot(sa[0], sb[0], preferred_element_type=jnp.float32)
    return (d2 + d1) + d0


def matmul_sliced(backend: str, sa, sb):
    """Dispatch a pre-split product by autotune backend name
    (``"split3"`` | ``"split6"``) — the panel-folded call sites keep
    one code path for both grades."""
    if backend == "split6":
        return matmul_sliced6(sa, sb)
    return matmul_sliced3(sa, sb)


def matmul_split3(a, b):
    """fp32 matmul via the K-folded bf16x3 split: ~(2⁷ + 3k)·ε₃₂
    componentwise — the stock k·ε₃₂ envelope class for k ≥ 64 — at
    3 bf16-gemm passes."""
    _guard(a, b)
    return matmul_sliced3(split_slices(a), split_slices(b))


def matmul_split6(a, b):
    """fp32 matmul via the diagonal-combined bf16x6 split: true
    ~3k·ε₃₂ componentwise (no dropped-pair floor) at 6 bf16-gemm
    passes."""
    _guard(a, b)
    return matmul_sliced6(split_slices(a), split_slices(b))
