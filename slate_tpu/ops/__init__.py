"""Internal op layer — TPU-native analog of the reference's
``src/internal/`` tile-op layer (``src/internal/internal.hh``, 56 entry
points) and device kernel set (``include/slate/internal/device.hh:82-266``).

Organisation:

* :mod:`slate_tpu.ops.tile_ops` — elementwise/norm tile kernels
  (geadd/gecopy/gescale/geset/transpose/genorm…), batched over leading
  dims the way the reference batches over tile pointer arrays.
* :mod:`slate_tpu.ops.blocks` — recursive blocked Level-3 building
  blocks (potrf/trsm/trmm/herk/trtri/lauum…) whose base cases are
  nb×nb ``lax.linalg`` tile ops, mirroring how the reference base-cases
  into vendor LAPACK on a single tile (``internal_potrf.cc:34-72``).
* :mod:`slate_tpu.ops.pallas_kernels` — hand-written Pallas TPU kernels
  for hot tile batches, with XLA fallbacks.
"""

from . import tile_ops, blocks  # noqa: F401
