"""Recursive blocked Level-3 building blocks.

This module is the TPU-native replacement for the reference's internal
tile-op layer (``src/internal/internal_gemm.cc``, ``internal_trsm.cc``,
``internal_herk.cc``, ``internal_potrf.cc`` …): where the reference walks
a tile DAG and issues group-batched vendor-BLAS calls per device
(``internal_gemm.cc:383-689``), here each op is a *recursive blocked
algorithm over one dense array* whose base case is an nb×nb
``lax.linalg`` tile op — the same role vendor LAPACK plays for the
reference's diagonal tiles (``internal_potrf.cc:34-72``).

Why recursion instead of a tile loop: every split level exposes one
*large* matmul (trailing update), which is exactly what the MXU wants;
the recursion depth is log(n/nb) so XLA traces O(log n) distinct shapes
instead of O(n/nb) loop steps, and the schedule — panel op, then one big
GEMM — is the static-dataflow equivalent of the reference's
lookahead-pipelined task DAG (``src/potrf.cc:54-123``): XLA's scheduler
overlaps the next panel with the tail of the previous update because the
dependence structure is explicit in the graph.

All functions assume the transposition op has already been *materialised*
by the caller (drivers resolve ``Op`` into the effective array and
effective uplo), so only NoTrans cases appear here.  All are
shape-polymorphic in batch dims only where noted; shapes are static.
"""

from __future__ import annotations

from functools import partial

import jax.numpy as jnp
from jax import lax

from .. import config
from ..enums import Diag, Side, Uplo
from ..grid import ceildiv


def _on_tpu() -> bool:
    """Trace-time backend check for the fp64-on-MXU dispatch."""
    import jax

    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def matmul(a, b):
    """Dot with the configured precision (see :mod:`slate_tpu.config`),
    backend-dispatched through the autotune table
    (:mod:`slate_tpu.perf.autotune`).

    2-D real same-dtype products — the tile products and every blocked
    driver's trailing update — ask :func:`~slate_tpu.perf.autotune.
    choose_matmul` for the measured winner among stock XLA dot, the
    hand-tuned Pallas VMEM kernel
    (:func:`slate_tpu.ops.pallas_kernels.matmul`, f32-class tile-grid-
    aligned shapes), the Ozaki int8-slice fp64 MXU kernel
    (:mod:`slate_tpu.ops.ozaki`, real fp64 on TPU) and the bf16-slice
    fp32 split kernels (:mod:`slate_tpu.ops.split_gemm`, bf16x3/bf16x6
    on the MXU's bf16 peak).  The tri-state ``config.use_pallas`` /
    ``config.f64_mxu`` / ``config.split_gemm`` knobs force a backend on
    or off; complex and batched operands always take the XLA path.
    """
    if (a.ndim == 2 and b.ndim == 2 and a.dtype == b.dtype
            and jnp.issubdtype(a.dtype, jnp.floating)):
        from ..perf.autotune import choose_matmul

        backend = choose_matmul(a.shape, b.shape, a.dtype)
        if backend == "ozaki":
            from .ozaki import matmul_f64

            return matmul_f64(a, b)
        if backend in ("split3", "split6"):
            from .split_gemm import matmul_split3, matmul_split6

            return (matmul_split3 if backend == "split3"
                    else matmul_split6)(a, b)
        if backend == "pallas":
            from .pallas_kernels import matmul as pallas_matmul

            def blk(dim, pref):
                return pref if dim % pref == 0 else 128

            return pallas_matmul(a, b, bm=blk(a.shape[0], 256),
                                 bn=blk(b.shape[1], 256),
                                 bk=blk(a.shape[1], 512))
    return jnp.matmul(a, b, precision=config.matmul_precision)


def matmul_hi(a, b):
    """Dot pinned to ``Precision.HIGHEST`` regardless of the library
    default.  Accuracy-critical compositions — iterative-refinement
    residuals, CholQR Gram matrices — use this so the global
    ``matmul_precision`` knob (default ``high``, ~1.3e-5 on f32) cannot
    loosen them: these sites feed error estimates whose own error must
    sit well below what they measure."""
    return jnp.matmul(a, b, precision=lax.Precision.HIGHEST)


def matmul_backend(shape_a, shape_b, dtype) -> str:
    """Resolved matmul backend name for a 2-D product — the ops-layer
    face of :func:`~slate_tpu.perf.autotune.choose_matmul`.  The
    distributed drivers consult it BEFORE their cached shard_map builds
    so a split-gemm winner lets them pre-split a resident panel once
    per step (the registry contract keeps the backend kernel modules
    importable from ops/ only)."""
    from ..perf.autotune import choose_matmul

    return choose_matmul(shape_a, shape_b, dtype)


def panel_split(x):
    """bf16 mantissa slices of a resident fp32 panel — re-export of
    :func:`slate_tpu.ops.split_gemm.split_slices` for the
    registry-guarded layers.  Split once per panel; because the
    elementwise split commutes with slicing, windows of the result feed
    :func:`matmul_presplit` per strip with no re-split."""
    from .split_gemm import split_slices

    return split_slices(x)


def matmul_presplit(backend: str, sa, sb):
    """Split-product dot over pre-split operand slices — re-export of
    :func:`slate_tpu.ops.split_gemm.matmul_sliced` (``backend`` ∈
    {"split3", "split6"})."""
    from .split_gemm import matmul_sliced

    return matmul_sliced(backend, sa, sb)


def _split(n: int, nb: int) -> int:
    """Split point for recursion: half of n rounded up to a multiple of nb."""
    return max(nb, (ceildiv(n, 2 * nb)) * nb)


def _ct(a):
    """Conjugate-transpose (the ^H that appears throughout)."""
    return jnp.conj(jnp.swapaxes(a, -1, -2))


def _t(a, conj: bool):
    return _ct(a) if conj else jnp.swapaxes(a, -1, -2)


# ---------------------------------------------------------------------------
# Cholesky
# ---------------------------------------------------------------------------

def potrf_rec(a, nb: int):
    """Blocked lower Cholesky of SPD/HPD ``a``; returns L (lower triangle,
    zeros above).

    Recursive equivalent of the reference driver loop ``src/potrf.cc:210-288``
    (panel potrf → trsm → herk trailing update), with the diagonal-tile base
    case playing ``internal::potrf`` (``internal_potrf.cc:34-72``).
    """

    n = a.shape[-1]
    if n <= nb:
        return jnp.tril(lax.linalg.cholesky(a))
    n1 = _split(n, nb)
    a11 = a[..., :n1, :n1]
    a21 = a[..., n1:, :n1]
    a22 = a[..., n1:, n1:]
    l11 = potrf_rec(a11, nb)
    # L21 = A21 · L11^{-H}   (trailing panel trsm, src/potrf.cc:227-231)
    l21 = lax.linalg.triangular_solve(
        l11, a21, left_side=False, lower=True, transpose_a=True,
        conjugate_a=jnp.iscomplexobj(a))
    # A22 ← A22 − L21·L21^H  (herk trailing update, src/potrf.cc:256-259)
    l22 = potrf_rec(a22 - matmul(l21, _ct(l21)), nb)
    top = jnp.concatenate([l11, jnp.zeros_like(_t(a21, False))], axis=-1)
    bot = jnp.concatenate([l21, l22], axis=-1)
    return jnp.concatenate([top, bot], axis=-2)


# ---------------------------------------------------------------------------
# Triangular solve / multiply
# ---------------------------------------------------------------------------

def trsm_rec(side: Side, uplo: Uplo, diag: Diag, a, b, nb: int):
    """op-free blocked triangular solve: X with A·X = B (Left) or
    X·A = B (Right); ``a`` is the effective triangle (op already applied).

    Recursive form of ``src/work/work_trsm.cc`` — each level exposes one
    big GEMM update.
    """

    unit = diag is Diag.Unit
    n = a.shape[-1]
    if n <= nb:
        return lax.linalg.triangular_solve(
            a, b, left_side=(side is Side.Left),
            lower=(uplo is Uplo.Lower), unit_diagonal=unit)
    n1 = _split(n, nb)
    a11 = a[..., :n1, :n1]
    a22 = a[..., n1:, n1:]
    if side is Side.Left:
        b1, b2 = b[..., :n1, :], b[..., n1:, :]
        if uplo is Uplo.Lower:
            a21 = a[..., n1:, :n1]
            x1 = trsm_rec(side, uplo, diag, a11, b1, nb)
            x2 = trsm_rec(side, uplo, diag, a22, b2 - matmul(a21, x1), nb)
        else:
            a12 = a[..., :n1, n1:]
            x2 = trsm_rec(side, uplo, diag, a22, b2, nb)
            x1 = trsm_rec(side, uplo, diag, a11, b1 - matmul(a12, x2), nb)
        return jnp.concatenate([x1, x2], axis=-2)
    else:
        b1, b2 = b[..., :, :n1], b[..., :, n1:]
        if uplo is Uplo.Lower:
            a21 = a[..., n1:, :n1]
            x2 = trsm_rec(side, uplo, diag, a22, b2, nb)
            x1 = trsm_rec(side, uplo, diag, a11, b1 - matmul(x2, a21), nb)
        else:
            a12 = a[..., :n1, n1:]
            x1 = trsm_rec(side, uplo, diag, a11, b1, nb)
            x2 = trsm_rec(side, uplo, diag, a22, b2 - matmul(x1, a12), nb)
        return jnp.concatenate([x1, x2], axis=-1)


def _tri(a, uplo: Uplo, diag: Diag):
    """Materialise the triangle (with implicit unit diagonal if asked)."""
    t = jnp.tril(a) if uplo is Uplo.Lower else jnp.triu(a)
    if diag is Diag.Unit:
        n = a.shape[-1]
        eye = jnp.eye(n, dtype=a.dtype)
        t = t - t * jnp.eye(n, dtype=a.dtype) + eye  # force unit diagonal
    return t


def trmm_rec(side: Side, uplo: Uplo, diag: Diag, a, b, nb: int):
    """Blocked triangular multiply B ← A·B (Left) or B·A (Right);
    ``a`` effective triangle.  Ref ``src/work/work_trmm.cc``."""

    n = a.shape[-1]
    if n <= nb:
        t = _tri(a, uplo, diag)
        return matmul(t, b) if side is Side.Left else matmul(b, t)
    n1 = _split(n, nb)
    a11 = a[..., :n1, :n1]
    a22 = a[..., n1:, n1:]
    if side is Side.Left:
        b1, b2 = b[..., :n1, :], b[..., n1:, :]
        if uplo is Uplo.Lower:
            a21 = a[..., n1:, :n1]
            y2 = trmm_rec(side, uplo, diag, a22, b2, nb) + matmul(a21, b1)
            y1 = trmm_rec(side, uplo, diag, a11, b1, nb)
        else:
            a12 = a[..., :n1, n1:]
            y1 = trmm_rec(side, uplo, diag, a11, b1, nb) + matmul(a12, b2)
            y2 = trmm_rec(side, uplo, diag, a22, b2, nb)
        return jnp.concatenate([y1, y2], axis=-2)
    else:
        b1, b2 = b[..., :, :n1], b[..., :, n1:]
        if uplo is Uplo.Lower:
            a21 = a[..., n1:, :n1]
            y1 = trmm_rec(side, uplo, diag, a11, b1, nb) + matmul(b2, a21)
            y2 = trmm_rec(side, uplo, diag, a22, b2, nb)
        else:
            a12 = a[..., :n1, n1:]
            y2 = trmm_rec(side, uplo, diag, a22, b2, nb) + matmul(b1, a12)
            y1 = trmm_rec(side, uplo, diag, a11, b1, nb)
        return jnp.concatenate([y1, y2], axis=-1)


# ---------------------------------------------------------------------------
# Rank-k updates on a triangle
# ---------------------------------------------------------------------------

def herk_rec(uplo: Uplo, alpha, a, beta, c, nb: int, conj: bool = True):
    """C ← α·A·A^H + β·C on the ``uplo`` triangle (full tiles are updated
    at the base; the driver restores the untouched triangle).

    ``conj=False`` gives syrk (A·Aᵀ).  Recursive form of
    ``internal_herk.cc`` / ``internal_syrk.cc``: off-diagonal blocks are
    plain GEMMs — the O(n²k) hot loop of ``src/potrf.cc:256-259``.
    """

    n = c.shape[-1]
    if n <= nb:
        return alpha * matmul(a, _t(a, conj)) + beta * c
    n1 = _split(n, nb)
    a1, a2 = a[..., :n1, :], a[..., n1:, :]
    c11 = herk_rec(uplo, alpha, a1, beta, c[..., :n1, :n1], nb, conj)
    c22 = herk_rec(uplo, alpha, a2, beta, c[..., n1:, n1:], nb, conj)
    if uplo is Uplo.Lower:
        c21 = alpha * matmul(a2, _t(a1, conj)) + beta * c[..., n1:, :n1]
        top = jnp.concatenate([c11, c[..., :n1, n1:]], axis=-1)
        bot = jnp.concatenate([c21, c22], axis=-1)
    else:
        c12 = alpha * matmul(a1, _t(a2, conj)) + beta * c[..., :n1, n1:]
        top = jnp.concatenate([c11, c12], axis=-1)
        bot = jnp.concatenate([c[..., n1:, :n1], c22], axis=-1)
    return jnp.concatenate([top, bot], axis=-2)


def her2k_rec(uplo: Uplo, alpha, a, b, beta, c, nb: int, conj: bool = True):
    """C ← α·A·B^H + ᾱ·B·A^H + β·C on a triangle (syr2k when conj=False,
    with ᾱ→α).  Ref ``internal_her2k.cc`` / ``internal_syr2k.cc``."""

    alpha2 = jnp.conj(alpha) if conj else alpha
    n = c.shape[-1]
    if n <= nb:
        return (alpha * matmul(a, _t(b, conj))
                + alpha2 * matmul(b, _t(a, conj)) + beta * c)
    n1 = _split(n, nb)
    a1, a2 = a[..., :n1, :], a[..., n1:, :]
    b1, b2 = b[..., :n1, :], b[..., n1:, :]
    c11 = her2k_rec(uplo, alpha, a1, b1, beta, c[..., :n1, :n1], nb, conj)
    c22 = her2k_rec(uplo, alpha, a2, b2, beta, c[..., n1:, n1:], nb, conj)
    if uplo is Uplo.Lower:
        c21 = (alpha * matmul(a2, _t(b1, conj))
               + alpha2 * matmul(b2, _t(a1, conj)) + beta * c[..., n1:, :n1])
        top = jnp.concatenate([c11, c[..., :n1, n1:]], axis=-1)
        bot = jnp.concatenate([c21, c22], axis=-1)
    else:
        c12 = (alpha * matmul(a1, _t(b2, conj))
               + alpha2 * matmul(b1, _t(a2, conj)) + beta * c[..., :n1, n1:])
        top = jnp.concatenate([c11, c12], axis=-1)
        bot = jnp.concatenate([c[..., n1:, :n1], c22], axis=-1)
    return jnp.concatenate([top, bot], axis=-2)


# ---------------------------------------------------------------------------
# Triangular inverse and L^H·L / U·U^H products (potri ingredients)
# ---------------------------------------------------------------------------

def trtri_rec(uplo: Uplo, diag: Diag, a, nb: int, hi: bool = False):
    """Blocked triangular inverse (ref driver ``src/trtri.cc``).

    Base case: a lower non-unit f32 power-of-two tile dispatches through
    the autotune table between the fused Pallas recursive-doubling
    inverse (``pallas_kernels.trtri_panel``) and the XLA tile solver
    (T·X = I with ``triangular_solve``) — the analog of the reference's
    lapack::trtri on a diagonal tile.

    ``hi=True`` pins the off-diagonal assembly products to
    ``Precision.HIGHEST`` for accuracy-critical compositions (potri):
    the inverse's forward error feeds those residuals at full scale, so
    the library-default 3-pass-bf16 ``high`` (~1.3e-5) would put a
    ~110·ε₃₂ floor under them.
    """

    n = a.shape[-1]
    unit = diag is Diag.Unit
    mm = matmul_hi if hi else matmul
    if n <= nb:
        if (a.ndim == 2 and uplo is Uplo.Lower and not unit
                and a.dtype == jnp.float32 and n >= 32
                and (n & (n - 1)) == 0):
            from ..perf.autotune import choose_trtri_panel

            if choose_trtri_panel(n, a.dtype) == "pallas":
                from .pallas_kernels import trtri_panel

                return trtri_panel(a)
        eye = jnp.eye(n, dtype=a.dtype)
        if a.ndim > 2:
            eye = jnp.broadcast_to(eye, a.shape)
        return lax.linalg.triangular_solve(
            a, eye, left_side=True, lower=(uplo is Uplo.Lower),
            unit_diagonal=unit)
    n1 = _split(n, nb)
    a11 = a[..., :n1, :n1]
    a22 = a[..., n1:, n1:]
    x11 = trtri_rec(uplo, diag, a11, nb, hi)
    x22 = trtri_rec(uplo, diag, a22, nb, hi)
    if uplo is Uplo.Lower:
        a21 = a[..., n1:, :n1]
        x21 = -mm(x22, mm(a21, x11))
        top = jnp.concatenate([x11, jnp.zeros_like(jnp.swapaxes(a21, -1, -2))], axis=-1)
        bot = jnp.concatenate([x21, x22], axis=-1)
    else:
        a12 = a[..., :n1, n1:]
        x12 = -mm(x11, mm(a12, x22))
        top = jnp.concatenate([x11, x12], axis=-1)
        bot = jnp.concatenate([jnp.zeros_like(jnp.swapaxes(a12, -1, -2)), x22], axis=-1)
    return jnp.concatenate([top, bot], axis=-2)


def lauum_rec(uplo: Uplo, a, nb: int, conj: bool = True, hi: bool = False):
    """Triangular in-place product (LAPACK ``lauum``, reference
    ``internal::trtrm`` / ``src/trtrm.cc``): Lower → L^H·L, Upper → U·U^H.
    Result is Hermitian; the ``uplo`` triangle of the result is valid.
    ``hi`` pins the products to ``Precision.HIGHEST`` (see
    :func:`trtri_rec` — potri composes both stages, so their errors
    multiply into its residual gate).
    """

    n = a.shape[-1]
    mm = matmul_hi if hi else matmul
    if n <= nb:
        t = jnp.tril(a) if uplo is Uplo.Lower else jnp.triu(a)
        return mm(_t(t, conj), t) if uplo is Uplo.Lower else mm(t, _t(t, conj))
    n1 = _split(n, nb)
    a11 = a[..., :n1, :n1]
    a22 = a[..., n1:, n1:]
    r11 = lauum_rec(uplo, a11, nb, conj, hi)
    r22 = lauum_rec(uplo, a22, nb, conj, hi)
    if uplo is Uplo.Lower:
        l21 = a[..., n1:, :n1]
        l22 = jnp.tril(a22)
        # (L^H L)_11 = L11^H L11 + L21^H L21 ; _21 = L22^H L21
        r11 = r11 + mm(_t(l21, conj), l21)
        r21 = mm(_t(l22, conj), l21)
        top = jnp.concatenate([r11, _t(r21, conj)], axis=-1)
        bot = jnp.concatenate([r21, r22], axis=-1)
    else:
        u12 = a[..., :n1, n1:]
        u22 = jnp.triu(a22)
        # (U U^H)_11 = U11 U11^H + U12 U12^H ; _12 = U12 U22^H
        r11 = r11 + mm(u12, _t(u12, conj))
        r12 = mm(u12, _t(u22, conj))
        top = jnp.concatenate([r11, r12], axis=-1)
        bot = jnp.concatenate([_t(r12, conj), r22], axis=-1)
    return jnp.concatenate([top, bot], axis=-2)


def _potrf_step_bytes(n: int, nb: int, tc: int) -> int:
    """Resident working set of the fused potrf step: the (n, nb) panel
    column, two (tc, tc) streaming tiles and three (nb, nb) diag-block
    scratches."""
    return (n * nb + 2 * tc * tc + 3 * nb * nb) * 4


def potrf_step_tc(n: int, nb: int) -> int:
    """Trailing-tile edge for the fused potrf step: the largest divisor
    of nb (floor 128) whose double-buffered (tc, tc) pair fits the VMEM
    budget (:mod:`slate_tpu.ops.vmem`) next to the (n, nb) panel
    column."""
    from . import vmem
    return vmem.largest_tc(nb, lambda tc: _potrf_step_bytes(n, nb, tc))


def use_fused_potrf_step(n: int, nb: int, dtype) -> bool:
    """Shape/VMEM ELIGIBILITY of the fused potrf step kernel
    (:func:`potrf_steps`): f32 on a uniform nb grid (nb a power of two
    ≥ 128 so the kernel's lane-aligned column DMA and recursive-doubling
    inverse hold), panel column within the VMEM budget.  Whether an
    eligible shape actually takes it is the ``potrf_step`` autotune
    decision."""
    if config.use_pallas_mode() == "off":
        return False
    if dtype != jnp.float32 or n % nb != 0 or n <= nb:
        return False
    if nb < 128 or (nb & (nb - 1)) != 0:
        return False
    from . import vmem
    tc = potrf_step_tc(n, nb)
    return vmem.fits(_potrf_step_bytes(n, nb, tc))


def potrf_steps(a, nb: int = 512, tc: int | None = None):
    """Right-looking blocked Cholesky whose WHOLE step — diagonal
    chol+inverse, panel trsm-as-gemm, symmetric rank-nb trailing update
    — is ONE Pallas invocation per block column
    (:func:`~slate_tpu.ops.pallas_kernels.potrf_step_fused`): the
    aliased carry round-trips HBM once per step instead of once per
    sub-stage, and the trailing tiles stream through a double-buffered
    VMEM residency at the composed strip driver's exact flop count.
    The ``potrf_step`` autotune site times this against
    :func:`potrf_panels` (the composed path) per (n, nb, dtype).

    Requires ``n % nb == 0`` and nb a power of two (the in-kernel
    recursive-doubling inverse); f32 on TPU, f32/f64 in interpret mode.
    """

    from ..perf import metrics
    from .pallas_kernels import potrf_step_fused

    n = a.shape[-1]
    tc = tc if tc is not None else potrf_step_tc(n, nb)
    metrics.inc("step.potrf.steps", float(n // nb))
    with metrics.step_timer("potrf", "fused"):
        for k0 in range(0, n, nb):
            a = potrf_step_fused(a, k0, nb=nb, tc=tc)
    return jnp.tril(a)


def _potrf_full_bytes(n: int, nb: int, tc: int) -> int:
    """Resident working set of the whole-factorization potrf kernel:
    the step kernel's set plus the (n, nb) lookahead column buffer."""
    return (2 * n * nb + 2 * tc * tc + 3 * nb * nb) * 4


def potrf_full_tc(n: int, nb: int) -> int:
    from . import vmem
    return vmem.largest_tc(nb, lambda tc: _potrf_full_bytes(n, nb, tc))


def use_full_potrf(n: int, nb: int, dtype) -> bool:
    """Shape/VMEM ELIGIBILITY of the whole-factorization Cholesky
    mega-kernel (:func:`potrf_full`, depth ``full``): the fused-step
    conditions with the larger resident set — the lookahead holds TWO
    (n, nb) block-columns in VMEM at once.  Whether an eligible shape
    actually takes the full depth is the ``potrf_step`` autotune
    decision."""
    if config.use_pallas_mode() == "off":
        return False
    if dtype != jnp.float32 or n % nb != 0 or n <= nb:
        return False
    if nb < 128 or (nb & (nb - 1)) != 0:
        return False
    from . import vmem
    tc = potrf_full_tc(n, nb)
    return vmem.fits(_potrf_full_bytes(n, nb, tc))


def potrf_full(a, nb: int = 512, tc: int | None = None):
    """Right-looking blocked Cholesky whose WHOLE factorization is ONE
    Pallas invocation
    (:func:`~slate_tpu.ops.pallas_kernels.potrf_full_fused`): the grid
    iterates the block-column steps inside a single ``pallas_call``,
    each step streams its shrinking trailing window through the
    double-buffered VMEM residency against the aliased carry, and the
    next panel block-column is lookahead-updated in VMEM — one kernel
    launch and ``step.hbm_roundtrips == 0`` for the whole
    factorization.  The ``potrf_step`` autotune site arbitrates this
    ``full`` depth against :func:`potrf_steps` (per-step fused) and
    :func:`potrf_panels` (composed) per (n, nb, dtype).

    Requires ``n % nb == 0`` and nb a power of two (the in-kernel
    recursive-doubling inverse); f32 on TPU, f32/f64 in interpret mode.
    """

    from ..perf import metrics
    from .pallas_kernels import potrf_full_fused

    n = a.shape[-1]
    tc = tc if tc is not None else potrf_full_tc(n, nb)
    metrics.inc("step.potrf.steps", float(n // nb))
    with metrics.step_timer("potrf", "full"):
        a = potrf_full_fused(a, nb=nb, tc=tc)
    return jnp.tril(a)


def potrf_panels(a, nb: int = 512):
    """Right-looking blocked Cholesky whose panel step is the fused
    Pallas ``chol_inv_panel`` kernel (L and L⁻¹ of the diagonal block in
    one VMEM launch): every panel trsm becomes an MXU gemm against L⁻¹,
    and the trailing herk touches only block-column strips at/below the
    diagonal — half the flops of the full-square update (the reference's
    ``internal::herk`` also updates only the stored triangle,
    ``internal_herk.cc``).

    The TPU-default potrf path (reference ``internal_potrf.cc:53-72`` +
    batched trsm): the round-3 unrolled kernel factors a 512² diagonal
    block + inverse in ~290 µs vs ~1190 µs for XLA's cholesky on the
    same chip.  f32 only (other dtypes take the XLA base case).
    """

    from .pallas_kernels import chol_inv_panel

    def panel(akk, w):
        if w == nb and (nb & (nb - 1)) == 0 and a.dtype == jnp.float32:
            return chol_inv_panel(akk)
        return _chol_panel_xla(akk, w)

    return _potrf_strips(a, nb, panel)


def _chol_panel_xla(akk, w):
    """XLA base-case panel: factor + explicit inverse.  Reads only the
    stored lower triangle — the strip updates never touch the
    strictly-upper part, so it may hold stale values."""
    lkk = jnp.tril(lax.linalg.cholesky(
        jnp.tril(akk), symmetrize_input=False))
    linv = lax.linalg.triangular_solve(
        lkk, jnp.eye(w, dtype=akk.dtype), left_side=True, lower=True)
    return lkk, linv


def _potrf_strips(a, nb, panel):
    """Shared right-looking strip-wise Cholesky core: ``panel(akk, w)``
    returns the diagonal block's (L, L⁻¹); everything else — the panel
    trsm-as-gemm and the triangular trailing update in block-column
    strips — is identical across the f32/f64 drivers."""
    from ..perf import metrics

    n = a.shape[-1]
    # trailing strip width: measured optimum on v5e (tools sweep:
    # ws=2048 → 54.9 TF/s, 4096 → 39.9, full-square → 29.9 at n=8192),
    # rounded to a multiple of nb so strip boundaries never fall inside a
    # later diagonal block (the strip update only writes rows >= its own
    # start, so an interior boundary would leave that block's upper
    # triangle stale)
    ws = nb * max(1, 2048 // nb)
    for k0 in range(0, n, nb):
        w = min(nb, n - k0)
        akk = a[k0:k0 + w, k0:k0 + w]
        with metrics.step_timer("potrf", "panel"):
            lkk, linv = panel(akk, w)
            a = a.at[k0:k0 + w, k0:k0 + w].set(lkk)
        if k0 + w < n:
            with metrics.step_timer("potrf", "trsm"):
                l21 = matmul(a[k0 + w:, k0:k0 + w], _ct(linv))
                a = a.at[k0 + w:, k0:k0 + w].set(l21)
            # triangular trailing update in block-column strips: strip j
            # only updates rows >= its own start.  Each materialized
            # inter-stage intermediate (the l21 write-back + one
            # read-modify-write per strip) is an HBM round trip the
            # fused step kernel does not pay — counted so CI can pin
            # the fused path at zero.
            nstrips = len(range(k0 + w, n, ws))
            metrics.count_hbm_roundtrips(1.0 + nstrips)
            # LP-GEMM operand folding: when the matmul site resolves to
            # a split backend for this step's strip products, the
            # resident panel splits into its bf16 slices ONCE here —
            # the elementwise split commutes with slicing, so every
            # strip reuses row/column windows of the same slices
            # instead of re-splitting per chunk.
            sl = sr = None
            if a.ndim == 2 and a.dtype == jnp.float32 and nstrips:
                from ..perf.autotune import choose_matmul

                mrem = n - (k0 + w)
                sbk = choose_matmul((mrem, w), (w, mrem), a.dtype)
                if sbk in ("split3", "split6"):
                    from .split_gemm import split_slices

                    sl = split_slices(l21)
                    sr = tuple(_ct(s) for s in sl)
            with metrics.step_timer("potrf", "update"):
                for j0 in range(k0 + w, n, ws):
                    jw = min(ws, n - j0)
                    o = j0 - (k0 + w)
                    if sl is not None:
                        from .split_gemm import matmul_sliced

                        upd = matmul_sliced(
                            sbk, tuple(s[o:] for s in sl),
                            tuple(s[:, o:o + jw] for s in sr))
                    else:
                        upd = matmul(l21[o:], _ct(l21[o:o + jw]))
                    a = a.at[j0:, j0:j0 + jw].add(-upd)
    return jnp.tril(a)


def _chol_panel_refine_f64(akk):
    """fp64 diagonal-block Cholesky + inverse at MXU speed: factor the
    f32 image with the fused Pallas panel kernel, then take ONE fp64
    Newton step on the factor (``F = X₀(A − L₀L₀ᵀ)X₀ᵀ``,
    ``L₁ = L₀(I + tril(F,−1) + diag(F)/2)``) and one on the inverse
    (``X₁ = X₀ + X₀(I − L₁X₀)``).  Quadratic convergence takes the
    eps32-grade seed to ~cond²·eps32² ≈ fp64 grade for the
    well-conditioned trailing-updated diagonal blocks potrf produces.

    Precision placement: only the two products of f32-exact operands
    against themselves — ``L₀L₀ᵀ`` and ``L₀X₀`` — enter the residuals
    at full scale and ride the Ozaki fp64 MXU path (:func:`matmul`);
    every other product multiplies an O(ε₃₂) residual where f32
    ``HIGHEST`` already delivers the O(ε₃₂²) ≈ fp64-grade absolute
    accuracy the correction needs.  That keeps the per-panel graph at
    2 Ozaki + 5 plain dots (compile-size matters: the panel body is
    unrolled once per block column).

    A SECOND Newton step runs entirely on O(ε₃₂)-scale f32 products
    (the step-2 residual comes incrementally: ``A − L₁L₁ᵀ =
    r − L₁ΔLᵀ − ΔL·L₀ᵀ``, and ``I − L₁X₁ = (I − L₁X₀)²`` exactly), so
    blocks up to cond ~1e7 reach fp64-grade instead of stalling at the
    one-step (cond·ε₃₂)² floor.

    Breakdown (f32 cholesky of a block with cond ≳ 1/ε₃₂ goes
    non-finite) propagates NaN out of this panel; the driver
    (:func:`slate_tpu.linalg.cholesky.potrf`) detects it and reruns the
    whole factorization on XLA's emulated-fp64 path via ``lax.cond``.
    """
    from .pallas_kernels import chol_inv_panel

    hi = lax.Precision.HIGHEST

    def mm32(p, q):
        return jnp.matmul(p, q, precision=hi)

    w = akk.shape[-1]
    eye = jnp.eye(w, dtype=jnp.float64)
    asym = jnp.tril(akk) + _ct(jnp.tril(akk, -1))
    l0_32, x0_32 = chol_inv_panel(asym.astype(jnp.float32))
    l0_32 = jnp.tril(l0_32)
    x0_32 = jnp.tril(x0_32)
    l0 = l0_32.astype(jnp.float64)
    x0 = x0_32.astype(jnp.float64)
    # r = A − L₀L₀ᵀ: cancellation at full scale — exact-product path
    r = asym - matmul(l0, _ct(l0))
    # F = X₀ r X₀ᵀ is already O(ε₃₂): f32 products leave O(ε₃₂²)
    r32 = r.astype(jnp.float32)
    f1 = mm32(mm32(x0_32, r32), x0_32.T)
    corr1 = jnp.tril(f1, -1) + jnp.diag(0.5 * jnp.diagonal(f1))
    dl1 = mm32(l0_32, corr1)
    l1 = jnp.tril(l0 + dl1.astype(jnp.float64))
    # inverse Newton vs L₁ = L₀ + ΔL:  I − L₁X₀ = (I − L₀X₀) − ΔL·X₀
    e1 = (eye - matmul(l0, x0)) \
        - mm32(dl1, x0_32).astype(jnp.float64)
    e1_32 = e1.astype(jnp.float32)
    x1 = jnp.tril(x0 + mm32(x0_32, e1_32).astype(jnp.float64))

    # ---- second Newton step, all on residual-scale f32 products ----
    l1_32 = l1.astype(jnp.float32)
    x1_32 = x1.astype(jnp.float32)
    # A − L₁L₁ᵀ = r − L₁ΔLᵀ − ΔL·L₀ᵀ  (exact expansion of (L₀+ΔL)(…)ᵀ)
    r2 = r - (mm32(l1_32, dl1.T).astype(jnp.float64)
              + mm32(dl1, l0_32.T).astype(jnp.float64))
    f2 = mm32(mm32(x1_32, r2.astype(jnp.float32)), x1_32.T)
    corr2 = jnp.tril(f2, -1) + jnp.diag(0.5 * jnp.diagonal(f2))
    dl2 = mm32(l1_32, corr2)
    l2 = jnp.tril(l1 + dl2.astype(jnp.float64))
    # I − L₂X₁ = (I − L₁X₁) − ΔL₂X₁ = e₁² − ΔL₂X₁  (algebraic identity)
    e2 = (mm32(e1_32, e1_32) - mm32(dl2, x1_32)).astype(jnp.float64)
    x2 = jnp.tril(x1 + mm32(x1_32, e2.astype(jnp.float32))
                  .astype(jnp.float64))
    return l2, x2


def potrf_panels_f64(a, nb: int = 512):
    """fp64 variant of :func:`potrf_panels` for TPU: same strip-wise
    right-looking structure, panel step = :func:`_chol_panel_refine_f64`
    (f32 Pallas kernel + two fp64 Newton steps), trailing gemms on the
    Ozaki fp64 MXU path.  Replaces XLA's software-emulated fp64
    cholesky (~59 GF/s at n=4096 measured) with MXU-rate factorization;
    blocks whose f32 seed breaks down (cond ≳ 1/ε₃₂) propagate NaN,
    which the potrf driver detects to rerun on the emulated path.
    """

    def panel(akk, w):
        if w == nb and (nb & (nb - 1)) == 0:
            return _chol_panel_refine_f64(akk)
        return _chol_panel_xla(akk, w)

    return _potrf_strips(a, nb, panel)
