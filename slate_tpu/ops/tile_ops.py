"""Elementwise / norm tile kernels.

TPU-native analog of the reference device kernel set declared in
``include/slate/internal/device.hh:82-266`` and implemented three times in
``src/cuda/``, ``src/hip/``, ``src/omptarget/`` (geadd, gecopy, genorm,
gescale, gescale_row_col, geset, henorm, synorm, transpose, trnorm, tzadd,
tzcopy, tzscale, tzset).  One implementation replaces all three backends:
each op is a pure jnp function over arrays of shape ``(..., mb, nb)`` — the
leading batch dims play the role of the reference's batched tile-pointer
arrays, and XLA fuses these into neighbouring matmuls instead of launching
standalone kernels.

Precision-converting copy (reference ``gecopy`` with distinct src/dst
types, ``src/cuda/device_gecopy.cu``) is ``gecopy(a, dtype=...)``.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..enums import Norm, Uplo


def geset(shape, offdiag_value, diag_value, dtype=jnp.float32):
    """Set tile to a constant with a different diagonal
    (ref ``device::geset``, ``device.hh``)."""
    m, n = shape[-2], shape[-1]
    eye = jnp.eye(m, n, dtype=bool)
    out = jnp.full(shape, offdiag_value, dtype)
    return jnp.where(eye, jnp.asarray(diag_value, dtype), out)


def tzset(shape, uplo: Uplo, offdiag_value, diag_value, dtype=jnp.float32):
    """Trapezoid set (ref ``device::tzset``): only the stored triangle."""
    m, n = shape[-2], shape[-1]
    full = geset(shape, offdiag_value, diag_value, dtype)
    i = jnp.arange(m)[:, None]
    j = jnp.arange(n)[None, :]
    keep = (i >= j) if uplo is Uplo.Lower else (i <= j)
    return jnp.where(keep, full, 0)


def geadd(alpha, a, beta, b):
    """B = alpha*A + beta*B (ref ``device::geadd``)."""
    return alpha * a + beta * b


def tzadd(uplo: Uplo, alpha, a, beta, b):
    m, n = a.shape[-2], a.shape[-1]
    i = jnp.arange(m)[:, None]
    j = jnp.arange(n)[None, :]
    keep = (i >= j) if uplo is Uplo.Lower else (i <= j)
    return jnp.where(keep, alpha * a + beta * b, b)


def gecopy(a, dtype=None):
    """Copy, optionally precision-converting (ref ``device::gecopy``)."""
    return a.astype(dtype) if dtype is not None else a


def tzcopy(uplo: Uplo, a, b, dtype=None):
    """Copy the ``uplo`` trapezoid of A over B, optionally converting
    precision (ref ``device::tzcopy``, ``src/cuda/device_tzcopy.cu``)."""
    m, n = a.shape[-2], a.shape[-1]
    i = jnp.arange(m)[:, None]
    j = jnp.arange(n)[None, :]
    keep = (i >= j) if uplo is Uplo.Lower else (i <= j)
    out_dtype = dtype or b.dtype
    return jnp.where(keep, a.astype(out_dtype), b.astype(out_dtype))


def gescale(numer, denom, a):
    """A *= numer/denom (ref ``device::gescale``) — the two-scalar form
    avoids overflow when numer/denom would."""
    return a * (jnp.asarray(numer, a.dtype) / jnp.asarray(denom, a.dtype))


def gescale_row_col(r, c, a):
    """A = diag(r) · A · diag(c) (ref ``device::gescale_row_col``)."""
    return a * r[..., :, None] * c[..., None, :]


def transpose(a, conj: bool = False):
    """Batched (conjugate-)transpose (ref ``device::transpose``)."""
    t = jnp.swapaxes(a, -1, -2)
    return jnp.conj(t) if conj else t


def _abs(a):
    return jnp.abs(a)


def genorm(norm: Norm, a, axis=(-2, -1)):
    """Per-tile general-matrix norm (ref ``device::genorm``,
    ``src/cuda/device_genorm.cu``).  Returns, per batch element:

    * Max  → scalar max|a|
    * One  → vector of column sums (reduced over rows)
    * Inf  → vector of row sums
    * Fro  → (scaled) sum of squares as a scalar ‖a‖_F
    """
    if norm is Norm.Max:
        return jnp.max(_abs(a), axis=axis)
    if norm is Norm.One:
        return jnp.sum(_abs(a), axis=-2)
    if norm is Norm.Inf:
        return jnp.sum(_abs(a), axis=-1)
    if norm is Norm.Fro:
        return jnp.sqrt(jnp.sum(_abs(a) ** 2, axis=axis))
    raise ValueError(f"unsupported norm {norm}")


def trnorm(norm: Norm, uplo: Uplo, a, diag_one: bool = False):
    """Trapezoid/triangular tile norm (ref ``device::trnorm``)."""
    m, n = a.shape[-2], a.shape[-1]
    i = jnp.arange(m)[:, None]
    j = jnp.arange(n)[None, :]
    keep = (i >= j) if uplo is Uplo.Lower else (i <= j)
    masked = jnp.where(keep, a, 0)
    if diag_one:
        eye = jnp.eye(m, n, dtype=bool)
        masked = jnp.where(eye, jnp.asarray(1, a.dtype), masked)
    return genorm(norm, masked)


def synorm(norm: Norm, uplo: Uplo, a):
    """Symmetric tile norm over the stored triangle mirrored
    (ref ``device::synorm``)."""
    full = symmetrize(uplo, a)
    return genorm(norm, full)


def henorm(norm: Norm, uplo: Uplo, a):
    full = hermitize(uplo, a)
    return genorm(norm, full)


def symmetrize(uplo: Uplo, a):
    """Reflect the stored triangle to form the full symmetric matrix."""
    n = a.shape[-1]
    if uplo is Uplo.Lower:
        t = jnp.tril(a, -1)
    else:
        t = jnp.triu(a, 1)
    d = jnp.diagonal(a, axis1=-2, axis2=-1)
    return t + jnp.swapaxes(t, -1, -2) + d[..., None] * jnp.eye(n, dtype=a.dtype)


def hermitize(uplo: Uplo, a):
    """Reflect with conjugation; the diagonal is forced real
    (Hermitian semantics, ref ``HermitianMatrix``)."""
    n = a.shape[-1]
    if uplo is Uplo.Lower:
        t = jnp.tril(a, -1)
    else:
        t = jnp.triu(a, 1)
    d = jnp.real(jnp.diagonal(a, axis1=-2, axis2=-1)).astype(a.dtype)
    return t + jnp.conj(jnp.swapaxes(t, -1, -2)) + d[..., None] * jnp.eye(n, dtype=a.dtype)
