"""Shared VMEM working-set budget arithmetic for the fused Pallas
kernels — ONE place that knows how much scratch a ``pallas_call`` may
pin, instead of per-gate copy-pasted constants.

Every fused mega-kernel (LU panel/step, potrf step, and the grid-batched
many-problem kernels) pins a ~110 MB ``vmem_limit_bytes`` in its
compiler params and must leave headroom for Mosaic's own spills; until
round 8 each eligibility gate carried its own ``100 * 1024 * 1024``
literal and its own bytes formula.  The batched drivers make that
untenable: their gates must additionally solve for **B-per-launch** (how
many whole problems one grid step may hold resident), which is the same
budget question asked one more time.  This module centralizes it:

* :data:`BUDGET_BYTES` — the single shared working-set budget;
* :func:`fits` — does a working set fit;
* :func:`batch_per_launch` — the largest per-grid-step problem count
  whose resident working set fits (the batched kernels' ``bt``).

The budget is overridable per process with ``SLATE_TPU_VMEM_BUDGET_MB``
(new TPU generations ship different VMEM sizes; the gates all move
together).
"""

from __future__ import annotations

import os

__all__ = ["BUDGET_BYTES", "PALLAS_CALL_LIMIT_BYTES", "budget_bytes",
           "pallas_call_limit_bytes", "fits", "batch_per_launch",
           "checksum_block_rows", "largest_tc"]

#: default ``vmem_limit_bytes`` the fused kernels pin in their
#: pallas_call compiler params (what Mosaic is allowed to allocate).
PALLAS_CALL_LIMIT_BYTES = 110 * 1024 * 1024

#: default working-set budget the ELIGIBILITY gates plan against — the
#: pinned limit minus headroom for Mosaic's own spills/temporaries.
BUDGET_BYTES = 100 * 1024 * 1024

#: the headroom between what the gates plan and what the kernels pin
#: — kept as the DIFFERENCE so an env-overridden budget moves both
#: numbers together (a raised budget with a stale 110 MB pin would
#: admit working sets Mosaic cannot allocate).
_HEADROOM_BYTES = PALLAS_CALL_LIMIT_BYTES - BUDGET_BYTES


def budget_bytes() -> int:
    """The planning budget, honouring ``SLATE_TPU_VMEM_BUDGET_MB``."""
    raw = os.environ.get("SLATE_TPU_VMEM_BUDGET_MB", "").strip()
    if raw:
        try:
            return int(float(raw) * 1024 * 1024)
        except ValueError:
            pass
    return BUDGET_BYTES


def pallas_call_limit_bytes() -> int:
    """The ``vmem_limit_bytes`` a fused kernel should pin: the planning
    budget plus the spill headroom — tracks the env override so the
    gates and the compiler cap can never disagree."""
    return budget_bytes() + _HEADROOM_BYTES


def fits(working_set_bytes: float) -> bool:
    """True when a kernel's resident working set fits the budget."""
    return working_set_bytes <= budget_bytes()


def largest_tc(nb: int, bytes_at, floor: int = 128) -> int:
    """Trailing-chunk edge planner shared by the fused step/full
    kernels: the largest divisor of ``nb`` on the halving chain (floor
    ``floor``) whose working set ``bytes_at(tc)`` fits the budget.
    Halves only while the result stays at/above the floor — nb need
    only be a multiple of the floor, so a blind halving chain could
    dip below it for nb = 384, 640, ...."""
    tc = nb
    while tc // 2 >= floor and not fits(bytes_at(tc)):
        tc //= 2
    return tc


#: sublane tile edge per element width — the row granularity TPU
#: operand slabs tile at (8 f32 rows, 4 f64 rows per sublane tile).
_SUBLANE_ROWS = {4: 8, 8: 4}


def checksum_block_rows(dtype) -> int:
    """Height of the ABFT checksum block-row
    (:mod:`slate_tpu.resilience.abft`): ONE checksum lane padded up to
    the dtype's sublane tile edge, so a checksum-augmented operand
    ``[A; eᵀA]`` keeps the row-divisibility every tile-shaped gate and
    kernel in this package assumes (the pad lanes ride the trailing
    gemm as exact zeros).  The same constant is what the attribution
    model prices the checksum traffic with
    (``slate_tpu/perf/attr.py``)."""
    import numpy as np

    return _SUBLANE_ROWS.get(np.dtype(dtype).itemsize, 8)


def batch_per_launch(per_problem_bytes: float, fixed_bytes: float = 0.0,
                     cap: int = 0) -> int:
    """How many whole problems one grid step of a batched kernel may
    hold resident: the largest ``bt ≥ 1`` with ``fixed_bytes + bt ·
    per_problem_bytes`` inside the budget (0 when even one problem
    doesn't fit).  ``cap`` bounds the answer (e.g. the actual batch
    size, or a lane-dimension tile limit)."""
    if per_problem_bytes <= 0:
        return max(1, cap) if cap else 1
    avail = budget_bytes() - fixed_bytes
    bt = int(avail // per_problem_bytes)
    if cap:
        bt = min(bt, cap)
    return max(0, bt)
