"""Ozaki-style fp64 matmul on the MXU (int8 slice products).

TPU matrix units multiply bf16/int8; fp64 arrives only through XLA's
software emulation (~1.1 TF/s measured at n=4096 on v5e — STATUS §6).
This module implements the error-free-splitting scheme (Ozaki et al.,
and its integer tensor-core descendants) the survey names as the TPU
answer to the reference's native-fp64 ``blas::batch::gemm`` role
(``/root/reference/src/internal/internal_gemm.cc:614-689``, SURVEY §7
hard part #5): split each fp64 operand into 6-bit integer slices whose
pairwise products accumulate EXACTLY in the MXU's s32 accumulator, then
combine the slice products in fp64.

Scheme:

1.  Row-scale A (col-scale B) by the power of two that brings each
    row's (col's) max magnitude into [1/4, 1): ``r = a · 2^{−e}``
    (``exp2`` of an integer-valued float is correctly rounded, hence
    exact — no frexp/ldexp, whose s64 bitcasts TPU's X64 rewriter
    rejects).
2.  Slice ``r`` into ``W = 6``-bit mantissa windows: slice ``t`` holds
    bits ``[Wt, W(t+1))`` as an integer in [−64, 64] — int8-exact.
    The extraction runs 4 windows at a time on an f32 image of the
    fp64 remainder (f32 holds exactly 4 windows), so the expensive
    emulated-fp64 traffic is 2 casts + 1 exact reconstruct-subtract
    per 4 slices instead of 4 fp64 ops per slice.  The f32 image
    ROUNDS at its 24th bit; the spill (±1 in the group's last window,
    hence values up to ±64, still int8/product-safe) is recovered
    exactly by the fp64 remainder update, so no accuracy is lost.
3.  For every slice pair with ``t + s ≤ SMAX`` (= 7), one int8×int8
    MXU product with s32 accumulation.  Each scalar product has ≤ 12
    bits, and pairs sharing a total weight (up to ``_NSL`` of them)
    are summed in one s32 group before the single fp64 cast per
    diagonal — so the contraction is chunked at ``_KMAX =
    2^{31−12−ceil(log2(_NSL))}`` (65536 for the default 8 slices) to
    keep every group sum exactly below 2³¹.
4.  Combine the 8 diagonal sums in fp64 with their window weights
    ``2^{−W(tot+2)}`` and undo the row/col scaling.

Error: exact up to the dropped tail (pairs with ``t+s > 7``), bounded
by ``k · Σ_{t+s≥8} 2^{12−W(t+s+2)} ≈ k · 2^{−48}`` relative to the
row/col scale — inside LAPACK's own ``k·ε₆₄`` backward-error envelope
for dgemm, and measured ~1e-15 max componentwise relative error against
NumPy fp64 (vs ~2.4e-4 for a plain f32 gemm at n=4096).

Throughput: measured ~4 TF/s fp64-equivalent at n=4096 on v5e
(BENCH_r05), ~3.5× XLA's emulated fp64 dot; the slice/pair multiplier
is constant in n.

Caveats: real f64 only (complex128 falls back to XLA emulation at the
dispatch site, :func:`slate_tpu.ops.blocks.matmul`); non-finite inputs
produce garbage (the scaling/truncation passes have no Inf/NaN path),
as with every error-free-transformation scheme.  Subnormal entries
contribute zero: XLA's backends run DAZ/FTZ, so the values are flushed
before the split can boost them — the same semantics as vendor BLAS in
flush-to-zero mode (verified: a 2^-1060 × 2^1000 product yields 0, not
NaN/Inf).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import lax

_W = 6                        # mantissa bits per slice
# 8 slices · 6 bits = 48 bits + the pair tail ≈ k·ε₆₄-grade; SMAX pairs
# (t+s ≤ 7) keep 36 of the 64 products.  SLATE_TPU_F64_SLICES=9 buys
# the full-53-bit split (45 pairs, ~20% slower) if a caller needs it.
_NSL = int(os.environ.get("SLATE_TPU_F64_SLICES", "8"))
_SMAX = _NSL - 1
# Exactness cap: one diagonal group sums up to _NSL pair products, each
# a sum of k terms ≤ 2^{2W} (slice values reach ±2^W at the f32-image
# rounding boundary), so k ≤ 2^{31 − 2W − ceil(log2(_NSL))} keeps
# |group| < 2^31.  65536 for the default 8 slices.
_KMAX = 1 << (31 - 2 * _W - max(1, (_NSL - 1).bit_length()))


def _split_int8(r):
    """Slice ``r`` (|r| < 1, fp64) into 6-bit int8 windows, 4 per f32
    image of the running remainder.  Every step is a power-of-two
    scale, a truncation, or an exactly-representable difference."""
    slices = []
    rem = r
    t = 0
    while t < _NSL:
        ngrp = min(4, _NSL - t)
        c = rem.astype(jnp.float32)
        recon = None
        for j in range(ngrp):
            w = _W * (t + j + 1)
            d = jnp.trunc(c * jnp.float32(2.0 ** w))
            term = d * jnp.float32(2.0 ** -w)
            c = c - term
            recon = term if recon is None else recon + term
            slices.append(d.astype(jnp.int8))
        t += ngrp
        if t < _NSL:
            rem = rem - recon.astype(jnp.float64)
    return slices


def _pow2_scale(ax):
    """Integer-valued ``e`` with ``ax · 2^{−e} ∈ [1/4, 1)`` (0 where
    ``ax == 0``).  log2+floor with a one-step fixup for the rounding of
    ``log2`` at exact powers of two."""
    safe = jnp.where(ax > 0, ax, 1.0)
    # XLA's log2 flushes subnormals to -inf; boost tiny magnitudes into
    # the normal range first (exact power-of-two multiply)
    tiny = safe < 2.0 ** -900
    boosted = jnp.where(tiny, safe * 2.0 ** 900, safe)
    e = jnp.where(ax > 0,
                  jnp.floor(jnp.log2(boosted)) + 1.0
                  - jnp.where(tiny, 900.0, 0.0), 0.0)
    r = _mul_pow2(ax, -e)
    e = e + (r >= 1.0)          # overshoot: bring max below 1
    e = e - (r < 0.25)          # undershoot by a full step
    return e


def _mul_pow2(x, e):
    """``x · 2^e`` for integer-valued fp64 ``e``, exact, with the scale
    split into two half-exponent factors: a single ``exp2(e)`` is
    Inf/zero for |e| ≳ 1024 even when the product itself is in range
    (huge-scale rows against tiny-scale columns)."""
    e1 = jnp.trunc(e * 0.5)
    return x * jnp.exp2(e1) * jnp.exp2(e - e1)


def _chunk_matmul(a, b):
    """One ≤-KMAX-contraction chunk: split, pair products, f64 combine."""
    ea = _pow2_scale(jnp.max(jnp.abs(a), axis=1))
    eb = _pow2_scale(jnp.max(jnp.abs(b), axis=0))
    ra = _mul_pow2(a, -ea[:, None])
    rb = _mul_pow2(b, -eb[None, :])
    ua = _split_int8(ra)
    vb = _split_int8(rb)

    acc = None
    for tot in range(_SMAX + 1):
        pairs = [(t, tot - t) for t in range(max(0, tot - _NSL + 1),
                                             min(tot, _NSL - 1) + 1)]
        g = None
        for t, s in pairs:
            p = lax.dot(ua[t], vb[s], preferred_element_type=jnp.int32)
            g = p if g is None else g + p          # exact in s32
        scaled = g.astype(jnp.float64) * (2.0 ** (-_W * (tot + 2)))
        acc = scaled if acc is None else acc + scaled

    # rescale on the combined per-element exponent, half-split so no
    # intermediate overflows while the true product is in range
    return _mul_pow2(acc, ea[:, None] + eb[None, :])


def matmul_f64(a, b):
    """``a @ b`` for real fp64 2-D operands via MXU int8 slice products.

    Contractions longer than ``_KMAX`` are chunked so every chunk's
    s32 accumulation stays exact; chunk results are summed in fp64.
    """
    if a.dtype != jnp.float64 or b.dtype != jnp.float64:
        raise TypeError("matmul_f64 requires float64 operands")
    if a.ndim != 2 or b.ndim != 2:
        raise ValueError("matmul_f64 is 2-D only")
    k = a.shape[1]
    if k == 0:
        return jnp.zeros((a.shape[0], b.shape[1]), jnp.float64)
    nchunks = -(-k // _KMAX)
    bounds = [(k * i) // nchunks for i in range(nchunks + 1)]
    out = None
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        part = _chunk_matmul(a[:, lo:hi], b[lo:hi, :])
        out = part if out is None else out + part
    return out
