"""Host-DRAM-backed tile pool — software residency for out-of-core
factorizations (ISSUE 17).

Every in-core driver assumes the whole matrix fits HBM, which caps the
single-chip size axis around n=65536 fp32.  This module stores a matrix
as an (nb, nb)-tile grid in host DRAM and manages a BOUNDED window of
device-resident tiles with the classic software-cache trio:

* **LRU residency** — ``get()`` returns the device copy of a tile,
  fetching over the host link on a miss and evicting the
  least-recently-used resident tile once the window is full;
* **dirty write-back** — tiles rewritten by the factorization
  (``put()``) are marked dirty and flushed to host DRAM exactly once,
  at eviction or ``flush()``, so host DRAM is the single source of
  truth between steps (the coherence protocol is trivial because there
  is one device);
* **async prefetch** — ``prefetch()`` issues ``jax.device_put`` for the
  tiles the next panel/trailing strip will need WITHOUT blocking; the
  transfer overlaps the current step's MXU work exactly like the
  double-buffered ``_stream_chunks`` DMA residency inside the fused
  step kernels (ops/pallas_kernels.py), one level up the hierarchy
  (PCIe→HBM instead of HBM→VMEM).

The BLASX two-level tile-cache design (PAPERS.md) is the shape being
reproduced: compute stays at in-core rates while the working set lives
a PCIe hop away, and the prefetch schedule is priced — not guessed —
by the ``host`` roofline stage in :mod:`slate_tpu.perf.attr` on the
``SLATE_TPU_PCIE_GBS`` link peak, arbitrated through the ``ooc``
autotune site.

Observability rides the PR 4 metrics contract: the
``ooc.prefetch.hits`` / ``ooc.prefetch.misses`` / ``ooc.evictions`` /
``ooc.write_backs`` counters and the ``ooc.host_bytes`` byte odometer
are all routed through :func:`slate_tpu.perf.metrics.inc`, so with the
registry off (the default) each event costs one attribute read and
records nothing.

Inert at import: importing this module touches no jax API, allocates
nothing on any device and reads no environment variable — all state is
per-:class:`TilePool` (pinned by tests/test_backend_registry.py).
"""

from __future__ import annotations

import os
from collections import OrderedDict

import numpy as np

from ..perf import metrics

__all__ = [
    "TilePool", "DEFAULT_WINDOW_TILES", "DEFAULT_PREFETCH_DEPTH",
    "window_tiles", "prefetch_depth", "hbm_budget_bytes", "ooc_nb",
]

#: resident-window size (tiles) when ``SLATE_TPU_OOC_WINDOW_TILES`` is
#: unset: 64 × (512² fp32 = 1 MiB) tiles ≈ 64 MiB of managed HBM per
#: pool — small against any real HBM, large enough that one panel plus
#: the strip being updated plus the prefetch depth all stay resident.
DEFAULT_WINDOW_TILES = 64

#: tiles fetched ahead per ``prefetch()`` call when
#: ``SLATE_TPU_OOC_PREFETCH_DEPTH`` is unset.
DEFAULT_PREFETCH_DEPTH = 4


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


def window_tiles() -> int:
    """Resident-window capacity in tiles (``SLATE_TPU_OOC_WINDOW_TILES``,
    floor 2 — one tile being computed on plus one being prefetched)."""
    return max(2, _env_int("SLATE_TPU_OOC_WINDOW_TILES",
                           DEFAULT_WINDOW_TILES))


def prefetch_depth() -> int:
    """Prefetch look-ahead in tiles (``SLATE_TPU_OOC_PREFETCH_DEPTH``,
    0 disables prefetching; capped by the window so prefetch can never
    thrash the tile being computed on)."""
    return max(0, _env_int("SLATE_TPU_OOC_PREFETCH_DEPTH",
                           DEFAULT_PREFETCH_DEPTH))


def hbm_budget_bytes() -> int:
    """The HBM byte budget the ``ooc`` autotune site weighs a working
    set against (``SLATE_TPU_OOC_HBM_MB``, default 24576 MiB — one
    v5p-class chip next to the 819 GB/s roofline constant in
    perf/attr.py)."""
    return _env_int("SLATE_TPU_OOC_HBM_MB", 24576) * (1 << 20)


def ooc_nb() -> int:
    """The out-of-core tile edge (``SLATE_TPU_OOC_NB``, default 512 —
    the fused step kernels' panel width, so the pool feeds the existing
    lu_step/potrf_step rungs exactly the operand shapes they already
    tune for)."""
    return max(8, _env_int("SLATE_TPU_OOC_NB", 512))


class TilePool:
    """A bounded device-resident window over a host-DRAM tile grid.

    ``a`` (array-like, 2-D) is copied into a zero-padded host grid of
    ``(nb, nb)`` tiles.  ``capacity`` bounds the number of
    simultaneously resident device tiles (default
    :func:`window_tiles`); ``depth`` the prefetch look-ahead (default
    :func:`prefetch_depth`).  ``op`` names the driver for the
    ``step.<op>.host`` stage timer so the attr.py measured-timer join
    sees the host-transfer stage like every other stage.

    Device arrays returned by :meth:`get` stay valid after eviction
    (eviction drops the pool's reference, not the buffer), so a caller
    may assemble a panel strip wider than the window — the window then
    only determines how much re-use the NEXT step gets for free.
    Residency never changes arithmetic: results are bitwise identical
    across window sizes (pinned in tests/test_tilepool.py).
    """

    def __init__(self, a, nb: int, capacity: int | None = None,
                 depth: int | None = None, op: str = "ooc"):
        a_np = np.asarray(a)
        if a_np.ndim != 2:
            raise ValueError(f"TilePool needs a 2-D matrix, got "
                             f"{a_np.shape}")
        self.nb = int(nb)
        self.m, self.n = (int(a_np.shape[0]), int(a_np.shape[1]))
        self.gi = -(-self.m // self.nb)
        self.gj = -(-self.n // self.nb)
        self.dtype = a_np.dtype
        self.op = op
        host = np.zeros((self.gi * self.nb, self.gj * self.nb),
                        dtype=a_np.dtype)
        host[:self.m, :self.n] = a_np
        self.host = host
        self.capacity = max(2, int(capacity) if capacity is not None
                            else window_tiles())
        self.depth = (int(depth) if depth is not None
                      else prefetch_depth())
        self._resident: OrderedDict = OrderedDict()   # (i, j) -> device
        self._dirty: set = set()
        self._prefetched: set = set()
        #: total bytes moved across the host link, both directions —
        #: the measured number behind the bench `_host_gb_transferred`
        #: submetric and the attr.py host-stage byte model
        self.bytes_moved = 0

    # -- geometry ----------------------------------------------------------

    @property
    def tile_bytes(self) -> int:
        return self.nb * self.nb * self.dtype.itemsize

    def _slice(self, i: int, j: int):
        nb = self.nb
        return (slice(i * nb, (i + 1) * nb), slice(j * nb, (j + 1) * nb))

    # -- the residency protocol --------------------------------------------

    def _fetch(self, i: int, j: int):
        """host → device transfer of one tile (async under the hood —
        ``jax.device_put`` returns a future-backed array, so a prefetch
        overlaps whatever the MXU is doing now)."""
        import jax

        self.bytes_moved += self.tile_bytes
        metrics.inc("ooc.host_bytes", float(self.tile_bytes))
        return jax.device_put(self.host[self._slice(i, j)])

    def _write_back(self, key, dev) -> None:
        """device → host flush of one dirty tile (exact: the host copy
        is byte-for-byte the device value)."""
        with metrics.step_timer(self.op, "host"):
            self.host[self._slice(*key)] = np.asarray(dev)
        self.bytes_moved += self.tile_bytes
        metrics.inc("ooc.host_bytes", float(self.tile_bytes))
        metrics.inc("ooc.write_backs")

    def _evict_to_capacity(self, keep=()) -> None:
        while len(self._resident) > self.capacity:
            victim = next((k for k in self._resident if k not in keep),
                          None)
            if victim is None:
                return            # everything pinned by the caller
            dev = self._resident.pop(victim)
            self._prefetched.discard(victim)
            if victim in self._dirty:
                self._dirty.discard(victim)
                self._write_back(victim, dev)
            metrics.inc("ooc.evictions")

    def get(self, i: int, j: int):
        """The device copy of tile (i, j): a window hit is free, a miss
        pays one synchronous host→HBM transfer and may evict the LRU
        resident tile (writing it back first when dirty)."""
        key = (i, j)
        dev = self._resident.get(key)
        if dev is not None:
            self._resident.move_to_end(key)
            if key in self._prefetched:
                self._prefetched.discard(key)
                metrics.inc("ooc.prefetch.hits")
            return dev
        metrics.inc("ooc.prefetch.misses")
        with metrics.step_timer(self.op, "host"):
            dev = self._fetch(i, j)
        self._resident[key] = dev
        self._evict_to_capacity(keep=(key,))
        return dev

    def put(self, i: int, j: int, dev) -> None:
        """Install a freshly computed device tile as the resident copy
        and mark it dirty (host DRAM is stale until write-back)."""
        key = (i, j)
        self._resident[key] = dev
        self._resident.move_to_end(key)
        self._dirty.add(key)
        self._prefetched.discard(key)
        self._evict_to_capacity(keep=(key,))

    def prefetch(self, coords) -> int:
        """Issue host→HBM transfers for up to ``depth`` of ``coords``
        not yet resident, without blocking: ``jax.device_put`` queues
        the copy and returns immediately, so the next panel's tiles
        stream in UNDER the current step's compute (the
        ``_stream_chunks`` overlap, one level up).  Returns the number
        of transfers issued."""
        budget = min(self.depth, max(0, self.capacity - 1))
        issued = 0
        for key in coords:
            if issued >= budget:
                break
            if key in self._resident:
                continue
            self._resident[key] = self._fetch(*key)
            self._prefetched.add(key)
            self._evict_to_capacity(keep=(key,))
            issued += 1
        return issued

    # -- coherence ----------------------------------------------------------

    def flush(self) -> None:
        """Write back every dirty resident tile; host DRAM becomes the
        exact image of the computation so far (window boundaries call
        this before a checkpoint snapshot)."""
        for key in list(self._dirty):
            self._write_back(key, self._resident[key])
        self._dirty.clear()

    def array(self) -> np.ndarray:
        """Flush and return the (trimmed, copied) host matrix."""
        self.flush()
        return self.host[:self.m, :self.n].copy()

    def host_gb_transferred(self) -> float:
        return self.bytes_moved / 1e9
