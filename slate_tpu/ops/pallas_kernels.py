"""Pallas TPU kernels — the device-kernel layer of the framework, the
TPU-native replacement for the reference's per-backend batched tile
kernels (``src/cuda/`` 15 files ≈4.5k LoC: ``device_geadd.cu``,
``device_genorm.cu``, ``device_transpose.cu``, ``device_tzset.cu`` … and
the vendor batched GEMM behind ``internal_gemm.cc:383-689``).

One backend replaces CUDA/HIP/omptarget: each kernel is a
``pl.pallas_call`` tiled to the MXU/VPU geometry (128-lane minor dim).
Kernels run in interpret mode on CPU (CI) and compiled on TPU.  On TPU
they are first-class DEFAULT candidates: the autotune table
(:mod:`slate_tpu.perf.autotune`) times each against its XLA sibling per
(op, shape, dtype) key and dispatches to the measured winner, with the
tri-state ``config.use_pallas`` knob forcing them on/off.

All kernels assume shapes padded to the tile grid (the dense drivers
pad; SLATE's cleanup-tile groups — ``internal_gemm.cc:448-689`` — become
padding here, which the MXU prefers over ragged batches).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax < 0.5 names the Mosaic params class TPUCompilerParams
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

from .. import config
from . import vmem
from .._jax_compat import ensure_pallas_complex_interpret

ensure_pallas_complex_interpret()


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _x32_trace(fn):
    """Trace the kernel with x64 OFF: under ``jax_enable_x64`` the
    pallas machinery (grid index maps, weakly-typed scalars) produces
    int64/f64 intermediates that Mosaic's vector layout rejects
    (``bitwidth_ <= 32`` check).  Every kernel here is ≤32-bit by
    contract, so a 32-bit trace context is semantics-preserving; it
    lets fp64 drivers (e.g. :func:`blocks.potrf_panels_f64`) call the
    f32 kernels mid-graph."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        leaves = jax.tree_util.tree_leaves((args, kwargs))
        if _interpret() or \
                any(getattr(getattr(x, "dtype", None), "itemsize", 0) > 4
                    for x in leaves):
            # 64-bit operands: only legal in interpret mode (CPU CI);
            # the x32 context would silently truncate them.  Interpret
            # mode never needs the x32 trace at all (the bitwidth_<=32
            # Mosaic layout check is TPU-only), and flipping the x64
            # flag mid-trace under an x64 outer jit emits mixed
            # i32/i64 loop counters the MLIR verifier rejects
            return fn(*args, **kwargs)
        from .._jax_compat import enable_x64
        with enable_x64(False):
            return fn(*args, **kwargs)

    return wrapper


# ---------------------------------------------------------------------------
# Tiled matmul with K-loop accumulation — the MXU hot loop (the role
# vendor blas::batch::gemm plays in the reference).
# ---------------------------------------------------------------------------

def _matmul_kernel(a_ref, b_ref, o_ref, acc_ref, *, k_steps):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # HIGHEST: in-kernel DEFAULT is a single bf16 MXU pass — ~1e-3
    # relative error on f32 data, far beyond the 3·eps residual gates
    acc_ref[:] += jnp.dot(a_ref[:], b_ref[:],
                          preferred_element_type=acc_ref.dtype,
                          precision=jax.lax.Precision.HIGHEST)

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _store():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


@_x32_trace
def matmul(a, b, *, bm: int = 256, bn: int = 256, bk: int = 512,
           out_dtype=None):
    """C = A·B as a Pallas MXU kernel with fp32 VMEM accumulation.

    Grid (M/bm, N/bn, K/bk); the accumulator lives in VMEM scratch across
    the K loop — the Pallas analog of one group of the reference's
    batched GEMM (``internal_gemm.cc:614-689``).
    """

    m, k = a.shape
    k2, n = b.shape
    assert k == k2, (a.shape, b.shape)
    bm, bn, bk = min(bm, m), min(bn, n), min(bk, k)
    assert m % bm == 0 and n % bn == 0 and k % bk == 0, \
        "pad shapes to the tile grid"
    if jnp.issubdtype(a.dtype, jnp.complexfloating):
        raise NotImplementedError(
            "Pallas TPU has no complex support; use the XLA path "
            "(ops.blocks.matmul routes complex there automatically)")
    out_dtype = out_dtype or a.dtype
    k_steps = k // bk
    # accumulate in at-least-fp32 (bf16/f16 widen, f64 stays f64)
    acc_dtype = jnp.promote_types(a.dtype, jnp.float32)
    return pl.pallas_call(
        functools.partial(_matmul_kernel, k_steps=k_steps),
        grid=(m // bm, n // bn, k_steps),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), acc_dtype)],
        interpret=_interpret(),
    )(a, b)


# ---------------------------------------------------------------------------
# Batched per-tile norms — device_genorm.cu: one partial norm per tile,
# host (here: XLA) reduces across tiles/ranks.
# ---------------------------------------------------------------------------

def _norm_max_kernel(x_ref, o_ref):
    o_ref[0, 0] = jnp.max(jnp.abs(x_ref[:]))


def _norm_fro_kernel(x_ref, o_ref):
    v = x_ref[:]
    o_ref[0, 0] = jnp.sum(jnp.real(v * jnp.conj(v))
                          if jnp.iscomplexobj(v) else v * v)


@_x32_trace
def tile_norms(x, norm: str = "max"):
    """Per-tile partial norms of a (nt, mb, nb) tile batch — reference
    ``device::genorm`` (``device_genorm.cu``; two-phase norm,
    ``internal_genorm.cc``).  Returns (nt,) partials: max → tile max-abs,
    fro → tile sum-of-squares (caller sqrt-reduces)."""

    nt, mb, nb = x.shape
    kern = _norm_max_kernel if norm == "max" else _norm_fro_kernel
    out_dtype = x.dtype if not jnp.iscomplexobj(x) else \
        jnp.float64 if x.dtype == jnp.complex128 else jnp.float32
    res = pl.pallas_call(
        kern,
        grid=(nt,),
        in_specs=[pl.BlockSpec((1, mb, nb), lambda t: (t, 0, 0))],
        out_specs=pl.BlockSpec((1, 1), lambda t: (t, 0)),
        out_shape=jax.ShapeDtypeStruct((nt, 1), out_dtype),
        interpret=_interpret(),
    )(x)
    return res[:, 0]


# ---------------------------------------------------------------------------
# Trapezoid (masked) elementwise kernels — device_tzset.cu / tzscale /
# tzadd: triangle masks built from iota inside the kernel.
# ---------------------------------------------------------------------------

def _tz_kernel(a_ref, o_ref, *, lower, offdiag, diag, op, bm, bn):
    i = pl.program_id(0)
    j = pl.program_id(1)
    rows = jax.lax.broadcasted_iota(jnp.int32, (bm, bn), 0) + i * bm
    cols = jax.lax.broadcasted_iota(jnp.int32, (bm, bn), 1) + j * bn
    in_tri = (rows >= cols) if lower else (rows <= cols)
    on_diag = rows == cols
    v = a_ref[:]
    if op == "set":
        out = jnp.where(on_diag, diag, jnp.where(in_tri, offdiag, v))
    elif op == "scale":
        out = jnp.where(in_tri & ~on_diag, v * offdiag,
                        jnp.where(on_diag, v * diag, v))
    else:
        raise ValueError(op)
    o_ref[:] = out.astype(o_ref.dtype)


@_x32_trace
def tzset(a, lower: bool, offdiag_value, diag_value,
          bm: int = 256, bn: int = 256):
    """Set the stored triangle to constants — ``device::tzset``
    (``device_tzset.cu``)."""
    return _tz_call(a, lower, offdiag_value, diag_value, "set", bm, bn)


@_x32_trace
def tzscale(a, lower: bool, offdiag_factor, diag_factor,
            bm: int = 256, bn: int = 256):
    """Scale the stored triangle — ``device::tzscale``."""
    return _tz_call(a, lower, offdiag_factor, diag_factor, "scale", bm, bn)


def _tz_call(a, lower, offdiag, diag, op, bm, bn):
    m, n = a.shape
    bm, bn = min(bm, m), min(bn, n)
    assert m % bm == 0 and n % bn == 0, "pad shapes to the tile grid"
    return pl.pallas_call(
        functools.partial(_tz_kernel, lower=lower, offdiag=offdiag,
                          diag=diag, op=op, bm=bm, bn=bn),
        grid=(m // bm, n // bn),
        in_specs=[pl.BlockSpec((bm, bn), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), a.dtype),
        interpret=_interpret(),
    )(a)


# ---------------------------------------------------------------------------
# geadd / gescale_row_col — device_geadd.cu / device_gescale_row_col.cu
# as one fused elementwise kernel each.
# ---------------------------------------------------------------------------

def _geadd_kernel(a_ref, b_ref, o_ref, *, alpha, beta):
    o_ref[:] = (alpha * a_ref[:] + beta * b_ref[:]).astype(o_ref.dtype)


@_x32_trace
def geadd(alpha, a, beta, b, bm: int = 256, bn: int = 256):
    """B ← α·A + β·B — ``device::geadd`` (``device_geadd.cu``)."""
    m, n = a.shape
    bm, bn = min(bm, m), min(bn, n)
    assert m % bm == 0 and n % bn == 0
    return pl.pallas_call(
        functools.partial(_geadd_kernel, alpha=alpha, beta=beta),
        grid=(m // bm, n // bn),
        in_specs=[pl.BlockSpec((bm, bn), lambda i, j: (i, j))] * 2,
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), b.dtype),
        interpret=_interpret(),
    )(a, b)


def _scale_rc_kernel(r_ref, c_ref, a_ref, o_ref):
    o_ref[:] = (r_ref[:].reshape(-1, 1) * a_ref[:] *
                c_ref[:].reshape(1, -1)).astype(o_ref.dtype)


@_x32_trace
def gescale_row_col(r, c, a, bm: int = 256, bn: int = 256):
    """A ← diag(r)·A·diag(c) — ``device::gescale_row_col``."""
    m, n = a.shape
    bm, bn = min(bm, m), min(bn, n)
    assert m % bm == 0 and n % bn == 0
    return pl.pallas_call(
        _scale_rc_kernel,
        grid=(m // bm, n // bn),
        in_specs=[
            pl.BlockSpec((bm,), lambda i, j: (i,)),
            pl.BlockSpec((bn,), lambda i, j: (j,)),
            pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), a.dtype),
        interpret=_interpret(),
    )(r, c, a)


# ---------------------------------------------------------------------------
# Fused factorization panel: blocked Cholesky + triangular inverse in
# VMEM.  This is the latency killer for the blocked potrf driver: one
# kernel launch replaces XLA's small-cholesky + triangular_solve chain
# (~1 ms + ~10 ms per panel step on the MXU's host-dispatch path), and
# the returned L⁻¹ turns every panel trsm into an MXU gemm — the role
# the vendor `lapack::potrf` + batched trsm play in the reference
# (``internal_potrf.cc:53-72``, ``internal_trsm.cc``).
# ---------------------------------------------------------------------------

def _chol_unblocked(blk, ib):
    """Unblocked rank-1 Cholesky of an (ib, ib) SPD block (value form,
    VPU where-masked columns).  On TPU the column loop is
    Python-UNROLLED: a ``fori_loop`` here costs per-iteration Mosaic
    loop overhead on a ~6-op body, which made the round-2 kernel
    latency-bound (VERDICT Weak #1); unrolling trades one-time compile
    for straight-line VPU code.  Interpret mode (CPU CI) keeps the
    rolled loop — tracing thousands of unrolled steps there takes
    minutes and tests nothing extra."""

    rows = jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 1)
    idx = jax.lax.iota(jnp.int32, ib)

    def body(j, a):
        colj = jnp.sum(jnp.where(cols == j, a, 0.0), axis=1)
        ajj = jnp.sum(jnp.where(idx == j, colj, 0.0))
        inv_ljj = jax.lax.rsqrt(ajj)
        v = jnp.where(idx > j, colj * inv_ljj, 0.0)
        a = a - v[:, None] * v[None, :]
        colj_new = jnp.where(idx == j, ajj * inv_ljj,
                             jnp.where(idx > j, v, colj))
        return jnp.where(cols == j, colj_new[:, None], a)

    if _interpret():
        a = jax.lax.fori_loop(0, ib, body, blk)
    else:
        a = blk
        for j in range(ib):
            a = body(j, a)
    return jnp.where(rows >= cols, a, 0.0)


def _trtri_unblocked(l, ib):
    """Row-by-row forward substitution: inverse of a lower non-unit
    triangular (ib, ib) block (value form, unrolled on TPU like
    :func:`_chol_unblocked`)."""

    rows = jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 0)
    idx = jax.lax.iota(jnp.int32, ib)

    def body(i, x):
        li = jnp.sum(jnp.where(rows == i, l, 0.0), axis=0)
        lii = jnp.sum(jnp.where(idx == i, li, 0.0))
        lmask = jnp.where(idx < i, li, 0.0)
        contr = jnp.sum(x * lmask[:, None], axis=0)
        ei = jnp.where(idx == i, 1.0, 0.0).astype(l.dtype)
        xrow = (ei - contr) / lii
        return jnp.where(rows == i, xrow[None, :], x)

    if _interpret():
        return jax.lax.fori_loop(0, ib, body, jnp.zeros_like(l))
    x = jnp.zeros_like(l)
    for i in range(ib):
        x = body(i, x)
    return x


def _block_inv_doubling(l_ref, inv_ref, nb, ib):
    """Assemble the full lower-triangular inverse from per-block diagonal
    inverses (already in inv_ref's diagonal ib-blocks; everything else in
    inv_ref must be ZERO) by recursive doubling:

        [[L11, 0], [L21, L22]]⁻¹ = [[X11, 0], [-X22·L21·X11, X22]]

    log₂(nb/ib) levels, two (s,s) MXU products per combined pair — far
    fewer, larger products than row-block forward substitution.  Shared
    by the fused chol+inv, trtri and LU panel kernels (dtype follows
    the refs: f32 on TPU, f32/f64 in interpret mode)."""

    f32 = jnp.promote_types(inv_ref.dtype, jnp.float32)
    hi = jax.lax.Precision.HIGHEST
    s = ib
    while s < nb:
        for o in range(0, nb - s, 2 * s):
            x11 = inv_ref[o:o + s, o:o + s]
            x22 = inv_ref[o + s:o + 2 * s, o + s:o + 2 * s]
            l21 = l_ref[o + s:o + 2 * s, o:o + s]
            t = jnp.dot(l21, x11, preferred_element_type=f32, precision=hi)
            inv_ref[o + s:o + 2 * s, o:o + s] = \
                -jnp.dot(x22, t, preferred_element_type=f32, precision=hi)
        s *= 2


def _chol_inv_kernel(a_ref, l_ref, inv_ref, *, nb, ib):
    dt = jnp.promote_types(l_ref.dtype, jnp.float32)
    hi = jax.lax.Precision.HIGHEST
    l_ref[:] = a_ref[:]
    inv_ref[:] = jnp.zeros((nb, nb), dt)    # doubling needs clean zeros
    nblk = nb // ib
    for bi in range(nblk):
        k0 = bi * ib
        blk = _chol_unblocked(l_ref[k0:k0 + ib, k0:k0 + ib], ib)
        l_ref[k0:k0 + ib, k0:k0 + ib] = blk
        inv_ref[k0:k0 + ib, k0:k0 + ib] = _trtri_unblocked(blk, ib)
        if k0 + ib < nb:
            binv = inv_ref[k0:k0 + ib, k0:k0 + ib]
            a21 = l_ref[k0 + ib:nb, k0:k0 + ib]
            l21 = jnp.dot(a21, binv.T, preferred_element_type=dt,
                          precision=hi)
            l_ref[k0 + ib:nb, k0:k0 + ib] = l21
            tr = l_ref[k0 + ib:nb, k0 + ib:nb]
            l_ref[k0 + ib:nb, k0 + ib:nb] = \
                tr - jnp.dot(l21, l21.T, preferred_element_type=dt,
                             precision=hi)
    rows = jax.lax.broadcasted_iota(jnp.int32, (nb, nb), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (nb, nb), 1)
    l_ref[:] = jnp.where(rows >= cols, l_ref[:], 0.0)
    _block_inv_doubling(l_ref, inv_ref, nb, ib)


@_x32_trace
@functools.partial(jax.jit, static_argnums=())
def chol_inv_panel(a):
    """Factor an (nb, nb) SPD panel: returns ``(L, L⁻¹)`` (both lower
    triangular) from one fused VMEM kernel.  nb must be a power of two
    ≥ 32 (the inverse assembly doubles block sizes).  f32 on TPU;
    f32/f64 in interpret mode (the dtype follows the operand)."""

    nb = a.shape[-1]
    ib = min(32, nb)
    assert nb % ib == 0 and (nb & (nb - 1)) == 0, nb
    dt = jnp.promote_types(a.dtype, jnp.float32)
    out = pl.pallas_call(
        functools.partial(_chol_inv_kernel, nb=nb, ib=ib),
        out_shape=(jax.ShapeDtypeStruct((nb, nb), dt),
                   jax.ShapeDtypeStruct((nb, nb), dt)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)),
        interpret=_interpret(),
    )(a.astype(dt))
    return out


def _lu_unblocked(blk, ib):
    """Unblocked no-pivot LU of an (ib, ib) block (value form, packed:
    unit L strictly below, U on/above; unrolled on TPU like
    :func:`_chol_unblocked`)."""

    cols = jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 1)
    rows = jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 0)
    idx = jax.lax.iota(jnp.int32, ib)

    def body(j, a):
        colj = jnp.sum(jnp.where(cols == j, a, 0.0), axis=1)
        ajj = jnp.sum(jnp.where(idx == j, colj, 0.0))
        lcol = jnp.where(idx > j, colj / ajj, 0.0)
        urow = jnp.sum(jnp.where(rows == j, a, 0.0), axis=0)
        urow = jnp.where(idx > j, urow, 0.0)
        a = a - lcol[:, None] * urow[None, :]
        return jnp.where(cols == j,
                         jnp.where(idx > j, lcol, colj)[:, None], a)

    if _interpret():
        return jax.lax.fori_loop(0, ib, body, blk)
    a = blk
    for j in range(ib):
        a = body(j, a)
    return a


def _triu_tri_unblocked(u, ib):
    """Inverse of a non-unit upper-triangular (ib, ib) block by reverse
    row-wise back substitution (Mosaic has no ``rev``, so this is a
    direct mirror of :func:`_trtri_unblocked`, not a flip of it)."""

    rows = jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 0)
    idx = jax.lax.iota(jnp.int32, ib)

    def body(step, x):
        i = ib - 1 - step
        ui = jnp.sum(jnp.where(rows == i, u, 0.0), axis=0)
        uii = jnp.sum(jnp.where(idx == i, ui, 0.0))
        umask = jnp.where(idx > i, ui, 0.0)
        contr = jnp.sum(x * umask[:, None], axis=0)
        ei = jnp.where(idx == i, 1.0, 0.0).astype(u.dtype)
        xrow = (ei - contr) / uii
        return jnp.where(rows == i, xrow[None, :], x)

    if _interpret():
        return jax.lax.fori_loop(0, ib, body, jnp.zeros_like(u))
    x = jnp.zeros_like(u)
    for step in range(ib):
        x = body(step, x)
    return x


def _block_uinv_doubling(u_ref, inv_ref, nb, ib):
    """Upper-triangular recursive-doubling inverse assembly (the
    transpose analog of :func:`_block_inv_doubling`):

        [[U11, U12], [0, U22]]⁻¹ = [[X11, -X11·U12·X22], [0, X22]]
    """

    f32 = jnp.float32
    hi = jax.lax.Precision.HIGHEST
    s = ib
    while s < nb:
        for o in range(0, nb - s, 2 * s):
            x11 = inv_ref[o:o + s, o:o + s]
            x22 = inv_ref[o + s:o + 2 * s, o + s:o + 2 * s]
            u12 = u_ref[o:o + s, o + s:o + 2 * s]
            t = jnp.dot(u12, x22, preferred_element_type=f32, precision=hi)
            inv_ref[o:o + s, o + s:o + 2 * s] = \
                -jnp.dot(x11, t, preferred_element_type=f32, precision=hi)
        s *= 2


def _lu_inv_kernel(a_ref, lu_ref, linv_ref, uinv_ref, *, nb, ib):
    f32 = jnp.float32
    hi = jax.lax.Precision.HIGHEST
    lu_ref[:] = a_ref[:]
    linv_ref[:] = jnp.zeros((nb, nb), f32)
    uinv_ref[:] = jnp.zeros((nb, nb), f32)
    rows = jax.lax.broadcasted_iota(jnp.int32, (nb, nb), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (nb, nb), 1)
    eye_ib = (jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 0)
              == jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 1)
              ).astype(f32)
    for bi in range(nb // ib):
        k0 = bi * ib
        blk = _lu_unblocked(lu_ref[k0:k0 + ib, k0:k0 + ib], ib)
        lu_ref[k0:k0 + ib, k0:k0 + ib] = blk
        lblk = jnp.where(
            jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 0)
            > jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 1), blk, 0.0) \
            + eye_ib
        ublk = jnp.where(
            jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 0)
            <= jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 1), blk, 0.0)
        linv_ref[k0:k0 + ib, k0:k0 + ib] = _trtri_unblocked(lblk, ib)
        uinv_ref[k0:k0 + ib, k0:k0 + ib] = _triu_tri_unblocked(ublk, ib)
        if k0 + ib < nb:
            lb = linv_ref[k0:k0 + ib, k0:k0 + ib]
            ub_ = uinv_ref[k0:k0 + ib, k0:k0 + ib]
            # L21 = A21·U11⁻¹ ; U12 = L11⁻¹·A12 ; A22 -= L21·U12
            a21 = lu_ref[k0 + ib:nb, k0:k0 + ib]
            a12 = lu_ref[k0:k0 + ib, k0 + ib:nb]
            l21 = jnp.dot(a21, ub_, preferred_element_type=f32, precision=hi)
            u12 = jnp.dot(lb, a12, preferred_element_type=f32, precision=hi)
            lu_ref[k0 + ib:nb, k0:k0 + ib] = l21
            lu_ref[k0:k0 + ib, k0 + ib:nb] = u12
            tr = lu_ref[k0 + ib:nb, k0 + ib:nb]
            lu_ref[k0 + ib:nb, k0 + ib:nb] = \
                tr - jnp.dot(l21, u12, preferred_element_type=f32,
                             precision=hi)
    lfull = jnp.where(rows > cols, lu_ref[:], 0.0) + \
        (rows == cols).astype(f32)
    _block_inv_doubling(lfull, linv_ref, nb, ib)
    ufull = jnp.where(rows <= cols, lu_ref[:], 0.0)
    _block_uinv_doubling(ufull, uinv_ref, nb, ib)


@_x32_trace
def lu_inv_panel(a):
    """No-pivot LU of an (nb, nb) f32 block in one fused VMEM kernel:
    returns ``(LU_packed, L⁻¹, U⁻¹)`` (L unit lower).  nb must be a
    power of two ≥ 32.  The diagonal-block workhorse for the LU driver
    and the Householder-reconstruction step of the CholQR2 panel QR
    (reference vendor ``getrf`` slot, ``internal_getrf.cc``)."""

    nb = a.shape[-1]
    ib = min(32, nb)
    assert nb % ib == 0 and (nb & (nb - 1)) == 0, nb
    return pl.pallas_call(
        functools.partial(_lu_inv_kernel, nb=nb, ib=ib),
        out_shape=(jax.ShapeDtypeStruct((nb, nb), jnp.float32),
                   jax.ShapeDtypeStruct((nb, nb), jnp.float32),
                   jax.ShapeDtypeStruct((nb, nb), jnp.float32)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)),
        interpret=_interpret(),
    )(a)


def _trtri_panel_kernel(l_in_ref, inv_ref, *, nb, ib):
    inv_ref[:] = jnp.zeros((nb, nb),
                           jnp.promote_types(inv_ref.dtype, jnp.float32))
    for bi in range(nb // ib):
        k0 = bi * ib
        inv_ref[k0:k0 + ib, k0:k0 + ib] = \
            _trtri_unblocked(l_in_ref[k0:k0 + ib, k0:k0 + ib], ib)
    _block_inv_doubling(l_in_ref, inv_ref, nb, ib)


@_x32_trace
def trtri_panel(l):
    """Inverse of an (nb, nb) lower-triangular panel in one fused VMEM
    kernel — the companion of :func:`chol_inv_panel` for factor layouts
    where L arrives pre-computed (the autotuned ``trtri_panel``
    backend).  nb must be a power of two ≥ 32.  f32 on TPU; f32/f64 in
    interpret mode (the dtype follows the operand)."""

    nb = l.shape[-1]
    ib = min(32, nb)
    assert nb % ib == 0 and (nb & (nb - 1)) == 0, nb
    dt = jnp.promote_types(l.dtype, jnp.float32)
    return pl.pallas_call(
        functools.partial(_trtri_panel_kernel, nb=nb, ib=ib),
        out_shape=jax.ShapeDtypeStruct((nb, nb), dt),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(l.astype(dt))


def _chol_l21_kernel(a_ref, pan_ref, l_ref, x_ref, inv_ref, *, nb, ib):
    dt = jnp.promote_types(l_ref.dtype, jnp.float32)
    hi = jax.lax.Precision.HIGHEST
    _chol_inv_kernel(a_ref, l_ref, inv_ref, nb=nb, ib=ib)
    # trailing correction fused in: L21 = panel · L⁻ᵀ (trsm-as-gemm on
    # the whole replicated panel, same VMEM residency as the factor)
    x_ref[:] = jnp.dot(pan_ref[:], inv_ref[:].T,
                       preferred_element_type=dt, precision=hi)


@_x32_trace
def chol_l21_panel(a, panel):
    """ISSUE 13 fused dist_panel body for ppotrf: the (nb, nb) Cholesky
    + explicit inverse of :func:`chol_inv_panel` AND the full-height
    trailing trsm-as-gemm L21 = panel·L⁻ᵀ in ONE pallas invocation —
    the per-step launch of the distributed driver's ``pallas_fused``
    backend.  Returns ``(L, L21)``.  nb a power of two ≥ 32; f32 on
    TPU, f32/f64 in interpret mode (dtype follows the operands)."""

    nb = a.shape[-1]
    ib = min(32, nb)
    assert nb % ib == 0 and (nb & (nb - 1)) == 0, nb
    dt = jnp.promote_types(a.dtype, jnp.float32)
    m = panel.shape[0]
    l, x = pl.pallas_call(
        functools.partial(_chol_l21_kernel, nb=nb, ib=ib),
        out_shape=(jax.ShapeDtypeStruct((nb, nb), dt),
                   jax.ShapeDtypeStruct((m, nb), dt)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)),
        scratch_shapes=[pltpu.VMEM((nb, nb), dt)],
        interpret=_interpret(),
    )(a.astype(dt), panel.astype(dt))
    return l, x


def _lu_u12_kernel(l_ref, b_ref, u_ref, dev_ref, inv_ref, *, nb, ib):
    dt = jnp.promote_types(l_ref.dtype, jnp.float32)
    hi = jax.lax.Precision.HIGHEST
    _trtri_panel_kernel(l_ref, inv_ref, nb=nb, ib=ib)
    b = b_ref[:]
    # U12 = L⁻¹·A12 with one Newton-style residual correction (the
    # composed pallas_panel path's gemm pair, fused into the launch)
    u1 = jnp.dot(inv_ref[:], b, preferred_element_type=dt, precision=hi)
    r1 = b - jnp.dot(l_ref[:], u1, preferred_element_type=dt, precision=hi)
    u_ref[:] = u1 + jnp.dot(inv_ref[:], r1,
                            preferred_element_type=dt, precision=hi)
    tiny = jnp.finfo(dt).tiny
    dev_ref[0, 0] = jnp.max(jnp.abs(r1)) / jnp.maximum(
        jnp.max(jnp.abs(b)), tiny)


@_x32_trace
def lu_u12_panel(l11, rowblk):
    """ISSUE 13 fused dist_panel body for pgetrf: the unit-lower
    (nb, nb) trtri of :func:`trtri_panel` AND the block-row solve
    U12 = L₁₁⁻¹·A12 with its residual-correction gemm pair in ONE
    pallas invocation.  Returns ``(u12, dev)`` where ``dev`` is the
    (1, 1) scaled departure ‖A12 − L₁₁·U12′‖∞/‖A12‖∞ of the
    pre-correction solve — the caller's guard threshold for falling
    back to the exact trsm (a correction step cannot rescue a wrong
    inverse on a high-growth panel).  nb a power of two ≥ 32; f32 on
    TPU, f32/f64 in interpret mode."""

    nb = l11.shape[-1]
    ib = min(32, nb)
    assert nb % ib == 0 and (nb & (nb - 1)) == 0, nb
    dt = jnp.promote_types(l11.dtype, jnp.float32)
    w = rowblk.shape[1]
    return pl.pallas_call(
        functools.partial(_lu_u12_kernel, nb=nb, ib=ib),
        out_shape=(jax.ShapeDtypeStruct((nb, w), dt),
                   jax.ShapeDtypeStruct((1, 1), dt)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)),
        scratch_shapes=[pltpu.VMEM((nb, nb), dt)],
        interpret=_interpret(),
    )(l11.astype(dt), rowblk.astype(dt))


# ---------------------------------------------------------------------------
# Tall-panel LU with TRUE partial pivoting, scattered-row (no-swap) form —
# the TPU answer to the reference's multithreaded panel kernel
# (``src/internal/Tile_getrf.hh:154-320``: per-column global argmax +
# row swap + rank-1).  Physical row interchanges are hostile to an
# accelerator (measured: XLA's fused LU panel and jax-level fori_loop
# panels both cost ~30 µs per column step — an HBM round trip per
# step), so here pivoting is LOGICAL: every step picks the argmax over
# the rows still active, retires that row from the mask, and leaves all
# data in place.  The packed-LAPACK layout is recovered by ONE row
# gather at the very end of the whole factorization (driver:
# linalg.lu.getrf_scattered).
# ---------------------------------------------------------------------------


def _factor_block_lane_major(out_ref, act_out, piv_ref, ohsub,
                             *, m, bb, ib, piv0=0):
    """Shared core: TRUE partial-pivot elimination of the (bb, m)
    lane-major block held in ``out_ref``, active mask in ``act_out``
    (both updated in place); see :func:`_getrf_panel_fused_kernel`.

    ``piv0`` (static or traced) offsets the pivot writes into a wider
    ``piv_ref`` — the fused panel kernel records all nb pivots of a
    panel through one ref while each grid step eliminates one bb
    block.  ``ohsub`` is a (bb, m) scratch: the one-hot pivot rows of
    sub-block s land at rows [s·ib, (s+1)·ib), so the whole block's
    one-hot matrix survives the call (the fused kernel's cross-block
    trailing update needs it).  Dtype follows the refs (f32 on TPU;
    f32/f64 in interpret mode)."""

    dt = jnp.promote_types(out_ref.dtype, jnp.float32)
    hi = jax.lax.Precision.HIGHEST
    iota_lane = jax.lax.broadcasted_iota(jnp.int32, (1, m), 1)
    iota_sub = jax.lax.broadcasted_iota(jnp.int32, (ib, 1), 0)
    piv_cols = jax.lax.broadcasted_iota(
        jnp.int32, (1, piv_ref.shape[-1]), 1)
    eye_ib = (jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 0)
              == jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 1)
              ).astype(dt)
    tril_ib = (jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 0)
               > jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 1))

    for s in range(bb // ib):
        s0 = s * ib
        oh_lo = s0

        def col_step(j, _, s0=s0, oh_lo=oh_lo):
            sub = out_ref[s0:s0 + ib, :]
            col = out_ref[pl.ds(s0 + j, 1), :]   # dynamic row read
            act = act_out[:]
            mag = jnp.abs(col) * act
            mx = jnp.max(mag)
            cand = jnp.where((mag >= mx) & (act > 0), iota_lane, m)
            p = jnp.min(cand).astype(jnp.int32)
            piv_ref[:] = jnp.where(piv_cols == piv0 + s0 + j, p,
                                   piv_ref[:])
            oh = (iota_lane == p).astype(dt)
            pval = jnp.sum(col * oh)
            safe = jnp.where(pval == 0, 1.0, pval)
            live = (act > 0) & (oh == 0)
            lrow = jnp.where(live, col / safe, 0.0)
            newcol = jnp.where(live, lrow, col)
            pcol = jnp.sum(sub * oh, axis=1, keepdims=True)
            out_ref[s0:s0 + ib, :] = jnp.where(
                iota_sub == j, newcol,
                sub - jnp.where(iota_sub > j, pcol, 0.0) * lrow)
            ohsub[oh_lo:oh_lo + ib, :] = jnp.where(
                iota_sub == j, oh, ohsub[oh_lo:oh_lo + ib, :])
            act_out[:] = act * (1.0 - oh)
            return 0

        ohsub[oh_lo:oh_lo + ib, :] = jnp.zeros((ib, m), dt)
        jax.lax.fori_loop(0, ib, col_step, 0)
        if s0 + ib < bb:
            ohs = ohsub[oh_lo:oh_lo + ib, :]
            sub = out_ref[s0:s0 + ib, :]
            l11 = jax.lax.dot_general(
                ohs, sub,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=dt, precision=hi)
            l11u = jnp.where(tril_ib, l11, 0.0) + eye_ib
            l11inv = _trtri_unblocked(l11u, ib)
            rest = out_ref[s0 + ib:bb, :]
            ut = jax.lax.dot_general(
                rest, ohs,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=dt, precision=hi)
            u12t = jnp.dot(ut, l11inv.T,
                           preferred_element_type=dt, precision=hi)
            pivm = jnp.sum(ohs, axis=0, keepdims=True)
            lsubt = sub * act_out[:]
            out_ref[s0 + ib:bb, :] = (
                rest * (1.0 - pivm)
                - jnp.dot(u12t, lsubt, preferred_element_type=dt,
                          precision=hi)
                + jnp.dot(u12t, ohs, preferred_element_type=dt,
                          precision=hi))


def _factor_panel_linv_kernel(slab_in, act_in, out_ref, piv_ref, act_out,
                              linv_ref, ohsub, lfull_ref, *, m, bb, ib):
    """v2 of the scattered-row panel core (r5): TRUE partial-pivot
    elimination of the whole (bb, m) lane-major panel in ONE kernel,
    plus the unit-lower ``L11⁻¹`` of the panel's pivot block as a second
    output — the composition replaces XLA's ~0.4 ms-per-panel
    triangular solve with one MXU gemm against it (measured: the 16
    u12 trsms cost 6.5 of getrf's 41 ms at n=8192).

    vs v1 (:func:`_factor_block_lane_major`): the two trailing k=ib
    dots merge into one (``u12t @ (ohsub − lsubt)``), and the per-
    sub-block ib×ib inverses are saved and assembled into the full
    (bb, bb) inverse by recursive doubling at the end.
    """

    f32 = jnp.float32
    hi = jax.lax.Precision.HIGHEST
    out_ref[:] = slab_in[:]
    act_out[:] = act_in[:]
    piv_ref[:] = jnp.zeros((1, bb), jnp.int32)
    linv_ref[:] = jnp.zeros((bb, bb), f32)
    iota_lane = jax.lax.broadcasted_iota(jnp.int32, (1, m), 1)
    iota_sub = jax.lax.broadcasted_iota(jnp.int32, (ib, 1), 0)
    piv_cols = jax.lax.broadcasted_iota(jnp.int32, (1, bb), 1)
    eye_ib = (jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 0)
              == jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 1)
              ).astype(f32)
    tril_ib = (jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 0)
               > jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 1))

    for s in range(bb // ib):
        s0 = s * ib

        def col_step(j, _, s0=s0):
            sub = out_ref[s0:s0 + ib, :]
            col = out_ref[pl.ds(s0 + j, 1), :]   # dynamic row read
            act = act_out[:]
            mag = jnp.abs(col) * act
            mx = jnp.max(mag)
            cand = jnp.where((mag >= mx) & (act > 0), iota_lane, m)
            p = jnp.min(cand).astype(jnp.int32)
            piv_ref[:] = jnp.where(piv_cols == s0 + j, p, piv_ref[:])
            oh = (iota_lane == p).astype(f32)
            pval = jnp.sum(col * oh)
            safe = jnp.where(pval == 0, 1.0, pval)
            live = (act > 0) & (oh == 0)
            lrow = jnp.where(live, col / safe, 0.0)
            newcol = jnp.where(live, lrow, col)
            pcol = jnp.sum(sub * oh, axis=1, keepdims=True)
            out_ref[s0:s0 + ib, :] = jnp.where(
                iota_sub == j, newcol,
                sub - jnp.where(iota_sub > j, pcol, 0.0) * lrow)
            ohsub[:] = jnp.where(iota_sub == j, oh, ohsub[:])
            act_out[:] = act * (1.0 - oh)
            return 0

        ohsub[:] = jnp.zeros((ib, m), f32)
        jax.lax.fori_loop(0, ib, col_step, 0)
        sub = out_ref[s0:s0 + ib, :]
        # packed-factor rows of this sub-block over the columns factored
        # so far (pivot-row gather as a one-hot MXU dot) — feeds both
        # the ib-block inverse and the full-panel inverse assembly
        lpart = jax.lax.dot_general(
            ohsub[:], out_ref[0:s0 + ib, :],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=f32, precision=hi)
        lfull_ref[s0:s0 + ib, 0:s0 + ib] = lpart
        l11 = lpart[:, s0:s0 + ib]
        l11u = jnp.where(tril_ib, l11, 0.0) + eye_ib
        l11inv = _trtri_unblocked(l11u, ib)
        linv_ref[s0:s0 + ib, s0:s0 + ib] = l11inv
        if s0 + ib < bb:
            rest = out_ref[s0 + ib:bb, :]
            ut = jax.lax.dot_general(
                rest, ohsub[:],
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=f32, precision=hi)
            u12t = jnp.dot(ut, l11inv.T,
                           preferred_element_type=f32, precision=hi)
            pivm = jnp.sum(ohsub[:], axis=0, keepdims=True)
            # one fused trailing operand: ohsub − L-part of the
            # sub-slab (the two k=ib dots of v1 merged)
            lsubt = sub * act_out[:]
            out_ref[s0 + ib:bb, :] = (
                rest * (1.0 - pivm)
                + jnp.dot(u12t, ohsub[:] - lsubt,
                          preferred_element_type=f32, precision=hi))
    # assemble the full unit-lower inverse: the off-diagonal blocks of
    # L11 live in the panel's pivot columns — gather them with the
    # one-hot pivot matrix, then recursive doubling
    if bb > ib:
        rows_bb = jax.lax.broadcasted_iota(jnp.int32, (bb, bb), 0)
        cols_bb = jax.lax.broadcasted_iota(jnp.int32, (bb, bb), 1)
        lfull_ref[:] = jnp.where(rows_bb > cols_bb, lfull_ref[:], 0.0) + \
            (rows_bb == cols_bb).astype(f32)
        _block_inv_doubling(lfull_ref, linv_ref, bb, ib)


@_x32_trace
def getrf_panel_linv(slab_t, active_row, ib: int = 32):
    """TRUE partial-pivot LU of a TRANSPOSED (bb, m) f32 panel in ONE
    kernel, returning ``(panel_t, piv, active_out, linv)`` where
    ``linv`` is the (bb, bb) inverse of the panel's unit-lower pivot
    block — the v2 panel core (see
    :func:`_factor_panel_linv_kernel`)."""

    bb, m = slab_t.shape
    ib = min(ib, bb)
    assert bb % ib == 0 and m % 8 == 0, (m, bb, ib)
    f32 = jnp.float32
    out, piv, act_out, linv = pl.pallas_call(
        functools.partial(_factor_panel_linv_kernel, m=m, bb=bb, ib=ib),
        out_shape=(jax.ShapeDtypeStruct((bb, m), f32),
                   jax.ShapeDtypeStruct((1, bb), jnp.int32),
                   jax.ShapeDtypeStruct((1, m), f32),
                   jax.ShapeDtypeStruct((bb, bb), f32)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=tuple([pl.BlockSpec(memory_space=pltpu.VMEM)] * 4),
        scratch_shapes=[pltpu.VMEM((ib, m), f32),
                        pltpu.VMEM((bb, bb), f32)],
        compiler_params=_CompilerParams(
            vmem_limit_bytes=vmem.pallas_call_limit_bytes()),
        interpret=_interpret(),
    )(slab_t, active_row)
    return out, piv[0], act_out, linv


# ---------------------------------------------------------------------------
# Fused single-invocation LU panel mega-kernel — ONE pallas_call owns the
# whole panel loop.  The r4/r5 scattered driver composed the panel from a
# chain of per-block kernel calls (64 invocations at n=8192/nb=512) whose
# glue — per-block HBM round trips, unaliased carry copies XLA inserts
# around custom calls (~26 ms/block), transposes (~2 ms each) — cost
# ~30 µs/step against the kernel's measured 2.2 µs/step.  Here the grid
# iterates the panel's bb-wide column-block steps inside a single
# invocation: the (nb, m) panel is DMA'd HBM→VMEM once at step 0, stays
# resident across grid steps (grid iterations are sequential on TPU and
# scratch persists), every per-step update runs on the VMEM copy, and
# ONE DMA at the last step writes the factored panel back into the
# aliased HBM carry (input_output_aliases: no copy, no round trip).
# Pivoting stays TRUE partial + scattered (argmax of the fully-updated
# column over all still-active rows; rows never move) and the (nb, nb)
# unit-lower inverse of the pivot block rides along so the driver's u12
# solve is one MXU gemm.
# ---------------------------------------------------------------------------


def _fused_panel_phase(s, nsteps, at_hbm, act_in, k0, out_hbm, piv_ref,
                       act_out, linv_ref, panel, cur, ohblk, lfull,
                       l11s, l11i, sem, *, m, nb, bb, ib, ohfull=None,
                       piv_base=0, global_init=None, skip_dma=None):
    """Shared panel phase of the fused panel/step mega-kernels — one
    grid step = one bb-wide column block of the (nb, m) panel:

    * step 0 DMAs panel rows [k0, k0+nb) of the transposed matrix into
      the resident ``panel`` scratch and seeds the carried state;
    * every step s eliminates block rows [s·bb, (s+1)·bb) of the
      resident panel with the shared TRUE-partial-pivot core
      (:func:`_factor_block_lane_major`), then applies the masked
      right-looking trailing update to the panel rows after the block
      (the proven ``rest·(1−pivm) + u12ᵗ·(oh − lᵗ)`` composition of
      :func:`_factor_panel_linv_kernel`, here at bb granularity with an
      in-kernel residual-correction pass on u12ᵗ);
    * the last step assembles the (nb, nb) unit-lower pivot-block
      inverse (per-ib diagonal inverses + recursive doubling, exactly
      :func:`_trtri_panel_kernel`'s scheme) and DMAs the factored panel
      back into the aliased HBM carry.

    When ``ohfull`` (an (nb, m) scratch) is given, every block's one-hot
    pivot rows are also accumulated there — the step kernel's trailing
    phase folds the pivot-row gather into its trsm/update gemms through
    it.  After the call at ``s == nsteps-1``: ``panel`` holds the
    factored panel (already written back to HBM), ``lfull`` the
    unit-lower pivot block L₁₁ and ``linv_ref`` its inverse.

    The full-factorization mega-kernel reuses this phase once per
    block-column step: ``piv_base`` offsets the pivot writes into its
    factorization-wide pivot ref, ``global_init`` (a traced predicate)
    restricts the carried act/piv seeding to the very first step, and
    ``skip_dma`` (traced) skips the panel fetch when the lookahead
    already left the panel resident in VMEM.
    """

    dt = jnp.promote_types(panel.dtype, jnp.float32)
    hi = jax.lax.Precision.HIGHEST

    @pl.when(s == 0)
    def _init():
        if skip_dma is None:
            dma = pltpu.make_async_copy(
                at_hbm.at[pl.ds(k0, nb), :], panel, sem)
            dma.start()
            dma.wait()
        else:
            @pl.when(jnp.logical_not(skip_dma))
            def _fetch():
                dma = pltpu.make_async_copy(
                    at_hbm.at[pl.ds(k0, nb), :], panel, sem)
                dma.start()
                dma.wait()
        if global_init is None:
            act_out[:] = act_in[:]
            piv_ref[:] = jnp.zeros(piv_ref.shape, jnp.int32)
        else:
            @pl.when(global_init)
            def _seed():
                act_out[:] = act_in[:]
                piv_ref[:] = jnp.zeros(piv_ref.shape, jnp.int32)
        linv_ref[:] = jnp.zeros((nb, nb), dt)
        lfull[:] = jnp.zeros((nb, nb), dt)

    r0 = pl.multiple_of(s * bb, bb)
    cur[:] = panel[pl.ds(r0, bb), :]
    _factor_block_lane_major(cur, act_out, piv_ref, ohblk,
                             m=m, bb=bb, ib=ib, piv0=piv_base + r0)
    panel[pl.ds(r0, bb), :] = cur[:]
    if ohfull is not None:
        ohfull[pl.ds(r0, bb), :] = ohblk[:]
    # packed rows of this block across every panel column, gathered by
    # the one-hot pivot matrix (an MXU dot, not a scatter): final for
    # columns ≤ the block end; later columns are masked off in the
    # final unit-lower assembly
    lpart = jax.lax.dot_general(
        ohblk[:], panel[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=dt, precision=hi)
    lfull[pl.ds(r0, bb), :] = lpart

    @pl.when(s < nsteps - 1)
    def _panel_trailing():
        # diagonal pivot block of this step, unit-lower, and its
        # inverse (ib-diagonal inverses + recursive doubling — the
        # trtri_panel scheme on in-step scratch)
        eye_bb = (jax.lax.broadcasted_iota(jnp.int32, (bb, bb), 0)
                  == jax.lax.broadcasted_iota(jnp.int32, (bb, bb), 1)
                  ).astype(dt)
        tril_bb = (jax.lax.broadcasted_iota(jnp.int32, (bb, bb), 0)
                   > jax.lax.broadcasted_iota(jnp.int32, (bb, bb), 1))
        l11 = jax.lax.dot_general(
            ohblk[:], cur[:],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=dt, precision=hi)
        l11s[:] = jnp.where(tril_bb, l11, 0.0) + eye_bb
        l11i[:] = jnp.zeros((bb, bb), dt)
        for bi in range(bb // ib):
            q0 = bi * ib
            l11i[q0:q0 + ib, q0:q0 + ib] = _trtri_unblocked(
                l11s[q0:q0 + ib, q0:q0 + ib], ib)
        _block_inv_doubling(l11s, l11i, bb, ib)
        # masked right-looking update of the panel rows after the block
        # (fixed-shape ops; the row mask stands in for a shrinking
        # dynamic slice, which Mosaic cannot shape)
        ut_all = jax.lax.dot_general(
            panel[:], ohblk[:],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=dt, precision=hi)
        u12t = jnp.dot(ut_all, l11i[:].T,
                       preferred_element_type=dt, precision=hi)
        # one in-kernel residual-correction pass (k=bb dots — cheap)
        # keeps the inverse-based solve at trsm-grade accuracy
        r1 = ut_all - jnp.dot(u12t, l11s[:].T,
                              preferred_element_type=dt, precision=hi)
        u12t = u12t + jnp.dot(r1, l11i[:].T,
                              preferred_element_type=dt, precision=hi)
        pivm = jnp.sum(ohblk[:], axis=0, keepdims=True)
        lsubt = cur[:] * act_out[:]
        upd = jnp.dot(u12t, ohblk[:] - lsubt,
                      preferred_element_type=dt, precision=hi)
        after = jax.lax.broadcasted_iota(jnp.int32, (nb, 1), 0) >= r0 + bb
        panel[:] = jnp.where(after, panel[:] * (1.0 - pivm) + upd,
                             panel[:])

    @pl.when(s == nsteps - 1)
    def _finish():
        rows_nb = jax.lax.broadcasted_iota(jnp.int32, (nb, nb), 0)
        cols_nb = jax.lax.broadcasted_iota(jnp.int32, (nb, nb), 1)
        lfull[:] = jnp.where(rows_nb > cols_nb, lfull[:], 0.0) + \
            (rows_nb == cols_nb).astype(dt)
        for bi in range(nb // ib):
            q0 = bi * ib
            linv_ref[q0:q0 + ib, q0:q0 + ib] = _trtri_unblocked(
                lfull[q0:q0 + ib, q0:q0 + ib], ib)
        _block_inv_doubling(lfull, linv_ref, nb, ib)
        dma = pltpu.make_async_copy(
            panel, out_hbm.at[pl.ds(k0, nb), :], sem)
        dma.start()
        dma.wait()


def _getrf_panel_fused_kernel(at_hbm, act_in, k0_ref, out_hbm, piv_ref,
                              act_out, linv_ref, panel, cur, ohblk, lfull,
                              l11s, l11i, sem, *, m, nb, bb, ib):
    """The panel-only fused mega-kernel: exactly the shared panel phase
    (:func:`_fused_panel_phase`); the driver composes the trailing
    trsm/update in XLA."""

    s = pl.program_id(0)
    nsteps = pl.num_programs(0)
    k0 = pl.multiple_of(k0_ref[0], bb)
    _fused_panel_phase(s, nsteps, at_hbm, act_in, k0, out_hbm, piv_ref,
                       act_out, linv_ref, panel, cur, ohblk, lfull,
                       l11s, l11i, sem, m=m, nb=nb, bb=bb, ib=ib)


@_x32_trace
def getrf_panel_fused(at_full, active_row, k0, nb: int = 512,
                      bb: int = 128, ib: int = 16):
    """TRUE partial-pivot LU of panel rows [k0, k0+nb) of the TRANSPOSED
    matrix in ONE pallas invocation whose grid iterates the panel's
    bb-wide column-block steps (see :func:`_getrf_panel_fused_kernel`).
    The HBM carry is aliased (no copy per call) and ``k0`` is a scalar
    operand, so ONE Mosaic compilation serves every panel of the
    factorization.  Returns ``(at_full', piv, active_out, linv)`` with
    ``piv`` the nb physical pivot rows in order and ``linv`` the
    (nb, nb) inverse of the panel's unit-lower pivot block."""

    n_rows, m = at_full.shape
    bb = min(bb, nb)
    ib = min(ib, bb)
    assert nb % bb == 0 and bb % ib == 0 and m % 8 == 0, (m, nb, bb, ib)
    # the in-kernel pl.multiple_of hints and the (8,128)-tiled HBM
    # slices need 8 | bb and bb | k0
    assert bb % 8 == 0, bb
    if isinstance(k0, int):
        assert k0 % bb == 0, (k0, bb)
    dt = jnp.promote_types(at_full.dtype, jnp.float32)
    out, piv, act_out, linv = pl.pallas_call(
        functools.partial(_getrf_panel_fused_kernel, m=m, nb=nb, bb=bb,
                          ib=ib),
        grid=(nb // bb,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_shape=(jax.ShapeDtypeStruct((n_rows, m), dt),
                   jax.ShapeDtypeStruct((1, nb), jnp.int32),
                   jax.ShapeDtypeStruct((1, m), dt),
                   jax.ShapeDtypeStruct((nb, nb), dt)),
        out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)),
        scratch_shapes=[pltpu.VMEM((nb, m), dt),     # resident panel
                        pltpu.VMEM((bb, m), dt),     # current block
                        pltpu.VMEM((bb, m), dt),     # one-hot pivot rows
                        pltpu.VMEM((nb, nb), dt),    # packed L rows
                        pltpu.VMEM((bb, bb), dt),    # step L11
                        pltpu.VMEM((bb, bb), dt),    # step L11⁻¹
                        pltpu.SemaphoreType.DMA(())],
        input_output_aliases={0: 0},
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=_interpret(),
    )(at_full.astype(dt), active_row.astype(dt),
      jnp.asarray(k0, jnp.int32).reshape(1))
    return out, piv[0], act_out, linv


# ---------------------------------------------------------------------------
# Fused right-looking factorization STEP mega-kernels — ONE pallas_call
# owns panel + trsm + rank-nb trailing update of a whole block-column
# step.  BENCH_r03/r04 put getrf at 13.6% and potrf at 30% of measured
# gemm with the panel already fused (PR 3): the remaining cost is the
# GLUE between the sub-stages — the pivot-row gather that materializes
# the U12 operand in HBM, the u12 write-back, the trailing-update
# read-modify-write, and a kernel launch per sub-stage.  Here the whole
# step shares one VMEM residency: the panel factors in place (the
# shared :func:`_fused_panel_phase`), the pivot gather is FOLDED into
# the trsm/update gemms as one-hot-matrix operands prepared once per
# step (the LP-GEMM move: layout conversion lives in the GEMM epilogue,
# never materialized), the triangular solve is a gemm against the
# Newton-refined pivot-block inverse, and the trailing matrix streams
# through a double-buffered VMEM residency against the ALIASED HBM
# carry — zero materialized intermediates between sub-stages.
# ---------------------------------------------------------------------------


def _stream_chunks(hbm, bufs, in_sems, out_sems, c_lo, c_hi, slicer,
                   compute):
    """Double-buffered read-modify-write stream over HBM chunks
    ``c ∈ [c_lo, c_hi)`` (traced bounds; no-op when empty): chunk c is
    DMA'd from ``hbm[slicer(c)]`` into ``bufs[(c-c_lo) % 2]``,
    transformed in place by ``compute(buf, c)`` and DMA'd back, with
    chunk c+1's fetch and chunk c's write-back in flight across the
    neighbouring computes (the double-buffered VMEM residency of the
    fused step kernels)."""

    def _step(c, cur, cin, cout, nxt, nout, nin):
        pltpu.make_async_copy(hbm.at[slicer(c)], cur, cin).wait()

        @pl.when(c + 1 < c_hi)
        def _prefetch():
            # the next chunk lands in the OTHER buffer: drain that
            # buffer's write-back (chunk c-1) before overwriting it
            @pl.when(c - 1 >= c_lo)
            def _drain():
                pltpu.make_async_copy(nxt, hbm.at[slicer(c - 1)],
                                      nout).wait()
            pltpu.make_async_copy(hbm.at[slicer(c + 1)], nxt, nin).start()

        compute(cur, c)
        pltpu.make_async_copy(cur, hbm.at[slicer(c)], cout).start()

    def body(c, carry):
        rel = c - c_lo

        @pl.when(rel % 2 == 0)
        def _even():
            _step(c, bufs[0], in_sems[0], out_sems[0],
                  bufs[1], out_sems[1], in_sems[1])

        @pl.when(rel % 2 == 1)
        def _odd():
            _step(c, bufs[1], in_sems[1], out_sems[1],
                  bufs[0], out_sems[0], in_sems[0])

        return carry

    @pl.when(c_lo < c_hi)
    def _prologue():
        pltpu.make_async_copy(hbm.at[slicer(c_lo)], bufs[0],
                              in_sems[0]).start()

    jax.lax.fori_loop(c_lo, c_hi, body, 0)

    # the last two chunks' write-backs are still in flight (the loop
    # drains a buffer only when refilling it)
    for back in (2, 1):
        c = c_hi - back
        if isinstance(c, int) and c < 0:
            continue            # statically too few chunks for this slot

        @pl.when(c >= c_lo)
        def _flush(c=c):
            @pl.when((c - c_lo) % 2 == 0)
            def _a():
                pltpu.make_async_copy(bufs[0], hbm.at[slicer(c)],
                                      out_sems[0]).wait()

            @pl.when((c - c_lo) % 2 == 1)
            def _b():
                pltpu.make_async_copy(bufs[1], hbm.at[slicer(c)],
                                      out_sems[1]).wait()


def _newton_x2(lfull, linv_ref, dt):
    """Newton-refine the pivot-block inverse in place:
    ``X₂ = X(2I − L₁₁X)`` — ``lfull`` holds unit-lower L₁₁ on entry
    (the panel phase leaves it there) and X₂ on exit.  Algebraically
    the composed driver's HIGHEST residual-correction pair, precomputed
    once at (nb, nb) scale; shared by the step and full LU kernels so
    the depths stay arithmetic-identical."""
    hi = jax.lax.Precision.HIGHEST
    t = jnp.dot(lfull[:], linv_ref[:], preferred_element_type=dt,
                precision=hi)
    lfull[:] = 2.0 * linv_ref[:] - jnp.dot(
        linv_ref[:], t, preferred_element_type=dt, precision=hi)


def _lu_chunk_update(rows, gbuf, wbuf, pivm_ref, dt):
    """The LU trailing update of one resident row block — gather +
    solve + scatter + rank-nb update in one pass:
    ``rows·(1−pivm) + (rows·Gᵗ)·W`` (HIGH — the X₂ precompute already
    absorbed the inverse's departure, so the remaining error is one
    HIGH-gemm rounding, the same class as every library trailing
    product).  ONE definition serves the step kernel's streamed chunks
    and the full kernel's lookahead block + streamed chunks, which is
    what makes the depths bitwise-comparable."""
    hp = jax.lax.Precision.HIGH
    u12t = jax.lax.dot_general(
        rows, gbuf[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=dt, precision=hp)
    return rows * (1.0 - pivm_ref[:]) + jnp.dot(
        u12t, wbuf[:], preferred_element_type=dt, precision=hp)


def _getrf_step_fused_kernel(at_hbm, act_in, k0_ref, out_hbm, piv_ref,
                             act_out, linv_ref, panel, cur, ohblk, lfull,
                             l11s, l11i, ohfull, pivm_ref, bufa, bufb,
                             sem, ina, inb, outa, outb,
                             *, m, n_rows, nb, bb, ib, tc, update):
    """One grid step = one bb block of the panel phase (shared with the
    panel-only kernel); the LAST grid step then streams the trailing
    block rows of the aliased carry through a double-buffered VMEM
    residency:

    * the pivot-block inverse is Newton-refined once per step
      (``X₂ = X(2I − L₁₁X)`` — algebraically the composed driver's
      HIGHEST residual-correction pair, precomputed at (nb, nb) scale);
    * the pivot-row gather is never materialized: the trsm operand is
      ``G = X₂·Π`` (Π the step's one-hot pivot matrix), so
      ``u12ᵗ = chunk·Gᵗ`` gathers AND solves in one MXU pass (2× the
      composed path's trailing flops — the autotuned ``lu_step`` site
      arbitrates that trade against the composed path's HBM glue);
    * with ``update=True`` the rank-nb trailing update and the u12
      scatter land in the same pass:
      ``chunk ← chunk·(1−pivm) + u12ᵗ·(Π − Lᵗ)`` (the proven panel-
      phase composition at trailing scale); with ``update=False``
      (depth ``panel+trsm``) only the u12 scatter happens in-kernel and
      the rank-nb gemm stays in XLA.
    """

    dt = jnp.promote_types(panel.dtype, jnp.float32)
    hi = jax.lax.Precision.HIGHEST
    s = pl.program_id(0)
    nsteps = pl.num_programs(0)
    k0 = pl.multiple_of(k0_ref[0], bb)
    _fused_panel_phase(s, nsteps, at_hbm, act_in, k0, out_hbm, piv_ref,
                       act_out, linv_ref, panel, cur, ohblk, lfull,
                       l11s, l11i, sem, m=m, nb=nb, bb=bb, ib=ib,
                       ohfull=ohfull)

    @pl.when(s == nsteps - 1)
    def _trailing():
        # pivot-lane mask of THIS step's nb pivots (the scatter target)
        pivm_ref[:] = jnp.sum(ohfull[:], axis=0, keepdims=True)
        _newton_x2(lfull, linv_ref, dt)       # lfull ← X₂
        if update:
            # W = Π − Lᵗ into the panel buffer (its write-back DMA was
            # waited in the panel phase), then G = X₂·Π into ohfull
            panel[:] = ohfull[:] - panel[:] * act_out[:]
            ohfull[:] = jnp.dot(lfull[:], ohfull[:],
                                preferred_element_type=dt, precision=hi)
            gbuf, wbuf = ohfull, panel
        else:
            # panel+trsm depth: G goes to the (free) panel buffer and
            # Π stays intact — the in-kernel epilogue only scatters u12
            panel[:] = jnp.dot(lfull[:], ohfull[:],
                               preferred_element_type=dt, precision=hi)
            gbuf, wbuf = panel, ohfull

        def compute(buf, c):
            buf[:] = _lu_chunk_update(buf[:], gbuf, wbuf, pivm_ref, dt)

        c_lo = (k0 + nb) // tc
        _stream_chunks(out_hbm, (bufa, bufb), (ina, inb), (outa, outb),
                       c_lo, n_rows // tc,
                       lambda c: (pl.ds(c * tc, tc), slice(None)),
                       compute)


@_x32_trace
def getrf_step_fused(at_full, active_row, k0, nb: int = 512,
                     bb: int = 128, ib: int = 16, tc: int | None = None,
                     update: bool = True):
    """ONE pallas invocation owns a whole right-looking getrf step on
    the TRANSPOSED scattered carry: TRUE partial-pivot panel
    factorization of rows [k0, k0+nb), the pivot-gather-fused U₁₂
    solve, and (``update=True``) the rank-nb trailing update of every
    later block row — see :func:`_getrf_step_fused_kernel`.  The HBM
    carry is aliased and ``k0`` is a scalar operand, so ONE Mosaic
    compilation serves every step of the factorization.  Returns
    ``(at_full', piv, active_out, linv)`` (the
    :func:`getrf_panel_fused` contract; with ``update=True`` the
    trailing rows of ``at_full'`` are already updated and scattered).
    """

    n_rows, m = at_full.shape
    bb = min(bb, nb)
    ib = min(ib, bb)
    tc = tc if tc is not None else nb
    tc = min(tc, nb)
    assert nb % bb == 0 and bb % ib == 0 and m % 8 == 0, (m, nb, bb, ib)
    assert bb % 8 == 0, bb
    # trailing chunks tile the carry exactly, and every step boundary
    # k0 + nb falls on a chunk boundary
    assert nb % tc == 0 and n_rows % tc == 0, (n_rows, nb, tc)
    if isinstance(k0, int):
        assert k0 % bb == 0, (k0, bb)
    dt = jnp.promote_types(at_full.dtype, jnp.float32)
    out, piv, act_out, linv = pl.pallas_call(
        functools.partial(_getrf_step_fused_kernel, m=m, n_rows=n_rows,
                          nb=nb, bb=bb, ib=ib, tc=tc, update=update),
        grid=(nb // bb,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_shape=(jax.ShapeDtypeStruct((n_rows, m), dt),
                   jax.ShapeDtypeStruct((1, nb), jnp.int32),
                   jax.ShapeDtypeStruct((1, m), dt),
                   jax.ShapeDtypeStruct((nb, nb), dt)),
        out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)),
        scratch_shapes=[pltpu.VMEM((nb, m), dt),     # resident panel / W
                        pltpu.VMEM((bb, m), dt),     # current block
                        pltpu.VMEM((bb, m), dt),     # one-hot pivot rows
                        pltpu.VMEM((nb, nb), dt),    # packed L rows / X₂
                        pltpu.VMEM((bb, bb), dt),    # step L11
                        pltpu.VMEM((bb, bb), dt),    # step L11⁻¹
                        pltpu.VMEM((nb, m), dt),     # step Π / G
                        pltpu.VMEM((1, m), dt),      # pivot-lane mask
                        pltpu.VMEM((tc, m), dt),     # trailing buffer A
                        pltpu.VMEM((tc, m), dt),     # trailing buffer B
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(())],
        input_output_aliases={0: 0},
        compiler_params=_CompilerParams(
            vmem_limit_bytes=vmem.pallas_call_limit_bytes()),
        interpret=_interpret(),
    )(at_full.astype(dt), active_row.astype(dt),
      jnp.asarray(k0, jnp.int32).reshape(1))
    return out, piv[0], act_out, linv


# ---------------------------------------------------------------------------
# Full-factorization mega-kernels (ISSUE 12) — ONE pallas_call owns the
# ENTIRE right-looking factorization.  The PR 6 step kernels still
# launch once per block-column: between steps the trailing window
# round-trips dispatch glue, the panel re-fetches from HBM, and the
# one-hot layout state is rebuilt.  Here the grid's leading dimension
# iterates the block-column steps themselves: the carried state (active
# mask, factorization-wide pivots, the VMEM-resident panel) persists
# across steps inside one invocation, the shrinking trailing window
# streams through the same double-buffered residency against the
# aliased HBM carry, and the LP-GEMM layout propagation (pivot gather
# folded into the gemm operands) carries ACROSS steps instead of being
# re-gathered per step.  Single-chip lookahead: each step's trailing
# phase updates the NEXT panel's rows first, in VMEM, and keeps them
# resident — panel k+1 never waits on (or round-trips through) the
# step-k trailing stream's HBM traffic, so the MXU enters the next
# panel phase with zero HBM dependency (``step.hbm_roundtrips == 0``
# for the whole factorization, structurally).
# ---------------------------------------------------------------------------


def _getrf_full_fused_kernel(at_hbm, act_in, out_hbm, piv_ref, act_out,
                             panel, nxt, cur, ohblk, lfull, l11s, l11i,
                             linv, ohfull, pivm_ref, bufa, bufb,
                             sem, ina, inb, outa, outb,
                             *, m, n_rows, nb, bb, ib, tc):
    """Grid (ksteps, nb//bb): the leading dimension iterates the
    factorization's block-column steps, the trailing one the panel's
    bb-blocks (the shared :func:`_fused_panel_phase`).  The last panel
    block of each step runs the step's trailing phase — Newton-refined
    pivot-block inverse, pivot-gather-fused operands G/W, the lookahead
    update of the next panel into the resident ``nxt`` buffer, then the
    double-buffered stream over the remaining trailing rows."""

    dt = jnp.promote_types(panel.dtype, jnp.float32)
    hi = jax.lax.Precision.HIGHEST
    kstep = pl.program_id(0)
    ksteps = pl.num_programs(0)
    s = pl.program_id(1)
    nsteps = pl.num_programs(1)
    k0 = pl.multiple_of(kstep * nb, nb)

    # lookahead hand-off: the previous step's trailing phase already
    # applied its rank-nb update to this panel's rows, in VMEM — carry
    # them over instead of fetching the (stale) HBM copy
    @pl.when((s == 0) & (kstep > 0))
    def _carry_panel():
        panel[:] = nxt[:]

    _fused_panel_phase(s, nsteps, out_hbm, act_in, k0, out_hbm, piv_ref,
                       act_out, linv, panel, cur, ohblk, lfull,
                       l11s, l11i, sem, m=m, nb=nb, bb=bb, ib=ib,
                       ohfull=ohfull, piv_base=k0,
                       global_init=(kstep == 0), skip_dma=(kstep > 0))

    has_trail = (k0 + nb) < n_rows          # wide carries keep updating
    look = kstep + 1 < ksteps               # a next panel exists

    @pl.when((s == nsteps - 1) & has_trail)
    def _trailing():
        pivm_ref[:] = jnp.sum(ohfull[:], axis=0, keepdims=True)
        _newton_x2(lfull, linv, dt)           # lfull ← X₂
        # W = Π − Lᵗ into the panel buffer, G = X₂·Π into ohfull — the
        # layout propagation carried across steps: Π is consumed as a
        # gemm operand here and never materialized in HBM
        panel[:] = ohfull[:] - panel[:] * act_out[:]
        ohfull[:] = jnp.dot(lfull[:], ohfull[:],
                            preferred_element_type=dt, precision=hi)

        @pl.when(look)
        def _lookahead():
            # panel k+1 first, in VMEM, kept resident: the next step's
            # panel phase starts with zero HBM dependency while the
            # trailing stream below still owns the DMA engines; the
            # shared _lu_chunk_update makes it bitwise-identical to
            # what the step kernel streams for these rows
            ndma = pltpu.make_async_copy(
                out_hbm.at[pl.ds(k0 + nb, nb), :], nxt, sem)
            ndma.start()
            ndma.wait()
            nxt[:] = _lu_chunk_update(nxt[:], ohfull, panel,
                                      pivm_ref, dt)

        def compute(buf, c):
            buf[:] = _lu_chunk_update(buf[:], ohfull, panel,
                                      pivm_ref, dt)

        # the lookahead already covered the next panel's rows — the
        # stream starts past them (they never round-trip HBM)
        c_lo = (k0 + nb) // tc + jnp.where(look, nb // tc, 0)
        _stream_chunks(out_hbm, (bufa, bufb), (ina, inb), (outa, outb),
                       c_lo, n_rows // tc,
                       lambda c: (pl.ds(c * tc, tc), slice(None)),
                       compute)


@_x32_trace
def getrf_full_fused(at_full, active_row, nb: int = 512, bb: int = 128,
                     ib: int = 16, tc: int | None = None):
    """ONE pallas invocation owns the WHOLE right-looking partial-pivot
    LU of the TRANSPOSED scattered carry — every block-column step's
    panel + pivot-gather-fused trsm + streamed rank-nb trailing update,
    with in-kernel lookahead (see :func:`_getrf_full_fused_kernel`).
    Returns ``(at_full', piv, active_out)`` with ``piv`` the ktot =
    min(m, n_rows) physical pivot rows in factorization order; the
    driver recovers the packed LAPACK layout with one column gather at
    the very end (the :func:`getrf_step_fused` contract, minus the
    per-step linv nobody composes against).  f32 on TPU; f32/f64 in
    interpret mode."""

    n_rows, m = at_full.shape
    ktot = min(n_rows, m)
    bb = min(bb, nb)
    ib = min(ib, bb)
    tc = tc if tc is not None else nb
    tc = min(tc, nb)
    assert nb % bb == 0 and bb % ib == 0 and m % 8 == 0, (m, nb, bb, ib)
    assert bb % 8 == 0, bb
    assert ktot % nb == 0, (n_rows, m, nb)
    assert nb % tc == 0 and n_rows % tc == 0, (n_rows, nb, tc)
    dt = jnp.promote_types(at_full.dtype, jnp.float32)
    out, piv, act_out = pl.pallas_call(
        functools.partial(_getrf_full_fused_kernel, m=m, n_rows=n_rows,
                          nb=nb, bb=bb, ib=ib, tc=tc),
        grid=(ktot // nb, nb // bb),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_shape=(jax.ShapeDtypeStruct((n_rows, m), dt),
                   jax.ShapeDtypeStruct((1, ktot), jnp.int32),
                   jax.ShapeDtypeStruct((1, m), dt)),
        out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)),
        scratch_shapes=[pltpu.VMEM((nb, m), dt),     # resident panel / W
                        pltpu.VMEM((nb, m), dt),     # lookahead panel k+1
                        pltpu.VMEM((bb, m), dt),     # current block
                        pltpu.VMEM((bb, m), dt),     # one-hot pivot rows
                        pltpu.VMEM((nb, nb), dt),    # packed L rows / X₂
                        pltpu.VMEM((bb, bb), dt),    # step L11
                        pltpu.VMEM((bb, bb), dt),    # step L11⁻¹
                        pltpu.VMEM((nb, nb), dt),    # panel L₁₁⁻¹
                        pltpu.VMEM((nb, m), dt),     # step Π / G
                        pltpu.VMEM((1, m), dt),      # pivot-lane mask
                        pltpu.VMEM((tc, m), dt),     # trailing buffer A
                        pltpu.VMEM((tc, m), dt),     # trailing buffer B
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(())],
        input_output_aliases={0: 0},
        compiler_params=_CompilerParams(
            vmem_limit_bytes=vmem.pallas_call_limit_bytes()),
        interpret=_interpret(),
    )(at_full.astype(dt), active_row.astype(dt))
    return out, piv[0], act_out


def _potrf_panel_phase(a_out, k0, col, akk, lkk, linv_ref, sem,
                       *, n, nb, ib, tc):
    """Factor the RESIDENT (n, nb) block-column of one right-looking
    Cholesky step (the caller DMA'd it in or lookahead-carried it):
    diagonal chol+inverse (:func:`_chol_inv_kernel`), panel
    trsm-as-gemm ``L₂₁ = A₂₁·L₁₁⁻ᵀ`` over the trailing row chunks, and
    the write-back DMA into the aliased carry.  Shared by the step and
    full potrf mega-kernels so the depths stay arithmetic-identical."""

    dt = jnp.promote_types(col.dtype, jnp.float32)
    hi = jax.lax.Precision.HIGHEST
    akk[:] = col[pl.ds(k0, nb), :]
    _chol_inv_kernel(akk, lkk, linv_ref, nb=nb, ib=ib)
    col[pl.ds(k0, nb), :] = lkk[:]
    c_lo = (k0 + nb) // tc
    c_hi = n // tc

    def l21_body(c, carry):
        rows = pl.ds(c * tc, tc)
        col[rows, :] = jax.lax.dot_general(
            col[rows, :], linv_ref[:],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=dt, precision=hi)
        return carry

    jax.lax.fori_loop(c_lo, c_hi, l21_body, 0)
    odma = pltpu.make_async_copy(col, a_out.at[:, pl.ds(k0, nb)], sem)
    odma.start()
    odma.wait()
    return c_lo, c_hi


def _potrf_trailing_stream(a_out, col, bufa, bufb, ina, inb, outa, outb,
                           j_lo, c_hi, tc):
    """The symmetric rank-nb trailing update streamed as (tc, tc)
    lower-triangle tile pairs through the double-buffered residency
    against the aliased carry, column tiles ``j ∈ [j_lo, c_hi)`` —
    flop-exact with the composed strip driver (tiles above the
    diagonal are never touched).  ONE definition serves the step and
    full kernels (the full kernel starts past its lookahead column)."""

    dt = jnp.promote_types(col.dtype, jnp.float32)
    hp = jax.lax.Precision.HIGH

    def j_body(j, carry):
        j0 = j * tc

        def compute(buf, i):
            buf[:] = buf[:] - jax.lax.dot_general(
                col[pl.ds(i * tc, tc), :], col[pl.ds(j0, tc), :],
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=dt, precision=hp)

        _stream_chunks(a_out, (bufa, bufb), (ina, inb), (outa, outb),
                       j, c_hi,
                       lambda i: (pl.ds(i * tc, tc), pl.ds(j0, tc)),
                       compute)
        return carry

    jax.lax.fori_loop(j_lo, c_hi, j_body, 0)


def _potrf_step_fused_kernel(a_in, k0_ref, a_out, linv_ref, col, akk,
                             lkk, bufa, bufb, sem, ina, inb, outa, outb,
                             *, n, nb, ib, tc):
    """One pallas invocation owns a whole right-looking potrf step:

    * the (n, nb) panel block-column DMAs into a resident VMEM strip;
    * the diagonal block factors with the fused chol+inverse core
      (:func:`_chol_inv_kernel` — per-ib unblocked Cholesky, recursive-
      doubling inverse), so the panel trsm is an MXU gemm
      ``L₂₁ = A₂₁·L₁₁⁻ᵀ`` over the trailing row chunks only
      (:func:`_potrf_panel_phase`);
    * the symmetric rank-nb trailing update streams (tc, tc) tiles of
      the lower-triangle pairs through a double-buffered VMEM residency
      against the aliased carry (:func:`_potrf_trailing_stream`).
    """

    k0 = pl.multiple_of(k0_ref[0], nb)
    cdma = pltpu.make_async_copy(a_in.at[:, pl.ds(k0, nb)], col, sem)
    cdma.start()
    cdma.wait()
    c_lo, c_hi = _potrf_panel_phase(a_out, k0, col, akk, lkk, linv_ref,
                                    sem, n=n, nb=nb, ib=ib, tc=tc)
    _potrf_trailing_stream(a_out, col, bufa, bufb, ina, inb, outa, outb,
                           c_lo, c_hi, tc)


@_x32_trace
def potrf_step_fused(a, k0, nb: int = 512, tc: int = 512):
    """ONE pallas invocation owns a whole right-looking Cholesky step
    (panel chol+inverse + trsm-as-gemm + streamed symmetric trailing
    update) on the aliased (n, n) carry — see
    :func:`_potrf_step_fused_kernel`.  ``k0`` is a scalar operand, so
    one Mosaic compilation serves every step.  nb must be a power of
    two ≥ 64 with tc | nb | n.  Returns the updated carry (rows/cols
    < k0 and the strict upper triangle of the trailing block pass
    through untouched — the driver tril-cleans once at the end).  f32
    on TPU; f32/f64 in interpret mode."""

    n = a.shape[-1]
    assert a.shape[-2] == n, a.shape
    ib = min(32, nb)
    tc = min(tc, nb)
    assert nb % ib == 0 and (nb & (nb - 1)) == 0 and nb >= 64, nb
    assert n % nb == 0 and nb % tc == 0, (n, nb, tc)
    if isinstance(k0, int):
        assert k0 % nb == 0, (k0, nb)
    dt = jnp.promote_types(a.dtype, jnp.float32)
    return pl.pallas_call(
        functools.partial(_potrf_step_fused_kernel, n=n, nb=nb, ib=ib,
                          tc=tc),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_shape=jax.ShapeDtypeStruct((n, n), dt),
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.VMEM((nb, nb), dt),    # L₁₁⁻¹
                        pltpu.VMEM((n, nb), dt),     # resident panel col
                        pltpu.VMEM((nb, nb), dt),    # diag block in
                        pltpu.VMEM((nb, nb), dt),    # diag block L
                        pltpu.VMEM((tc, tc), dt),    # trailing tile A
                        pltpu.VMEM((tc, tc), dt),    # trailing tile B
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(())],
        input_output_aliases={0: 0},
        compiler_params=_CompilerParams(
            vmem_limit_bytes=vmem.pallas_call_limit_bytes()),
        interpret=_interpret(),
    )(a.astype(dt), jnp.asarray(k0, jnp.int32).reshape(1))


def _potrf_full_fused_kernel(a_in, a_out, linv_ref, col, ncol, akk, lkk,
                             bufa, bufb, sem, ina, inb, outa, outb,
                             *, n, nb, ib, tc):
    """One grid step = one whole right-looking Cholesky step (the
    :func:`_potrf_step_fused_kernel` body), with the steps themselves
    iterated by the grid inside ONE invocation and single-chip
    lookahead: each step's trailing phase updates the NEXT panel
    block-column first, in VMEM, and keeps it resident in ``ncol`` — the
    next step's diagonal factor and trsm-as-gemm start with zero HBM
    dependency, and that column never round-trips HBM mid-step."""

    dt = jnp.promote_types(col.dtype, jnp.float32)
    hp = jax.lax.Precision.HIGH
    kstep = pl.program_id(0)
    ksteps = pl.num_programs(0)
    k0 = pl.multiple_of(kstep * nb, nb)

    @pl.when(kstep == 0)
    def _load():
        cdma = pltpu.make_async_copy(a_out.at[:, pl.ds(k0, nb)], col, sem)
        cdma.start()
        cdma.wait()

    @pl.when(kstep > 0)
    def _carry():
        # lookahead hand-off: this column was already rank-nb-updated
        # in VMEM by the previous step's trailing phase
        col[:] = ncol[:]

    c_lo, c_hi = _potrf_panel_phase(a_out, k0, col, akk, lkk, linv_ref,
                                    sem, n=n, nb=nb, ib=ib, tc=tc)
    look = kstep + 1 < ksteps

    @pl.when(look)
    def _lookahead():
        # next panel block-column first: fetch, apply this step's
        # symmetric rank-nb update over its trailing rows, keep
        # resident — the one column the stream below never touches
        ndma = pltpu.make_async_copy(
            a_out.at[:, pl.ds(k0 + nb, nb)], ncol, sem)
        ndma.start()
        ndma.wait()
        lj = col[pl.ds(k0 + nb, nb), :]

        def nupd(c, carry):
            rows = pl.ds(c * tc, tc)
            ncol[rows, :] = ncol[rows, :] - jax.lax.dot_general(
                col[rows, :], lj,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=dt, precision=hp)
            return carry

        jax.lax.fori_loop(c_lo, c_hi, nupd, 0)

    # the lookahead already owns the next panel's column block — the
    # streamed trailing tiles start past it
    j_lo = c_lo + jnp.where(look, nb // tc, 0)
    _potrf_trailing_stream(a_out, col, bufa, bufb, ina, inb, outa, outb,
                           j_lo, c_hi, tc)


@_x32_trace
def potrf_full_fused(a, nb: int = 512, tc: int = 512):
    """ONE pallas invocation owns the WHOLE right-looking Cholesky
    factorization — the grid iterates the block-column steps, each
    running the fused panel chol+inverse + trsm-as-gemm + streamed
    symmetric trailing update with the next panel column lookahead-
    updated in VMEM (see :func:`_potrf_full_fused_kernel`).  Same
    carry contract as :func:`potrf_step_fused` (the driver tril-cleans
    once at the end); nb must be a power of two ≥ 64 with tc | nb | n.
    f32 on TPU; f32/f64 in interpret mode."""

    n = a.shape[-1]
    assert a.shape[-2] == n, a.shape
    ib = min(32, nb)
    tc = min(tc, nb)
    assert nb % ib == 0 and (nb & (nb - 1)) == 0 and nb >= 64, nb
    assert n % nb == 0 and nb % tc == 0, (n, nb, tc)
    dt = jnp.promote_types(a.dtype, jnp.float32)
    return pl.pallas_call(
        functools.partial(_potrf_full_fused_kernel, n=n, nb=nb, ib=ib,
                          tc=tc),
        grid=(n // nb,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_shape=jax.ShapeDtypeStruct((n, n), dt),
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.VMEM((nb, nb), dt),    # L₁₁⁻¹
                        pltpu.VMEM((n, nb), dt),     # resident panel col
                        pltpu.VMEM((n, nb), dt),     # lookahead col k+1
                        pltpu.VMEM((nb, nb), dt),    # diag block in
                        pltpu.VMEM((nb, nb), dt),    # diag block L
                        pltpu.VMEM((tc, tc), dt),    # trailing tile A
                        pltpu.VMEM((tc, tc), dt),    # trailing tile B
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(())],
        input_output_aliases={0: 0},
        compiler_params=_CompilerParams(
            vmem_limit_bytes=vmem.pallas_call_limit_bytes()),
        interpret=_interpret(),
    )(a.astype(dt))
# eig/SVD stage-2 middle section (or one checkpointed sweep-range chunk
# of it).  The host chase in native/runtime.cc streams the band through
# a single core and ships the packed reflector log back to the device
# for the batched WY back-transform; here the grid iterates the
# wavefront staggers t = 3·sweep + window of the recorded dependence
# analysis (STATUS r4: same-t tasks touch disjoint band rows, every
# conflicting pair crosses a t boundary), the band lives in an ALIASED
# HBM carry DMA'd through VMEM in window-sized strips, and the log is
# written directly into the (nsweeps, tmax, kd+1) padded device layout
# that `linalg.eig._pack_hh_log` builds today — `unmtr_hb2st_hh`
# consumes it with zero host repacking and zero host↔device tunnel.
#
# The per-task arithmetic is a faithful port of the native task bodies
# (hb_sweep_start/step + hh_two_sided, tb_sweep_start/block): length-L
# reflectors via LAPACK-convention larfg (zlarfg for complex — chased
# beta real), two-sided window updates, and the length-1 trailing
# coupling apply.  Shapes are static at kd with traced-length masks, so
# one trace serves the whole chase; band-storage strips convert to
# dense window patches (and back) with a single shear gather each way.
# ---------------------------------------------------------------------------


def _wf_larfg(x, length, kd):
    """Masked LAPACK ``larfg`` over a (kd,) vector whose first ``length``
    entries are live: returns ``(v, tau, beta)`` with v[0] = 1 stored
    (the log convention of ``native/runtime.cc`` ``larfg_t``).  Complex
    follows zlarfg: beta real, tau complex."""

    dt = x.dtype
    cplx = jnp.issubdtype(dt, jnp.complexfloating)
    idx = jax.lax.iota(jnp.int32, kd)
    mask = idx < length
    x = jnp.where(mask, x, 0)
    alpha = x[0]
    tail = jnp.where(idx >= 1, x, 0)
    if cplx:
        xnorm2 = jnp.sum(jnp.real(tail * jnp.conj(tail)))
        alpha_r, alpha_i = jnp.real(alpha), jnp.imag(alpha)
    else:
        xnorm2 = jnp.sum(tail * tail)
        alpha_r, alpha_i = alpha, jnp.zeros_like(alpha)
    anorm = jnp.sqrt(alpha_r * alpha_r + alpha_i * alpha_i + xnorm2)
    beta_r = jnp.where(alpha_r >= 0, -anorm, anorm)
    is_zero = (xnorm2 == 0) & (alpha_i == 0)
    beta = beta_r.astype(dt)
    beta_safe = jnp.where(beta == 0, 1, beta)
    tau = jnp.where(is_zero, 0, (beta - alpha) / beta_safe).astype(dt)
    denom = alpha - beta
    denom = jnp.where(is_zero | (denom == 0), 1, denom)
    v = jnp.where(idx >= 1, x / denom, 0)
    v = jnp.where(idx == 0, jnp.ones((), dt), v)
    v = jnp.where(mask, v, 0).astype(dt)
    return v, tau, jnp.where(is_zero, alpha, beta)


def _wf_two_sided(s_blk, v, tau, length, kd):
    """Hermitian two-sided reflector apply on a dense (kd, kd) window:
    S ← Hᴴ·S·H, H = I − τ·v·vᴴ, live region ``length`` — the
    ``hh_two_sided`` task body of the native chase."""

    hi = jax.lax.Precision.HIGHEST
    idx = jax.lax.iota(jnp.int32, kd)
    m = idx < length
    wv = tau * jnp.where(m, jnp.dot(s_blk, v, precision=hi), 0)
    dot = jnp.sum(jnp.conj(v) * wv)
    wv = wv - (0.5 * jnp.conj(tau) * dot) * v
    upd = v[:, None] * jnp.conj(wv)[None, :] \
        + wv[:, None] * jnp.conj(v)[None, :]
    return s_blk - jnp.where(m[:, None] & m[None, :], upd, 0)


def _wf_dense_from_lower(strip, kd, ps, w):
    """Dense Hermitian patch P[r, c] = A[p0+r, p0+c] from a lower-band
    storage strip (``strip[c, d]`` = A[p0+c+d, p0+c]): one shear gather
    builds both triangles."""

    a0 = jax.lax.broadcasted_iota(jnp.int32, (ps, ps), 0)
    a1 = jax.lax.broadcasted_iota(jnp.int32, (ps, ps), 1)
    d = a1 - a0          # g[c, r] = strip[c, r - c]
    g = jnp.take_along_axis(strip, jnp.clip(d, 0, strip.shape[1] - 1),
                            axis=1)
    g = jnp.where((d >= 0) & (d < w), g, 0)
    # P[r, c]: lower (r >= c) from g.T, upper mirrored conjugate from g
    return jnp.where(a0 >= a1, g.T, jnp.conj(g))


def _wf_lower_from_dense(patch, strip_old, kd, ps, w):
    """Inverse shear: write the patch's lower triangle back into band
    storage; entries outside the patch (or past the stored width) keep
    their old values — the round trip is bit-exact for untouched
    entries."""

    ci = jax.lax.broadcasted_iota(jnp.int32, strip_old.shape, 0)
    di = jax.lax.broadcasted_iota(jnp.int32, strip_old.shape, 1)
    g2 = jnp.take_along_axis(patch.T, jnp.clip(ci + di, 0, ps - 1), axis=1)
    return jnp.where((ci + di < ps) & (di < w), g2, strip_old)


def _wf_dense_from_gen(strip, kd, ps, w):
    """Dense patch P[r, c] = A[q0+r, q0+c] from row-major general-band
    storage (``strip[r, d]`` = A[q0+r, q0+r+d−kd]) — the tb2bd layout."""

    ri = jax.lax.broadcasted_iota(jnp.int32, (ps, ps), 0)
    ci = jax.lax.broadcasted_iota(jnp.int32, (ps, ps), 1)
    d = ci - ri + kd
    g = jnp.take_along_axis(strip, jnp.clip(d, 0, strip.shape[1] - 1),
                            axis=1)
    return jnp.where((d >= 0) & (d < w), g, 0)


def _wf_gen_from_dense(patch, strip_old, kd, ps, w):
    ri = jax.lax.broadcasted_iota(jnp.int32, strip_old.shape, 0)
    di = jax.lax.broadcasted_iota(jnp.int32, strip_old.shape, 1)
    c = ri + di - kd
    g2 = jnp.take_along_axis(patch, jnp.clip(c, 0, ps - 1), axis=1)
    return jnp.where((c >= 0) & (c < ps) & (di < w), g2, strip_old)


def _hb_tail(patch, off, v, tau, length, apply_flag, kd, ps):
    """The length-1 trailing coupling apply (``hb_sweep_tail``): right-
    apply the window's reflector to the single row past the window."""

    ridx = jax.lax.iota(jnp.int32, ps)
    cidx = jax.lax.iota(jnp.int32, ps)
    rowsel = ridx == off + length
    arow = jnp.sum(jnp.where(rowsel[:, None], patch, 0), axis=0)
    seg = arow[off:off + kd]
    acc = jnp.sum(seg * v) * tau
    seg_new = seg - acc * jnp.conj(v)
    padded = jnp.zeros((ps,), patch.dtype).at[off:off + kd].set(seg_new)
    cmask = (cidx >= off) & (cidx < off + length)
    return jnp.where(apply_flag & rowsel[:, None] & cmask[None, :],
                     padded[None, :], patch)


def _hb2st_wave_kernel(ab_in, vt_in, ab_hbm, vt_hbm, strip, vtrow,
                       state_v, state_tau, sem, *, n, kd, j0, nsweeps,
                       nwin_max, nl, w_real, ps):
    """One grid step = one wavefront stagger t; the inner loop visits
    the (disjoint) live sweeps and runs each sweep's window task —
    ``hb_sweep_start`` for window 0, ``hb_sweep_step`` after."""

    t = pl.program_id(0)
    hi = jax.lax.Precision.HIGHEST
    idx_k = jax.lax.iota(jnp.int32, kd)
    ridx_ps = jax.lax.iota(jnp.int32, ps)
    js_lo = jnp.maximum((t - nwin_max + 3) // 3, 0)
    js_hi = jnp.minimum(t // 3, nsweeps - 1)

    def _emit(js, wlog, v, tau, patch, p0):
        sl = jax.lax.rem(js, jnp.int32(nl))
        state_v[pl.ds(sl, 1), :] = v[None, :]
        state_tau[pl.ds(sl, 1), :] = tau.reshape(1, 1)
        vtrow[:, :] = jnp.concatenate([tau.reshape(1), v])[None, :]
        dma_l = pltpu.make_async_copy(vtrow, vt_hbm.at[pl.ds(js, 1), wlog],
                                      sem)
        dma_l.start()
        dma_l.wait()
        strip[:, :] = _wf_lower_from_dense(patch, strip[:, :], kd, ps,
                                           w_real)
        dma_o = pltpu.make_async_copy(strip, ab_hbm.at[pl.ds(p0, ps), :],
                                      sem)
        dma_o.start()
        dma_o.wait()

    def task(js, carry):
        j = j0 + js
        wwin = t - 3 * js
        nwin_j = (n - 3 - j) // kd + 1
        valid = (wwin >= 0) & (wwin < nwin_j)

        @pl.when(valid & (wwin == 0))
        def _start():
            p0 = j
            dma_i = pltpu.make_async_copy(ab_hbm.at[pl.ds(p0, ps), :],
                                          strip, sem)
            dma_i.start()
            dma_i.wait()
            patch = _wf_dense_from_lower(strip[:, :], kd, ps, w_real)
            length = jnp.minimum(kd, n - 1 - j)
            v, tau, beta = _wf_larfg(patch[1:1 + kd, 0], length, kd)
            col0 = patch[:, 0]
            col0 = jnp.where(ridx_ps == 1, beta,
                             jnp.where((ridx_ps >= 2)
                                       & (ridx_ps < 1 + length), 0, col0))
            patch = patch.at[:, 0].set(col0)
            s_blk = _wf_two_sided(patch[1:1 + kd, 1:1 + kd], v, tau,
                                  length, kd)
            patch = patch.at[1:1 + kd, 1:1 + kd].set(s_blk)
            patch = _hb_tail(patch, 1, v, tau, length,
                             (nwin_j == 1) & (n - (j + 1 + length) == 1),
                             kd, ps)
            _emit(js, 0, v, tau, patch, p0)

        @pl.when(valid & (wwin > 0))
        def _step():
            p0 = j + 1 + (wwin - 1) * kd
            r1 = p0 + kd
            lt = jnp.minimum(kd, n - r1)
            dma_i = pltpu.make_async_copy(ab_hbm.at[pl.ds(p0, ps), :],
                                          strip, sem)
            dma_i.start()
            dma_i.wait()
            patch = _wf_dense_from_lower(strip[:, :], kd, ps, w_real)
            sl = jax.lax.rem(js, jnp.int32(nl))
            u_prev = state_v[pl.ds(sl, 1), :][0]
            tau_prev = state_tau[pl.ds(sl, 1), :][0, 0]
            blk = patch[kd:2 * kd, 0:kd]
            # right-apply the previous window's reflector to the block
            acc = jnp.dot(blk, u_prev, precision=hi)
            blk = blk - tau_prev * jnp.where(idx_k < lt, acc, 0)[:, None] \
                * jnp.conj(u_prev)[None, :]
            v, tau, beta = _wf_larfg(blk[:, 0], lt, kd)
            col = jnp.where(idx_k == 0, beta,
                            jnp.where((idx_k >= 1) & (idx_k < lt), 0,
                                      blk[:, 0]))
            blk = blk.at[:, 0].set(col)
            # left-apply the new reflector to the remaining block columns
            accc = jnp.dot(jnp.conj(v), blk, precision=hi)
            blk = blk - v[:, None] \
                * (jnp.conj(tau) * jnp.where(idx_k >= 1, accc, 0))[None, :]
            patch = patch.at[kd:2 * kd, 0:kd].set(blk)
            s_blk = _wf_two_sided(patch[kd:2 * kd, kd:2 * kd], v, tau,
                                  lt, kd)
            patch = patch.at[kd:2 * kd, kd:2 * kd].set(s_blk)
            patch = _hb_tail(patch, kd, v, tau, lt,
                             (wwin == nwin_j - 1) & (n - (r1 + lt) == 1),
                             kd, ps)
            _emit(js, wwin, v, tau, patch, p0)

        return carry

    jax.lax.fori_loop(js_lo, js_hi + 1, task, 0)


def _hb_wave_meta(n, kd, j0, j1):
    """Static wavefront geometry shared by the wrapper and tests:
    per-sweep window counts, the log's tmax, the grid's stagger count
    and the state-ring size (all host-side ints)."""

    j1 = min(j1 if j1 is not None else n - 2, n - 2)
    sweeps = list(range(j0, max(j1, j0)))
    nwin = [(n - 3 - j) // kd + 1 for j in sweeps]
    nsweeps = len(sweeps)
    if nsweeps == 0 or not nwin:
        return 0, 0, 0, 1
    nwin_max = max(nwin)
    tmax_grid = max(3 * js + nw - 1 for js, nw in enumerate(nwin))
    nl = min(nsweeps, nwin_max // 3 + 2)
    return nsweeps, nwin_max, tmax_grid, nl


@_x32_trace
@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def hb2st_wavefront(abw, kd: int, j0: int = 0, j1: int | None = None):
    """Device-resident Householder band→tridiagonal bulge chase: sweeps
    ``[j0, j1)`` of the SLATE hebr1/2/3 schedule in ONE Pallas
    invocation (``native/runtime.cc`` ``hb2st_hh_wave`` moved on
    device; the wavefront task DAG of ``src/hb2st.cc:23-90``).

    ``abw`` is WIDE lower-band storage ``(n, 2·kd+2)`` (``abw[c, d]`` =
    A[c+d, c]); returns ``(abw', vt)`` where ``vt`` has shape
    ``(nsweeps, tmax, kd+1)`` with ``vt[..., 0]`` = τ and
    ``vt[..., 1:]`` = v (v[0] = 1 stored) — exactly the padded layout
    of ``linalg.eig._pack_hh_log`` once split, so ``unmtr_hb2st_hh``
    consumes it with zero host repacking.  f32/f64 compile on TPU;
    c128 runs in interpret mode (CPU CI parity vs the native chase).
    """

    n, wdth = abw.shape
    assert wdth == 2 * kd + 2, (abw.shape, kd)
    assert kd >= 4, "wavefront patches need kd >= 4 (host chase below)"
    nsweeps, nwin_max, tmax_grid, nl = _hb_wave_meta(n, kd, j0, j1)
    dt = abw.dtype
    if nsweeps == 0:
        return abw, jnp.zeros((0, 1, kd + 1), dt)
    ps = 2 * kd + 2
    w_real = 2 * kd + 2
    wp = w_real if _interpret() else ((w_real + 127) // 128) * 128
    ab_pad = jnp.zeros((n + ps, wp), dt).at[:n, :w_real].set(abw)
    vt0 = jnp.zeros((nsweeps, nwin_max, kd + 1), dt)
    out_ab, out_vt = pl.pallas_call(
        functools.partial(_hb2st_wave_kernel, n=n, kd=kd, j0=j0,
                          nsweeps=nsweeps, nwin_max=nwin_max, nl=nl,
                          w_real=w_real, ps=ps),
        grid=(tmax_grid + 1,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pl.ANY)),
        out_shape=(jax.ShapeDtypeStruct((n + ps, wp), dt),
                   jax.ShapeDtypeStruct((nsweeps, nwin_max, kd + 1), dt)),
        scratch_shapes=[pltpu.VMEM((ps, wp), dt),
                        pltpu.VMEM((1, kd + 1), dt),
                        pltpu.VMEM((nl, kd), dt),
                        pltpu.VMEM((nl, 1), dt),
                        pltpu.SemaphoreType.DMA(())],
        input_output_aliases={0: 0, 1: 1},
        compiler_params=_CompilerParams(
            vmem_limit_bytes=vmem.pallas_call_limit_bytes()),
        interpret=_interpret(),
    )(ab_pad, vt0)
    return out_ab[:n, :w_real], out_vt


def _tb2bd_wave_kernel(st_in, ut_in, vt_in, st_hbm, ut_hbm, vt_hbm,
                       strip, vtrow, state_u, state_tau, sem, *, n, kd,
                       s0, nsweeps, nblk_max, nl, w_real, ps):
    """tb2bd twin of :func:`_hb2st_wave_kernel`: general-band storage,
    two reflector logs (left U, right V), per-sweep carried left
    reflector — the ``tb_sweep_start``/``tb_sweep_block`` task bodies
    of the native wavefront."""

    t = pl.program_id(0)
    hi = jax.lax.Precision.HIGHEST
    idx_k = jax.lax.iota(jnp.int32, kd)
    js_lo = jnp.maximum((t - nblk_max + 3) // 3, 0)
    js_hi = jnp.minimum(t // 3, nsweeps - 1)

    def _emit(js, b, u, tauu, v, tauv, patch, q0):
        sl = jax.lax.rem(js, jnp.int32(nl))
        state_u[pl.ds(sl, 1), :] = u[None, :]
        state_tau[pl.ds(sl, 1), :] = tauu.reshape(1, 1)
        vtrow[:, :] = jnp.concatenate([tauv.reshape(1), v])[None, :]
        dma_v = pltpu.make_async_copy(vtrow, vt_hbm.at[pl.ds(js, 1), b],
                                      sem)
        dma_v.start()
        dma_v.wait()
        vtrow[:, :] = jnp.concatenate([tauu.reshape(1), u])[None, :]
        dma_u = pltpu.make_async_copy(vtrow, ut_hbm.at[pl.ds(js, 1), b],
                                      sem)
        dma_u.start()
        dma_u.wait()
        strip[:, :] = _wf_gen_from_dense(patch, strip[:, :], kd, ps,
                                         w_real)
        dma_o = pltpu.make_async_copy(strip, st_hbm.at[pl.ds(q0, ps), :],
                                      sem)
        dma_o.start()
        dma_o.wait()

    def task(js, carry):
        s = s0 + js
        b = t - 3 * js
        nblk_s = (n - 2 - s) // kd + 1
        valid = (b >= 0) & (b < nblk_s)

        @pl.when(valid & (b == 0))
        def _start():
            q0 = s
            dma_i = pltpu.make_async_copy(st_hbm.at[pl.ds(q0, ps), :],
                                          strip, sem)
            dma_i.start()
            dma_i.wait()
            patch = _wf_dense_from_gen(strip[:, :], kd, ps, w_real)
            lv = jnp.minimum(kd, n - 1 - s)
            cidx_ps = jax.lax.iota(jnp.int32, ps)
            # right reflector from row s beyond the superdiagonal
            v, tauv, betav = _wf_larfg(patch[0, 1:1 + kd], lv, kd)
            row0 = patch[0, :]
            row0 = jnp.where(cidx_ps == 1, betav,
                             jnp.where((cidx_ps >= 2)
                                       & (cidx_ps < 1 + lv), 0, row0))
            patch = patch.at[0, :].set(row0)
            blk = patch[1:1 + kd, 1:1 + kd]
            acc = jnp.dot(blk, v, precision=hi)
            blk = blk - tauv * jnp.where(idx_k < lv, acc, 0)[:, None] \
                * v[None, :]
            # left reflector from the first column below the diagonal
            u, tauu, betau = _wf_larfg(blk[:, 0], lv, kd)
            col = jnp.where(idx_k == 0, betau,
                            jnp.where((idx_k >= 1) & (idx_k < lv), 0,
                                      blk[:, 0]))
            blk = blk.at[:, 0].set(col)
            accc = jnp.dot(u, blk, precision=hi)
            blk = blk - tauu * u[:, None] \
                * jnp.where((idx_k >= 1) & (idx_k < lv), accc, 0)[None, :]
            patch = patch.at[1:1 + kd, 1:1 + kd].set(blk)
            _emit(js, 0, u, tauu, v, tauv, patch, q0)

        @pl.when(valid & (b > 0))
        def _block():
            i_lo = (b - 1) * kd + 1 + s
            j_lo = i_lo + kd
            li = jnp.minimum(kd, n - i_lo)
            lj = jnp.minimum(kd, n - j_lo)
            q0 = i_lo
            dma_i = pltpu.make_async_copy(st_hbm.at[pl.ds(q0, ps), :],
                                          strip, sem)
            dma_i.start()
            dma_i.wait()
            patch = _wf_dense_from_gen(strip[:, :], kd, ps, w_real)
            sl = jax.lax.rem(js, jnp.int32(nl))
            u_prev = state_u[pl.ds(sl, 1), :][0]
            tau_prev = state_tau[pl.ds(sl, 1), :][0, 0]
            off = patch[0:kd, kd:2 * kd]
            # gebr2: left-apply the previous U to the off-diagonal block
            accc = jnp.dot(u_prev, off, precision=hi)
            off = off - tau_prev * u_prev[:, None] \
                * jnp.where(idx_k < lj, accc, 0)[None, :]
            # next right reflector from the block's first row
            v, tauv, betav = _wf_larfg(off[0, :], lj, kd)
            row = jnp.where(idx_k == 0, betav,
                            jnp.where((idx_k >= 1) & (idx_k < lj), 0,
                                      off[0, :]))
            off = off.at[0, :].set(row)
            acc = jnp.dot(off, v, precision=hi)
            off = off - tauv \
                * jnp.where((idx_k >= 1) & (idx_k < li), acc, 0)[:, None] \
                * v[None, :]
            patch = patch.at[0:kd, kd:2 * kd].set(off)
            # gebr3: right-apply it to the diagonal block
            diag = patch[kd:2 * kd, kd:2 * kd]
            acc = jnp.dot(diag, v, precision=hi)
            diag = diag - tauv * jnp.where(idx_k < lj, acc, 0)[:, None] \
                * v[None, :]
            # next left reflector from the block's first column
            u, tauu, betau = _wf_larfg(diag[:, 0], lj, kd)
            col = jnp.where(idx_k == 0, betau,
                            jnp.where((idx_k >= 1) & (idx_k < lj), 0,
                                      diag[:, 0]))
            diag = diag.at[:, 0].set(col)
            accc = jnp.dot(u, diag, precision=hi)
            diag = diag - tauu * u[:, None] \
                * jnp.where((idx_k >= 1) & (idx_k < lj), accc, 0)[None, :]
            patch = patch.at[kd:2 * kd, kd:2 * kd].set(diag)
            _emit(js, b, u, tauu, v, tauv, patch, q0)

        return carry

    jax.lax.fori_loop(js_lo, js_hi + 1, task, 0)


def _tb_wave_meta(n, kd, s0, s1):
    s1 = min(s1 if s1 is not None else n - 1, n - 2)
    sweeps = list(range(s0, max(s1, s0)))
    nblk = [(n - 2 - s) // kd + 1 for s in sweeps]
    nsweeps = len(sweeps)
    if nsweeps == 0 or not nblk:
        return 0, 0, 0, 1
    nblk_max = max(nblk)
    tmax_grid = max(3 * js + nb - 1 for js, nb in enumerate(nblk))
    nl = min(nsweeps, nblk_max // 3 + 2)
    return nsweeps, nblk_max, tmax_grid, nl


@_x32_trace
@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def tb2bd_wavefront(st, kd: int, s0: int = 0, s1: int | None = None):
    """Device-resident Householder band→bidiagonal bulge chase: sweeps
    ``[s0, s1)`` of the SLATE gebr1/2/3 schedule in ONE Pallas
    invocation — ``native/runtime.cc`` ``tb2bd_hh_wave`` on device.

    ``st`` is row-major general-band storage ``(n, 3·kd+2)``
    (``st[r, c−r+kd]`` = A[r, c]); returns ``(st', ut, vt)`` — the left
    (U) and right (V) logs in the same ``(nsweeps, tmax, kd+1)``
    τ-prefixed padded layout as :func:`hb2st_wavefront`."""

    n, wdth = st.shape
    assert wdth == 3 * kd + 2, (st.shape, kd)
    assert kd >= 4, "wavefront patches need kd >= 4 (host chase below)"
    nsweeps, nblk_max, tmax_grid, nl = _tb_wave_meta(n, kd, s0, s1)
    dt = st.dtype
    if nsweeps == 0:
        empty = jnp.zeros((0, 1, kd + 1), dt)
        return st, empty, empty
    ps = 2 * kd + 2
    w_real = 3 * kd + 2
    wp = w_real if _interpret() else ((w_real + 127) // 128) * 128
    st_pad = jnp.zeros((n + ps, wp), dt).at[:n, :w_real].set(st)
    log0 = jnp.zeros((nsweeps, nblk_max, kd + 1), dt)
    out_st, out_ut, out_vt = pl.pallas_call(
        functools.partial(_tb2bd_wave_kernel, n=n, kd=kd, s0=s0,
                          nsweeps=nsweeps, nblk_max=nblk_max, nl=nl,
                          w_real=w_real, ps=ps),
        grid=(tmax_grid + 1,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
        out_specs=tuple([pl.BlockSpec(memory_space=pl.ANY)] * 3),
        out_shape=(jax.ShapeDtypeStruct((n + ps, wp), dt),
                   jax.ShapeDtypeStruct((nsweeps, nblk_max, kd + 1), dt),
                   jax.ShapeDtypeStruct((nsweeps, nblk_max, kd + 1), dt)),
        scratch_shapes=[pltpu.VMEM((ps, wp), dt),
                        pltpu.VMEM((1, kd + 1), dt),
                        pltpu.VMEM((nl, kd), dt),
                        pltpu.VMEM((nl, 1), dt),
                        pltpu.SemaphoreType.DMA(())],
        input_output_aliases={0: 0, 1: 1, 2: 2},
        compiler_params=_CompilerParams(
            vmem_limit_bytes=vmem.pallas_call_limit_bytes()),
        interpret=_interpret(),
    )(st_pad, log0, log0)
    return out_st[:n, :w_real], out_ut, out_vt


# ---------------------------------------------------------------------------
# Grid-batched many-problem kernels (ISSUE 8) — the serving workload is
# thousands of SMALL independent factorizations, not one giant one
# (per-user covariance / least-squares / whitening).  Launching the
# single-problem drivers per problem pays one dispatch + compile-cache
# walk + HBM round trip each; here ONE pallas_call owns B problems at
# once: the grid iterates batch BLOCKS of ``bt`` problems, each grid
# step DMAs its (bt, n, n) slab into VMEM, factors every resident
# problem to completion (the whole problem is the panel at these sizes),
# and writes the slab back — the BLASX many-problems-per-launch shape.
# ``bt`` (problems per launch step) comes from the shared VMEM budget
# (:func:`slate_tpu.ops.vmem.batch_per_launch`), not a per-gate
# constant.
# ---------------------------------------------------------------------------


def _chol_blocked_value(a, ib):
    """Value-form right-looking blocked Cholesky of ONE (n, n) SPD
    problem — :func:`_chol_inv_kernel`'s loop re-expressed over values
    so the batched kernel can run it per resident problem: ib-block
    diagonal chol (:func:`_chol_unblocked`) + block inverse
    (:func:`_trtri_unblocked`) turn the panel trsm into an MXU gemm,
    the trailing update is a rank-ib gemm.  Returns the lower factor
    (upper triangle zeroed)."""

    n = a.shape[-1]
    dt = jnp.promote_types(a.dtype, jnp.float32)
    hi = jax.lax.Precision.HIGHEST
    for k0 in range(0, n, ib):
        blk = _chol_unblocked(a[k0:k0 + ib, k0:k0 + ib], ib)
        a = a.at[k0:k0 + ib, k0:k0 + ib].set(blk)
        if k0 + ib < n:
            binv = _trtri_unblocked(blk, ib)
            a21 = a[k0 + ib:, k0:k0 + ib]
            l21 = jnp.dot(a21, binv.T, preferred_element_type=dt,
                          precision=hi)
            a = a.at[k0 + ib:, k0:k0 + ib].set(l21)
            a = a.at[k0 + ib:, k0 + ib:].add(
                -jnp.dot(l21, l21.T, preferred_element_type=dt,
                         precision=hi))
    rows = jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
    return jnp.where(rows >= cols, a, 0.0)


def _potrf_batched_kernel(a_ref, l_ref, *, bt, ib):
    for b in range(bt):
        l_ref[b] = _chol_blocked_value(a_ref[b], ib)


@_x32_trace
def potrf_batched(a, *, bt: int = 1, ib: int = 32):
    """Grid-batched Cholesky: ``a`` is (B, n, n) SPD, returns the (B,
    n, n) lower factors from ONE pallas_call whose grid iterates
    B/bt batch blocks (``bt`` resident problems per step).  Requires
    ``B % bt == 0`` and ``n % ib == 0``; f32 on TPU, f32/f64 in
    interpret mode."""

    bsz, n, n2 = a.shape
    assert n == n2 and bsz % bt == 0 and n % min(ib, n) == 0, (a.shape, bt)
    ib = min(ib, n)
    dt = jnp.promote_types(a.dtype, jnp.float32)
    return pl.pallas_call(
        functools.partial(_potrf_batched_kernel, bt=bt, ib=ib),
        grid=(bsz // bt,),
        in_specs=[pl.BlockSpec((bt, n, n), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((bt, n, n), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((bsz, n, n), dt),
        compiler_params=_CompilerParams(
            vmem_limit_bytes=vmem.pallas_call_limit_bytes()),
        interpret=_interpret(),
    )(a.astype(dt))


def _lu_scattered_value(at, ib):
    """Value-form scattered-row partial-pivot LU of ONE square problem
    held LANE-MAJOR (``at`` is Aᵀ, (n, n)) — the elimination core of
    :func:`_factor_panel_linv_kernel` with the panel width equal to the
    whole problem (for the batched small-problem workload the problem
    IS the panel): TRUE partial pivoting as a masked argmax over the
    still-active lanes, rows never move, ib-block trailing updates run
    as MXU gemms with the pivot-row gather folded in as one-hot dots.
    Returns ``(at_factored, piv (1, n) int32, act (1, n))`` — packed
    factor rows live in the pivot lanes (``at[:, piv].T`` is the
    LAPACK-packed LU)."""

    n, m = at.shape
    dt = jnp.promote_types(at.dtype, jnp.float32)
    hi = jax.lax.Precision.HIGHEST
    iota_lane = jax.lax.broadcasted_iota(jnp.int32, (1, m), 1)
    iota_sub = jax.lax.broadcasted_iota(jnp.int32, (ib, 1), 0)
    iota_ibrow = jax.lax.broadcasted_iota(jnp.int32, (1, ib), 1)
    eye_ib = (jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 0)
              == jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 1)
              ).astype(dt)
    tril_ib = (jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 0)
               > jax.lax.broadcasted_iota(jnp.int32, (ib, ib), 1))
    act = jnp.ones((1, m), dt)
    piv = jnp.zeros((1, n), jnp.int32)

    for s0 in range(0, n, ib):
        def col_step(j, carry, s0=s0):
            sub, act, pivb, ohsub = carry
            col = jax.lax.dynamic_slice_in_dim(sub, j, 1, axis=0)
            mag = jnp.abs(col) * act
            mx = jnp.max(mag)
            cand = jnp.where((mag >= mx) & (act > 0), iota_lane, m)
            p = jnp.min(cand).astype(jnp.int32)
            pivb = jnp.where(iota_ibrow == j, p, pivb)
            oh = (iota_lane == p).astype(dt)
            pval = jnp.sum(col * oh)
            safe = jnp.where(pval == 0, 1.0, pval)
            live = (act > 0) & (oh == 0)
            lrow = jnp.where(live, col / safe, 0.0)
            newcol = jnp.where(live, lrow, col)
            pcol = jnp.sum(sub * oh, axis=1, keepdims=True)
            sub = jnp.where(iota_sub == j, newcol,
                            sub - jnp.where(iota_sub > j, pcol, 0.0) * lrow)
            ohsub = jnp.where(iota_sub == j, oh, ohsub)
            act = act * (1.0 - oh)
            return sub, act, pivb, ohsub

        sub, act, pivb, ohsub = jax.lax.fori_loop(
            0, ib, col_step,
            (at[s0:s0 + ib], act, jnp.zeros((1, ib), jnp.int32),
             jnp.zeros((ib, m), dt)))
        at = at.at[s0:s0 + ib].set(sub)
        piv = jax.lax.dynamic_update_slice(piv, pivb, (0, s0))
        if s0 + ib < n:
            # trailing block rows: pivot-row gather as one-hot dots, the
            # ib-block u12 solve against the block's unit-lower inverse,
            # rank-ib MXU update with the L-part/pivot-part fused into
            # one operand (ohsub − lsubt), as in the fused panel kernel
            l11 = jax.lax.dot_general(
                ohsub, sub, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=dt, precision=hi)
            l11u = jnp.where(tril_ib, l11, 0.0) + eye_ib
            l11inv = _trtri_unblocked(l11u, ib)
            rest = at[s0 + ib:]
            ut = jax.lax.dot_general(
                rest, ohsub, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=dt, precision=hi)
            u12t = jnp.dot(ut, l11inv.T, preferred_element_type=dt,
                           precision=hi)
            pivm = jnp.sum(ohsub, axis=0, keepdims=True)
            lsubt = sub * act
            at = at.at[s0 + ib:].set(
                rest * (1.0 - pivm)
                + jnp.dot(u12t, ohsub - lsubt, preferred_element_type=dt,
                          precision=hi))
    return at, piv, act


def _getrf_batched_kernel(at_ref, out_ref, piv_ref, *, bt, ib):
    for b in range(bt):
        at, piv, _ = _lu_scattered_value(at_ref[b], ib)
        out_ref[b] = at
        piv_ref[b] = piv


@_x32_trace
def getrf_batched(at, *, bt: int = 1, ib: int = 32):
    """Grid-batched partial-pivot LU: ``at`` is (B, n, n) holding each
    problem TRANSPOSED (lane-major); returns ``(at_factored, piv)``
    with ``piv`` (B, n) — per problem ``at_factored[b][:, piv[b]].T``
    is the LAPACK-packed LU of ``at[b].T`` and ``piv[b]`` the full row
    permutation (square problems pivot every row).  ONE pallas_call,
    grid over B/bt batch blocks.  Requires ``B % bt == 0`` and
    ``n % ib == 0``; f32 on TPU, f32/f64 in interpret mode."""

    bsz, n, n2 = at.shape
    assert n == n2 and bsz % bt == 0 and n % min(ib, n) == 0, (at.shape, bt)
    ib = min(ib, n)
    dt = jnp.promote_types(at.dtype, jnp.float32)
    out, piv = pl.pallas_call(
        functools.partial(_getrf_batched_kernel, bt=bt, ib=ib),
        grid=(bsz // bt,),
        in_specs=[pl.BlockSpec((bt, n, n), lambda i: (i, 0, 0))],
        out_specs=(pl.BlockSpec((bt, n, n), lambda i: (i, 0, 0)),
                   pl.BlockSpec((bt, 1, n), lambda i: (i, 0, 0))),
        out_shape=(jax.ShapeDtypeStruct((bsz, n, n), dt),
                   jax.ShapeDtypeStruct((bsz, 1, n), jnp.int32)),
        compiler_params=_CompilerParams(
            vmem_limit_bytes=vmem.pallas_call_limit_bytes()),
        interpret=_interpret(),
    )(at.astype(dt))
    return out, piv[:, 0, :]
