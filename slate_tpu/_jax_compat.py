"""Version-compat shims for the jax APIs the distributed stack uses.

The SPMD kernels target current jax (public ``jax.shard_map`` and the
vma "varying" type system with ``lax.pcast``/``jax.typeof``).  Older
jax (< 0.5) ships shard_map under ``jax.experimental`` and has no
varying-axes bookkeeping at all; there the shims degrade gracefully:

* :data:`shard_map` resolves to whichever implementation exists.  On
  the experimental version ``check_rep=False`` is forced — the old
  replication checker predates the psum/pmax-derived replication
  patterns several kernels rely on (e.g. the pgetrf pivot vector) and
  rejects valid programs.
* :func:`pvary` is ``lax.pcast(..., to="varying")`` where the vma
  system exists and the identity otherwise (with no varying types
  there is nothing to satisfy).
* :func:`varying_axes` reports a value's varying-axes set (always
  empty on old jax), for carries that must match a loop input's type.
"""

from __future__ import annotations

import jax
from jax import lax

try:                                    # jax >= 0.6: public API
    from jax import shard_map           # type: ignore[attr-defined]
except ImportError:                     # jax 0.4.x: experimental module
    from functools import partial as _partial

    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f=None, /, **kw):
        kw.setdefault("check_rep", False)
        if f is None:
            return _partial(_shard_map_exp, **kw)
        return _shard_map_exp(f, **kw)


def enable_x64(enabled: bool):
    """Context manager forcing the x64 mode flag: ``jax.enable_x64``
    where it exists (jax >= 0.5), the ``jax.experimental``
    enable/disable pair on older jax."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(enabled)
    if enabled:
        from jax.experimental import enable_x64 as _ctx
    else:
        from jax.experimental import disable_x64 as _ctx
    return _ctx()


_pallas_interpret_patched = [False]


def ensure_pallas_complex_interpret() -> None:
    """jax 0.4.x's Pallas interpret mode cannot initialize COMPLEX
    scratch buffers: ``primitives.uninitialized_value`` has no complex
    branch and then dereferences a ``semaphore_dtype`` attribute its
    own core module no longer defines (AttributeError).  The c128
    wavefront-chase parity path (CPU CI) allocates complex VMEM
    scratch, so wrap the function once with a complex-aware shim; on
    jax versions whose implementation already handles complex the shim
    never reaches the fallback."""
    if _pallas_interpret_patched[0]:
        return
    _pallas_interpret_patched[0] = True
    try:
        import jax.numpy as jnp
        from jax._src.pallas import primitives as _pl_primitives

        _orig = _pl_primitives.uninitialized_value

        def _uninitialized_value(shape, dtype):
            try:
                return _orig(shape, dtype)
            except (AttributeError, NotImplementedError):
                if jnp.issubdtype(dtype, jnp.complexfloating):
                    return jnp.full(shape, jnp.nan * (1 + 1j), dtype)
                raise

        _pl_primitives.uninitialized_value = _uninitialized_value
    except Exception:       # pragma: no cover - private-API drift
        pass


def pvary(x, axes):
    """``lax.pcast(x, axes, to="varying")`` on jax with the vma type
    system; identity on older jax."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, tuple(axes), to="varying")
    return x


def varying_axes(x):
    """The value's varying-axes set (empty tuple on older jax)."""
    t = jax.typeof(x) if hasattr(jax, "typeof") else None
    return tuple(getattr(t, "vma", ()) or ())
