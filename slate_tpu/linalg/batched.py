"""Batched many-problem drivers: ``potrf / getrf / gesv / posv / geqrf
/ gels`` over a LEADING BATCH DIM — the serving workload (ROADMAP item
1).

The production scenario for "millions of users" is not one n=65536
factorization — it is thousands of small/medium independent solves per
second (per-user covariance, least-squares, whitening).  Looping the
single-problem drivers pays per-problem dispatch latency and HBM round
trips; these drivers own the whole batch per launch, two ways:

* ``"vmapped"`` — the composed candidate: ``jax.vmap`` over the fused
  single-problem XLA kernel (``lax.linalg.cholesky`` / ``lu`` / batched
  Householder QR).  XLA's native batching; bitwise-identical to a loop
  of the same composed function (regression-tested).
* ``"grid"`` — the grid-batched Pallas candidate (BLASX: own many
  problems per launch): ONE ``pallas_call`` whose grid iterates batch
  blocks of ``bt`` problems, each block VMEM-resident and factored to
  completion in-kernel (:func:`slate_tpu.ops.pallas_kernels.
  potrf_batched` / ``getrf_batched``).  ``bt`` (problems per launch
  step) comes from the shared VMEM budget helper
  (:func:`slate_tpu.ops.vmem.batch_per_launch`) — the same arithmetic
  the fused single-problem gates use, extended with B-per-launch.

The two arbitrate through the new autotune sites ``batched_potrf`` /
``batched_lu`` / ``batched_qr`` whose keys pow2-BUCKET both the batch
size and n (one probe serves a bucket — a probe per exact shape is too
slow when the serving layer produces many buckets; Design-in-Tiles'
decision-table argument).  ``SLATE_TPU_AUTOTUNE_FORCE=batched_potrf=
grid`` pins either way, including in interpret mode (CPU CI).

The async serving front door over these drivers lives in
:mod:`slate_tpu.serve`.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..options import Options
from ..perf import metrics
from ..perf.metrics import instrument_driver

__all__ = [
    "potrf_batched", "potrs_batched", "posv_batched",
    "getrf_batched", "getrs_batched", "gesv_batched",
    "geqrf_batched", "gels_batched", "heev_batched",
]


def _check_batched(a, name: str, square: bool = True):
    av = jnp.asarray(a)
    if av.ndim != 3:
        from ..exceptions import SlateError
        raise SlateError(f"{name} requires a (batch, m, n) operand, "
                         f"got shape {av.shape}")
    if square and av.shape[-1] != av.shape[-2]:
        from ..exceptions import SlateError
        raise SlateError(f"{name} requires square problems, "
                         f"got shape {av.shape}")
    return av


def _rhs_3d(b, bsz: int):
    """Normalize a batched right-hand side to (B, n, k); returns
    ``(bv, squeeze)``."""
    bv = jnp.asarray(b)
    if bv.ndim == 2 and bv.shape[0] == bsz:
        return bv[:, :, None], True
    if bv.ndim != 3:
        from ..exceptions import SlateError
        raise SlateError(f"batched rhs must be (batch, n) or "
                         f"(batch, n, k), got shape {bv.shape}")
    return bv, False


# ---------------------------------------------------------------------------
# Backend implementations (the autotune candidates; probes call these
# directly so a probe can never recurse into the dispatching driver)
# ---------------------------------------------------------------------------

def _potrf_single_composed(x):
    """The single-problem composed function the vmapped backend vmaps —
    also the loop body of the bitwise-parity regression test."""
    return jnp.tril(lax.linalg.cholesky(x))


def _potrf_vmapped(a):
    return jax.vmap(_potrf_single_composed)(a)


def _getrf_single_composed(x):
    lu, _, perm = lax.linalg.lu(x)
    return lu, perm


def _getrf_vmapped(a):
    return jax.vmap(_getrf_single_composed)(a)


def _geqrf_single_composed(x):
    h, tau = jnp.linalg.qr(x, mode="raw")
    return jnp.swapaxes(h, -1, -2), tau


def _geqrf_vmapped(a):
    # jnp.linalg.qr batches natively; vmap keeps loop-bitwise parity
    return jax.vmap(_geqrf_single_composed)(a)


def _grid_bt(bsz: int, n: int, itemsize: int = 4) -> int:
    """Problems per grid step for the batched Pallas kernels: the
    shared VMEM budget solved for B-per-launch (in + out slabs + one
    problem of working values per resident problem), then snapped down
    to a divisor of the batch size (the grid must tile the batch
    exactly)."""
    from ..ops import vmem

    per_problem = 3 * n * n * itemsize
    bt = vmem.batch_per_launch(per_problem, cap=bsz)
    if bt < 1:
        return 0
    while bsz % bt:
        bt -= 1
    return bt


def _grid_eligible(bsz: int, n: int, m: int, dtype) -> bool:
    """Shape/VMEM eligibility of the grid-batched Pallas kernels:
    square problems on the in-kernel ib=32 block grid whose per-launch
    working set fits the shared VMEM budget; f32 on TPU (any float in
    interpret mode).  Whether an eligible shape actually takes the grid
    path is the ``batched_*`` autotune decision."""
    from .. import config

    if config.use_pallas_mode() == "off":
        return False
    if m != n or n < 32 or n % 32 != 0 or bsz < 1:
        return False
    dt = jnp.dtype(dtype)
    if not jnp.issubdtype(dt, jnp.floating):
        return False
    if jax.default_backend() == "tpu" and dt != jnp.float32:
        return False
    return _grid_bt(bsz, n, max(4, dt.itemsize)) >= 1


def _potrf_grid(a):
    from ..perf.autotune import kernel

    bsz, n, _ = a.shape
    bt = _grid_bt(bsz, n, max(4, a.dtype.itemsize))
    return kernel("potrf_batched")(a, bt=bt).astype(a.dtype)


def _getrf_grid(a):
    from ..perf.autotune import kernel

    bsz, n, _ = a.shape
    bt = _grid_bt(bsz, n, max(4, a.dtype.itemsize))
    at = jnp.swapaxes(a, -1, -2)
    out, piv = kernel("getrf_batched")(at, bt=bt)
    # packed rows live in the pivot lanes: gather each problem's pivot
    # columns, transpose back to row-major packed LU
    idx = jnp.broadcast_to(piv[:, None, :], out.shape)
    lu_t = jnp.take_along_axis(out, idx, axis=2)
    return jnp.swapaxes(lu_t, -1, -2).astype(a.dtype), piv


# ---------------------------------------------------------------------------
# Residual probes (shared with the autotune accuracy guards)
# ---------------------------------------------------------------------------

def _scaled(num, spd, x, n):
    import numpy as np

    eps = float(np.finfo(np.dtype(spd.dtype.name)).eps)
    den = (jnp.linalg.norm(spd.astype(jnp.float32), axis=(-2, -1))
           * float(jnp.linalg.norm(x.astype(jnp.float32))) * eps * n)
    return float(jnp.max(num / jnp.maximum(den, 1e-300)))


def batched_factor_resid_potrf(spd, l) -> float:
    """Max scaled matvec residual ‖L(Lᵀx) − Ax‖ over the batch (the
    reference tester's criterion, O(n²) per problem)."""
    if not bool(jnp.all(jnp.isfinite(l))):
        return float("inf")
    n = spd.shape[-1]
    x = jax.random.normal(jax.random.PRNGKey(23), (n,), spd.dtype)
    lt = jnp.tril(l)
    r = jnp.linalg.norm(
        (jnp.einsum("bij,bj->bi", lt,
                    jnp.einsum("bji,j->bi", lt, x)) -
         jnp.einsum("bij,j->bi", spd, x)).astype(jnp.float32), axis=-1)
    return _scaled(r, spd, x, n)


def batched_factor_resid_lu(a, out) -> float:
    """Max scaled matvec residual of L·(U·x) = A[perm]·x over the
    batch."""
    lu, perm = out
    if not bool(jnp.all(jnp.isfinite(lu))):
        return float("inf")
    n = a.shape[-1]
    x = jax.random.normal(jax.random.PRNGKey(24), (n,), a.dtype)
    y = jnp.einsum("bij,j->bi", jnp.triu(lu), x)
    z = jnp.einsum("bij,bj->bi", jnp.tril(lu, -1), y) + y
    ap = jnp.take_along_axis(a, perm[:, :, None], axis=1)
    r = jnp.linalg.norm(
        (z - jnp.einsum("bij,j->bi", ap, x)).astype(jnp.float32), axis=-1)
    return _scaled(r, a, x, n)


# ---------------------------------------------------------------------------
# Drivers
# ---------------------------------------------------------------------------

@instrument_driver("potrf_batched")
def potrf_batched(a, opts: Optional[Options] = None):
    """Batched Cholesky: ``a`` (B, n, n) SPD (full arrays) → the (B, n,
    n) lower factors.  Backend per pow2-bucketed (B, n, dtype) key via
    the ``batched_potrf`` autotune site."""

    av = _check_batched(a, "potrf_batched")
    bsz, n = av.shape[0], av.shape[-1]
    metrics.inc("batched.problems", float(bsz))
    from ..method import select_backend
    choice = select_backend(
        "batched_potrf", b=bsz, n=n, dtype=av.dtype,
        eligible=_grid_eligible(bsz, n, av.shape[-2], av.dtype))
    if choice == "grid":
        return _potrf_grid(av)
    return _potrf_vmapped(av)


def potrs_batched(l, b):
    """Batched triangular solve pair from the lower Cholesky factors:
    solve A·X = B given L (B, n, n).  ``b`` is (B, n) or (B, n, k)."""
    lv = _check_batched(l, "potrs_batched")
    bv, squeeze = _rhs_3d(b, lv.shape[0])
    y = lax.linalg.triangular_solve(lv, bv, left_side=True, lower=True)
    x = lax.linalg.triangular_solve(lv, y, left_side=True, lower=True,
                                    transpose_a=True)
    return x[:, :, 0] if squeeze else x


@instrument_driver("posv_batched")
def posv_batched(a, b, opts: Optional[Options] = None):
    """Batched factor + solve for SPD systems — returns ``(L, X)``."""
    l = potrf_batched(a, opts)
    return l, potrs_batched(l, b)


@instrument_driver("getrf_batched")
def getrf_batched(a, opts: Optional[Options] = None
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Batched partial-pivot LU: ``a`` (B, n, n) → ``(LU, perm)`` with
    ``a[i][perm[i]] = L·U`` per problem, LU packed LAPACK-style —
    :func:`slate_tpu.linalg.lu.getrf`'s contract with a leading batch
    dim.  Backend via the ``batched_lu`` site."""

    av = _check_batched(a, "getrf_batched")
    bsz, n = av.shape[0], av.shape[-1]
    metrics.inc("batched.problems", float(bsz))
    from ..method import select_backend
    choice = select_backend(
        "batched_lu", b=bsz, n=n, dtype=av.dtype,
        eligible=_grid_eligible(bsz, n, av.shape[-2], av.dtype))
    if choice == "grid":
        return _getrf_grid(av)
    return _getrf_vmapped(av)


def getrs_batched(lu, perm, b):
    """Batched solve from the LU factors: permute-gather then two
    batched triangular sweeps."""
    luv = _check_batched(lu, "getrs_batched")
    bv, squeeze = _rhs_3d(b, luv.shape[0])
    bp = jnp.take_along_axis(bv, perm[:, :, None], axis=1)
    y = lax.linalg.triangular_solve(luv, bp, left_side=True, lower=True,
                                    unit_diagonal=True)
    x = lax.linalg.triangular_solve(luv, y, left_side=True, lower=False)
    return x[:, :, 0] if squeeze else x


@instrument_driver("gesv_batched")
def gesv_batched(a, b, opts: Optional[Options] = None):
    """Batched factor + solve — returns ``(LU, perm, X)``."""
    lu, perm = getrf_batched(a, opts)
    return lu, perm, getrs_batched(lu, perm, b)


@instrument_driver("geqrf_batched")
def geqrf_batched(a, opts: Optional[Options] = None):
    """Batched QR: ``a`` (B, m, n) → ``(packed, taus)`` (Householder
    factors packed LAPACK-style per problem).  Registered through the
    ``batched_qr`` site (single vmapped candidate today)."""

    av = _check_batched(a, "geqrf_batched", square=False)
    bsz, m, n = av.shape
    metrics.inc("batched.problems", float(bsz))
    from ..method import select_backend
    select_backend("batched_qr", b=bsz, m=m, n=n, dtype=av.dtype)
    return _geqrf_vmapped(av)


@instrument_driver("gels_batched")
def gels_batched(a, b, opts: Optional[Options] = None):
    """Batched least squares min ‖A·X − B‖₂ for tall problems (m ≥ n):
    batched Householder QR + one batched triangular solve.  ``b`` is
    (B, m) or (B, m, k); returns X (B, n[, k])."""

    av = _check_batched(a, "gels_batched", square=False)
    bsz, m, n = av.shape
    if m < n:
        from ..exceptions import SlateError
        raise SlateError("gels_batched requires m >= n per problem "
                         f"(got {av.shape}); use gels per problem for "
                         "minimum-norm underdetermined solves")
    metrics.inc("batched.problems", float(bsz))
    bv, squeeze = _rhs_3d(b, bsz)
    from ..method import select_backend
    select_backend("batched_qr", b=bsz, m=m, n=n, dtype=av.dtype)
    q, r = jnp.linalg.qr(av, mode="reduced")
    qtb = jnp.matmul(jnp.swapaxes(q, -1, -2), bv)
    x = lax.linalg.triangular_solve(r, qtb, left_side=True, lower=False)
    return x[:, :, 0] if squeeze else x


@instrument_driver("heev_batched")
def heev_batched(a, opts: Optional[Options] = None
                 ) -> Tuple[jax.Array, jax.Array]:
    """Batched Hermitian eigensolver: ``a`` (B, n, n) → ``(w, z)`` with
    per-problem eigenvalues ascending (B, n) and eigenvectors in the
    columns of ``z`` (B, n, n) — the batched-drivers gap ROADMAP item 3
    names, closing the served surface (ISSUE 20).  Registered through
    the ``batched_heev`` site (single vmapped candidate — XLA's
    natively batched ``eigh`` — today, like ``batched_qr``), so the
    serving layer's warm start can enumerate its buckets and a
    grid-batched spectral candidate can arbitrate here later."""

    av = _check_batched(a, "heev_batched")
    bsz, n, _ = av.shape
    metrics.inc("batched.problems", float(bsz))
    from ..method import select_backend
    select_backend("batched_heev", b=bsz, n=n, dtype=av.dtype)
    w, z = jnp.linalg.eigh(av)
    return w, z
