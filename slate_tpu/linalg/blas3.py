"""Parallel BLAS-3 drivers.

TPU-native re-design of the reference drivers ``src/gemm.cc`` (method
dispatch ``:72-86``), ``src/symm.cc``/``hemm.cc``, ``src/syrk.cc`` /
``herk.cc`` / ``syr2k.cc`` / ``her2k.cc``, ``src/trmm.cc``, ``src/trsm.cc``
(+ work loops ``src/work/work_trsm.cc``, ``work_trmm.cc``).

Semantics follow the reference/BLAS: ``C = α·op(A)·op(B) + β·C`` etc.,
with matrices carrying their op/uplo/diag; functions return the updated
matrix (functional style) rather than writing in place.

The reference's method selectors (``method.hh:77-126`` gemmA vs gemmC —
*where* the reduction happens relative to data layout) govern collective
placement only in the distributed path (``slate_tpu.parallel``); on a
single chip XLA picks the contraction schedule, so ``MethodGemm`` is
accepted and recorded but does not change the emitted program.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from .. import config
from ..enums import Diag, Op, Side, Uplo
from ..matrix import (BaseMatrix, BaseTrapezoidMatrix, HermitianMatrix,
                      Matrix, SymmetricMatrix, TriangularMatrix, as_array)
from ..options import Options, get_option
from ..ops import blocks
from ..ops.blocks import matmul
from ..perf.metrics import instrument_driver


def _arr(x):
    return as_array(x)


def _uplo_of(a, default=Uplo.Lower):
    if isinstance(a, BaseTrapezoidMatrix):
        return a.logical_uplo
    return default


def _diag_of(a, default=Diag.NonUnit):
    return getattr(a, "diag", default)


def _wrap_like(template, data):
    if isinstance(template, BaseMatrix):
        out = template._like(data)
        out.op = Op.NoTrans
        return out
    return data


def _nb(a, opts):
    """Blocking size: per-call option → matrix nb → SLATE_TPU_NB default."""
    nb = get_option(opts, "block_size", None)
    if nb is None:
        nb = getattr(a, "nb", None) or config.default_block_size
    return int(nb)


@instrument_driver("gemm")
def gemm(alpha, a, b, beta, c, opts: Optional[Options] = None):
    """C ← α·op(A)·op(B) + β·C — reference ``slate::gemm`` (``src/gemm.cc``).

    On a single chip this is one fused XLA dot (the MXU hot loop); on a
    mesh, arrays sharded block-cyclic make XLA insert the SUMMA-style
    collectives that ``listBcastMT`` performed explicitly in the reference
    (``src/gemm.cc`` work loop); the hand-scheduled variant lives in
    ``slate_tpu.parallel.dist_blas3``.
    """

    av, bv, cv = _arr(a), _arr(b), _arr(c)
    out = alpha * matmul(av, bv) + beta * cv
    return _wrap_like(c, out)


def symm(side: Side, alpha, a, b, beta, c, opts: Optional[Options] = None):
    """C ← α·A·B + β·C with A symmetric (stored triangle), reference
    ``slate::symm`` (``src/symm.cc``)."""

    return _symm_hemm(side, alpha, a, b, beta, c, conj=False)


def hemm(side: Side, alpha, a, b, beta, c, opts: Optional[Options] = None):
    """Hermitian variant, reference ``slate::hemm`` (``src/hemm.cc``)."""

    return _symm_hemm(side, alpha, a, b, beta, c, conj=True)


def _symm_hemm(side, alpha, a, b, beta, c, conj):
    from ..ops.tile_ops import hermitize, symmetrize
    # logical_uplo pairs with the op-applied array: after a transpose view,
    # the valid triangle of .array sits in the flipped uplo position.
    uplo = _uplo_of(a)
    raw = a.array if isinstance(a, BaseMatrix) else jnp.asarray(a)
    full = hermitize(uplo, raw) if conj else symmetrize(uplo, raw)
    bv, cv = _arr(b), _arr(c)
    if side is Side.Left:
        out = alpha * matmul(full, bv) + beta * cv
    else:
        out = alpha * matmul(bv, full) + beta * cv
    return _wrap_like(c, out)


def _require_notrans_c(c):
    """Rank-k/2k updates write C in place of its storage; an op-tagged C
    would make 'preserve the unstored triangle' ambiguous — reject like
    the reference's typed API does by construction."""
    if isinstance(c, BaseMatrix) and c.op is not Op.NoTrans:
        from ..exceptions import SlateError
        raise SlateError("C of a rank-k/2k update must be a NoTrans view")


def _rank_k(alpha, a, beta, c, conj):
    """Shared syrk/herk core with triangle-restore semantics."""
    _require_notrans_c(c)
    uplo = _uplo_of(c)
    av = _arr(a)
    cv = c.data if isinstance(c, BaseMatrix) else jnp.asarray(c)
    nb = getattr(c, "nb", None) or config.default_block_size
    if conj:
        alpha = jnp.real(jnp.asarray(alpha))
        beta = jnp.real(jnp.asarray(beta))
    new = blocks.herk_rec(uplo, alpha, av, beta, cv, int(nb), conj=conj)
    # only the stored triangle is defined; keep the other triangle as-is
    out = jnp.where(_tri_mask(cv.shape[-1], uplo, cv.dtype), new, cv)
    return _wrap_like(c, out)


def _tri_mask(n, uplo, dtype):
    i = jnp.arange(n)[:, None]
    j = jnp.arange(n)[None, :]
    return (i >= j) if uplo is Uplo.Lower else (i <= j)


def syrk(alpha, a, beta, c, opts: Optional[Options] = None):
    """C ← α·op(A)·op(A)ᵀ + β·C on C's triangle, reference ``src/syrk.cc``."""
    return _rank_k(alpha, a, beta, c, conj=False)


def herk(alpha, a, beta, c, opts: Optional[Options] = None):
    """C ← α·op(A)·op(A)ᴴ + β·C (α, β real), reference ``src/herk.cc``."""
    return _rank_k(alpha, a, beta, c, conj=True)


def _rank_2k(alpha, a, b, beta, c, conj):
    _require_notrans_c(c)
    uplo = _uplo_of(c)
    av, bv = _arr(a), _arr(b)
    cv = c.data if isinstance(c, BaseMatrix) else jnp.asarray(c)
    nb = getattr(c, "nb", None) or config.default_block_size
    if conj:
        beta = jnp.real(jnp.asarray(beta))
    new = blocks.her2k_rec(uplo, alpha, av, bv, beta, cv, int(nb), conj=conj)
    out = jnp.where(_tri_mask(cv.shape[-1], uplo, cv.dtype), new, cv)
    return _wrap_like(c, out)


def syr2k(alpha, a, b, beta, c, opts: Optional[Options] = None):
    """Reference ``src/syr2k.cc``."""
    return _rank_2k(alpha, a, b, beta, c, conj=False)


def her2k(alpha, a, b, beta, c, opts: Optional[Options] = None):
    """Reference ``src/her2k.cc``."""
    return _rank_2k(alpha, a, b, beta, c, conj=True)


def trmm(side: Side, alpha, a, b, opts: Optional[Options] = None):
    """B ← α·op(A)·B or α·B·op(A), A triangular — reference ``src/trmm.cc``
    + ``src/work/work_trmm.cc:428``."""

    uplo = _uplo_of(a)
    diag = _diag_of(a)
    av, bv = _arr(a), _arr(b)
    nb = _nb(a, opts)
    out = alpha * blocks.trmm_rec(side, uplo, diag, av, bv, nb)
    return _wrap_like(b, out)


@instrument_driver("trsm")
def trsm(side: Side, alpha, a, b, opts: Optional[Options] = None):
    """Solve op(A)·X = α·B or X·op(A) = α·B — reference ``src/trsm.cc``
    (work loop ``src/work/work_trsm.cc:395``; the trsmA data-placement
    variant ``src/trsmA.cc`` is a distributed-path concern, see
    ``parallel.dist_blas3``)."""

    uplo = _uplo_of(a)
    diag = _diag_of(a)
    av, bv = _arr(a), _arr(b)
    nb = _nb(a, opts)
    out = blocks.trsm_rec(side, uplo, diag, av, alpha * bv, nb)
    return _wrap_like(b, out)


# ---------------------------------------------------------------------------
# Data-placement method variants.  The reference exposes gemmA/gemmC,
# hemmA/hemmC and trsmA/trsmB as separate drivers that differ only in
# *which operand stays resident* while the others move
# (``src/gemmA.cc``/``src/gemmC.cc``, method dispatch ``src/gemm.cc:72-86``,
# ``method.hh:25-126``).  Under XLA the compiler owns operand residency,
# so the variants share one lowering; the names are kept so reference
# call sites port unchanged, and the distributed path makes the real
# stationary-operand choice in ``parallel.dist_blas3.pgemm_auto``.
# ---------------------------------------------------------------------------

def gemmA(alpha, a, b, beta, c, opts: Optional[Options] = None):
    """gemm, A-stationary method — reference ``slate::gemmA``
    (``src/gemmA.cc``, picked by ``MethodGemm`` when B is narrow)."""
    return gemm(alpha, a, b, beta, c, opts)


def gemmC(alpha, a, b, beta, c, opts: Optional[Options] = None):
    """gemm, C-stationary method — reference ``slate::gemmC``
    (``src/gemmC.cc``, the default method)."""
    return gemm(alpha, a, b, beta, c, opts)


def hemmA(side: Side, alpha, a, b, beta, c, opts: Optional[Options] = None):
    """hemm, A-stationary method — reference ``slate::hemmA``
    (``src/hemmA.cc``)."""
    return hemm(side, alpha, a, b, beta, c, opts)


def hemmC(side: Side, alpha, a, b, beta, c, opts: Optional[Options] = None):
    """hemm, C-stationary method — reference ``slate::hemmC``."""
    return hemm(side, alpha, a, b, beta, c, opts)


def trsmA(side: Side, alpha, a, b, opts: Optional[Options] = None):
    """trsm, A-stationary method — reference ``slate::trsmA``
    (``src/trsmA.cc``, 589-line work variant)."""
    return trsm(side, alpha, a, b, opts)


def trsmB(side: Side, alpha, a, b, opts: Optional[Options] = None):
    """trsm, B-stationary method — reference ``slate::trsmB`` (the
    default; ``src/trsm.cc``)."""
    return trsm(side, alpha, a, b, opts)
