"""Out-of-core getrf/potrf — right-looking factorizations over the
host-DRAM tile pool (ISSUE 17).

The drivers here factor a matrix whose fp32 footprint exceeds the HBM
window: the matrix lives in host DRAM as an (nb, nb)-tile grid
(:class:`slate_tpu.ops.tilepool.TilePool`) and each right-looking step
assembles its panel and trailing strips from the pool's bounded
device-resident window — the existing in-core kernels do every flop
(the panel factors through ``linalg.lu._getrf_partial_impl``, trailing
updates through ``ops.blocks.matmul``), the pool only decides WHERE the
operands live and prefetches the next strip's tiles under the current
step's MXU work.

Residency never changes arithmetic: the same jnp operations run in the
same order whatever the window size, so a forced 2-tile window and an
all-resident window produce bitwise-identical factors (the parity pin
in tests/test_tilepool.py) — an all-resident pool IS the in-core
execution of this driver.

Checkpoint composition (PR 14): with ``SLATE_TPU_CKPT_EVERY_STEPS`` set
the step loop runs under
:func:`slate_tpu.resilience.checkpoint.run_checkpointed` — the pool is
flushed at every window boundary so the snapshot is the exact host
image, and an injected ``device_loss`` rewinds to the last boundary and
replays bitwise (multi-hour n=131072 runs restart mid-factorization
instead of from zero).

Dispatch: the ``ooc`` autotune site
(:func:`slate_tpu.perf.autotune.choose_ooc`) arbitrates ``"pool"`` vs
``"incore"`` per (n, nb, dtype) exactly like every other backend
ladder; ``SLATE_TPU_OOC`` is the tri-state force knob.  Importing this
module never imports the tile pool — ``ops.tilepool`` loads only when
a driver actually runs (the inert-at-import pin).
"""

from __future__ import annotations

import os

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..perf import metrics

__all__ = ["getrf_ooc", "potrf_ooc", "ooc_nb", "pool_eligible", "choose"]

#: the pool pays off only when the tile grid is at least this many
#: tiles on a side (a 1×1 grid is definitionally in-core)
_OOC_MIN_GRID = 2


def ooc_nb() -> int:
    """Out-of-core tile edge (``SLATE_TPU_OOC_NB``, default 512 — the
    fused step kernels' panel width).  Read here, NOT from
    ``ops.tilepool``, so the dispatch gate in linalg/ can run without
    importing the pool (the inert-at-import contract)."""
    raw = os.environ.get("SLATE_TPU_OOC_NB", "").strip()
    try:
        return max(8, int(raw)) if raw else 512
    except ValueError:
        return 512


def _is_tracer(x) -> bool:
    try:
        import jax

        return isinstance(x, jax.core.Tracer)
    except Exception:                      # pragma: no cover
        return False


def pool_eligible(av) -> bool:
    """Shape/dtype ELIGIBILITY of the out-of-core drivers: CONCRETE
    (the pool is host-side/eager-only, like the ABFT layer — a traced
    operand keeps the in-core path whatever the knobs say) real square
    f32/f64 matrices on a uniform (nb, nb) tile grid of at least
    2×2 tiles.  Whether an eligible shape actually takes the pool is
    the ``ooc`` autotune site's decision (forced with ``SLATE_TPU_OOC``
    or ``SLATE_TPU_AUTOTUNE_FORCE=ooc=pool``) — no raw env read decides
    dispatch here."""
    if _is_tracer(av) or getattr(av, "ndim", 0) != 2:
        return False
    m, n = int(av.shape[0]), int(av.shape[1])
    if m != n or av.dtype not in (jnp.float32, jnp.float64):
        return False
    t = ooc_nb()
    return n % t == 0 and n // t >= _OOC_MIN_GRID


def choose(av) -> str:
    """The ``ooc`` site decision for one operand — ONE derivation
    shared by the getrf and potrf dispatches (mirrors
    ``linalg.lu._choose_lu_driver``)."""
    from ..method import select_backend

    n = int(av.shape[-1]) if getattr(av, "ndim", 0) == 2 else 0
    return select_backend("ooc", n=n, nb=ooc_nb(), dtype=av.dtype,
                          eligible=pool_eligible(av))


def _ckpt_every():
    from ..resilience import checkpoint

    return checkpoint, checkpoint.every_steps()


# ---------------------------------------------------------------------------
# getrf
# ---------------------------------------------------------------------------

def _getrf_steps(pool, perm: np.ndarray, k0: int, k1: int) -> np.ndarray:
    """Run right-looking LU steps ``k ∈ [k0, k1)`` on the pool in
    place; returns the updated global row permutation.  Per step: the
    block-column panel is assembled from resident tiles and factored by
    the in-core PartialPiv driver, then every other block column's
    rows-below-k strip is assembled, row-swapped (laswp on BOTH sides,
    the LAPACK contract), triangular-solved and rank-nb updated — all
    with the same jnp ops at every window size."""
    from .lu import _getrf_incore
    from ..ops import blocks

    g, nb = pool.gi, pool.nb
    for k in range(k0, k1):
        rows = list(range(k, g))
        pool.prefetch((i, k) for i in rows)
        with metrics.step_timer("getrf", "panel"):
            panel = jnp.concatenate([pool.get(i, k) for i in rows],
                                    axis=0)
            lu_p, piv = _getrf_incore(panel, nb)
        for t, i in enumerate(rows):
            pool.put(i, k, lu_p[t * nb:(t + 1) * nb])
        piv_np = np.asarray(piv)
        perm = perm.copy()
        perm[k * nb:] = perm[k * nb:][piv_np]
        l11 = lu_p[:nb]
        l21 = lu_p[nb:]
        # trailing columns first: their tiles are the next step's
        # working set, so they end the step most-recently-used
        for j in [jj for jj in range(k + 1, g)] + list(range(k)):
            pool.prefetch((i, j) for i in rows)
            strip = jnp.concatenate([pool.get(i, j) for i in rows],
                                    axis=0)
            with metrics.step_timer("getrf", "pivot"):
                strip = strip[piv]
            if j > k:
                with metrics.step_timer("getrf", "trsm"):
                    u = lax.linalg.triangular_solve(
                        l11, strip[:nb], left_side=True, lower=True,
                        unit_diagonal=True)
                if strip.shape[0] > nb:
                    with metrics.step_timer("getrf", "update"):
                        rest = strip[nb:] - blocks.matmul(l21, u)
                    strip = jnp.concatenate([u, rest], axis=0)
                else:
                    strip = u
            for t, i in enumerate(rows):
                pool.put(i, j, strip[t * nb:(t + 1) * nb])
    return perm


def getrf_ooc(a, nb: int | None = None, capacity: int | None = None,
              depth: int | None = None, to_device: bool = True):
    """Out-of-core partial-pivot LU over the host-DRAM tile pool.
    Same ``(lu, perm)`` contract as the in-core drivers
    (``A[perm] = L·U``); ``capacity``/``depth`` override the
    ``SLATE_TPU_OOC_WINDOW_TILES`` / ``_PREFETCH_DEPTH`` knobs (the
    tests force a 2–4-tile window through them).  ``to_device=False``
    returns host ndarrays — the only possible form at the sizes this
    driver exists for, where the factor itself exceeds HBM."""
    from ..ops.tilepool import TilePool

    a_np = np.asarray(a)
    nb = int(nb) if nb else ooc_nb()
    m, n = a_np.shape
    if m != n or n % nb:
        raise ValueError(f"getrf_ooc needs a square matrix on a uniform "
                         f"{nb}-tile grid, got {a_np.shape}")
    g = n // nb
    ckpt, every = _ckpt_every()
    if every > 0 and g > 1:
        def run_chunk(carry, k0, k1):
            host, perm = carry if carry is not None \
                else (a_np, np.arange(m))
            pool = TilePool(host, nb, capacity, depth, op="getrf")
            perm = _getrf_steps(pool, np.asarray(perm), k0, k1)
            return (pool.array(), perm)

        host, perm = ckpt.run_checkpointed(g, every, run_chunk,
                                           label="getrf_ooc")
    else:
        pool = TilePool(a_np, nb, capacity, depth, op="getrf")
        perm = _getrf_steps(pool, np.arange(m), 0, g)
        host = pool.array()
    if not to_device:
        return host, np.asarray(perm)
    return jnp.asarray(host), jnp.asarray(perm)


# ---------------------------------------------------------------------------
# potrf
# ---------------------------------------------------------------------------

def _potrf_steps(pool, k0: int, k1: int) -> None:
    """Right-looking tiled Cholesky steps ``k ∈ [k0, k1)``: diagonal
    factor, block-column trsm, symmetric rank-nb trailing update on the
    lower tiles only — per-tile gemms with a full (un-split) nb
    contraction, so tiling changes nothing bitwise."""
    from ..ops import blocks

    g = pool.gi
    for k in range(k0, k1):
        with metrics.step_timer("potrf", "panel"):
            lkk = jnp.tril(lax.linalg.cholesky(pool.get(k, k)))
        pool.put(k, k, lkk)
        below = list(range(k + 1, g))
        pool.prefetch((i, k) for i in below)
        for i in below:
            with metrics.step_timer("potrf", "trsm"):
                lik = lax.linalg.triangular_solve(
                    lkk, pool.get(i, k), left_side=False, lower=True,
                    transpose_a=True)
            pool.put(i, k, lik)
        for j in below:
            ljk_t = pool.get(j, k).T
            pool.prefetch((i, j) for i in range(j, g))
            for i in range(j, g):
                with metrics.step_timer("potrf", "update"):
                    upd = pool.get(i, j) - blocks.matmul(pool.get(i, k),
                                                         ljk_t)
                pool.put(i, j, upd)


def potrf_ooc(a, nb: int | None = None, capacity: int | None = None,
              depth: int | None = None, to_device: bool = True):
    """Out-of-core Cholesky over the host-DRAM tile pool: returns the
    full lower-triangular factor array (the ``_potrf_dispatch``
    contract — ``linalg.cholesky.potrf`` wraps it).
    ``to_device=False`` returns the host ndarray for factors that
    exceed HBM."""
    from ..ops.tilepool import TilePool

    a_np = np.asarray(a)
    nb = int(nb) if nb else ooc_nb()
    n = a_np.shape[-1]
    if a_np.ndim != 2 or a_np.shape[0] != n or n % nb:
        raise ValueError(f"potrf_ooc needs a square matrix on a uniform "
                         f"{nb}-tile grid, got {a_np.shape}")
    g = n // nb
    ckpt, every = _ckpt_every()
    if every > 0 and g > 1:
        def run_chunk(carry, k0, k1):
            host = carry if carry is not None else a_np
            pool = TilePool(host, nb, capacity, depth, op="potrf")
            _potrf_steps(pool, k0, k1)
            return pool.array()

        host = ckpt.run_checkpointed(g, every, run_chunk,
                                     label="potrf_ooc")
    else:
        pool = TilePool(a_np, nb, capacity, depth, op="potrf")
        _potrf_steps(pool, 0, g)
        host = pool.array()
    if not to_device:
        return np.tril(host)
    return jnp.tril(jnp.asarray(host))
