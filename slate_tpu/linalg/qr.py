"""QR / LQ family: geqrf, gelqf, unmqr, unmlq, ungqr, gels, cholqr.

TPU-native re-design of the reference QR stack:

* ``src/geqrf.cc`` (485 LoC) — CAQR: blocked Householder panel
  (``internal_geqrf.cc`` + ``Tile_geqrf.hh``) + triangle-triangle tree
  reduction across ranks (``internal_ttqrt.cc``; tree apply
  ``internal_ttmqr.cc``).
* ``src/gelqf.cc`` (434), ``src/unmqr.cc`` (384) / ``src/unmlq.cc``,
  ``src/gels.cc`` (QR vs CholQR auto, ``method.hh:236``),
  ``src/gels_qr.cc`` / ``src/gels_cholqr.cc``, ``src/cholqr.cc``.

Design stance (TPU-first):

* **Compact-WY everywhere.**  The reflector block (I − V·T·Vᴴ) turns the
  panel's reflector chain into three MXU matmuls; the T factor comes
  from the closed form T⁻¹ = strict_upper(VᴴV) + diag(1/τ) — one Gram
  matmul + one log-depth triangular inverse, so neither a sequential
  column loop nor an O(k)-node recursion appears in the trace.
* The factorization recursion mirrors :func:`~slate_tpu.ops.blocks.potrf_rec`:
  each level factors the left half, applies one block reflector to the
  right half (two big matmuls — the hot loop), and recurses.  XLA's
  scheduler overlaps the next panel with the trailing tail exactly where
  the reference used OpenMP lookahead (``src/geqrf.cc:196-208``).
* The single-chip panel base case is XLA's fused ``lax.linalg.geqrf``
  (the analog of the reference's multithreaded ``Tile_geqrf.hh`` panel);
  the *distributed* tree reduction (ttqrt over mesh rows) lives in
  ``slate_tpu.parallel.dist_qr``.
* Pivots/taus convention: LAPACK-compatible — packed V below the
  diagonal (unit lower), R on/above, Q = H₀·H₁⋯H_{k−1} with
  Hᵢ = I − τᵢ·vᵢ·vᵢᴴ.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..enums import Diag, MethodGels, Op, Side, Uplo
from ..matrix import as_array
from ..options import Options, get_option
from ..ops import blocks
from ..ops.blocks import _ct, matmul, matmul_hi
from .blas3 import _nb, _wrap_like
from ..perf.metrics import instrument_driver


def _reject_complex_trans(a, op: Op):
    """LAPACK/SLATE reject plain Trans for complex unmqr/unmlq — Qᵀ is
    not expressible from the stored reflectors without extra conjugation."""
    if op is Op.Trans and jnp.iscomplexobj(a):
        from ..exceptions import SlateError
        raise SlateError("Op.Trans with a complex factor is unsupported "
                         "(use Op.ConjTrans), matching LAPACK unmqr/unmlq")


def _unit_lower(packed, k: int):
    """Extract the unit-lower-trapezoid V (m×k) from a packed QR factor."""
    m = packed.shape[0]
    return jnp.tril(packed[:, :k], -1) + jnp.eye(m, k, dtype=packed.dtype)


def larft_rec(v, tau):
    """Forward column-wise compact-WY T: H₀⋯H_{k−1} = I − V·T·Vᴴ.

    Closed-form ``larft`` (the reference builds T column-by-column inside
    ``Tile_geqrf.hh``'s panel loop): the larfg normalization guarantees
    ``Re(1/τⱼ) = ‖vⱼ‖²/2``, so orthogonality of the block reflector
    forces ``T⁻¹ = strict_upper(VᴴV) + diag(1/τ)``.  One Gram matmul and
    one log-depth triangular inverse replace both LAPACK's sequential
    column loop and the O(k) recursive-halving tree — two MXU-shaped ops
    whose trace size is independent of k (the halving tree re-traced
    ~2k nodes per distinct panel shape, dominating compile time).

    Columns with τⱼ = 0 (Hⱼ = I) get T[:, j] = 0, matching ``dlarft``.
    """

    k = v.shape[1]
    dt = v.dtype
    if k == 1:
        return tau.reshape(1, 1).astype(dt)
    s = matmul(_ct(v), v)                      # Gram matrix VᴴV
    zero = tau == 0
    safe_tau = jnp.where(zero, jnp.ones((), tau.dtype), tau)
    # a τⱼ = 0 column contributes Hⱼ = I: zero both its row in T⁻¹'s
    # strict-upper part (so the inversion propagates no cross terms
    # through it) and, below, its column of T — matching dlarft
    su = jnp.where(zero[:, None], jnp.zeros((), dt), jnp.triu(s, 1))
    tinv = su + jnp.diag(1.0 / safe_tau).astype(dt)
    t = blocks.trtri_rec(Uplo.Upper, Diag.NonUnit, tinv,
                         max(32, k // 8))
    t = jnp.triu(t)
    return jnp.where(zero[None, :], jnp.zeros((), dt), t)


def _apply_block_reflector(v, t, c, *, forward: bool, hi: bool = False):
    """C ← (I − V·T·Vᴴ)·C if forward else (I − V·Tᴴ·Vᴴ)·C — LAPACK
    ``larfb`` (Left; the Right side is handled by the callers via
    transposition identities).  ``hi`` pins the three products to
    ``Precision.HIGHEST`` for the eig/svd back-transforms."""

    mm = matmul_hi if hi else matmul
    tt = t if forward else _ct(t)
    return c - mm(v, mm(tt, mm(_ct(v), c)))


@partial(jax.jit, static_argnums=2)
def apply_reflector_chain(vts, cv, forward: bool):
    """Apply a chain of tail-aligned block reflectors under one jit (one
    device dispatch for the whole chain): each (V, T) panel spans the
    last ``V.shape[0]`` rows of C.  ``forward`` applies Q (panels
    last-to-first), else Qᴴ.  Shared by ``unmqr``-style back-transforms
    in the two-stage eig (``unmtr_he2hb``) and SVD (``unmbr_ge2tb``).

    Products are pinned to ``Precision.HIGHEST``: the back-transform's
    forward error lands on the eigen/singular vectors at full scale, and
    the chain applies n/nb panels in sequence, so at the library default
    (3-pass bf16 ``high``, ~1.3e-5 ≈ 110·ε₃₂ per product) the
    accumulated error crosses the reference tester's ≤ 3·ε·n residual
    gate on-chip once n/nb panels stack up — the round-5 ``heev``
    quick-run failure (the same algorithm at true-f32 precision passes
    with tester error 4.5e-2).  Cost: one HIGHEST-grade GEMM chain of
    ~2n³ flops total, small next to stage 1's 4n³/3 and paid only by
    eig/svd drivers — ``geqrf``/``unmqr`` keep the library precision."""

    n = cv.shape[0]
    seq = vts[::-1] if forward else vts
    for v, t in seq:
        r0 = n - v.shape[0]
        tail = _apply_block_reflector(v, t, cv[r0:], forward=forward,
                                      hi=True)
        cv = jnp.concatenate([cv[:r0], tail], axis=0)
    return cv


# ---------------------------------------------------------------------------
# Factorizations
# ---------------------------------------------------------------------------

def _panel_geqrf(a):
    """Unblocked Householder panel: returns (packed, taus).

    LAPACK ``geqrf``/``larfg`` semantics — Hⱼ = I − τⱼ·vⱼ·vⱼᴴ with
    vⱼ[j] = 1, real β, Hᴴ·x = β·e₁ — as one ``lax.fori_loop`` whose body
    is a masked rank-1 update (the analog of the reference's
    multithreaded panel kernel ``Tile_geqrf.hh``, with XLA:TPU fusing
    the reflector generation + application per column).
    """

    m, n = a.shape
    k = min(m, n)
    dt = a.dtype
    rows = jnp.arange(m)
    cols = jnp.arange(n)

    def body(j, carry):
        a, taus = carry
        col = a[:, j]
        alpha = col[j]
        tail = jnp.where(rows > j, col, 0)
        sigma = jnp.sum(jnp.abs(tail) ** 2)
        nrm = jnp.sqrt(jnp.abs(alpha) ** 2 + sigma)
        beta = jnp.where(jnp.real(alpha) >= 0, -nrm, nrm).astype(dt)
        zero_col = nrm == 0
        denom = jnp.where(zero_col, 1, alpha - beta)
        v = jnp.where(rows > j, col / denom, 0)
        v = jnp.where(rows == j, 1, v).astype(dt)
        tau = jnp.where(zero_col, 0,
                        (beta - alpha) / jnp.where(zero_col, 1, beta))
        # apply Hⱼᴴ = I − τ̄ⱼ·vⱼ·vⱼᴴ to the trailing columns
        w = jnp.conj(tau) * matmul(jnp.conj(v), a)
        w = jnp.where(cols > j, w, 0)
        a = a - v[:, None] * w[None, :]
        newcol = jnp.where(rows > j, v, col)
        newcol = jnp.where(rows == j, beta, newcol)
        a = a.at[:, j].set(newcol)
        return a, taus.at[j].set(tau)

    taus0 = jnp.zeros((k,), dt)
    # under shard_map the panel input is device-varying; the taus carry
    # must carry the same varying-axes type or the fori_loop rejects it
    from .._jax_compat import pvary, varying_axes
    vma = varying_axes(a)
    if vma:
        taus0 = pvary(taus0, vma)
    return lax.fori_loop(0, k, body, (a, taus0))


def geqrf_rec(a, nb: int):
    """Blocked Householder QR: returns (packed, taus) LAPACK-style.

    Recursive equivalent of the reference driver loop
    ``src/geqrf.cc:196-277`` (panel geqrf → larfb trailing update), the
    tree reduction being a no-op on one chip.
    """

    m, n = a.shape
    k = min(m, n)
    if n <= nb or m == 1:
        return _panel_geqrf(a)
    if k < n:  # wide: factor left square part, then apply Qᴴ to the rest
        f1, tau = geqrf_rec(a[:, :k], nb)
        v = _unit_lower(f1, k)
        t = larft_rec(v, tau)
        right = _apply_block_reflector(v, t, a[:, k:], forward=False)
        return jnp.concatenate([f1, right], axis=1), tau
    n1 = blocks._split(n, nb)
    f1, tau1 = geqrf_rec(a[:, :n1], nb)
    v1 = _unit_lower(f1, n1)
    t1 = larft_rec(v1, tau1)
    # trailing update: Qᴴ·A_right = A_right − V·Tᴴ·(Vᴴ·A_right)
    c = _apply_block_reflector(v1, t1, a[:, n1:], forward=False)
    f2, tau2 = geqrf_rec(c[n1:], nb)
    top = jnp.concatenate([f1[:n1], c[:n1]], axis=1)
    bot = jnp.concatenate([f1[n1:], f2], axis=1)
    return jnp.concatenate([top, bot], axis=0), jnp.concatenate([tau1, tau2])


def _cholqr2_panel(pan):
    """Panel QR via shifted CholQR² + Householder reconstruction
    (Ballard et al., "Reconstructing Householder Vectors from TSQR"):
    three MXU gemm pairs + two fused Pallas kernels replace XLA's
    sequential Householder panel.  Returns ``(y, rprime, tau, tmat)``
    with A_panel = (I − Y·T·Yᵀ)·R′ exactly (Y unit lower trapezoid,
    R′ = diag(s)·R upper, τᵢ = −sᵢ·Uᵢᵢ from the no-pivot LU of
    Q − [diag(s); 0]).  f32, panel width a power of two ≥ 32.

    The tiny diagonal shift before the first Cholesky keeps the Gram
    factorization well-posed for ill-conditioned panels; the identity
    A = Q·(L₁L₂)ᵀ holds for any shift, and the second pass restores
    orthogonality — so the shift costs nothing in exactness.

    Also returns ``dev = max|g₂ − I|``, the departure of the first-pass
    Q from orthogonality: CholQR² restores ‖I − QᵀQ‖ to O(ε) only while
    dev < 1 (Yamamoto et al.), i.e. cond(panel) ≲ 1/√ε — callers use it
    to fall back to the unconditionally stable Householder panel.

    The Gram products are pinned to ``Precision.HIGHEST``: their error
    enters Q's orthogonality directly, so the library-wide ``high``
    (3-pass bf16) default would put a ~1e-5 floor under it.
    """

    from ..perf.autotune import kernel
    chol_inv_panel = kernel("chol_inv_panel")
    lu_inv_panel = kernel("lu_inv_panel")

    mk, w = pan.shape
    gram = matmul_hi(_ct(pan), pan)
    eps = jnp.finfo(pan.dtype).eps
    shift = (100.0 * w) * eps * jnp.max(jnp.diag(gram))
    l1, l1inv = chol_inv_panel(gram + shift * jnp.eye(w, dtype=pan.dtype))
    q = matmul(pan, _ct(l1inv))
    g2 = matmul_hi(_ct(q), q)
    l2, l2inv = chol_inv_panel(g2)
    # departure of the first-pass Q from orthogonality, spectral-norm
    # sensitive: the elementwise max|g₂ − I| alone misses a *spread*
    # near-null direction (g₂ ≈ I − v·vᵀ with small entries but
    # λ_min ≈ 0), so also watch the second Cholesky factor's diagonal —
    # one eigenvalue collapsing drags min(diag(L₂)) toward √λ_min
    dev = jnp.maximum(
        jnp.max(jnp.abs(g2 - jnp.eye(w, dtype=pan.dtype))),
        1.0 - jnp.min(jnp.real(jnp.diag(l2))))
    q = matmul(q, _ct(l2inv))
    r = _ct(matmul(l1, l2))
    dq = jnp.diag(q[:w])
    s = jnp.where(dq >= 0, -1.0, 1.0).astype(pan.dtype)
    b = q.at[:w].add(-jnp.diag(s))
    lu, _, uinv = lu_inv_panel(b[:w])
    ytop = jnp.tril(lu, -1) + jnp.eye(w, dtype=pan.dtype)
    y = jnp.concatenate([ytop, matmul(b[w:], uinv)], axis=0)
    tau = -s * jnp.diag(lu)
    rprime = s[:, None] * r
    tinv = jnp.triu(matmul(_ct(y), y), 1) + jnp.diag(1.0 / tau)
    trtri_panel = kernel("trtri_panel")
    tmat = jnp.triu(trtri_panel(tinv[::-1, ::-1])[::-1, ::-1])
    return y, rprime, tau, tmat, dev


def _geqrf_panels_core(a, nb: int, use_cholqr: bool):
    """One pass of the blocked Householder loop.  ``use_cholqr`` picks
    the panel kernel statically (no traced branches inside the loop).
    Returns ``(packed, taus, devmax)`` — ``devmax`` aggregates the
    CholQR² orthogonality-departure guard across panels (0 on the
    Householder pass)."""

    m, n = a.shape
    k = min(m, n)
    taus = []
    devmax = jnp.zeros((), jnp.float32)
    any_cholqr = False
    for k0 in range(0, k, nb):
        w = min(nb, k - k0)
        pan = a[k0:, k0:k0 + w]
        # CholQR² wants a tall panel (orthogonality degrades with
        # cond², and a square panel is as conditioned as the matrix);
        # short/ragged panels take XLA's fused Householder panel
        if use_cholqr and w == nb and (nb & (nb - 1)) == 0 and nb >= 32 \
                and pan.shape[0] >= 2 * nb and a.dtype == jnp.float32:
            y, rp, tau, tmat, dev = _cholqr2_panel(pan)
            col = jnp.concatenate(
                [rp + jnp.tril(y[:w], -1), y[w:]], axis=0)
            devmax = jnp.maximum(devmax,
                                 jnp.where(jnp.isfinite(dev), dev, 2.0))
            any_cholqr = True
        else:
            f, tau = _panel_geqrf(pan)
            y = _unit_lower(f, w)
            tmat = larft_rec(y, tau)
            col = f
        a = a.at[k0:, k0:k0 + w].set(col)
        taus.append(tau)
        if k0 + w < n:
            c = a[k0:, k0 + w:]
            c = c - matmul(y, matmul(_ct(tmat), matmul(_ct(y), c)))
            a = a.at[k0:, k0 + w:].set(c)
    return (a, (jnp.concatenate(taus) if len(taus) > 1 else taus[0]),
            devmax, any_cholqr)


def geqrf_panels(a, nb: int = 512):
    """Loop-based blocked Householder QR whose panel step is
    :func:`_cholqr2_panel` — the TPU-default geqrf path.  Returns
    ``(packed, taus)`` in exact LAPACK form (V unit-lower below the
    diagonal, R above, Q = H₀·H₁⋯).  Ragged or non-power-of-two
    panels fall back to XLA's fused geqrf panel.

    Conditioning guard: CholQR² loses orthogonality once the
    first-pass Gram departure nears 1 (cond(panel) ≳ 1/√ε for f32
    ≈ 3e3).  The guard is aggregated across panels and ONE whole-loop
    ``lax.cond`` reruns the factorization with Householder panels when
    any panel trips — the r4 per-panel cond compiled both branches for
    every panel, which cost 20% throughput and minutes of compile
    (VERDICT r4 Weak #2); the fast path now compiles branch-free."""

    fast, taus, devmax, any_cholqr = _geqrf_panels_core(
        a, nb, use_cholqr=True)
    if not any_cholqr:          # no panel used CholQR² — nothing to guard
        return fast, taus

    def _keep(_):
        return fast, taus

    def _hh_rerun(_):
        f2, t2, _, _ = _geqrf_panels_core(a, nb, use_cholqr=False)
        return f2, t2

    return lax.cond(devmax < 0.25, _keep, _hh_rerun, operand=None)


@instrument_driver("geqrf")
def geqrf(a, opts: Optional[Options] = None):
    """QR factorization — reference ``slate::geqrf`` (``src/geqrf.cc``).
    Returns ``(packed, taus)`` with R on/above the diagonal and the
    Householder V below (unit lower).

    Method dispatch (reference ``method.hh``): under Auto the f32
    backend comes from the autotune table
    (:func:`slate_tpu.method.select_backend`): ``cholqr2`` =
    :func:`geqrf_panels` (shifted-CholQR² panels + Householder
    reconstruction — all-MXU, no sequential panel) timed against XLA's
    blocked geqrf (the vendor library slot) per (m, n, nb, dtype) key;
    off-TPU Auto resolves to XLA with zero timing.  "recursive" keeps
    the explicit-nb blocked recursion.
    """

    from ..options import get_option

    from ..method import select_backend

    av = as_array(a)
    method = get_option(opts, "method_factor", "auto")
    nb = _nb(a, opts)
    nbsel = 512 if nb <= 256 else nb
    if method == "auto" and av.dtype == jnp.float32 and av.ndim == 2 \
            and select_backend("geqrf_panel", m=int(av.shape[0]),
                               n=int(av.shape[1]), nb=nbsel,
                               dtype=av.dtype) == "cholqr2":
        packed, taus = geqrf_panels(av, nbsel)
    elif method == "auto":
        h, taus = jnp.linalg.qr(av, mode="raw")
        # numpy/LAPACK raw mode returns the F-order factor transposed
        packed = jnp.swapaxes(h, -1, -2)
    else:
        packed, taus = geqrf_rec(av, _nb(a, opts))
    return _wrap_like(a, packed), taus


def gelqf(a, opts: Optional[Options] = None):
    """LQ factorization — reference ``slate::gelqf`` (``src/gelqf.cc``).

    Computed as the adjoint of QR of Aᴴ (A = L·Q with L = R̃ᴴ,
    Q = Q̃ᴴ): packed holds L on/below the diagonal and Vᴴ above —
    LAPACK ``gelqf`` layout.  Returns ``(packed, taus)``.
    """

    av = as_array(a)
    f, taus = geqrf_rec(_ct(av), _nb(a, opts))
    return _wrap_like(a, _ct(f)), taus


# ---------------------------------------------------------------------------
# Q application / generation
# ---------------------------------------------------------------------------

def unmqr_rec(packed, taus, c, side: Side, op: Op, nb: int):
    """Apply Q (or Qᴴ) from a packed QR factor — reference
    ``slate::unmqr`` (``src/unmqr.cc``), blocked larfb chain.

    Splitting the reflector chain Q = Q₁·Q₂ gives the four side/op
    orders; Q₂ acts as identity on the first k₁ rows/cols.
    """

    k = taus.shape[0]
    if k <= nb:
        v = _unit_lower(packed, k)
        t = larft_rec(v, taus)
        if side is Side.Left:
            return _apply_block_reflector(v, t, c, forward=op is Op.NoTrans)
        # Right: C·(I − V·T·Vᴴ) = C − ((C·V)·T)·Vᴴ
        tt = t if op is Op.NoTrans else _ct(t)
        return c - matmul(matmul(matmul(c, v), tt), _ct(v))
    k1 = blocks._split(k, nb)
    p1, tau1 = packed[:, :k1], taus[:k1]
    p2, tau2 = packed[k1:, k1:], taus[k1:]
    if side is Side.Left:
        if op is Op.NoTrans:       # Q·C = Q₁·(Q₂·C)
            c2 = unmqr_rec(p2, tau2, c[k1:], side, op, nb)
            c = jnp.concatenate([c[:k1], c2], axis=0)
            return unmqr_rec(p1, tau1, c, side, op, nb)
        c = unmqr_rec(p1, tau1, c, side, op, nb)     # Qᴴ·C = Q₂ᴴ·(Q₁ᴴ·C)
        c2 = unmqr_rec(p2, tau2, c[k1:], side, op, nb)
        return jnp.concatenate([c[:k1], c2], axis=0)
    else:
        if op is Op.NoTrans:       # C·Q = (C·Q₁)·Q₂
            c = unmqr_rec(p1, tau1, c, side, op, nb)
            c2 = unmqr_rec(p2, tau2, c[:, k1:], side, op, nb)
            return jnp.concatenate([c[:, :k1], c2], axis=1)
        c2 = unmqr_rec(p2, tau2, c[:, k1:], side, op, nb)   # C·Qᴴ = (C·Q₂ᴴ)·Q₁ᴴ
        c = jnp.concatenate([c[:, :k1], c2], axis=1)
        return unmqr_rec(p1, tau1, c, side, op, nb)


def unmqr(side: Side, op: Op, a_factor, taus, c, opts: Optional[Options] = None):
    """Reference ``slate::unmqr``."""
    av, cv = as_array(a_factor), as_array(c)
    _reject_complex_trans(av, op)
    out = unmqr_rec(av, taus, cv, side, op, _nb(a_factor, opts))
    return _wrap_like(c, out)


def unmlq(side: Side, op: Op, a_factor, taus, c, opts: Optional[Options] = None):
    """Apply the LQ's Q — reference ``slate::unmlq`` (``src/unmlq.cc``).
    With Q_lq = Q̃ᴴ of the underlying QR of Aᴴ, applying Q_lq is
    applying Q̃ with the opposite op."""

    av, cv = as_array(a_factor), as_array(c)
    _reject_complex_trans(av, op)
    packed = _ct(av)               # back to QR-of-Aᴴ layout
    flip = {Op.NoTrans: Op.ConjTrans if jnp.iscomplexobj(cv) else Op.Trans,
            Op.Trans: Op.NoTrans, Op.ConjTrans: Op.NoTrans}
    out = unmqr_rec(packed, taus, cv, side, flip[op], _nb(a_factor, opts))
    return _wrap_like(c, out)


def ungqr(a_factor, taus, n_cols: Optional[int] = None,
          opts: Optional[Options] = None):
    """Generate the explicit Q (first ``n_cols`` columns) — LAPACK
    ``ungqr`` (the reference exposes this via ``unmqr`` on identity)."""

    av = as_array(a_factor)
    m = av.shape[0]
    k = taus.shape[0]
    n_cols = k if n_cols is None else n_cols
    eye = jnp.eye(m, n_cols, dtype=av.dtype)
    return unmqr_rec(av, taus, eye, Side.Left, Op.NoTrans,
                     _nb(a_factor, opts))


# ---------------------------------------------------------------------------
# Least squares
# ---------------------------------------------------------------------------

def gels_qr(a, b, opts: Optional[Options] = None):
    """Least squares via QR — reference ``slate::gels_qr``
    (``src/gels_qr.cc``): minimum-residual for m ≥ n, minimum-norm via
    LQ for m < n."""

    av, bv = as_array(a), as_array(b)
    nb = _nb(a, opts)
    m, n = av.shape
    squeeze = bv.ndim == 1
    if squeeze:
        bv = bv[:, None]
    if m >= n:
        f, taus = geqrf_rec(av, nb)
        c = unmqr_rec(f, taus, bv, Side.Left,
                      Op.ConjTrans if jnp.iscomplexobj(av) else Op.Trans, nb)
        x = blocks.trsm_rec(Side.Left, Uplo.Upper, Diag.NonUnit,
                            f[:n], c[:n], nb)
    else:
        # minimum-norm: A = L·Q, x = Qᴴ·[L⁻¹b; 0]
        f, taus = geqrf_rec(_ct(av), nb)       # QR of Aᴴ (n×m)
        l = _ct(jnp.triu(f[:m]))               # L = R̃ᴴ (m×m lower)
        y = blocks.trsm_rec(Side.Left, Uplo.Lower, Diag.NonUnit, l, bv, nb)
        z = jnp.concatenate(
            [y, jnp.zeros((n - m, bv.shape[1]), av.dtype)], axis=0)
        x = unmqr_rec(f, taus, z, Side.Left, Op.NoTrans, nb)
    if squeeze:
        x = x[:, 0]
    return _wrap_like(b, x)


def cholqr(a, opts: Optional[Options] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Cholesky QR — reference ``slate::cholqr`` (``src/cholqr.cc``):
    R = chol(AᴴA)ᴴ (upper), Q = A·R⁻¹.  One herk + one potrf + one trsm
    — three MXU-dense ops, the TPU-preferred tall-skinny factorization.
    Returns ``(Q, R)``."""

    av = as_array(a)
    nb = _nb(a, opts)
    gram = blocks.herk_rec(Uplo.Lower, 1.0, _ct(av), 0.0,
                           jnp.zeros((av.shape[1], av.shape[1]), av.dtype),
                           nb, conj=jnp.iscomplexobj(av))
    # herk fills only the lower triangle meaningfully; potrf_rec wants full
    from ..ops.tile_ops import hermitize
    l = blocks.potrf_rec(hermitize(Uplo.Lower, gram), nb)
    r = _ct(l)
    q = blocks.trsm_rec(Side.Right, Uplo.Upper, Diag.NonUnit, r, av, nb)
    return q, r


def gels_cholqr(a, b, opts: Optional[Options] = None):
    """Least squares via CholQR — reference ``slate::gels_cholqr``
    (``src/gels_cholqr.cc``): solve R x = Qᴴ b."""

    av, bv = as_array(a), as_array(b)
    nb = _nb(a, opts)
    squeeze = bv.ndim == 1
    if squeeze:
        bv = bv[:, None]
    q, r = cholqr(av, opts)
    x = blocks.trsm_rec(Side.Left, Uplo.Upper, Diag.NonUnit,
                        r, matmul(_ct(q), bv), nb)
    if squeeze:
        x = x[:, 0]
    return _wrap_like(b, x)


@instrument_driver("gels")
def gels(a, b, opts: Optional[Options] = None):
    """Least squares driver with method auto-selection — reference
    ``slate::gels`` (``src/gels.cc``; QR vs CholQR per ``method.hh:236``)."""

    av = as_array(a)
    m, n = av.shape
    from ..method import select_gels
    method = select_gels(get_option(opts, "method_gels", MethodGels.Auto),
                         m, n)
    if method is MethodGels.CholQR and m >= n:
        return gels_cholqr(a, b, opts)
    return gels_qr(a, b, opts)


def gels_mixed(a, b, opts: Optional[Options] = None, *, tol=None):
    """Mixed-precision least squares with iterative refinement — the QR
    analogue of ``gesv_mixed``/``posv_mixed`` (the reference has no
    gels_mixed; this is corrected semi-normal equations, Björck 1987,
    over the shared refine core).  Factor A = Q·R once in the low leg —
    an fp32 leg runs its trailing updates through the bf16x3 split
    product under :func:`~slate_tpu.linalg._refine.split_factor_leg` —
    then iterate the NORMAL-EQUATION residual ``s = Aᴴ(b − A·x)``,
    which vanishes at the LS solution even when the plain residual does
    not; each correction solves the semi-normal equations
    ``Rᴴ·R·d = s`` (two triangular sweeps against the resident low
    factor).  Condition-aware demotion re-factors stock when
    κ(R)²·n·ε_lo approaches 1 (the SNE contraction bound).
    Overdetermined shapes only (m ≥ n).  Returns ``(x, iters)``;
    negative ``iters`` flags the working-precision :func:`gels_qr`
    fallback (reference info convention)."""

    from ..enums import Norm
    from .norms import norm as _norm
    from ._refine import (ir_refine_core, lo_dtype, split_factor_leg,
                          use_split_leg)

    av, bv = as_array(a), as_array(b)
    m, n = av.shape
    if m < n:
        raise ValueError("gels_mixed refines overdetermined systems "
                         "(m >= n); use gels for minimum-norm shapes")
    nb = _nb(a, opts)
    itermax = int(get_option(opts, "max_iterations", 30))
    use_fallback = bool(get_option(opts, "use_fallback_solver", True))
    squeeze = bv.ndim == 1
    if squeeze:
        bv = bv[:, None]
    eps = float(jnp.finfo(av.dtype).eps)
    # the refined operator is AᴴA: scale the stopping test with
    # ‖AᴴA‖∞ ≤ ‖Aᴴ‖∞·‖A‖∞ = ‖A‖₁·‖A‖∞
    anorm2 = float(_norm(Norm.One, av)) * float(_norm(Norm.Inf, av))
    thresh = float(tol) if tol is not None else eps * float(n) ** 0.5

    lo = lo_dtype(av.dtype)

    def _factor():
        f, _taus = geqrf_rec(av.astype(lo), nb)
        return jnp.triu(f[:n])

    if use_split_leg(lo):
        from .condest import refine_kappa_eps

        with split_factor_leg():
            r_lo = _factor()
        # κ₁(R)²·n·ε_lo is the SNE contraction bound: past ~0.25 the
        # semi-normal corrections stop converging on a split factor,
        # so demote to the stock low-precision factorization
        ke = refine_kappa_eps(
            lambda v: blocks.trsm_rec(Side.Left, Uplo.Upper, Diag.NonUnit,
                                      r_lo, v, nb),
            lambda v: blocks.trsm_rec(Side.Left, Uplo.Lower, Diag.NonUnit,
                                      _ct(r_lo), v, nb),
            n, float(_norm(Norm.One, r_lo)), lo, power=2)
        if ke > 0.25:
            r_lo = _factor()
    else:
        r_lo = _factor()

    ah = _ct(av)

    def solve_lo(s):
        w = blocks.trsm_rec(Side.Left, Uplo.Lower, Diag.NonUnit,
                            _ct(r_lo), s.astype(lo), nb)
        d = blocks.trsm_rec(Side.Left, Uplo.Upper, Diag.NonUnit,
                            r_lo, w, nb)
        return d.astype(av.dtype)

    def solve_full(_s0):
        # working-precision fallback: stock gels_qr on the ORIGINAL
        # right-hand side (the core hands us the normal-equation rhs,
        # which the full path does not need)
        return as_array(gels_qr(av, bv, opts))

    residual = jax.jit(lambda x: matmul_hi(ah, bv - matmul_hi(av, x)))
    s0 = residual(jnp.zeros((n, bv.shape[1]), av.dtype))
    x, iters = ir_refine_core(s0, solve_lo, solve_full, residual,
                              anorm=anorm2, thresh=thresh,
                              itermax=itermax, use_fallback=use_fallback)
    if squeeze:
        x = x[:, 0]
    return _wrap_like(b, x), iters
