"""Driver/algorithm layer — the analog of the reference's ``src/*.cc``
drivers enumerated in ``include/slate/slate.hh`` (93 public entry points).

Every driver is a pure function (JAX-functional: returns results instead
of mutating) and is jit-compatible; shapes and blocking are static.
"""

from .blas3 import (  # noqa: F401
    gemm, gemmA, gemmC, symm, hemm, hemmA, hemmC, syrk, herk, syr2k, her2k,
    trmm, trsm, trsmA, trsmB,
)
from .cholesky import (  # noqa: F401
    posv, posvMixed, posv_mixed, posv_mixed_gmres, potrf, potri, potrs,
    trtri, trtrm,
)
from .lu import (  # noqa: F401
    gesv, gesvMixed, gesv_mixed, gesv_mixed_gmres, gesv_nopiv, getrf,
    getrf_nopiv, getrf_tntpiv, getri, getrs, getrs_nopiv,
)
from .norms import (  # noqa: F401
    col_norms, gbnorm, genorm, hbnorm, henorm, norm, synorm, trnorm,
)
from .qr import (  # noqa: F401
    cholqr, gelqf, gels, gels_cholqr, gels_mixed, gels_qr, geqrf, ungqr,
    unmlq, unmqr,
)
from .util import add, copy, scale, scale_row_col, set  # noqa: F401
from .eig import (  # noqa: F401
    he2hb, heev, heev_vals, hegst, hegv, hb2st, stedc, stemr, steqr, sterf,
    syev, sygst, sygv, unmtr_he2hb, unmtr_hb2st,
)
from .svd import (  # noqa: F401
    bdsqr, ge2tb, gesvd, svd, svd_vals, tb2bd, unmbr_ge2tb, unmbr_tb2bd,
)
from .hesv import hesv, hetrf, hetrs, sysv, sytrf, sytrs  # noqa: F401
from .batched import (  # noqa: F401
    gels_batched, geqrf_batched, gesv_batched, getrf_batched,
    getrs_batched, posv_batched, potrf_batched, potrs_batched,
)
from .band import (  # noqa: F401
    gbmm, gbsv, gbtrf, gbtrs, hbmm, pbsv, pbtrf, pbtrs, tbsm,
)
from .condest import (  # noqa: F401
    gecondest, norm1est, pocondest, refine_kappa_eps, spectral_interval,
    trcondest,
)
from .polar import heev_qdwh, polar, svd_qdwh  # noqa: F401
from ._stedc import (  # noqa: F401
    stedc_deflate, stedc_merge, stedc_secular, stedc_solve, stedc_sort,
    stedc_z_vector,
)
