"""SVD family — the reference's two-stage chain ``src/svd.cc:207-372``:

``ge2tb`` (dense→triangular-band, ``src/ge2tb.cc`` 589 LoC) → ``tb2bd``
(band→bidiagonal bulge chasing, ``src/tb2bd.cc`` 421 LoC) → LAPACK
``bdsqr`` on rank 0 → back-transforms ``unmbr_tb2bd`` / ``unmbr_ge2tb``.

TPU-first stance mirrors :mod:`slate_tpu.linalg.eig`: stage 1 carries the
O(mn²) flops as compact-WY panel QRs/LQs + whole-trailing-matrix GEMMs on
the MXU; stage 2 is O(n²·nb), sequential, and runs on host exactly where
the reference gathers to a single node; the bidiagonal core uses host
LAPACK (the reference calls ``lapack::bdsqr`` on rank 0,
``src/svd.cc:300+``); back-transforms are MXU matmul chains again.
"""

from __future__ import annotations

from functools import partial
from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..enums import MethodSVD, Op, Side
from ..exceptions import SlateError
from ..matrix import as_array
from ..options import Options, get_option
from ..perf import metrics as _metrics
from ..perf.metrics import instrument_driver
from ..ops.blocks import _ct, matmul
from .blas3 import _nb
from .eig import _givens, sterf
from .qr import _unit_lower, geqrf_rec, larft_rec


class Ge2tbFactors(NamedTuple):
    """Stage-1 output: A = Q₁·B·P₁ᴴ with B upper-triangular band of
    superdiagonal width ``kd``; ``qpanels``/``ppanels`` hold the
    ``(offset, V, T)`` block reflectors of Q₁ (row space) and P₁
    (column space) — reference ``src/ge2tb.cc`` stores the same U/V
    factor matrices."""

    band: jnp.ndarray
    kd: int
    qpanels: Tuple[Tuple[int, jnp.ndarray, jnp.ndarray], ...]
    ppanels: Tuple[Tuple[int, jnp.ndarray, jnp.ndarray], ...]


def ge2tb(a, opts: Optional[Options] = None) -> Ge2tbFactors:
    """Reduce a general m×n (m ≥ n) matrix to upper-triangular band form
    — reference ``slate::ge2tb`` (``src/ge2tb.cc``).

    Per panel k: QR of the block column from the diagonal down (kills
    below-diagonal), apply Q̂ᴴ to the trailing columns; then LQ of the
    block row right of the band (kills right of the band), apply P̂ from
    the right — each application two large GEMMs (the reference's
    ``internal::unmqr/unmlq`` tile batches).
    """

    av = as_array(a)
    m, n = av.shape
    if m < n:
        raise SlateError("ge2tb requires m >= n (drivers transpose)")
    nb = _nb(a, opts)
    band, qvts, pvts = _ge2tb_impl(av, nb)
    # offsets derive from V row counts (single source of truth; the jit
    # boundary carries only arrays)
    qpanels = tuple((m - v.shape[0], v, t) for v, t in qvts)
    ppanels = tuple((n - v.shape[0], v, t) for v, t in pvts)
    return Ge2tbFactors(band=band, kd=nb, qpanels=qpanels, ppanels=ppanels)


@partial(jax.jit, static_argnums=1)
def _ge2tb_impl(av, nb: int):
    """The whole two-sided panel chain under one jit — one device
    dispatch per call instead of dozens per panel (see
    ``eig._he2hb_impl``)."""

    m, n = av.shape
    qpanels = []
    ppanels = []
    for j0 in range(0, n, nb):
        w = min(nb, n - j0)
        # QR panel on rows j0.. of block column j0:j0+w
        if m - j0 > 1:
            p = av[j0:, j0:j0 + w]
            f, tau = geqrf_rec(p, nb)
            k = min(p.shape[0], w)
            v = _unit_lower(f, k)
            t = larft_rec(v, tau)
            r_part = jnp.triu(f[:w]) if f.shape[0] >= w else jnp.triu(f)
            zeros = jnp.zeros((p.shape[0] - r_part.shape[0], w), av.dtype)
            av = av.at[j0:, j0:j0 + w].set(
                jnp.concatenate([r_part, zeros], axis=0))
            if j0 + w < n:
                c = av[j0:, j0 + w:]
                c = c - matmul(v, matmul(_ct(t), matmul(_ct(v), c)))
                av = av.at[j0:, j0 + w:].set(c)
            qpanels.append((v, t))
        # LQ panel on the block row, columns right of the band
        c0 = j0 + nb
        if c0 < n and n - c0 > 1:
            wr = min(w, n - j0)
            row = av[j0:j0 + wr, c0:]
            # LQ(row) = (QR(rowᴴ))ᴴ
            f, tau = geqrf_rec(_ct(row), nb)
            k = min(f.shape[0], f.shape[1])
            v = _unit_lower(f, k)
            t = larft_rec(v, tau)
            l_part = _ct(jnp.triu(f[:wr]) if f.shape[0] >= wr else jnp.triu(f))
            zeros = jnp.zeros((wr, row.shape[1] - l_part.shape[1]), av.dtype)
            av = av.at[j0:j0 + wr, c0:].set(
                jnp.concatenate([l_part, zeros], axis=1))
            # apply P̂ = I − V·T·Vᴴ from the right to the trailing rows
            if j0 + wr < m:
                c = av[j0 + wr:, c0:]
                c = c - matmul(matmul(matmul(c, v), t), _ct(v))
                av = av.at[j0 + wr:, c0:].set(c)
            ppanels.append((v, t))
    # clamp to the upper band
    i = jnp.arange(m)[:, None]
    j = jnp.arange(n)[None, :]
    band = jnp.where((j - i >= 0) & (j - i <= nb), av, 0)
    return band, tuple(qpanels), tuple(ppanels)


def unmbr_ge2tb(side: Side, op: Op, factors: Ge2tbFactors, c):
    """Apply Q₁ (side=Left) or P₁ (side=Right, applied as P₁·C to row
    space of C) from :func:`ge2tb` — reference ``slate::unmbr_ge2tb``
    (``src/unmbr_ge2tb.cc``).

    ``side`` selects which factor; ``op`` NoTrans applies Q₁ (P₁),
    ConjTrans applies the adjoint.  C is multiplied from the left.
    """

    cv = as_array(c)
    panels = factors.qpanels if side is Side.Left else factors.ppanels
    vts = tuple((v, t) for _, v, t in panels)
    from .qr import apply_reflector_chain
    return apply_reflector_chain(vts, cv, op is Op.NoTrans)


# ---------------------------------------------------------------------------
# Stage 2: triangular band → bidiagonal (host, Givens bulge chasing)
# ---------------------------------------------------------------------------

class Tb2bdRotations(NamedTuple):
    """Rotation logs of :func:`tb2bd`: B = U₂·B_bd·V₂ᴴ with
    U₂ = L₁ᴴ⋯L_qᴴ·diag(uphase), V₂ = M₁⋯M_p·diag(vphase)."""

    lplanes: np.ndarray
    lcs: np.ndarray
    lss: np.ndarray
    rplanes: np.ndarray
    rcs: np.ndarray
    rss: np.ndarray
    uphase: np.ndarray
    vphase: np.ndarray
    kd: int = 0          # chase bandwidth (0 = generic/legacy log)


def _phase_bidiag(d_c, e_c, n, dt):
    """Phase-normalize a complex bidiagonal to real (LAPACK gebrd's final
    step); shared by the Python and compiled tb2bd paths."""

    uphase = np.ones((n,), dtype=dt)
    vphase = np.ones((n,), dtype=dt)
    if np.iscomplexobj(np.zeros((), dtype=dt)):
        for j in range(n):
            val = d_c[j] * vphase[j]
            absv = abs(val)
            uphase[j] = val / absv if absv != 0 else 1.0
            d_c[j] = absv
            if j < n - 1:
                val = np.conj(uphase[j]) * e_c[j]
                absv = abs(val)
                vphase[j + 1] = np.conj(val) / absv if absv != 0 else 1.0
                e_c[j] = absv
    return uphase, vphase


def _tb2bd_ab(ab: np.ndarray, kd_eff: int, want_rots: bool = True):
    """Compiled stage 2 core on prepared upper-band storage
    ``ab[(n, kd_eff+3)]`` (modified in place) — O(n·kd) end to end."""

    from .. import native

    n = ab.shape[0]
    with _metrics.timer("chase.tb2bd"):
        lrot, rrot = native.tb2bd_banded(ab, n, kd_eff, want_rots)
    d_c = ab[:, 1].copy()
    e_c = ab[1:, 2].copy()
    uphase, vphase = _phase_bidiag(d_c, e_c, n, ab.dtype)
    rots = Tb2bdRotations(
        lplanes=lrot[0], lcs=lrot[1], lss=lrot[2],
        rplanes=rrot[0], rcs=rrot[1], rss=rrot[2],
        uphase=uphase, vphase=vphase, kd=kd_eff)
    return np.real(d_c), np.real(e_c), rots


def _tb2bd_native(b: np.ndarray, kd: int, want_rots: bool = True):
    """Compiled stage 2 from a dense band matrix: pack the band storage
    and run :func:`_tb2bd_ab` (``native/runtime.cc`` ``slate_tb2bd_*``)."""

    n = b.shape[0]
    dt = np.complex128 if np.iscomplexobj(b) else np.float64
    kd_eff = min(kd, n - 1)
    ab = np.zeros((n, kd_eff + 3), dtype=dt, order="C")
    for dd in range(kd_eff + 1):
        ab[dd:, dd + 1] = np.diagonal(b, dd)
    return _tb2bd_ab(ab, kd_eff, want_rots)


def tb2bd(band, kd: int, want_rots: bool = True
          ) -> Tuple[np.ndarray, np.ndarray, Tb2bdRotations]:
    """Reduce an upper-triangular band matrix (superdiagonal width ``kd``)
    to real upper bidiagonal — reference ``slate::tb2bd``
    (``src/tb2bd.cc``; the bulge-chasing sweeps of ``gebr1/2/3``,
    ``internal_gebr.cc``, run on host like the reference's single-node
    stage 2; compiled via the native runtime when available, Python
    schedule as fallback).

    Returns ``(d, e, rotations)`` with B = U₂·bidiag(d, e)·V₂ᴴ.
    """

    b = np.array(band)
    n = b.shape[1]
    b = b[:n, :n].copy()
    from .. import native
    if native.available() and n > 2 and kd >= 2:
        return _tb2bd_native(b, kd, want_rots)
    ll: List[Tuple[int, float, complex]] = []
    rl: List[Tuple[int, float, complex]] = []
    for bw in range(kd, 1, -1):
        for j in range(0, n - bw):
            row, p = j, j + bw - 1
            while True:
                # right rotation on columns (p, p+1) kills B[row, p+1]
                f, g = b[row, p], b[row, p + 1]
                c, s = _givens(f, g)
                gt = np.array([[c, s], [-np.conj(s), c]]).T
                lo = max(0, p - bw - 1)
                hi = min(n, p + 2)
                b[lo:hi, [p, p + 1]] = b[lo:hi, [p, p + 1]] @ gt
                rl.append((p + 1, c, s))
                # bulge now at (p+1, p): kill with left rotation rows (p, p+1)
                f, g = b[p, p], b[p + 1, p]
                c, s = _givens(f, g)
                gm = np.array([[c, s], [-np.conj(s), c]])
                lo = max(0, p - 1)
                hi = min(n, p + bw + 2)
                b[[p, p + 1], lo:hi] = gm @ b[[p, p + 1], lo:hi]
                ll.append((p + 1, c, s))
                # bulge now at (p, p+1+bw) if inside
                if p + 1 + bw >= n:
                    break
                row, p = p, p + bw
    d_c = np.diagonal(b).copy()
    e_c = np.diagonal(b, 1).copy()
    uphase, vphase = _phase_bidiag(d_c, e_c, n, b.dtype)
    d = np.real(d_c)
    e = np.real(e_c)
    rots = Tb2bdRotations(
        lplanes=np.asarray([x[0] for x in ll], dtype=np.int32),
        lcs=np.asarray([x[1] for x in ll], dtype=np.float64),
        lss=np.asarray([x[2] for x in ll]),
        rplanes=np.asarray([x[0] for x in rl], dtype=np.int32),
        rcs=np.asarray([x[1] for x in rl], dtype=np.float64),
        rss=np.asarray([x[2] for x in rl]),
        uphase=uphase, vphase=vphase,
    )
    return d, e, rots


def unmbr_tb2bd(side: Side, rots: Tb2bdRotations, z: np.ndarray) -> np.ndarray:
    """Back-transform through the tb2bd chase — reference
    ``slate::unmbr_tb2bd`` (``src/unmbr_tb2bd.cc``): Z ← U₂·Z
    (side=Left) or Z ← V₂·Z (side=Right)."""

    z = np.asarray(z)
    if side is Side.Left:
        phase, planes, cs, ss = rots.uphase, rots.lplanes, rots.lcs, rots.lss
    else:
        phase, planes, cs, ss = rots.vphase, rots.rplanes, rots.rcs, rots.rss
    from .. import native
    if native.available():
        cplx = (np.iscomplexobj(phase) or np.iscomplexobj(ss)
                or np.iscomplexobj(z))
        dt = np.complex128 if cplx else np.float64
        zz = np.asarray(z, dtype=dt) * phase[:z.shape[0], None].astype(dt)
        if len(planes):
            zz = native.apply_rot_seq(zz, planes, cs, ss,
                                      0 if side is Side.Left else 1,
                                      kd=getattr(rots, "kd", 0))
        return zz
    if np.iscomplexobj(phase):
        z = z.astype(phase.dtype)
    z = phase[:z.shape[0], None] * z
    for idx in range(len(planes) - 1, -1, -1):
        i = int(planes[idx])
        c, s = cs[idx], ss[idx]
        if side is Side.Left:
            # L = [[c, s], [−s̄, c]] on rows; apply Lᴴ (reverse order)
            m2 = np.array([[c, -s], [np.conj(s), c]])
        else:
            # M = Gᵀ = [[c, −s̄], [s, c]] on the plane; apply M itself
            m2 = np.array([[c, -np.conj(s)], [s, c]])
        z[[i - 1, i], :] = m2 @ z[[i - 1, i], :]
    return z


# ---------------------------------------------------------------------------
# Bidiagonal core (host LAPACK, like the reference's rank-0 bdsqr)
# ---------------------------------------------------------------------------

def bdsqr(d, e, want_uv: bool = False, method: MethodSVD = MethodSVD.Auto):
    """Singular values (and vectors) of a real upper bidiagonal matrix —
    the reference calls LAPACK ``bdsqr`` on rank 0 (``src/svd.cc:300+``).

    Values-only uses the Golub–Kahan tridiagonal (zero diagonal,
    interleaved (d₁,e₁,d₂,…) off-diagonal; eigenvalues ±σ) with LAPACK
    ``sterf``; vectors use the dense bidiagonal via LAPACK gesdd/gesvd
    (D&C / QR per ``MethodSVD``).
    """

    d = np.asarray(d, dtype=np.float64)
    e = np.asarray(e, dtype=np.float64)
    n = d.shape[0]
    if not want_uv:
        if n == 0:
            return d
        gk_off = np.zeros((2 * n - 1,))
        gk_off[0::2] = d
        if n > 1:
            gk_off[1::2] = e
        w = sterf(np.zeros((2 * n,)), gk_off)
        return np.sort(w[n:])[::-1]
    b = np.diag(d) + (np.diag(e, 1) if n > 1 else 0)
    if method is MethodSVD.QR:
        import scipy.linalg as sla
        u, s, vh = sla.svd(b, lapack_driver="gesvd")
    else:
        u, s, vh = np.linalg.svd(b)
    return u, s, vh


# ---------------------------------------------------------------------------
# Drivers
# ---------------------------------------------------------------------------

#: above this size svd's Auto method solves the band middle factor with
#: one host-LAPACK gesdd call instead of the staged tb2bd chain (tests
#: lower it to cover the fast path)
_BAND_SOLVER_MIN_N = 512


def _band_svd(band_sq, kd: int, want_u: bool, want_vt: bool, method,
              auto: bool):
    """Stage 2+3 on the n×n upper-band middle factor, shared by
    single-chip :func:`svd` and the distributed ``psvd``: band →
    bidiagonal → bdsqr → back-transform through the chase.  Returns
    ``(s, u_b, vh_b)`` (None where not requested; device arrays on the
    device-resident chase path, numpy otherwise).

    The autotuned ``chase`` site decides the stage-2 backend first:
    ``pallas_wavefront`` keeps the band ON DEVICE (packed on device,
    chased by one Pallas invocation, both reflector logs consumed by
    the WY back-transforms with zero host repacking); ``host_native``
    is the historical single-node path below.

    Large-n Auto fast path (host route only): one host-LAPACK gesdd
    call on the n×n band where the compiled stage 2 is unavailable.
    """

    from .. import native
    from . import _chase

    n = int(band_sq.shape[0])
    want_uv = want_u or want_vt
    kd_dev = min(kd, n - 1)
    real = not np.issubdtype(np.dtype(band_sq.dtype), np.complexfloating)
    if n > 2 and kd_dev >= 2 and _chase.backend(
            "tb2bd", n, kd_dev, band_sq.dtype,
            want_uv and real) == "pallas_wavefront":
        st_dev = _chase.tb2bd_st_from_dense(band_sq, kd_dev)
        st_dev, ulog, vlog = _chase.tb2bd_device(st_dev, kd_dev)
        d, e = _chase.tb2bd_d_e(st_dev, kd_dev, n)
        return _stage3_svd_hh(d, e, ulog, vlog, kd_dev, want_u, want_vt,
                              method, auto)
    band_sq = np.asarray(band_sq)
    # The dense-gesdd bypass survives only where the compiled stage 2 is
    # unavailable (no toolchain); with the native runtime the staged
    # chain is both the default and the faster path.
    if auto and n > _BAND_SOLVER_MIN_N and not native.available():
        if not want_uv:
            return np.ascontiguousarray(
                np.linalg.svd(band_sq, compute_uv=False)), None, None
        u_b, s, vh_b = np.linalg.svd(band_sq, full_matrices=False)
        return s, (u_b if want_u else None), (vh_b if want_vt else None)
    import jax as _jax
    if want_uv and not np.iscomplexobj(band_sq) and native.available() \
            and n > 2 and min(kd, n - 1) >= 2 \
            and _jax.default_backend() != "cpu":
        # real with vectors: Householder chase + on-device WY appliers
        kd_eff = min(kd, n - 1)
        st = np.zeros((n, 3 * kd_eff + 2), dtype=np.float64)
        for dd in range(kd_eff + 1):
            st[:n - dd, dd + kd_eff] = np.real(np.diagonal(band_sq, dd))
        return _band_svd_hh_ab(st, kd_eff, want_u, want_vt, method, auto)
    d, e, rots = tb2bd(band_sq, kd, want_rots=want_uv)
    return _stage3_svd(d, e, rots, want_u, want_vt, method, auto)


def _stage3_svd(d, e, rots, want_u, want_vt, method, auto):
    """Bidiagonal SVD + chase back-transforms (stage 3)."""

    from .. import native

    n = d.shape[0]
    want_uv = want_u or want_vt
    if not want_uv:
        return bdsqr(d, e).copy(), None, None
    if auto and native.available() and n > 1:
        # compiled D&C bidiagonal core (LAPACK bdsdc; the reference's
        # rank-0 lapack::bdsqr slot, src/svd.cc:300+)
        u_bd, s, vh_bd = native.bdsdc(d, e)
        u_bd = np.ascontiguousarray(u_bd)
        vh_bd = np.ascontiguousarray(vh_bd)
    else:
        u_bd, s, vh_bd = bdsqr(d, e, want_uv=True, method=method)
    u_b = unmbr_tb2bd(Side.Left, rots, u_bd) if want_u else None
    vh_b = None
    if want_vt:
        vh_b = _ct(unmbr_tb2bd(Side.Right, rots, _ct(vh_bd)))
    return s, u_b, vh_b


def _bd_sweep_counts(n, kd, s0: int = 0, s1=None):
    """Per-sweep reflector counts of the bidiagonal Householder chase
    for sweeps ``[s0, s1)`` (mirrors ``native.bd_step_count``'s window
    logic per sweep; the range serves the checkpointed log packer)."""
    if s1 is None:
        s1 = max(n - 1, 0)
    counts = []
    for s in range(s0, min(s1, max(n - 1, 0))):
        hi = min(s + kd, n - 1)
        if hi <= s + 1:
            continue
        cnt, b = 1, 1
        while b * kd + 1 + s <= n - 1:
            cnt += 1
            b += 1
        counts.append(cnt)
    return counts


def _stage3_svd_hh(d, e, ulog, vlog, kd_eff: int, want_u: bool,
                   want_vt: bool, method, auto: bool):
    """Bidiagonal solve + batched-WY back-transforms for the
    Householder-chase paths; each log is a ``(v3, t2, s0)`` triple —
    host numpy (native chase) or device arrays (wavefront kernel)."""

    from .. import native
    from .eig import unmtr_hb2st_hh

    n = np.asarray(d).shape[0]
    if auto and native.available() and n > 1:
        u_bd, s, vh_bd = native.bdsdc(d, e)
        u_bd = np.ascontiguousarray(u_bd)
        vh_bd = np.ascontiguousarray(vh_bd)
    else:
        u_bd, s, vh_bd = bdsqr(d, e, want_uv=True, method=method)
    u_b = vh_b = None
    if want_u:
        u_b = unmtr_hb2st_hh(*ulog, u_bd, kd_eff)
    if want_vt:
        vh_b = unmtr_hb2st_hh(*vlog, vh_bd.T, kd_eff).T
    return s, u_b, vh_b


def _band_svd_hh_ab(st: np.ndarray, kd_eff: int, want_u: bool,
                    want_vt: bool, method, auto: bool):
    """Real-f64 stage 2+3 via the HOST Householder bidiagonal chase:
    the U and V reflector logs back-transform ON DEVICE as batched WY
    gemms (reference ``unmbr_tb2bd`` applies its V blocks the same
    way) — the ``host_native`` backend of the ``chase`` site."""

    from .. import native
    from . import _chase
    from .eig import _pack_hh_log

    n = st.shape[0]
    with _metrics.timer("chase.tb2bd"):
        ulog, vlog = native.tb2bd_hh_banded(st, n, kd_eff)
    d = st[:, kd_eff].copy()
    e = st[:n - 1, kd_eff + 1].copy()
    counts = _bd_sweep_counts(n, kd_eff)
    pu = _pack_hh_log(*ulog, n, kd_eff, counts=counts)
    pv = _pack_hh_log(*vlog, n, kd_eff, counts=counts)
    _chase.mark_host_path("tb2bd", pu + pv)
    return _stage3_svd_hh(d, e, pu, pv, kd_eff, want_u, want_vt,
                          method, auto)


def _band_svd_ab(ab, kd_eff: int, want_u: bool, want_vt: bool, method,
                 auto: bool):
    """Stage 2+3 from O(n·kd) upper-band storage directly (the
    distributed drivers\' path).  Real f64 with vectors takes the
    Householder chase + on-device WY back-transform; complex (and
    values-only) keeps the Givens chase."""

    from .. import native
    from . import _chase

    n = ab.shape[0]
    if not (native.available() and n > 2 and kd_eff >= 2):
        dense = np.zeros((n, n), dtype=ab.dtype)
        idx = np.arange(n)
        for dd in range(min(kd_eff, n - 1) + 1):
            dense[idx[:n - dd], idx[:n - dd] + dd] = ab[dd:, dd + 1]
        return _band_svd(dense, kd_eff, want_u, want_vt, method, auto)
    import jax as _jax
    if (want_u or want_vt) and ab.dtype == np.float64 and _chase.backend(
            "tb2bd", n, kd_eff, ab.dtype, True) == "pallas_wavefront":
        # device-resident wavefront chase: one O(n·kd) operand upload,
        # then the band, both logs and the back-transforms stay device
        st_dev, ulog, vlog = _chase.tb2bd_device(
            _chase.tb2bd_st_from_ab(ab, kd_eff), kd_eff)
        d, e = _chase.tb2bd_d_e(st_dev, kd_eff, n)
        return _stage3_svd_hh(d, e, ulog, vlog, kd_eff, want_u, want_vt,
                              method, auto)
    if (want_u or want_vt) and ab.dtype == np.float64 \
            and _jax.default_backend() != "cpu":
        # device WY back-transform only pays off off-host (see eig.py)
        st = np.zeros((n, 3 * kd_eff + 2), dtype=np.float64)
        for dd in range(kd_eff + 1):
            st[:n - dd, dd + kd_eff] = ab[dd:, dd + 1]
        return _band_svd_hh_ab(st, kd_eff, want_u, want_vt, method, auto)
    d, e, rots = _tb2bd_ab(ab, kd_eff, want_rots=want_u or want_vt)
    return _stage3_svd(d, e, rots, want_u, want_vt, method, auto)


def svd_vals(a, opts: Optional[Options] = None):
    """Singular values — reference ``slate::svd_vals`` (``src/svd.cc``)."""
    return svd(a, jobu=False, jobvt=False, opts=opts)[0]


@instrument_driver("svd")
def svd(a, jobu: bool = True, jobvt: bool = True,
        opts: Optional[Options] = None):
    """Two-stage SVD — reference ``slate::svd`` (``src/svd.cc:207-372``).

    Returns ``(sigma, U, Vᴴ)`` (economy: U is m×k, Vᴴ is k×n with
    k = min(m, n)); U/Vᴴ are None when not requested.

    Driver selection consults the autotuned ``svd_driver`` site
    (``twostage`` vs ``qdwh`` — :mod:`slate_tpu.linalg.polar`); an
    ``svd_driver`` per-call option or a
    ``SLATE_TPU_AUTOTUNE_FORCE=svd_driver=...`` pin overrides.
    """

    av = as_array(a)
    m, n = av.shape
    if m < n:
        # work on Aᴴ = V·Σ·Uᴴ and swap — reference ``src/svd.cc:207``
        s, u, vh = svd(_ct(av), jobu=jobvt, jobvt=jobu, opts=opts)
        return s, (None if vh is None else _ct(vh)), \
            (None if u is None else _ct(u))
    method = get_option(opts, "method_svd", MethodSVD.Auto)
    driver = get_option(opts, "svd_driver", None)
    if driver is None:
        from ..perf import autotune

        driver = autotune.select("svd_driver", m=m, n=n, dtype=av.dtype,
                                 eligible=method is MethodSVD.Auto)
    if driver == "qdwh":
        from .polar import svd_qdwh

        return svd_qdwh(a, jobu=jobu, jobvt=jobvt, opts=opts)
    return _svd_twostage(a, jobu, jobvt, opts)


def _svd_twostage(a, jobu: bool, jobvt: bool, opts: Optional[Options]):
    """The two-stage chain (ge2tb → band SVD → back-transforms) — the
    ``svd_driver=twostage`` backend; callers guarantee m ≥ n."""

    av = as_array(a)
    m, n = av.shape
    with _metrics.timer("stage.svd.stage1"):
        factors = ge2tb(a, opts)
        if _metrics.enabled():
            jax.block_until_ready(factors.band)
    method = get_option(opts, "method_svd", MethodSVD.Auto)
    auto = method is MethodSVD.Auto
    # ge2tb leaves the middle factor upper-triangular-banded: only its
    # top n rows are nonzero, so stage 2 operates on the n×n head —
    # passed as the DEVICE array so the wavefront-chase backend never
    # pulls it to host (the host backends np.asarray it themselves)
    with _metrics.timer("stage.svd.stage2"):
        s, u_b, vh_b = _band_svd(factors.band[:n], factors.kd, jobu,
                                 jobvt, method, auto)
    dtype = factors.band.dtype
    # stage 2/3 may run in float64 internally (the HH fast path); the
    # dtype contract is LAPACK's: sigma in the real precision of A
    real_dt = np.zeros(0, dtype=dtype).real.dtype
    if not (jobu or jobvt):
        return jnp.asarray(s, dtype=real_dt), None, None
    u = vh = None
    with _metrics.timer("stage.svd.stage3"):
        if jobu:
            u2 = jnp.asarray(u_b)
            if m > n:
                u2 = jnp.concatenate(
                    [u2, jnp.zeros((m - n, u2.shape[1]), dtype=u2.dtype)],
                    axis=0)
            u = unmbr_ge2tb(Side.Left, Op.NoTrans, factors,
                            u2.astype(dtype))
        if jobvt:
            v = unmbr_ge2tb(Side.Right, Op.NoTrans, factors,
                            jnp.asarray(_ct(vh_b)).astype(dtype))
            vh = _ct(v)
        if _metrics.enabled():
            jax.block_until_ready([x for x in (u, vh) if x is not None])
    return jnp.asarray(s, dtype=real_dt), u, vh


#: Deprecated alias kept by the reference (``slate.hh``: ``gesvd``).
gesvd = svd
