"""Autotuned stage-2 bulge-chase dispatch shared by the eig and SVD
middles (single-chip and distributed).

The two-stage eig/SVD drivers historically pulled the band to host,
ran the bulge chase single-core in ``native/runtime.cc`` and shipped
the packed reflector log back to the device for the batched WY
back-transform (``unmtr_hb2st_hh``) — a host↔device tunnel on the
hottest sequential section.  This module is the one seam where that
choice is made:

* :func:`backend` resolves the autotuned ``chase`` site
  (:func:`slate_tpu.perf.autotune.choose_chase`) — candidates
  ``host_native`` (today's path) and ``pallas_wavefront`` (ONE Pallas
  invocation per chase chunk, aliased HBM band carry, log written
  directly into the padded device layout) — timed/persisted/forceable
  like ``lu_driver``.
* The ``*_device`` helpers run the device-resident chase and hand back
  ``(d, e, log)`` with the log STAYING on device — zero host repacking,
  zero tunnel.
* Every transfer of band/log state across the host↔device boundary
  performed by either path is counted into ``chase.host_bytes``
  (``metrics``), so the "zero tunnel on the device path" claim is
  observable in every bench JSON line; operand ingestion that the
  caller would do anyway (the O(n·kd) band upload of the distributed
  drivers) is counted under ``chase.ingest_bytes`` instead.

The Pallas kernels are fetched through :func:`autotune.kernel` — the
backend-registry guard keeps ``linalg/`` free of direct
``pallas_kernels`` imports.
"""

from __future__ import annotations

import os

import numpy as np

from ..perf import metrics
from ..perf.autotune import kernel as _kernel, select as _select

#: below this window width the patch/shear machinery of the wavefront
#: kernels has no room to work (and the chase is host-trivial anyway)
_MIN_KD = 4

#: HBM budget for the distributed drivers' checkpoint snapshots (the
#: chunk count of ``chase_chunk_bounds`` was tuned for HOST RAM, so at
#: the 65k north star ~11 live O(n·kd) device snapshots could crowd a
#: 16 GB chip): past the budget the snapshots spill to host — an
#: O(n·kd·nchunks) transfer counted into ``chase.host_bytes``, still
#: far below the O(n²) log tunnel this path deletes.
_SNAP_BUDGET_BYTES = float(os.environ.get(
    "SLATE_TPU_CHASE_SNAPSHOT_BUDGET_MB", "2048")) * 1e6


def snapshots_fit_device(nbytes_one: int, nchunks: int) -> bool:
    """True when every checkpoint snapshot of one chase can stay in
    device memory simultaneously (pass 1 holds all of them live until
    pass 2 consumes them in reverse)."""
    return float(nbytes_one) * max(nchunks, 1) <= _SNAP_BUDGET_BYTES


def snapshot_store(dev):
    """Spill one checkpoint snapshot to host (counted as tunnel)."""
    arr = np.array(dev)
    _count_tunnel(arr.nbytes)
    return arr


def snapshot_restore(arr: np.ndarray):
    """Re-upload one spilled snapshot for pass-2 log regeneration."""
    import jax.numpy as jnp

    _count_tunnel(arr.nbytes)
    return jnp.asarray(arr)


def eligible(n: int, kd: int, want_vectors: bool) -> bool:
    """Shape gate for the device chase: vectors wanted (values-only
    callers skip the log and the host chase is already O(n·kd)-cheap),
    a wide-enough band, and enough rows for at least one sweep."""
    return bool(want_vectors) and kd >= _MIN_KD and n > kd + 2


def backend(kind: str, n: int, kd: int, dtype, want_vectors: bool) -> str:
    """Resolve (and record) the chase decision for one problem."""
    return _select("chase", kind=kind, n=n, kd=kd, dtype=dtype,
                   eligible=eligible(n, kd, want_vectors))


def _count_tunnel(nbytes: int) -> None:
    metrics.inc("chase.host_bytes", float(nbytes), force=False)


def _mark_device_path() -> None:
    """The device path's observability contract: the dispatch counter
    ticks and ``chase.host_bytes`` materializes at 0 so
    ``metrics.snapshot()`` reports the zero explicitly."""
    metrics.inc("chase.dispatch.pallas_wavefront")
    metrics.inc("chase.host_bytes", 0.0)


def mark_host_path(kind: str, log_arrays=()) -> None:
    """Count a host-native chase dispatch: the packed reflector log is
    about to cross to the device for the WY back-transform (the tunnel
    this module exists to delete)."""
    metrics.inc("chase.dispatch.host_native")
    total = 0
    for a in log_arrays:
        arr = np.asarray(a) if a is not None else None
        if arr is not None:
            total += arr.nbytes
    _count_tunnel(total)


def split_hh_log(vt, kd: int, s0: np.ndarray):
    """Split a wavefront-kernel log ``(nsweeps, tmax, kd+1)`` into the
    ``(v3, t2, s0)`` triple :func:`slate_tpu.linalg.eig.unmtr_hb2st_hh`
    consumes — two device-side slices, no host repacking."""
    return vt[:, :, 1:], vt[:, :, 0], s0


def _log_s0(n: int, lo: int, hi: int) -> np.ndarray:
    """First-reflector row per sweep of a ``[lo, hi)`` range — the s0
    column of the padded log layout, shared by both chase kinds (each
    sweep's first window starts at sweep+1)."""
    hi = min(hi, max(n - 2, 0))
    return np.arange(lo + 1, hi + 1, dtype=np.int32)


# ---------------------------------------------------------------------------
# hb2st (Hermitian band → tridiagonal)
# ---------------------------------------------------------------------------

def hb2st_abw_from_dense(band, kd_eff: int):
    """WIDE lower-band storage ``(n, 2·kd+2)`` from a dense Hermitian
    band, built ON DEVICE (one gather) — the device-resident entry of
    the single-chip drivers; the dense band never visits the host."""
    import jax
    import jax.numpy as jnp

    band = jnp.asarray(band)
    n = band.shape[0]
    w = 2 * kd_eff + 2

    @jax.jit
    def pack(b):
        c = jnp.arange(n)[:, None]
        d = jnp.arange(w)[None, :]
        r = c + d
        valid = (d <= kd_eff) & (r < n)
        vals = b[jnp.clip(r, 0, n - 1), jnp.broadcast_to(c, r.shape)]
        if jnp.issubdtype(b.dtype, jnp.complexfloating):
            vals = jnp.where(d == 0, jnp.real(vals).astype(b.dtype), vals)
        return jnp.where(valid, vals, 0)

    return pack(band)


def hb2st_abw_from_ab(ab: np.ndarray, kd_eff: int):
    """WIDE device band storage from the distributed drivers' host
    ``(n, kd+2)`` lower storage — ONE O(n·kd) operand upload, counted
    as ingestion (the caller assembled the band on host regardless)."""
    import jax.numpy as jnp

    n = ab.shape[0]
    abw = np.zeros((n, 2 * kd_eff + 2), dtype=ab.dtype)
    w = min(ab.shape[1], kd_eff + 1)
    abw[:, :w] = ab[:, :w]
    metrics.inc("chase.ingest_bytes", float(abw.nbytes))
    return jnp.asarray(abw)


def tb2bd_st_from_ab(ab: np.ndarray, kd_eff: int):
    """Row-major general-band device storage from the distributed
    drivers' host ``(n, kd+3)`` upper storage (``ab[c, (c−r)+1]`` =
    A[r, c]) — ONE O(n·kd) operand upload, counted as ingestion."""
    import jax.numpy as jnp

    n = ab.shape[0]
    st = np.zeros((n, 3 * kd_eff + 2), dtype=np.float64)
    for dd in range(kd_eff + 1):
        st[:n - dd, dd + kd_eff] = ab[dd:, dd + 1]
    metrics.inc("chase.ingest_bytes", float(st.nbytes))
    return jnp.asarray(st)


def hb2st_device(abw_dev, kd_eff: int, j0: int = 0, j1=None,
                 want_log: bool = True):
    """One device-resident chase chunk over sweeps ``[j0, j1)``:
    returns ``(abw_dev', log)`` with ``log = (v3, t2, s0)`` device
    arrays (None when not ``want_log``) — ONE Pallas invocation."""
    import jax

    n = abw_dev.shape[0]
    if j1 is None:
        j1 = max(n - 2, 0)
    with metrics.timer("chase.hb2st"):
        abw_dev, vt = _kernel("hb2st_wavefront")(abw_dev, kd_eff, j0, j1)
        if metrics.enabled():
            # the kernel call is async: sync inside the timer so the
            # *_stage2_chase_s bench submetric measures the chase, not
            # its dispatch (off by default — zero sync points added)
            jax.block_until_ready((abw_dev, vt))
    _mark_device_path()
    if not want_log:
        return abw_dev, None
    return abw_dev, split_hh_log(vt, kd_eff, _log_s0(n, j0, j1))


def hb2st_d_e(abw_dev, n: int):
    """Pull the chased tridiagonal (d, e) to host — the O(n) handoff to
    the LAPACK tridiagonal solve, NOT part of the band/log tunnel."""
    import jax.numpy as jnp

    d = np.array(jnp.real(abw_dev[:, 0]))
    e_c = np.array(abw_dev[:n - 1, 1])
    return d, e_c


# ---------------------------------------------------------------------------
# tb2bd (triangular band → bidiagonal)
# ---------------------------------------------------------------------------

def tb2bd_st_from_dense(band_sq, kd_eff: int):
    """Row-major general-band storage ``(n, 3·kd+2)`` from the dense
    upper-triangular band middle factor, built ON DEVICE."""
    import jax
    import jax.numpy as jnp

    band_sq = jnp.asarray(band_sq)
    n = band_sq.shape[0]
    w = 3 * kd_eff + 2

    @jax.jit
    def pack(b):
        r = jnp.arange(n)[:, None]
        d = jnp.arange(w)[None, :]
        c = r + d - kd_eff
        valid = (c >= r) & (c <= r + kd_eff) & (c >= 0) & (c < n)
        vals = b[jnp.broadcast_to(r, c.shape), jnp.clip(c, 0, n - 1)]
        return jnp.where(valid, vals, 0)

    return pack(band_sq)


def tb2bd_device(st_dev, kd_eff: int, s0: int = 0, s1=None,
                 want_log: bool = True):
    """One device-resident bidiagonal chase chunk over sweeps
    ``[s0, s1)``: returns ``(st_dev', ulog, vlog)`` with each log a
    ``(v3, t2, s0)`` device triple (None when not ``want_log``)."""
    import jax

    n = st_dev.shape[0]
    if s1 is None:
        s1 = max(n - 1, 0)
    with metrics.timer("chase.tb2bd"):
        st_dev, ut, vt = _kernel("tb2bd_wavefront")(st_dev, kd_eff, s0, s1)
        if metrics.enabled():
            jax.block_until_ready((st_dev, ut, vt))
    _mark_device_path()
    if not want_log:
        return st_dev, None, None
    rows = _log_s0(n, s0, s1)
    return (st_dev, split_hh_log(ut, kd_eff, rows),
            split_hh_log(vt, kd_eff, rows))


def tb2bd_d_e(st_dev, kd_eff: int, n: int):
    """(d, e) of the chased bidiagonal — the O(n) stage-3 handoff."""
    d = np.array(st_dev[:, kd_eff])
    e = np.array(st_dev[:n - 1, kd_eff + 1])
    return d, e
