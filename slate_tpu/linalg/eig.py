"""Hermitian eigensolver family — the two-stage path of the reference:

* ``src/heev.cc`` (driver chain ``:104-176``): ``he2hb`` (dense→band,
  ``src/he2hb.cc:53-177``) → ``hb2st`` (band→tridiag bulge chasing,
  ``src/hb2st.cc:23-90``) → tridiagonal solve (``sterf`` no-vectors /
  ``steqr2`` QR / ``stedc`` divide-and-conquer) → back-transform
  ``unmtr_hb2st`` then ``unmtr_he2hb`` (``src/heev.cc:168-171``).
* generalized ``hegv/sygv`` via ``hegst`` (``src/hegst.cc``, 331 LoC).

TPU-first design stance:

* **Stage 1 (he2hb) carries the O(n³) flops** and runs on the MXU: each
  panel is a compact-WY Householder QR (reusing
  :func:`slate_tpu.linalg.qr.geqrf_rec`) and the two-sided trailing
  update is three large matmuls + a her2k-shaped symmetric update —
  exactly the reference's ``internal_he2hb_hemm/her2k`` tile batch
  turned into whole-trailing-matrix GEMMs.
* **Stage 2 (hb2st) is O(n²·nb) and sequential** — the reference also
  runs it on a *single node* after gathering the band
  (``src/heev.cc:111-113``); we mirror that: the band is pulled to host
  and reduced by windowed Givens bulge-chasing (the wavefront of
  ``src/hb2st.cc:23-90`` collapsed to its sequential schedule), logging
  rotations for the back-transform like the reference's V storage.
* **Tridiagonal solve on host LAPACK** (scipy ``stev/stevd/stebz/stemr``)
  — the reference likewise calls LAPACK ``sterf/steqr2/stedc`` on rank 0
  (``src/heev.cc:141-176``).
* **Back-transforms run on device again**: ``unmtr_hb2st`` applies the
  logged rotations; ``unmtr_he2hb`` is a chain of block reflectors
  (pure MXU matmuls).
"""

from __future__ import annotations

from functools import partial
from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..enums import Diag, MethodEig, Op, Side, Uplo
from ..exceptions import SlateError
from ..matrix import BaseTrapezoidMatrix, as_array
from ..options import Options, get_option
from ..perf import metrics as _metrics
from ..perf.metrics import instrument_driver
from ..ops import blocks
from ..ops.blocks import _ct, matmul
from ..ops.tile_ops import hermitize
from .blas3 import _nb, _wrap_like
from .qr import _unit_lower, geqrf_rec, larft_rec


class He2hbFactors(NamedTuple):
    """Stage-1 output: band matrix + the block reflectors that made it.

    ``band`` is the dense Hermitian array with lower bandwidth ``kd``;
    ``panels`` holds one ``(row0, V, T)`` triple per panel with
    Q_k = I − V·T·Vᴴ acting on rows ``row0:`` (reference stores the same
    V's in the zeroed sub-band and T via ``internal_ttqrt``-style
    triangles, ``src/he2hb.cc:53-177``).
    """

    band: jnp.ndarray
    kd: int
    panels: Tuple[Tuple[int, jnp.ndarray, jnp.ndarray], ...]


def _hermitian_full(a):
    if isinstance(a, BaseTrapezoidMatrix):
        return hermitize(a.logical_uplo, a.array)
    return as_array(a)  # raw array: assume full Hermitian given


def he2hb(a, opts: Optional[Options] = None) -> He2hbFactors:
    """Reduce a Hermitian matrix to Hermitian band form (bandwidth = nb)
    by a unitary congruence A = Q₁·B·Q₁ᴴ — reference ``slate::he2hb``
    (``src/he2hb.cc:53-177``).

    Per panel k: QR-factor the block column below the band
    (``internal::geqrf`` panel), then apply the block reflector
    two-sidedly to the trailing matrix via the her2k update
    B ← B − V·Wᴴ − W·Vᴴ with Y = B·V·T, S = Tᴴ·(Vᴴ·Y),
    W = Y − ½·V·S (the reference's ``he2hb_hemm`` + ``he2hb_her2k``
    tile ops fused into whole-matrix GEMMs).
    """

    nb = _nb(a, opts)
    full = _hermitian_full(a)
    n = full.shape[-1]
    if full.shape[-2] != n:
        raise SlateError(f"he2hb requires square, got {full.shape}")
    band, vts = _he2hb_impl(full, nb)
    # row0 is derivable from V's row count (V spans rows r0..n); store it
    # for convenience but the shapes stay the single source of truth
    panels = tuple((n - v.shape[0], v, t) for v, t in vts)
    return He2hbFactors(band=band, kd=nb, panels=panels)


@partial(jax.jit, static_argnums=1)
def _he2hb_impl(full, nb: int):
    """The whole panel loop under one jit: per-panel ops have static
    (shrinking) shapes, XLA schedules the chain, and there is exactly
    one device dispatch per call instead of dozens per panel (which over
    a ~100 ms host↔device tunnel dominated the wall time)."""

    n = full.shape[-1]
    vts = []
    for j0 in range(0, max(n - nb, 0), nb):
        r0 = j0 + nb
        w = min(nb, n - j0)
        if n - r0 <= 0:
            break
        # panel QR of the block column below the band
        p = full[r0:, j0:j0 + w]
        f, tau = geqrf_rec(p, nb)
        k = min(p.shape[0], w)
        v = _unit_lower(f, k)
        t = larft_rec(v, tau)
        r_part = jnp.triu(f[:w]) if f.shape[0] >= w else jnp.triu(f)
        # write back [R; 0] into the panel
        zeros = jnp.zeros((p.shape[0] - r_part.shape[0], w), full.dtype)
        newp = jnp.concatenate([r_part, zeros], axis=0)
        full = full.at[r0:, j0:j0 + w].set(newp)
        full = full.at[j0:j0 + w, r0:].set(_ct(newp))
        # two-sided trailing update B ← QᴴBQ (her2k form)
        b = full[r0:, r0:]
        y = matmul(b, matmul(v, t))
        s = matmul(_ct(t), matmul(_ct(v), y))
        wmat = y - 0.5 * matmul(v, s)
        b = b - matmul(v, _ct(wmat)) - matmul(wmat, _ct(v))
        full = full.at[r0:, r0:].set(b)
        vts.append((v, t))
    # clamp to the band (numerical zeros outside) and re-hermitize
    i = jnp.arange(n)
    mask = jnp.abs(i[:, None] - i[None, :]) <= nb
    band = jnp.where(mask, full, 0)
    band = 0.5 * (band + _ct(band))
    return band, tuple(vts)


def unmtr_he2hb(side: Side, op: Op, factors: He2hbFactors, c,
                opts: Optional[Options] = None):
    """Apply Q₁ (or Q₁ᴴ) from :func:`he2hb` — reference
    ``slate::unmtr_he2hb`` (``src/unmtr_he2hb.cc``): a chain of block
    reflectors, each three matmuls."""

    cv = as_array(c)
    if side is not Side.Left:
        # C·Q = (Qᴴ·Cᴴ)ᴴ
        flip = Op.NoTrans if op is not Op.NoTrans else Op.ConjTrans
        return _ct(unmtr_he2hb(Side.Left, flip, factors, _ct(cv), opts))
    vts = tuple((v, t) for _, v, t in factors.panels)
    from .qr import apply_reflector_chain
    return apply_reflector_chain(vts, cv, op is Op.NoTrans)


# ---------------------------------------------------------------------------
# Stage 2: band → tridiagonal (host, Givens bulge chasing)
# ---------------------------------------------------------------------------

class Hb2stRotations(NamedTuple):
    """Rotation log of :func:`hb2st`: Q₂ = G₁ᴴ·G₂ᴴ⋯G_Nᴴ·diag(phase);
    each Gₗ acts in plane (iₗ−1, iₗ)."""

    planes: np.ndarray   # int32[N] — the i of each rotation
    cs: np.ndarray       # real[N]
    ss: np.ndarray       # scalar[N] (complex for Hermitian input)
    phase: np.ndarray    # complex[n] diagonal making the tridiagonal real
    kd: int = 0          # chase bandwidth (0 = generic/legacy log)


def _givens(f, g):
    """Complex-safe Givens: returns (c real, s) with
    [[c, s], [−s̄, c]]·[f, g]ᵀ = [r, 0]."""

    absf, absg = abs(f), abs(g)
    if absg == 0.0:
        return 1.0, 0.0 * g
    r = np.hypot(absf, absg)
    signf = f / absf if absf != 0 else 1.0
    c = absf / r
    s = signf * np.conj(g) / r
    return c, s


def _phase_tridiag(e_c, n, dt):
    """Phase-normalize a complex subdiagonal to real (LAPACK hbtrd's
    final step); shared by the compiled and Python hb2st paths."""

    phase = np.ones((n,), dtype=dt)
    if np.iscomplexobj(np.zeros((), dtype=dt)):
        for j in range(n - 1):
            val = e_c[j] * phase[j]
            absv = abs(val)
            phase[j + 1] = val / absv if absv != 0 else 1.0
            e_c[j] = absv
    return phase


def _hb2st_ab(ab: np.ndarray, kd_eff: int, want_rots: bool = True):
    """Compiled stage 2 core on prepared lower-band storage
    ``ab[(n, kd_eff+2)]`` (modified in place) — O(n·kd) end to end."""

    from .. import native

    n = ab.shape[0]
    with _metrics.timer("chase.hb2st"):
        planes, cs, ss = native.hb2st_banded(ab, n, kd_eff, want_rots)
    d = np.real(ab[:, 0]).copy()
    e_c = ab[:n - 1, 1].copy()
    phase = _phase_tridiag(e_c, n, ab.dtype)
    e = np.real(e_c)
    return d, e, Hb2stRotations(planes=planes, cs=cs, ss=ss, phase=phase,
                                kd=kd_eff)


def _hb2st_native(a: np.ndarray, kd: int, want_rots: bool = True):
    """Compiled stage 2 from a dense band matrix: pack the band storage
    and run :func:`_hb2st_ab` (``native/runtime.cc`` ``slate_hb2st_*``)."""

    n = a.shape[0]
    dt = np.complex128 if np.iscomplexobj(a) else np.float64
    kd_eff = min(kd, n - 1)
    ab = np.zeros((n, kd_eff + 2), dtype=dt, order="C")
    for dd in range(kd_eff + 1):
        ab[:n - dd, dd] = np.diagonal(a, -dd)
    return _hb2st_ab(ab, kd_eff, want_rots)


def hb2st(band, kd: int, want_rots: bool = True
          ) -> Tuple[np.ndarray, np.ndarray, Hb2stRotations]:
    """Reduce a Hermitian band matrix (lower bandwidth ``kd``) to real
    symmetric tridiagonal — reference ``slate::hb2st``
    (``src/hb2st.cc:23-90`` bulge-chasing sweeps run on host like the
    reference's single-node stage 2, ``src/heev.cc:113``; compiled via
    the native runtime when available, Python schedule as fallback).

    Returns ``(d, e, rotations)`` with A_band = Q₂·T·Q₂ᴴ.
    """

    a = np.array(band)
    n = a.shape[0]
    from .. import native
    if native.available() and n > 2 and kd >= 2:
        return _hb2st_native(a, kd, want_rots)
    planes: List[int] = []
    cs: List[float] = []
    ss: List[complex] = []
    for bw in range(kd, 1, -1):
        for j in range(0, n - bw):
            col, i = j, j + bw
            while True:
                c, s = _givens(a[i - 1, col], a[i, col])
                g = np.array([[c, s], [-np.conj(s), c]])
                lo = max(0, i - 1 - bw - 1)
                hi = min(n, i + bw + 2)
                a[[i - 1, i], lo:hi] = g @ a[[i - 1, i], lo:hi]
                a[lo:hi, [i - 1, i]] = a[lo:hi, [i - 1, i]] @ np.conj(g.T)
                planes.append(i)
                cs.append(c)
                ss.append(s)
                if i + bw >= n:
                    break
                col, i = i - 1, i + bw
    # phase-scale the subdiagonal real (LAPACK hbtrd's final step)
    d = np.real(np.diagonal(a)).copy()
    e_c = np.diagonal(a, -1).copy()
    phase = _phase_tridiag(e_c, n, a.dtype)
    e = np.real(e_c)
    rots = Hb2stRotations(
        planes=np.asarray(planes, dtype=np.int32),
        cs=np.asarray(cs, dtype=np.float64),
        ss=np.asarray(ss),
        phase=phase,
    )
    return d, e, rots


def _hb_sweep_counts(n, kd, j0: int = 0, j1=None):
    """Per-sweep reflector counts of the symmetric Householder chase
    (mirrors the deterministic window logic; boundary inference from
    row0 alone is ambiguous when consecutive sweeps have one step
    each).  ``j0``/``j1`` restrict to a sweep range — the checkpointed
    streaming back-transform packs one chunk at a time."""
    counts = []
    if j1 is None:
        j1 = max(n - 2, 0)
    for j in range(j0, min(j1, max(n - 2, 0))):
        L = min(kd, n - 1 - j)
        if L < 2:
            continue
        cnt, r0 = 1, j + 1
        while True:
            r1 = r0 + L
            lt = min(kd, n - r1)
            if lt < 2:
                break
            cnt += 1
            r0, L = r1, lt
        counts.append(cnt)
    return counts


def _pack_hh_log(v, tau, row0, length, n, kd, counts=None):
    """Group the flat reflector log by sweep into padded (nsweeps, tmax,
    kd) tensors.  Within one sweep the windows are adjacent disjoint
    kd-strided rows starting at the sweep's first row — the property
    that makes the whole sweep one batched WY apply."""

    row0 = np.asarray(row0)
    if len(row0) == 0:
        return (np.zeros((0, 1, kd)), np.zeros((0, 1)),
                np.zeros((0,), np.int32))
    if counts is None:
        counts = _hb_sweep_counts(n, kd)
    counts = np.asarray(counts)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    assert counts.sum() == len(row0), (counts.sum(), len(row0))
    nsweeps = len(starts)
    tmax = int(counts.max())
    v3 = np.zeros((nsweeps, tmax, kd), dtype=v.dtype)
    t2 = np.zeros((nsweeps, tmax), dtype=tau.dtype)
    s0 = np.zeros((nsweeps,), dtype=np.int32)
    for s, (b, c) in enumerate(zip(starts, counts)):
        v3[s, :c] = v[b:b + c]
        t2[s, :c] = tau[b:b + c]
        s0[s] = row0[b]
    return v3, t2, s0


def unmtr_hb2st_hh(v3, t2, s0, z, kd: int):
    """Back-transform through the Householder chase ON DEVICE:
    Z ← Q₂·Z as one ``lax.scan`` over sweeps (reverse order), each step
    a batched WY apply — two batched contractions over the sweep's
    disjoint reflector windows (reference ``src/unmtr_hb2st.cc`` applies
    its V blocks the same way; here the accelerator does it instead of
    single-core rotation streaming)."""

    import jax
    from jax import lax as _lax

    v3 = jnp.asarray(v3)
    t2 = jnp.asarray(t2)
    s0 = jnp.asarray(s0)
    z = jnp.asarray(z)
    if v3.shape[0] == 0:
        return z
    # complex reflectors (the zhbtrd-style chase) promote a real Z
    zdt = jnp.promote_types(z.dtype, v3.dtype)
    z = z.astype(zdt)
    nsweeps, tmax, _ = v3.shape
    n, ncols = z.shape
    win = tmax * kd
    zp = jnp.zeros((n + win, ncols), z.dtype).at[:n].set(z)

    def body(zc, inp):
        vj, tj, start = inp
        zw = _lax.dynamic_slice(zc, (start, jnp.zeros((), start.dtype)),
                                (win, ncols))
        zw = zw.reshape(tmax, kd, ncols)
        u = jnp.einsum("tk,tkc->tc", jnp.conj(vj), zw,
                       precision=_lax.Precision.HIGHEST)
        zw = zw - vj[:, :, None] * (tj[:, None] * u)[:, None, :]
        zc = _lax.dynamic_update_slice(zc, zw.reshape(win, ncols),
                                       (start, jnp.zeros((), start.dtype)))
        return zc, None

    out, _ = _lax.scan(body, zp, (v3[::-1], t2[::-1], s0[::-1]))
    return out[:n]


def _hb2st_hh_ab(abw: np.ndarray, kd_eff: int):
    """Compiled Householder stage 2 on WIDE band storage
    ``abw[(n, 2·kd+2)]`` (modified in place) — the real-f64 fast path
    whose log back-transforms on device.  Returns
    ``(d, e, (v3, t2, s0))``."""

    from .. import native
    from . import _chase

    n = abw.shape[0]
    with _metrics.timer("chase.hb2st"):
        v, tau, row0, length = native.hb2st_hh_banded(abw, n, kd_eff)
    d = abw[:, 0].copy()
    e = abw[:n - 1, 1].copy()
    log = _pack_hh_log(v, tau, row0, length, n, kd_eff)
    _chase.mark_host_path("hb2st", log)
    return d, e, log


def unmtr_hb2st(rots: Hb2stRotations, z: np.ndarray) -> np.ndarray:
    """Back-transform tridiagonal eigenvectors through the bulge-chase:
    Z_band = Q₂·Z — reference ``slate::unmtr_hb2st``
    (``src/unmtr_hb2st.cc``, applied to the 1-D-distributed Z)."""

    from .. import native
    if native.available():
        cplx = (np.iscomplexobj(rots.phase) or np.iscomplexobj(rots.ss)
                or np.iscomplexobj(np.asarray(z)))
        dt = np.complex128 if cplx else np.float64
        zz = np.asarray(z, dtype=dt) * rots.phase[:, None].astype(dt)
        if len(rots.planes):
            zz = native.apply_rot_seq(zz, rots.planes, rots.cs, rots.ss, 0,
                                      kd=getattr(rots, "kd", 0))
        return zz
    z = np.asarray(z).astype(rots.phase.dtype if np.iscomplexobj(rots.phase)
                             else z.dtype)
    z = rots.phase[:, None] * z
    for idx in range(len(rots.planes) - 1, -1, -1):
        i = int(rots.planes[idx])
        c, s = rots.cs[idx], rots.ss[idx]
        # apply Gᴴ = [[c, −s], [s̄, c]] to rows (i−1, i)
        gh = np.array([[c, -s], [np.conj(s), c]])
        z[[i - 1, i], :] = gh @ z[[i - 1, i], :]
    return z


# ---------------------------------------------------------------------------
# Tridiagonal solvers (host LAPACK, like the reference's rank-0 calls)
# ---------------------------------------------------------------------------

def sterf(d, e) -> np.ndarray:
    """Eigenvalues of a real symmetric tridiagonal (no vectors) —
    reference's LAPACK ``sterf`` call (``src/heev.cc:141-176``)."""

    from scipy.linalg import eigvalsh_tridiagonal
    d = np.asarray(d, dtype=np.float64)
    e = np.asarray(e, dtype=np.float64)
    if d.size == 1:
        return d
    return eigvalsh_tridiagonal(d, e, lapack_driver="sterf")


def steqr(d, e, want_z: bool = True):
    """Implicit-QR tridiagonal eigensolver — reference ``steqr2``
    (modified Fortran kernels ``src/?steqr2.f``)."""
    return _tridiag_solve(d, e, want_z, "stev")


def stedc(d, e, want_z: bool = True):
    """Divide-and-conquer tridiagonal eigensolver — reference ``stedc``
    (``src/stedc.cc``), implemented with the same stage decomposition
    (``stedc_solve/merge/deflate/secular/sort/z_vector``) in
    :mod:`slate_tpu.linalg._stedc`."""
    from ._stedc import stedc as _dc_stedc
    return _dc_stedc(d, e, want_z)


def stemr(d, e, want_z: bool = True):
    """MRRR tridiagonal eigensolver (LAPACK ``stemr``)."""
    return _tridiag_solve(d, e, want_z, "stemr")


def stebz_stein(d, e):
    """Bisection + inverse iteration (LAPACK ``stebz``+``stein``)."""
    return _tridiag_solve(d, e, True, "stebz")


def _tridiag_solve(d, e, want_z, driver):
    from scipy.linalg import eigh_tridiagonal, eigvalsh_tridiagonal
    d = np.asarray(d, dtype=np.float64)
    e = np.asarray(e, dtype=np.float64)
    if d.size == 1:
        return (d, np.ones((1, 1))) if want_z else d
    def call(fn, drv):
        try:
            return fn(d, e, lapack_driver=drv)
        except ValueError as err:
            # scipy >= 1.14 dropped stevd/stevr from the accepted driver
            # set; 'auto' (stemr/stebz) is always valid and numerically
            # interchangeable here
            if "lapack_driver" not in str(err) or drv == "auto":
                raise
            return fn(d, e, lapack_driver="auto")

    if not want_z:
        vdriver = driver if driver in ("stev", "stevd", "stebz") else "auto"
        return call(eigvalsh_tridiagonal, vdriver)
    return call(eigh_tridiagonal, driver)


_EIG_DRIVERS = {
    MethodEig.QR: steqr,
    MethodEig.DC: stedc,
    MethodEig.MRRR: stemr,
    MethodEig.Bisection: lambda d, e, want_z=True: stebz_stein(d, e),
}


# ---------------------------------------------------------------------------
# Drivers
# ---------------------------------------------------------------------------

#: above this size heev's Auto method solves the band stage with one
#: host-LAPACK hbevd call instead of the staged hb2st chain (tests lower
#: it to cover the fast path)
_BAND_SOLVER_MIN_N = 512


def _band_eig(band, kd: int, jobz: bool, method, auto: bool):
    """Stage 2+3 on the band matrix, shared by single-chip :func:`heev`
    and the distributed ``pheev``: band → tridiag → solve →
    back-transform through the bulge-chase.  Returns ``(w, z_band)``
    (``z_band`` None when not ``jobz``; a device array on the
    device-resident chase path, numpy otherwise).

    The autotuned ``chase`` site decides the stage-2 backend first:
    ``pallas_wavefront`` keeps the band ON DEVICE end to end (packed on
    device, chased by one Pallas invocation, reflector log consumed by
    the WY back-transform with zero host repacking — only the O(n)
    tridiagonal visits the host); ``host_native`` is the historical
    single-node path below (the reference's stance,
    ``src/heev.cc:113``).

    Large-n Auto fast path (host route only): one host-LAPACK hbevd
    call (scipy eig_banded) where the compiled stage 2 is unavailable —
    the Python Givens sweeps cost O(n²·kd) interpreter steps.
    """

    from .. import native
    from . import _chase

    n = int(band.shape[0])
    kd_dev = min(kd, n - 1)
    real = not np.issubdtype(np.dtype(band.dtype), np.complexfloating)
    if n > 2 and kd_dev >= 2 and _chase.backend(
            "hb2st", n, kd_dev, band.dtype,
            jobz and real) == "pallas_wavefront":
        abw = _chase.hb2st_abw_from_dense(band, kd_dev)
        abw, log = _chase.hb2st_device(abw, kd_dev)
        d, e = _chase.hb2st_d_e(abw, n)
        return _stage3_eig_hh(d, e, log, kd_dev, method, auto)
    band_np = np.asarray(band)
    # The scipy hbevd bypass survives only where the compiled stage 2 is
    # unavailable (no toolchain); with the native runtime the staged
    # chain is both the default and the faster path.
    if auto and n > _BAND_SOLVER_MIN_N and not native.available():
        from scipy.linalg import eig_banded, eigvals_banded
        kd2 = min(kd, n - 1)
        bands = np.asarray(
            [np.concatenate([np.diagonal(band_np, -k),
                             np.zeros(k, band_np.dtype)])
             for k in range(kd2 + 1)])
        if not jobz:
            w = eigvals_banded(bands, lower=True)
            return np.sort(np.real(w)), None
        w, z_band = eig_banded(bands, lower=True)
        return np.real(w), z_band
    import jax as _jax
    if jobz and band_np.dtype == np.float64 and native.available() \
            and n > 2 and min(kd, n - 1) >= 2 \
            and _jax.default_backend() != "cpu":
        # route through the band-storage path so the real-f64 case gets
        # the Householder chase + on-device WY back-transform
        kd_eff = min(kd, n - 1)
        ab = np.zeros((n, kd_eff + 2), dtype=np.float64)
        for dd in range(kd_eff + 1):
            ab[:n - dd, dd] = np.real(np.diagonal(band_np, -dd))
        return _band_eig_ab(ab, kd_eff, jobz, method, auto)
    d, e, rots = hb2st(band_np, kd, want_rots=jobz)
    return _stage3_eig(d, e, rots, jobz, method, auto)


def _stage3_eig(d, e, rots, jobz, method, auto):
    """Tridiagonal solve + bulge-chase back-transform (stage 3)."""

    if not jobz:
        if method in (MethodEig.QR, MethodEig.Bisection):
            w = sterf(d, e)
        elif method is MethodEig.MRRR:
            w = _tridiag_solve(d, e, False, "stemr")
        else:
            w = _tridiag_solve(d, e, False, "stevd")
        return np.sort(w), None
    if auto:
        # Auto = fastest correct: LAPACK D&C (stevd) for the tridiagonal
        w, z_tri = _tridiag_solve(d, e, True, "stevd")
    else:
        w, z_tri = _EIG_DRIVERS[method](d, e)
    z_band = unmtr_hb2st(rots, z_tri)
    return np.asarray(w), z_band


def _stage3_eig_hh(d, e, log, kd_eff: int, method, auto: bool):
    """Tridiagonal solve + batched-WY back-transform for the
    Householder-chase paths; ``log`` is the ``(v3, t2, s0)`` triple —
    host numpy (native chase) or device arrays (wavefront kernel), the
    applier consumes either without repacking."""

    if auto or method not in _EIG_DRIVERS:
        w, z_tri = _tridiag_solve(d, e, True, "stevd")
    else:
        w, z_tri = _EIG_DRIVERS[method](d, e)
    z_band = unmtr_hb2st_hh(*log, z_tri, kd_eff)
    return np.asarray(w), z_band


def _band_eig_ab(ab, kd_eff: int, jobz: bool, method, auto: bool):
    """Stage 2+3 from O(n·kd) band storage directly (the distributed
    drivers\' path — no dense n×n host operand is ever built when the
    compiled stage 2 is available).

    Real f64 with vectors takes the Householder chase whose reflector
    log back-transforms ON DEVICE as batched WY gemms
    (:func:`unmtr_hb2st_hh`) — the round-3 answer to the single-core
    rotation-streaming applier.  Complex (and values-only, which needs
    no log at all) keeps the Givens chase.
    """

    from .. import native
    from . import _chase

    n = ab.shape[0]
    if not (native.available() and n > 2 and kd_eff >= 2):
        # fallback (no toolchain / tiny n): reconstruct the dense band —
        # this path only runs where the dense operand is small
        dense = np.zeros((n, n), dtype=ab.dtype)
        idx = np.arange(n)
        for dd in range(min(kd_eff, n - 1) + 1):
            dense[idx[:n - dd] + dd, idx[:n - dd]] = ab[:n - dd, dd]
        dense = dense + np.tril(dense, -1).conj().T
        return _band_eig(dense, kd_eff, jobz, method, auto)
    import jax as _jax
    if jobz and ab.dtype == np.float64 and _chase.backend(
            "hb2st", n, kd_eff, ab.dtype, True) == "pallas_wavefront":
        # device-resident wavefront chase: one O(n·kd) operand upload,
        # then the band, log and back-transform never leave the device
        abw_dev, log = _chase.hb2st_device(
            _chase.hb2st_abw_from_ab(ab, kd_eff), kd_eff)
        d, e = _chase.hb2st_d_e(abw_dev, n)
        return _stage3_eig_hh(d, e, log, kd_eff, method, auto)
    if jobz and ab.dtype == np.float64 \
            and _jax.default_backend() != "cpu":
        # Householder chase + device WY back-transform: a win only when
        # an accelerator applies the log (the scan applier is HBM-bound;
        # on host the cache-blocked Givens applier is far faster)
        abw = np.zeros((n, 2 * kd_eff + 2), dtype=np.float64)
        abw[:, :min(ab.shape[1], kd_eff + 1)] = \
            ab[:, :min(ab.shape[1], kd_eff + 1)]
        d, e, log = _hb2st_hh_ab(abw, kd_eff)
        return _stage3_eig_hh(d, e, log, kd_eff, method, auto)
    d, e, rots = _hb2st_ab(ab, kd_eff, want_rots=jobz)
    return _stage3_eig(d, e, rots, jobz, method, auto)


@instrument_driver("heev")
def heev(a, jobz: bool = True, opts: Optional[Options] = None):
    """Hermitian eigensolver — reference ``slate::heev``
    (``src/heev.cc``; two-stage chain ``:104-176``).

    Returns ``(w, Z)`` with eigenvalues ascending; ``Z`` is None when
    ``jobz`` is False.  Method selection mirrors ``MethodEig``
    (``enums.hh:60-63``): D&C by default, QR / Bisection / MRRR on
    request.

    Driver selection consults the autotuned ``eig_driver`` site
    (``twostage`` — the band-reduction chain below — vs ``qdwh``, the
    gemm-rich spectral divide-and-conquer of
    :mod:`slate_tpu.linalg.polar`); an ``eig_driver`` per-call option
    or a ``SLATE_TPU_AUTOTUNE_FORCE=eig_driver=...`` pin overrides.
    """

    method = get_option(opts, "method_eig", MethodEig.Auto)
    driver = get_option(opts, "eig_driver", None)
    if driver is None:
        from ..perf import autotune

        av = as_array(a)
        driver = autotune.select("eig_driver", n=av.shape[-1],
                                 dtype=av.dtype,
                                 eligible=method is MethodEig.Auto)
    if driver == "qdwh":
        from .polar import heev_qdwh

        return heev_qdwh(a, jobz=jobz, opts=opts)
    return _heev_twostage(a, jobz, opts)


def _heev_twostage(a, jobz: bool, opts: Optional[Options]):
    """The two-stage chain (he2hb → band eig → back-transform) — the
    ``eig_driver=twostage`` backend, and the crossover leaf the QDWH
    recursion bottoms out on."""

    method = get_option(opts, "method_eig", MethodEig.Auto)
    auto = method is MethodEig.Auto
    if auto:
        method = MethodEig.DC
    with _metrics.timer("stage.heev.stage1"):
        factors = he2hb(a, opts)
        if _metrics.enabled():
            jax.block_until_ready(factors.band)
    with _metrics.timer("stage.heev.stage2"):
        w, z_band = _band_eig(factors.band, factors.kd, jobz, method, auto)
    if not jobz:
        return jnp.asarray(w), None
    dtype = factors.band.dtype
    with _metrics.timer("stage.heev.stage3"):
        z = unmtr_he2hb(Side.Left, Op.NoTrans, factors,
                        jnp.asarray(z_band, dtype=dtype), opts)
        if _metrics.enabled():
            jax.block_until_ready(z)
    return jnp.asarray(w), z


def syev(a, jobz: bool = True, opts: Optional[Options] = None):
    """Real-symmetric alias — reference ``slate::syev``."""
    return heev(a, jobz, opts)


def heev_vals(a, opts: Optional[Options] = None):
    """Eigenvalues only (reference simplified API ``eig_vals``)."""
    return heev(a, jobz=False, opts=opts)[0]


def hegst(itype: int, a, b_factor, opts: Optional[Options] = None):
    """Reduce a generalized Hermitian-definite eigenproblem to standard
    form — reference ``slate::hegst`` (``src/hegst.cc``, 331 LoC).

    itype 1:  A ← L⁻¹·A·L⁻ᴴ   (for A·x = λ·B·x)
    itype 2/3: A ← Lᴴ·A·L      (for A·B·x = λ·x / B·A·x = λ·x)

    ``b_factor`` is the Cholesky factor of B (lower).  Expressed as two
    whole-matrix triangular solves / multiplies — the blocked recursion
    in :mod:`slate_tpu.ops.blocks` supplies the tile-level algorithm.
    """

    nb = _nb(a, opts)
    av = _hermitian_full(a)
    lv = jnp.tril(as_array(b_factor))
    if itype == 1:
        w = blocks.trsm_rec(Side.Left, Uplo.Lower, Diag.NonUnit, lv, av, nb)
        out = blocks.trsm_rec(Side.Right, Uplo.Upper, Diag.NonUnit,
                              _ct(lv), w, nb)
    elif itype in (2, 3):
        w = blocks.trmm_rec(Side.Left, Uplo.Upper, Diag.NonUnit, _ct(lv), av, nb)
        out = blocks.trmm_rec(Side.Right, Uplo.Lower, Diag.NonUnit, lv, w, nb)
    else:
        raise SlateError(f"hegst: invalid itype {itype}")
    out = 0.5 * (out + _ct(out))
    return out


def hegv(a, b, itype: int = 1, jobz: bool = True,
         opts: Optional[Options] = None):
    """Generalized Hermitian-definite eigensolver — reference
    ``slate::hegv`` (``src/hegv.cc``): potrf(B) → hegst → heev →
    back-substitute eigenvectors."""

    from .cholesky import potrf
    lfac = potrf(b, opts)
    lv = jnp.tril(as_array(lfac))
    nb = _nb(a, opts)
    c = hegst(itype, a, lv, opts)
    w, z = heev(c, jobz, opts)
    if not jobz:
        return w, None
    zv = as_array(z)
    if itype in (1, 2):
        # x = L⁻ᴴ·y
        zv = blocks.trsm_rec(Side.Left, Uplo.Upper, Diag.NonUnit, _ct(lv),
                             zv, nb)
    else:
        zv = blocks.trmm_rec(Side.Left, Uplo.Lower, Diag.NonUnit, lv, zv, nb)
    return w, zv


def sygv(a, b, itype: int = 1, jobz: bool = True,
         opts: Optional[Options] = None):
    """Real-symmetric generalized alias — reference ``slate::sygv``."""
    return hegv(a, b, itype, jobz, opts)


def sygst(itype: int, a, b_factor, opts: Optional[Options] = None):
    """Real-symmetric alias of :func:`hegst` — reference ``slate::sygst``
    (``include/slate/slate.hh``)."""
    return hegst(itype, a, b_factor, opts)
