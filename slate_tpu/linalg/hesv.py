"""Hermitian-indefinite solvers: hetrf / hetrs / hesv (+ sy aliases) —
reference ``src/hetrf.cc`` (625 LoC), ``src/hetrs.cc``, ``src/hesv.cc``:
Aasen-style L·T·Lᴴ factorization with a banded T and a band solve.

TPU-native design stance: the reference's blocked Aasen builds a
bandwidth-nb T and solves it with ``gbtrf/gbtrs``; pivoting makes the
panel control-flow heavy.  Here the factorization is a **pivoted
Parlett–Reid congruence** — the same L·T·Lᴴ decomposition family with T
*tridiagonal* — expressed as one ``lax.fori_loop`` of two-sided
elementary eliminations (two masked rank-1 updates per step: outer
products the MXU executes directly, with `lax`-traced dynamic pivot
swaps).  The whole factorization jits as a single static-shape loop —
the XLA-friendly replacement for the reference's panel/update task DAG.

Solves then run L (unit lower, implicit), T (tridiagonal), Lᴴ — with the
same pivot sequence applied/unapplied, mirroring ``hetrs``'s
permute → trsm → band-solve → trsm → permute chain.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp
from jax import lax

import numpy as np

from ..enums import Uplo
from ..matrix import BaseTrapezoidMatrix, as_array
from ..options import Options
from ..ops.blocks import _ct, matmul
from ..ops.tile_ops import hermitize
from .blas3 import _wrap_like


class HetrfFactors(NamedTuple):
    """A = P·L·T·Lᴴ·Pᴴ-style factorization record (pivots interleaved
    with the eliminations as in LAPACK ``sytrf_aa``): ``l`` holds the
    multiplier columns (unit diagonal implicit, column 0 = e₀), ``d``/
    ``e`` the real/complex tridiagonal of T, ``ipiv`` the pivot row
    chosen at each step."""

    l: jnp.ndarray
    d: jnp.ndarray
    e: jnp.ndarray
    ipiv: jnp.ndarray


def _hermitian_full(a):
    if isinstance(a, BaseTrapezoidMatrix):
        return hermitize(a.logical_uplo, a.array)
    return as_array(a)


def hetrf(a, opts: Optional[Options] = None) -> HetrfFactors:
    """Factor a Hermitian (possibly indefinite) matrix A = L·T·Lᴴ with
    unit-lower L and tridiagonal T, with symmetric partial pivoting —
    reference ``slate::hetrf`` (``src/hetrf.cc``; Aasen LTLᵀ).

    Step j eliminates column j below the first subdiagonal: pivot the
    largest |A(i,j)|, i>j, into row j+1 (two-sided swap), then apply the
    elementary congruence E·A·Eᴴ, E = I − l·e_{j+1}ᵀ.
    """

    av = _hermitian_full(a)
    n = av.shape[-1]
    dt = av.dtype
    rows = jnp.arange(n)

    def swap2(x, i, p, axis):
        xi = jnp.take(x, i, axis=axis)
        xp = jnp.take(x, p, axis=axis)
        if axis == 0:
            return x.at[i].set(xp).at[p].set(xi)
        return x.at[:, i].set(xp).at[:, p].set(xi)

    def body(j, carry):
        a, l, ipiv = carry
        # pivot: argmax |a[i, j]| over i >= j+1
        col = jnp.where(rows >= j + 1, jnp.abs(a[:, j]), -1.0)
        p = jnp.argmax(col)
        a = swap2(swap2(a, j + 1, p, 0), j + 1, p, 1)
        l = swap2(l, j + 1, p, 0)
        alpha = a[j + 1, j]
        safe = jnp.where(alpha == 0, 1, alpha)
        lcol = jnp.where(rows >= j + 2, a[:, j] / safe, 0).astype(dt)
        pivot_row = a[j + 1, :]
        a = a - lcol[:, None] * pivot_row[None, :]
        a = a - a[:, j + 1][:, None] * jnp.conj(lcol)[None, :]
        l = l.at[:, j + 1].add(lcol)
        return a, l, ipiv.at[j].set(p.astype(jnp.int32))

    l0 = jnp.zeros((n, n), dt)
    ipiv0 = jnp.zeros((n,), jnp.int32)
    if n > 2:
        av, l0, ipiv0 = lax.fori_loop(0, n - 2, body, (av, l0, ipiv0))
    d = jnp.real(jnp.diagonal(av)) if jnp.iscomplexobj(av) \
        else jnp.diagonal(av)
    e = jnp.diagonal(av, -1)
    return HetrfFactors(l=l0, d=d, e=e, ipiv=ipiv0)


def _tridiag_dense(d, e, dt):
    n = d.shape[0]
    t = jnp.zeros((n, n), dt)
    t = t + jnp.diag(d.astype(dt))
    if n > 1:
        t = t + jnp.diag(e, -1) + jnp.diag(jnp.conj(e), 1)
    return t


def hetrs(factors: HetrfFactors, b, opts: Optional[Options] = None):
    """Solve with the :func:`hetrf` factorization — reference
    ``slate::hetrs`` (``src/hetrs.cc``): pivots → L → T (tridiagonal
    solve) → Lᴴ → pivots back."""

    from ..enums import Diag, Side
    from ..ops import blocks

    bv = as_array(b)
    squeeze = bv.ndim == 1
    if squeeze:
        bv = bv[:, None]
    l, d, e, ipiv = factors
    n = l.shape[0]
    dt = l.dtype
    bv = bv.astype(dt)

    # row-swapped multiplier storage ⇒ P·A·Pᴴ = L·T·Lᴴ (same argument as
    # LU's interleaved-pivot identity; swaps at step j only touch rows
    # ≥ j+2, commuting past e_{j+1})
    def fwd_swap(j, z):
        p = ipiv[j]
        zi = z[j + 1]
        return z.at[j + 1].set(z[p]).at[p].set(zi)

    def bwd_swap(idx, z):
        j = n - 3 - idx
        p = ipiv[j]
        zi = z[j + 1]
        return z.at[j + 1].set(z[p]).at[p].set(zi)

    if n > 2:
        bv = lax.fori_loop(0, n - 2, fwd_swap, bv)
    lfull = l + jnp.eye(n, dtype=dt)
    nb = max(32, n // 8)
    y = blocks.trsm_rec(Side.Left, Uplo.Lower, Diag.Unit, lfull, bv, nb)
    # tridiagonal solve (dense LU with pivoting; T is n×n tridiag —
    # the reference's band gbtrf/gbtrs; dense is the robust first cut)
    t = _tridiag_dense(d, e, dt)
    w = jnp.linalg.solve(t, y)
    v = blocks.trsm_rec(Side.Left, Uplo.Upper, Diag.Unit, _ct(lfull), w, nb)
    if n > 2:
        v = lax.fori_loop(0, n - 2, bwd_swap, v)
    if squeeze:
        v = v[:, 0]
    return _wrap_like(b, v)


def hesv(a, b, opts: Optional[Options] = None):
    """Factor + solve — reference ``slate::hesv`` (``src/hesv.cc``).
    Returns ``(factors, x)``."""

    f = hetrf(a, opts)
    return f, hetrs(f, b, opts)


# real-symmetric aliases (reference ``slate::sytrf/sytrs/sysv``)
sytrf = hetrf
sytrs = hetrs
sysv = hesv
