"""Hermitian-indefinite solvers: hetrf / hetrs / hesv (+ sy aliases) —
reference ``src/hetrf.cc`` (625 LoC), ``src/hetrs.cc``, ``src/hesv.cc``:
Aasen-style L·T·Lᴴ factorization with a banded T and a band solve.

TPU-native design stance: the reference's blocked Aasen builds a
bandwidth-nb T and solves it with ``gbtrf/gbtrs``; pivoting makes the
panel control-flow heavy.  Here the factorization is a **pivoted
Parlett–Reid congruence** — the same L·T·Lᴴ decomposition family with T
*tridiagonal* — expressed as one ``lax.fori_loop`` of two-sided
elementary eliminations (two masked rank-1 updates per step: outer
products the MXU executes directly, with `lax`-traced dynamic pivot
swaps).  The whole factorization jits as a single static-shape loop —
the XLA-friendly replacement for the reference's panel/update task DAG.

Solves then run L (unit lower, implicit), T (tridiagonal), Lᴴ — with the
same pivot sequence applied/unapplied, mirroring ``hetrs``'s
permute → trsm → band-solve → trsm → permute chain.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp
from jax import lax

import numpy as np

from ..enums import Uplo
from ..matrix import BaseTrapezoidMatrix, as_array
from ..options import Options
from ..ops.blocks import _ct, matmul
from ..ops.tile_ops import hermitize
from .blas3 import _wrap_like


class HetrfFactors(NamedTuple):
    """A = P·L·T·Lᴴ·Pᴴ-style factorization record (pivots interleaved
    with the eliminations as in LAPACK ``sytrf_aa``): ``l`` holds the
    multiplier columns (unit diagonal implicit, column 0 = e₀), ``d``/
    ``e`` the real/complex tridiagonal of T, ``ipiv`` the pivot row
    chosen at each step."""

    l: jnp.ndarray
    d: jnp.ndarray
    e: jnp.ndarray
    ipiv: jnp.ndarray


def _hermitian_full(a):
    if isinstance(a, BaseTrapezoidMatrix):
        return hermitize(a.logical_uplo, a.array)
    return as_array(a)


def hetrf(a, opts: Optional[Options] = None) -> HetrfFactors:
    """Factor a Hermitian (possibly indefinite) matrix A = L·T·Lᴴ with
    unit-lower L and tridiagonal T, with symmetric partial pivoting —
    reference ``slate::hetrf`` (``src/hetrf.cc``; blocked Aasen LTLᵀ).

    Step j eliminates column j below the first subdiagonal: pivot the
    largest |A(i,j)|, i>j, into row j+1 (two-sided swap), then apply the
    elementary congruence E·A·Eᴴ, E = I − l·e_{j+1}ᵀ.  The blocked path
    (:func:`_hetrf_blocked`) defers the two rank-1 congruence terms of
    each panel into one rank-2·nb her2k-shaped GEMM on the trailing
    matrix — the reference's panel/update structure on the MXU; the
    unblocked rank-1 loop below remains for tiny n and as the reference
    implementation the blocked path is tested against.
    """

    from ..options import get_option

    av = _hermitian_full(a)
    n = av.shape[-1]
    nb = int(get_option(opts, "block_size", None)
             or getattr(a, "nb", None) or 64)
    if n > 2 * nb + 2 and n > 8:
        l0, d, e, ipiv0 = _hetrf_blocked(av, nb)
        return HetrfFactors(l=l0, d=d, e=e, ipiv=ipiv0)
    dt = av.dtype
    rows = jnp.arange(n)

    def swap2(x, i, p, axis):
        xi = jnp.take(x, i, axis=axis)
        xp = jnp.take(x, p, axis=axis)
        if axis == 0:
            return x.at[i].set(xp).at[p].set(xi)
        return x.at[:, i].set(xp).at[:, p].set(xi)

    def body(j, carry):
        a, l, ipiv = carry
        # pivot: argmax |a[i, j]| over i >= j+1
        col = jnp.where(rows >= j + 1, jnp.abs(a[:, j]), -1.0)
        p = jnp.argmax(col)
        a = swap2(swap2(a, j + 1, p, 0), j + 1, p, 1)
        l = swap2(l, j + 1, p, 0)
        alpha = a[j + 1, j]
        safe = jnp.where(alpha == 0, 1, alpha)
        lcol = jnp.where(rows >= j + 2, a[:, j] / safe, 0).astype(dt)
        pivot_row = a[j + 1, :]
        a = a - lcol[:, None] * pivot_row[None, :]
        a = a - a[:, j + 1][:, None] * jnp.conj(lcol)[None, :]
        l = l.at[:, j + 1].add(lcol)
        return a, l, ipiv.at[j].set(p.astype(jnp.int32))

    l0 = jnp.zeros((n, n), dt)
    ipiv0 = jnp.zeros((n,), jnp.int32)
    if n > 2:
        av, l0, ipiv0 = lax.fori_loop(0, n - 2, body, (av, l0, ipiv0))
    d = jnp.real(jnp.diagonal(av)) if jnp.iscomplexobj(av) \
        else jnp.diagonal(av)
    e = jnp.diagonal(av, -1)
    return HetrfFactors(l=l0, d=d, e=e, ipiv=ipiv0)


from functools import partial as _partial

import jax as _jax


@_partial(_jax.jit, static_argnums=1)
def _hetrf_blocked(av, nb: int):
    """Panel-blocked Parlett–Reid LTLᴴ: within a panel the two-sided
    eliminations update only an (n × nb+1) window; their rank-1 terms
    are accumulated (V = multipliers, U = pre-update pivot columns,
    C = post-left-update columns) and applied to the trailing columns as
    one V·Uᴴ + C·Vᴴ GEMM per panel.  Pivot swaps move whole rows/columns
    immediately (O(n) each); a per-column step watermark records how many
    panel steps a swapped-out window column has already absorbed so the
    deferred GEMM subtracts only the missing terms.
    """

    n = av.shape[-1]
    dt = av.dtype
    a = av
    l = jnp.zeros((n, n), dt)
    ipiv = jnp.zeros((n,), jnp.int32)

    def swap_rows(x, i, p):
        xi = x[i]
        return x.at[i].set(x[p]).at[p].set(xi)

    def swap_cols(x, i, p):
        xi = x[:, i]
        return x.at[:, i].set(x[:, p]).at[:, p].set(xi)

    for j0 in range(0, max(n - 2, 0), nb):
        w = min(nb, n - 2 - j0)
        if w <= 0:
            break
        m = n - j0                  # the panel runs on the trailing
        wide = min(w + 1, m)        # square a[j0:, j0:] — rows/columns
        rs = jnp.arange(m)          # above/left of it are never read
        asq = a[j0:, j0:]           # again (only d/e are extracted)
        V0 = jnp.zeros((m, w), dt)
        U0 = jnp.zeros((m, w), dt)
        C0 = jnp.zeros((m, w), dt)
        wm0 = jnp.zeros((m,), jnp.int32)   # deferred-from step per column
        steps = jnp.arange(w)

        def body(t, carry):
            asq, ipiv, V, U, C, wm = carry
            win = lax.dynamic_slice(asq, (0, 0), (m, wide))
            # pivot: argmax |win[:, t]| over local rows >= t+1
            col = jnp.where(rs >= t + 1, jnp.abs(win[:, t]), -1.0)
            p = jnp.argmax(col).astype(jnp.int32)
            asq = swap_cols(swap_rows(asq, t + 1, p), t + 1, p)
            V = swap_rows(V, t + 1, p)
            U = swap_rows(U, t + 1, p)
            C = swap_rows(C, t + 1, p)
            # plain watermark exchange: window-resident columns carry
            # wm = t (kept current at the end of every step below), so a
            # swapped-in trailing column brings its true deferred-from
            # step and an in-window swap brings t (empty refresh)
            wmi = wm[t + 1]
            wm = wm.at[t + 1].set(wm[p]).at[p].set(wmi)
            win = lax.dynamic_slice(asq, (0, 0), (m, wide))
            # refresh the swapped-in column t+1 with its missing deferred
            # panel terms (steps wm[t+1]..t-1)
            mask = ((steps >= wm[t + 1]) & (steps < t)).astype(dt)
            cj1 = win[:, t + 1]
            cj1 = cj1 - matmul(V, mask * jnp.conj(U[t + 1])) \
                - matmul(C, mask * jnp.conj(V[t + 1]))
            win = win.at[:, t + 1].set(cj1)
            # elimination column and multipliers
            colj = win[:, t]
            aj1 = colj[t + 1]
            safe = jnp.where(aj1 == 0, 1, aj1)
            lcol = jnp.where(rs >= t + 2, colj / safe, 0).astype(dt)
            u_t = cj1                        # column t+1 before left update
            # left congruence term on the window (row t+1 is current
            # there — window columns are fully updated)
            pr_win = win[t + 1, :]
            win = win - lcol[:, None] * pr_win[None, :]
            c_t = win[:, t + 1]              # column t+1 after left update
            # right congruence term: column c's coefficient is conj(lcol[c])
            win = win - c_t[:, None] * jnp.conj(lcol[:wide])[None, :]
            asq = lax.dynamic_update_slice(asq, win, (0, 0))
            V = V.at[:, t].set(lcol)
            U = U.at[:, t].set(u_t)
            C = C.at[:, t].set(c_t)
            ipiv = ipiv.at[j0 + t].set(p + j0)
            # window columns are now current through step t
            wm = lax.dynamic_update_slice(
                wm, jnp.full((wide,), t + 1, jnp.int32), (0,))
            return asq, ipiv, V, U, C, wm

        asq, ipiv, V, U, C, wm = lax.fori_loop(
            0, w, body, (asq, ipiv, V0, U0, C0, wm0))
        # deferred her2k-shaped trailing update on columns >= wide,
        # masked per column by its swap watermark
        if wide < m:
            atr = asq[:, wide:]
            maskc = (steps[None, :] >= wm[wide:][:, None]).astype(dt)
            coef_u = jnp.conj(U[wide:, :]) * maskc
            coef_v = jnp.conj(V[wide:, :]) * maskc
            atr = atr - matmul(V, coef_u.T) - matmul(C, coef_v.T)
            asq = asq.at[:, wide:].set(atr)
            # re-hermitize the trailing square: the deferred GEMM's
            # rounding asymmetry is otherwise amplified by the element
            # growth of every subsequent elimination (measured ~40× per
            # panel at n=96 — backward error 3e-9 vs 3e-15 with the
            # symmetrization)
            blk = asq[wide:, wide:]
            asq = asq.at[wide:, wide:].set(0.5 * (blk + jnp.conj(blk.T)))
        a = a.at[j0:, j0:].set(asq)
        # apply this panel's row swaps to the earlier L columns, then
        # install the panel's multipliers (V *is* L[:, j0+1 : j0+w+1])
        def lswap(t, l):
            p = ipiv[j0 + t]
            li = l[j0 + t + 1]
            return l.at[j0 + t + 1].set(l[p]).at[p].set(li)

        l = lax.fori_loop(0, w, lswap, l)
        l = l.at[j0:, j0 + 1:j0 + w + 1].set(V)

    d = jnp.real(jnp.diagonal(a)) if jnp.iscomplexobj(a) \
        else jnp.diagonal(a)
    e = jnp.diagonal(a, -1)
    return l, d, e, ipiv


def _gtsv_scan(d, e, b):
    """Traceable partial-pivot tridiagonal solve (LAPACK ``gtsv``
    algorithm as two ``lax.scan`` sweeps), O(n·nrhs) — the jit-safe
    replacement for the host banded solve.  T is Hermitian tridiagonal:
    diag ``d``, sub ``e``, super ``conj(e)``.

    Forward sweep: the carry is the not-yet-finalized current row
    (d, du, du2, rhs); each step compares it against the next row's
    subdiagonal and either eliminates (no swap) or swaps then
    eliminates, emitting the finalized row — exactly dgtsv's adjacent
    -row pivoting with its single extra ``du2`` fill-in band.  Backward
    sweep: standard 2-term back substitution.
    """

    dt = jnp.result_type(d.dtype, e.dtype, b.dtype)
    n = d.shape[0]
    d = d.astype(dt)
    e = e.astype(dt)
    b = b.astype(dt)
    if n == 1:
        return b / d[0]
    du = jnp.conj(e)
    zero = jnp.zeros((), dt)
    zrow = jnp.zeros(b.shape[1:], dt)

    def fwd(carry, row):
        cd, cdu, cdu2, cb = carry
        dl_i, d_next, du_next, b_next = row
        swap = jnp.abs(cd) < jnp.abs(dl_i)
        fact = jnp.where(swap, cd, dl_i) / jnp.where(swap, dl_i, cd)
        out_d = jnp.where(swap, dl_i, cd)
        out_du = jnp.where(swap, d_next, cdu)
        out_du2 = jnp.where(swap, du_next, cdu2)
        out_b = jnp.where(swap, b_next, cb)
        new_d = jnp.where(swap, cdu - fact * d_next, d_next - fact * cdu)
        new_du = jnp.where(swap, cdu2 - fact * du_next,
                           du_next - fact * cdu2)
        new_b = jnp.where(swap, cb - fact * b_next, b_next - fact * cb)
        return (new_d, new_du, zero, new_b), (out_d, out_du, out_du2, out_b)

    rows = (e, d[1:], jnp.concatenate([du[1:], zero[None]]), b[1:])
    (last_d, _, _, last_b), (fd, fdu, fdu2, fb) = lax.scan(
        fwd, (d[0], du[0], zero, b[0]), rows)
    # finalized rows 0..n-2 plus the remaining carry as row n-1
    fd = jnp.concatenate([fd, last_d[None]])
    fdu = jnp.concatenate([fdu, zero[None]])
    fdu2 = jnp.concatenate([fdu2, zero[None]])
    fb = jnp.concatenate([fb, last_b[None]])

    def bwd(carry, row):
        x1, x2 = carry
        di, dui, du2i, bi = row
        xi = (bi - dui * x1 - du2i * x2) / di
        return (xi, x1), xi

    _, xs = lax.scan(bwd, (zrow, zrow),
                     (fd, fdu, fdu2, fb), reverse=True)
    return xs


def hetrs(factors: HetrfFactors, b, opts: Optional[Options] = None):
    """Solve with the :func:`hetrf` factorization — reference
    ``slate::hetrs`` (``src/hetrs.cc``): pivots → L → T (tridiagonal
    solve) → Lᴴ → pivots back."""

    from ..enums import Diag, Side
    from ..ops import blocks

    bv = as_array(b)
    squeeze = bv.ndim == 1
    if squeeze:
        bv = bv[:, None]
    l, d, e, ipiv = factors
    n = l.shape[0]
    dt = l.dtype
    bv = bv.astype(dt)

    # row-swapped multiplier storage ⇒ P·A·Pᴴ = L·T·Lᴴ (same argument as
    # LU's interleaved-pivot identity; swaps at step j only touch rows
    # ≥ j+2, commuting past e_{j+1})
    def fwd_swap(j, z):
        p = ipiv[j]
        zi = z[j + 1]
        return z.at[j + 1].set(z[p]).at[p].set(zi)

    def bwd_swap(idx, z):
        j = n - 3 - idx
        p = ipiv[j]
        zi = z[j + 1]
        return z.at[j + 1].set(z[p]).at[p].set(zi)

    if n > 2:
        bv = lax.fori_loop(0, n - 2, fwd_swap, bv)
    lfull = l + jnp.eye(n, dtype=dt)
    nb = max(32, n // 8)
    y = blocks.trsm_rec(Side.Left, Uplo.Lower, Diag.Unit, lfull, bv, nb)
    # tridiagonal solve — the reference's band gbtrf/gbtrs on T
    # (``src/hetrs.cc``): LAPACK banded solve on host, O(n·nrhs).  Under
    # tracing (jit/vmap callers) the traceable scan-based gtsv keeps the
    # same O(n·nrhs) cost — the dense jnp.linalg.solve fallback it
    # replaces was silently O(n³) exactly where users wrap hesv in jit.
    import jax as _jax
    if isinstance(y, _jax.core.Tracer):
        w = _gtsv_scan(d, e, y)
    else:
        from scipy.linalg import solve_banded
        dnp = np.asarray(d)
        enp = np.asarray(e)
        ab = np.zeros((3, n), dtype=np.asarray(jnp.zeros((), dt)).dtype)
        ab[1, :] = dnp
        if n > 1:
            ab[0, 1:] = np.conj(enp)
            ab[2, :-1] = enp
        w = jnp.asarray(solve_banded((1, 1), ab, np.asarray(y)), dtype=dt)
    v = blocks.trsm_rec(Side.Left, Uplo.Upper, Diag.Unit, _ct(lfull), w, nb)
    if n > 2:
        v = lax.fori_loop(0, n - 2, bwd_swap, v)
    if squeeze:
        v = v[:, 0]
    return _wrap_like(b, v)


def hesv(a, b, opts: Optional[Options] = None):
    """Factor + solve — reference ``slate::hesv`` (``src/hesv.cc``).
    Returns ``(factors, x)``."""

    f = hetrf(a, opts)
    return f, hetrs(f, b, opts)


# real-symmetric aliases (reference ``slate::sytrf/sytrs/sysv``)
sytrf = hetrf
sytrs = hetrs
sysv = hesv
