"""Matrix norm drivers.

TPU-native re-design of the reference's norm drivers ``src/norm.cc`` (377
LoC: max/one/inf/fro over every matrix type) and the per-type internal ops
``internal_genorm.cc`` / ``internal_henorm.cc`` / ``internal_synorm.cc`` /
``internal_trnorm.cc`` / ``internal_gbnorm.cc`` / ``internal_hbnorm.cc``.

The reference runs two phases — per-tile device kernels producing tile
partials, then an MPI reduction (``src/norm.cc``).  Here both phases are
one fused XLA reduction over the (masked) logical array: on a single chip
XLA tiles the reduction over the VPU; on a mesh the same code under
``shard_map`` ends with a ``psum``/``pmax`` (see
:func:`slate_tpu.parallel.dist_norms.pnorm`).

``colNorms`` mirrors ``slate::colNorms`` (``src/colNorms.cc``, max-abs per
column), used by the LU panel's growth monitoring.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..enums import Diag, Norm, Uplo
from ..matrix import (BaseBandMatrix, BaseMatrix, BaseTrapezoidMatrix,
                      HermitianBandMatrix, HermitianMatrix, SymmetricMatrix,
                      TriangularBandMatrix, as_array)
from ..options import Options


def _masked_array(a):
    """Resolve a matrix-family object into (array, needs_symmetrize) with
    structural zeros/mirroring applied — the per-type dispatch the
    reference does by overloading ``slate::norm`` per matrix class."""

    if isinstance(a, (SymmetricMatrix, HermitianMatrix)):
        return a.full()
    if isinstance(a, HermitianBandMatrix):
        from ..ops.tile_ops import hermitize, symmetrize
        full = (hermitize if jnp.iscomplexobj(a.data) else symmetrize)(
            a.uplo, a.array)
        kd = a.kd
        n = full.shape[-1]
        i = jnp.arange(n)[:, None]
        j = jnp.arange(n)[None, :]
        return jnp.where(jnp.abs(i - j) <= kd, full, 0)
    if isinstance(a, TriangularBandMatrix):
        base = a.banded()
        if a.diag is Diag.Unit:
            n = min(base.shape[-2], base.shape[-1])
            eye = jnp.eye(base.shape[-2], base.shape[-1], dtype=bool)
            base = jnp.where(eye, jnp.asarray(1, base.dtype), base)
        return base
    if isinstance(a, BaseBandMatrix):
        return a.banded()
    if isinstance(a, BaseTrapezoidMatrix):
        t = a.tril_or_triu()
        if getattr(a, "diag", Diag.NonUnit) is Diag.Unit:
            eye = jnp.eye(t.shape[-2], t.shape[-1], dtype=bool)
            t = jnp.where(eye, jnp.asarray(1, t.dtype), t)
        return t
    return as_array(a)


def norm(norm_type: Norm, a, opts: Optional[Options] = None):
    """‖A‖ for Max/One/Inf/Fro — reference ``slate::norm`` (``src/norm.cc``).

    Accepts any matrix-family object (triangle storage, band, Hermitian
    mirroring and unit diagonals are honoured) or a raw array.
    Returns a real scalar of the matching real dtype.
    """

    v = _masked_array(a)
    av = jnp.abs(v)
    if norm_type is Norm.Max:
        return jnp.max(av)
    if norm_type is Norm.One:
        return jnp.max(jnp.sum(av, axis=-2))
    if norm_type is Norm.Inf:
        return jnp.max(jnp.sum(av, axis=-1))
    if norm_type is Norm.Fro:
        # scaled sum-of-squares like LAPACK lassq to dodge overflow
        scale = jnp.max(av)
        safe = jnp.where(scale > 0, scale, 1)
        ssq = jnp.sum((av / safe) ** 2)
        return jnp.where(scale > 0, scale * jnp.sqrt(ssq), jnp.asarray(0, av.dtype))
    raise ValueError(f"unsupported norm {norm_type}")


def col_norms(norm_type: Norm, a, opts: Optional[Options] = None):
    """Per-column norms — reference ``slate::colNorms`` (``src/colNorms.cc``;
    only Norm::Max is supported there, mirrored here)."""

    if norm_type is not Norm.Max:
        raise ValueError("colNorms supports Norm.Max (like the reference)")
    return jnp.max(jnp.abs(_masked_array(a)), axis=-2)


# BLAS-style aliases matching the reference's per-type entry points.
def genorm(norm_type: Norm, a, opts: Optional[Options] = None):
    return norm(norm_type, a, opts)


def synorm(norm_type: Norm, a, opts: Optional[Options] = None):
    return norm(norm_type, a, opts)


def henorm(norm_type: Norm, a, opts: Optional[Options] = None):
    return norm(norm_type, a, opts)


def trnorm(norm_type: Norm, a, opts: Optional[Options] = None):
    return norm(norm_type, a, opts)


def gbnorm(norm_type: Norm, a, opts: Optional[Options] = None):
    return norm(norm_type, a, opts)


def hbnorm(norm_type: Norm, a, opts: Optional[Options] = None):
    return norm(norm_type, a, opts)
