"""Condition estimation: norm1est, gecondest, pocondest, trcondest —
reference ``src/internal/internal_norm1est.cc`` (Higham–Tisseur /
LAPACK ``lacn2`` block 1-norm estimator), ``src/gecondest.cc``,
``src/trcondest.cc`` (and ``pocondest`` in ``slate.hh``).

Design: the estimator is host-driven (a handful of data-dependent
iterations, each a device solve/matvec — the reference likewise loops
``lacn2`` around distributed solves on rank 0's say-so); the inner
solves are the jitted blocked triangular/LU solves, so the O(n²) work
per iteration still runs on the MXU.
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..enums import Diag, Norm, Op, Side, Uplo
from ..matrix import as_array
from ..options import Options
from ..ops import blocks
from ..ops.blocks import _ct
from .blas3 import _nb
from .norms import norm as _norm


def norm1est(apply_a: Callable, apply_ah: Callable, n: int,
             dtype=np.float64, maxiter: int = 5) -> float:
    """Estimate ‖A‖₁ given matvec closures x↦A·x and x↦Aᴴ·x —
    Higham–Tisseur power iteration on the 1-norm dual (LAPACK ``lacn2``;
    reference ``internal::norm1est``)."""

    x = np.ones((n, 1), dtype=dtype) / n
    est = 0.0
    for _ in range(maxiter):
        y = np.asarray(apply_a(jnp.asarray(x)))
        est_new = float(np.abs(y).sum())
        xi = np.where(y == 0, 1.0, np.sign(y.real) +
                      (1j * np.sign(y.imag) if np.iscomplexobj(y) else 0))
        z = np.asarray(apply_ah(jnp.asarray(xi.astype(x.dtype))))
        j = int(np.argmax(np.abs(z.real)))
        if est_new <= est:
            break
        est = est_new
        if np.abs(z.real[j]) <= np.abs(np.vdot(z.ravel(), x.ravel())):
            break
        x = np.zeros((n, 1), dtype=dtype)
        x[j] = 1.0
    return est


def gecondest(norm_type: Norm, lu, perm, anorm: Optional[float] = None,
              opts: Optional[Options] = None) -> float:
    """Reciprocal condition estimate from an LU factorization —
    reference ``slate::gecondest`` (``src/gecondest.cc``): returns
    rcond = 1/(‖A‖₁·est‖A⁻¹‖₁)."""

    from .lu import getrs
    luv = as_array(lu)
    n = luv.shape[-1]
    if anorm is None:
        raise ValueError("gecondest requires anorm (norm of the original A)")
    if anorm == 0 or n == 0:
        return 0.0 if n else 1.0

    def solve(x):
        return as_array(getrs(luv, perm, x, opts=opts))

    def solve_h(x):
        return as_array(getrs(luv, perm, x, op=Op.ConjTrans, opts=opts))

    dt = np.dtype(np.complex128 if jnp.iscomplexobj(luv) else np.float64)
    ainv_norm = norm1est(solve, solve_h, n, dtype=dt)
    return 1.0 / (float(anorm) * ainv_norm) if ainv_norm else 0.0


def pocondest(norm_type: Norm, chol_factor, anorm: Optional[float] = None,
              opts: Optional[Options] = None) -> float:
    """Reciprocal condition estimate from a Cholesky factorization —
    reference ``slate::pocondest`` (``include/slate/slate.hh``)."""

    from .cholesky import potrs
    if anorm is None:
        raise ValueError("pocondest requires anorm")
    lv = as_array(chol_factor)
    n = lv.shape[-1]
    if anorm == 0 or n == 0:
        return 0.0 if n else 1.0

    def solve(x):
        return as_array(potrs(chol_factor, x, opts))

    dt = np.dtype(np.complex128 if jnp.iscomplexobj(lv) else np.float64)
    ainv_norm = norm1est(solve, solve, n, dtype=dt)
    return 1.0 / (float(anorm) * ainv_norm) if ainv_norm else 0.0


def trcondest(norm_type: Norm, a, uplo: Optional[Uplo] = None,
              diag: Diag = Diag.NonUnit,
              opts: Optional[Options] = None) -> float:
    """Reciprocal condition estimate of a triangular matrix — reference
    ``slate::trcondest`` (``src/trcondest.cc``)."""

    av = as_array(a)
    n = av.shape[-1]
    if n == 0:
        return 1.0
    uplo = uplo or getattr(a, "logical_uplo", Uplo.Upper)
    nb = _nb(a, opts)
    anorm = float(_norm(norm_type, a, opts))
    if anorm == 0:
        return 0.0

    def solve(x):
        return blocks.trsm_rec(Side.Left, uplo, diag, av, x, nb)

    def solve_h(x):
        flip = Uplo.Lower if uplo is Uplo.Upper else Uplo.Upper
        return blocks.trsm_rec(Side.Left, flip, diag, _ct(av), x, nb)

    dt = np.dtype(np.complex128 if jnp.iscomplexobj(av) else np.float64)
    ainv_norm = norm1est(solve, solve_h, n, dtype=dt)
    return 1.0 / (anorm * ainv_norm) if ainv_norm else 0.0


# ---------------------------------------------------------------------------
# Shared condition probes: the mixed-precision split legs and QDWH
# ---------------------------------------------------------------------------

def refine_kappa_eps(apply_inv, apply_inv_h, n: int, anorm: float, lo,
                     power: int = 1) -> float:
    """κ·ε condition probe shared by the mixed-precision split-factor
    legs (lu / cholesky / qr demotion gates): estimate ``‖A⁻¹‖₁`` with
    :func:`norm1est` from solve closures whose inputs are cast to the
    low precision ``lo`` HERE (one cast site instead of per-caller
    lambda pairs), form ``κ = anorm·est``, and return
    ``κ**power · n · ε(lo)``.  A non-finite estimate collapses to
    ``inf`` so callers gate with a single comparison against their
    contraction threshold (0.25 for IR / SNE)."""

    lo = np.dtype(lo)

    def _cast(fn):
        return lambda v: as_array(fn(jnp.asarray(v).astype(lo)))

    dt = np.dtype(np.complex128 if lo.kind == "c" else np.float64)
    ainv = norm1est(_cast(apply_inv), _cast(apply_inv_h), n, dtype=dt)
    kappa = float(anorm) * float(ainv)
    ke = (kappa ** power) * float(n) * float(np.finfo(lo).eps)
    return ke if math.isfinite(ke) else math.inf


def spectral_interval(a, opts: Optional[Options] = None,
                      ) -> Tuple[float, float]:
    """Two-sided singular-spectrum interval ``(alpha, smin_est)``:
    ``alpha ≥ σ_max(A)`` rigorously (``sqrt(‖A‖₁·‖A‖∞)``, cross-checked
    against a two-pass power-iteration lower bound so a norm bug cannot
    return an interval the power estimate refutes) and ``smin_est`` a
    deliberately LOW estimate of ``σ_min(A)`` from a Higham–Tisseur
    1-norm estimate on the inverse of A's triangular QR factor, divided
    by √n (norm-equivalence slack — :func:`norm1est` lower-bounds the
    1-norm, so the raw reciprocal would overestimate σ_min).

    Shared by QDWH's ``(alpha, l0 = smin_est/alpha)`` scaling — where
    underestimating σ_min only costs Halley iterations while
    overestimating breaks the weight recurrence — and by condition
    reporting around the ``_refine`` probes.  Costs one ``geqrf`` of A
    plus O(n²) estimator sweeps."""

    av = as_array(a)
    if av.ndim != 2:
        raise ValueError("spectral_interval expects a 2-D matrix")
    m, n = av.shape
    if m < n:                      # σ(A) = σ(Aᴴ); factor the tall side
        av = _ct(av)
        m, n = n, m
    if n == 0:
        return 0.0, 0.0
    nb = _nb(a, opts)
    abs_a = jnp.abs(av)
    n1 = float(abs_a.sum(axis=0).max())
    ninf = float(abs_a.sum(axis=1).max())
    alpha = math.sqrt(n1 * ninf)
    if alpha == 0.0 or not math.isfinite(alpha):
        return alpha, 0.0
    # power-iteration lower bound on σ_max (deterministic probe, two
    # AᴴA passes): certifies alpha from below and guards against a
    # pathological norm product
    x = jnp.asarray(1.0 + np.cos(np.arange(n, dtype=np.float64)),
                    dtype=av.dtype)
    low = 0.0
    for _ in range(2):
        y = av @ x
        nx = float(jnp.linalg.norm(x))
        if nx == 0.0:
            break
        low = float(jnp.linalg.norm(y)) / nx
        x = _ct(av) @ y
    alpha = max(alpha, low)
    # σ_min via the R factor: σ_min(A) = σ_min(R) = 1/‖R⁻¹‖₂, with
    # ‖R⁻¹‖₂ ≤ √n·‖R⁻¹‖₁ absorbing the estimator's lower-bound bias
    from .qr import geqrf_rec

    f, _taus = geqrf_rec(av, nb)
    r = jnp.triu(f[:n])

    # probe vectors are built in the estimator's f64 bookkeeping dtype;
    # cast to the factor's dtype at the closure boundary (the one cast
    # site, as in :func:`refine_kappa_eps`) — without it an x64-enabled
    # session feeds f64 probes to an f32 triangular factor
    def solve(v):
        return blocks.trsm_rec(Side.Left, Uplo.Upper, Diag.NonUnit, r,
                               jnp.asarray(v).astype(r.dtype), nb)

    def solve_h(v):
        return blocks.trsm_rec(Side.Left, Uplo.Lower, Diag.NonUnit,
                               _ct(r), jnp.asarray(v).astype(r.dtype), nb)

    dt = np.dtype(np.complex128 if jnp.iscomplexobj(av) else np.float64)
    rinv = norm1est(solve, solve_h, n, dtype=dt)
    if not (rinv > 0.0) or not math.isfinite(rinv):
        return alpha, 0.0
    smin = 1.0 / (rinv * math.sqrt(n))
    return alpha, min(smin, alpha)
