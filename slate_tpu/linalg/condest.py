"""Condition estimation: norm1est, gecondest, pocondest, trcondest —
reference ``src/internal/internal_norm1est.cc`` (Higham–Tisseur /
LAPACK ``lacn2`` block 1-norm estimator), ``src/gecondest.cc``,
``src/trcondest.cc`` (and ``pocondest`` in ``slate.hh``).

Design: the estimator is host-driven (a handful of data-dependent
iterations, each a device solve/matvec — the reference likewise loops
``lacn2`` around distributed solves on rank 0's say-so); the inner
solves are the jitted blocked triangular/LU solves, so the O(n²) work
per iteration still runs on the MXU.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from ..enums import Diag, Norm, Op, Side, Uplo
from ..matrix import as_array
from ..options import Options
from ..ops import blocks
from ..ops.blocks import _ct
from .blas3 import _nb
from .norms import norm as _norm


def norm1est(apply_a: Callable, apply_ah: Callable, n: int,
             dtype=np.float64, maxiter: int = 5) -> float:
    """Estimate ‖A‖₁ given matvec closures x↦A·x and x↦Aᴴ·x —
    Higham–Tisseur power iteration on the 1-norm dual (LAPACK ``lacn2``;
    reference ``internal::norm1est``)."""

    x = np.ones((n, 1), dtype=dtype) / n
    est = 0.0
    for _ in range(maxiter):
        y = np.asarray(apply_a(jnp.asarray(x)))
        est_new = float(np.abs(y).sum())
        xi = np.where(y == 0, 1.0, np.sign(y.real) +
                      (1j * np.sign(y.imag) if np.iscomplexobj(y) else 0))
        z = np.asarray(apply_ah(jnp.asarray(xi.astype(x.dtype))))
        j = int(np.argmax(np.abs(z.real)))
        if est_new <= est:
            break
        est = est_new
        if np.abs(z.real[j]) <= np.abs(np.vdot(z.ravel(), x.ravel())):
            break
        x = np.zeros((n, 1), dtype=dtype)
        x[j] = 1.0
    return est


def gecondest(norm_type: Norm, lu, perm, anorm: Optional[float] = None,
              opts: Optional[Options] = None) -> float:
    """Reciprocal condition estimate from an LU factorization —
    reference ``slate::gecondest`` (``src/gecondest.cc``): returns
    rcond = 1/(‖A‖₁·est‖A⁻¹‖₁)."""

    from .lu import getrs
    luv = as_array(lu)
    n = luv.shape[-1]
    if anorm is None:
        raise ValueError("gecondest requires anorm (norm of the original A)")
    if anorm == 0 or n == 0:
        return 0.0 if n else 1.0

    def solve(x):
        return as_array(getrs(luv, perm, x, opts=opts))

    def solve_h(x):
        return as_array(getrs(luv, perm, x, op=Op.ConjTrans, opts=opts))

    dt = np.dtype(np.complex128 if jnp.iscomplexobj(luv) else np.float64)
    ainv_norm = norm1est(solve, solve_h, n, dtype=dt)
    return 1.0 / (float(anorm) * ainv_norm) if ainv_norm else 0.0


def pocondest(norm_type: Norm, chol_factor, anorm: Optional[float] = None,
              opts: Optional[Options] = None) -> float:
    """Reciprocal condition estimate from a Cholesky factorization —
    reference ``slate::pocondest`` (``include/slate/slate.hh``)."""

    from .cholesky import potrs
    if anorm is None:
        raise ValueError("pocondest requires anorm")
    lv = as_array(chol_factor)
    n = lv.shape[-1]
    if anorm == 0 or n == 0:
        return 0.0 if n else 1.0

    def solve(x):
        return as_array(potrs(chol_factor, x, opts))

    dt = np.dtype(np.complex128 if jnp.iscomplexobj(lv) else np.float64)
    ainv_norm = norm1est(solve, solve, n, dtype=dt)
    return 1.0 / (float(anorm) * ainv_norm) if ainv_norm else 0.0


def trcondest(norm_type: Norm, a, uplo: Optional[Uplo] = None,
              diag: Diag = Diag.NonUnit,
              opts: Optional[Options] = None) -> float:
    """Reciprocal condition estimate of a triangular matrix — reference
    ``slate::trcondest`` (``src/trcondest.cc``)."""

    av = as_array(a)
    n = av.shape[-1]
    if n == 0:
        return 1.0
    uplo = uplo or getattr(a, "logical_uplo", Uplo.Upper)
    nb = _nb(a, opts)
    anorm = float(_norm(norm_type, a, opts))
    if anorm == 0:
        return 0.0

    def solve(x):
        return blocks.trsm_rec(Side.Left, uplo, diag, av, x, nb)

    def solve_h(x):
        flip = Uplo.Lower if uplo is Uplo.Upper else Uplo.Upper
        return blocks.trsm_rec(Side.Left, flip, diag, _ct(av), x, nb)

    dt = np.dtype(np.complex128 if jnp.iscomplexobj(av) else np.float64)
    ainv_norm = norm1est(solve, solve_h, n, dtype=dt)
    return 1.0 / (anorm * ainv_norm) if ainv_norm else 0.0
