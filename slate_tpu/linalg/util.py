"""Element-wise utility drivers: add, copy, scale, scale_row_col, set.

TPU-native analogs of the reference drivers ``src/add.cc``, ``src/copy.cc``
(precision-converting, 411 LoC), ``src/scale.cc``, ``src/scale_row_col.cc``,
``src/set.cc`` — thin functional wrappers over the tile kernel set in
:mod:`slate_tpu.ops.tile_ops` (the analog of ``src/cuda/device_*.cu``),
applied to whole logical arrays so XLA fuses them into neighbours.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..enums import Uplo
from ..matrix import BaseMatrix, BaseTrapezoidMatrix, as_array
from ..options import Options
from ..ops import tile_ops


def _wrap_like(template, data):
    if isinstance(template, BaseMatrix):
        out = template._like(data)
        return out
    return data


def add(alpha, a, beta, b, opts: Optional[Options] = None):
    """B ← α·A + β·B — reference ``slate::add`` (``src/add.cc``).
    Trapezoid operands update only the stored triangle (``tzadd``)."""

    av, bv = as_array(a), as_array(b)
    if isinstance(b, BaseTrapezoidMatrix) and b.logical_uplo is not Uplo.General:
        out = tile_ops.tzadd(b.logical_uplo, alpha, av, beta, bv)
    else:
        out = tile_ops.geadd(alpha, av, beta, bv)
    return _wrap_like(b, out)


def copy(a, dtype=None, opts: Optional[Options] = None):
    """Precision-converting copy — reference ``slate::copy``
    (``src/copy.cc``): C++ overloads on (src_type, dst_type); here the
    destination dtype is an argument."""

    av = as_array(a)
    out = tile_ops.gecopy(av, dtype=dtype)
    return _wrap_like(a, out)


def scale(numer, denom, a, opts: Optional[Options] = None):
    """A ← (numer/denom)·A — reference ``slate::scale`` (``src/scale.cc``)."""

    out = tile_ops.gescale(numer, denom, as_array(a))
    return _wrap_like(a, out)


def scale_row_col(r, c, a, opts: Optional[Options] = None):
    """A ← diag(r)·A·diag(c) — reference ``slate::scale_row_col``
    (``src/scale_row_col.cc``), the equilibration primitive."""

    out = tile_ops.gescale_row_col(jnp.asarray(r), jnp.asarray(c), as_array(a))
    return _wrap_like(a, out)


def set(offdiag_value, diag_value, a, opts: Optional[Options] = None):
    """A ← offdiag constant with diag constant — reference ``slate::set``
    (``src/set.cc``).  ``a`` supplies shape/dtype/wrapper."""

    av = as_array(a)
    if isinstance(a, BaseTrapezoidMatrix) and a.logical_uplo is not Uplo.General:
        out = tile_ops.tzset(av.shape, a.logical_uplo, offdiag_value,
                             diag_value, av.dtype)
    else:
        out = tile_ops.geset(av.shape, offdiag_value, diag_value, av.dtype)
    return _wrap_like(a, out)
