"""QDWH polar decomposition and the spectral drivers built on it.

``polar`` computes A = U_p·H (U_p a partial isometry, H Hermitian
positive semidefinite) by the QR-based dynamically-weighted Halley
iteration of Nakatsukasa, Bai & Gygi (2010): at most six iterations
for κ up to 1/ε, each one either a QR factorization of the stacked
``[√c·X; I]`` operand (backward stable at any conditioning) or — once
the convergence parameter makes ``I + c·XᴴX`` well-conditioned — a
Cholesky factorization plus two triangular solves.  Every flop is a
geqrf / potrf / trsm / gemm already owned by the autotuned sites, so
the polar iteration rides the split-gemm and Pallas rungs for free and
its roofline is the gemm roofline rather than the bulge chase's.

On top of it, QDWH-eig and QDWH-SVD (Nakatsukasa & Higham, 2013):

* :func:`heev_qdwh` — spectral divide-and-conquer: the polar factor of
  a shifted matrix is a matrix sign, its projector splits the spectrum
  at the shift, an orthonormal basis from one geqrf rotates A into
  block-diagonal form, and the halves recurse down to a crossover where
  the stock two-stage solver finishes the small blocks.
* :func:`svd_qdwh` — polar first (A = U_p·H), then ``heev_qdwh`` of the
  SPSD factor H: Σ are H's eigenvalues, V its eigenvectors, U = U_p·V.

The iteration start is condition-aware: ``(alpha, l0)`` come from the
shared :func:`slate_tpu.linalg.condest.spectral_interval` estimate, so
a well-conditioned input skips straight to the cheap Cholesky variant.
The scale-and-stack epilogues (``[√c·X; I]`` assembly, the
``X' = β·X + α·Q₁Q₂ᴴ`` update) fold into the geqrf operand and the
gemm α/β so no separate materialization pass runs (the LP-GEMM
fused-epilogue idiom).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .. import config
from ..enums import Diag, Op, Side, Uplo
from ..matrix import as_array
from ..options import Options, get_option
from ..ops import blocks
from ..ops.blocks import _ct, matmul
from ..perf import metrics as _metrics
from ..perf.metrics import instrument_driver
from .blas3 import _nb
from .condest import spectral_interval
from .qr import geqrf_rec, unmqr_rec

__all__ = ["polar", "heev_qdwh", "svd_qdwh"]

#: depth backstop for the divide-and-conquer recursion — 2^64 exceeds
#: any representable dimension, so hitting it means a degenerate split
#: loop and the block is handed to the two-stage solver instead
_DC_MAX_DEPTH = 64


def _timer(ns: str, stage: str):
    return _metrics.timer("stage.%s.%s" % (ns, stage))


def _halley_weights(l: float) -> Tuple[float, float, float]:
    """Dynamical Halley weights (a, b, c) from the lower bound ``l`` of
    σ_min(X) — Nakatsukasa–Bai–Gygi eq. (2.4); at ``l = 1`` they reduce
    to the classical Halley (3, 1, 3)."""
    l = min(max(l, 1e-17), 1.0)
    l2 = l * l
    dd = (4.0 * (1.0 - l2) / (l2 * l2)) ** (1.0 / 3.0)
    sq = math.sqrt(1.0 + dd)
    a = sq + 0.5 * math.sqrt(8.0 - 4.0 * dd
                             + 8.0 * (2.0 - l2) / (l2 * sq))
    b = (a - 1.0) ** 2 / 4.0
    return a, b, a + b - 1.0


def _qr_step(x, a_k: float, b_k: float, c_k: float, nb: int, ns: str):
    """One QR-based Halley step: X' = (b/c)·X + (a − b/c)/√c · Q₁Q₂ᴴ
    from the thin QR of ``[√c·X; I]``.  The √c scale folds into the
    stacked-operand build and the rank-n update folds into the gemm's
    α/β epilogue — nothing is materialized twice.  The update runs on
    the internal :func:`~slate_tpu.ops.blocks.matmul` building block,
    not the public ``gemm`` facade: a driver internal re-entering an
    instrumented facade would nest health gates and fault-injection
    polls inside the heev/svd gate."""
    m, n = x.shape
    dt = x.dtype
    sc = math.sqrt(c_k)
    with _timer(ns, "qr"):
        stacked = jnp.concatenate([sc * x, jnp.eye(n, dtype=dt)], axis=0)
        f, taus = geqrf_rec(stacked, nb)
        q = unmqr_rec(f, taus, jnp.eye(m + n, n, dtype=dt),
                      Side.Left, Op.NoTrans, nb)
    with _timer(ns, "gemm"):
        out = ((a_k - b_k / c_k) / sc) * matmul(q[:m], _ct(q[m:])) \
            + (b_k / c_k) * x
    return as_array(out)


def _chol_step(x, a_k: float, b_k: float, c_k: float, nb: int, ns: str):
    """One Cholesky-based Halley step: Z = I + c·XᴴX = WWᴴ, then
    X' = (b/c)·X + (a − b/c)·X·Z⁻¹ via two triangular solves.  Only
    admitted once c is small (Z's condition ≈ c near convergence)."""
    n = x.shape[1]
    dt = x.dtype
    with _timer(ns, "gemm"):
        z = c_k * matmul(_ct(x), x) + jnp.eye(n, dtype=dt)
        z = 0.5 * (z + _ct(z))
    with _timer(ns, "chol"):
        w = blocks.potrf_rec(z, nb)
        # X·Z⁻¹ = (Z⁻¹·Xᴴ)ᴴ — two left solves on the factor
        t = blocks.trsm_rec(Side.Left, Uplo.Lower, Diag.NonUnit,
                            w, _ct(x), nb)
        s = blocks.trsm_rec(Side.Left, Uplo.Upper, Diag.NonUnit,
                            _ct(w), t, nb)
        y = _ct(s)
    return (b_k / c_k) * x + (a_k - b_k / c_k) * y


def _polar_u(av, nb: int, opts, ns: str,
             interval: Optional[Tuple[float, float]] = None):
    """The Halley iteration proper: the polar factor U_p of ``av``
    (m ≥ n), timers namespaced under ``stage.<ns>.*``."""
    from ..perf import autotune

    m, n = av.shape
    dt = av.dtype
    if n == 0:
        return av
    eps = float(jnp.finfo(dt).eps)
    if interval is None:
        alpha, smin = spectral_interval(av, opts)
    else:
        alpha, smin = float(interval[0]), float(interval[1])
    if not (alpha > 0.0) or not math.isfinite(alpha):
        # the zero matrix: U_p is any isometry; pick the canonical one
        return jnp.eye(m, n, dtype=dt)
    # l underestimates σ_min(X₀) by design (extra iterations are the
    # only cost); the ε floor keeps the weight recurrence finite and
    # still converges within the six-iteration QDWH bound
    l = min(max(smin / alpha, eps), 1.0)
    x = (av / alpha).astype(dt)
    maxiter = int(get_option(opts, "qdwh_maxiter", 6))
    it = 0
    while it < maxiter and abs(1.0 - l) > 10.0 * eps:
        a_k, b_k, c_k = _halley_weights(l)
        variant = autotune.select("qdwh_step", n=n, c=c_k, dtype=dt)
        if variant == "chol":
            x = _chol_step(x, a_k, b_k, c_k, nb, ns)
            _metrics.inc("qdwh.step.chol")
        else:
            x = _qr_step(x, a_k, b_k, c_k, nb, ns)
            _metrics.inc("qdwh.step.qr")
        l = l * (a_k + b_k * l * l) / (1.0 + c_k * l * l)
        it += 1
    return x


@instrument_driver("polar")
def polar(a, opts: Optional[Options] = None, *,
          interval: Optional[Tuple[float, float]] = None):
    """QDWH polar decomposition A = U_p·H — returns ``(U_p, H)`` with
    U_p an m×n partial isometry (UᴴU = I) and H = UᴴA symmetrized, the
    Hermitian positive-semidefinite factor.  ``interval`` optionally
    supplies a precomputed ``(alpha ≥ σ_max, σ_min estimate)`` pair
    (the :func:`~slate_tpu.linalg.condest.spectral_interval` contract);
    otherwise one is estimated here."""

    av = as_array(a)
    if av.ndim != 2:
        raise ValueError("polar expects a 2-D matrix")
    m, n = av.shape
    if m < n:
        raise ValueError("polar expects m >= n (factor Aᴴ instead)")
    nb = _nb(a, opts)
    u = _polar_u(av, nb, opts, "polar", interval)
    with _timer("polar", "gemm"):
        uh_a = matmul(_ct(u), av)
        h = 0.5 * (uh_a + _ct(uh_a))
    return u, h


# ---------------------------------------------------------------------------
# QDWH-eig: spectral divide and conquer
# ---------------------------------------------------------------------------

def _small_heev(av, opts):
    """Crossover leaf: the stock two-stage solver on a dense block
    (bypassing the eig_driver dispatch — a forced qdwh pin must not
    recurse back here)."""
    from .eig import _heev_twostage

    w, z = _heev_twostage(av, True, opts)
    return jnp.asarray(w), as_array(z)


def _dc(av, nb: int, crossover: int, opts, ns: str, depth: int):
    """One divide step: polar of the shifted block → sign projector →
    orthonormal split basis from one geqrf → rotate, recurse on the
    diagonal blocks.  Returns ``(w ascending, Z)``."""
    n = av.shape[-1]
    if n <= crossover or depth >= _DC_MAX_DEPTH:
        return _small_heev(av, opts)
    dt = av.dtype
    eye = jnp.eye(n, dtype=dt)
    dvec = np.asarray(jnp.diagonal(av)).real.astype(np.float64)
    row_abs = np.asarray(jnp.abs(av).sum(axis=1), dtype=np.float64)
    off = row_abs - np.abs(np.asarray(jnp.diagonal(av)))
    # shift candidates: mean eigenvalue (trace/n — splits any
    # non-constant spectrum), then the Gershgorin midpoint and the
    # diagonal median when the projector degenerates
    shifts = [float(dvec.mean()),
              0.5 * (float((dvec - off).min()) + float((dvec + off).max())),
              float(np.median(dvec))]
    u_s, k = None, 0
    for sigma in shifts:
        u_s = _polar_u(av - dt.type(sigma) * eye, nb, opts, ns)
        # U_s ≈ sign(A − σI): trace counts (#λ>σ) − (#λ<σ)
        k = int(round((float(jnp.trace(u_s).real) + n) / 2.0))
        if 0 < k < n:
            break
    else:
        # flat / fully clustered spectrum: no shift separates it
        _metrics.inc("qdwh.dc.degenerate")
        return _small_heev(av, opts)
    p = 0.5 * (u_s + eye)        # spectral projector onto λ > σ
    # deterministic mixing (replayable runs): P·G₁ spans range(P) and
    # (I−P)·G₂ its complement almost surely; one full QR orthonormalizes
    # both while preserving the leading-column span
    rng = np.random.default_rng(0x0D_5EED + depth)
    g = jnp.asarray(rng.standard_normal((n, n)), dtype=eye.real.dtype
                    ).astype(dt)
    with _timer(ns, "gemm"):
        m1 = matmul(p, g[:, :k])
        m2 = g[:, k:] - matmul(p, g[:, k:])
        basis = jnp.concatenate([m1, m2], axis=1)
    with _timer(ns, "qr"):
        f, taus = geqrf_rec(basis, nb)
        v = unmqr_rec(f, taus, eye, Side.Left, Op.NoTrans, nb)
    with _timer(ns, "gemm"):
        b = matmul(_ct(v), matmul(av, v))
    a1 = b[:k, :k]
    a2 = b[k:, k:]
    w1, z1 = _dc(0.5 * (a1 + _ct(a1)), nb, crossover, opts, ns, depth + 1)
    w2, z2 = _dc(0.5 * (a2 + _ct(a2)), nb, crossover, opts, ns, depth + 1)
    with _timer(ns, "gemm"):
        zz1 = matmul(v[:, :k], z1)
        zz2 = matmul(v[:, k:], z2)
    return (jnp.concatenate([w2, w1]),
            jnp.concatenate([zz2, zz1], axis=1))


def _heev_qdwh(a, jobz: bool, opts, ns: str):
    from .eig import _hermitian_full

    av = _hermitian_full(a)
    nb = _nb(a, opts)
    crossover = max(2, int(get_option(opts, "qdwh_crossover",
                                      config.qdwh_crossover)))
    w, z = _dc(av, nb, crossover, opts, ns, 0)
    order = jnp.argsort(w)
    if not jobz:
        return jnp.asarray(w[order]), None
    return jnp.asarray(w[order]), z[:, order]


def heev_qdwh(a, jobz: bool = True, opts: Optional[Options] = None):
    """QDWH-eig: Hermitian eigensolver by spectral divide-and-conquer
    over the polar factor (Nakatsukasa & Higham, 2013).  Same contract
    as :func:`~slate_tpu.linalg.eig.heev` — ``(w ascending, Z | None)``
    — reachable from it via the autotuned ``eig_driver`` site."""

    return _heev_qdwh(a, jobz, opts, "heev")


# ---------------------------------------------------------------------------
# QDWH-SVD
# ---------------------------------------------------------------------------

def svd_qdwh(a, jobu: bool = True, jobvt: bool = True,
             opts: Optional[Options] = None):
    """QDWH-SVD: A = U_p·H, then QDWH-eig of the SPSD factor H = VΣVᴴ,
    so A = (U_p·V)·Σ·Vᴴ.  Same contract as
    :func:`~slate_tpu.linalg.svd.svd` — ``(sigma descending, U, Vᴴ)``
    economy, None for unrequested factors — reachable from it via the
    autotuned ``svd_driver`` site."""

    av = as_array(a)
    m, n = av.shape
    if m < n:
        s, u, vh = svd_qdwh(_ct(av), jobu=jobvt, jobvt=jobu, opts=opts)
        return s, (None if vh is None else _ct(vh)), \
            (None if u is None else _ct(u))
    nb = _nb(a, opts)
    u_p = _polar_u(av, nb, opts, "svd")
    with _timer("svd", "gemm"):
        uh_a = matmul(_ct(u_p), av)
        h = 0.5 * (uh_a + _ct(uh_a))
    w, v = _heev_qdwh(h, True, opts, "svd")
    real_dt = np.zeros(0, dtype=av.dtype).real.dtype
    # H is SPSD: ascending eigenvalues reversed are the singular values
    s = jnp.maximum(jnp.asarray(w, dtype=real_dt)[::-1], 0)
    vd = v[:, ::-1]
    u = vh = None
    if jobu:
        with _timer("svd", "gemm"):
            u = matmul(u_p, vd)
    if jobvt:
        vh = _ct(vd)
    return s, u, vh
