"""LU family: getrf (partial-pivot / no-pivot / CALU tournament), getrs,
gesv, getri, plus the distributed-pivot helpers.

TPU-native re-design of the reference LU stack:

* ``src/getrf.cc`` (381 LoC) — right-looking LU with a multithreaded
  partial-pivot panel (``internal_getrf.cc`` + ``Tile_getrf.hh:154-320``:
  per-thread argmax, barrier, MPI MAXLOC, explicit row swaps).
* ``src/getrf_nopiv.cc`` — no pivoting.
* ``src/getrf_tntpiv.cc`` (456 LoC) — CALU tournament pivoting: local LU
  of stacked tiles + binary tournament (``internal_getrf_tntpiv.cc``).
* ``src/getrs.cc`` / ``src/gesv.cc`` / ``src/getri.cc`` /
  ``src/getriOOP.cc``.

Design stance (TPU-first, not a translation):

* **Pivots are permutation index vectors**, not LAPACK swap sequences
  (reference ``Pivots``, ``types.hh:64-97``).  A gather ``a[perm]`` is
  one XLA op that the compiler fuses and shards; a swap sequence is a
  serial chain.  :func:`perm_to_ipiv` / :func:`ipiv_to_perm` convert at
  the LAPACK-compat boundary.
* The **panel** is XLA's fused ``lax.linalg.lu`` on a tall block — the
  analog of the reference's multithreaded panel kernel
  (``Tile_getrf.hh``), with XLA:TPU owning the within-panel schedule
  instead of a hand-rolled ThreadBarrier.
* The **recursion** exposes one big trsm + one big gemm per level (the
  MXU hot loop), exactly like the reference's trailing update
  (``src/getrf.cc:175-215``), with XLA overlapping panel k+1 against
  update k the way OpenMP ``depend`` lookahead did.
* **Tournament pivoting** batches the stacked-tile LUs with ``vmap`` —
  MXU-shaped and free of cross-tile argmax latency — matching the
  communication-avoiding design goal of ``getrf_tntpiv`` (its MPI
  tournament becomes a tree reduction over the batch axis).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..enums import Diag, MethodLU, Norm, Op, Side, Uplo
from ..matrix import Matrix, as_array
from ..options import Options, get_option
from ..ops import blocks
from ..ops.blocks import matmul, matmul_hi
from ..perf import metrics
from ..perf.metrics import instrument_driver
from .blas3 import _nb, _wrap_like
from .norms import norm as _norm


# ---------------------------------------------------------------------------
# Pivot representation
# ---------------------------------------------------------------------------

def ipiv_to_perm(ipiv, m: int):
    """LAPACK ipiv (1-based swap sequence) → permutation vector."""
    perm = list(range(m))
    for k, p in enumerate(ipiv):
        p = int(p) - 1
        perm[k], perm[p] = perm[p], perm[k]
    return jnp.asarray(perm)


def perm_to_ipiv(perm):
    """Permutation vector → LAPACK 1-based swap sequence (for the
    LAPACK/ScaLAPACK compat layers; reference ``Pivots`` ``types.hh:64``)."""
    perm = [int(x) for x in perm]
    m = len(perm)
    ipiv = [0] * m
    cur = list(range(m))      # current row order being built by swaps
    loc = {r: i for i, r in enumerate(cur)}
    for k in range(m):
        j = loc[perm[k]]
        ipiv[k] = j + 1
        rk, rj = cur[k], cur[j]
        cur[k], cur[j] = rj, rk
        loc[rj], loc[rk] = k, j
    return jnp.asarray(ipiv, jnp.int32)


def inverse_perm(perm):
    return jnp.argsort(perm)


# ---------------------------------------------------------------------------
# Panels
# ---------------------------------------------------------------------------

def _panel_lu(a):
    """Partial-pivot panel factor: returns (lu, perm) with a[perm] = L·U.

    One fused XLA kernel (the analog of ``internal::getrf_panel``'s
    thread team, ``internal_getrf.cc:75-92``).
    """
    lu, _, perm = lax.linalg.lu(a)
    return lu, perm


#: widest panel the one-call Pallas leaf accepts (VMEM: the (w, m)
#: transposed slab + its output copy + scratch must fit)
_PALLAS_PANEL_MAX_M = 16384


def _panel_lu_pallas(a):
    """Partial-pivot panel factor in ONE Pallas invocation — the r4→r5
    fix for LU's panel bottleneck (VERDICT r4 Next #1): XLA's fused
    ``lax.linalg.lu`` costs ~1.5 ms per (m, 512) panel on v5e (16
    panels ≈ 23 ms of the 41 ms total at n=8192); the masked
    lane-major kernel factors the whole transposed panel in VMEM at
    ~1 µs per column step with TRUE partial pivoting (argmax of the
    fully-updated column over all active rows; pivots match LAPACK up
    to magnitude ties).  Same contract as :func:`_panel_lu`:
    ``a[perm] = L·U`` packed LAPACK-style.

    Matches the reference's multithreaded panel kernel
    (``src/internal/Tile_getrf.hh:154-320``) in role; the scattered
    no-row-motion form replaces its swap traffic, and the single
    column gather at the end re-packs.
    """

    return _panel_lu_lane_major(a, "getrf_panel_linv")


def _panel_lu_fused(a):
    """Partial-pivot panel factor through the fused mega-kernel
    (:func:`~slate_tpu.ops.pallas_kernels.getrf_panel_fused` at k0=0):
    the same contract as :func:`_panel_lu_pallas`, but the kernel's
    grid iterates the panel's bb-wide column-block steps instead of
    unrolling the whole width — one compilation per (w, m) bucket at a
    fraction of the monolithic kernel's Mosaic compile time, and a
    single-copy VMEM working set (the slab is held once, not
    in+out)."""

    return _panel_lu_lane_major(a, "getrf_panel_fused")


def _panel_lu_lane_major(a, kernel_name: str):
    """Shared pad-to-bucket / call / perm-assembly wrapper for the
    lane-major scattered panel kernels."""

    m, w = a.shape
    from ..perf.autotune import kernel
    # bucket the lane dimension to the next power of two: the recursion
    # produces ~n/nb distinct panel heights, and each distinct slab
    # shape is a separate Mosaic kernel compile (~40 s each); buckets
    # cap that at log2 shapes.  Padding rows enter with act=0, so the
    # masked argmax can never select them.
    m_pad = max(512, 1 << (m - 1).bit_length())
    at = a.T                                   # (w, m) lane-major slab
    if m_pad != m:
        at = jnp.pad(at, ((0, 0), (0, m_pad - m)))
    act = (jnp.arange(m_pad) < m).astype(jnp.float32).reshape(1, m_pad)
    if kernel_name == "getrf_panel_fused":
        out, piv, act_out, linv = kernel(kernel_name)(
            at, act, 0, nb=w, bb=min(128, w), ib=32)
    else:
        out, piv, act_out, linv = kernel(kernel_name)(at, act, ib=32)
    if m > w:
        # active (non-pivot) rows follow in original order
        rem = jnp.argsort(act_out[0, :m] < 0.5, stable=True)[: m - w]
        perm = jnp.concatenate([piv, rem])
    else:
        perm = piv
    return out[:, perm].T, perm, linv


from ..ops import vmem as _vmem


def _use_pallas_panel(m: int, w: int, dtype) -> bool:
    import jax as _jax
    from .. import config
    if config.use_pallas_mode() == "off":
        return False
    if not (dtype == jnp.float32 and w % 32 == 0 and m % 8 == 0
            and w >= 64 and m >= w and m <= _PALLAS_PANEL_MAX_M
            and m >= 3072 and _jax.default_backend() == "tpu"):
        return False
    # VMEM budget on panel WIDTH, not just height: the kernel holds the
    # (w, m_pad) transposed slab plus its output copy (2·w·m_pad·4 B)
    # and the (ib, m_pad) + (w, w) + linv/act scratch; at nb=1024 the
    # slab pair alone is ~134 MB at m_pad=16384 and Mosaic fails to
    # compile — fall back to the XLA panel instead
    if w <= 512:
        return True
    m_pad = max(512, 1 << (m - 1).bit_length())
    scratch = (32 * m_pad + 2 * w * w + 2 * m_pad) * 4
    return _vmem.fits(2 * w * m_pad * 4 + scratch)


def _use_fused_panel(m: int, w: int, dtype) -> bool:
    """VMEM-budget eligibility of the fused mega-kernel as an
    ``lu_panel`` candidate (:func:`_panel_lu_fused`): the shape gate of
    :func:`_use_pallas_panel` plus the kernel's own grid divisibility
    (bb=min(128, w) column-block steps), but the VMEM term differs —
    the kernel holds the (w, m_pad) slab ONCE (aliased HBM carry, no
    output copy) plus two (bb, m_pad) block scratches and the (w, w)
    inverse pair, so wider panels fit."""
    import jax as _jax
    from .. import config
    if config.use_pallas_mode() == "off":
        return False
    if not (dtype == jnp.float32 and w % 32 == 0 and m % 8 == 0
            and w >= 64 and m >= w and m <= _PALLAS_PANEL_MAX_M
            and m >= 3072 and (w <= 128 or w % 128 == 0)
            and _jax.default_backend() == "tpu"):
        return False
    m_pad = max(512, 1 << (m - 1).bit_length())
    bb = min(128, w)
    scratch = (2 * bb * m_pad + 3 * w * w + 2 * bb * bb + 2 * m_pad) * 4
    return _vmem.fits(w * m_pad * 4 + scratch)


def _panel_lu_auto(a):
    """Panel dispatch through the autotune table
    (:func:`slate_tpu.method.select_backend`): the Pallas one-call
    leaves — the monolithic unrolled kernel (``pallas``) and the fused
    grid-stepped mega-kernel (``pallas_fused``) — are timed against
    XLA's fused ``lax.linalg.lu`` per (m, w, dtype) key wherever
    :func:`_use_pallas_panel` / :func:`_use_fused_panel` admit them
    (TPU, f32, tall panels — their per-step cost is flat in m, XLA's
    scales with m, so short panels keep XLA's fused kernel).  Returns
    ``(lu, perm)`` or ``(lu, perm, linv)`` — the recursion uses the
    panel inverse to turn the u12 triangular solve into MXU gemms."""
    m, w = a.shape
    from ..method import select_backend
    choice = select_backend("lu_panel", m=m, w=w, dtype=a.dtype,
                            eligible=_use_pallas_panel(m, w, a.dtype),
                            eligible_fused=_use_fused_panel(m, w, a.dtype))
    if choice == "pallas":
        return _panel_lu_pallas(a)
    if choice == "pallas_fused":
        return _panel_lu_fused(a)
    return _panel_lu(a)


def _panel_lu_nopiv(a, ib: int = 128):
    """No-pivot panel via inner blocking ``ib`` (reference
    ``Option::InnerBlocking``): recursion down to an unblocked masked
    ``fori_loop`` of rank-1 updates.  The base is one traced loop body
    regardless of width, so ``ib`` trades trace size (2·n/ib recursion
    nodes) against how much of the update runs as VPU rank-1s instead
    of MXU matmuls; 128 keeps compile time flat and the VPU share of a
    512-wide panel under 2·m·128² flops per base."""

    m, n = a.shape
    if n <= ib:
        def body(k, acc):
            col = acc[:, k]
            piv = acc[k, k]
            rows = jnp.arange(m)
            factor = jnp.where(rows > k, col / piv, 0)
            urow = jnp.where(jnp.arange(n)[None, :] > k, acc[k, :][None, :], 0)
            acc = acc - factor[:, None] * urow
            return acc.at[:, k].set(jnp.where(rows > k, factor, col))
        return lax.fori_loop(0, min(m, n), body, a)
    n1 = n // 2
    f1 = _panel_lu_nopiv(a[:, :n1], ib)
    l11 = f1[:n1]
    u12 = lax.linalg.triangular_solve(
        l11, a[:n1, n1:], left_side=True, lower=True, unit_diagonal=True)
    a22 = a[n1:, n1:] - matmul(f1[n1:], u12)
    f2 = _panel_lu_nopiv(a22, ib)
    top = jnp.concatenate([f1[:n1], u12], axis=1)
    bot = jnp.concatenate([f1[n1:], f2], axis=1)
    return jnp.concatenate([top, bot], axis=0)


def _panel_lu_tntpiv(a, nb: int):
    """CALU tournament-pivot panel (reference ``getrf_tntpiv``,
    ``internal_getrf_tntpiv.cc``): round 0 factors every mb-row tile
    independently (batched — vmap over the stack, one MXU batch like the
    reference's batched device getrf); each tournament round stacks pairs
    of winners and re-factors, halving the candidate set; the final
    pivot rows factor the panel exactly.

    Returns (lu, perm) with a[perm] = L·U — same contract as
    :func:`_panel_lu`, different (communication-avoiding) pivot choice.
    """

    m, n = a.shape
    mb = max(nb, n)
    nt = -(-m // mb)
    pad_m = nt * mb
    apad = jnp.zeros((pad_m, n), a.dtype)
    # padded rows must never win the tournament: they are exact zeros
    apad = apad.at[:m, :].set(a)
    rows = jnp.arange(pad_m)

    # round 0: independent LU of each tile (batched)
    tiles = apad.reshape(nt, mb, n)
    _, _, perms = jax.vmap(lax.linalg.lu)(tiles)
    # candidate = top-n rows of each tile, in pivoted order
    cand_rows = (perms[:, :n] + (jnp.arange(nt) * mb)[:, None]).reshape(-1)

    # tournament tree: pairwise stack candidates, re-factor, keep top n
    while cand_rows.shape[0] > n:
        k = cand_rows.shape[0]
        if (k // n) % 2 == 1:        # odd number of contenders: bye
            bye = cand_rows[-n:]
            cand_rows = cand_rows[:-n]
        else:
            bye = None
        pairs = cand_rows.reshape(-1, 2 * n)
        stacked = apad[pairs.reshape(-1)].reshape(-1, 2 * n, n)
        _, _, perms = jax.vmap(lax.linalg.lu)(stacked)
        win = jnp.take_along_axis(pairs, perms[:, :n], axis=1).reshape(-1)
        cand_rows = jnp.concatenate([win, bye]) if bye is not None else win

    # the n winning rows lead; the rest follow in original order (stable
    # argsort); re-factor only the n×n winner block (pivoting *within*
    # winners is local), then one trailing solve for L21 — no further
    # pivoting, the tournament already guaranteed a strong pivot block
    mask = jnp.zeros((pad_m,), bool).at[cand_rows].set(True)
    order = jnp.argsort(~mask, stable=True)
    ap = apad[order]
    lu_top, _, p2 = lax.linalg.lu(ap[:n])
    l21 = lax.linalg.triangular_solve(
        jnp.triu(lu_top), ap[n:], left_side=False, lower=False)
    lu = jnp.concatenate([lu_top, l21], axis=0)
    order = jnp.concatenate([order[:n][p2], order[n:]])
    # drop padded rows (they are exact zeros and never chosen as pivots)
    sel = jnp.argsort(order >= m, stable=True)[:m]
    return lu[sel], order[sel]


# ---------------------------------------------------------------------------
# Blocked factorization
# ---------------------------------------------------------------------------

def _u12_with_linv(lu_top, linv, c):
    """U₁₂ from the panel's unit-lower inverse: the inverse is
    Newton-refined ONCE at panel scale (``X₂ = X(2I − L₁₁X)`` — nb³
    flops, HIGHEST) and then applied with ONE MXU gemm plus one
    residual-correction gemm (measured: XLA's trsm costs ~0.4 ms per
    panel, 6.5 of getrf's 41 ms at n=8192).  Guarded (mirrors the
    geqrf CholQR² devmax guard): ‖r₁‖∞/‖c‖∞ = ‖(I − L11·X₂)·c‖∞ /
    ‖c‖∞ reuses the correction residual already computed; Newton steps
    square a small departure but cannot rescue a wrong inverse — past
    the threshold the exact trsm takes over.  The refinement squares
    the departure the guard sees, so fallback ACTIVATIONS drop
    quadratically (countable via ``SLATE_TPU_METRICS_DEVICE=1``), and
    the fallback branch solves against the SAME ``l11`` operand the
    residual already materialized — the raw panel slice is no longer
    kept live in HBM just for the cond's cold branch."""

    n1 = lu_top.shape[0]
    l11 = jnp.tril(lu_top, -1) + jnp.eye(n1, dtype=lu_top.dtype)
    li = linv.astype(lu_top.dtype)
    li = 2.0 * li - matmul_hi(li, matmul_hi(l11, li))
    u12 = matmul(li, c)
    r1 = c - matmul(l11, u12)
    dev = jnp.max(jnp.abs(r1)) / jnp.maximum(
        jnp.max(jnp.abs(c)), jnp.finfo(lu_top.dtype).tiny)
    if metrics.enabled():
        metrics.inc("lu.u12_linv.sites")      # trace-time: guarded sites
    if metrics.device_metrics_wanted():
        # runtime outcome of the guard (which branch the cond takes)
        # needs a device→host callback, so it rides its OWN opt-in knob:
        # with SLATE_TPU_METRICS_DEVICE unset no callback is traced and
        # the compiled program is bit-identical to the uninstrumented one
        jax.debug.callback(metrics.record_fallback_outcome, dev >= 1e-2)
    return lax.cond(
        dev < 1e-2,
        lambda _: u12 + matmul(li, r1),
        lambda _: lax.linalg.triangular_solve(
            l11, c, left_side=True, lower=True, unit_diagonal=True),
        operand=None)


def getrf_rec(a, nb: int, panel=_panel_lu_auto):
    """Blocked right-looking LU with row pivoting: a[perm] = L·U packed
    LAPACK-style (unit L strictly below, U on/above the diagonal).

    Recursive equivalent of the reference driver loop
    ``src/getrf.cc:94-215`` (panel → pivot bcast → row swaps → trsm →
    gemm trailing update).
    """

    m, n = a.shape
    if m < n:
        # wide: factor the square left part, then one trsm for the rest
        # of U (LAPACK getrf semantics; reference supports m < n)
        lu_l, perm = getrf_rec(a[:, :m], nb, panel)
        u_r = lax.linalg.triangular_solve(
            lu_l, a[perm][:, m:], left_side=True, lower=True,
            unit_diagonal=True)
        return jnp.concatenate([lu_l, u_r], axis=1), perm
    if n <= nb:
        out = panel(a)
        return (out[0], out[1]) if len(out) > 2 else out
    n1 = blocks._split(n, nb)
    if n1 <= nb:
        out = panel(a[:, :n1])
        lu1, perm1 = out[0], out[1]
        linv = out[2] if len(out) > 2 else None
    else:
        lu1, perm1 = getrf_rec(a[:, :n1], nb, panel)
        linv = None
    right = a[perm1][:, n1:]           # permuteRows of the trailing block
    if linv is not None:
        u12 = _u12_with_linv(lu1[:n1], linv, right[:n1])
    else:
        u12 = lax.linalg.triangular_solve(
            lu1[:n1], right[:n1], left_side=True, lower=True,
            unit_diagonal=True)
    a22 = right[n1:] - matmul(lu1[n1:], u12)
    lu2, perm2 = getrf_rec(a22, nb, panel)
    l21 = lu1[n1:][perm2]
    top = jnp.concatenate([lu1[:n1], u12], axis=1)
    bot = jnp.concatenate([l21, lu2], axis=1)
    perm = jnp.concatenate([perm1[:n1], perm1[n1:][perm2]])
    return jnp.concatenate([top, bot], axis=0), perm


#: tallest panel XLA's fused LuDecompositionBlock can hold in scoped
#: VMEM on v5e (f32[16384,128] blocks overflow the 16M scoped limit)
_MAX_LU_PANEL_ROWS = 8192


def _tall_panel_lu(pan, max_rows: int = _MAX_LU_PANEL_ROWS):
    """Tournament (CALU) factorization of a panel taller than the fused
    XLA LU kernel's VMEM limit — reference ``getrf_tntpiv``
    (``src/getrf_tntpiv.cc``): round 0 factors row chunks independently,
    rounds stack pairs of winner sets; the winner block leads and the
    panel factors against it without further row search.

    Returns ``(lu_packed, pl)`` with ``pl`` the full local row
    permutation (``pan[pl] = L·U``) — the same contract as
    ``lax.linalg.lu``'s third output.
    """

    m, w = pan.shape
    # round 0: winners of each chunk
    cand = []
    for c0 in range(0, m, max_rows):
        chunk = pan[c0:c0 + max_rows]
        if chunk.shape[0] <= w:
            cand.append(c0 + jnp.arange(chunk.shape[0]))
            continue
        _, _, cperm = lax.linalg.lu(chunk)
        cand.append(c0 + cperm[:w])
    rows = jnp.concatenate(cand)
    # knockout rounds on stacked winners
    while rows.shape[0] > w:
        take = min(2 * w, rows.shape[0])
        stacked = pan[rows[:take]]
        _, _, sperm = lax.linalg.lu(stacked)
        winners = rows[:take][sperm[:w]]
        rows = jnp.concatenate([winners, rows[take:]]) \
            if rows.shape[0] > take else winners
    # full permutation: winners first (tournament order), the rest in
    # original order — any L21 row order is valid as long as tracked
    is_w = jnp.zeros((m,), bool).at[rows].set(True)
    pos = jnp.full((m,), m, dtype=rows.dtype).at[rows].set(
        jnp.arange(w, dtype=rows.dtype))
    score = jnp.where(is_w, pos, m + jnp.arange(m, dtype=rows.dtype))
    pl = jnp.argsort(score)
    permuted = pan[pl]
    # factor the winner block (pivoting inside the top w×w is local),
    # then one triangular solve for L21
    top, _, permw = lax.linalg.lu(permuted[:w])
    pl = jnp.concatenate([pl[:w][permw], pl[w:]])
    l21 = lax.linalg.triangular_solve(
        jnp.triu(top), permuted[w:], left_side=False, lower=False)
    return jnp.concatenate([top, l21], axis=0), pl


def _tall_panel_lu_pp(pan, ib: int = 64):
    """TRUE partial-pivot factorization of a panel taller than the fused
    XLA LU kernel's VMEM limit — the analog of the reference's
    multithreaded panel (``Tile_getrf.hh:154-320``: per-column global
    argmax, swap, rank-1), expressed as an inner-blocked
    ``lax.fori_loop`` so each rank-1 update touches only an ib-wide
    slab.  Unlike :func:`_tall_panel_lu` (tournament/CALU), every pivot
    is the argmax of the fully-updated column, preserving partial
    pivoting's element-growth guarantee for callers who explicitly
    selected ``MethodLU.PartialPiv``.

    Returns ``(lu_packed, pl)`` with ``pan[pl] = L·U`` — the same
    contract as ``lax.linalg.lu``'s first/third outputs.
    """

    m, w = pan.shape
    a = pan
    gperm = jnp.arange(m)
    for b0 in range(0, w, ib):
        bw = min(ib, w - b0)
        slab = a[b0:, b0:b0 + bw]
        mrows = slab.shape[0]
        rows = jnp.arange(mrows)

        def body(jj, carry):
            slab, bperm = carry
            mag = jnp.abs(slab[:, jj])
            mag = jnp.where(rows >= jj, mag, -1.0)
            p = jnp.argmax(mag)
            rj, rp = slab[jj], slab[p]
            slab = slab.at[jj].set(rp).at[p].set(rj)
            bj, bp = bperm[jj], bperm[p]
            bperm = bperm.at[jj].set(bp).at[p].set(bj)
            pivval = slab[jj, jj]
            denom = jnp.where(pivval == 0, 1, pivval)
            lcol = jnp.where(rows > jj, slab[:, jj] / denom, slab[:, jj])
            slab = slab.at[:, jj].set(lcol)
            upd = jnp.outer(jnp.where(rows > jj, lcol, 0),
                            jnp.where(jnp.arange(bw) > jj, slab[jj], 0))
            return slab - upd, bperm

        slab, bperm = lax.fori_loop(0, bw, body, (slab, jnp.arange(mrows)))
        body_rows = a[b0:][bperm]
        body_rows = body_rows.at[:, b0:b0 + bw].set(slab)
        gperm = gperm.at[b0:].set(gperm[b0:][bperm])
        if b0 + bw < w:
            u12 = lax.linalg.triangular_solve(
                slab[:bw], body_rows[:bw, b0 + bw:], left_side=True,
                lower=True, unit_diagonal=True)
            body_rows = body_rows.at[:bw, b0 + bw:].set(u12)
            body_rows = body_rows.at[bw:, b0 + bw:].add(
                -matmul(slab[bw:], u12))
        a = a.at[b0:].set(body_rows)
    return a, gperm


def getrf_panels(a, nb: int = 512, tall_panel: str = "tournament"):
    """Right-looking blocked partial-pivot LU (loop form): per panel,
    the autotuned panel leaf (:func:`_panel_lu_auto` — XLA's fused
    ``lax.linalg.lu``, the vendor ``getrf`` slot
    ``internal_getrf.cc:75-92``, vs the Pallas one-call leaves) or, for
    panels taller than the kernel's VMEM limit, either the CALU
    tournament (``tall_panel="tournament"``, the Auto default —
    stronger MXU utilisation, weaker growth bound) or the true
    partial-pivot loop (``"pp"`` — what an explicit
    ``MethodLU.PartialPiv`` request gets), then ONE permutation gather
    of the sub-matrix rows and one big trailing gemm.  When the Pallas
    leaf wins it hands back the panel's L11⁻¹ and the u12 triangular
    solve becomes MXU gemms (:func:`_u12_with_linv`).  Returns
    ``(lu, perm)`` with ``a[perm] = L·U``.

    The per-panel gather reads/rewrites the (m-k0)×n trailing slab —
    ~HBM-bound but measured 5× FASTER under jit than "cheap"
    transposition-pair swaps, whose 2·nb sequential 2-row updates per
    panel are pure dispatch latency on an accelerator.
    """

    m, n = a.shape
    k = min(m, n)
    gperm = jnp.arange(m)
    for k0 in range(0, k, nb):
        w = min(nb, k - k0)
        pan = a[k0:, k0:k0 + w]
        linv = None
        if pan.shape[0] > _MAX_LU_PANEL_ROWS:
            if tall_panel == "pp":
                lu_p, pl = _tall_panel_lu_pp(pan)
            else:
                lu_p, pl = _tall_panel_lu(pan)
        else:
            out = _panel_lu_auto(pan)
            lu_p, pl = out[0], out[1]
            linv = out[2] if len(out) > 2 else None
        # one permutation gather of the sub-matrix rows (left L-blocks +
        # trailing); sequential transposition loops measured 5× worse
        # under jit (32k tiny device steps of pure latency)
        body = a[k0:][pl]
        body = body.at[:, k0:k0 + w].set(lu_p)
        gperm = gperm.at[k0:].set(gperm[k0:][pl])
        if k0 + w < n:
            if linv is not None:
                u12 = _u12_with_linv(lu_p[:w], linv, body[:w, k0 + w:])
            else:
                u12 = lax.linalg.triangular_solve(
                    lu_p[:w], body[:w, k0 + w:], left_side=True,
                    lower=True, unit_diagonal=True)
            body = body.at[:w, k0 + w:].set(u12)
            if w < body.shape[0]:
                body = body.at[w:, k0 + w:].add(-matmul(lu_p[w:], u12))
        a = a.at[k0:].set(body)
    return a, gperm


def _fused_step_tc(m: int, n: int, nb: int) -> int:
    """Trailing-chunk height for the fused LU step: the largest divisor
    of nb (floor 128) whose double-buffered (tc, m) pair fits the VMEM
    budget (:mod:`slate_tpu.ops.vmem`) next to the resident panel, Π/G
    and block scratches."""
    return _vmem.largest_tc(nb, lambda tc: _fused_step_bytes(m, nb, tc))


def _fused_step_bytes(m: int, nb: int, tc: int, bb: int = 128) -> int:
    bb = min(bb, nb)
    return 4 * (m * (2 * nb + 2 * bb + 2 * tc + 2)
                + 2 * nb * nb + 2 * bb * bb)


def _use_fused_step(m: int, n: int, nb: int, dtype) -> bool:
    """Shape/VMEM ELIGIBILITY of the fused whole-step LU kernel
    (:func:`~slate_tpu.ops.pallas_kernels.getrf_step_fused`) for the
    scattered driver: the scattered driver's own gate already holds
    (f32, min(m,n) % nb == 0, m % 8 == 0); on top, the trailing chunks
    must tile the carry exactly (n % 128 == 0 keeps a tc divisor
    available) and the resident panel + Π/G pair + double-buffered
    chunks must fit VMEM.  Whether an eligible shape actually takes a
    fused depth is the ``lu_step`` autotune decision."""
    from .. import config
    if config.use_pallas_mode() == "off":
        return False
    if nb % 128 != 0:
        return False
    tc = _fused_step_tc(m, n, nb)
    if n % tc != 0:
        return False
    return _vmem.fits(_fused_step_bytes(m, nb, tc))


def _full_fused_bytes(m: int, nb: int, tc: int, bb: int = 128) -> int:
    """Resident working set of the whole-factorization LU mega-kernel:
    the step kernel's set plus the (nb, m) lookahead panel buffer and
    the (nb, nb) panel-inverse scratch."""
    bb = min(bb, nb)
    return 4 * (m * (3 * nb + 2 * bb + 2 * tc + 2)
                + 3 * nb * nb + 2 * bb * bb)


def _full_fused_tc(m: int, nb: int) -> int:
    return _vmem.largest_tc(nb, lambda tc: _full_fused_bytes(m, nb, tc))


def _use_full_fused(m: int, n: int, nb: int, dtype) -> bool:
    """Shape/VMEM ELIGIBILITY of the whole-factorization LU mega-kernel
    (:func:`~slate_tpu.ops.pallas_kernels.getrf_full_fused`, depth
    ``full``): the fused-step conditions with the larger resident set —
    the lookahead holds TWO (nb, m) panels in VMEM at once.  Whether an
    eligible shape actually takes the full depth is the ``lu_step``
    autotune decision."""
    from .. import config
    if config.use_pallas_mode() == "off":
        return False
    if nb % 128 != 0:
        return False
    tc = _full_fused_tc(m, nb)
    if n % tc != 0:
        return False
    return _vmem.fits(_full_fused_bytes(m, nb, tc))


def _scattered_tail(at, piv_all, act, m: int, k: int):
    """Recover the packed LAPACK layout from the scattered carry — the
    factorization-order pivots plus, for m > k, the never-pivoted
    remainder rows in stable scatter order, with ONE column gather at
    the very end.  Shared by every depth of :func:`getrf_scattered` so
    the tail contract (the act < 0.5 threshold, the stable sort) cannot
    diverge between them."""
    if m > k:
        rem = jnp.argsort(act[0, :] < 0.5, stable=True)[: m - k]
        perm = jnp.concatenate([piv_all, rem])
    else:
        perm = piv_all
    return at[:, perm].T, perm


def getrf_scattered(a, nb: int = 512, bb: int = 128, step=None):
    """Right-looking partial-pivot LU in SCATTERED-ROW form — the
    TPU-native re-design of the reference driver loop
    (``src/getrf.cc:94-215``) that eliminates its per-panel row-swap
    traffic (``internal_swap.cc``):

    * pivoting is LOGICAL: each pivot is the masked argmax over the
      still-active rows and retires the row from the mask — no row
      ever moves (XLA's fused LU panel and jax-level loop panels both
      cost ~30 µs per column step in HBM round trips; the
      VMEM-resident masked step costs ~2 µs);
    * ONE Pallas invocation owns each panel's whole column-block loop
      (:func:`~slate_tpu.ops.pallas_kernels.getrf_panel_fused`): the
      grid iterates the bb-wide block steps over the VMEM-resident
      panel, the HBM carry is aliased, and ``k0`` is a scalar operand
      — one compilation and two DMAs per panel, replacing the r4/r5
      per-block call chain (64 invocations at n=8192/nb=512) whose
      glue — unaliased carry copies (~26 ms/block), per-block
      transposes (~2 ms) — cost ~30 µs/step against the kernel's
      measured 2.2 µs/step;
    * the WHOLE matrix lives TRANSPOSED for the factorization (one
      transpose in, one column gather + transpose out);
    * the panel's unit-lower inverse rides out of the kernel, so every
      trailing triangular solve is a gemm plus one residual-correction
      step (solve-grade accuracy, all-MXU), with the trailing
      permutation applied inside the U₁₂ operand gather;
    * the trailing update runs over ALL m rows with retired rows'
      multipliers zeroed (static-slice writes — no scatter of the big
      trailing slab).

    The STEP composition is itself autotuned (the ``lu_step`` site,
    fusion depth per (m, n, nb, dtype)): ``"composed"`` keeps the
    panel kernel + XLA glue above; ``"fused_trsm"`` moves the
    pivot-gather-fused U₁₂ solve into the panel's invocation (panel +
    trsm depth); ``"fused"`` makes the WHOLE step one pallas_call —
    panel, trsm and the double-buffered streamed rank-nb trailing
    update share one VMEM residency against the aliased carry
    (:func:`~slate_tpu.ops.pallas_kernels.getrf_step_fused`), zero
    materialized intermediates between sub-stages
    (``step.hbm_roundtrips == 0``, pinned in CI); ``"full"`` goes one
    rung further — ONE pallas_call owns the ENTIRE factorization
    (:func:`~slate_tpu.ops.pallas_kernels.getrf_full_fused`): the grid
    iterates the block-column steps, the layout state carries across
    them, and each step's trailing phase lookahead-updates the next
    panel in VMEM, so ``step.hbm_roundtrips == 0`` holds for the whole
    factorization with a single kernel launch.  ``step`` overrides the
    table (the autotuner's probe hook).

    Returns ``(lu, perm)`` with ``a[perm] = L·U`` — the
    :func:`getrf_rec` contract.  Requires min(m,n) % nb == 0; f32 on
    TPU (f32/f64 in interpret mode).
    """

    from ..perf.autotune import kernel

    m, n = a.shape
    k = min(m, n)
    bb = min(bb, nb)
    assert nb % bb == 0, (nb, bb)   # blocks must tile the panel exactly
    if step is None:
        from ..method import select_backend
        step = select_backend(
            "lu_step", m=m, n=n, nb=nb, dtype=a.dtype,
            eligible=_use_fused_step(m, n, nb, a.dtype),
            eligible_full=_use_full_fused(m, n, nb, a.dtype))
    if step == "full":
        # the whole factorization — every step's panel + trsm + trailing
        # update, with in-kernel lookahead — is ONE pallas invocation on
        # the aliased carry: zero materialized intermediates anywhere
        at = a.T
        act = jnp.ones((1, m), a.dtype)
        metrics.inc("step.getrf.steps", float(k // nb))
        with metrics.step_timer("getrf", "full"):
            at, piv_all, act = kernel("getrf_full_fused")(
                at, act, nb=nb, bb=bb, tc=_full_fused_tc(m, nb))
        return _scattered_tail(at, piv_all, act, m, k)
    if step in ("fused", "fused_trsm"):
        getrf_step_fused = kernel("getrf_step_fused")
        tc = _fused_step_tc(m, n, nb)
    else:
        getrf_panel_fused = kernel("getrf_panel_fused")
    at = a.T
    act = jnp.ones((1, m), a.dtype)
    pivs = []
    for k0 in range(0, k, nb):
        metrics.inc("step.getrf.steps")
        if step == "fused":
            # the whole step — panel + pivot-gather-fused trsm + rank-nb
            # trailing update — is ONE pallas invocation on the aliased
            # carry: zero materialized intermediates between sub-stages
            with metrics.step_timer("getrf", "fused"):
                at, piv, act, _ = getrf_step_fused(
                    at, act, k0, nb=nb, bb=bb, tc=tc)
            pivs.append(piv)
            continue
        if step == "fused_trsm":
            # panel + trsm depth: the kernel factors the panel AND
            # scatters the solved U₁₂ into the pivot lanes; only the
            # rank-nb trailing gemm stays in XLA (one gather to rebuild
            # its operand — counted as the depth's single round trip)
            with metrics.step_timer("getrf", "fused"):
                at, piv, act, _ = getrf_step_fused(
                    at, act, k0, nb=nb, bb=bb, tc=tc, update=False)
            pivs.append(piv)
            if k0 + nb < n:
                metrics.count_hbm_roundtrips(1.0)
                with metrics.step_timer("getrf", "update"):
                    lmt = at[k0:k0 + nb, :] * act
                    u12t = at[k0 + nb:, :][:, piv]
                    at = at.at[k0 + nb:, :].add(-matmul(u12t, lmt))
            continue
        with metrics.step_timer("getrf", "panel"):
            at, piv, act, linv = getrf_panel_fused(at, act, k0,
                                                   nb=nb, bb=bb)
        pivs.append(piv)
        if k0 + nb < n:
            # composed glue: the pivot-row gather, the u12 write-back
            # and the trailing read-modify-write each materialize an
            # HBM intermediate the fused step does not
            metrics.count_hbm_roundtrips(3.0)
            with metrics.step_timer("getrf", "trsm"):
                slab_t = at[k0:k0 + nb, :]
                l11 = (jnp.tril(slab_t[:, piv].T, -1)
                       + jnp.eye(nb, dtype=a.dtype))
                linv = linv.astype(a.dtype)
                c1t = at[k0 + nb:, :][:, piv]
                u12t = matmul_hi(c1t, linv.T)
                u12t = u12t + matmul_hi(c1t - matmul_hi(u12t, l11.T),
                                        linv.T)
            with metrics.step_timer("getrf", "update"):
                lmt = slab_t * act
                at = at.at[k0 + nb:, :].add(-matmul(u12t, lmt))
                at = at.at[k0 + nb:, piv].set(u12t)
    piv_all = jnp.concatenate(pivs) if len(pivs) > 1 else pivs[0]
    return _scattered_tail(at, piv_all, act, m, k)


#: panel width of the scattered driver (the fused kernel's nb)
_SCATTERED_NB = 512


def _use_scattered(av, nb: int) -> bool:
    """Shape/VMEM ELIGIBILITY of the scattered-row fused-panel driver:
    f32 matrices whose (nb, m) panel fits the kernel's VMEM budget
    (m ≤ 16384) on a uniform tile grid.  Whether an eligible shape
    actually takes the driver is the autotune table's decision
    (``lu_driver`` op site, :func:`slate_tpu.perf.autotune.
    choose_lu_driver`): timed against :func:`getrf_rec` on TPU, forced
    with ``SLATE_TPU_SCATTERED_LU=1/0`` or
    ``SLATE_TPU_AUTOTUNE_FORCE=lu_driver=scattered`` — no raw env read
    lives here."""
    from .. import config
    if config.use_pallas_mode() == "off":
        return False      # the documented force-off escape hatch wins
    if av.ndim != 2:
        return False
    m, n = av.shape
    return (av.dtype == jnp.float32
            and min(m, n) % nb == 0 and m <= 16384 and m >= nb
            and m % 8 == 0)              # kernel lane-tile divisibility


def _getrf_partial(av, nb: int, raw_method=MethodLU.Auto):
    """The PartialPiv driver dispatch: the scattered fused-panel driver
    where the autotune table picks it (``lu_driver`` site), else the
    tall-panel loop or the blocked recursion.  Shared by
    :func:`getrf` and the bench harness so the measured path IS the
    shipped path.

    With ``SLATE_TPU_ABFT`` on (ISSUE 14) eager square calls route
    through the checksum-carried ABFT layer
    (:mod:`slate_tpu.resilience.abft`): the composed rung runs the
    Huang–Abraham step loop (checksum block-row/column riding each
    step's trailing gemm, per-step verify/correct/recompute), the
    scattered/fused/full Pallas rungs run under the checksum envelope.
    Off (default) this is one env read — same path, bit-identical
    lowering."""
    from ..resilience import abft as _abft

    if _abft.eligible(av):
        return _abft.getrf_guarded(av, nb, raw_method)
    return _getrf_partial_impl(av, nb, raw_method)


def _choose_lu_driver(av) -> str:
    """The ``lu_driver`` site decision for one operand — ONE derivation
    shared by the shipped dispatch and the ABFT layer (which must
    predict the same branch it wraps; a second hand-rolled eligibility
    check here would drift)."""
    from ..method import select_backend
    m, n = (av.shape[0], av.shape[1]) if av.ndim == 2 else (0, 0)
    return select_backend(
        "lu_driver", m=m, n=n, nb=_SCATTERED_NB, dtype=av.dtype,
        eligible=_use_scattered(av, _SCATTERED_NB))


def _getrf_partial_impl(av, nb: int, raw_method=MethodLU.Auto):
    from . import ooc as _ooc

    if _ooc.choose(av) == "pool":
        # out-of-core (ISSUE 17): the matrix lives in host DRAM as an
        # (nb, nb)-tile grid and factors through a bounded HBM window
        # (ops/tilepool.py) — same (lu, perm) contract, the existing
        # in-core kernels do every flop on resident operands
        return _ooc.getrf_ooc(av)
    return _getrf_incore(av, nb, raw_method)


def _getrf_incore(av, nb: int, raw_method=MethodLU.Auto):
    """The in-core PartialPiv body below the ``ooc`` gate — also the
    panel factor of the out-of-core driver itself, which must never
    re-enter the gate (a forced-pool panel would recurse)."""
    driver = _choose_lu_driver(av)
    if driver == "scattered":
        # TPU f32 fast path: scattered-row partial pivoting (no swap
        # traffic, one fused Pallas panel invocation per step) — LAPACK
        # pivots up to magnitude ties (on an exact tie the kernel takes
        # the lowest still-active physical row, LAPACK the first max in
        # swapped order), same (lu, perm) contract
        return getrf_scattered(av, _SCATTERED_NB)
    if av.ndim == 2 and av.shape[0] > _MAX_LU_PANEL_ROWS:
        # tall panels exceed XLA's scoped-VMEM fused-LU limit; under
        # Auto the tournament (CALU) panel substitutes — documented,
        # like the reference exposing tntpiv as a variant — while an
        # EXPLICIT PartialPiv request keeps true partial pivoting via
        # the inner-blocked loop panel
        tall = "pp" if raw_method is MethodLU.PartialPiv else "tournament"
        return getrf_panels(av, max(nb, 512), tall_panel=tall)
    return getrf_rec(av, nb)


@instrument_driver("getrf")
def getrf(a, opts: Optional[Options] = None) -> Tuple[Matrix, jnp.ndarray]:
    """LU factorization with partial pivoting — reference ``slate::getrf``
    (``src/getrf.cc``).  Returns ``(LU, perm)`` with ``A[perm] = L·U``;
    LU packed LAPACK-style in one Matrix.

    ``Option.MethodLU`` picks the pivot strategy: PartialPiv (default,
    ``lax.linalg.lu`` panel), CALU (tournament, reference
    ``getrf_tntpiv``), NoPiv (reference ``getrf_nopiv``).
    """

    av = as_array(a)
    nb = _nb(a, opts)
    raw_method = get_option(opts, "method_lu", MethodLU.Auto)
    from ..method import select_lu
    method = select_lu(raw_method)
    if method is MethodLU.NoPiv:
        lu = getrf_nopiv_rec(av, nb, int(get_option(opts, "inner_blocking")))
        perm = jnp.arange(av.shape[0])
    elif method is MethodLU.CALU:
        lu, perm = getrf_rec(av, nb, panel=lambda p: _panel_lu_tntpiv(p, nb))
    elif method is MethodLU.PartialPiv:
        lu, perm = _getrf_partial(av, nb, raw_method)
    else:
        raise NotImplementedError(f"MethodLU.{method.name} is not implemented "
                                  "(supported: PartialPiv, CALU, NoPiv)")
    return _wrap_like(a, lu), perm


def getrf_nopiv_rec(a, nb: int, ib: int = 128):
    m, n = a.shape
    if m < n:
        f_l = getrf_nopiv_rec(a[:, :m], nb, ib)
        u_r = lax.linalg.triangular_solve(
            f_l, a[:, m:], left_side=True, lower=True, unit_diagonal=True)
        return jnp.concatenate([f_l, u_r], axis=1)
    if n <= nb:
        return _panel_lu_nopiv(a, ib)
    n1 = blocks._split(n, nb)
    f1 = getrf_nopiv_rec(a[:, :n1], nb, ib)
    u12 = lax.linalg.triangular_solve(
        f1[:n1], a[:n1, n1:], left_side=True, lower=True, unit_diagonal=True)
    a22 = a[n1:, n1:] - matmul(f1[n1:], u12)
    f2 = getrf_nopiv_rec(a22, nb, ib)
    top = jnp.concatenate([f1[:n1], u12], axis=1)
    bot = jnp.concatenate([f1[n1:], f2], axis=1)
    return jnp.concatenate([top, bot], axis=0)


def getrf_nopiv(a, opts: Optional[Options] = None):
    """Reference ``slate::getrf_nopiv`` (``src/getrf_nopiv.cc``).
    ``Option.InnerBlocking`` tunes the unblocked panel base width."""
    av = as_array(a)
    ib = int(get_option(opts, "inner_blocking"))  # table default
    return _wrap_like(a, getrf_nopiv_rec(av, _nb(a, opts), ib))


def getrf_tntpiv(a, opts: Optional[Options] = None):
    """CALU tournament-pivot LU — reference ``slate::getrf_tntpiv``
    (``src/getrf_tntpiv.cc``)."""
    av = as_array(a)
    nb = _nb(a, opts)
    lu, perm = getrf_rec(av, nb, panel=lambda p: _panel_lu_tntpiv(p, nb))
    return _wrap_like(a, lu), perm


# ---------------------------------------------------------------------------
# Solves / inverse
# ---------------------------------------------------------------------------

def _lu_solve(luv, perm, bv, nb: int):
    """permuteRows(Forward) → trsm(L, unit) → trsm(U) — the core of getrs,
    shared by the mixed-precision solvers (reference ``src/getrs.cc``)."""
    y = blocks.trsm_rec(Side.Left, Uplo.Lower, Diag.Unit, luv, bv[perm], nb)
    return blocks.trsm_rec(Side.Left, Uplo.Upper, Diag.NonUnit, luv, y, nb)


@instrument_driver("getrs")
def getrs(lu, perm, b, op: Op = Op.NoTrans, opts: Optional[Options] = None):
    """Solve op(A)·X = B from the LU factor — reference ``slate::getrs``
    (``src/getrs.cc``: permuteRows(Forward) → trsm(L) → trsm(U))."""

    luv, bv = as_array(lu), as_array(b)
    nb = _nb(lu, opts)
    if op is Op.NoTrans:
        x = _lu_solve(luv, perm, bv, nb)
    else:
        # op(A) = Uᵗ Lᵗ P (A[perm] = LU): solve Uᵗ y = B, Lᵗ w = y, x = Pᵗw
        t = luv.T if op is Op.Trans else jnp.conj(luv.T)
        y = blocks.trsm_rec(Side.Left, Uplo.Lower, Diag.NonUnit, t, bv, nb)
        w = blocks.trsm_rec(Side.Left, Uplo.Upper, Diag.Unit, t, y, nb)
        x = jnp.zeros_like(w).at[perm].set(w)
    return _wrap_like(b, x)


@instrument_driver("gesv")
def gesv(a, b, opts: Optional[Options] = None):
    """Factor + solve — reference ``slate::gesv`` (``src/gesv.cc``).
    Returns ``(lu, perm, x)``."""

    lu, perm = getrf(a, opts)
    x = getrs(lu, perm, b, opts=opts)
    return lu, perm, x


@instrument_driver("getri")
def getri(lu, perm, opts: Optional[Options] = None):
    """Matrix inverse from the LU factor — reference ``slate::getri``
    (``src/getri.cc``: trtri(U) then solve; out-of-place variant
    ``getriOOP.cc``).  A⁻¹ = U⁻¹·L⁻¹·P, evaluated as two triangular
    inverses and one triangular product plus a column gather."""

    luv = as_array(lu)
    n = luv.shape[-1]
    nb = _nb(lu, opts)
    uinv = blocks.trtri_rec(Uplo.Upper, Diag.NonUnit, luv, nb)
    linv = blocks.trtri_rec(Uplo.Lower, Diag.Unit, luv, nb)
    linv = jnp.tril(linv, -1) + jnp.eye(n, dtype=luv.dtype)
    m = matmul(jnp.triu(uinv), linv)
    inv = m[:, inverse_perm(perm)]    # · P as a column gather
    return _wrap_like(lu, inv)


# ---------------------------------------------------------------------------
# Mixed precision + iterative refinement (gesv_mixed / gesv_mixed_gmres)
# ---------------------------------------------------------------------------

from ._refine import fgmres_refine, ir_refine, lo_dtype as _lo_dtype


def _getrf_lo(av, lo, nb, anorm):
    """Low-precision LU factor leg shared by the mixed drivers.  Under
    :func:`~slate_tpu.linalg._refine.use_split_leg` an fp32 leg factors
    with every trailing update forced through the bf16x3 split product
    (:mod:`slate_tpu.ops.split_gemm`, ~3·k·ε₃₂ backward error at the
    MXU's bf16 rate); a Higham–Tisseur condition probe on the fresh
    factor (the :func:`~slate_tpu.linalg.condest.gecondest` closures)
    demotes back to the stock factor when κ(A)·n·ε₃₂ approaches 1 —
    past that a split-seeded iteration cannot contract and would only
    stagnate into the full-precision fallback."""
    from ._refine import split_factor_leg, use_split_leg

    if not use_split_leg(lo):
        return getrf_rec(av.astype(lo), nb)
    from .condest import refine_kappa_eps

    with split_factor_leg():
        lu_lo, perm = _getrf_lo(av, lo, nb, anorm)
    kappa_eps = refine_kappa_eps(
        lambda v: getrs(lu_lo, perm, v),
        lambda v: getrs(lu_lo, perm, v, op=Op.ConjTrans),
        av.shape[-1], anorm, lo)
    if kappa_eps > 0.25:
        return getrf_rec(av.astype(lo), nb)
    return lu_lo, perm


def gesv_mixed(a, b, opts: Optional[Options] = None, *, tol=None,
               return_info: bool = False):
    """Mixed-precision LU solve with iterative refinement — reference
    ``slate::gesv_mixed`` (``src/gesv_mixed.cc``): factor in low
    precision (fp32 — MXU-fast), refine the residual in working
    precision, fall back to a full-precision factor if refinement stalls
    (``Option.UseFallbackSolver``).

    Returns ``(x, iters)``; ``iters < 0`` flags fallback (reference info
    convention).
    """

    av, bv = as_array(a), as_array(b)
    n = av.shape[-1]
    nb = _nb(a, opts)
    itermax = int(get_option(opts, "max_iterations", 30))
    use_fallback = bool(get_option(opts, "use_fallback_solver", True))
    eps = jnp.finfo(av.dtype).eps
    # reference stopping criterion: ||r||∞ ≤ ||x||∞ · ||A||∞ · ε · √n
    anorm = _norm(Norm.Inf, av)
    thresh = (float(tol) if tol is not None
              else float(eps) * float(jnp.sqrt(n)))

    lo = _lo_dtype(av.dtype)
    lu_lo, perm = _getrf_lo(av, lo, nb, anorm)
    solve_lo = jax.jit(
        lambda r: _lu_solve(lu_lo, perm, r.astype(lo), nb).astype(av.dtype))

    def solve_full(bv):
        # full-precision fallback (reference gesv_mixed.cc fallback path)
        lu, perm_f = getrf_rec(av, nb)
        return _lu_solve(lu, perm_f, bv, nb)

    x, iters = ir_refine(av, bv, solve_lo, solve_full, anorm=anorm,
                         thresh=thresh, itermax=itermax,
                         use_fallback=use_fallback)
    return (_wrap_like(b, x), iters)


def gesv_mixed_gmres(a, b, opts: Optional[Options] = None, *, tol=None,
                     restart: int = 30):
    """GMRES-IR: FGMRES in working precision, left-preconditioned by the
    low-precision LU solve — reference ``slate::gesv_mixed_gmres``
    (``src/gesv_mixed_gmres.cc``, itermax 30, fallback on stagnation).

    Single right-hand-side per GMRES cycle (reference restriction: it
    iterates nrhs=1; multiple columns are solved column-by-column).
    Returns ``(x, iters)``.
    """

    av, bv = as_array(a), as_array(b)
    nb = _nb(a, opts)
    itermax = int(get_option(opts, "max_iterations", 30))
    use_fallback = bool(get_option(opts, "use_fallback_solver", True))
    n = av.shape[-1]
    eps = jnp.finfo(av.dtype).eps
    anorm = _norm(Norm.Inf, av)
    thresh = float(tol) if tol is not None else float(eps) * float(jnp.sqrt(n))

    lo = _lo_dtype(av.dtype)
    lu_lo, perm = _getrf_lo(av, lo, nb, anorm)
    precond = jax.jit(
        lambda r: _lu_solve(lu_lo, perm, r.astype(lo), nb).astype(av.dtype))

    _full = []                    # lazily-factored, shared by columns

    def solve_full(bv2):
        # the refine cores always pass a 2-D block
        if not _full:
            _full.append(getrf_rec(av, nb))
        lu, perm_f = _full[0]
        return _lu_solve(lu, perm_f, bv2, nb)

    x, iters = fgmres_refine(av, bv, precond, solve_full, anorm=anorm,
                             thresh=thresh, itermax=itermax, restart=restart,
                             use_fallback=use_fallback)
    return _wrap_like(b, x), iters


def getrs_nopiv(lu, b, op: Op = Op.NoTrans, opts: Optional[Options] = None):
    """Solve from a no-pivot factor — reference ``slate::getrs_nopiv``
    (``src/getrs_nopiv.cc``): the two triangular sweeps of :func:`getrs`
    with the identity permutation."""

    luv = as_array(lu)
    n = luv.shape[-1]
    return getrs(lu, jnp.arange(n), b, op=op, opts=opts)


def gesv_nopiv(a, b, opts: Optional[Options] = None):
    """Factor (no pivoting) + solve — reference ``slate::gesv_nopiv``
    (``src/gesv_nopiv.cc``).  Only stable for diagonally-dominant /
    well-conditioned systems, as in the reference.  Returns
    ``(lu, x)``."""

    lu = getrf_nopiv(a, opts)
    x = getrs_nopiv(lu, b, opts=opts)
    return lu, x


#: Deprecated camel-case alias kept by the reference (slate.hh).
gesvMixed = gesv_mixed
