"""Divide-and-conquer symmetric tridiagonal eigensolver (Cuppen).

Re-design of the reference's distributed ``stedc`` stack —
``src/stedc.cc`` (driver), ``src/stedc_solve.cc`` (recursion),
``src/stedc_merge.cc`` (rank-one merge), ``src/stedc_deflate.cc`` (595
LoC, deflation of tiny/duplicate z-components), ``src/stedc_secular.cc``
(271 LoC, secular-equation roots), ``src/stedc_sort.cc`` (eigenvalue
ordering), ``src/stedc_z_vector.cc`` (coupling vector) — with the same
stage decomposition as public functions.

Numerical scheme (LAPACK ``dlaed1/2/3/4`` lineage):

* split T at the midpoint and tear the coupling ``e_m`` into a rank-one
  update ``T = diag(T₁', T₂') + ρ·z·zᵀ`` with ``ρ = 2|e_m| > 0``, the
  sign of ``e_m`` folded into z's second half;
* deflate z-components below ``8·ε·max(|d|,|ρ z|)`` and near-duplicate
  poles (a Givens rotation zeroes one of the two z-components);
* solve the secular equation ``1 + ρ·Σ zⱼ²/(dⱼ−λ) = 0`` for all k roots
  *simultaneously* with a vectorized bisection — the stage the reference
  distributes over ranks (``stedc_secular.cc``) becomes a data-parallel
  (k,k) iteration, unconditionally convergent and branch-free;
* recompute ẑ from the computed roots by the Gu–Eisenstat interlacing
  product (LAPACK ``dlaed3``) so eigenvectors stay orthogonal to machine
  precision even for clustered spectra;
* assemble Q = diag(Q₁,Q₂)·P·[S | deflated columns], then sort.

Everything is float64 host NumPy (the reference's tridiagonal stages
also run per-rank on the host, ``src/heev.cc:141-176``); the (k,k)
vectorized stages are the shape a jnp port shards over the mesh.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = [
    "stedc", "stedc_deflate", "stedc_merge", "stedc_secular",
    "stedc_solve", "stedc_sort", "stedc_z_vector",
]

#: below this size the QR algorithm beats a merge step (SMLSIZ analog,
#: reference stedc.cc)
_SMLSIZ = 32


def _steqr_base(d, e):
    from scipy.linalg import eigh_tridiagonal
    if d.size == 1:
        return d.copy(), np.ones((1, 1))
    return eigh_tridiagonal(d, e)


def stedc_z_vector(q1_last_row: np.ndarray, q2_first_row: np.ndarray,
                   sign: float = 1.0) -> np.ndarray:
    """Rank-one coupling vector from the boundary rows of the sub-problem
    eigenvector matrices — reference ``stedc_z_vector.cc``:
    ``z = (1/√2)·[Q₁ᵀ·e_last; ±Q₂ᵀ·e_first]`` (the ± carries the sign of
    the torn off-diagonal so that ρ = 2|e_m| stays positive).  ‖z‖ = 1.
    """

    return np.concatenate([q1_last_row, sign * q2_first_row]) / np.sqrt(2.0)


def stedc_sort(d: np.ndarray, q: Optional[np.ndarray] = None):
    """Ascending eigenvalue sort with matching column permutation of Q —
    reference ``stedc_sort.cc``.  Returns ``(d_sorted, Q_sorted)``."""

    order = np.argsort(d, kind="stable")
    return (d[order], q[:, order] if q is not None else None)


def stedc_deflate(d: np.ndarray, z: np.ndarray, rho: float):
    """Deflation stage — reference ``stedc_deflate.cc`` (LAPACK
    ``dlaed2``).

    Given ascending poles ``d`` and unit-norm coupling ``z``, returns
    ``(keep, d_upd, z_upd, givens)``:

    * ``keep``  — boolean mask of entries that stay in the secular
      problem (a pole with negligible coupling is already an eigenpair);
      ``d_upd[keep] / z_upd[keep]`` is the reduced secular problem and
      ``d_upd[~keep]`` are finished eigenvalues,
    * ``d_upd, z_upd`` — poles/couplings after the deflation rotations
      (a rotation updates *both* diagonal entries of the pair, dlaed2),
    * ``givens`` — ``(i, j, c, s)`` rotations applied; the caller
      applies the same rotations to the corresponding Q columns.
    """

    n = d.size
    absd = np.abs(d).max() if n else 0.0
    absz = np.abs(z).max() if n else 0.0
    tol = 8.0 * np.finfo(np.float64).eps * max(absd, abs(rho) * absz, 1e-300)

    keep = np.abs(rho * z) > tol
    d = d.copy()
    z = z.copy()
    givens = []
    # rotate near-duplicate poles (ascending d ⇒ only live neighbours can
    # collide).  dlaed2's criterion: the rotation that merges the two
    # couplings leaves an off-diagonal element c·s·(d_b − d_a); the pair
    # deflates iff that element is negligible (absolute tol).  The
    # rotated 2×2 diagonal block replaces both d's; the kept value stays
    # inside (d_a, d_b), so the ascending order of live poles survives.
    live = np.flatnonzero(keep)
    for a, b in zip(live[:-1], live[1:]):
        r = np.hypot(z[a], z[b])
        if r == 0.0:
            continue
        c, s = z[b] / r, z[a] / r
        if abs(c * s * (d[b] - d[a])) <= tol:
            z[b], z[a] = r, 0.0
            keep[a] = False
            da, db = d[a], d[b]
            d[a] = c * c * da + s * s * db
            d[b] = s * s * da + c * c * db
            givens.append((int(a), int(b), float(c), float(s)))
    return keep, d, z, givens


def stedc_secular(dk: np.ndarray, zk: np.ndarray, rho: float,
                  iters: int = 110):
    """Secular-equation roots — reference ``stedc_secular.cc`` (LAPACK
    ``dlaed4``), vectorized over all k roots at once.

    Solves ``f(λ) = 1 + ρ·Σⱼ zⱼ²/(dⱼ−λ) = 0`` with ``ρ > 0`` and
    ascending ``dk``; root i lies in ``(d_i, d_{i+1})``, the last in
    ``(d_k, d_k + ρ‖z‖²)``.

    Each root is computed in a *shifted frame* ``λᵢ = σᵢ + μᵢ`` with the
    origin σᵢ at the nearer interval end (chosen by the sign of f at the
    midpoint, as in dlaed4), so pole differences ``dⱼ − λᵢ`` are formed
    as ``(dⱼ − σᵢ) − μᵢ`` without catastrophic cancellation.  f is
    increasing on each interval, so bisection over the whole batch —
    a branch-free (k,k) dense iteration, the shape the reference
    distributes over ranks — converges unconditionally.  110 halvings
    (not ~55) because a barely-undeflated root can sit within
    ~ρ·z²_min ≈ 1e-28·gap of its pole: resolving μ down to that scale is
    what keeps the recomputed ẑ (and hence the residual) at ε; stopping
    at fp64-ulp-of-λ accuracy perturbs ẑ by √μ_err ≈ 1e-9.

    Returns ``(lam, dmat)`` where ``dmat[j, i] = dⱼ − λᵢ`` is the
    stably-computed difference matrix that the eigenvector stage
    (``dlaed3``) consumes.
    """

    k = dk.size
    if k == 0:
        return np.empty(0), np.empty((0, 0))
    z2 = zk * zk
    upper = np.empty(k)                      # upper interval end per root
    upper[:-1] = dk[1:]
    upper[-1] = dk[-1] + rho * z2.sum()
    gap = upper - dk

    # choose the shift origin: evaluate f at the interval midpoint
    mid = dk + 0.5 * gap
    with np.errstate(divide="ignore"):
        fmid = 1.0 + rho * (z2[None, :]
                            / (dk[None, :] - mid[:, None])).sum(axis=1)
    from_lower = fmid >= 0.0                 # root in the lower half
    sigma = np.where(from_lower, dk, upper)
    # μ-interval relative to σ (root strictly inside the open interval)
    lo = np.where(from_lower, 0.0, -0.5 * gap)
    hi = np.where(from_lower, 0.5 * gap, 0.0)

    # pole offsets in each root's frame: delta[j, i] = d_j − σ_i
    delta = dk[:, None] - sigma[None, :]
    for _ in range(iters):
        mu = 0.5 * (lo + hi)
        with np.errstate(divide="ignore", invalid="ignore"):
            f = 1.0 + rho * (z2[:, None]
                             / (delta - mu[None, :])).sum(axis=0)
        # at an exact pole hit the sum is ±inf − ∓inf = nan; resolve by
        # treating it as "above the root" (shrinks the interval safely)
        up = np.where(np.isnan(f), False, f < 0.0)
        lo = np.where(up, mu, lo)
        hi = np.where(up, hi, mu)
    mu = 0.5 * (lo + hi)
    lam = sigma + mu
    dmat = delta - mu[None, :]               # d_j − λ_i, cancellation-free
    return lam, dmat


def _gu_eisenstat_z(dk: np.ndarray, dmat: np.ndarray,
                    zk: np.ndarray) -> np.ndarray:
    """Recompute ẑ from the computed roots (LAPACK ``dlaed3``): by the
    interlacing product formula ``ẑⱼ² ∝ Πᵢ(λᵢ−dⱼ) / Πᵢ≠ⱼ(dᵢ−dⱼ)``, the
    vector whose *exact* secular roots are the computed ``lam``;
    eigenvectors built from ẑ are orthogonal to working precision.
    ``dmat[j, i] = dⱼ − λᵢ`` comes from :func:`stedc_secular`.  (The
    uniform 1/ρ factor is dropped — it cancels in the normalization.)"""

    diff_d = dk[None, :] - dk[:, None]
    np.fill_diagonal(diff_d, 1.0)
    # interleave each (λᵢ−dⱼ) with its (dᵢ−dⱼ): the ratios are O(1) by
    # interlacing, so the product cannot under/overflow the way the two
    # raw Π's do on graded spectra (dlaed3 does the same)
    ratio = -dmat / diff_d
    np.fill_diagonal(ratio, 1.0)
    zhat2 = np.abs(np.prod(ratio, axis=1) * (-np.diagonal(dmat)))
    return np.where(zk < 0, -1.0, 1.0) * np.sqrt(zhat2)


def stedc_merge(d1: np.ndarray, q1: np.ndarray, d2: np.ndarray,
                q2: np.ndarray, e_mid: float):
    """Rank-one merge of two solved sub-problems — reference
    ``stedc_merge.cc`` (LAPACK ``dlaed1``).

    The caller has already subtracted ``|e_mid|`` from the two boundary
    diagonals, so ``T = diag(T₁', T₂') + ρ·z·zᵀ`` exactly, with
    ``ρ = 2|e_mid|`` and z from :func:`stedc_z_vector`.  Returns the
    merged ``(w, Q)`` ascending.
    """

    n1 = d1.size
    n = n1 + d2.size
    rho = 2.0 * abs(e_mid)
    if rho == 0.0:                            # decoupled: just interleave
        d = np.concatenate([d1, d2])
        qbig = np.zeros((n, n))
        qbig[:n1, :n1] = q1
        qbig[n1:, n1:] = q2
        return stedc_sort(d, qbig)
    z = stedc_z_vector(q1[-1, :], q2[0, :], sign=np.sign(e_mid))
    d = np.concatenate([d1, d2])

    # sort the poles ascending (the reference's stedc_sort pre-pass)
    order = np.argsort(d, kind="stable")
    d_s, z_s = d[order], z[order]

    keep, d_u, z_u, givens = stedc_deflate(d_s, z_s, rho)
    dk, zk = d_u[keep], z_u[keep]

    qbig = np.zeros((n, n))
    qbig[:n1, :n1] = q1
    qbig[n1:, n1:] = q2
    qperm = qbig[:, order]
    for (a, b, c, s) in givens:
        qa, qb = qperm[:, a].copy(), qperm[:, b].copy()
        qperm[:, a] = c * qa - s * qb
        qperm[:, b] = s * qa + c * qb

    k = int(keep.sum())
    w = np.empty(n)
    qout = np.empty((n, n))
    # deflated pairs pass through (with their rotated diagonal values)
    w[k:] = d_u[~keep]
    qout[:, k:] = qperm[:, ~keep]

    if k:
        lam, dmat = stedc_secular(dk, zk, rho)
        zhat = _gu_eisenstat_z(dk, dmat, zk)
        # secular eigenvectors: v_i ∝ ẑⱼ/(dⱼ−λᵢ), then normalize; the
        # difference matrix comes from the shifted frames (stable).
        # Clamp |dmat| away from exact zero: a bisection interval that
        # collapses to zero width (mu underflow next to a pole) would
        # otherwise turn a column into inf/nan.  The floor is
        # sqrt(tiny)·scale (~1e-154·scale) — far below the deflation
        # tolerance (~eps·scale) that bounds legitimate gaps, so it
        # cannot perturb undeflated roots; the max-abs prescale keeps
        # the 2-norm from overflowing for near-pole columns (the column
        # limits to the pole coordinate axis).
        tiny = np.finfo(dmat.dtype).tiny ** 0.5 * max(np.abs(dk).max(), 1.0)
        gap = np.abs(dmat).min(axis=0)
        pole = np.abs(dmat).argmin(axis=0)
        dmat = np.where(np.abs(dmat) < tiny,
                        np.where(dmat < 0, -tiny, tiny), dmat)
        vs = zhat[:, None] / dmat
        vs /= np.abs(vs).max(axis=0, keepdims=True)
        vs /= np.linalg.norm(vs, axis=0, keepdims=True)
        # A root whose interval collapsed onto its pole (gap below the
        # floor) has eigenvector → the pole coordinate axis; the clamped
        # quotient cannot represent that (zhat at the pole is 0 too), so
        # substitute e_pole explicitly.
        collapsed = gap < tiny
        if collapsed.any():
            for i in np.flatnonzero(collapsed):
                vs[:, i] = 0.0
                vs[pole[i], i] = 1.0
        w[:k] = lam
        qout[:, :k] = qperm[:, keep] @ vs

    return stedc_sort(w, qout)


def stedc_solve(d: np.ndarray, e: np.ndarray):
    """Recursive D&C driver — reference ``stedc_solve.cc``.  Returns
    ``(w, Q)`` ascending."""

    n = d.size
    if n <= _SMLSIZ:
        return _steqr_base(d, e)
    m = n // 2
    em = e[m - 1]
    # tear: T = diag(T1', T2') + |e_m|·u·uᵀ, u = [e_last; sign(e_m)·e_first]
    d1 = d[:m].copy()
    d2 = d[m:].copy()
    d1[-1] -= abs(em)
    d2[0] -= abs(em)
    w1, q1 = stedc_solve(d1, e[:m - 1])
    w2, q2 = stedc_solve(d2, e[m:])
    return stedc_merge(w1, q1, w2, q2, em)


def stedc(d: np.ndarray, e: np.ndarray, want_z: bool = True):
    """Divide-and-conquer tridiagonal eigensolver — reference
    ``slate::stedc`` (``src/stedc.cc``).  Returns ``(w, Q)`` or ``w``."""

    d = np.asarray(d, dtype=np.float64)
    e = np.asarray(e, dtype=np.float64)
    if not want_z:
        # values-only: skip the O(n³) vector recursion entirely (the
        # reference's heev likewise switches to sterf when no vectors
        # are wanted, src/heev.cc:141-176)
        from scipy.linalg import eigvalsh_tridiagonal
        if d.size == 1:
            return d.copy()
        return eigvalsh_tridiagonal(d, e)
    w, q = stedc_solve(d, e)
    return w, q
