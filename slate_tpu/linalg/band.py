"""Band-matrix routines: gbmm, hbmm, tbsm, gbtrf/gbtrs/gbsv,
pbtrf/pbtrs/pbsv — reference ``src/gbmm.cc`` (312), ``src/hbmm.cc``
(542), ``src/tbsm.cc`` (440), ``src/gbtrf.cc``/``gbtrs``/``gbsv``,
``src/pbtrf.cc``/``pbtrs``/``pbsv``.

TPU-native stance: bands are stored dense-with-implicit-zeros (see
``BaseBandMatrix``); multiplies are one masked GEMM (XLA DCEs the zero
tiles it can prove); the band Cholesky is band-*aware* — each panel only
touches the kd-row window below it, so work is O(n·kd²) like the
reference's tile loop over the band.  The pivoted band LU falls back to
the dense blocked ``getrf`` (pivot fill makes the windowed variant
control-flow heavy; the factor's upper bandwidth grows to kl+ku as in
LAPACK ``gbtrf``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from ..enums import Diag, Op, Side, Uplo
from ..exceptions import SlateError
from ..matrix import (BandMatrix, BaseBandMatrix, HermitianBandMatrix,
                      TriangularBandMatrix, as_array)
from ..options import Options
from ..ops import blocks
from ..ops.blocks import _ct, matmul
from ..ops.tile_ops import hermitize
from .blas3 import _nb, _wrap_like


def _band_arr(a):
    """Logical array of a band operand with outside-band zeros applied."""
    if isinstance(a, BaseBandMatrix):
        return a.banded()
    return as_array(a)


def _herm_band_full(a):
    if isinstance(a, HermitianBandMatrix):
        return hermitize(a.uplo, a.banded())
    return _band_arr(a)


def gbmm(alpha, a, b, beta, c, opts: Optional[Options] = None):
    """C ← α·op(A_band)·B + β·C — reference ``slate::gbmm``
    (``src/gbmm.cc``): the masked band times a dense matrix is a single
    MXU GEMM."""

    av, bv = _band_arr(a), as_array(b)
    cv = as_array(c)
    out = alpha * matmul(av, bv) + beta * cv
    return _wrap_like(c, out)


def hbmm(side: Side, alpha, a, b, beta, c, opts: Optional[Options] = None):
    """C ← α·A_hermband·B + β·C (or B·A) — reference ``slate::hbmm``
    (``src/hbmm.cc``)."""

    av = _herm_band_full(a)
    bv, cv = as_array(b), as_array(c)
    prod = matmul(av, bv) if side is Side.Left else matmul(bv, av)
    return _wrap_like(c, alpha * prod + beta * cv)


def pbtrf(a, opts: Optional[Options] = None):
    """Band Cholesky — reference ``slate::pbtrf`` (``src/pbtrf.cc``).

    Band-aware blocked loop: per block column only the kd-row window
    below the diagonal block participates (panel potrf → window trsm →
    window herk); the factor keeps bandwidth kd (no fill, as the
    windowed Schur update stays inside the band).  Returns a
    TriangularBandMatrix.
    """

    if not isinstance(a, HermitianBandMatrix):
        raise SlateError("pbtrf expects a HermitianBandMatrix")
    kd = a.kd
    uplo = a.uplo
    full = hermitize(uplo, a.banded())
    n = full.shape[-1]
    nb = min(_nb(a, opts), max(kd, 1))
    for j0 in range(0, n, nb):
        w = min(nb, n - j0)
        r1 = j0 + w
        r2 = min(n, r1 + kd)
        a11 = full[j0:r1, j0:r1]
        l11 = blocks.potrf_rec(a11, nb)
        full = full.at[j0:r1, j0:r1].set(l11)
        if r1 < r2:
            a21 = full[r1:r2, j0:r1]
            l21 = blocks.trsm_rec(Side.Right, Uplo.Upper, Diag.NonUnit,
                                  _ct(l11), a21, nb)
            full = full.at[r1:r2, j0:r1].set(l21)
            upd = full[r1:r2, r1:r2] - matmul(l21, _ct(l21))
            full = full.at[r1:r2, r1:r2].set(upd)
    lfac = jnp.tril(full)
    data = lfac if uplo is Uplo.Lower else _ct(lfac)
    return TriangularBandMatrix(data, kd=kd, uplo=uplo, diag=Diag.NonUnit,
                                mb=a.mb, nb=a.nb, grid=a.grid)


def pbtrs(factor, b, opts: Optional[Options] = None):
    """Solve with the band Cholesky factor — reference ``slate::pbtrs``
    (``src/pbtrs.cc``): two triangular band solves."""

    uplo = getattr(factor, "uplo", Uplo.Lower)
    lv = _band_arr(factor)
    if uplo is not Uplo.Lower:
        lv = _ct(lv)
    bv = as_array(b)
    nb = _nb(factor, opts)
    y = blocks.trsm_rec(Side.Left, Uplo.Lower, Diag.NonUnit, lv, bv, nb)
    x = blocks.trsm_rec(Side.Left, Uplo.Upper, Diag.NonUnit, _ct(lv), y, nb)
    return _wrap_like(b, x)


def pbsv(a, b, opts: Optional[Options] = None):
    """Factor + solve — reference ``slate::pbsv``. Returns (factor, x)."""
    f = pbtrf(a, opts)
    return f, pbtrs(f, b, opts)


def gbtrf(a, opts: Optional[Options] = None):
    """Pivoted band LU — reference ``slate::gbtrf`` (``src/gbtrf.cc``).

    Row pivoting fills the upper band to kl+ku (LAPACK ``gbtrf``
    semantics); computed via the dense blocked ``getrf`` on the masked
    band (the dense factorization of a band matrix leaves L with
    bandwidth kl and U with bandwidth kl+ku, which the returned
    BandMatrix records).  Returns ``(factor_band, pivots)``.
    """

    from .lu import getrf
    if not isinstance(a, BandMatrix):
        raise SlateError("gbtrf expects a BandMatrix")
    fac, piv = getrf(a.banded(), opts)
    fb = BandMatrix(as_array(fac), kl=a.kl, ku=a.kl + a.ku,
                    mb=a.mb, nb=a.nb, grid=a.grid)
    return fb, piv


def gbtrs(factor, pivots, b, opts: Optional[Options] = None):
    """Solve with the band LU — reference ``slate::gbtrs``."""
    from .lu import getrs
    fv = factor.data if isinstance(factor, BaseBandMatrix) else factor
    return _wrap_like(b, as_array(
        getrs(as_array(fv), pivots, as_array(b), opts=opts)))


def gbsv(a, b, opts: Optional[Options] = None):
    """Factor + solve — reference ``slate::gbsv``.
    Returns ``(factor, pivots, x)``."""

    f, piv = gbtrf(a, opts)
    x = gbtrs(f, piv, b, opts)
    return f, piv, x


def tbsm(side: Side, alpha, a, b, pivots=None,
         opts: Optional[Options] = None):
    """Triangular band solve op(A_band)·X = α·B — reference
    ``slate::tbsm`` (``src/tbsm.cc``; the pivoted variant applies the
    band-LU row swaps first)."""

    if not isinstance(a, TriangularBandMatrix):
        raise SlateError("tbsm expects a TriangularBandMatrix")
    av = a.banded()
    uplo = a.uplo
    if a.op is not Op.NoTrans:
        uplo = Uplo.Lower if uplo is Uplo.Upper else Uplo.Upper
    bv = as_array(b)
    nb = _nb(a, opts)
    if pivots is not None and side is Side.Left:
        bv = bv[pivots]  # row permutation from the band LU
    out = blocks.trsm_rec(side, uplo, a.diag, av, alpha * bv, nb)
    return _wrap_like(b, out)
