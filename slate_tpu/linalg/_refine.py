"""Shared mixed-precision refinement cores.

The reference implements the same two refinement loops four times —
``src/gesv_mixed.cc``, ``src/posv_mixed.cc`` (classic iterative
refinement) and ``src/gesv_mixed_gmres.cc``, ``src/posv_mixed_gmres.cc``
(FGMRES-IR) — differing only in the factorization used for the
low-precision solve.  Here the loops are written once over three
callables:

* ``solve_lo(r)``  — apply the low-precision factor to a residual block
  (working-precision in, working-precision out),
* ``solve_full(b)`` — factor in working precision and solve (fallback
  path, ``Option::UseFallbackSolver``),
* ``matvec`` is derived from the matrix itself.

Stopping criterion (both loops, reference ``gesv_mixed.cc``):
``‖r‖∞ ≤ ‖x‖∞ · ‖A‖∞ · ε · √n``.
"""

from __future__ import annotations

from contextlib import contextmanager

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.blocks import matmul_hi


def ir_refine_core(b, solve_lo, solve_full, residual, *, anorm, thresh,
                   itermax, use_fallback,
                   add=lambda x, d: x + d,
                   absmax=lambda v: float(jnp.max(jnp.abs(v)))):
    """Classic iterative refinement over opaque solution objects (dense
    arrays here, :class:`~slate_tpu.parallel.DistMatrix` on the mesh via
    the ``add``/``absmax`` hooks).  Returns ``(x, iters)``; negative
    ``iters`` flags the full-precision fallback (reference convention)."""

    x = solve_lo(b)
    iters = 0
    converged = False
    for it in range(itermax):
        r = residual(x)
        rnorm = absmax(r)
        xnorm = absmax(x)
        if rnorm <= xnorm * float(anorm) * thresh:
            converged = True
            iters = it
            break
        x = add(x, solve_lo(r))
        iters = it + 1
    if not converged:
        rnorm = absmax(residual(x))
        xnorm = absmax(x)
        converged = rnorm <= xnorm * float(anorm) * thresh
    if not converged and use_fallback:
        x = solve_full(b)
        iters = -(iters + 1)
    return x, iters


def ir_refine(av, bv, solve_lo, solve_full, *, anorm, thresh, itermax,
              use_fallback):
    """Dense-array front end of :func:`ir_refine_core` (handles 1-D
    right-hand sides and supplies the matmul residual)."""

    squeeze = bv.ndim == 1
    if squeeze:
        bv = bv[:, None]
    residual = jax.jit(lambda x: bv - matmul_hi(av, x))
    x, iters = ir_refine_core(bv, solve_lo, solve_full, residual,
                              anorm=anorm, thresh=thresh, itermax=itermax,
                              use_fallback=use_fallback)
    if squeeze:
        x = x[:, 0]
    return x, iters


def fgmres_refine(av, bv, precond, solve_full, *, anorm, thresh, itermax,
                  restart, use_fallback, matvec=None):
    """FGMRES-IR: flexible GMRES in working precision, left-preconditioned
    by the low-precision solve; one GMRES sequence per right-hand-side
    column (the reference iterates nrhs=1).  Returns ``(x, iters)``.

    ``matvec`` (v ↦ A·v on 1-D vectors) may be supplied by distributed
    callers whose A never exists as one dense array (``av`` is then only
    used by ``solve_full``/norm bookkeeping and may be None)."""

    squeeze = bv.ndim == 1
    if squeeze:
        bv = bv[:, None]
    if matvec is None:
        matvec = jax.jit(lambda v: matmul_hi(av, v[:, None])[:, 0])

    cols = []
    total_iters = 0
    any_fallback = False
    full_solution = None          # fallback solve, shared by all columns
    for j in range(bv.shape[1]):
        bj = bv[:, j]
        x = precond(bj[:, None])[:, 0]
        col_iters = 0
        converged = False
        # FGMRES(restart) cycles, bounded by the itermax option
        # (reference gesv_mixed_gmres.cc:24-47)
        while col_iters < itermax:
            r = bj - matvec(x)
            rnorm = float(jnp.linalg.norm(r))
            xnorm = float(jnp.max(jnp.abs(x)))
            if rnorm <= max(xnorm, 1.0) * float(anorm) * thresh:
                converged = True
                break
            # Arnoldi with preconditioned directions (flexible GMRES);
            # the (restart+1)×restart Hessenberg LSQ is solved on host —
            # complex-safe, O(restart³) ≪ one matvec
            V = [r / rnorm]
            Z = []
            H = np.zeros((restart + 1, restart),
                         dtype=np.dtype(bv.dtype))
            k_used = 0
            for k in range(restart):
                z = precond(V[k][:, None])[:, 0]
                Z.append(z)
                w = matvec(z)
                for i in range(k + 1):
                    H[i, k] = complex(jnp.vdot(V[i], w)) if \
                        np.iscomplexobj(H) else float(jnp.vdot(V[i], w).real)
                    w = w - H[i, k] * V[i]
                hk1 = float(jnp.linalg.norm(w))
                H[k + 1, k] = hk1
                total_iters += 1
                col_iters += 1
                k_used = k + 1
                if hk1 == 0.0:       # happy breakdown
                    break
                V.append(w / hk1)
                # running LSQ residual of min‖β·e₁ − H·y‖ for early exit
                g = np.zeros(k + 2, H.dtype)
                g[0] = rnorm
                _, res, *_ = np.linalg.lstsq(H[:k + 2, :k + 1], g,
                                             rcond=None)
                lsq_res = np.sqrt(float(res[0])) if res.size else 0.0
                if lsq_res <= max(xnorm, 1.0) * float(anorm) * thresh:
                    break
            if k_used:
                g = np.zeros(k_used + 1, H.dtype)
                g[0] = rnorm
                yk, *_ = np.linalg.lstsq(H[:k_used + 1, :k_used], g,
                                         rcond=None)
                for i in range(k_used):
                    x = x + complex(yk[i]) * Z[i] if np.iscomplexobj(H) \
                        else x + float(yk[i].real) * Z[i]
        if not converged:
            r = bj - matvec(x)
            rnorm = float(jnp.linalg.norm(r))
            xnorm = float(jnp.max(jnp.abs(x)))
            converged = rnorm <= max(xnorm, 1.0) * float(anorm) * thresh
        if not converged and use_fallback:
            # full-precision fallback (reference fallback path), factored
            # once and reused across right-hand-side columns
            if full_solution is None:
                full_solution = solve_full(bv)
            x = full_solution[:, j]
            any_fallback = True
        cols.append(x)
    x = jnp.stack(cols, axis=1)
    if squeeze:
        x = x[:, 0]
    iters = -(total_iters + 1) if any_fallback else total_iters
    return x, iters


def lo_dtype(dtype):
    """The reference pairs fp64→fp32 (``gesv_mixed`` 278 LoC).  A raw
    fp32→bf16 demotion is *not* accurate enough for IR's contraction
    bound, so fp64→fp32 and fp32→fp32 are used — but the fp32 "low" leg
    is not a no-op on TPU: under :func:`split_factor_leg` its trailing
    updates run as bf16x3 split products
    (:mod:`slate_tpu.ops.split_gemm`), ε₃₂-grade accuracy at the MXU's
    bf16 rate, so the fp32→fp32 pairing gets a genuine speed leg the
    residual loop then polishes."""
    d = jnp.dtype(dtype)
    if d == jnp.float64:
        return jnp.float32
    if d == jnp.complex128:
        return jnp.complex64
    return d


def use_split_leg(dtype) -> bool:
    """Should an fp32 mixed-precision driver factor its low leg under
    :func:`split_factor_leg`?  True for real fp32 operands when the
    split-gemm knob is forced on, or (``auto``) when running on TPU —
    where the bf16x3 trailing updates actually outrun the emulated-fp32
    dot.  Off-TPU ``auto`` resolves False so default CPU lowering (and
    CI timing) is untouched; ``SLATE_TPU_SPLIT_GEMM=0`` disables the
    leg everywhere."""
    from .. import config

    if jnp.dtype(dtype) != jnp.float32:
        return False
    mode = config.split_gemm_mode()
    if mode != "auto":
        return mode == "on"
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


@contextmanager
def split_factor_leg():
    """Force the bf16x3 split backend at the ``matmul`` site for the
    scope of a mixed driver's low-precision factor leg: every eligible
    fp32 trailing update inside resolves to ``split3`` (the
    ``config.split_gemm`` pin, no 128-alignment requirement), and the
    forced resolutions are kept out of the stored autotune table
    (:func:`~slate_tpu.perf.autotune.suppress_knob_records`) so a
    refinement leg cannot pollute the census or bundles the
    unconstrained sites train on.  The knob is restored on exit even if
    the factor throws."""
    from .. import config
    from ..perf import autotune

    saved = config.split_gemm
    config.split_gemm = True
    try:
        with autotune.suppress_knob_records():
            yield
    finally:
        config.split_gemm = saved
