"""Cholesky family: potrf / potrs / posv / potri (+ trtri, trtrm).

TPU-native re-design of the reference drivers ``src/potrf.cc`` (the
canonical lookahead task-DAG driver, ``:54-133``), ``src/potrs.cc``,
``src/posv.cc``, ``src/potri.cc`` (inverse via ``trtri`` + ``trtrm``,
``src/trtri.cc`` / ``src/trtrm.cc``).

Where the reference expresses panel/update overlap as an OpenMP task DAG
with ``Option::Lookahead``, here the recursion in
:func:`slate_tpu.ops.blocks.potrf_rec` hands XLA an explicit dependence
graph and the compiler's static scheduler performs the overlap; on a mesh
the distributed variant lives in ``slate_tpu.parallel.dist_factor``.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .. import config
from ..enums import Diag, Op, Side, Uplo
from ..matrix import BaseMatrix, BaseTrapezoidMatrix, HermitianMatrix, TriangularMatrix
from ..options import Options, get_option
from ..ops import blocks
from ..perf.metrics import instrument_driver
from ..ops.tile_ops import hermitize
from .blas3 import _arr, _diag_of, _nb, _uplo_of, _wrap_like


def _hermitian_full(a):
    """Expand the stored triangle of ``a`` into the full Hermitian array."""
    if isinstance(a, BaseTrapezoidMatrix):
        return hermitize(a.logical_uplo, a.array)
    return jnp.asarray(a)  # raw array: assume full Hermitian given


@instrument_driver("potrf")
def potrf(a, opts: Optional[Options] = None):
    """Cholesky factorization A = L·Lᴴ (or UᴴU) — reference ``slate::potrf``
    (``src/potrf.cc:369``).

    Parameters: ``a`` — HermitianMatrix (stored triangle) or full
    Hermitian array.  Returns a TriangularMatrix holding the factor in the
    same uplo (other triangle zeroed), matching the reference's in-place
    overwrite of the stored triangle.
    """

    uplo = _uplo_of(a)
    nb = _nb(a, opts)
    full = _hermitian_full(a)
    if full.shape[-1] != full.shape[-2]:
        from ..exceptions import SlateError
        raise SlateError(f"potrf requires a square matrix, got {full.shape}")
    from ..options import get_option
    method = get_option(opts, "method_factor", "auto")
    nbsel = 512 if nb <= 256 else nb
    branch = _potrf_branch(full, nb, nbsel, method)
    from ..resilience import abft as _abft
    if branch == "ooc":
        # the OOC driver carries its own resilience envelope — window-
        # boundary checkpoint/restart (resilience/checkpoint.py) with
        # bitwise rewind — so the ABFT checksum loop does not wrap it
        l = _potrf_dispatch(branch, full, nb, nbsel)
    elif _abft.eligible(full):
        # ABFT (ISSUE 14): the stock branches run the checksum-carried
        # step loop (the checksum block-row rides each trailing
        # syrk-gemm, per-step verify/correct/recompute) at the CALLER's
        # nb — the jnp-composed loop has no 512-wide panel-kernel
        # constraint, and finer steps mean finer verify/recompute
        # granularity.  The kernel-owned branches (fused/full depths,
        # Pallas panels, Ozaki) run under the checksum envelope —
        # verify the factor identity after the invocation (still
        # dispatched at nbsel, unchanged), recompute it on detection.
        l = _abft.potrf_guarded(
            full, nb, branch,
            lambda: _potrf_dispatch(branch, full, nb, nbsel))
    else:
        l = _potrf_dispatch(branch, full, nb, nbsel)
    fac = l if uplo is Uplo.Lower else jnp.conj(l.T)
    out = TriangularMatrix(fac, uplo=uplo, diag=Diag.NonUnit,
                           mb=getattr(a, "mb", nb), nb=nb,
                           grid=getattr(a, "grid", None))
    return out


def _potrf_branch(full, nb: int, nbsel: int, method) -> str:
    """Resolve which potrf backend branch the Auto dispatch takes —
    reference method.hh / internal_potrf.cc:53-72 (the diagonal factor
    goes to the vendor library), autotuned per ISSUE 2/6/12: the
    ``potrf_step`` site arbitrates the fusion-depth ladder first
    ("full" = the whole factorization in ONE pallas invocation with
    in-kernel lookahead; "fused" = one invocation per right-looking
    step), then the f32 Pallas panel path (~290 µs/512² vs ~1190 µs
    for XLA's cholesky at n=8192), then the fp64
    f32-panel+Newton+Ozaki path, with XLA's fused cholesky as the
    stock fallback.  Off-TPU (CPU mesh tests, complex) Auto resolves
    to "xla" with zero timing; "recursive" keeps the explicit nb
    recursion.  Split out of :func:`potrf` so the ABFT layer can see
    WHICH branch ships (kernel-owned branches take the checksum
    envelope, stock ones the checksum-carried loop)."""
    from ..method import select_backend

    if method != "auto":
        return "recursive"
    from . import ooc as _ooc
    if _ooc.choose(full) == "pool":
        # out-of-core (ISSUE 17): host-DRAM tile grid + bounded HBM
        # window (ops/tilepool.py) for footprints past the HBM budget
        return "ooc"
    step_depth = None
    if full.ndim == 2 and jnp.issubdtype(full.dtype, jnp.floating):
        step_depth = select_backend(
            "potrf_step", n=int(full.shape[-1]), nb=nbsel,
            dtype=full.dtype,
            eligible=blocks.use_fused_potrf_step(
                int(full.shape[-1]), nbsel, full.dtype),
            eligible_full=blocks.use_full_potrf(
                int(full.shape[-1]), nbsel, full.dtype))
    if step_depth in ("full", "fused"):
        return step_depth
    if full.dtype == jnp.float32 and full.ndim == 2 \
            and select_backend("potrf_panel", n=int(full.shape[-1]),
                               nb=nbsel, dtype=full.dtype) == "pallas":
        return "pallas"
    if full.dtype == jnp.float64 and full.ndim == 2 \
            and select_backend("potrf_panel_f64", n=int(full.shape[-1]),
                               nb=nbsel) == "ozaki_newton":
        return "ozaki"
    return "xla"


def _potrf_dispatch(branch: str, full, nb: int, nbsel: int):
    """Run one resolved potrf branch (see :func:`_potrf_branch`)."""
    if branch == "full":
        return blocks.potrf_full(full, nbsel)
    if branch == "fused":
        return blocks.potrf_steps(full, nbsel)
    if branch == "pallas":
        return blocks.potrf_panels(full, nbsel)
    if branch == "ozaki":
        # fp64 on TPU: f32 Pallas panel + fp64 Newton refinement, Ozaki
        # MXU trailing updates — replaces XLA's emulated-fp64 cholesky.
        # A panel whose f32 seed breaks down (SPD but cond ≳ 1/ε₃₂)
        # propagates NaN; rerun those inputs on the emulated path so
        # every fp64-factorizable matrix still factors (genuinely
        # non-SPD input stays NaN there too — the info signal).
        from jax import lax as _lax
        fast = blocks.potrf_panels_f64(full, nbsel)
        return _lax.cond(
            jnp.all(jnp.isfinite(fast)),
            lambda ops: ops[0],
            lambda ops: jnp.tril(_lax.linalg.cholesky(ops[1])),
            (fast, full))
    if branch == "ooc":
        from . import ooc as _ooc
        return _ooc.potrf_ooc(full)
    if branch == "recursive":
        return blocks.potrf_rec(full, nb)
    from jax import lax as _lax
    return jnp.tril(_lax.linalg.cholesky(full))


@instrument_driver("potrs")
def potrs(a_factor, b, opts: Optional[Options] = None):
    """Solve A·X = B given the Cholesky factor — reference ``src/potrs.cc``:
    two triangular solves."""

    uplo = _uplo_of(a_factor)
    av = _arr(a_factor)
    bv = _arr(b)
    nb = _nb(a_factor, opts)
    conj = jnp.iscomplexobj(av)
    if uplo is Uplo.Lower:
        # L y = b ; L^H x = y
        y = blocks.trsm_rec(Side.Left, Uplo.Lower, Diag.NonUnit, av, bv, nb)
        lh = jnp.conj(av.T) if conj else av.T
        x = blocks.trsm_rec(Side.Left, Uplo.Upper, Diag.NonUnit, lh, y, nb)
    else:
        uh = jnp.conj(av.T) if conj else av.T
        y = blocks.trsm_rec(Side.Left, Uplo.Lower, Diag.NonUnit, uh, bv, nb)
        x = blocks.trsm_rec(Side.Left, Uplo.Upper, Diag.NonUnit, av, y, nb)
    return _wrap_like(b, x)


@instrument_driver("posv")
def posv(a, b, opts: Optional[Options] = None):
    """Factor + solve — reference ``slate::posv`` (``src/posv.cc``).
    Returns ``(factor, x)``."""

    fac = potrf(a, opts)
    x = potrs(fac, b, opts)
    return fac, x


@instrument_driver("trtri")
def trtri(a, opts: Optional[Options] = None, hi: bool = False):
    """Triangular inverse — reference ``slate::trtri`` (``src/trtri.cc``).
    ``hi`` pins the assembly products to ``Precision.HIGHEST`` for
    accuracy-critical callers (potri)."""

    uplo = _uplo_of(a)
    diag = _diag_of(a)
    nb = _nb(a, opts)
    inv = blocks.trtri_rec(uplo, diag, _arr(a), nb, hi=hi)
    inv = jnp.tril(inv) if uplo is Uplo.Lower else jnp.triu(inv)
    return _wrap_like(a, inv)


@instrument_driver("trtrm")
def trtrm(a, opts: Optional[Options] = None, hi: bool = False):
    """Triangular × triangular product Lᴴ·L / U·Uᴴ — reference
    ``slate::trtrm`` (``src/trtrm.cc``, LAPACK ``lauum``)."""

    uplo = _uplo_of(a)
    nb = _nb(a, opts)
    av = _arr(a)
    out = blocks.lauum_rec(uplo, av, nb, conj=jnp.iscomplexobj(av), hi=hi)
    return _wrap_like(a, out)


@instrument_driver("potri")
def potri(a_factor, opts: Optional[Options] = None):
    """Hermitian-positive-definite inverse from the Cholesky factor —
    reference ``slate::potri`` (``src/potri.cc``): ``trtri`` then
    ``trtrm`` (A⁻¹ = L⁻ᴴ·L⁻¹).  Returns a HermitianMatrix (stored
    triangle valid).

    Both stages run with products pinned to ``Precision.HIGHEST``: the
    composition squares the per-stage forward error, and at the library
    default (3-pass bf16 ``high``, ~1.3e-5 ≈ 110·ε₃₂ on the MXU) the
    on-chip scaled residual measured past the reference tester's ≤ 3
    gate while the same algorithm at true-f32 precision (CPU x32,
    tester ``potri`` = 8.7e-2) sits three orders inside it — a
    precision-threshold failure, not an algorithmic one.  potri is not
    a throughput driver, so vendor-grade accuracy wins here (the same
    trade :func:`slate_tpu.ops.blocks.matmul_hi` makes for the
    refinement residuals)."""

    uplo = _uplo_of(a_factor)
    inv_t = trtri(a_factor, opts, hi=True)
    prod = trtrm(inv_t, opts, hi=True)
    data = prod.data if isinstance(prod, BaseMatrix) else prod
    return HermitianMatrix(data, uplo=uplo,
                           mb=getattr(a_factor, "mb", 256),
                           nb=getattr(a_factor, "nb", 256),
                           grid=getattr(a_factor, "grid", None))


# ---------------------------------------------------------------------------
# Mixed precision + iterative refinement (posv_mixed / posv_mixed_gmres)
# ---------------------------------------------------------------------------

def _chol_solve(lv, bv, nb):
    """Two triangular sweeps from the lower factor (src/potrs.cc shape)."""
    conj = jnp.iscomplexobj(lv)
    y = blocks.trsm_rec(Side.Left, Uplo.Lower, Diag.NonUnit, lv, bv, nb)
    lh = jnp.conj(lv.T) if conj else lv.T
    return blocks.trsm_rec(Side.Left, Uplo.Upper, Diag.NonUnit, lh, y, nb)


def _posv_mixed_setup(a, b, opts, tol):
    import jax

    from ..enums import Norm
    from ..options import get_option
    from .norms import norm as _norm
    from ._refine import lo_dtype, split_factor_leg, use_split_leg

    full = _hermitian_full(a)
    bv = _arr(b)
    n = full.shape[-1]
    nb = _nb(a, opts)
    itermax = int(get_option(opts, "max_iterations", 30))
    use_fallback = bool(get_option(opts, "use_fallback_solver", True))
    eps = jnp.finfo(full.dtype).eps
    anorm = _norm(Norm.Inf, full)
    thresh = (float(tol) if tol is not None
              else float(eps) * float(jnp.sqrt(n)))

    lo = lo_dtype(full.dtype)
    if use_split_leg(lo):
        # fp32 low-precision leg on the MXU's bf16 peak: factor with
        # every trailing update forced through the bf16x3 split product
        # (ops/split_gemm.py, ~3·k·ε₃₂ backward error — inside what the
        # refinement loop contracts).  Condition-aware demotion: when
        # κ(A)·n·ε₃₂ approaches 1 the split factor cannot seed a
        # converging iteration, so re-factor stock before the loop ever
        # stagnates into the full-precision fallback.
        from .condest import refine_kappa_eps

        with split_factor_leg():
            l_lo = blocks.potrf_rec(full.astype(lo), nb)

        def _solve(v):
            return _chol_solve(l_lo, v, nb)

        if refine_kappa_eps(_solve, _solve, full.shape[-1],
                            anorm, lo) > 0.25:
            l_lo = blocks.potrf_rec(full.astype(lo), nb)
    else:
        l_lo = blocks.potrf_rec(full.astype(lo), nb)
    solve_lo = jax.jit(
        lambda r: _chol_solve(l_lo, r.astype(lo), nb).astype(full.dtype))

    def solve_full(bv2):
        # full-precision fallback (reference posv_mixed.cc fallback path);
        # the refine cores always pass a 2-D block
        l = blocks.potrf_rec(full, nb)
        return _chol_solve(l, bv2, nb)

    return full, bv, nb, dict(anorm=anorm, thresh=thresh, itermax=itermax,
                              use_fallback=use_fallback), solve_lo, solve_full


def posv_mixed(a, b, opts: Optional[Options] = None, *, tol=None):
    """Mixed-precision Cholesky solve with iterative refinement —
    reference ``slate::posv_mixed`` (``src/posv_mixed.cc``): factor the
    HPD matrix in low precision, refine the residual in working
    precision, full-precision fallback on stagnation.

    Returns ``(x, iters)``; ``iters < 0`` flags fallback (reference info
    convention)."""

    from ._refine import ir_refine

    full, bv, nb, kw, solve_lo, solve_full = _posv_mixed_setup(a, b, opts,
                                                               tol)
    x, iters = ir_refine(full, bv, solve_lo, solve_full, **kw)
    return _wrap_like(b, x), iters


def posv_mixed_gmres(a, b, opts: Optional[Options] = None, *, tol=None,
                     restart: int = 30):
    """FGMRES-IR over a low-precision Cholesky preconditioner — reference
    ``slate::posv_mixed_gmres`` (``src/posv_mixed_gmres.cc``).  Returns
    ``(x, iters)``."""

    from ._refine import fgmres_refine

    full, bv, nb, kw, solve_lo, solve_full = _posv_mixed_setup(a, b, opts,
                                                               tol)
    x, iters = fgmres_refine(full, bv, solve_lo, solve_full, restart=restart,
                             **kw)
    return _wrap_like(b, x), iters


#: Deprecated camel-case alias kept by the reference (slate.hh).
posvMixed = posv_mixed
