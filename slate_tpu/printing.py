"""Matrix printing + redistribution utilities.

* ``print_matrix`` — reference ``slate::print`` (``src/print.cc``,
  1281 LoC): distributed-aware printing with verbosity levels 0-4
  (``Option::PrintVerbose``, ``enums.hh:80-90``): 0 = silent, 1 = header
  only, 2 = abbreviated corners (``PrintEdgeItems``), 3 = full,
  4 = full with tile-boundary rules.
* ``redistribute`` — reference ``slate::redistribute``
  (``src/redistribute.cc:20``): move a distributed matrix onto another
  mesh / tile size.  Where the reference issues tile-granular P2P, here
  the gather→rescatter is a single resharding ``device_put`` and XLA
  emits the all-to-all.
"""

from __future__ import annotations

import io
import sys
from typing import Optional

import jax
import numpy as np

from .matrix import BaseMatrix, as_array
from .parallel.dist import DistMatrix, distribute, undistribute


def _fmt(x, width, precision):
    if np.iscomplexobj(x):
        return (f"{x.real:{width}.{precision}f}"
                f"{x.imag:+{width - 1}.{precision}f}i")
    return f"{x:{width}.{precision}f}"


def sprint_matrix(label: str, a, verbose: int = 3, width: int = 10,
                  precision: int = 4, edgeitems: int = 3) -> str:
    """Render a matrix (Matrix family, DistMatrix, or raw array) to a
    string — the worker behind :func:`print_matrix`."""

    if verbose <= 0:
        return ""
    out = io.StringIO()
    if isinstance(a, DistMatrix):
        p, q = a.grid_shape
        header = (f"% {label}: DistMatrix {a.m}x{a.n}, nb={a.nb}, "
                  f"grid={p}x{q}, dtype={a.dtype}")
        arr = np.asarray(undistribute(a))
        nb = mb = a.nb
    elif isinstance(a, BaseMatrix):
        header = (f"% {label}: {type(a).__name__} {a.m}x{a.n}, "
                  f"mb={a.mb}, nb={a.nb}, dtype={a.dtype}")
        arr = np.asarray(as_array(a))
        nb, mb = a.nb, a.mb
    else:
        arr = np.asarray(a)
        header = f"% {label}: array {arr.shape}, dtype={arr.dtype}"
        mb = nb = max(1, arr.shape[0] if arr.ndim else 1)
    out.write(header + "\n")
    if verbose == 1 or arr.ndim != 2:
        return out.getvalue()
    m, n = arr.shape
    if verbose == 2 and (m > 2 * edgeitems or n > 2 * edgeitems):
        rows = list(range(min(edgeitems, m))) + \
            [-1] + list(range(max(m - edgeitems, edgeitems), m))
        cols = list(range(min(edgeitems, n))) + \
            [-1] + list(range(max(n - edgeitems, edgeitems), n))
    else:
        rows = list(range(m))
        cols = list(range(n))
    out.write(f"{label} = [\n")
    for i in rows:
        if i < 0:
            out.write("  ...\n")
            continue
        if verbose >= 4 and i > 0 and i % mb == 0:
            out.write("  " + "-" * (len(cols) * (width + 1)) + "\n")
        cells = []
        for j in cols:
            if j < 0:
                cells.append("...")
                continue
            if verbose >= 4 and j > 0 and j % nb == 0:
                cells.append("|")
            cells.append(_fmt(arr[i, j], width, precision))
        out.write("  " + " ".join(cells) + "\n")
    out.write("]\n")
    return out.getvalue()


def print_matrix(label: str, a, verbose: int = 3, width: int = 10,
                 precision: int = 4, edgeitems: int = 3,
                 file=None) -> None:
    """Print a matrix with the reference's verbosity semantics."""
    text = sprint_matrix(label, a, verbose, width, precision, edgeitems)
    if text:
        (file or sys.stdout).write(text)


def redistribute(a: DistMatrix, mesh: Optional[jax.sharding.Mesh] = None,
                 nb: Optional[int] = None) -> DistMatrix:
    """Re-grid a distributed matrix — reference ``slate::redistribute``
    (``src/redistribute.cc:20``)."""

    # materialise the gather host-side so the rescatter starts from a
    # replicated array (device→device resharding in one hop)
    dense = np.asarray(undistribute(a))
    return distribute(jax.numpy.asarray(dense),
                      mesh if mesh is not None else a.mesh,
                      nb if nb is not None else a.nb)
