"""Tracing/profiling — reference ``include/slate/internal/Trace.hh``
(``trace::Block`` RAII events, ``:24-108``) and ``src/auxiliary/Trace.cc``
(MPI gather + self-contained SVG gantt, ``:261-276, 330-448``).

Design: a ``Block`` context manager (usable as decorator) records
(name, start, stop, lane) into per-process buffers when tracing is on;
``finish()`` renders a zero-dependency SVG timeline — lanes × time with
a legend, colour-keyed by event name like the reference's per-kernel
colours.  For device-side truth, ``Block`` also emits a
``jax.profiler.TraceAnnotation`` so events line up in XProf; the SVG is
the quick-look artifact.  Host-side timestamps measure dispatch unless
``sync=True`` blocks on the result (JAX is async — the reference's
``queue->sync()`` analog).
"""

from __future__ import annotations

import functools
import threading
import time
from typing import List, NamedTuple, Optional

try:  # profiler annotation is optional — tracing must not require TPU
    from jax.profiler import TraceAnnotation as _JaxAnnotation
except Exception:  # pragma: no cover
    _JaxAnnotation = None


class Event(NamedTuple):
    name: str
    start: float
    stop: float
    lane: str


_events: List[Event] = []
_lock = threading.Lock()
_enabled = False
_origin = 0.0

# profiler-annotation forcing: an xprof capture window needs Block spans
# (dist.<driver>.k<k> chunk windows etc.) on the device-profiler
# timeline even while SVG tracing is OFF — forcing emits ONLY the
# TraceAnnotation (host-side, never changes a compiled program); event
# recording stays gated on _enabled alone.
_annotations_forced = False


def force_annotations(on: bool) -> None:
    """Emit ``jax.profiler.TraceAnnotation`` from every :class:`Block`
    regardless of the tracing flag (see note above) — installed/cleared
    by ``slate_tpu.perf.xprof.capture`` around its window."""
    global _annotations_forced
    _annotations_forced = bool(on)

# ---------------------------------------------------------------------------
# Lane naming: one STABLE, DISTINCT lane per thread.  Keying lanes by
# thread NAME alone collapses spans when names collide — exactly what
# happens with serve dispatcher threads (every BatchQueue names its
# dispatcher "slate-serve-dispatch") and default "Thread-N" workers
# across pools.  The first thread seen with a name keeps the bare name
# (existing tests and artifacts stay unchanged); each further DISTINCT
# ident with the same name gets "name#2", "name#3", ... — stable for
# the thread's lifetime, regression-tested in test_trace_api.py.
# ---------------------------------------------------------------------------

_lane_by_ident: dict = {}       # ident -> (thread name, lane string)
_lane_counts: dict = {}         # thread name -> distinct idents seen


def current_lane() -> str:
    """The calling thread's trace lane (see the lane-naming note
    above).  Public: the telemetry request spans record through it so
    serve spans and ``Block`` spans land in the same Perfetto track."""
    t = threading.current_thread()
    with _lock:
        hit = _lane_by_ident.get(t.ident)
        if hit is not None and hit[0] == t.name:
            return hit[1]
        k = _lane_counts.get(t.name, 0) + 1
        _lane_counts[t.name] = k
        lane = t.name if k == 1 else "%s#%d" % (t.name, k)
        _lane_by_ident[t.ident] = (t.name, lane)
        return lane


def on() -> None:
    """Enable tracing — reference ``Trace::on()``."""
    global _enabled, _origin
    _enabled = True
    if not _origin:
        _origin = time.perf_counter()


def off() -> None:
    global _enabled
    _enabled = False


def is_on() -> bool:
    return _enabled


def clear() -> None:
    global _origin
    with _lock:
        _events.clear()
    _origin = time.perf_counter()


class Block:
    """RAII trace scope — reference ``trace::Block`` (``Trace.hh:24``).

    Usable as context manager or decorator::

        with trace.Block("potrf"):
            ...
    """

    def __init__(self, name: str, lane: Optional[str] = None):
        self.name = name[:30]          # reference caps names at 30 chars
        self._lane_arg = lane          # None = resolve at entry/call time
        self.lane = lane or threading.current_thread().name
        self._ann = None
        self._t0 = 0.0

    def __enter__(self):
        if (_enabled or _annotations_forced) and _JaxAnnotation is not None:
            self._ann = _JaxAnnotation(self.name)
            self._ann.__enter__()
        if _enabled:
            if self._lane_arg is None:
                # the disambiguated per-thread lane (colliding thread
                # names must not collapse into one Perfetto track);
                # resolved at ENTRY so the executing thread wins
                self.lane = current_lane()
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._ann is not None:
            self._ann.__exit__(*exc)
            self._ann = None
        if _enabled and self._t0:
            t1 = time.perf_counter()
            with _lock:
                _events.append(Event(self.name, self._t0 - _origin,
                                     t1 - _origin, self.lane))
            self._t0 = 0.0
        return False

    def __call__(self, fn):
        # pass the ORIGINAL lane argument, not the resolved self.lane:
        # a decorator is built once (on the decorating thread), but the
        # wrapped function may run on any worker thread — when no lane
        # was given explicitly it must resolve at CALL time, or every
        # worker-thread call lands in the decorating thread's lane
        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with Block(self.name, self._lane_arg):
                return fn(*a, **kw)
        return wrapper


_PALETTE = ["#4878d0", "#ee854a", "#6acc64", "#d65f5f", "#956cb4",
            "#8c613c", "#dc7ec0", "#797979", "#d5bb67", "#82c6e2"]


def events() -> List[Event]:
    with _lock:
        return list(_events)


def finish(path: Optional[str] = None) -> Optional[str]:
    """Render the collected events as a standalone SVG gantt and reset —
    reference ``Trace::finish()`` (``Trace.cc:261-276``; rank gather is
    a no-op here: JAX is single-process multi-device).  Returns the file
    path (``trace_<epoch>.svg`` by default), or None if no events."""

    evts = events()
    clear()
    if not evts:
        return None
    path = path or f"trace_{int(time.time())}.svg"
    lanes = sorted({e.lane for e in evts})
    names = sorted({e.name for e in evts})
    colors = {n: _PALETTE[i % len(_PALETTE)] for i, n in enumerate(names)}
    t0 = min(e.start for e in evts)
    t1 = max(e.stop for e in evts)
    span = max(t1 - t0, 1e-9)
    width, row_h, left = 1000.0, 24.0, 120.0
    height = row_h * len(lanes) + 60 + 16 * ((len(names) + 3) // 4)
    x = lambda t: left + (t - t0) / span * (width - left - 10)
    parts = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{width:.0f}" '
             f'height="{height:.0f}" font-family="monospace" font-size="11">']
    for li, lane in enumerate(lanes):
        y = 30 + li * row_h
        parts.append(f'<text x="4" y="{y + row_h * 0.7:.1f}">{lane[:14]}</text>')
        parts.append(f'<line x1="{left}" y1="{y + row_h:.1f}" x2="{width - 10}" '
                     f'y2="{y + row_h:.1f}" stroke="#ddd"/>')
    for e in evts:
        li = lanes.index(e.lane)
        y = 30 + li * row_h
        w = max(x(e.stop) - x(e.start), 0.5)
        parts.append(
            f'<rect x="{x(e.start):.2f}" y="{y + 2:.1f}" width="{w:.2f}" '
            f'height="{row_h - 6:.1f}" fill="{colors[e.name]}">'
            f'<title>{e.name}: {(e.stop - e.start) * 1e3:.3f} ms</title></rect>')
    # time ticks
    for k in range(6):
        t = t0 + span * k / 5
        parts.append(f'<line x1="{x(t):.1f}" y1="20" x2="{x(t):.1f}" '
                     f'y2="{30 + row_h * len(lanes):.1f}" stroke="#eee"/>')
        parts.append(f'<text x="{x(t) - 14:.1f}" y="16">'
                     f'{(t - t0) * 1e3:.1f}ms</text>')
    # legend
    ly = 30 + row_h * len(lanes) + 18
    for i, n in enumerate(names):
        lx = 10 + (i % 4) * 240
        lyy = ly + (i // 4) * 16
        parts.append(f'<rect x="{lx}" y="{lyy - 9}" width="10" height="10" '
                     f'fill="{colors[n]}"/>')
        parts.append(f'<text x="{lx + 14}" y="{lyy}">{n}</text>')
    parts.append("</svg>")
    with open(path, "w") as f:
        f.write("\n".join(parts))
    return path


def finish_perfetto(path: Optional[str] = None) -> Optional[str]:
    """Export the collected events as Chrome-trace/Perfetto JSON and
    reset — the machine-readable sibling of :func:`finish` (the SVG
    stays the quick-look artifact).  Load the file at
    https://ui.perfetto.dev or ``chrome://tracing``.

    The export merges three sources on one clock:

    * every :class:`Block` span as a complete event (``"ph": "X"``),
      one Perfetto track per lane (thread-name metadata rides along);
    * the metrics registry's counter samples
      (:func:`slate_tpu.perf.metrics.counter_series`) as counter tracks
      (``"ph": "C"``) — autotune decisions, driver calls, collective
      bytes line up under the spans that caused them.  Samples named
      ``roofline.<label>.<stage>`` (the attribution engine's per-stage
      achieved roofline fractions, fed by
      :func:`slate_tpu.perf.attr.record_rooflines`) get their own
      ``"roofline"`` category so Perfetto's track filter isolates the
      gap-report view with one query;
    * the live-telemetry request spans
      (:func:`slate_tpu.perf.telemetry.drain_spans`: ``queue_wait`` /
      ``dispatch`` / ``compile`` / ``post_check`` per served request)
      as complete events under category ``"serve.request"`` — one lane
      per dispatcher thread — joined by FLOW events (``"ph": "s"`` /
      ``"t"`` / ``"f"``, flow id = the request's trace id, the value
      on ``future.trace_id``) so ui.perfetto.dev draws one arrowed
      chain per request across lanes.

    Returns the file path (``trace_<epoch>.perfetto.json`` by default)
    or None when there is nothing to export.  Consumes the event
    buffer, the registry's sample buffer (counter VALUES keep
    accumulating — only the time series is drained) and the telemetry
    span buffer.
    """

    origin = _origin
    evts = events()
    clear()
    try:
        from .perf import metrics as _metrics

        samples = _metrics.drain_samples()
    except Exception:       # pragma: no cover - metrics must never block
        samples = []
    try:
        from .perf import telemetry as _telemetry

        req_spans = _telemetry.drain_spans()
    except Exception:       # pragma: no cover - telemetry must never block
        req_spans = []
    if not evts and not samples and not req_spans:
        return None
    # one clock: events store times relative to the trace origin;
    # samples and request spans carry absolute perf_counter stamps.
    # Stamps recorded BEFORE trace.on() set the origin (metrics enabled
    # first) must not go negative — the earliest of (origin, first
    # stamp) anchors t=0, with block-event timestamps shifted by the
    # same amount.
    shift = 0.0
    absolute = [ts for ts, _, _ in samples] \
        + [sp[2] for sp in req_spans]
    if absolute:
        first = min(absolute)
        if not origin:
            origin = first
        elif first < origin:
            shift = origin - first      # added to every block event
            origin = first
    lanes = sorted({e.lane for e in evts}
                   | {sp[4] for sp in req_spans})
    tids = {lane: i for i, lane in enumerate(lanes)}
    out = []
    for lane, tid in tids.items():
        out.append({"name": "thread_name", "ph": "M", "pid": 0,
                    "tid": tid, "args": {"name": lane}})
    for e in evts:
        out.append({"name": e.name, "cat": "block", "ph": "X",
                    "ts": round((e.start + shift) * 1e6, 3),
                    "dur": round(max(e.stop - e.start, 0.0) * 1e6, 3),
                    "pid": 0, "tid": tids[e.lane]})
    # request spans: X events + flow arrows joining each trace id's
    # chain.  Flow binding points sit at each span's midpoint so they
    # land strictly inside the slice they bind to.
    flows: dict = {}
    for trace_id, name, t0, t1, lane, args in req_spans:
        span_args = {"trace_id": trace_id}
        if args:
            span_args.update(args)
        out.append({"name": name, "cat": "serve.request", "ph": "X",
                    "ts": round((t0 - origin) * 1e6, 3),
                    "dur": round(max(t1 - t0, 0.0) * 1e6, 3),
                    "pid": 0, "tid": tids[lane],
                    "args": span_args})
        flows.setdefault(trace_id, []).append((t0, t1, lane))
    for trace_id, chain in flows.items():
        if len(chain) < 2:
            continue                    # an arrow needs two ends
        chain.sort()
        for i, (t0, t1, lane) in enumerate(chain):
            ph = "s" if i == 0 else ("f" if i == len(chain) - 1 else "t")
            fev = {"name": "request", "cat": "serve.request", "ph": ph,
                   "id": trace_id, "pid": 0, "tid": tids[lane],
                   "ts": round(((t0 + t1) / 2.0 - origin) * 1e6, 3)}
            if ph == "f":
                fev["bp"] = "e"
            out.append(fev)
    for ts, name, value in samples:
        cat = "roofline" if name.startswith("roofline.") else "metrics"
        out.append({"name": name, "cat": cat, "ph": "C",
                    "ts": round((ts - origin) * 1e6, 3),
                    "pid": 0, "args": {"value": value}})
    path = path or f"trace_{int(time.time())}.perfetto.json"
    import json

    with open(path, "w") as f:
        json.dump({"traceEvents": out, "displayTimeUnit": "ms"}, f)
    return path
