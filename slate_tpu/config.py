"""Global configuration, reference ``include/slate/config.hh:16-75``.

The reference's one runtime config knob is GPU-aware MPI
(``SLATE_GPU_AWARE_MPI``); on TPU collectives are always device-native so
that knob is moot.  The knobs that matter on TPU instead:

* ``matmul_precision`` — XLA dot precision for float32 inputs.  TPU MXU
  natively multiplies bf16; measured on v5e (tools/probe_precision.py):
  single-pass bf16 (``default``) ~2.5e-3 max-rel error, ``high``
  (3-pass bf16) ~1.3e-5, ``highest`` (6-pass) ~6.3e-7, at 36 / 20 / 15
  TF/s for n=4096.  The library default is ``high``: its error sits two
  orders of magnitude inside every 3·ε(f32)·n residual gate (the
  reference tester's criterion) at twice the throughput of ``highest``.
  Use ``highest`` for full-f32 vendor-BLAS-grade accuracy, ``default``
  when bf16-grade suffices.  Accuracy-critical compositions (iterative-
  refinement residuals, CholQR Gram products) are pinned to ``highest``
  internally (:func:`slate_tpu.ops.blocks.matmul_hi`) and do not follow
  this knob.
* ``default_block_size`` — the global nb default (reference per-call
  ``Option::BlockSize``), tuned for the 128×128 MXU: multiples of 256
  keep every tile op MXU-shaped.

Env vars: ``SLATE_TPU_PRECISION`` ∈ {highest, high, default},
``SLATE_TPU_NB`` (int), and the tri-state backend knobs
``SLATE_TPU_USE_PALLAS`` / ``SLATE_TPU_F64_MXU`` /
``SLATE_TPU_SCATTERED_LU`` / ``SLATE_TPU_SPLIT_GEMM`` ∈ {auto, 1, 0}
consumed by the autotuned dispatch layer
(:mod:`slate_tpu.perf.autotune`; see also ``SLATE_TPU_AUTOTUNE``,
``SLATE_TPU_AUTOTUNE_CACHE``, ``SLATE_TPU_AUTOTUNE_FORCE`` there).
"""

from __future__ import annotations

import os

from jax import lax

_PRECS = {
    "highest": lax.Precision.HIGHEST,
    "high": lax.Precision.HIGH,
    "default": lax.Precision.DEFAULT,
}

matmul_precision = _PRECS.get(os.environ.get("SLATE_TPU_PRECISION", "high"),
                              lax.Precision.HIGH)

default_block_size = int(os.environ.get("SLATE_TPU_NB", "256"))


def set_matmul_precision(p) -> None:
    """Set the dot precision used by every driver ('highest'|'high'|'default')."""
    global matmul_precision
    matmul_precision = _PRECS[p] if isinstance(p, str) else p


def get_matmul_precision():
    return matmul_precision


def _tri_state(env: str):
    """Parse a force-off / force-on / auto knob: returns False, True or
    the string ``"auto"`` (the default when the variable is unset or
    unrecognised)."""
    raw = os.environ.get(env, "auto").strip().lower()
    if raw in ("1", "true", "on", "yes"):
        return True
    if raw in ("0", "false", "off", "no", ""):
        return False
    return "auto"


#: Route hot tile batches through the hand-written Pallas kernels
#: (:mod:`slate_tpu.ops.pallas_kernels`) instead of stock XLA ops.
#: Tri-state (``SLATE_TPU_USE_PALLAS``): ``auto`` (default) lets the
#: autotuner (:mod:`slate_tpu.perf.autotune`) time Pallas against XLA
#: per (op, shape, dtype) on TPU and cache the winner; ``1`` forces the
#: Pallas kernels wherever they are shape-eligible (no timing); ``0``
#: forces them off everywhere.
use_pallas = _tri_state("SLATE_TPU_USE_PALLAS")

#: Route real-fp64 2-D matmuls on TPU through the Ozaki-split MXU
#: kernel (:mod:`slate_tpu.ops.ozaki`) instead of XLA's software fp64
#: emulation (~3.5x faster at fp64-grade accuracy).  Off on CPU
#: backends automatically (native fp64 there).  Tri-state
#: (``SLATE_TPU_F64_MXU``): ``auto`` (default) lets the autotuner time
#: Ozaki against the emulated dot per shape; ``1`` forces Ozaki on TPU;
#: ``0`` restores the emulated path everywhere.
f64_mxu = _tri_state("SLATE_TPU_F64_MXU")

#: Route eligible f32 partial-pivot LU factorizations through the
#: scattered-row fused-panel driver (``linalg.lu.getrf_scattered`` —
#: one Pallas invocation per panel step) instead of the blocked
#: recursion.  Tri-state (``SLATE_TPU_SCATTERED_LU``): ``auto``
#: (default) lets the autotuner time the two drivers per (m, n, nb,
#: dtype) key on TPU and cache the winner; ``1`` forces the scattered
#: driver wherever it is shape-eligible; ``0`` forces it off.  (Until
#: round 6 this was a raw opt-in env read inside ``linalg/lu.py``;
#: it now resolves through the ``lu_driver`` autotune decision like
#: every other multi-backend site.)
scattered_lu = _tri_state("SLATE_TPU_SCATTERED_LU")

#: Route fp32 2-D matmuls through the bf16x3/bf16x6 split-product MXU
#: kernel (:mod:`slate_tpu.ops.split_gemm`): HIGHEST-grade (~k·ε₃₂
#: envelope) accuracy at 3 (or 6) bf16 passes instead of the 6-pass
#: emulated fp32 dot.  Tri-state (``SLATE_TPU_SPLIT_GEMM``): ``auto``
#: (default) admits the split as an autotune candidate at the
#: ``matmul`` site on TPU — off-TPU the ladder still resolves to stock
#: XLA, so unset-knob lowering stays bit-identical; ``1`` forces
#: ``split3`` for every eligible fp32 product (no 128-alignment
#: requirement — the K-fold is a concat, not a tile grid); ``0``
#: removes the split candidates everywhere.
split_gemm = _tri_state("SLATE_TPU_SPLIT_GEMM")


#: Route eligible square f32/f64 factorizations through the
#: out-of-core tile-pool drivers (``linalg.ooc.getrf_ooc`` /
#: ``potrf_ooc`` over ``ops.tilepool`` — host-DRAM tile grid, bounded
#: HBM window, LRU + dirty write-back + async prefetch) instead of the
#: in-core paths.  Tri-state (``SLATE_TPU_OOC``): ``auto`` (default)
#: lets the ``ooc`` autotune site weigh the working set against the
#: HBM budget (``SLATE_TPU_OOC_HBM_MB``) analytically on TPU — off-TPU
#: the ladder resolves to in-core, so unset-knob lowering stays
#: bit-identical; ``1`` forces the pool wherever it is shape-eligible
#: (CPU CI proves the mechanism with a forced tiny window); ``0``
#: forces it off everywhere.
ooc = _tri_state("SLATE_TPU_OOC")


#: Route heev/svd through the QDWH spectral tier
#: (:mod:`slate_tpu.linalg.polar` — polar decomposition by
#: dynamically-weighted Halley iteration, then spectral divide-and-
#: conquer), replacing the two-stage band reduction with geqrf / potrf
#: / gemm calls that run on the autotuned sites.  Tri-state
#: (``SLATE_TPU_QDWH``): ``auto`` (default) lets the ``eig_driver`` /
#: ``svd_driver`` autotune sites time qdwh against twostage per
#: (n-bucket, dtype) on TPU — off-TPU the ladder resolves to twostage,
#: so unset-knob lowering stays bit-identical; ``1`` forces qdwh
#: wherever it is shape-eligible; ``0`` forces it off everywhere.
qdwh = _tri_state("SLATE_TPU_QDWH")

#: Block dimension at which the QDWH divide-and-conquer recursion hands
#: the remaining subproblem to the stock two-stage solver
#: (``SLATE_TPU_QDWH_CROSSOVER``, default 128).  Below this size the
#: band reduction's O(n³) is too small for the polar iteration's
#: constant factors to pay off.
qdwh_crossover = int(os.environ.get("SLATE_TPU_QDWH_CROSSOVER", "128"))

#: Halley-weight threshold at which a QDWH iteration switches from the
#: QR-based step (backward stable at any conditioning) to the cheaper
#: Cholesky-based step ``chol(I + c·XᴴX)`` (``SLATE_TPU_QDWH_SWITCH_C``,
#: default 100).  ``I + c·XᴴX`` has condition ≈ c once X is nearly
#: orthogonal, so small c makes the Cholesky variant safe; the
#: ``qdwh_step`` autotune site can override per (n, c-regime, dtype).
qdwh_switch_c = float(os.environ.get("SLATE_TPU_QDWH_SWITCH_C", "100"))


def use_pallas_mode() -> str:
    """Resolve the tri-state :data:`use_pallas` knob to one of
    ``"auto" | "on" | "off"`` (reading the module global so tests that
    monkeypatch ``config.use_pallas = True/False`` keep working)."""
    v = use_pallas
    return "auto" if v == "auto" else ("on" if v else "off")


def f64_mxu_mode() -> str:
    """Resolve the tri-state :data:`f64_mxu` knob to
    ``"auto" | "on" | "off"``."""
    v = f64_mxu
    return "auto" if v == "auto" else ("on" if v else "off")


def scattered_lu_mode() -> str:
    """Resolve the tri-state :data:`scattered_lu` knob to
    ``"auto" | "on" | "off"``."""
    v = scattered_lu
    return "auto" if v == "auto" else ("on" if v else "off")


def split_gemm_mode() -> str:
    """Resolve the tri-state :data:`split_gemm` knob to
    ``"auto" | "on" | "off"``."""
    v = split_gemm
    return "auto" if v == "auto" else ("on" if v else "off")


def ooc_mode() -> str:
    """Resolve the tri-state :data:`ooc` knob to
    ``"auto" | "on" | "off"``."""
    v = ooc
    return "auto" if v == "auto" else ("on" if v else "off")


def qdwh_mode() -> str:
    """Resolve the tri-state :data:`qdwh` knob to
    ``"auto" | "on" | "off"``."""
    v = qdwh
    return "auto" if v == "auto" else ("on" if v else "off")
