"""Spectrum-controlled matrix generator.

TPU-native analog of the reference test generator
``test/matrix_generator.cc:705-843`` (params ``test/matrix_params.hh:34``):
named matrix kinds with controlled singular-/eigen-spectra so correctness
checks are grid- and blocking-independent (reference guarantees
determinism independent of the process grid, ``CHANGELOG.md:8-9``).

Supported kinds (reference names kept):

* ``zeros``, ``ones``, ``identity``, ``jordan``
* ``rand`` / ``rands`` (uniform; rands is sign-symmetric), ``randn``
* ``rand_dominant`` — random with diagonal dominance (LU-safe without pivots)
* ``svd`` — A = U·Σ·Vᴴ with Σ from a named distribution
* ``heev`` — Hermitian A = V·Λ·Vᴴ
* ``poev`` — HPD A = V·Σ·Vᴴ (positive spectrum)
* ``cond`` — geometric spectrum with condition number ``cond``

Spectrum suffixes (e.g. ``svd:arith``): ``arith`` (default geometric
``geo``), ``cluster0``, ``cluster1``, ``rarith``…; a plain float list can
also be passed via ``sigma``.

Determinism: seeded ``jax.random`` keys; generation happens at full
precision then casts to the requested dtype.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..ops.blocks import matmul as _mm
import numpy as np


def _spectrum(kind: str, n: int, cond: float) -> np.ndarray:
    if kind in ("", "geo", "default"):
        # geometric from 1 to 1/cond (reference default sigma distribution)
        return np.geomspace(1.0, 1.0 / cond, n)
    if kind == "arith":
        return np.linspace(1.0, 1.0 / cond, n)
    if kind == "cluster0":
        s = np.full(n, 1.0 / cond); s[0] = 1.0
        return s
    if kind == "cluster1":
        s = np.ones(n); s[-1] = 1.0 / cond
        return s
    if kind == "rgeo":
        return np.geomspace(1.0 / cond, 1.0, n)
    if kind == "rarith":
        return np.linspace(1.0 / cond, 1.0, n)
    raise ValueError(f"unknown spectrum {kind!r}")


def _haar(key, m: int, n: int, dtype) -> jnp.ndarray:
    """Random orthonormal columns (Haar via QR of Gaussian)."""
    if jnp.issubdtype(dtype, jnp.complexfloating):
        kr, ki = jax.random.split(key)
        g = (jax.random.normal(kr, (m, n)) + 1j * jax.random.normal(ki, (m, n)))
        g = g.astype(dtype)
    else:
        g = jax.random.normal(key, (m, n), dtype=dtype)
    q, r = jnp.linalg.qr(g)
    # fix phases so the distribution is Haar
    d = jnp.diagonal(r)
    ph = d / jnp.abs(d)
    return q * jnp.conj(ph)[None, :]


def generate_matrix(kind: str, m: int, n: Optional[int] = None, *,
                    dtype=jnp.float32, seed: int = 0,
                    cond: float = 1e2,
                    sigma: Optional[Sequence[float]] = None):
    """Generate an m×n test matrix of the named ``kind`` (see module doc)."""

    n = m if n is None else n
    key = jax.random.PRNGKey(seed)
    base, _, spec = kind.partition(":")
    # generate at the widest available precision (f64 only under x64 —
    # on TPU without x64, generating in f32 avoids truncation warnings)
    if jax.config.jax_enable_x64:
        gen_dtype = jnp.complex128 if jnp.issubdtype(dtype, jnp.complexfloating) else jnp.float64
    else:
        gen_dtype = jnp.complex64 if jnp.issubdtype(dtype, jnp.complexfloating) else jnp.float32
    real_gen = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    k = min(m, n)

    if base == "zeros":
        a = jnp.zeros((m, n), gen_dtype)
    elif base == "ones":
        a = jnp.ones((m, n), gen_dtype)
    elif base == "identity":
        a = jnp.eye(m, n, dtype=gen_dtype)
    elif base == "jordan":
        a = jnp.eye(m, n, dtype=gen_dtype) + jnp.eye(m, n, k=-1, dtype=gen_dtype)
    elif base in ("rand", "rands", "randn", "rand_dominant"):
        if base == "randn":
            a = jax.random.normal(key, (m, n), dtype=real_gen)
        else:
            lo = -1.0 if base != "rand" else 0.0
            a = jax.random.uniform(key, (m, n), dtype=real_gen,
                                   minval=lo, maxval=1.0)
        if jnp.issubdtype(dtype, jnp.complexfloating):
            key2 = jax.random.fold_in(key, 1)
            b = jax.random.uniform(key2, (m, n), dtype=real_gen,
                                   minval=-1.0, maxval=1.0)
            a = a + 1j * b
        a = a.astype(gen_dtype)
        if base == "rand_dominant":
            a = a + 2 * max(m, n) * jnp.eye(m, n, dtype=gen_dtype)
    elif base in ("svd", "heev", "poev", "cond"):
        s = np.asarray(sigma) if sigma is not None else _spectrum(spec, k, cond)
        s = jnp.asarray(s, gen_dtype)
        ku, kv = jax.random.split(key)
        u = _haar(ku, m, k, gen_dtype)
        if base in ("heev", "poev"):
            if base == "heev":
                # mixed-sign spectrum: alternate signs (reference heev kind)
                signs = jnp.asarray(np.where(np.arange(k) % 2 == 0, 1.0, -1.0),
                                    gen_dtype)
                s = s * signs
            a = _mm(u * s[None, :], jnp.conj(u.T))
            # force exact Hermitian-ness after rounding
            a = (a + jnp.conj(a.T)) / 2
        else:
            v = _haar(kv, n, k, gen_dtype)
            a = _mm(u * s[None, :], jnp.conj(v.T))
    else:
        raise ValueError(f"unknown matrix kind {kind!r}")

    return a.astype(dtype)


def random_spd(n: int, *, dtype=jnp.float32, seed: int = 0, cond: float = 1e2):
    """Hermitian positive-definite test matrix (reference kind ``poev``)."""
    return generate_matrix("poev", n, dtype=dtype, seed=seed, cond=cond)
