from .matgen import generate_matrix, random_spd  # noqa: F401
