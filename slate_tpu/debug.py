"""Debug invariants — the analog of the reference's ``Debug`` class
(``src/auxiliary/Debug.cc``): ``checkTilesLives`` (life-counter
consistency, ``:66``), ``checkTilesLayout`` (``:100``),
``checkHostMemoryLeaks/checkDeviceMemoryLeaks`` on the pool
(``:316,336``) and the ``printTiles_`` state dumps (``:169``).

The TPU design has no MOSI states or life counters (XLA owns placement),
so the invariants that remain meaningful are value sanity (NaN/Inf per
tile), distribution-layout consistency of :class:`DistMatrix`, and the
native memory pool's leak counters.
"""

from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp
import numpy as np

from .exceptions import SlateError


def check_finite(a, nb: int = 256, name: str = "A") -> None:
    """Raise :class:`SlateError` listing every (i, j) tile containing a
    NaN/Inf — the debugging role of the reference's per-tile state dumps
    (``Debug::printTiles_``)."""

    arr = np.asarray(getattr(a, "array", a))
    bad: List[Tuple[int, int]] = []
    mt = -(-arr.shape[-2] // nb)
    nt = -(-arr.shape[-1] // nb)
    finite = np.isfinite(arr)
    if finite.all():
        return
    for i in range(mt):
        for j in range(nt):
            blk = finite[..., i * nb:(i + 1) * nb, j * nb:(j + 1) * nb]
            if not blk.all():
                bad.append((i, j))
    raise SlateError(f"{name}: non-finite values in tiles {bad} (nb={nb})")


def check_dist_layout(dm) -> None:
    """Validate a :class:`~slate_tpu.parallel.DistMatrix`'s layout
    invariants — the analog of ``Debug::checkTilesLayout``: padded shape
    divisible by nb, tile counts divisible by the grid, true dims inside
    the padding."""

    p, q = dm.grid_shape
    mp, np_ = dm.data.shape
    if mp % dm.nb or np_ % dm.nb:
        raise SlateError(f"padded shape {dm.data.shape} not a multiple of "
                         f"nb={dm.nb}")
    if dm.mtp % p or dm.ntp % q:
        raise SlateError(f"tile grid {dm.mtp}x{dm.ntp} not divisible by "
                         f"process grid {p}x{q}")
    if dm.m > mp or dm.n > np_:
        raise SlateError(f"true dims ({dm.m},{dm.n}) exceed padded storage "
                         f"{dm.data.shape}")


def check_pool_leaks(pool) -> None:
    """Leak check on a native :class:`~slate_tpu.native.MemoryPool` —
    ``Debug::checkHostMemoryLeaks`` (``Debug.cc:316``): every allocated
    block must have been returned."""

    outstanding = pool.num_allocated - pool.num_free
    if outstanding:
        raise SlateError(
            f"memory pool leak: {outstanding} block(s) outstanding "
            f"({pool.num_allocated} allocated, {pool.num_free} free)")


def memory_stats() -> dict:
    """Native runtime stats — ``Debug::printNumFreeMemBlocks``
    (``Debug.cc:304``) territory.  Returns availability + thread count;
    per-pool counters live on :class:`~slate_tpu.native.MemoryPool`."""

    try:
        from . import native
    except Exception:                       # pragma: no cover
        return {"available": False}
    if not native.available():
        return {"available": False}
    return {"available": True, "host_threads": native.num_threads()}
