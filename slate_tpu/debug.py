"""Debug invariants — the analog of the reference's ``Debug`` class
(``src/auxiliary/Debug.cc``): ``checkTilesLives`` (life-counter
consistency, ``:66``), ``checkTilesLayout`` (``:100``),
``checkHostMemoryLeaks/checkDeviceMemoryLeaks`` on the pool
(``:316,336``) and the ``printTiles_`` state dumps (``:169``).

The TPU design has no MOSI states or life counters (XLA owns placement),
so the invariants that remain meaningful are value sanity (NaN/Inf per
tile), distribution-layout consistency of :class:`DistMatrix`, and the
native memory pool's leak counters.
"""

from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp
import numpy as np

from .exceptions import SlateError


def check_finite(a, nb: int = 256, name: str = "A") -> None:
    """Raise :class:`SlateError` listing every (i, j) tile containing a
    NaN/Inf — the debugging role of the reference's per-tile state dumps
    (``Debug::printTiles_``)."""

    arr = np.asarray(getattr(a, "array", a))
    bad: List[Tuple[int, int]] = []
    mt = -(-arr.shape[-2] // nb)
    nt = -(-arr.shape[-1] // nb)
    finite = np.isfinite(arr)
    if finite.all():
        return
    for i in range(mt):
        for j in range(nt):
            blk = finite[..., i * nb:(i + 1) * nb, j * nb:(j + 1) * nb]
            if not blk.all():
                bad.append((i, j))
    raise SlateError(f"{name}: non-finite values in tiles {bad} (nb={nb})")


def check_dist_layout(dm) -> None:
    """Validate a :class:`~slate_tpu.parallel.DistMatrix`'s layout
    invariants — the analog of ``Debug::checkTilesLayout``: padded shape
    divisible by nb, tile counts divisible by the grid, true dims inside
    the padding."""

    p, q = dm.grid_shape
    mp, np_ = dm.data.shape
    if mp % dm.nb or np_ % dm.nb:
        raise SlateError(f"padded shape {dm.data.shape} not a multiple of "
                         f"nb={dm.nb}")
    if dm.mtp % p or dm.ntp % q:
        raise SlateError(f"tile grid {dm.mtp}x{dm.ntp} not divisible by "
                         f"process grid {p}x{q}")
    if dm.m > mp or dm.n > np_:
        raise SlateError(f"true dims ({dm.m},{dm.n}) exceed padded storage "
                         f"{dm.data.shape}")


def check_pool_leaks(pool) -> None:
    """Leak check on a native :class:`~slate_tpu.native.MemoryPool` —
    ``Debug::checkHostMemoryLeaks`` (``Debug.cc:316``): every allocated
    block must have been returned."""

    outstanding = pool.num_allocated - pool.num_free
    if outstanding:
        raise SlateError(
            f"memory pool leak: {outstanding} block(s) outstanding "
            f"({pool.num_allocated} allocated, {pool.num_free} free)")


def device_memory_stats() -> list:
    """Per-device allocator stats via ``device.memory_stats()`` —
    hardened: one dict per device that reports the API
    (``bytes_in_use`` / ``peak_bytes_in_use`` / ``bytes_limit`` plus
    platform and device id), and ``[]`` on backends without it (the CPU
    allocator returns None) instead of raising — CPU CI and jax-free
    processes get an empty list, never an exception."""

    out = []
    try:
        import jax

        devices = jax.devices()
    except Exception:                       # pragma: no cover
        return out
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            continue
        if not stats:
            continue
        row = {"device": str(d.id), "platform": str(d.platform)}
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                  "largest_alloc_size", "num_allocs"):
            v = stats.get(k)
            if v is not None:
                row[k] = int(v)
        out.append(row)
    return out


def memory_stats() -> dict:
    """Native runtime + device allocator stats —
    ``Debug::printNumFreeMemBlocks`` (``Debug.cc:304``) territory.
    Returns availability + thread count and, under ``"devices"``, the
    per-device HBM gauges from :func:`device_memory_stats` (``[]`` on
    backends without the API); per-pool counters live on
    :class:`~slate_tpu.native.MemoryPool`."""

    devices = device_memory_stats()
    try:
        from . import native
    except Exception:                       # pragma: no cover
        return {"available": False, "devices": devices}
    if not native.available():
        return {"available": False, "devices": devices}
    return {"available": True, "host_threads": native.num_threads(),
            "devices": devices}
