"""Python side of the C driver API.

The generated C wrappers (``src/c_api/driver_api.c``, from
``tools/generate_c_api.py``) embed CPython and funnel every driver call
through :func:`call`: NumPy views of the caller's column-major buffers
come in, driver results go back as a tuple of arrays that the C core
copies into caller-allocated output buffers, in order.

This mirrors the reference's generated C API (``tools/c_api/
generate_wrappers.py`` → ``include/slate/c_api/slate.h``): there the
wrappers call the C++ templates directly; here the compute path is
JAX/XLA, so the shim hops through the interpreter — the TPU still does
the math.
"""

from __future__ import annotations

import numpy as np

# the C ABI promises d/z precision — keep f64 inputs f64 (this module
# is only imported by the embedded interpreter the C core starts)
import jax

jax.config.update("jax_enable_x64", True)


def _j(a):
    import jax.numpy as jnp
    return jnp.asarray(np.ascontiguousarray(a))


def _np(x):
    return np.ascontiguousarray(np.asarray(x))


def call(op: str, a, b=None, uplo: str = "L", trans: str = "N"):
    """Dispatch one driver call.  ``a``/``b`` arrive as column-major
    NumPy views of the caller's buffers (transposed to row-major here).
    Returns a tuple of row-major arrays; the C core transposes back."""

    from .. import linalg as L
    from ..enums import Diag, Norm, Side, Uplo, Op
    from ..matrix import HermitianMatrix, TriangularMatrix

    a = np.asarray(a).T          # column-major view -> row-major array
    if b is not None:
        b = np.asarray(b).T
    u = Uplo.Lower if uplo.upper().startswith("L") else Uplo.Upper

    if op == "gesv":
        lu, piv, x = L.gesv(_j(a), _j(b))
        return (_np(x).T, _np(piv).astype(np.int64))
    if op == "gesv_full":
        # ScaLAPACK pdgesv semantics: return the LU factor, the LAPACK
        # 1-based swap sequence, AND the solution (A and B both
        # overwritten on the caller side)
        from ..linalg.lu import perm_to_ipiv
        lu, perm = L.getrf(_j(a))
        x = L.getrs(getattr(lu, "data", lu), perm, _j(b))
        ipiv = perm_to_ipiv(perm)
        return (_np(getattr(lu, "data", lu)).T,
                _np(ipiv).astype(np.int64), _np(x).T)
    if op == "getrf":
        lu, piv = L.getrf(_j(a))
        return (_np(getattr(lu, "data", lu)).T, _np(piv).astype(np.int64))
    if op == "getrf_ipiv":
        # LAPACK 1-based swap sequence (ScaLAPACK's distributed-ipiv
        # convention) instead of the library's permutation vector
        from ..linalg.lu import perm_to_ipiv
        lu, perm = L.getrf(_j(a))
        return (_np(getattr(lu, "data", lu)).T,
                _np(perm_to_ipiv(perm)).astype(np.int64))
    if op == "getri":
        lu, piv = L.getrf(_j(a))
        inv = L.getri(getattr(lu, "data", lu), piv)
        return (_np(getattr(inv, "data", inv)).T,)
    if op == "posv":
        h = HermitianMatrix(_j(a), uplo=u)
        fac, x = L.posv(h, _j(b))
        return (_np(x).T,)
    if op == "potrf":
        h = HermitianMatrix(_j(a), uplo=u)
        fac = L.potrf(h)
        return (_np(fac.data).T,)
    if op == "potri":
        h = HermitianMatrix(_j(a), uplo=u)
        inv = L.potri(L.potrf(h))
        return (_np(getattr(inv, "data", inv)).T,)
    if op == "trtri":
        t = TriangularMatrix(_j(a), uplo=u, diag=Diag.NonUnit)
        inv = L.trtri(t)
        return (_np(getattr(inv, "data", inv)).T,)
    if op == "potrs":
        # a holds the Cholesky factor in the `uplo` triangle
        t = TriangularMatrix(_j(a), uplo=u, diag=Diag.NonUnit)
        x = L.potrs(t, _j(b))
        return (_np(getattr(x, "data", x)).T,)
    if op == "posv_full":
        # ScaLAPACK pdposv semantics: factor AND solution
        h = HermitianMatrix(_j(a), uplo=u)
        fac, x = L.posv(h, _j(b))
        return (_np(getattr(fac, "data", fac)).T, _np(x).T)
    if op == "lu_solve_factored":
        # a = packed LU (unit lower + upper), b already row-permuted
        import jax.numpy as jnp
        from jax import lax as _lax
        aj, bj = _j(a), _j(b)
        y = _lax.linalg.triangular_solve(
            aj, bj, left_side=True, lower=True, unit_diagonal=True)
        x = _lax.linalg.triangular_solve(
            aj, y, left_side=True, lower=False)
        return (_np(x).T,)
    if op == "lu_solve_trans":
        # solve op(A) x = b from packed LU where op per `uplo` slot:
        # 'T' -> A^T = U^T L^T P, 'C' -> A^H; caller applies the final
        # P^T row swaps.  (uplo carries the trans char here.)
        from jax import lax as _lax
        conj = uplo.upper().startswith("C")
        aj, bj = _j(a), _j(b)
        y = _lax.linalg.triangular_solve(
            aj, bj, left_side=True, lower=False, transpose_a=True,
            conjugate_a=conj)
        x = _lax.linalg.triangular_solve(
            aj, y, left_side=True, lower=True, unit_diagonal=True,
            transpose_a=True, conjugate_a=conj)
        return (_np(x).T,)
    if op == "potri_factored":
        # a holds the Cholesky factor in the `uplo` triangle
        t = TriangularMatrix(_j(a), uplo=u, diag=Diag.NonUnit)
        inv = L.potri(t)
        return (_np(getattr(inv, "data", inv)).T,)
    if op == "hesv" or op == "sysv":
        fac, x = L.hesv(_j(a), _j(b))
        return (_np(x).T,)
    if op == "gels":
        x = L.gels(_j(a), _j(b))
        return (_np(getattr(x, "data", x)).T,)
    if op == "geqrf":
        f, taus = L.geqrf(_j(a))
        return (_np(getattr(f, "data", f)).T, _np(taus))
    if op == "gelqf":
        f, taus = L.gelqf(_j(a))
        return (_np(getattr(f, "data", f)).T, _np(taus))
    if op == "heev" or op == "syev":
        w, z = L.heev(HermitianMatrix(_j(a), uplo=u), jobz=True)
        return (_np(w).astype(np.float64), _np(z).T)
    if op == "heev_vals" or op == "syev_vals":
        w = L.heev(HermitianMatrix(_j(a), uplo=u), jobz=False)[0]
        return (_np(w).astype(np.float64),)
    if op == "svd":
        s, uu, vt = L.svd(_j(a), jobu=True, jobvt=True)
        return (_np(s).astype(np.float64), _np(uu).T, _np(vt).T)
    if op == "svd_vals":
        s = L.svd_vals(_j(a))
        return (_np(s).astype(np.float64),)
    if op == "gemm":
        zero = np.zeros((a.shape[0], b.shape[1]), a.dtype)
        c = L.gemm(1.0, _j(a), _j(b), 0.0, _j(zero))
        return (_np(getattr(c, "data", c)).T,)
    if op == "symm" or op == "hemm":
        h = HermitianMatrix(_j(a), uplo=u)
        zero = np.zeros((a.shape[0], b.shape[1]), a.dtype)
        c = (L.hemm if op == "hemm" else L.symm)(
            Side.Left, 1.0, h, _j(b), 0.0, _j(zero))
        return (_np(getattr(c, "data", c)).T,)
    if op == "syrk" or op == "herk":
        zero = np.zeros((a.shape[0], a.shape[0]), a.dtype)
        c = (L.herk if op == "herk" else L.syrk)(
            1.0, _j(a), 0.0, HermitianMatrix(_j(zero), uplo=u))
        return (_np(getattr(c, "data", c)).T,)
    if op == "trsm":
        t = TriangularMatrix(_j(a), uplo=u, diag=Diag.NonUnit)
        x = L.trsm(Side.Left, 1.0, t, _j(b))
        return (_np(getattr(x, "data", x)).T,)
    if op == "trmm":
        t = TriangularMatrix(_j(a), uplo=u, diag=Diag.NonUnit)
        x = L.trmm(Side.Left, 1.0, t, _j(b))
        return (_np(getattr(x, "data", x)).T,)
    if op == "lange":
        nm = {"M": Norm.Max, "1": Norm.One, "I": Norm.Inf,
              "F": Norm.Fro}[trans.upper()]
        v = L.norm(nm, _j(a))
        return (np.asarray([float(v)], np.float64),)
    if op == "gecondest":
        lu, piv = L.getrf(_j(a))
        v = L.gecondest(Norm.One, getattr(lu, "data", lu), piv,
                        anorm=float(L.norm(Norm.One, _j(a))))
        return (np.asarray([float(v)], np.float64),)
    raise ValueError(f"unknown driver op: {op}")
