"""Simplified verb-named API — reference
``include/slate/simplified_api.hh`` (838 LoC): ``multiply``,
``triangular_solve``, ``lu_solve``, ``chol_solve``,
``least_squares_solve``, ``eig_vals``, ``svd_vals``, … forwarding to the
BLAS-named drivers (``simplified_api.hh:19,110,133,230``).
"""

from __future__ import annotations

from typing import Optional

from ..enums import Diag, Norm, Op, Side, Uplo
from ..options import Options
from .. import linalg as L

__all__ = [
    "multiply", "triangular_multiply", "triangular_solve",
    "rank_k_update", "rank_2k_update", "band_multiply",
    "lu_factor", "lu_factor_nopiv", "lu_solve", "lu_solve_nopiv",
    "lu_solve_using_factor", "lu_solve_using_factor_nopiv",
    "lu_inverse_using_factor", "lu_inverse_using_factor_out_of_place",
    "chol_factor", "chol_solve", "chol_solve_using_factor",
    "chol_inverse_using_factor",
    "indefinite_factor", "indefinite_solve",
    "indefinite_solve_using_factor",
    "least_squares_solve", "qr_factor", "lq_factor",
    "qr_multiply_by_q", "lq_multiply_by_q",
    "eig", "eig_vals", "svd", "svd_vals", "norm",
    "lu_factor_batched", "lu_solve_batched", "chol_factor_batched",
    "chol_solve_batched", "least_squares_solve_batched",
    "qr_factor_batched",
]


# -- Level 3 BLAS ----------------------------------------------------------

def multiply(alpha, a, b, beta, c, opts: Optional[Options] = None):
    """C ← α·A·B + β·C — ``simplified_api.hh:19`` → gemm (hemm/symm when
    A is Hermitian/symmetric is dispatched by the driver's types)."""
    return L.gemm(alpha, a, b, beta, c, opts)


def triangular_multiply(alpha, a, b, side: Side = Side.Left,
                        opts: Optional[Options] = None):
    """B ← α·op(T)·B — → trmm."""
    return L.trmm(side, alpha, a, b, opts)


def triangular_solve(alpha, a, b, side: Side = Side.Left,
                     opts: Optional[Options] = None):
    """Solve op(T)·X = α·B — ``simplified_api.hh:110`` → trsm."""
    return L.trsm(side, alpha, a, b, opts)


def rank_k_update(alpha, a, beta, c, opts: Optional[Options] = None):
    """C ← α·A·Aᴴ + β·C — → herk."""
    return L.herk(alpha, a, beta, c, opts)


def rank_2k_update(alpha, a, b, beta, c, opts: Optional[Options] = None):
    """C ← α·A·Bᴴ + ᾱ·B·Aᴴ + β·C — → her2k."""
    return L.her2k(alpha, a, b, beta, c, opts)


def band_multiply(alpha, a, b, beta, c, opts: Optional[Options] = None):
    """C ← α·A_band·B + β·C — → gbmm."""
    return L.gbmm(alpha, a, b, beta, c, opts)


# -- LU --------------------------------------------------------------------

def lu_factor(a, opts: Optional[Options] = None):
    """``simplified_api.hh`` lu_factor → getrf; returns (LU, pivots)."""
    return L.getrf(a, opts)


def lu_solve(a, b, opts: Optional[Options] = None):
    """Solve A·X = B — ``simplified_api.hh:230`` → gesv; returns X."""
    return L.gesv(a, b, opts)[2]


def lu_solve_using_factor(lu, pivots, b, opts: Optional[Options] = None):
    return L.getrs(lu, pivots, b, opts=opts)


def lu_inverse_using_factor(lu, pivots, opts: Optional[Options] = None):
    return L.getri(lu, pivots, opts)


def lu_inverse_using_factor_out_of_place(lu, pivots,
                                         opts: Optional[Options] = None):
    """``simplified_api.hh`` lu_inverse_using_factor_out_of_place →
    getriOOP; functional style is always out-of-place here, so this is
    the same computation returning a fresh array."""
    return L.getri(lu, pivots, opts)


def lu_factor_nopiv(a, opts: Optional[Options] = None):
    """``simplified_api.hh`` lu_factor_nopiv → getrf_nopiv."""
    return L.getrf_nopiv(a, opts)


def lu_solve_nopiv(a, b, opts: Optional[Options] = None):
    """Solve A·X = B without pivoting — → gesv_nopiv; returns X."""
    return L.gesv_nopiv(a, b, opts)[1]


def lu_solve_using_factor_nopiv(lu, b, opts: Optional[Options] = None):
    return L.getrs_nopiv(lu, b, opts=opts)


# -- Cholesky --------------------------------------------------------------

def chol_factor(a, opts: Optional[Options] = None):
    return L.potrf(a, opts)


def chol_solve(a, b, opts: Optional[Options] = None):
    """Solve SPD/HPD A·X = B — → posv; returns X."""
    return L.posv(a, b, opts)[1]


def chol_solve_using_factor(factor, b, opts: Optional[Options] = None):
    return L.potrs(factor, b, opts)


def chol_inverse_using_factor(factor, opts: Optional[Options] = None):
    return L.potri(factor, opts)


# -- Hermitian indefinite --------------------------------------------------

def indefinite_factor(a, opts: Optional[Options] = None):
    return L.hetrf(a, opts)


def indefinite_solve(a, b, opts: Optional[Options] = None):
    """Solve Hermitian-indefinite A·X = B — → hesv; returns X."""
    return L.hesv(a, b, opts)[1]


def indefinite_solve_using_factor(factors, b, opts: Optional[Options] = None):
    return L.hetrs(factors, b, opts)


# -- Least squares / QR / LQ ----------------------------------------------

def least_squares_solve(a, b, opts: Optional[Options] = None):
    """min ‖A·X − B‖₂ — → gels."""
    return L.gels(a, b, opts)


def qr_factor(a, opts: Optional[Options] = None):
    return L.geqrf(a, opts)


def qr_multiply_by_q(side: Side, op: Op, factor, taus, c,
                     opts: Optional[Options] = None):
    return L.unmqr(side, op, factor, taus, c, opts)


def lq_factor(a, opts: Optional[Options] = None):
    return L.gelqf(a, opts)


def lq_multiply_by_q(side: Side, op: Op, factor, taus, c,
                     opts: Optional[Options] = None):
    return L.unmlq(side, op, factor, taus, c, opts)


# -- Batched many-problem verbs (leading batch dim; ISSUE 8) ---------------
# The simplified-API siblings of :mod:`slate_tpu.linalg.batched` — the
# serving layer (:mod:`slate_tpu.serve`) queues exactly these solves.

def lu_factor_batched(a, opts: Optional[Options] = None):
    """Batched ``lu_factor``: (B, n, n) → (LU, perm) stacks."""
    return L.getrf_batched(a, opts)


def lu_solve_batched(a, b, opts: Optional[Options] = None):
    """Batched ``lu_solve``: solve A·X = B per problem; returns X."""
    return L.gesv_batched(a, b, opts)[2]


def chol_factor_batched(a, opts: Optional[Options] = None):
    """Batched ``chol_factor``: (B, n, n) SPD → lower factors."""
    return L.potrf_batched(a, opts)


def chol_solve_batched(a, b, opts: Optional[Options] = None):
    """Batched ``chol_solve``: SPD A·X = B per problem; returns X."""
    return L.posv_batched(a, b, opts)[1]


def least_squares_solve_batched(a, b, opts: Optional[Options] = None):
    """Batched ``least_squares_solve`` (tall problems, m ≥ n)."""
    return L.gels_batched(a, b, opts)


def qr_factor_batched(a, opts: Optional[Options] = None):
    """Batched ``qr_factor``: (B, m, n) → (packed, taus) stacks."""
    return L.geqrf_batched(a, opts)


# -- Eigen / SVD / norms ---------------------------------------------------

def eig(a, opts: Optional[Options] = None):
    """Hermitian eigendecomposition — returns (w, Z)."""
    return L.heev(a, True, opts)


def eig_vals(a, opts: Optional[Options] = None):
    """``simplified_api.hh`` eig_vals → heev(values-only)."""
    return L.heev(a, False, opts)[0]


def svd(a, opts: Optional[Options] = None):
    return L.svd(a, opts=opts)


def svd_vals(a, opts: Optional[Options] = None):
    return L.svd_vals(a, opts)


def norm(norm_type: Norm, a, opts: Optional[Options] = None):
    return L.norm(norm_type, a, opts)
